"""Interconnect topology: analytic ICI cost model + DCN probing.

The reference measures its network empirically: a device kernel times
pairwise small/large NVSHMEM puts and slope-intercept fits alpha (latency,
ms) / beta (ms/MB) per peer (``csrc/include/flashmoe/topo.cuh:43-82``), with
block-specialized publishers for remote vs P2P paths, and each rank
broadcasting its adjacency row (``topo.cuh:207-262``).

On TPU the intra-slice network is a known torus: geometry comes from
``device.coords`` and per-generation link specs, so the alpha-beta adjacency
matrix is *derived*, not probed (no warm-up kernels, no measurement noise).
Probing remains meaningful across slices (DCN), where
:func:`probe_dcn_costs` times real transfers the same way the reference
does — but over XLA collectives.

The produced ``Adjacency`` feeds the Decider
(:mod:`flashmoe_tpu.parallel.decider`) exactly like the reference's
``adjMatrix`` feeds ``Decider::operator()``.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import numpy as np

# Per-generation link characteristics (one-way, per ICI link).
# Sources: public TPU system papers / scaling-book numbers; conservative.
_ICI_SPECS = {
    # gen: (latency_us, GB/s per link direction)
    "v4": (1.0, 50.0),
    "v5e": (1.0, 45.0),
    "v5p": (1.0, 90.0),
    "v6e": (1.0, 90.0),
    "cpu": (10.0, 10.0),  # virtual/testing backend
    "default": (1.0, 45.0),
}
_DCN_SPEC = (10.0, 25.0)  # (latency_us, GB/s) per host NIC, conservative

# Per-chip compute / memory peaks (public spec sheets, bf16 matmul) —
# the roofline ceilings the analytical planner prices against.  One
# table for every consumer (overlap bound, bench MXU label, planner):
# a generation added here becomes plannable everywhere at once.
_PEAK_TFLOPS = {"v4": 275.0, "v5e": 197.0, "v5p": 459.0, "v6e": 918.0}
_HBM_GBPS = {"v4": 1228.0, "v5e": 819.0, "v5p": 2765.0, "v6e": 1638.0}


def chip_spec(gen: str) -> tuple[float, float]:
    """(peak bf16 TFLOP/s, HBM GB/s) for a TPU generation.

    Raises ``ValueError`` naming the supported set for anything else —
    the planner and overlap bound call this with arbitrary user strings,
    and a bare ``KeyError`` carried no hint of what is accepted
    (ADVICE round 5)."""
    if gen not in _PEAK_TFLOPS:
        raise ValueError(
            f"unknown TPU generation {gen!r}; supported: "
            f"{', '.join(sorted(_PEAK_TFLOPS))}")
    return _PEAK_TFLOPS[gen], _HBM_GBPS[gen]


def tpu_generation(device) -> str:
    """Map a device to a generation key for the spec tables.

    ``device.platform`` is only 'tpu'/'cpu' — the generation lives in
    ``device_kind`` (e.g. "TPU v5e", "TPU v5 lite", "TPU v5p") or, under
    the tunneled backend, in ``PALLAS_AXON_TPU_GEN``."""
    import os

    if device.platform == "cpu":
        return "cpu"
    kind = (getattr(device, "device_kind", "") or "").lower()
    env = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    for probe in (kind, env):
        if "v5e" in probe or "v5 lite" in probe or "v5lite" in probe:
            return "v5e"
        if "v5p" in probe or probe == "v5" or "v5 pod" in probe:
            return "v5p"
        if "v6e" in probe or "v6 lite" in probe or "trillium" in probe:
            return "v6e"
        if "v4" in probe:
            return "v4"
    return "default"


@dataclasses.dataclass
class WorkerAttr:
    """Per-device attributes for the Decider (the reference's
    ``WorkerAttribute`` {throughput, memoryCapacity}, ``topo.cuh:26-41``)."""

    throughput: float  # expert-FFN throughput, experts/ms (higher = faster)
    memory_gb: float


@dataclasses.dataclass
class Adjacency:
    """alpha[i,j] ms latency, beta[i,j] ms/MB inverse bandwidth."""

    alpha: np.ndarray
    beta: np.ndarray

    @property
    def n(self) -> int:
        return self.alpha.shape[0]

    def transfer_ms(self, i: int, j: int, mbytes: float) -> float:
        return float(self.alpha[i, j] + self.beta[i, j] * mbytes)

    def export(self, path: str, rank: int = 0):
        """Dump the adjacency to text (the reference's ``exportTopo``
        debug dump, ``bootstrap.cuh:69-96``, which writes
        ``adjMatrix_Rank{r}.txt`` per rank)."""
        with open(path, "w") as f:
            f.write(f"# adjacency rank={rank} n={self.n}\n")
            f.write("# alpha (ms)\n")
            for row in self.alpha:
                f.write(" ".join(f"{v:.6f}" for v in row) + "\n")
            f.write("# beta (ms/MB)\n")
            for row in self.beta:
                f.write(" ".join(f"{v:.6f}" for v in row) + "\n")


def default_ring(n: int) -> np.ndarray:
    """The fused kernel's default source schedule: row r processes
    sources (r, r+1, ..., r-1) — ``order[r, s] = (r + s) mod n``.  The
    single definition both :mod:`flashmoe_tpu.runtime.bootstrap` (to
    suppress redundant tables) and the kernel launcher compare against."""
    r = np.arange(n, dtype=np.int32)
    return (r[:, None] + r[None, :]) % n


def arrival_order(adj: Adjacency, payload_mb: float,
                  stagger_ms: float = 0.0) -> np.ndarray:
    """Per-rank source-processing order for the fused RDMA kernel, sorted
    by predicted slab arrival time.

    Row r is a permutation of ranks starting with r (the own slab is
    local); the remaining sources are ordered by the alpha-beta transfer
    estimate of their slab to r (+ ``stagger_ms`` x ring distance for the
    send-issue stagger of the kernel's phase 1).  On a homogeneous ICI
    torus this reduces to ring order; with heterogeneous links (e.g. a
    DCN hop between slices) slow sources sink to the end so fast slabs
    are never stalled behind them.

    This is the static counterpart of the reference's subscriber, which
    consumes packets in physical arrival order
    (``csrc/include/flashmoe/os/subscriber.cuh:333-451``): a Pallas
    kernel cannot poll semaphores without blocking, so the expected order
    is bound at trace time from the same measured topology the Decider
    uses.  Mispredictions cost stall time but never correctness (every
    slab's recv semaphore is awaited; bound quantified in
    ``scripts/skew_sim.py``).
    """
    n = adj.n
    order = np.empty((n, n), dtype=np.int32)
    for r in range(n):
        others = [s for s in range(n) if s != r]
        # sender s issues its copy toward r at phase-1 step
        # (r - s - 1) mod n (the kernel sends dst = my+1, my+2, ...), so
        # that is the issue-stagger penalty direction; ties keep the
        # kernel's default ring order so stagger_ms=0 is zero-diff
        issue_step = lambda s: (r - s - 1) % n
        ring_dist = lambda s: (s - r) % n
        others.sort(key=lambda s: (adj.transfer_ms(s, r, payload_mb)
                                   + stagger_ms * issue_step(s),
                                   ring_dist(s)))
        order[r, 0] = r
        order[r, 1:] = others
    return order


def _mock_slices(n: int) -> int | None:
    """Parse ``FLASHMOE_MOCK_SLICES`` against a world of ``n`` devices.

    Returns the slice count, or ``None`` when the mock is unset (or
    asks for a single slice — no blocking).  Malformed values are a
    configuration error the job must see at bootstrap, not a silent
    fall-back to the flat transport (the pre-hardening guard was
    parse-only): a non-integer, a non-positive count, or a count that
    does not divide the world size all raise a ``ValueError`` naming
    the world size and the accepted format (docs/PLANNER.md)."""
    import os

    raw = os.environ.get("FLASHMOE_MOCK_SLICES")
    if raw is None or raw.strip() == "":
        return None
    try:
        outer = int(raw)
    except ValueError:
        raise ValueError(
            f"FLASHMOE_MOCK_SLICES={raw!r} is not an integer; the mock "
            f"format is a single positive slice count dividing the "
            f"world size ({n} devices), e.g. FLASHMOE_MOCK_SLICES=2")
    if outer < 1:
        raise ValueError(
            f"FLASHMOE_MOCK_SLICES={outer} must be >= 1 (a positive "
            f"slice count dividing the world size, {n} devices)")
    if outer > 1 and n % outer:
        raise ValueError(
            f"FLASHMOE_MOCK_SLICES={outer} does not divide the world "
            f"size ({n} devices); pick a divisor of {n} so every mocked "
            f"slice holds the same contiguous rank block")
    return outer if outer > 1 else None


def device_slice_ids(devices=None) -> list:
    """Per-device slice membership ids, the ONE resolution every
    consumer shares: ``FLASHMOE_MOCK_SLICES`` (validated by
    :func:`_mock_slices`) partitions the device list into equal
    contiguous blocks; otherwise ``device.slice_index`` with a
    ``process_index`` fallback (0 for non-device objects).  Both the
    blocking detector (:func:`slice_structure`) and the adjacency
    builder (:func:`ici_adjacency`) read membership through this
    helper, so a mocked topology gets DCN-priced edges in the Decider's
    adjacency exactly like a real multislice job."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    mock = _mock_slices(n)
    if mock is not None:
        inner = n // mock
        return [i // inner for i in range(n)]
    sids = [getattr(d, "slice_index", None) for d in devices]
    if any(s is None for s in sids):
        sids = [getattr(d, "process_index", 0) for d in devices]
    return sids


def slice_structure(devices=None) -> tuple[int, int] | None:
    """Detect a (num_slices, ranks_per_slice) blocking of the device
    list, or None when it is a single slice / irregular.

    This is the trigger for the two-stage ICI+DCN exchange
    (:func:`flashmoe_tpu.parallel.ep._hierarchical_a2a`): the TPU
    analogue of the reference resolving P2P vs remote per peer at init
    (``bootstrap.cuh:442-446``) and branching transport per send
    (``os/packet.cuh:221-258``).  Slice membership comes from
    ``device.slice_index`` (fallback ``process_index``); the blocking
    must be contiguous and equal-sized (rank = slice * inner + i), which
    is how jax orders devices on multislice jobs — an interleaved
    ordering returns None and the flat all-to-all stands (correct on any
    layout, just not DCN-message-aggregated).

    ``FLASHMOE_MOCK_SLICES=k`` partitions the first ``n`` devices into
    ``k`` equal contiguous "slices" regardless of their real topology —
    the virtual-mesh hook (CPU devices all share process 0) used by the
    multislice tests, the weak-scaling bench (``bench.py --scaling``)
    and the chaos drills.  Malformed mock values (non-integer,
    non-positive, non-divisor of ``n``) raise a ``ValueError`` naming
    the world size (:func:`_mock_slices`) — a mis-typed mock must fail
    the bootstrap, not silently run the flat transport.
    """
    devices = list(devices if devices is not None else jax.devices())
    return contiguous_blocking(device_slice_ids(devices))


def contiguous_blocking(sids) -> tuple[int, int] | None:
    """(num_blocks, block_size) of a contiguous equal-sized blocking of
    a slice-id sequence, or None when it is single-valued / irregular —
    the structural half of :func:`slice_structure`, public so the
    bootstrap can derive the blocking of an ep PREFIX from the WORLD's
    slice ids (re-running the mock on a subset would mis-partition it,
    and reject world-valid mocks whose count does not divide the
    subset)."""
    sids = list(sids)
    n = len(sids)
    uniq = sorted(set(sids))
    if len(uniq) <= 1:
        return None
    inner = n // len(uniq)
    if inner * len(uniq) != n:
        return None
    # contiguous equal blocks in device order
    for b in range(len(uniq)):
        block = sids[b * inner:(b + 1) * inner]
        if len(set(block)) != 1:
            return None
    return len(uniq), inner


def _torus_hops(a, b, dims):
    """Minimal hop count between coords on a (possibly wrap-around) torus."""
    hops = 0
    for x, y, d in zip(a, b, dims):
        delta = abs(x - y)
        hops += min(delta, d - delta) if d > 2 else delta
    return hops


def ici_adjacency(devices=None, platform: str | None = None) -> Adjacency:
    """Analytic alpha-beta adjacency for the device set.

    Devices on the same slice get torus-hop-scaled ICI costs; devices on
    different slices (different ``slice_index``/process — or different
    mocked blocks under ``FLASHMOE_MOCK_SLICES``, via
    :func:`device_slice_ids`) get DCN costs.  The mock therefore feeds
    the Decider a genuinely heterogeneous adjacency, so DP x EP group
    formation is CI-testable on the virtual CPU mesh.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    plat = platform or tpu_generation(devices[0])
    lat_us, bw = _ICI_SPECS.get(plat, _ICI_SPECS["default"])
    dcn_lat_us, dcn_bw = _DCN_SPEC

    coords = []
    slice_ids = device_slice_ids(devices)
    dims = None
    for d in devices:
        c = getattr(d, "coords", None)
        coords.append(tuple(c) if c is not None else (getattr(d, "id", 0),))
    if coords and all(len(c) == len(coords[0]) for c in coords):
        dims = tuple(
            max(c[k] for c in coords) + 1 for k in range(len(coords[0]))
        )

    alpha = np.zeros((n, n))
    beta = np.zeros((n, n))
    for i in range(n):
        for j in range(n):
            if i == j:
                continue
            if slice_ids[i] != slice_ids[j]:
                alpha[i, j] = dcn_lat_us / 1e3
                beta[i, j] = 1e3 / (dcn_bw * 1e3)  # ms per MB
            else:
                hops = max(
                    1, _torus_hops(coords[i], coords[j], dims or (n,))
                )
                alpha[i, j] = hops * lat_us / 1e3
                # bandwidth is per link; multi-hop paths share links, model
                # as single-link bandwidth with per-hop latency
                beta[i, j] = 1e3 / (bw * 1e3)
    return Adjacency(alpha, beta)


def probe_dcn_costs(sizes_mb=(0.25, 4.0), trials: int = 3,
                    max_pairwise: int = 8):
    """Measure the cross-process alpha-beta adjacency with timed transfers.

    The analogue of the reference's topology-discovery kernel
    (``topo.cuh:207-262``): where each GPU rank times one-sided puts to
    every peer and broadcasts its adjacency row, here each process pair is
    timed with a real cross-process ``ppermute`` carrying only that pair's
    payload (collectives being two-sided, every rank participates in each
    probe anyway, so every process observes every pair's wall time and no
    row broadcast is needed).  Two payload sizes give a slope-intercept
    alpha (ms) / beta (ms/MB) fit per pair.

    Up to ``max_pairwise`` processes every ordered pair is probed
    individually (O(P^2) probes); beyond that, pairs at equal ring offset
    are probed concurrently (O(P) probes — each rank sends to rank+k, so
    the per-offset wall time upper-bounds every pair at that offset).

    Returns (alpha[P, P], beta[P, P]) ndarrays, or None single-process.
    """
    import functools

    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from flashmoe_tpu.utils.compat import shard_map

    p = jax.process_count()
    if p <= 1:
        return None
    devs = jax.devices()
    # one representative device per process (DCN cost is host-level)
    rep = {}
    for d in devs:
        rep.setdefault(d.process_index, d)
    reps = [rep[i] for i in sorted(rep)]
    mesh = Mesh(np.array(reps), ("x",))
    spec = NamedSharding(mesh, PartitionSpec("x"))

    @functools.lru_cache(maxsize=None)
    def probe_fn(perm, rows):
        def body(s):
            return jax.lax.ppermute(s, "x", perm=list(perm))
        return jax.jit(shard_map(
            body, mesh=mesh, in_specs=PartitionSpec("x", None),
            out_specs=PartitionSpec("x", None), check_vma=False,
        ))

    def timed(perm, mb):
        rows = max(1, int(mb * 1024 * 1024 // (4 * 128)))
        x = jax.device_put(
            jnp.zeros((p * rows, 128), jnp.float32), spec
        )
        f = probe_fn(perm, rows)
        jax.block_until_ready(f(x))  # compile + warm
        ts = []
        for _ in range(trials):
            t0 = time.perf_counter()
            jax.block_until_ready(f(x))
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2] * 1e3  # ms

    alpha = np.zeros((p, p))
    beta = np.zeros((p, p))
    small, large = sizes_mb[0], sizes_mb[-1]
    if p <= max_pairwise:
        pairs = [(i, j) for i in range(p) for j in range(p) if i != j]
        for i, j in pairs:
            t_s = timed(((i, j),), small)
            t_l = timed(((i, j),), large)
            b = max((t_l - t_s) / (large - small), 0.0)
            alpha[i, j] = max(t_s - b * small, 0.0)
            beta[i, j] = b
    else:
        for k in range(1, p):
            perm = tuple((i, (i + k) % p) for i in range(p))
            t_s = timed(perm, small)
            t_l = timed(perm, large)
            b = max((t_l - t_s) / (large - small), 0.0)
            a = max(t_s - b * small, 0.0)
            for i in range(p):
                alpha[i, (i + k) % p] = a
                beta[i, (i + k) % p] = b
    return alpha, beta


def merge_dcn_costs(adj: Adjacency, dcn, devices=None) -> Adjacency:
    """Replace the analytic cross-process entries of ``adj`` with measured
    (alpha[P,P], beta[P,P]) DCN costs from :func:`probe_dcn_costs`."""
    if dcn is None:
        return adj
    d_alpha, d_beta = dcn
    devices = list(devices if devices is not None else jax.devices())
    alpha, beta = adj.alpha.copy(), adj.beta.copy()
    for i, di in enumerate(devices):
        for j, dj in enumerate(devices):
            pi, pj = di.process_index, dj.process_index
            if pi != pj:
                alpha[i, j] = d_alpha[pi, pj]
                beta[i, j] = d_beta[pi, pj]
    return Adjacency(alpha, beta)


def device_memory_gb(device) -> float:
    """Usable memory for one device, measured live when the runtime exposes
    it (the reference's ``estimateMemory`` sizes capacity from actually-free
    VRAM, ``bootstrap.cuh:98-111``), else a per-generation table.
    ``FLASHMOE_MEMORY_GB`` overrides (tests / chaos drills)."""
    import os

    override = os.environ.get("FLASHMOE_MEMORY_GB")
    if override:
        return float(override)
    try:
        stats = device.memory_stats()
        if stats:
            limit = stats.get("bytes_limit") or stats.get(
                "bytes_reservable_limit")
            used = stats.get("bytes_in_use", 0)
            if limit:
                return (limit - used) / 1e9
    except Exception:
        pass
    return {
        "v4": 32.0, "v5e": 16.0, "v5p": 95.0, "v6e": 32.0,
    }.get(tpu_generation(device), 16.0)


def measured_worker_attrs(devices=None, cfg=None,
                          probe: bool = False) -> list[WorkerAttr]:
    """Per-device throughput/memory attributes.

    With ``probe=True`` the expert-FFN throughput is *measured* on this
    process's backend (:mod:`flashmoe_tpu.runtime.throughput`, the
    reference's ``mT`` probe) and, in multi-process jobs, exchanged so
    every process sees every worker's real rate — heterogeneous workers
    then shift the Decider's rate-proportional expert assignment.
    ``FLASHMOE_THROUGHPUT_SCALE`` scales this process's measured rate
    (fault/skew injection for tests, like the reference's synthetic
    ``testDecider`` workers).
    """
    import os

    devices = list(devices if devices is not None else jax.devices())
    throughput = 1.0
    if probe:
        from flashmoe_tpu.config import MoEConfig
        from flashmoe_tpu.runtime.throughput import measure_expert_throughput

        pcfg = cfg if cfg is not None else MoEConfig()
        if devices[0].platform == "cpu":
            # the virtual backend only needs *relative* rates; shrink the
            # synthetic workload so bootstrap stays fast
            pcfg = pcfg.replace(
                hidden_size=min(512, pcfg.hidden_size),
                intermediate_size=min(512, pcfg.intermediate_size),
            )
        throughput = measure_expert_throughput(
            pcfg, experts=min(4, pcfg.num_experts), rows_per_expert=64,
        )
    throughput *= float(os.environ.get("FLASHMOE_THROUGHPUT_SCALE", "1.0"))

    per_process = {jax.process_index(): throughput}
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        rates = multihost_utils.process_allgather(
            np.array([throughput], np.float64)
        ).reshape(-1)
        per_process = {i: float(r) for i, r in enumerate(rates)}

    return [
        WorkerAttr(
            throughput=per_process.get(d.process_index, throughput),
            memory_gb=device_memory_gb(d),
        )
        for d in devices
    ]
