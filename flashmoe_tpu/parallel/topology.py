"""Interconnect topology: analytic ICI cost model + DCN probing.

The reference measures its network empirically: a device kernel times
pairwise small/large NVSHMEM puts and slope-intercept fits alpha (latency,
ms) / beta (ms/MB) per peer (``csrc/include/flashmoe/topo.cuh:43-82``), with
block-specialized publishers for remote vs P2P paths, and each rank
broadcasting its adjacency row (``topo.cuh:207-262``).

On TPU the intra-slice network is a known torus: geometry comes from
``device.coords`` and per-generation link specs, so the alpha-beta adjacency
matrix is *derived*, not probed (no warm-up kernels, no measurement noise).
Probing remains meaningful across slices (DCN), where
:func:`probe_dcn_costs` times real transfers the same way the reference
does — but over XLA collectives.

The produced ``Adjacency`` feeds the Decider
(:mod:`flashmoe_tpu.parallel.decider`) exactly like the reference's
``adjMatrix`` feeds ``Decider::operator()``.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import numpy as np

# Per-generation link characteristics (one-way, per ICI link).
# Sources: public TPU system papers / scaling-book numbers; conservative.
_ICI_SPECS = {
    # gen: (latency_us, GB/s per link direction)
    "v4": (1.0, 50.0),
    "v5e": (1.0, 45.0),
    "v5p": (1.0, 90.0),
    "v6e": (1.0, 90.0),
    "cpu": (10.0, 10.0),  # virtual/testing backend
    "default": (1.0, 45.0),
}
_DCN_SPEC = (10.0, 25.0)  # (latency_us, GB/s) per host NIC, conservative


@dataclasses.dataclass
class WorkerAttr:
    """Per-device attributes for the Decider (the reference's
    ``WorkerAttribute`` {throughput, memoryCapacity}, ``topo.cuh:26-41``)."""

    throughput: float  # expert-FFN throughput, experts/ms (higher = faster)
    memory_gb: float


@dataclasses.dataclass
class Adjacency:
    """alpha[i,j] ms latency, beta[i,j] ms/MB inverse bandwidth."""

    alpha: np.ndarray
    beta: np.ndarray

    @property
    def n(self) -> int:
        return self.alpha.shape[0]

    def transfer_ms(self, i: int, j: int, mbytes: float) -> float:
        return float(self.alpha[i, j] + self.beta[i, j] * mbytes)

    def export(self, path: str, rank: int = 0):
        """Dump the adjacency to text (the reference's ``exportTopo``
        debug dump, ``bootstrap.cuh:69-96``, which writes
        ``adjMatrix_Rank{r}.txt`` per rank)."""
        with open(path, "w") as f:
            f.write(f"# adjacency rank={rank} n={self.n}\n")
            f.write("# alpha (ms)\n")
            for row in self.alpha:
                f.write(" ".join(f"{v:.6f}" for v in row) + "\n")
            f.write("# beta (ms/MB)\n")
            for row in self.beta:
                f.write(" ".join(f"{v:.6f}" for v in row) + "\n")


def _torus_hops(a, b, dims):
    """Minimal hop count between coords on a (possibly wrap-around) torus."""
    hops = 0
    for x, y, d in zip(a, b, dims):
        delta = abs(x - y)
        hops += min(delta, d - delta) if d > 2 else delta
    return hops


def ici_adjacency(devices=None, platform: str | None = None) -> Adjacency:
    """Analytic alpha-beta adjacency for the device set.

    Devices on the same slice get torus-hop-scaled ICI costs; devices on
    different slices (different ``slice_index``/process) get DCN costs.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    plat = platform or devices[0].platform
    lat_us, bw = _ICI_SPECS.get(plat, _ICI_SPECS["default"])
    dcn_lat_us, dcn_bw = _DCN_SPEC

    coords = []
    slice_ids = []
    dims = None
    for d in devices:
        c = getattr(d, "coords", None)
        coords.append(tuple(c) if c is not None else (d.id,))
        slice_ids.append(getattr(d, "slice_index", getattr(d, "process_index", 0)))
    if coords and all(len(c) == len(coords[0]) for c in coords):
        dims = tuple(
            max(c[k] for c in coords) + 1 for k in range(len(coords[0]))
        )

    alpha = np.zeros((n, n))
    beta = np.zeros((n, n))
    for i in range(n):
        for j in range(n):
            if i == j:
                continue
            if slice_ids[i] != slice_ids[j]:
                alpha[i, j] = dcn_lat_us / 1e3
                beta[i, j] = 1e3 / (dcn_bw * 1e3)  # ms per MB
            else:
                hops = max(
                    1, _torus_hops(coords[i], coords[j], dims or (n,))
                )
                alpha[i, j] = hops * lat_us / 1e3
                # bandwidth is per link; multi-hop paths share links, model
                # as single-link bandwidth with per-hop latency
                beta[i, j] = 1e3 / (bw * 1e3)
    return Adjacency(alpha, beta)


def probe_dcn_costs(mesh_devices, sizes_mb=(1.0, 64.0), trials: int = 3):
    """Measure effective alpha/beta between processes by timing device_put
    round-trips (the DCN analogue of the reference's timed puts).  Only
    meaningful in multi-process jobs; returns None single-process."""
    if jax.process_count() <= 1:
        return None
    import jax.numpy as jnp

    results = {}
    for mb in sizes_mb:
        x = jnp.zeros((int(mb * 1024 * 1024 // 4),), jnp.float32)
        t0 = time.perf_counter()
        for _ in range(trials):
            y = jax.device_put(x, mesh_devices[0])
            jax.block_until_ready(y)
        results[mb] = (time.perf_counter() - t0) / trials * 1e3
    small, large = sizes_mb[0], sizes_mb[-1]
    beta = (results[large] - results[small]) / (large - small)
    alpha = max(results[small] - beta * small, 0.0)
    return alpha, beta


def measured_worker_attrs(devices=None) -> list[WorkerAttr]:
    """Per-device throughput/memory attributes.

    Homogeneous TPU slices get uniform attributes from the device kind; the
    throughput probe (:mod:`flashmoe_tpu.runtime.throughput`) refines the
    number with a timed grouped-GEMM when hardware is live.
    """
    devices = list(devices if devices is not None else jax.devices())
    mem = {
        "v4": 32.0, "v5e": 16.0, "v5p": 95.0, "v6e": 32.0,
    }.get(devices[0].platform, 16.0)
    return [WorkerAttr(throughput=1.0, memory_gb=mem) for _ in devices]
