"""Analytical performance planner: predicted per-path latency +
automatic path selection.

Synthesizes the byte/FLOP accounting (:mod:`flashmoe_tpu.analysis`),
the overlap bounds (:mod:`flashmoe_tpu.parallel.overlap`), the
per-generation link/peak tables (:mod:`flashmoe_tpu.parallel.topology`)
and measured tuning entries (:mod:`flashmoe_tpu.tuning`) into a
predicted end-to-end latency per execution path, and a selection policy
(predicted winner, measured-winner override) that
``parallel/ep.py`` / ``models/transformer.py`` (``moe_backend='auto'``)
and ``bench.py`` consult.

CLI::

    python -m flashmoe_tpu.planner --config reference --d 8

Model details: :mod:`flashmoe_tpu.planner.model` docstring and
``docs/PLANNER.md``.
"""

from flashmoe_tpu.planner.adapt import (  # noqa: F401
    MorphPlan, measured_ledger, replan,
)
from flashmoe_tpu.planner.drift import (  # noqa: F401
    DriftRecord, OverlapDriftRecord, drift_report, record_drift,
    record_overlap_drift,
)
from flashmoe_tpu.planner.model import (  # noqa: F401
    BACKEND_OF, PathPrediction, explain_table, predict_paths,
)
from flashmoe_tpu.planner.select import (  # noqa: F401
    Selection, resolve_moe_backend, resolve_moe_plan, select_path,
)
