"""Planner CLI: print the explain-table for any config.

Examples::

    python -m flashmoe_tpu.planner                      # reference, d=8,
                                                        # all generations
    python -m flashmoe_tpu.planner --config mixtral --d 8 --gen v5p
    python -m flashmoe_tpu.planner --slices 2           # ep spans 2 slices
    python -m flashmoe_tpu.planner --wire e4m3          # price fp8 EP wire
    python -m flashmoe_tpu.planner --json               # machine-readable
    python -m flashmoe_tpu.planner --regen-golden       # refresh the
                                                        # CI-gated tables
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys


def main(argv=None) -> int:
    from flashmoe_tpu.config import BENCH_CONFIGS, MoEConfig
    from flashmoe_tpu.planner.golden import GOLDEN_GENS, write_golden
    from flashmoe_tpu.planner.model import explain_table
    from flashmoe_tpu.planner.select import select_path

    ap = argparse.ArgumentParser(prog="python -m flashmoe_tpu.planner")
    ap.add_argument("--config", default="reference",
                    help="BENCH_CONFIGS name or path to a "
                         "flashmoe_config.json")
    ap.add_argument("--d", type=int, default=8,
                    help="expert-parallel ranks (1 = single chip)")
    ap.add_argument("--gen", action="append", default=None,
                    choices=list(GOLDEN_GENS),
                    help="TPU generation(s); default: all supported")
    ap.add_argument("--slices", type=int, default=1,
                    help="DCN-connected slices the ep axis spans")
    ap.add_argument("--links", type=int, default=4,
                    help="ICI links per chip serving the exchange")
    ap.add_argument("--mxu", type=float, default=1.0,
                    help="achieved fraction of peak matmul throughput "
                         "(1.0 = roofline; pass a measured mxu_util "
                         "for a calibrated prediction)")
    ap.add_argument("--wire", default=None,
                    help="EP payload wire dtype for the dispatch leg "
                         "(bf16 / e4m3 / e5m2; default off)")
    ap.add_argument("--wire-combine", default=None,
                    help="EP payload wire dtype for the combine leg "
                         "(default off — high-precision returns)")
    ap.add_argument("--wire-dcn", default=None,
                    help="per-hop wire for the CROSS-SLICE stage of "
                         "the hierarchical a2a (fp8 across DCN; "
                         "meaningful with --slices > 1)")
    ap.add_argument("--chunks", type=int, default=None,
                    help="price the chunked double-buffered a2a "
                         "pipeline at this depth "
                         "(MoEConfig.a2a_chunks; default serial)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON document instead of tables")
    ap.add_argument("--write-golden", "--regen-golden",
                    dest="write_golden", action="store_true",
                    help="regenerate the CI-gated golden tables "
                         "(includes the wire-dtype dimension)")
    args = ap.parse_args(argv)

    if args.write_golden:
        path = write_golden()
        print(f"wrote {path}")
        return 0

    if args.config in BENCH_CONFIGS:
        cfg = BENCH_CONFIGS[args.config]
    else:
        cfg = MoEConfig.from_json(args.config)
    if args.wire or args.wire_combine:
        cfg = cfg.replace(wire_dtype=args.wire,
                          wire_dtype_combine=args.wire_combine)
    if args.wire_dcn:
        cfg = cfg.replace(wire_dtype_dcn=args.wire_dcn)
    if args.chunks and args.chunks > 1:
        cfg = cfg.replace(a2a_chunks=args.chunks)
    gens = args.gen or list(GOLDEN_GENS)

    doc = {"config": args.config, "d": args.d, "slices": args.slices,
           "gens": {}}
    for gen in gens:
        sel = select_path(cfg, args.d, gen, slices=args.slices,
                          links=args.links, mxu_fraction=args.mxu,
                          record=False)
        preds = sel.predictions
        if args.json:
            doc["gens"][gen] = {
                "winner": sel.winner, "backend": sel.backend,
                "mode": sel.mode, "measured": sel.measured,
                "paths": [
                    {k: v for k, v in dataclasses.asdict(p).items()
                     if k != "cost"}
                    for p in preds
                ],
            }
            continue
        wire_tag = ""
        if cfg.wire_dtype or cfg.wire_dtype_combine:
            wire_tag = (f" wire={cfg.wire_dtype or 'off'}/"
                        f"{cfg.wire_dtype_combine or 'off'}")
        if cfg.wire_dtype_dcn:
            wire_tag += f" wire_dcn={cfg.wire_dtype_dcn}"
        if cfg.a2a_chunks:
            wire_tag += f" chunks={cfg.a2a_chunks}"
        print(f"\n# {args.config}: E={cfg.num_experts} "
              f"k={cfg.expert_top_k} H={cfg.hidden_size} "
              f"I={cfg.intermediate_size} S={cfg.tokens} "
              f"d={args.d} gen={gen} slices={args.slices} "
              f"mxu={args.mxu:.2f}{wire_tag}")
        print(explain_table(preds))
        if sel.mode == "measured":
            print(f"winner: {sel.winner} (MEASURED "
                  f"{sel.measured_ms:.3f} ms beats prediction; "
                  f"predicted winner was {sel.predicted_winner}) -> "
                  f"moe_backend={sel.backend!r}")
        else:
            print(f"predicted winner: {sel.winner} "
                  f"({sel.predicted_ms:.3f} ms) -> "
                  f"moe_backend={sel.backend!r}")
    if args.json:
        json.dump(doc, sys.stdout)
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
