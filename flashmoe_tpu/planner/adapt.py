"""Drift-corrected mid-job re-selection: the planner half of the
self-healing runtime controller (:mod:`flashmoe_tpu.runtime.controller`).

PR 1's planner selects an execution path ONCE, from analytic priors
(plus committed measurements); PR 8's profiler measures where reality
disagrees.  This module closes that gap RaMP-style (runtime-aware
polymorphism, arXiv 2604.26039): given the live telemetry the
controller accumulates — the measured cost of the path actually running
and the observed routing shape — re-run the selection with the
*measured* ledger overriding the analytic prior for the running path,
and emit a :class:`MorphPlan` the runner can re-jit onto at a step
boundary (``models/transformer._resolved_plan`` re-resolves on the
fresh trace).

Two morph axes:

* **path re-selection** — :func:`replan` prices every feasible
  candidate with the measured ledger CORRECTING the analytic prior for
  the families it covers (deliberately NOT select_path's
  measured-winner rule: with only the running path measured, that rule
  would re-elect the degraded path it was meant to demote), so a path
  that has drifted slow in production loses to the next candidate on
  real numbers; the chunk sweep and wire identity ride along
  unchanged.
* **capacity -> dropless morphing** — when the trigger is *token
  drops* (sustained routing skew overflowing the capacity buffers, the
  chaos harness's ``skew_sustained`` drill), latency re-pricing cannot
  help: the capacity-format paths are pricing tokens they THREW AWAY.
  ``prefer_dropless=True`` then targets a dropless execution: the
  ragged transport when the planner prices it feasible at this width,
  else the same path with ``drop_tokens=False`` (capacity = all
  tokens).

Everything here is a pure host-side query — no graph is touched until
the runner rebuilds its step with the returned overrides.
"""

from __future__ import annotations

import dataclasses

from flashmoe_tpu.config import MoEConfig


@dataclasses.dataclass(frozen=True)
class MorphPlan:
    """One re-selection verdict: the config overrides a runner applies
    (``cfg.replace(**overrides)``) before re-jitting, plus the evidence
    trail for the ``controller.morph`` decision record."""

    overrides: dict             # MoEConfig.replace kwargs ({} = no-op)
    backend: str                # execution path the morph targets
    a2a_chunks: int | None
    dropless: bool              # True when the morph disables drops
    mode: str                   # 'reselect' | 'dropless' | 'noop'
    predicted_ms: float | None  # target's predicted latency (d>1 only)
    reason: str

    @property
    def is_noop(self) -> bool:
        return not self.overrides


def measured_ledger(family: str, measured_ms: float) -> dict:
    """The measured-override dict for :func:`replan`: the running
    path's family priced at its OBSERVED per-step MoE cost.  Thin, but
    named — the controller and tests build the ledger through one
    spelling."""
    return {family: float(measured_ms)}


def current_family(cfg: MoEConfig, d: int) -> str:
    """The measurement family of the path ``cfg`` is running at width
    ``d`` (what :func:`replan`'s measured override should be keyed
    by)."""
    if d <= 1 or cfg.ep <= 1:
        return "local"
    if cfg.moe_backend == "auto":
        from flashmoe_tpu.planner.select import resolve_moe_plan

        return resolve_moe_plan(cfg)[0]
    return cfg.moe_backend


def replan(cfg: MoEConfig, d: int = 1, *, gen: str | None = None,
           measured_ms: dict | None = None,
           prefer_dropless: bool = False,
           slices: int = 1) -> MorphPlan:
    """Re-select the MoE execution strategy from live telemetry.

    ``measured_ms``: {path_family: observed ms} — the drift-corrected
    ledger (:func:`measured_ledger`); it overrides the analytic prior
    for those families exactly like a committed tuning measurement.
    ``prefer_dropless``: the trigger is token drops, not latency — the
    morph must land on a dropless execution (see module docstring).

    Single-chip widths (``d <= 1``) have one execution path, so the
    only meaningful morph is the dropless flip."""
    if prefer_dropless and not cfg.drop_tokens:
        return MorphPlan({}, current_family(cfg, d), cfg.a2a_chunks,
                         dropless=True, mode="noop", predicted_ms=None,
                         reason="already dropless")
    if d <= 1:
        if prefer_dropless:
            return MorphPlan(
                {"drop_tokens": False}, "local", None, dropless=True,
                mode="dropless", predicted_ms=None,
                reason="single-chip capacity path overflowing: disable "
                       "token drops (capacity = all tokens)")
        return MorphPlan({}, "local", None, dropless=False, mode="noop",
                         predicted_ms=None,
                         reason="single-chip: nothing to re-select")

    from flashmoe_tpu import tuning
    from flashmoe_tpu.planner.select import select_path

    gen = gen or tuning.generation()
    # NOTE: the ledger is deliberately NOT passed through select_path's
    # ``measured=`` override.  That rule elects the fastest MEASURED
    # family over every prediction — correct for committed tuning
    # entries (all families measured), but with a single live entry
    # (the running path, measured precisely because it drifted SLOW)
    # the degraded path would be the only measured family and therefore
    # always re-elect itself.  Here the measurement must CORRECT the
    # running family's prior and then compete against the other
    # families' priors.
    sel = select_path(cfg, d, gen, slices=slices, record=False,
                      sweep_chunks=True)

    if prefer_dropless:
        # target the dropless transport the planner prices feasible at
        # this width; ragged is the native dropless path — fall back to
        # the capacity transport with drops disabled when it is not
        # runnable for this config
        ragged_ok = (not cfg.num_shared_experts and cfg.tp == 1 and any(
            p.feasible and p.family == "ragged" for p in sel.predictions))
        if ragged_ok:
            pred = min((p for p in sel.predictions
                        if p.feasible and p.family == "ragged"),
                       key=lambda p: p.total_ms)
            over: dict = {"drop_tokens": False}
            if cfg.moe_backend != "ragged":
                over["moe_backend"] = "ragged"
            if cfg.a2a_chunks is not None:
                over["a2a_chunks"] = None  # re-swept by the new path
            return MorphPlan(
                over, "ragged", None, dropless=True, mode="dropless",
                predicted_ms=pred.total_ms,
                reason="sustained drops: morph onto the dropless "
                       "ragged transport")
        return MorphPlan(
            {"drop_tokens": False}, sel.backend, sel.a2a_chunks,
            dropless=True, mode="dropless", predicted_ms=sel.predicted_ms,
            reason="sustained drops: ragged not runnable here — "
                   "disable token drops on the current transport")

    # drift-corrected comparison: each feasible family's cost is its
    # measured ms when the ledger covers it, else its analytic prior —
    # the slow running path now competes on its REAL number
    ledger = dict(measured_ms or {})
    feasible = [p for p in sel.predictions if p.feasible]
    by_family: dict = {}
    for p in feasible:
        cost = ledger.get(p.family, p.total_ms)
        prev = by_family.get(p.family)
        if prev is None or cost < prev[0]:
            by_family[p.family] = (cost, p)
    if not by_family:
        return MorphPlan({}, sel.backend, sel.a2a_chunks,
                         dropless=not cfg.drop_tokens, mode="noop",
                         predicted_ms=sel.predicted_ms,
                         reason="no feasible candidate to re-select")
    _, win = min(by_family.values(), key=lambda t: (t[0], t[1].family))

    over = {}
    if win.backend != current_family(cfg, d) \
            and win.backend != cfg.moe_backend:
        over["moe_backend"] = win.backend
    chunks = win.a2a_chunks if win.a2a_chunks and win.a2a_chunks > 1 \
        else None
    if chunks != cfg.a2a_chunks:
        over["a2a_chunks"] = chunks
    mode = "reselect" if over else "noop"
    return MorphPlan(
        over, win.backend, chunks, dropless=not cfg.drop_tokens,
        mode=mode, predicted_ms=win.total_ms,
        reason=(f"measured-corrected re-selection: {win.family!r} beats "
                f"the running path's observed cost" if over else
                "re-selection confirms the running path"))
