"""Planner drift monitor: predicted vs measured, continuously.

PR 1's planner predicts per-path latency and selects execution paths
from those predictions (plus committed measurements).  Nothing, however,
measured the *prediction error in production* or said when the golden
tables have drifted from reality — the feedback loop RaMP
(arXiv:2604.26039) closes by selecting kernels from measured runtime
signals.  This module is that loop's sensor: every real timing that
flows through it is compared against the analytical prediction for the
same (config, path, d, generation) point, the relative error lands in
telemetry as a ``planner.drift`` decision (plus an error histogram), and
errors past a threshold raise a visible warning that the cost model /
golden tables need recalibration.

Wired in: ``bench.py`` records drift for every executed path;
``python -m flashmoe_tpu.observe`` summarizes accumulated drift records
offline (:func:`drift_report`).
"""

from __future__ import annotations

import dataclasses
import os
import warnings

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.utils.telemetry import metrics

# Relative-error tolerance before a drift warning fires.  The cost model
# is a roofline — it deliberately predicts a *bound*, so real kernels sit
# above it by a config-dependent factor; 0.5 flags only gross divergence
# (a schedule regression, a stale golden table, wrong generation pin),
# not normal roofline optimism.  FLASHMOE_DRIFT_THRESHOLD overrides.
DEFAULT_THRESHOLD = 0.5


def drift_threshold() -> float:
    try:
        return float(os.environ.get("FLASHMOE_DRIFT_THRESHOLD",
                                    DEFAULT_THRESHOLD))
    except ValueError:
        return DEFAULT_THRESHOLD


@dataclasses.dataclass(frozen=True)
class DriftRecord:
    """One predicted-vs-measured comparison."""

    path: str
    gen: str
    d: int
    predicted_ms: float
    measured_ms: float
    rel_error: float            # measured / predicted - 1 (signed)
    threshold: float
    exceeded: bool


def record_drift(cfg: MoEConfig, path: str, measured_ms: float, *,
                 d: int = 1, gen: str | None = None,
                 predicted_ms: float | None = None,
                 threshold: float | None = None,
                 warn: bool = True) -> DriftRecord:
    """Compare one measured latency against the planner's prediction.

    ``path`` is a planner path or family name ('explicit', 'fused',
    'collective', ...).  ``predicted_ms=None`` asks the cost model for
    the prediction (fastest row of the family at this point); pass the
    value a caller already computed to keep the two sides consistent.
    The comparison is recorded as a ``planner.drift`` telemetry decision
    and in the ``planner.drift_abs_rel_error`` histogram; past the
    threshold a RuntimeWarning names the likely causes.
    """
    from flashmoe_tpu import tuning

    gen = gen or tuning.generation()
    if predicted_ms is None:
        from flashmoe_tpu.planner.model import predict_paths

        preds = predict_paths(cfg, d, gen)
        match = [p for p in preds if p.path == path or p.family == path]
        if not match:
            raise ValueError(
                f"no prediction for path {path!r} at d={d}; candidates: "
                f"{sorted({p.path for p in preds})}")
        predicted_ms = min(p.total_ms for p in match)
    if predicted_ms <= 0:
        raise ValueError(f"predicted_ms must be > 0, got {predicted_ms}")
    threshold = drift_threshold() if threshold is None else threshold
    rel = measured_ms / predicted_ms - 1.0
    exceeded = abs(rel) > threshold
    rec = DriftRecord(path=path, gen=gen, d=int(d),
                      predicted_ms=float(predicted_ms),
                      measured_ms=float(measured_ms),
                      rel_error=float(rel), threshold=float(threshold),
                      exceeded=exceeded)
    metrics.decision(
        "planner.drift", path=path, gen=gen, d=int(d),
        predicted_ms=round(float(predicted_ms), 4),
        measured_ms=round(float(measured_ms), 4),
        rel_error=round(float(rel), 4), threshold=float(threshold),
        exceeded=exceeded,
        config=dict(e=cfg.num_experts, k=cfg.expert_top_k,
                    h=cfg.hidden_size, i=cfg.intermediate_size,
                    s=cfg.tokens, wire=cfg.wire_dtype or "off",
                    wire_combine=cfg.wire_dtype_combine or "off"))
    metrics.histogram("planner.drift_abs_rel_error", abs(rel))
    if exceeded and warn:
        warnings.warn(
            f"planner drift on {path!r} (gen={gen}, d={d}): measured "
            f"{measured_ms:.3f} ms vs predicted {predicted_ms:.3f} ms "
            f"({rel:+.0%}, threshold ±{threshold:.0%}) — the cost model "
            f"or golden tables may be stale for this shape; recalibrate "
            f"with `python -m flashmoe_tpu.planner --write-golden` or "
            f"pass a measured mxu_fraction", RuntimeWarning, stacklevel=2)
    return rec


@dataclasses.dataclass(frozen=True)
class PhaseDriftRecord:
    """One per-phase predicted-vs-measured comparison (the cost ledger,
    :mod:`flashmoe_tpu.profiler.ledger`)."""

    path: str
    phase: str
    gen: str
    d: int
    chunks: int
    wire: str
    predicted_ms: float
    measured_ms: float
    rel_error: float            # measured / predicted - 1 (signed)
    threshold: float
    exceeded: bool


def record_phase_drift(cfg: MoEConfig, path: str, phase: str,
                       measured_ms: float, *, predicted_ms: float,
                       d: int = 1, gen: str | None = None,
                       threshold: float | None = None,
                       warn: bool = True) -> PhaseDriftRecord:
    """Compare one measured MoE *phase* time (gate / dispatch a2a /
    expert FFN / combine a2a — the profiler's timeline,
    :mod:`flashmoe_tpu.profiler.spans`) against the analytical model's
    prediction of that same phase.

    This is :func:`record_drift` at phase granularity: where the
    end-to-end monitor can only say "the layer is slower than priced",
    per-phase drift says WHICH term of the cost model is wrong — an
    a2a leg drifting alone points at the transport model (or a sick
    link), the expert phase drifting alone at the roofline's
    mxu_fraction.  Recorded as a ``planner.phase_drift`` decision plus
    the ``planner.phase_drift_abs_rel_error`` histogram; warns past the
    threshold like its end-to-end sibling."""
    from flashmoe_tpu import tuning
    from flashmoe_tpu.ops import wire as wr

    gen = gen or tuning.generation()
    if predicted_ms <= 0:
        raise ValueError(f"predicted_ms must be > 0, got {predicted_ms}")
    threshold = drift_threshold() if threshold is None else threshold
    rel = measured_ms / predicted_ms - 1.0
    exceeded = abs(rel) > threshold
    wire_tag = (f"{wr.canonical_name(cfg.wire_dtype)}/"
                f"{wr.canonical_name(cfg.wire_dtype_combine)}")
    rec = PhaseDriftRecord(
        path=path, phase=phase, gen=gen, d=int(d),
        chunks=int(cfg.a2a_chunks or 1), wire=wire_tag,
        predicted_ms=float(predicted_ms), measured_ms=float(measured_ms),
        rel_error=float(rel), threshold=float(threshold),
        exceeded=exceeded)
    metrics.decision(
        "planner.phase_drift", path=path, phase=phase, gen=gen,
        d=int(d), chunks=rec.chunks, wire=wire_tag,
        predicted_ms=round(float(predicted_ms), 6),
        measured_ms=round(float(measured_ms), 6),
        rel_error=round(float(rel), 4), threshold=float(threshold),
        exceeded=exceeded,
        config=dict(e=cfg.num_experts, k=cfg.expert_top_k,
                    h=cfg.hidden_size, i=cfg.intermediate_size,
                    s=cfg.tokens))
    metrics.histogram("planner.phase_drift_abs_rel_error", abs(rel))
    if exceeded and warn:
        warnings.warn(
            f"phase drift on {path!r}/{phase} (gen={gen}, d={d}): "
            f"measured {measured_ms:.4f} ms vs predicted "
            f"{predicted_ms:.4f} ms ({rel:+.0%}, threshold "
            f"±{threshold:.0%}) — this phase's cost-model term is "
            f"stale for this shape", RuntimeWarning, stacklevel=2)
    return rec


@dataclasses.dataclass(frozen=True)
class OverlapDriftRecord:
    """One predicted-vs-measured overlap-fraction comparison (the
    chunked-pipeline validation loop, ``bench.py --overlap``)."""

    path: str
    gen: str
    d: int
    chunks: int
    predicted_fraction: float
    measured_fraction: float
    rel_error: float            # measured / predicted - 1 (signed)
    threshold: float
    exceeded: bool


def record_overlap_drift(path: str, measured_fraction: float, *,
                         predicted_fraction: float, gen: str, d: int,
                         chunks: int = 1,
                         threshold: float | None = None,
                         warn: bool = True) -> OverlapDriftRecord:
    """Compare a measured overlap efficiency (``measure_overlap``)
    against the analytic bound for the same schedule
    (``overlap.chunked_overlap_bound`` for the chunked XLA pipeline,
    ``overlap.overlap_bound`` for the fused kernel).

    Same contract as :func:`record_drift`, on the dimensionless overlap
    fraction: a ``planner.overlap_drift`` telemetry decision, an
    ``planner.overlap_drift_abs_rel_error`` histogram observation, and
    a RuntimeWarning past the threshold — a chunked schedule whose
    measured hiding falls far short of the priced hiding means the
    pipeline model (or the chunk pick it drives) is stale for this
    shape."""
    if predicted_fraction <= 0:
        raise ValueError(
            f"predicted_fraction must be > 0, got {predicted_fraction}")
    threshold = drift_threshold() if threshold is None else threshold
    rel = measured_fraction / predicted_fraction - 1.0
    exceeded = abs(rel) > threshold
    rec = OverlapDriftRecord(
        path=path, gen=gen, d=int(d), chunks=int(chunks),
        predicted_fraction=float(predicted_fraction),
        measured_fraction=float(measured_fraction),
        rel_error=float(rel), threshold=float(threshold),
        exceeded=exceeded)
    metrics.decision(
        "planner.overlap_drift", path=path, gen=gen, d=int(d),
        chunks=int(chunks),
        predicted_fraction=round(float(predicted_fraction), 4),
        measured_fraction=round(float(measured_fraction), 4),
        rel_error=round(float(rel), 4), threshold=float(threshold),
        exceeded=exceeded)
    metrics.histogram("planner.overlap_drift_abs_rel_error", abs(rel))
    if exceeded and warn:
        warnings.warn(
            f"overlap-fraction drift on {path!r} (gen={gen}, d={d}, "
            f"chunks={chunks}): measured {measured_fraction:.3f} vs "
            f"predicted {predicted_fraction:.3f} ({rel:+.0%}, threshold "
            f"±{threshold:.0%}) — the chunked-pipeline model may be "
            f"stale for this shape; re-sweep a2a_chunks on hardware "
            f"(tuning_data README) or recalibrate with a measured "
            f"mxu_fraction", RuntimeWarning, stacklevel=2)
    return rec


def _as_drift_fields(rec: dict) -> dict | None:
    """Normalize a JSONL record to drift fields, or None.

    Accepts ``planner.drift`` decision records and bench.py records
    (which carry ``predicted_ms`` / ``value`` / ``path``)."""
    if rec.get("decision") == "planner.drift":
        return rec
    if ("predicted_ms" in rec and "value" in rec
            and isinstance(rec.get("value"), (int, float))):
        pred = rec["predicted_ms"]
        if not isinstance(pred, (int, float)) or pred <= 0:
            return None
        meas = float(rec["value"])
        return {
            "path": rec.get("predicted_path") or rec.get("path", "?"),
            "gen": rec.get("planner_gen", "?"),
            "d": rec.get("d", 1),
            "predicted_ms": float(pred),
            "measured_ms": meas,
            "rel_error": rec.get("prediction_error",
                                 meas / float(pred) - 1.0),
            "exceeded": rec.get("drift_exceeded", False),
        }
    return None


def drift_report(records: list[dict]) -> dict:
    """Summarize drift across a pile of JSONL records (decision logs,
    bench records, flight-recorder dumps — unrecognized records are
    skipped).  Per (path, gen): count, mean/worst |relative error|, and
    how many comparisons exceeded their threshold."""
    by_key: dict[str, dict] = {}
    seen: set = set()
    n = exceeded = 0
    for raw in records:
        d = _as_drift_fields(raw)
        if d is None:
            continue
        # bench.py mirrors each measurement into a planner.drift decision
        # (record_drift), so an obs-dir pair (bench_records.jsonl +
        # decisions.jsonl) presents the SAME comparison twice — dedup on
        # the (path, gen, d, predicted, measured) identity the mirror
        # preserves exactly.  Records without both numbers (synthetic /
        # partial) carry no such identity and always count.
        pred = d.get("predicted_ms")
        meas = d.get("measured_ms")
        if isinstance(pred, (int, float)) and pred > 0 \
                and isinstance(meas, (int, float)) and meas > 0:
            # 3 decimals: the coarser of the two mirrors' precisions
            # (bench rounds value to 3, record_drift measured_ms to 4)
            ident = (d.get("path"), d.get("gen"), d.get("d"),
                     round(float(pred), 3), round(float(meas), 3))
            if ident in seen:
                continue
            seen.add(ident)
        n += 1
        exceeded += bool(d.get("exceeded"))
        key = f"{d.get('path', '?')}@{d.get('gen', '?')}"
        b = by_key.setdefault(key, {
            "path": d.get("path", "?"), "gen": d.get("gen", "?"),
            "n": 0, "exceeded": 0, "mean_abs_rel_error": 0.0,
            "worst_rel_error": 0.0,
        })
        rel = float(d.get("rel_error", 0.0))
        b["n"] += 1
        b["exceeded"] += bool(d.get("exceeded"))
        b["mean_abs_rel_error"] += abs(rel)
        if abs(rel) > abs(b["worst_rel_error"]):
            b["worst_rel_error"] = rel
    for b in by_key.values():
        b["mean_abs_rel_error"] = round(b["mean_abs_rel_error"] / b["n"], 4)
        b["worst_rel_error"] = round(b["worst_rel_error"], 4)
    return {"n": n, "exceeded": exceeded,
            "by_path": dict(sorted(by_key.items()))}
