"""CI-gated golden prediction tables.

``golden.json`` (committed next to this module) freezes the planner's
predicted per-path latencies and winners on the canonical configs at
d=8 across every supported generation AND every golden knob variant:
the wire-dtype dimension (EP payload compression off / fp8,
``MoEConfig.wire_dtype``) crossed with the chunked-pipeline dimension
(serial / 4-chunk double-buffered a2a, ``MoEConfig.a2a_chunks`` —
chunk variants whose count does not divide the config's local-expert
axis are skipped, e.g. mixtral's nLx=1 at d=8).
``tests/test_planner.py`` recomputes and compares: any change to the
cost model, the kernels' schedule resolution, or the spec tables that
moves a prediction by more than the tolerance — or flips a predicted
winner — fails CI and must be re-approved by regenerating the table
(``python -m flashmoe_tpu.planner --regen-golden``) in the same PR, so
the diff shows exactly which numbers moved.
"""

from __future__ import annotations

import json
import os

from flashmoe_tpu.planner.model import predict_paths

GOLDEN_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "golden.json")
GOLDEN_CONFIGS = ("reference", "mixtral", "deepseek")
GOLDEN_GENS = ("v4", "v5e", "v5p", "v6e")
GOLDEN_D = 8
# the wire-dtype dimension: raw payloads and the activation-default fp8
# wire (dispatch leg e4m3, combine leg high-precision — the recommended
# production split, docs/PERF.md).  Keyed by the canonical wire tag.
GOLDEN_WIRES = {"off": {}, "e4m3": {"wire_dtype": "e4m3"}}
# the chunked-pipeline dimension (MoEConfig.a2a_chunks): the serial
# schedule and the 4-chunk double-buffered pipeline.  Keyed by the
# chunk tag; variants whose count does not divide a config's
# local-expert axis at GOLDEN_D are skipped for that config
# (golden_chunk_variants).
GOLDEN_CHUNKS = {"serial": {}, "c4": {"a2a_chunks": 4}}
# relative tolerance of the CI gate: generous enough for float noise,
# far below any modeling change worth reviewing
GOLDEN_RTOL = 1e-3
# the decode-mode dimension: per-step decode batch the serving regime
# is priced at (planner.model.decode_shape) — frozen so the decode-vs-
# training plan split (docs/SERVING.md) is itself golden-gated
GOLDEN_DECODE_TOKENS = 64
# the multi-slice weak-scaling dimension (ISSUE 13): the ep axis
# spanning 1/2/4/8 DCN-connected slices at d=8.  At each scale the
# planner's path/wire/chunk picks are frozen, along with the modeled
# DCN serialization of the flat-uncompressed exchange vs the
# hierarchical exchange with the fp8 DCN-hop wire — the acceptance gate
# that fp8-across-DCN + per-slice-pair aggregation beats flat
# (tests/test_planner.py::test_golden_slices_dimension_gates_dcn_wire)
GOLDEN_SLICES = (1, 2, 4, 8)
GOLDEN_WIRE_DCN = "e4m3"
# the quantized-expert-storage dimension (ISSUE 15,
# MoEConfig.expert_quant): full-precision weights vs the int8
# per-output-channel store.  Each point freezes the chunk-swept plan
# plus the fused[rowwin]-vs-collective race terms — the headline gate
# (tests/test_quant.py) is that int8 cuts the modeled fused[rowwin]
# weight-stream time to <= 0.55x its full-precision value on the
# mixtral point and thereby closes (or flips) the recorded
# rowwin-vs-collective margin.
GOLDEN_QUANT = {"off": {}, "int8": {"expert_quant": "int8"}}
# the disaggregated-fabric dimension (ISSUE 16,
# MoEConfig.kv_wire_dtype): the modeled DCN cost of handing one
# prefilled prompt's KV pages from the prefill pool to a decode
# replica (planner.model.kv_handoff_ms over _DCN_SPEC), wire off vs
# the fp8 page wire, next to the decode-priced per-step plan — frozen
# so the overlap verdict (does a handoff hide under one decode step?)
# is itself golden-gated (tests/test_fabric.py)
GOLDEN_KV_WIRES = {"off": None, "e4m3": "e4m3"}
GOLDEN_KV_PAGE = 16       # page_size the fabric dimension prices at
GOLDEN_KV_PAGES = 8       # pages per handed-off prompt (128 tokens)
# the speculative-decode dimension (ISSUE 20,
# ServeConfig.speculate): the one-token decode step vs the
# draft_tokens+1 verify span at the decode batch, the modeled
# tokens/step uplift at the reference acceptance, and the break-even
# acceptance the controller's spec-morph trigger compares against —
# frozen so the economics of speculation (cost ratio near 1 at
# wire/HBM-bound decode shapes => uplift > 1) are themselves
# golden-gated (tests/test_planner.py)
GOLDEN_SPEC_K = 3          # drafted tokens per slot priced
GOLDEN_SPEC_ACCEPT = 0.7   # reference acceptance the uplift is quoted at

_TERMS = ("compute_ms", "hbm_ms", "ici_ms", "dcn_ms", "total_ms")


def golden_chunk_variants(cfg) -> dict:
    """The GOLDEN_CHUNKS variants this config can run at GOLDEN_D: a
    chunk count must divide the local-expert axis (and the config's
    own ep-local axis, so ``cfg.replace`` constructs)."""
    nlx_d = cfg.num_experts // GOLDEN_D
    nlx_cfg = cfg.num_experts // max(cfg.ep, 1)
    return {cname: knobs for cname, knobs in GOLDEN_CHUNKS.items()
            if not knobs
            or (nlx_d and nlx_d % knobs["a2a_chunks"] == 0
                and nlx_cfg % knobs["a2a_chunks"] == 0)}


def _predicted_plan(cfg, gen: str, mode: str, slices: int = 1) -> dict:
    """Hermetic (prediction-only) plan for one (cfg, gen, mode) point:
    the fastest feasible prediction across the chunk sweep — the same
    sweep ``select_path(sweep_chunks=True)`` runs, minus the measured
    overrides (a golden table must not depend on the writer's env)."""
    from flashmoe_tpu.planner.select import _chunk_candidates

    best = None  # (total_ms, n, prediction)
    for n in _chunk_candidates(cfg, GOLDEN_D):
        cfg_n = (cfg if n == (cfg.a2a_chunks or 1)
                 else cfg.replace(a2a_chunks=None if n == 1 else n))
        preds = predict_paths(
            cfg_n, GOLDEN_D, gen, mode=mode, slices=slices,
            decode_tokens=GOLDEN_DECODE_TOKENS)
        pw = next((p for p in preds if p.feasible), None)
        if pw is None:
            continue
        if best is None or (pw.total_ms, n) < (best[0], best[1]):
            best = (pw.total_ms, n, pw)
    total, n, pw = best
    return {"winner": pw.path, "backend": pw.backend,
            "chunks": pw.a2a_chunks, "total_ms": round(total, 6)}


def _slice_point(cfg, gen: str, s: int) -> dict:
    """One frozen weak-scaling point: the chunk-swept plan with the
    wire off and with the fp8 DCN-hop wire, plus the modeled DCN
    serialization of the flat-uncompressed vs hierarchical+fp8-DCN
    exchanges (the acceptance comparison; ``None`` fields at s=1 —
    a single slice has no DCN hop)."""
    cfg_dcn = cfg.replace(wire_dtype_dcn=GOLDEN_WIRE_DCN)
    point = {
        "plan": _predicted_plan(cfg, gen, "training", slices=s),
        "plan_dcn": _predicted_plan(cfg_dcn, gen, "training", slices=s),
        "flat_dcn_ms": None, "hier_dcn_ms": None,
        "hier_dcn_wins": None,
    }
    if s > 1:
        flat = {p.path: p for p in predict_paths(cfg, GOLDEN_D, gen,
                                                 slices=s)}
        hier = {p.path: p for p in predict_paths(cfg_dcn, GOLDEN_D, gen,
                                                 slices=s)}
        f = flat["collective"].dcn_ms
        h = hier["hierarchical"].dcn_ms
        point.update(flat_dcn_ms=round(f, 6), hier_dcn_ms=round(h, 6),
                     hier_dcn_wins=bool(h < f))
    return point


def _quant_point(cfg, gen: str) -> dict:
    """One frozen quant point: the chunk-swept plan at this store plus
    the fused[rowwin]-vs-collective race decomposition (the PR 11
    mixtral verdict re-derived per store — weight-stream ms is the
    term the int8 store halves/quarters)."""
    from flashmoe_tpu.planner.model import _dtype_peak

    preds = {p.path: p for p in predict_paths(cfg, GOLDEN_D, gen)}
    _, hbm_bs = _dtype_peak(gen, cfg)
    rw, coll = preds["fused[rowwin]"], preds["collective"]
    rw_w_ms = rw.cost.weight_bytes / hbm_bs * 1e3
    return {
        "plan": _predicted_plan(cfg, gen, "training"),
        "rowwin_feasible": rw.feasible,
        "rowwin_weight_ms": round(rw_w_ms, 6),
        "rowwin_total_ms": round(rw.total_ms, 6),
        "collective_total_ms": round(coll.total_ms, 6),
        # the recorded race: < 1 means the fused rowwin schedule beats
        # the collective path on modeled latency at this store
        "rowwin_vs_collective": round(rw.total_ms / coll.total_ms, 6),
        "rowwin_beats_collective": bool(rw.feasible
                                        and rw.total_ms < coll.total_ms),
    }


def _fabric_point(cfg, gen: str) -> dict:
    """One frozen fabric point: the decode-priced per-step plan plus
    the modeled KV-handoff cost per wire (page MB at the wire row
    size, DCN ms over ``_DCN_SPEC``) and the overlap verdict — whether
    a whole prompt's page stream hides under one modeled decode step
    (the Comet-style transfer/compute overlap the fabric records on
    every ``fabric.handoff`` decision)."""
    from flashmoe_tpu.planner.model import kv_handoff_ms, kv_page_mb

    de = _predicted_plan(cfg, gen, "decode")
    point = {"decode_plan": de, "wires": {}}
    for tag, wire in GOLDEN_KV_WIRES.items():
        mb = kv_page_mb(cfg, GOLDEN_KV_PAGE, wire=wire)
        ms = kv_handoff_ms(cfg, GOLDEN_KV_PAGES, GOLDEN_KV_PAGE,
                           wire=wire)
        point["wires"][tag] = {
            "page_mb": round(mb, 6),
            "handoff_ms": round(ms, 6),
            "overlapped": bool(ms <= de["total_ms"]),
        }
    point["fp8_saves"] = bool(
        point["wires"]["e4m3"]["handoff_ms"]
        < point["wires"]["off"]["handoff_ms"])
    return point


def _speculate_point(cfg, gen: str) -> dict:
    """One frozen speculation point: decode-step vs verify-span cost at
    the golden decode batch, the modeled tokens/step uplift at the
    reference acceptance, and the break-even acceptance
    (:func:`~flashmoe_tpu.planner.model.speculate_break_even`) the
    ``controller.spec_morph`` trigger compares the live acceptance EMA
    against.  The acceptance gate: uplift > 1 with break-even well
    under the reference acceptance on every golden decode config."""
    from flashmoe_tpu.planner.model import (speculate_break_even,
                                            speculate_uplift)

    up = speculate_uplift(cfg, GOLDEN_D, gen,
                          decode_tokens=GOLDEN_DECODE_TOKENS,
                          verify_tokens=GOLDEN_SPEC_K,
                          accept_rate=GOLDEN_SPEC_ACCEPT)
    be = speculate_break_even(cfg, GOLDEN_D, gen,
                              decode_tokens=GOLDEN_DECODE_TOKENS,
                              verify_tokens=GOLDEN_SPEC_K)
    return {
        "verify_tokens": GOLDEN_SPEC_K,
        "accept_rate": GOLDEN_SPEC_ACCEPT,
        "decode_ms": round(up["t1_ms"], 6),
        "verify_ms": round(up["tk_ms"], 6),
        "cost_ratio": round(up["cost_ratio"], 6),
        "tokens_per_step": round(up["tokens_per_step"], 6),
        "uplift": round(up["uplift"], 6),
        "break_even_accept": round(be, 6),
        "pays": bool(up["uplift"] > 1.0 and be < GOLDEN_SPEC_ACCEPT),
    }


def golden_snapshot() -> dict:
    """Recompute the full golden structure from the live model."""
    from flashmoe_tpu.config import BENCH_CONFIGS

    out = {"d": GOLDEN_D, "configs": {}, "decode": {}, "slices": {},
           "quant": {}, "fabric": {}, "speculate": {}}
    for name in GOLDEN_CONFIGS:
        cfg = BENCH_CONFIGS[name]
        out["fabric"][name] = {gen: _fabric_point(cfg, gen)
                               for gen in GOLDEN_GENS}
        out["speculate"][name] = {gen: _speculate_point(cfg, gen)
                                  for gen in GOLDEN_GENS}
    for name in GOLDEN_CONFIGS:
        cfg = BENCH_CONFIGS[name]
        gens = {}
        for gen in GOLDEN_GENS:
            gens[gen] = {qtag: _quant_point(cfg.replace(**qknobs), gen)
                         for qtag, qknobs in GOLDEN_QUANT.items()}
        out["quant"][name] = gens
    for name in GOLDEN_CONFIGS:
        cfg = BENCH_CONFIGS[name]
        gens = {}
        for gen in GOLDEN_GENS:
            gens[gen] = {str(s): _slice_point(cfg, gen, s)
                         for s in GOLDEN_SLICES}
        out["slices"][name] = gens
    for name in GOLDEN_CONFIGS:
        cfg = BENCH_CONFIGS[name]
        gens = {}
        for gen in GOLDEN_GENS:
            tr = _predicted_plan(cfg, gen, "training")
            de = _predicted_plan(cfg, gen, "decode")
            gens[gen] = {
                "training": tr, "decode": de,
                # the serving thesis, CI-gated: decode steps must NOT
                # inherit the training-shaped plan wholesale — at least
                # the overlap schedule (chunks), usually the path too,
                # re-resolves at decode token counts
                "differs": (tr["winner"], tr["chunks"])
                != (de["winner"], de["chunks"]),
            }
        out["decode"][name] = gens
    for name in GOLDEN_CONFIGS:
        cfg = BENCH_CONFIGS[name]
        gens = {}
        for gen in GOLDEN_GENS:
            wires = {}
            for wname, wknobs in GOLDEN_WIRES.items():
                chunks = {}
                for cname, cknobs in golden_chunk_variants(cfg).items():
                    preds = predict_paths(
                        cfg.replace(**wknobs, **cknobs), GOLDEN_D, gen)
                    winner = next(p for p in preds if p.feasible)
                    chunks[cname] = {
                        "winner": winner.path,
                        "backend": winner.backend,
                        "paths": {
                            p.path: dict(
                                {t: round(getattr(p, t), 6)
                                 for t in _TERMS},
                                feasible=p.feasible)
                            for p in preds
                        },
                    }
                wires[wname] = chunks
            gens[gen] = wires
        out["configs"][name] = gens
    return out


def write_golden(path: str = GOLDEN_PATH) -> str:
    with open(path, "w") as f:
        json.dump(golden_snapshot(), f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def load_golden(path: str = GOLDEN_PATH) -> dict:
    with open(path) as f:
        return json.load(f)
