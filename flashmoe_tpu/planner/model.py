"""Predicted end-to-end latency per execution path — the roofline
synthesis layer.

Rounds 1-5 built the ingredients separately: per-path HBM bytes and
FLOPs (:mod:`flashmoe_tpu.analysis`), the fused kernel's schedule-aware
overlap bound (:mod:`flashmoe_tpu.parallel.overlap`), per-generation
link/peak tables (:mod:`flashmoe_tpu.parallel.topology`), and the
ICI+DCN two-stage transport model (``analysis.a2a_transport_cost``).
The round-5 verdict's highest-leverage gap: nowhere did the framework
combine its bytes and its overlap bound into a predicted per-path
latency and state which path should win.  This module is that
combination — one number per candidate path, decomposed into the terms
that produce it, so the prediction is arguable line by line.

Latency model (per chip, one MoE-layer forward, ``d`` expert-parallel
ranks, uniform routing):

  compute_ms   ``PathCost.flops`` at the generation's peak matmul
               throughput x ``mxu_fraction`` (1.0 = roofline; pass a
               measured ``mxu_util`` for a calibrated prediction).
               f32 runs at half the bf16 peak.
  hbm_ms       ``PathCost.total_bytes`` at the generation's HBM
               bandwidth — the analysis module's per-path accounting,
               consumed verbatim so the planner can never drift from
               the CI-gated byte model.
  chip_ms      max(compute_ms, hbm_ms): the on-chip roofline (MXU and
               HBM pipelines overlap within a kernel).
  ici_ms       wire serialization of the expert all-to-all on this
               rank's ICI links, both directions, alpha included.  Each
               leg serializes at its own wire-dtype row size
               (``MoEConfig.wire_dtype`` / ``wire_dtype_combine``,
               priced via ``analysis.wire_row_bytes``), so fp8/bf16
               payload compression shrinks this term — and disqualifies
               the fused RDMA rows, whose transport moves raw slabs.
  dcn_ms       cross-slice share of that exchange when the ep axis
               spans slices (``a2a_transport_cost``: flat per-peer
               messages for the collective path, one aggregated message
               per slice pair for the hierarchical path).
  serial_ms    chip_ms + ici_ms + dcn_ms — the no-overlap makespan.
  total_ms     the overlap-adjusted prediction:
               * collective / ragged / hierarchical, serial schedule
                 (``a2a_chunks`` off): = serial_ms.  The dispatch
                 exchange must land before the FFN and the return
                 exchange starts after it, so within one layer XLA
                 cannot hide either leg (its latency-hiding scheduler
                 overlaps across surrounding ops, which this per-layer
                 model conservatively ignores);
               * same paths with ``MoEConfig.a2a_chunks = n``: the
                 chunked-pipeline makespan
                 (``analysis.chunked_pipeline_ms``) — chunk k's FFN
                 hides chunk k+1's exchange on both legs, at the price
                 of n per-peer message alphas per leg
                 (``a2a_transport_cost(chunks=n)``);
               * fused[schedule]: the kernel's arrival overlap, the
                 same makespan shapes as ``overlap.overlap_bound`` with
                 chip_ms in place of pure compute —
                 per-source (resident/stream):
                   T = max(chip, t_x + chip/d) + t_x/(d-1)
                 arrival-batched:
                   T = max(chip/d, t_x) + (d-1)/d * chip + t_x/nLx
                 row-windowed (rowwin): the batched makespan with the
                 finer per-row-tile return tail
                   T = max(chip/d, t_x) + (d-1)/d * chip
                       + t_x/(nLx * n_row_tiles)
                 where t_x is the one-direction egress serialization.

Every path the framework can execute is a row; rows the configuration
cannot run (VMEM-infeasible schedule, fused across DCN, gather kernel
in training) are kept but marked infeasible with the reason, so the
explain-table shows WHY a path is out, not just that it is.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from flashmoe_tpu.analysis import (
    PathCost, a2a_transport_cost, chunked_pipeline_ms, path_costs,
)
from flashmoe_tpu.config import MoEConfig

# planner path name -> the moe_backend string that runs it
BACKEND_OF = {
    "collective": "collective",
    "hierarchical": "collective",   # same layer, two-stage dcn_inner a2a
    "ragged": "ragged",
    "fused[batched]": "fused",
    "fused[resident]": "fused",
    "fused[stream]": "fused",
    "fused[rowwin]": "fused",
    "fused_combine": "fused",
    # single-chip paths (d == 1): ops/moe.py dispatch, not an ep backend
    "xla": "local",
    "explicit": "local",
    "gather": "local",
}


@dataclasses.dataclass(frozen=True)
class PathPrediction:
    """One explain-table row: the predicted latency decomposition of a
    single candidate path."""

    path: str
    backend: str
    schedule: str | None       # fused rows: the FFN schedule priced
    compute_ms: float
    hbm_ms: float
    ici_ms: float
    dcn_ms: float
    serial_ms: float           # no-overlap makespan
    total_ms: float            # overlap-adjusted prediction
    feasible: bool
    note: str                  # why infeasible / which overlap model
    cost: PathCost             # the byte decomposition priced
    wire: str = "off/off"      # wire dtypes priced (dispatch/combine
                               # legs, canonical names; "off/off" = raw)
    a2a_chunks: int = 1        # chunked-pipeline depth priced (XLA
                               # transports; 1 = serial schedule; the
                               # fused rows always carry 1 — their
                               # in-kernel transport ignores the knob)
    dp_allreduce_ms: float = 0.0  # DP gradient-ring share included in
                               # serial_ms/total_ms (0 unless the
                               # caller priced a dp axis; same value on
                               # every row of one prediction set)
    quant: str = "off"         # expert-weight store priced
                               # (MoEConfig.expert_quant canonical
                               # name; "off" = full-precision weights)

    @property
    def family(self) -> str:
        """Path name without the schedule qualifier ('fused[batched]'
        -> 'fused') — the granularity measurements are recorded at."""
        return self.path.split("[")[0]


def _dtype_peak(gen: str, cfg: MoEConfig) -> tuple[float, float]:
    """(peak FLOP/s at cfg.dtype, HBM B/s) — ValueError on unknown gen."""
    from flashmoe_tpu.parallel.topology import chip_spec

    peak_tf, hbm_gb = chip_spec(gen)
    if jnp.dtype(cfg.dtype).itemsize >= 4:  # staticcheck: ok static config dtype — host metadata, never a tracer
        peak_tf /= 2.0              # f32 runs the MXU at half rate
    return peak_tf * 1e12, hbm_gb * 1e9


def _ici_link(gen: str) -> tuple[float, float]:
    """(alpha_ms, one-way B/ms per link)."""
    from flashmoe_tpu.parallel.topology import _ICI_SPECS

    lat_us, gbps = _ICI_SPECS.get(gen, _ICI_SPECS["default"])
    return lat_us / 1e3, gbps * 1e6


def a2a_leg_ms(slab: float, kind: str, *, d: int, gen: str,
               slices: int = 1, links: int = 4,
               chunks: int = 1,
               dcn_slab: float | None = None) -> tuple[float, float]:
    """(ici_ms, dcn_ms) of ONE exchange leg moving a ``slab`` of bytes
    at its wire row size, per-message alpha multiplied by the chunk
    count (``analysis.a2a_transport_cost``).  Public because it is THE
    per-leg pricing formula: ``predict_paths`` prices every XLA row
    through it and the profiler's cost ledger
    (:func:`flashmoe_tpu.profiler.ledger.predicted_phase_ms`) prices
    each measured a2a phase through the same call, so planner and
    ledger can never price the same bytes differently.  ``kind``
    selects the ``a2a_transport_cost`` row when the exchange spans
    slices (> 1); single-slice legs use the closed flat form.
    ``dcn_slab``: the slab at the CROSS-SLICE hop's own wire row size
    (``MoEConfig.wire_dtype_dcn``; None = inherit ``slab``) — only the
    hierarchical DCN stage re-encodes, so only that row's dcn term
    moves."""
    a_ici, bw_link = _ici_link(gen)
    if slices > 1:
        t = a2a_transport_cost(d, d // slices, slab, gen=gen,
                               links=links, chunks=chunks,
                               dcn_slab_bytes=dcn_slab)[kind]
        return t["ici_ms"], t["dcn_ms"]
    return (d - 1) * (chunks * a_ici + slab / (bw_link * links)), 0.0


def slab_bytes(cfg: MoEConfig, d: int, *, padded: bool = False,
               leg: str = "dispatch", hop: str = "ici") -> float:
    """One (dest-rank) capacity slab: the unit both exchanges move.
    Public because the collective census
    (:mod:`flashmoe_tpu.staticcheck.census` via ``analysis.comm_census``)
    reconciles the lowered graph's all_to_all operand bytes against
    exactly ``d x slab_bytes`` per exchange leg — the planner's pricing
    unit is statically checked against what the layer actually sends.

    ``padded``: the fused kernel RDMAs capacity padded to a 32-multiple
    (the same padding ``analysis._geom`` prices); the collective layer
    exchanges the unpadded ``[E, C, H]`` buffer (``ep._ep_moe_shard``).
    ``leg`` selects which exchange is priced: rows serialize at that
    leg's WIRE row size (``analysis.wire_row_bytes`` — compute row size
    when ``wire_dtype`` is off), so compression shrinks the ici/dcn
    terms by the wire/compute itemsize ratio.  ``hop`` ('ici'/'dcn')
    selects the stage of a two-stage multi-slice exchange: 'dcn'
    prices at the ``wire_dtype_dcn`` override when set."""
    from flashmoe_tpu.analysis import wire_row_bytes
    from flashmoe_tpu.parallel.ep import local_capacity

    s_loc = cfg.tokens // d
    cap = local_capacity(cfg, s_loc)
    nlx = cfg.num_experts // d
    if padded:
        # fused kernel slabs: raw compute rows, 32-padded — the RDMA
        # transport never compresses (config.py rejects fused + wire)
        cap = -(-cap // 32) * 32
        return nlx * cap * cfg.hidden_size * jnp.dtype(cfg.dtype).itemsize
    return nlx * cap * wire_row_bytes(cfg, leg, hop)


def dp_allreduce_ms(cfg: MoEConfig, dp: int, gen: str, *,
                    over_dcn: bool = False, links: int = 4) -> float:
    """Per-step gradient-allreduce time of the DP axis, priced from the
    Decider's ring model (:func:`flashmoe_tpu.parallel.decider.
    ring_allreduce_ms`, the reference's ``ARArgs`` pricing): ``2(G-1)``
    chunks of ``grad / G`` over the bottleneck hop — the host DCN NIC
    when the DP groups live on different slices (``over_dcn=True``),
    the chip's striped ICI links otherwise.  0 for inference jobs or
    ``dp <= 1``.

    This is the term that lets the planner trade EP-across-DCN against
    DP-across-DCN (``select.scaleout_plan``): packing the ep axis
    inside a slice frees the a2a from DCN but pushes the gradient ring
    across it — whichever axis moves fewer bytes per step should own
    the slow hop."""
    if dp <= 1 or not cfg.is_training:
        return 0.0
    from flashmoe_tpu.parallel.decider import ring_allreduce_ms
    from flashmoe_tpu.parallel.topology import _DCN_SPEC, _ICI_SPECS

    grad_mb = (cfg.param_count
               * jnp.dtype(cfg.param_dtype).itemsize) / 1e6
    if over_dcn:
        lat_us, gbps = _DCN_SPEC
        beta = 1e3 / (gbps * 1e3)                       # ms per MB
    else:
        lat_us, gbps = _ICI_SPECS.get(gen, _ICI_SPECS["default"])
        beta = 1e3 / (gbps * 1e3 * max(links, 1))
    return ring_allreduce_ms(grad_mb, dp, beta, lat_us / 1e3)


def kv_page_mb(cfg: MoEConfig, page_size: int, *, wire=None) -> float:
    """MB one KV page pair (K + V, all layers) weighs on the handoff
    wire: ``2 x L x N_kv x page x D`` elements at the wire's row
    itemsize, plus the per-(layer, page) f32 ``_qscale`` sidecars the
    fp8 wires add (one per K row and one per V row — the fabric codec
    quantizes each (layer, page) block as ONE wire row)."""
    from flashmoe_tpu.ops import wire as wr

    wire_dt = wr.resolve(wire) if isinstance(wire, str) else wire
    nkv, dh = cfg.resolved_num_kv_heads, cfg.resolved_head_dim
    row = nkv * int(page_size) * dh
    per_layer = 2 * (wr.payload_row_bytes(wire_dt, row, cfg.dtype)
                     + wr.scale_bytes(wire_dt))
    return cfg.num_layers * per_layer / 1e6


def kv_handoff_ms(cfg: MoEConfig, pages: int, page_size: int, *,
                  wire=None) -> float:
    """Modeled DCN time to stream one finished prefill's ``pages`` KV
    pages from the prefill pool to a decode replica: one message (the
    run ships as a unit) over the host NIC —
    ``_DCN_SPEC`` alpha + bytes / DCN bandwidth, the same spec that
    prices ``dp_allreduce_ms``'s DCN arm and the cross-slice a2a hop.
    The fabric records this per handoff (``fabric.handoff``) and the
    golden ``fabric`` dimension gates it against the decode-step
    objective it must hide under."""
    from flashmoe_tpu.parallel.topology import _DCN_SPEC

    lat_us, gbps = _DCN_SPEC
    mb = max(int(pages), 0) * kv_page_mb(cfg, page_size, wire=wire)
    return lat_us / 1e3 + (mb / 1e3) / gbps * 1e3


#: Default per-step decode token count priced when ``mode='decode'``
#: and no explicit decode batch is given.  Decode steps move the decode
#: BATCH through the layer (each token then fans out ``top_k`` exchange
#: rows) — not B x S like training — so this is the token count every
#: decode-mode term is priced at.
DECODE_TOKENS_DEFAULT = 64


def decode_shape(cfg: MoEConfig, d: int = 1,
                 decode_tokens: int | None = None,
                 verify_tokens: int | None = None) -> MoEConfig:
    """The per-STEP problem a decode engine actually runs: ``tokens`` =
    the decode batch (``decode_tokens``, rounded up so the ranks
    divide it), inference mode.  This is the config the planner prices
    when ``mode='decode'`` — per-step tokens = batch x ``top_k``
    exchange rows, the regime where per-message alphas dominate the
    tiny slabs and the training-shaped schedule sweeps pick wrong
    (RaMP, arXiv 2604.26039; the reference's inference-mode Decider
    specialization, ``decider.cuh:177-268``).

    ``verify_tokens`` (ISSUE 20): drafted tokens ``k`` a speculative
    verify step scores on top of the canonical token — every slot
    feeds a ``k + 1`` position span, so the step moves
    ``decode_tokens x (k + 1)`` token rows through the layer.  The
    decode-vs-verify cost RATIO at this shape is the whole economics
    of speculation: at wire/HBM-bound decode shapes it sits near 1."""
    toks = int(decode_tokens if decode_tokens else DECODE_TOKENS_DEFAULT)
    if toks < 1:
        raise ValueError(f"decode_tokens={decode_tokens!r} must be >= 1")
    if verify_tokens is not None and int(verify_tokens) < 0:
        raise ValueError(
            f"verify_tokens={verify_tokens!r} must be >= 0")
    d = max(int(d), 1)
    toks = -(-toks // d) * d          # ranks must divide the step batch
    toks *= 1 + int(verify_tokens or 0)
    return cfg.replace(sequence_len=toks, mini_batch=1,
                       is_training=False)


def predict_paths(cfg: MoEConfig, d: int = 1, gen: str = "v5e", *,
                  slices: int = 1, links: int = 4,
                  mxu_fraction: float = 1.0, mode: str = "training",
                  decode_tokens: int | None = None,
                  verify_tokens: int | None = None,
                  dp: int = 1, dp_over_dcn: bool = False
                  ) -> list[PathPrediction]:
    """Predict every candidate path's latency at (cfg, d ranks, gen).

    ``slices``: how many DCN-connected slices the ep axis spans (1 =
    single slice); ``links``: ICI links per chip serving the exchange;
    ``mxu_fraction``: achieved fraction of peak matmul throughput.
    Rows are returned fastest-first among feasible, infeasible last.

    ``dp`` / ``dp_over_dcn``: price the DP axis's per-step gradient
    allreduce (:func:`dp_allreduce_ms`, training only) into every row —
    a constant across paths, so it never flips a path winner, but it
    makes predictions comparable ACROSS slice mappings: EP spanning the
    slices (``slices>1, dp_over_dcn=False``) vs EP packed per slice
    with the DP ring riding DCN (``slices=1, dp_over_dcn=True``) — the
    trade ``select.scaleout_plan`` makes.

    ``mode``: the pricing regime — ``'training'`` (default) prices the
    config's own B x S step; ``'decode'`` re-shapes it first
    (:func:`decode_shape`: per-step tokens = ``decode_tokens``, the
    decode batch — times ``verify_tokens + 1`` when a speculative
    verify span is priced); ``'prefill'`` keeps the full-sequence
    shape but prices inference-mode feasibility (the gather kernel
    qualifies).
    """
    if mode not in ("training", "prefill", "decode"):
        raise ValueError(
            f"mode {mode!r} not in ('training', 'prefill', 'decode')")
    if verify_tokens and mode != "decode":
        raise ValueError("verify_tokens prices the speculative verify "
                         "span — decode mode only")
    if mode == "decode":
        cfg = decode_shape(cfg, d, decode_tokens, verify_tokens)
    elif mode == "prefill" and cfg.is_training:
        cfg = cfg.replace(is_training=False)
    peak_fs, hbm_bs = _dtype_peak(gen, cfg)   # validates gen first
    if d < 1:
        raise ValueError(f"d={d} must be >= 1")
    if d > 1 and cfg.num_experts % d:
        raise ValueError(f"E={cfg.num_experts} not divisible by d={d}")
    if d > 1 and cfg.tokens % d:
        raise ValueError(f"S={cfg.tokens} not divisible by d={d}")
    if slices < 1 or d % slices:
        raise ValueError(f"d={d} not divisible into {slices} slices")
    mxu_fraction = max(min(mxu_fraction, 1.0), 1e-6)
    a_ici, bw_link = _ici_link(gen)
    rows = []

    from flashmoe_tpu.ops import wire as wr

    wire_tag = (f"{wr.canonical_name(cfg.wire_dtype)}/"
                f"{wr.canonical_name(cfg.wire_dtype_combine)}")
    wire_dcn_tag = wr.canonical_name(cfg.wire_dtype_dcn)
    if wire_dcn_tag != "off":
        wire_tag += f"/dcn:{wire_dcn_tag}"
    wire_on = wire_tag != "off/off"
    from flashmoe_tpu.quant import core as qcore

    quant_tag = qcore.canonical_name(cfg.expert_quant)
    ar_ms = dp_allreduce_ms(cfg, dp, gen, over_dcn=dp_over_dcn,
                            links=links)
    n_chunks = cfg.a2a_chunks or 1
    if n_chunks > 1 and d > 1 and (cfg.num_experts // d) % n_chunks:
        raise ValueError(
            f"a2a_chunks={n_chunks} does not divide the local-expert "
            f"axis (num_experts={cfg.num_experts} // d={d} = "
            f"{cfg.num_experts // d})")

    def mk(path, cost, ici_ms, dcn_ms, total_ms=None, schedule=None,
           feasible=True, note="", wire="off/off", chunks=1):
        compute_ms = cost.flops / (peak_fs * mxu_fraction) * 1e3
        hbm_ms = cost.total_bytes / hbm_bs * 1e3
        chip_ms = max(compute_ms, hbm_ms)
        # the DP gradient ring serializes after the step's MoE work on
        # every path alike (ar_ms = 0 unless a dp axis was priced)
        serial_ms = chip_ms + ici_ms + dcn_ms + ar_ms
        rows.append(PathPrediction(
            path=path, backend=BACKEND_OF[path], schedule=schedule,
            compute_ms=compute_ms, hbm_ms=hbm_ms, ici_ms=ici_ms,
            dcn_ms=dcn_ms, serial_ms=serial_ms,
            total_ms=serial_ms if total_ms is None else total_ms + ar_ms,
            feasible=feasible, note=note, cost=cost, wire=wire,
            a2a_chunks=chunks, dp_allreduce_ms=ar_ms,
            quant=quant_tag))
        return rows[-1]

    if d == 1:
        for p in ("xla", "explicit", "gather"):
            infeas = p == "gather" and cfg.is_training
            mk(p, path_costs(cfg, p, d_world=1), 0.0, 0.0,
               feasible=not infeas,
               note="inference-only kernel" if infeas else "on-chip roofline")
        rows.sort(key=lambda r: (not r.feasible, r.total_ms))
        return rows

    from flashmoe_tpu.parallel.fused import schedule_table

    def one_leg(slab, dcn_slab=None, *, kind):
        return a2a_leg_ms(slab, kind, d=d, gen=gen, slices=slices,
                          links=links, chunks=n_chunks,
                          dcn_slab=dcn_slab)

    def xla_row(path, cost, slab_by_leg, kind, note):
        """One XLA-transport row: legs priced separately (each at its
        own wire row size and chunked alpha), summed for the ici/dcn
        report; with a2a_chunks > 1 the overlap-adjusted total is the
        chunked-pipeline makespan (``analysis.chunked_pipeline_ms``)
        instead of the serial sum — chunk k's FFN hides chunk k+1's
        exchange on both legs.  ``slab_by_leg`` entries are either a
        slab or a (slab, dcn_slab) pair — the hierarchical row prices
        its DCN hop at the ``wire_dtype_dcn`` row size."""
        legs = [one_leg(*(slab if isinstance(slab, tuple) else (slab,)),
                        kind=kind) for slab in slab_by_leg]
        ici = sum(l[0] for l in legs)
        dcn = sum(l[1] for l in legs)
        total = None
        if n_chunks > 1:
            compute_ms = cost.flops / (peak_fs * mxu_fraction) * 1e3
            chip_ms = max(compute_ms, cost.total_bytes / hbm_bs * 1e3)
            total = chunked_pipeline_ms(chip_ms, sum(legs[0]),
                                        sum(legs[1]), n_chunks)
            note += f" [chunked a2a x{n_chunks} pipeline]"
        mk(path, cost, ici, dcn, total_ms=total, wire=wire_tag,
           note=note, chunks=n_chunks)

    slab_legs = [slab_bytes(cfg, d, leg="dispatch"),
                 slab_bytes(cfg, d, leg="combine")]
    wire_note = f" [wire {wire_tag}]" if wire_on else ""

    # --- collective EP: capacity slabs, flat all_to_all ---------------
    coll_note = ("capacity slabs" if n_chunks > 1 else
                 "serialized a2a (XLA cannot hide it within the layer)")
    xla_row("collective", path_costs(cfg, "explicit", d_world=d),
            slab_legs, "flat", coll_note + wire_note)

    # --- hierarchical two-stage ICI+DCN (multi-slice only) ------------
    if slices > 1:
        # the DCN hop serializes at its own wire row size when
        # wire_dtype_dcn is set (fp8 across DCN under a raw/bf16 ICI
        # hop); the ICI hop stays at the leg wire.  At inner=1 (one
        # rank per slice) the decomposition degenerates to the flat
        # exchange — the layer gates the two-stage path on
        # 1 < dcn_inner < d and never re-encodes there, so the row
        # must not price a discount the transport cannot deliver.
        dcn_applies = d // slices > 1 and wire_dcn_tag != "off"
        hier_legs = [(slab_bytes(cfg, d, leg=leg),
                      slab_bytes(cfg, d, leg=leg,
                                 hop="dcn" if dcn_applies else "ici"))
                     for leg in ("dispatch", "combine")]
        hier_note = "one aggregated DCN message per slice pair"
        if dcn_applies:
            hier_note += f" [dcn hop {wire_dcn_tag}]"
        elif wire_dcn_tag != "off":
            hier_note += " [dcn wire inert: one rank per slice]"
        xla_row("hierarchical", path_costs(cfg, "explicit", d_world=d),
                hier_legs, "hierarchical", hier_note + wire_note)

    # --- ragged / dropless EP: routed rows, no capacity padding -------
    from flashmoe_tpu.analysis import wire_row_bytes

    rag = path_costs(cfg, "ragged", d_world=d)
    rag_rows = (cfg.tokens // d) * cfg.expert_top_k / d
    xla_row("ragged", rag,
            [rag_rows * wire_row_bytes(cfg, "dispatch"),
             rag_rows * wire_row_bytes(cfg, "combine")], "flat",
            "uniform-routing expectation; skew moves more" + wire_note)

    # --- fused RDMA: one row per FFN schedule -------------------------
    meta = schedule_table(cfg, d)
    # rowwin geometry resolved ONCE (its tile search + tuning lookup is
    # the priciest resolution); reused by the fused[rowwin] row and a
    # rowwin-resolved fused_combine row alike
    nrt_rowwin = (meta if meta["priced"] == "rowwin"
                  else schedule_table(cfg, d,
                                      schedule="rowwin"))["n_row_tiles"]
    nlx = max(cfg.num_experts // d, 1)
    # the fused kernel RDMAs 32-padded slabs (analysis._geom pricing)
    pslab = slab_bytes(cfg, d, padded=True)
    t_x = (d - 1) * (a_ici + pslab / (bw_link * links))

    def fused_total(cost, sched):
        compute_ms = cost.flops / (peak_fs * mxu_fraction) * 1e3
        chip = max(compute_ms, cost.total_bytes / hbm_bs * 1e3)
        if sched == "batched":
            return (max(chip / d, t_x) + (d - 1) / d * chip + t_x / nlx)
        if sched == "rowwin":
            # batched-pass makespan; the last K-window returns row tiles
            # as it finishes them, so only the final tile's rows trail
            return (max(chip / d, t_x) + (d - 1) / d * chip
                    + t_x / max(nlx * nrt_rowwin, 1))
        return max(chip, t_x + chip / d) + t_x / max(d - 1, 1)

    def fused_why_out(sched=None):
        if wire_on:
            # the in-kernel RDMA moves raw slabs; config.py rejects the
            # combination outright, so the planner must never pick it
            return "wire-dtype compression is XLA-transport only"
        if slices > 1:
            return "fused RDMA is intra-slice only"
        if sched == "rowwin":
            # the one schedule whose VMEM footprint is capacity- and
            # width-independent: infeasibility means even the minimum
            # (row tile, K-window) pair cannot fit
            return ("rowwin infeasible: no (row tile, K-window) pair "
                    "fits the window double-buffer + accumulator "
                    "VMEM budget")
        if sched in ("batched", "resident"):
            return (f"{sched} infeasible: the weights-once hidden slab "
                    f"exceeds the VMEM budget (rowwin/stream remain)")
        return "VMEM budget exceeded"

    for sched in ("batched", "resident", "stream", "rowwin"):
        cost = path_costs(cfg, "fused", d_world=d, schedule=sched)
        ok = meta["feasible"][sched] and slices == 1 and not wire_on
        note = ("in-kernel arrival overlap" if ok
                else fused_why_out(sched))
        mk(f"fused[{sched}]", cost, 2 * t_x, 0.0,
           total_ms=fused_total(cost, sched), schedule=sched,
           feasible=ok, note=note)

    # --- fused + in-kernel combine at the resolved schedule -----------
    sched = meta["schedule"]
    cost = path_costs(cfg, "fused_combine", d_world=d)
    # the sorted-return combine has no quant arm: the layer forces the
    # XLA combine whenever expert_quant is on (parallel/fused.py), so
    # this row must be infeasible there — a selected plan the engine
    # silently downgrades is the modeled-vs-run divergence this PR
    # refuses everywhere else (code-review finding)
    base_ok = meta["feasible"][sched] and slices == 1 and not wire_on
    ok = base_ok and quant_tag == "off"
    if ok:
        fc_note = "sorted per-row returns; combine off the critical path"
    elif base_ok:
        fc_note = ("in-kernel combine has no quant arm; the layer runs "
                   "fused + XLA combine under expert_quant")
    else:
        fc_note = fused_why_out(sched)
    mk("fused_combine", cost, 2 * t_x, 0.0,
       total_ms=fused_total(cost, sched), schedule=sched, feasible=ok,
       note=fc_note)

    rows.sort(key=lambda r: (not r.feasible, r.total_ms))
    return rows


# ----------------------------------------------------------------------
# Speculative-decode economics (ISSUE 20)
# ----------------------------------------------------------------------

def speculate_tokens_per_step(accept_rate: float, k: int) -> float:
    """Expected tokens emitted per verify step when ``k`` drafts ride
    the span and each draft position accepts independently with
    probability ``accept_rate`` (the prefix-acceptance model): the
    canonical token always lands, plus the geometric accepted prefix —
    ``(1 - p^(k+1)) / (1 - p)``, saturating at ``k + 1`` when p = 1."""
    p = min(max(float(accept_rate), 0.0), 1.0)
    k = int(k)
    if k < 0:
        raise ValueError(f"k={k} must be >= 0")
    if p >= 1.0:
        return float(k + 1)
    return (1.0 - p ** (k + 1)) / (1.0 - p)


def _best_decode_ms(cfg: MoEConfig, d: int, gen: str, *,
                    decode_tokens: int | None,
                    verify_tokens: int | None) -> float:
    rows = predict_paths(cfg, d, gen, mode="decode",
                         decode_tokens=decode_tokens,
                         verify_tokens=verify_tokens)
    best = next((r for r in rows if r.feasible), rows[0])
    return best.total_ms


def speculate_uplift(cfg: MoEConfig, d: int = 1, gen: str = "v5e", *,
                     decode_tokens: int | None = None,
                     verify_tokens: int = 3,
                     accept_rate: float = 0.7) -> dict:
    """Modeled tokens/step uplift of draft-then-verify at
    ``accept_rate``: expected emitted tokens per step times the
    one-token/verify-span cost ratio —
    ``E[n](p) x t1 / tk``.  The reference kernel's wire/HBM-bound
    decode step makes ``tk / t1`` sit near 1 at decode shapes (the
    weights stream past once either way), which is why speculation
    pays at all; the golden ``speculate`` dimension freezes this."""
    k = int(verify_tokens)
    if k < 1:
        raise ValueError(f"verify_tokens={verify_tokens} must be >= 1")
    t1 = _best_decode_ms(cfg, d, gen, decode_tokens=decode_tokens,
                         verify_tokens=None)
    tk = _best_decode_ms(cfg, d, gen, decode_tokens=decode_tokens,
                         verify_tokens=k)
    e_n = speculate_tokens_per_step(accept_rate, k)
    cost_ratio = tk / t1 if t1 > 0 else float("inf")
    return {
        "verify_tokens": k,
        "accept_rate": float(accept_rate),
        "t1_ms": t1,
        "tk_ms": tk,
        "cost_ratio": cost_ratio,
        "tokens_per_step": e_n,
        "uplift": e_n / cost_ratio if cost_ratio else float("inf"),
    }


def speculate_break_even(cfg: MoEConfig, d: int = 1, gen: str = "v5e",
                         *, decode_tokens: int | None = None,
                         verify_tokens: int = 3) -> float:
    """The acceptance rate at which speculation exactly pays for its
    verify span: solves ``E[n](p) = tk / t1`` for p by bisection
    (E[n] is strictly increasing in p).  Below this the controller's
    spec-morph trigger switches speculation off
    (``controller.spec_morph``); returns 1.0 when even perfect
    acceptance cannot pay (cost ratio > k + 1) and 0.0 when the span
    is literally free (ratio <= 1)."""
    k = int(verify_tokens)
    if k < 1:
        raise ValueError(f"verify_tokens={verify_tokens} must be >= 1")
    t1 = _best_decode_ms(cfg, d, gen, decode_tokens=decode_tokens,
                         verify_tokens=None)
    tk = _best_decode_ms(cfg, d, gen, decode_tokens=decode_tokens,
                         verify_tokens=k)
    ratio = tk / t1 if t1 > 0 else float("inf")
    if ratio <= 1.0:
        return 0.0
    if ratio >= k + 1:
        return 1.0
    lo, hi = 0.0, 1.0
    for _ in range(60):
        mid = (lo + hi) / 2
        if speculate_tokens_per_step(mid, k) < ratio:
            lo = mid
        else:
            hi = mid
    return (lo + hi) / 2


def explain_table(preds: list[PathPrediction], *, markdown: bool = True
                  ) -> str:
    """Render predictions as the explain-table the CLI and docs show."""
    hdr = ("| path | compute ms | HBM ms | ICI ms | DCN ms | serial ms "
           "| predicted ms | note |")
    lines = [hdr, "|---|---|---|---|---|---|---|---|"]
    for p in preds:
        star = "" if p.feasible else " (infeasible)"
        lines.append(
            f"| {p.path}{star} | {p.compute_ms:.3f} | {p.hbm_ms:.3f} | "
            f"{p.ici_ms:.3f} | {p.dcn_ms:.3f} | {p.serial_ms:.3f} | "
            f"{p.total_ms:.3f} | {p.note} |")
    return "\n".join(lines)
