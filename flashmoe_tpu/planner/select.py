"""Path selection policy: predicted winner, measured winner when
measurements exist.

The policy (VERDICT r3 #4 "measured-winner", applied framework-wide):

  1. :func:`flashmoe_tpu.planner.model.predict_paths` prices every
     candidate path; the fastest *feasible* prediction is the
     **predicted winner**.
  2. If measured end-to-end latencies exist for this shape — committed
     ``path_latency`` tuning entries
     (:func:`flashmoe_tpu.tuning.measured_path_latencies`), a bench
     records file (``FLASHMOE_BENCH_RECORDS`` pointing at bench.py
     JSONL output), or an explicit ``measured=`` dict — the fastest
     *measured* path overrides the prediction (**measured winner**).
     Measurements only override for paths the predictor considers
     runnable; a stale measurement of an infeasible path is ignored.
  3. The decision and its full latency breakdown go through
     :mod:`flashmoe_tpu.utils.telemetry` (``metrics.decision``), so a
     postmortem can always answer "why did this run take this path".

Measurements are keyed at path-family granularity ('fused', not
'fused[batched]' / 'fused[rowwin]') because that is what a wall-clock
measurement of the kernel observes — the kernel resolves its own
schedule (``MoEConfig.fused_schedule`` pins it when a measurement must
target one schedule; the per-TILE geometry inside the rowwin schedule
is measured separately, as ``fused_tiles`` tuning entries swept by
``bench.py --tiles`` / ``tune_sweep.py --stage tiles``).
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os

import jax.numpy as jnp

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.planner.model import PathPrediction, predict_paths
from flashmoe_tpu.utils.telemetry import metrics


class PathFailure(RuntimeError):
    """A selected execution path failed at trace/compile/run time.

    Carries the backend so recovery layers (``auto_ep_moe_layer``,
    :func:`flashmoe_tpu.runtime.resilient.resilient_train`) can report
    it via :func:`report_path_failure` and re-resolve onto the next-best
    path instead of dying on a path the planner merely *predicted* would
    work."""

    def __init__(self, backend: str, reason: str = ""):
        super().__init__(reason or f"execution path {backend!r} failed")
        self.backend = backend
        self.reason = reason


# Backends observed failing this process — consulted (and demoted away
# from) by every subsequent 'auto' resolution.  'collective' is never
# blacklisted: it is the robust baseline every config can run.
_FAILED_BACKENDS: set[str] = set()


def failed_backends() -> frozenset[str]:
    return frozenset(_FAILED_BACKENDS)


def report_path_failure(backend: str, reason: str = "") -> None:
    """Record a path failure and demote the backend for the rest of the
    process: future ``moe_backend='auto'`` resolutions skip it (runtime
    path polymorphism, docs/RESILIENCE.md — demote to a healthy path,
    don't die).  Logged as a ``planner.fallback`` decision so
    postmortems see WHY the path changed mid-run."""
    metrics.decision("planner.fallback", failed=backend,
                     reason=reason or None, phase="report")
    if backend not in ("collective", "local", None):
        _FAILED_BACKENDS.add(backend)
        _cached_backend.cache_clear()


def reset_path_failures() -> None:
    """Forget reported failures (tests / chaos drills)."""
    if _FAILED_BACKENDS:
        _FAILED_BACKENDS.clear()
        _cached_backend.cache_clear()


@dataclasses.dataclass(frozen=True)
class Selection:
    """The planner's verdict for one (cfg, d, gen) point."""

    winner: str                 # winning path (family name if measured)
    backend: str                # moe_backend that runs it
    mode: str                   # 'predicted' | 'measured'
    predicted_winner: str       # what the model alone would pick
    predicted_ms: float         # the winner's predicted latency
    measured_ms: float | None   # the winner's measured latency (if any)
    predictions: tuple[PathPrediction, ...]
    measured: dict              # family -> measured ms consulted
    a2a_chunks: int = 1         # the winner's chunked-pipeline depth
                                # (1 = serial; >1 only for XLA
                                # transports when the sweep wins)
    chunk_sweep: tuple = ()     # ((n, best feasible predicted ms), ...)
                                # across the candidate chunk counts


def _shape_key(cfg: MoEConfig, d: int, spec: str = "off") -> dict:
    # wire/wire_combine/chunks/quant/spec ride the key so a latency
    # measured with payload compression, a chunked pipeline, a
    # quantized expert store, or a speculative verify span on is never
    # applied to a run without it (and vice versa) —
    # tuning.measured_path_latencies matches them STRICTLY, with
    # "off" / 1 as the implicit defaults for legacy entries.  spec is
    # "v<k>" when the decode step scores a verify_tokens=k span
    from flashmoe_tpu.ops import wire as wr
    from flashmoe_tpu.quant import core as qcore

    return dict(h=cfg.hidden_size, i=cfg.intermediate_size,
                e=cfg.num_experts, k=cfg.expert_top_k, s=cfg.tokens,
                d=d, dtype=jnp.dtype(cfg.dtype).name,
                wire=wr.canonical_name(cfg.wire_dtype),
                wire_combine=wr.canonical_name(cfg.wire_dtype_combine),
                wire_dcn=wr.canonical_name(cfg.wire_dtype_dcn),
                chunks=cfg.a2a_chunks or 1,
                quant=qcore.canonical_name(cfg.expert_quant),
                spec=spec)


def spec_tag(verify_tokens: int | None) -> str:
    """The measurement-identity tag of a speculative verify span:
    ``"off"`` for the plain one-token step, ``"v<k>"`` for a
    ``verify_tokens=k`` span (rides tuning/bench/select shape keys
    like ``wire`` / ``chunks``)."""
    k = int(verify_tokens or 0)
    return f"v{k}" if k else "off"


def _bench_record_latencies(cfg: MoEConfig, d: int,
                            spec: str = "off") -> dict:
    """Measured path latencies mined from a bench.py JSONL records file
    (``FLASHMOE_BENCH_RECORDS``).  A record matches when its metric
    string carries this exact shape signature (dtype included) AND its
    ``d`` field matches the queried rank count — a single-chip timing
    must never override an 8-rank selection.  ``path``/``value`` (ms)
    name the primary measurement; ``xla_path_ms`` contributes the xla
    leg of the same record.  Unreadable files contribute nothing."""
    from flashmoe_tpu.ops import wire as wr

    path = os.environ.get("FLASHMOE_BENCH_RECORDS")
    if not path or not os.path.exists(path):
        return {}
    from flashmoe_tpu.quant import core as qcore

    sig = (f"E={cfg.num_experts},k={cfg.expert_top_k},"
           f"H={cfg.hidden_size},I={cfg.intermediate_size},"
           f"S={cfg.tokens},{jnp.dtype(cfg.dtype).name}")
    wire_sig = (wr.canonical_name(cfg.wire_dtype),
                wr.canonical_name(cfg.wire_dtype_combine),
                wr.canonical_name(cfg.wire_dtype_dcn))
    quant_sig = qcore.canonical_name(cfg.expert_quant)
    out: dict[str, float] = {}

    def keep(p, v):
        if p and isinstance(v, (int, float)) and v > 0:
            out[p] = min(float(v), out.get(p, float("inf")))

    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if sig not in str(rec.get("metric", "")):
                    continue
                if int(rec.get("d", 1)) != d:
                    continue
                # wire/chunk knobs are part of the measurement's
                # identity: a compressed or chunk-pipelined timing
                # never overrides a selection without it (records
                # without the fields are legacy = off / serial)
                if (str(rec.get("wire_dtype", "off")),
                        str(rec.get("wire_dtype_combine", "off")),
                        str(rec.get("wire_dtype_dcn",
                                    "off"))) != wire_sig:
                    continue
                if int(rec.get("a2a_chunks", 1) or 1) != (
                        cfg.a2a_chunks or 1):
                    continue
                # quantized-store identity: a timing of int8 weights
                # never overrides a full-precision selection (records
                # without the field are legacy = off)
                if str(rec.get("expert_quant", "off")) != quant_sig:
                    continue
                # speculative-span identity: a verify-span timing
                # (spec="v<k>") never overrides a plain one-token
                # decode selection, and vice versa
                if str(rec.get("spec", "off")) != spec:
                    continue
                keep(rec.get("path"), rec.get("value"))
                keep("xla", rec.get("xla_path_ms"))
    except OSError:
        return {}
    return out


#: chunk counts the auto sweep considers (filtered per shape by
#: local-expert divisibility; 1 = the serial schedule, always present)
CHUNK_CANDIDATES = (1, 2, 4, 8)


def _chunk_candidates(cfg: MoEConfig, d: int) -> list[int]:
    """Valid ``a2a_chunks`` candidates at (cfg, d): divisors of the
    local-expert axis at BOTH the queried rank count and the config's
    own ep (so ``cfg.replace(a2a_chunks=n)`` always constructs)."""
    if d <= 1 or cfg.num_experts % d:
        return [1]
    nlx_d = cfg.num_experts // d
    nlx_cfg = cfg.num_experts // max(cfg.ep, 1)
    return [n for n in CHUNK_CANDIDATES
            if n == 1 or (nlx_d % n == 0 and nlx_cfg % n == 0)]


def select_path(cfg: MoEConfig, d: int = 1, gen: str | None = None, *,
                slices: int = 1, links: int = 4,
                mxu_fraction: float = 1.0,
                measured: dict | None = None,
                record: bool = True,
                sweep_chunks: bool = False,
                mode: str = "training",
                decode_tokens: int | None = None,
                verify_tokens: int | None = None,
                dp: int = 1, dp_over_dcn: bool = False) -> Selection:
    """Pick the execution path for (cfg, d ranks, gen).

    ``measured``: explicit {path_family: ms} overrides (highest
    precedence); the tuning table and ``FLASHMOE_BENCH_RECORDS`` are
    consulted automatically.  ``record=False`` suppresses the telemetry
    decision record (pure queries, e.g. the CLI's golden writer).

    ``sweep_chunks``: additionally sweep the chunked-pipeline depth
    (``MoEConfig.a2a_chunks``) over :data:`CHUNK_CANDIDATES` and pick
    the fastest (path, chunk count) — the ``moe_backend='auto'``
    resolution uses this; an explicit ``cfg.a2a_chunks`` pins the
    sweep to that value.  Measurements keep their chunk identity: a
    timing recorded at chunks=4 only competes inside the chunks=4
    candidate (tuning/bench ``chunks`` keys).

    ``mode``: the pricing regime (``planner.model.predict_paths``) —
    ``'decode'`` re-shapes the config to the per-step decode batch
    (``decode_tokens``, default ``DECODE_TOKENS_DEFAULT``) FIRST, so
    every downstream consumer (chunk candidates, measurement shape
    keys, predictions, the decision record) sees the decode-shaped
    problem; a decode measurement therefore keys at decode token
    counts and can never override a training-shape selection.
    ``verify_tokens`` additionally prices a speculative verify span
    (``decode_tokens x (k+1)`` rows) and stamps the ``spec="v<k>"``
    measurement-identity tag on every shape key, so a verify-span
    timing never crosses with a plain one-token decode timing.

    ``dp`` / ``dp_over_dcn``: price the DP gradient allreduce into
    every prediction (``planner.model.dp_allreduce_ms``) — constant
    across paths, so it never changes which path wins here, but it
    makes selections comparable across slice MAPPINGS; that comparison
    is :func:`scaleout_plan`."""
    from flashmoe_tpu import tuning
    from flashmoe_tpu.planner.model import decode_shape

    if mode not in ("training", "prefill", "decode"):
        raise ValueError(
            f"mode {mode!r} not in ('training', 'prefill', 'decode')")
    if verify_tokens and mode != "decode":
        raise ValueError("verify_tokens prices the speculative verify "
                         "span — decode mode only")
    spec = spec_tag(verify_tokens)
    if mode == "decode":
        cfg = decode_shape(cfg, d, decode_tokens, verify_tokens)
    elif mode == "prefill" and cfg.is_training:
        cfg = cfg.replace(is_training=False)

    gen = gen or tuning.generation()
    if sweep_chunks and cfg.a2a_chunks is None:
        cands = _chunk_candidates(cfg, d)
    else:
        cands = [cfg.a2a_chunks or 1]

    # price every candidate chunk count; measurements are keyed per
    # candidate (the chunks field rides the shape key)
    by_n = []
    for n in cands:
        cfg_n = (cfg if n == (cfg.a2a_chunks or 1)
                 else cfg.replace(a2a_chunks=None if n == 1 else n))
        preds = predict_paths(cfg_n, d, gen, slices=slices, links=links,
                              mxu_fraction=mxu_fraction, dp=dp,
                              dp_over_dcn=dp_over_dcn)
        feasible = [p for p in preds if p.feasible]
        if not feasible:
            continue
        pw = min(feasible, key=lambda p: p.total_ms)
        meas: dict[str, float] = {}
        meas.update(tuning.measured_path_latencies(
            gen, **_shape_key(cfg_n, d, spec)))
        meas.update(_bench_record_latencies(cfg_n, d, spec))
        if measured:
            meas.update(measured)
        runnable = {p.family for p in feasible}
        usable = {f: ms for f, ms in meas.items() if f in runnable}
        by_n.append((n, preds, feasible, pw, usable))
    if not by_n:
        raise ValueError(f"no feasible path at d={d} for this config")
    chunk_sweep = tuple((n, round(pw.total_ms, 6))
                        for n, _, _, pw, _ in by_n)
    # the predicted winner across candidates (ties -> fewer chunks:
    # the serial schedule needs no justification)
    n_win, preds, feasible, pred_win, usable = min(
        by_n, key=lambda t: (t[3].total_ms, t[0]))

    best_meas = None  # (ms, n, family, candidate predictions)
    for n, preds_n, feasible_n, _, usable_n in by_n:
        for f, ms in usable_n.items():
            if best_meas is None or (ms, n) < (best_meas[0],
                                               best_meas[1]):
                best_meas = (ms, n, f, preds_n, feasible_n, usable_n)

    if best_meas is not None:
        ms, n_m, win_family, preds_m, feasible_m, usable_m = best_meas
        win_pred = min((p for p in feasible_m
                        if p.family == win_family),
                       key=lambda p: p.total_ms)
        sel = Selection(
            winner=win_family, backend=win_pred.backend, mode="measured",
            predicted_winner=pred_win.path, predicted_ms=win_pred.total_ms,
            measured_ms=ms, predictions=tuple(preds_m),
            measured=dict(usable_m), a2a_chunks=win_pred.a2a_chunks,
            chunk_sweep=chunk_sweep)
    else:
        sel = Selection(
            winner=pred_win.path, backend=pred_win.backend,
            mode="predicted", predicted_winner=pred_win.path,
            predicted_ms=pred_win.total_ms, measured_ms=None,
            predictions=tuple(preds), measured={},
            a2a_chunks=pred_win.a2a_chunks, chunk_sweep=chunk_sweep)

    if record:
        metrics.decision(
            "planner.path_select",
            serving_mode=mode,
            winner=sel.winner, backend=sel.backend, mode=sel.mode,
            predicted_winner=sel.predicted_winner,
            predicted_ms=round(sel.predicted_ms, 4),
            measured_ms=(round(sel.measured_ms, 4)
                         if sel.measured_ms is not None else None),
            gen=gen, d=d, slices=slices,
            a2a_chunks=sel.a2a_chunks,
            chunk_sweep=[list(t) for t in chunk_sweep],
            config=_shape_key(cfg, d, spec),
            breakdown=[{
                "path": p.path, "feasible": p.feasible,
                "compute_ms": round(p.compute_ms, 4),
                "hbm_ms": round(p.hbm_ms, 4),
                "ici_ms": round(p.ici_ms, 4),
                "dcn_ms": round(p.dcn_ms, 4),
                "total_ms": round(p.total_ms, 4),
                "a2a_chunks": p.a2a_chunks,
            } for p in sel.predictions])
    return sel


@functools.lru_cache(maxsize=64)
def _cached_backend(cfg: MoEConfig, d: int, gen: str, slices: int,
                    mode: str = "training", decode_tokens: int = 0
                    ) -> tuple[str, int | None]:
    """(backend, a2a_chunks) plan for one (cfg, d, gen, slices, mode)
    point — the chunk count is the planner's sweep pick for the XLA
    transports (``None`` = serial), kept alongside the backend so
    ``moe_backend='auto'`` resolves both in one cached decision.
    ``mode``/``decode_tokens`` select the pricing regime (the serving
    engine resolves its decode path with ``mode='decode'``; 0 =
    default decode batch)."""
    # constraint filter first: combinations config.py rejects outright
    # never reach the latency comparison
    if cfg.tp > 1:
        return "collective", cfg.a2a_chunks
    sel = select_path(cfg, d, gen, slices=slices, sweep_chunks=True,
                      mode=mode, decode_tokens=decode_tokens or None)
    backend = sel.backend
    chunks = sel.a2a_chunks if sel.a2a_chunks > 1 else None
    if backend in _FAILED_BACKENDS:
        # path fallback: the predicted winner already failed in this
        # process; demote to the fastest feasible prediction on a
        # still-healthy backend, bottoming out at the collective layer
        ranked = sorted((p for p in sel.predictions if p.feasible),
                        key=lambda p: p.total_ms)
        alt = next((p for p in ranked
                    if p.backend not in _FAILED_BACKENDS), None)
        new_backend = alt.backend if alt is not None else "collective"
        metrics.decision(
            "planner.fallback", failed=backend, backend=new_backend,
            winner=(alt.path if alt is not None else "collective"),
            phase="resolve", d=d, gen=gen)
        backend = new_backend
        chunks = (alt.a2a_chunks if alt is not None
                  and alt.a2a_chunks > 1 else None)
    if backend == "ragged" and cfg.num_shared_experts:
        # the ragged layer cannot host shared experts; the demotion is
        # its own telemetry record so the path_select breakdown never
        # silently disagrees with what actually ran
        backend = "collective"
        metrics.decision(
            "planner.backend_constraint", winner=sel.winner,
            requested="ragged", backend=backend,
            reason="shared experts need the collective layer")
    if backend == "local":
        backend = "collective"
    if backend == "fused":
        chunks = None  # the in-kernel transport ignores the knob
    return backend, chunks


def resolve_moe_plan(cfg: MoEConfig, mesh=None, *,
                     mode: str | None = None,
                     decode_tokens: int | None = None
                     ) -> tuple[str, int | None]:
    """(moe_backend, a2a_chunks) an ``moe_backend='auto'`` config
    should run.

    Non-auto configs pass through untouched (their own
    ``cfg.a2a_chunks`` stands).  Auto consults the planner at this
    mesh's ep width, the trace-time generation pin
    (:func:`flashmoe_tpu.tuning.generation` — never touches a possibly
    wedged backend), and the detected slice structure; the chunked-
    pipeline depth is swept alongside the path.  Results are cached per
    (cfg, d, gen, slices, mode); the decision itself is recorded in
    telemetry once per cache fill.

    ``mode``: the pricing regime (None reads ``cfg.serving_mode``, so a
    decode-phase config resolves a decode-priced plan without every
    call site learning the axis); ``decode_tokens``: the per-step
    decode batch the decode regime prices (the serving engine passes
    its batch width; default ``planner.model.DECODE_TOKENS_DEFAULT``).
    """
    if cfg.moe_backend != "auto":
        return cfg.moe_backend, cfg.a2a_chunks
    from flashmoe_tpu import tuning

    mode = mode or cfg.serving_mode or "training"
    d = int(mesh.shape.get("ep", cfg.ep)) if mesh is not None else cfg.ep
    if d <= 1:
        return "collective", None
    slices = 1
    try:
        from flashmoe_tpu.parallel.topology import slice_structure

        ss = slice_structure()
        if ss and d % ss[0] == 0:
            slices = ss[0]
    except Exception:  # noqa: BLE001 — detection must never block trace
        slices = 1
    return _cached_backend(cfg, d, tuning.generation(), slices, mode,
                           int(decode_tokens or 0))


def resolve_moe_backend(cfg: MoEConfig, mesh=None) -> str:
    """The moe_backend an ``moe_backend='auto'`` config should run —
    :func:`resolve_moe_plan` without the chunk component."""
    return resolve_moe_plan(cfg, mesh)[0]


@dataclasses.dataclass(frozen=True)
class ScaleoutPlan:
    """The planner's verdict on how a multi-slice job should map its
    DP x EP axes onto the slice topology (:func:`scaleout_plan`)."""

    mapping: str                # 'ep_across_dcn' | 'dp_across_dcn'
    ep: int                     # expert-parallel width
    dp: int                     # data-parallel replica count
    a2a_slices: int             # slices the ep a2a spans (1 = in-slice)
    dp_over_dcn: bool           # the gradient ring rides DCN
    predicted_ms: float         # winning mapping's per-step prediction
    alternative_ms: float | None  # the losing mapping's (None when the
                                # other mapping is infeasible)
    selection: Selection        # the winner's full path selection
    reason: str


def scaleout_plan(cfg: MoEConfig, n_devices: int, n_slices: int,
                  gen: str | None = None, *, links: int = 4,
                  record: bool = True) -> ScaleoutPlan:
    """Trade **EP-across-DCN** against **DP-across-DCN** for a job of
    ``n_devices`` chips on ``n_slices`` DCN-connected slices — the
    planner-side counterpart of the bootstrap Decider's group formation
    (:func:`flashmoe_tpu.runtime.bootstrap.form_groups`), the tradeoff
    the reference's Decider objective makes with its inter-group
    allreduce term (``decider.cuh:60-158``).

    Two candidate mappings of the same ``dp x ep`` factorization:

    * ``ep_across_dcn`` — the ep axis spans every slice, so the expert
      all-to-all pays the DCN hop (hierarchical two-stage exchange,
      ``wire_dtype_dcn`` applies) while the DP gradient ring rides ICI
      inside each slice;
    * ``dp_across_dcn`` — the ep axis packs inside one slice (needs
      ``ep <= n_devices // n_slices``), the a2a never leaves ICI, and
      the gradient ring pays DCN instead
      (``planner.model.dp_allreduce_ms`` with ``over_dcn=True``).

    Whichever axis moves fewer bytes per step should own the slow hop;
    each candidate is priced end to end through :func:`select_path`
    (chunk sweep included) and the faster total wins.  Inference jobs
    have no allreduce, so ``dp_across_dcn`` wins whenever it is
    feasible.  Recorded as a ``planner.scaleout`` decision."""
    if n_slices < 1 or n_devices % n_slices:
        raise ValueError(
            f"n_devices={n_devices} not divisible into "
            f"{n_slices} slices")
    inner = n_devices // n_slices
    ep = min(cfg.ep if cfg.ep > 1 else n_devices, n_devices)
    while cfg.num_experts % ep:
        ep -= 1
    dp = n_devices // ep

    cands = []
    if n_slices == 1 or ep % n_slices == 0:
        # ep spans the slices evenly; dp replicas live inside slices
        cands.append(("ep_across_dcn", n_slices, False))
    if ep <= inner:
        # ep packs in one slice; the dp ring crosses slices (when any)
        cands.append(("dp_across_dcn", 1, n_slices > 1))
    if not cands:
        raise ValueError(
            f"ep={ep} neither spans {n_slices} slices evenly nor fits "
            f"one slice of {inner} ranks — no regular DP x EP mapping")

    priced = []
    for mapping, a2a_slices, over_dcn in cands:
        sel = select_path(cfg, ep, gen, slices=a2a_slices, links=links,
                          record=False, sweep_chunks=True, dp=dp,
                          dp_over_dcn=over_dcn)
        priced.append((sel.predicted_ms, mapping, a2a_slices, over_dcn,
                       sel))
    priced.sort(key=lambda t: t[0])
    win_ms, mapping, a2a_slices, over_dcn, sel = priced[0]
    alt_ms = priced[1][0] if len(priced) > 1 else None
    reason = (f"{mapping} predicts {win_ms:.3f} ms"
              + (f" vs {alt_ms:.3f} ms" if alt_ms is not None
                 else " (only regular mapping)"))
    plan = ScaleoutPlan(mapping=mapping, ep=ep, dp=dp,
                        a2a_slices=a2a_slices, dp_over_dcn=over_dcn,
                        predicted_ms=win_ms, alternative_ms=alt_ms,
                        selection=sel, reason=reason)
    if record:
        metrics.decision(
            "planner.scaleout", mapping=mapping, ep=ep, dp=dp,
            n_devices=n_devices, n_slices=n_slices,
            a2a_slices=a2a_slices, dp_over_dcn=over_dcn,
            winner=sel.winner, backend=sel.backend,
            a2a_chunks=sel.a2a_chunks,
            predicted_ms=round(win_ms, 4),
            alternative_ms=(round(alt_ms, 4) if alt_ms is not None
                            else None),
            reason=reason)
    return plan
