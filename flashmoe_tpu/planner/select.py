"""Path selection policy: predicted winner, measured winner when
measurements exist.

The policy (VERDICT r3 #4 "measured-winner", applied framework-wide):

  1. :func:`flashmoe_tpu.planner.model.predict_paths` prices every
     candidate path; the fastest *feasible* prediction is the
     **predicted winner**.
  2. If measured end-to-end latencies exist for this shape — committed
     ``path_latency`` tuning entries
     (:func:`flashmoe_tpu.tuning.measured_path_latencies`), a bench
     records file (``FLASHMOE_BENCH_RECORDS`` pointing at bench.py
     JSONL output), or an explicit ``measured=`` dict — the fastest
     *measured* path overrides the prediction (**measured winner**).
     Measurements only override for paths the predictor considers
     runnable; a stale measurement of an infeasible path is ignored.
  3. The decision and its full latency breakdown go through
     :mod:`flashmoe_tpu.utils.telemetry` (``metrics.decision``), so a
     postmortem can always answer "why did this run take this path".

Measurements are keyed at path-family granularity ('fused', not
'fused[batched]') because that is what a wall-clock measurement of the
kernel observes — the kernel resolves its own schedule.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os

import jax.numpy as jnp

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.planner.model import PathPrediction, predict_paths
from flashmoe_tpu.utils.telemetry import metrics


class PathFailure(RuntimeError):
    """A selected execution path failed at trace/compile/run time.

    Carries the backend so recovery layers (``auto_ep_moe_layer``,
    :func:`flashmoe_tpu.runtime.resilient.resilient_train`) can report
    it via :func:`report_path_failure` and re-resolve onto the next-best
    path instead of dying on a path the planner merely *predicted* would
    work."""

    def __init__(self, backend: str, reason: str = ""):
        super().__init__(reason or f"execution path {backend!r} failed")
        self.backend = backend
        self.reason = reason


# Backends observed failing this process — consulted (and demoted away
# from) by every subsequent 'auto' resolution.  'collective' is never
# blacklisted: it is the robust baseline every config can run.
_FAILED_BACKENDS: set[str] = set()


def failed_backends() -> frozenset[str]:
    return frozenset(_FAILED_BACKENDS)


def report_path_failure(backend: str, reason: str = "") -> None:
    """Record a path failure and demote the backend for the rest of the
    process: future ``moe_backend='auto'`` resolutions skip it (runtime
    path polymorphism, docs/RESILIENCE.md — demote to a healthy path,
    don't die).  Logged as a ``planner.fallback`` decision so
    postmortems see WHY the path changed mid-run."""
    metrics.decision("planner.fallback", failed=backend,
                     reason=reason or None, phase="report")
    if backend not in ("collective", "local", None):
        _FAILED_BACKENDS.add(backend)
        _cached_backend.cache_clear()


def reset_path_failures() -> None:
    """Forget reported failures (tests / chaos drills)."""
    if _FAILED_BACKENDS:
        _FAILED_BACKENDS.clear()
        _cached_backend.cache_clear()


@dataclasses.dataclass(frozen=True)
class Selection:
    """The planner's verdict for one (cfg, d, gen) point."""

    winner: str                 # winning path (family name if measured)
    backend: str                # moe_backend that runs it
    mode: str                   # 'predicted' | 'measured'
    predicted_winner: str       # what the model alone would pick
    predicted_ms: float         # the winner's predicted latency
    measured_ms: float | None   # the winner's measured latency (if any)
    predictions: tuple[PathPrediction, ...]
    measured: dict              # family -> measured ms consulted


def _shape_key(cfg: MoEConfig, d: int) -> dict:
    # wire/wire_combine ride the key so a latency measured with payload
    # compression on is never applied to an uncompressed run (and vice
    # versa) — tuning.measured_path_latencies matches them STRICTLY,
    # with "off" as the implicit default for legacy entries
    from flashmoe_tpu.ops import wire as wr

    return dict(h=cfg.hidden_size, i=cfg.intermediate_size,
                e=cfg.num_experts, k=cfg.expert_top_k, s=cfg.tokens,
                d=d, dtype=jnp.dtype(cfg.dtype).name,
                wire=wr.canonical_name(cfg.wire_dtype),
                wire_combine=wr.canonical_name(cfg.wire_dtype_combine))


def _bench_record_latencies(cfg: MoEConfig, d: int) -> dict:
    """Measured path latencies mined from a bench.py JSONL records file
    (``FLASHMOE_BENCH_RECORDS``).  A record matches when its metric
    string carries this exact shape signature (dtype included) AND its
    ``d`` field matches the queried rank count — a single-chip timing
    must never override an 8-rank selection.  ``path``/``value`` (ms)
    name the primary measurement; ``xla_path_ms`` contributes the xla
    leg of the same record.  Unreadable files contribute nothing."""
    from flashmoe_tpu.ops import wire as wr

    path = os.environ.get("FLASHMOE_BENCH_RECORDS")
    if not path or not os.path.exists(path):
        return {}
    sig = (f"E={cfg.num_experts},k={cfg.expert_top_k},"
           f"H={cfg.hidden_size},I={cfg.intermediate_size},"
           f"S={cfg.tokens},{jnp.dtype(cfg.dtype).name}")
    wire_sig = (wr.canonical_name(cfg.wire_dtype),
                wr.canonical_name(cfg.wire_dtype_combine))
    out: dict[str, float] = {}

    def keep(p, v):
        if p and isinstance(v, (int, float)) and v > 0:
            out[p] = min(float(v), out.get(p, float("inf")))

    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if sig not in str(rec.get("metric", "")):
                    continue
                if int(rec.get("d", 1)) != d:
                    continue
                # wire knobs are part of the measurement's identity: a
                # compressed timing never overrides an uncompressed
                # selection (records without the field are legacy = off)
                if (str(rec.get("wire_dtype", "off")),
                        str(rec.get("wire_dtype_combine",
                                    "off"))) != wire_sig:
                    continue
                keep(rec.get("path"), rec.get("value"))
                keep("xla", rec.get("xla_path_ms"))
    except OSError:
        return {}
    return out


def select_path(cfg: MoEConfig, d: int = 1, gen: str | None = None, *,
                slices: int = 1, links: int = 4,
                mxu_fraction: float = 1.0,
                measured: dict | None = None,
                record: bool = True) -> Selection:
    """Pick the execution path for (cfg, d ranks, gen).

    ``measured``: explicit {path_family: ms} overrides (highest
    precedence); the tuning table and ``FLASHMOE_BENCH_RECORDS`` are
    consulted automatically.  ``record=False`` suppresses the telemetry
    decision record (pure queries, e.g. the CLI's golden writer).
    """
    from flashmoe_tpu import tuning

    gen = gen or tuning.generation()
    preds = predict_paths(cfg, d, gen, slices=slices, links=links,
                          mxu_fraction=mxu_fraction)
    feasible = [p for p in preds if p.feasible]
    if not feasible:
        raise ValueError(f"no feasible path at d={d} for this config")
    pred_win = min(feasible, key=lambda p: p.total_ms)

    meas: dict[str, float] = {}
    meas.update(tuning.measured_path_latencies(gen, **_shape_key(cfg, d)))
    meas.update(_bench_record_latencies(cfg, d))
    if measured:
        meas.update(measured)
    runnable = {p.family for p in feasible}
    usable = {f: ms for f, ms in meas.items() if f in runnable}

    if usable:
        win_family = min(usable, key=usable.get)
        win_pred = min((p for p in feasible if p.family == win_family),
                       key=lambda p: p.total_ms)
        sel = Selection(
            winner=win_family, backend=win_pred.backend, mode="measured",
            predicted_winner=pred_win.path, predicted_ms=win_pred.total_ms,
            measured_ms=usable[win_family], predictions=tuple(preds),
            measured=dict(usable))
    else:
        sel = Selection(
            winner=pred_win.path, backend=pred_win.backend,
            mode="predicted", predicted_winner=pred_win.path,
            predicted_ms=pred_win.total_ms, measured_ms=None,
            predictions=tuple(preds), measured={})

    if record:
        metrics.decision(
            "planner.path_select",
            winner=sel.winner, backend=sel.backend, mode=sel.mode,
            predicted_winner=sel.predicted_winner,
            predicted_ms=round(sel.predicted_ms, 4),
            measured_ms=(round(sel.measured_ms, 4)
                         if sel.measured_ms is not None else None),
            gen=gen, d=d, slices=slices,
            config=_shape_key(cfg, d),
            breakdown=[{
                "path": p.path, "feasible": p.feasible,
                "compute_ms": round(p.compute_ms, 4),
                "hbm_ms": round(p.hbm_ms, 4),
                "ici_ms": round(p.ici_ms, 4),
                "dcn_ms": round(p.dcn_ms, 4),
                "total_ms": round(p.total_ms, 4),
            } for p in preds])
    return sel


@functools.lru_cache(maxsize=64)
def _cached_backend(cfg: MoEConfig, d: int, gen: str, slices: int) -> str:
    # constraint filter first: combinations config.py rejects outright
    # never reach the latency comparison
    if cfg.tp > 1:
        return "collective"
    sel = select_path(cfg, d, gen, slices=slices)
    backend = sel.backend
    if backend in _FAILED_BACKENDS:
        # path fallback: the predicted winner already failed in this
        # process; demote to the fastest feasible prediction on a
        # still-healthy backend, bottoming out at the collective layer
        ranked = sorted((p for p in sel.predictions if p.feasible),
                        key=lambda p: p.total_ms)
        alt = next((p for p in ranked
                    if p.backend not in _FAILED_BACKENDS), None)
        new_backend = alt.backend if alt is not None else "collective"
        metrics.decision(
            "planner.fallback", failed=backend, backend=new_backend,
            winner=(alt.path if alt is not None else "collective"),
            phase="resolve", d=d, gen=gen)
        backend = new_backend
    if backend == "ragged" and cfg.num_shared_experts:
        # the ragged layer cannot host shared experts; the demotion is
        # its own telemetry record so the path_select breakdown never
        # silently disagrees with what actually ran
        backend = "collective"
        metrics.decision(
            "planner.backend_constraint", winner=sel.winner,
            requested="ragged", backend=backend,
            reason="shared experts need the collective layer")
    if backend == "local":
        backend = "collective"
    return backend


def resolve_moe_backend(cfg: MoEConfig, mesh=None) -> str:
    """The moe_backend an ``moe_backend='auto'`` config should run.

    Non-auto configs pass through untouched.  Auto consults the planner
    at this mesh's ep width, the trace-time generation pin
    (:func:`flashmoe_tpu.tuning.generation` — never touches a possibly
    wedged backend), and the detected slice structure.  Results are
    cached per (cfg, d, gen, slices); the decision itself is recorded
    in telemetry once per cache fill.
    """
    if cfg.moe_backend != "auto":
        return cfg.moe_backend
    from flashmoe_tpu import tuning

    d = int(mesh.shape.get("ep", cfg.ep)) if mesh is not None else cfg.ep
    if d <= 1:
        return "collective"
    slices = 1
    try:
        from flashmoe_tpu.parallel.topology import slice_structure

        ss = slice_structure()
        if ss and d % ss[0] == 0:
            slices = ss[0]
    except Exception:  # noqa: BLE001 — detection must never block trace
        slices = 1
    return _cached_backend(cfg, d, tuning.generation(), slices)
