"""Phase-level profiler: the xprof-free measurement substrate.

The reference kernel's thesis is that an MoE layer's time decomposes
into four phases — gate, dispatch a2a, expert FFN, combine a2a — yet
until this package the framework could only *model* that decomposition
(the analytical planner) or capture it with xprof on real TPUs it has
never had.  This package measures it on any backend, CPU included:

* :mod:`flashmoe_tpu.profiler.spans` — a host-side span clock riding
  the existing ``trace_span`` sites: when a :class:`PhaseTimeline` is
  armed and the layer executes *eagerly* (no ``jit``), every phase is
  fenced with ``block_until_ready`` at its boundary, so per-step
  per-phase wall durations are real device time, not trace time;
* :mod:`flashmoe_tpu.profiler.ledger` — the predicted-vs-actual cost
  ledger: joins each measured phase against the planner's prediction
  for that same phase (``planner.phase_drift`` decisions), plus a
  measured overlap fraction per chunk cross-checked against
  ``overlap.chunked_overlap_bound``;
* :mod:`flashmoe_tpu.profiler.export` — Chrome-trace / Perfetto
  ``trace.json`` export (open in ``ui.perfetto.dev`` with zero TPU
  tooling);
* :mod:`flashmoe_tpu.profiler.slo` — step/phase-time SLO watchdog
  (``slo.breach`` / ``slo.recovered`` decisions, consecutive-breach
  escalation into the planner's path-demotion machinery);
* :mod:`flashmoe_tpu.profiler.postmortem` — crash postmortem bundles
  (flight ring + decisions + timeline + config + env + traceback),
  rendered by ``python -m flashmoe_tpu.observe --postmortem <dir>``.

Import the submodules directly — this ``__init__`` stays import-light
because the hot-path layers (:mod:`flashmoe_tpu.parallel.ep`) import
:mod:`~flashmoe_tpu.profiler.spans` at module load.
"""

from flashmoe_tpu.profiler import spans  # noqa: F401  (import-light)
