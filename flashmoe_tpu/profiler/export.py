"""Chrome-trace / Perfetto export of a :class:`PhaseTimeline`.

Writes the JSON-object flavor of the Trace Event Format — the format
``ui.perfetto.dev`` and ``chrome://tracing`` open directly — so a CPU
(or TPU) phase timeline becomes a zoomable trace with zero TPU tooling:

* one *process* (pid) per timeline (``bench.py --profile`` merges the
  whole ledger matrix into one file, one pid per matrix point, named
  via ``process_name`` metadata);
* ``tid 0``: MoE phase spans (``moe.gate`` .. ``moe.combine``, chunked
  sub-slices as their own ``moe.expert.k`` slices);
* ``tid 1``: trainer host sections (``train.*``);
* counter tracks (``ph: "C"``) for the stats the driver samples per
  step — expert-load imbalance and flight-recorder queue depth.

Timestamps/durations are microseconds (the format's unit), relative to
each timeline's birth.  :func:`validate_trace` checks the documented
schema invariants; the test suite runs it on every exported file so
"opens cleanly in Perfetto" is CI-gated, not aspirational.
"""

from __future__ import annotations

import json

from flashmoe_tpu.profiler.spans import PhaseTimeline

#: event types this exporter emits (a subset of the Trace Event spec);
#: "s"/"f" are flow start/finish — the arrows linking a request's
#: prefill-pool span to its decode-pool resume in the fleet document
_KNOWN_PH = ("X", "C", "M", "s", "f")


def chrome_trace_events(tl: PhaseTimeline, *, pid: int = 0,
                        process_name: str | None = None) -> list[dict]:
    """One timeline -> a list of Trace Event dicts."""
    name = process_name or tl.label or f"flashmoe timeline {pid}"
    events: list[dict] = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
         "args": {"name": name}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": 0,
         "args": {"name": "moe phases"}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": 1,
         "args": {"name": "host sections"}},
    ]

    def complete(rec: dict, tid: int) -> dict:
        args = {"step": rec.get("step")}
        if rec.get("phase") and rec["phase"] != rec["name"]:
            args["phase"] = rec["phase"]  # chunked sub-slice -> base
        return {
            "ph": "X", "name": rec["name"], "cat": rec.get(
                "kind", "phase"),
            "ts": round(rec["ts_ms"] * 1e3, 3),
            "dur": max(round(rec["dur_ms"] * 1e3, 3), 0.001),
            "pid": pid, "tid": tid, "args": args,
        }

    for rec in tl.spans:
        events.append(complete(rec, 0))
    for rec in tl.sections:
        events.append(complete(rec, 1))
    for c in tl.counters:
        events.append({
            "ph": "C", "name": c["name"], "pid": pid,
            "ts": round(c["ts_ms"] * 1e3, 3),
            "args": {"value": c["value"]},
        })
    return events


def trace_document(timelines, *, labels=None) -> dict:
    """Merge one or more timelines into a single trace document (one
    pid each)."""
    if isinstance(timelines, PhaseTimeline):
        timelines = [timelines]
    events: list[dict] = []
    for pid, tl in enumerate(timelines):
        label = labels[pid] if labels else None
        events.extend(chrome_trace_events(tl, pid=pid,
                                          process_name=label))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"producer": "flashmoe_tpu.profiler"}}


def write_trace(timelines, path: str, *, labels=None) -> dict:
    """Write ``trace.json``; returns the document (already validated —
    a malformed export should fail at write time, not in Perfetto)."""
    doc = trace_document(timelines, labels=labels)
    errors = validate_trace(doc)
    if errors:
        raise ValueError(f"malformed trace export: {errors[:3]}")
    with open(path, "w") as f:
        json.dump(doc, f)
    return doc


def request_trace_events(tracer, *, base_pid: int = 1000) -> list[dict]:
    """One Perfetto track PER REQUEST from a
    :class:`flashmoe_tpu.telemetry_plane.tracing.RequestTracer`: each
    request gets its own pid (named ``request <rid> [<trace_id>]``),
    with its lifecycle spans — ``serve.queued`` (eviction gaps render
    as ``serve.queued [resumed]`` slices), ``serve.prefill``,
    ``serve.step`` windows and the nested ``serve.decode`` device
    slices — as ``ph:"X"`` complete events.  Composable with
    :func:`chrome_trace_events` output (phase timelines keep pids <
    ``base_pid``), so one trace.json can carry both views."""
    events: list[dict] = []
    for idx, rid in enumerate(sorted(tracer.requests)):
        st = tracer.requests[rid]
        pid = base_pid + idx
        name = f"request {rid}"
        if st.trace_id:
            name += f" [{st.trace_id}]"
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": name}})
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": 0, "args": {"name": "request lifecycle"}})
        for s in tracer.request_track(rid):
            label = s["name"]
            if s.get("resumed"):
                label += " [resumed]"
            events.append({
                "ph": "X", "name": label, "cat": "request",
                "ts": round(s["ts_ms"] * 1e3, 3),
                "dur": max(round(s["dur_ms"] * 1e3, 3), 0.001),
                "pid": pid, "tid": 0,
                "args": {"rid": rid, "trace_id": st.trace_id,
                         "step": s.get("step")},
            })
    return events


def request_trace_document(tracer, *, timelines=None,
                           labels=None) -> dict:
    """A full trace document of per-request tracks, optionally merged
    with phase timelines (one pid each, below the request pids)."""
    events: list[dict] = []
    if timelines is not None:
        events = trace_document(timelines, labels=labels)["traceEvents"]
    events.extend(request_trace_events(tracer))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"producer": "flashmoe_tpu.profiler"}}


def write_request_trace(tracer, path: str, *, timelines=None,
                        labels=None) -> dict:
    """Write the per-request trace (``validate_trace``-gated, like
    :func:`write_trace` — a malformed export fails at write time)."""
    doc = request_trace_document(tracer, timelines=timelines,
                                 labels=labels)
    errors = validate_trace(doc)
    if errors:
        raise ValueError(f"malformed request-trace export: {errors[:3]}")
    with open(path, "w") as f:
        json.dump(doc, f)
    return doc


#: lifecycle spans that ran in the prefill pool (the fabric's handoff
#: prefills there; everything else is decode-replica work)
_PREFILL_POOL_SPANS = ("serve.prefill", "serve.handoff")


def fleet_trace_events(tracer, placement, *, prefill_pid: int = 1999,
                       base_pid: int = 2000,
                       replicas: int | None = None) -> list[dict]:
    """ONE fleet view of a fabric drill: a process track per decode
    replica (pid ``base_pid + r``) plus one for the prefill pool, each
    request a thread (``tid = rid``) on the pool(s) it visited, and a
    flow arrow (``ph "s"``/``"f"``, id = rid) linking the request's
    prefill-pool span to its decode-pool resume — the cross-pool
    journey the per-request view can't show.

    ``placement``: ``{rid: decode replica}`` (``ServingFabric.
    _placement`` / ``summary()["placement"]``)."""
    events: list[dict] = []
    if replicas is None:
        replicas = (max((int(r) for r in placement.values()),
                        default=0) + 1) if placement else 1
    events.append({"ph": "M", "name": "process_name",
                   "pid": prefill_pid, "tid": 0,
                   "args": {"name": "prefill pool"}})
    for r in range(replicas):
        events.append({"ph": "M", "name": "process_name",
                       "pid": base_pid + r, "tid": 0,
                       "args": {"name": f"decode pool r{r}"}})
    for rid in sorted(tracer.requests):
        st = tracer.requests[rid]
        replica = int(placement.get(rid, 0))
        dec_pid = base_pid + replica
        tid = int(rid)
        label = f"request {rid}"
        if st.trace_id:
            label += f" [{st.trace_id}]"
        track = tracer.request_track(rid)
        crossed = any(s["name"] in _PREFILL_POOL_SPANS for s in track)
        events.append({"ph": "M", "name": "thread_name", "pid": dec_pid,
                       "tid": tid, "args": {"name": label}})
        if crossed:
            events.append({"ph": "M", "name": "thread_name",
                           "pid": prefill_pid, "tid": tid,
                           "args": {"name": label}})
        prefill_start = None
        first_decode = None
        for s in track:
            name = s["name"]
            on_prefill = name in _PREFILL_POOL_SPANS
            lbl = name + (" [resumed]" if s.get("resumed") else "")
            events.append({
                "ph": "X", "name": lbl, "cat": "fabric",
                "ts": round(s["ts_ms"] * 1e3, 3),
                "dur": max(round(s["dur_ms"] * 1e3, 3), 0.001),
                "pid": prefill_pid if on_prefill else dec_pid,
                "tid": tid,
                "args": {"rid": rid, "trace_id": st.trace_id,
                         "step": s.get("step"), "replica": replica},
            })
            if on_prefill and prefill_start is None:
                prefill_start = s
            if name == "serve.decode" and first_decode is None:
                first_decode = s
        if prefill_start is not None and first_decode is not None:
            # the cross-pool flow: prefill-pool span -> decode resume
            for ph, pid, ts_ms, extra in (
                    ("s", prefill_pid, prefill_start["ts_ms"], {}),
                    ("f", dec_pid, first_decode["ts_ms"],
                     {"bp": "e"})):
                events.append({
                    "ph": ph, "id": tid, "name": "prefill->decode",
                    "cat": "fabric", "pid": pid, "tid": tid,
                    "ts": round(ts_ms * 1e3, 3), **extra,
                })
    return events


def fleet_trace_document(tracer, placement, *,
                         replicas: int | None = None) -> dict:
    """The fabric-wide Perfetto document (see
    :func:`fleet_trace_events`)."""
    return {"traceEvents": fleet_trace_events(tracer, placement,
                                              replicas=replicas),
            "displayTimeUnit": "ms",
            "otherData": {"producer": "flashmoe_tpu.fabric"}}


def write_fleet_trace(tracer, placement, path: str, *,
                      replicas: int | None = None) -> dict:
    """Write the fleet trace (``validate_trace``-gated like every
    other exporter here)."""
    doc = fleet_trace_document(tracer, placement, replicas=replicas)
    errors = validate_trace(doc)
    if errors:
        raise ValueError(f"malformed fleet-trace export: {errors[:3]}")
    with open(path, "w") as f:
        json.dump(doc, f)
    return doc


def validate_trace(doc: dict) -> list[str]:
    """Schema check against the Trace Event Format invariants this
    exporter relies on.  Returns human-readable problems (empty =
    valid)."""
    errors: list[str] = []
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return ["document is not a dict with a traceEvents list"]
    events = doc["traceEvents"]
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in _KNOWN_PH:
            errors.append(f"{where}: unknown ph {ph!r}")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            errors.append(f"{where}: missing name")
        if not isinstance(ev.get("pid"), int):
            errors.append(f"{where}: missing integer pid")
        if ph == "M":
            if not isinstance(ev.get("args"), dict):
                errors.append(f"{where}: metadata event without args")
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f"{where}: ts must be a non-negative number")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur <= 0:
                errors.append(f"{where}: complete event needs dur > 0")
            if not isinstance(ev.get("tid"), int):
                errors.append(f"{where}: complete event needs tid")
        if ph in ("s", "f"):
            if not isinstance(ev.get("tid"), int):
                errors.append(f"{where}: flow event needs tid")
            if not isinstance(ev.get("id"), (int, str)):
                errors.append(f"{where}: flow event needs an id")
        if ph == "C":
            args = ev.get("args")
            if not isinstance(args, dict) or not all(
                    isinstance(v, (int, float))
                    for v in args.values()):
                errors.append(
                    f"{where}: counter args must be numeric")
    try:
        json.dumps(doc)
    except (TypeError, ValueError) as e:
        errors.append(f"document not JSON-serializable: {e}")
    return errors
