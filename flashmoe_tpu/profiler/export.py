"""Chrome-trace / Perfetto export of a :class:`PhaseTimeline`.

Writes the JSON-object flavor of the Trace Event Format — the format
``ui.perfetto.dev`` and ``chrome://tracing`` open directly — so a CPU
(or TPU) phase timeline becomes a zoomable trace with zero TPU tooling:

* one *process* (pid) per timeline (``bench.py --profile`` merges the
  whole ledger matrix into one file, one pid per matrix point, named
  via ``process_name`` metadata);
* ``tid 0``: MoE phase spans (``moe.gate`` .. ``moe.combine``, chunked
  sub-slices as their own ``moe.expert.k`` slices);
* ``tid 1``: trainer host sections (``train.*``);
* counter tracks (``ph: "C"``) for the stats the driver samples per
  step — expert-load imbalance and flight-recorder queue depth.

Timestamps/durations are microseconds (the format's unit), relative to
each timeline's birth.  :func:`validate_trace` checks the documented
schema invariants; the test suite runs it on every exported file so
"opens cleanly in Perfetto" is CI-gated, not aspirational.
"""

from __future__ import annotations

import json

from flashmoe_tpu.profiler.spans import PhaseTimeline

#: event types this exporter emits (a subset of the Trace Event spec)
_KNOWN_PH = ("X", "C", "M")


def chrome_trace_events(tl: PhaseTimeline, *, pid: int = 0,
                        process_name: str | None = None) -> list[dict]:
    """One timeline -> a list of Trace Event dicts."""
    name = process_name or tl.label or f"flashmoe timeline {pid}"
    events: list[dict] = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
         "args": {"name": name}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": 0,
         "args": {"name": "moe phases"}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": 1,
         "args": {"name": "host sections"}},
    ]

    def complete(rec: dict, tid: int) -> dict:
        args = {"step": rec.get("step")}
        if rec.get("phase") and rec["phase"] != rec["name"]:
            args["phase"] = rec["phase"]  # chunked sub-slice -> base
        return {
            "ph": "X", "name": rec["name"], "cat": rec.get(
                "kind", "phase"),
            "ts": round(rec["ts_ms"] * 1e3, 3),
            "dur": max(round(rec["dur_ms"] * 1e3, 3), 0.001),
            "pid": pid, "tid": tid, "args": args,
        }

    for rec in tl.spans:
        events.append(complete(rec, 0))
    for rec in tl.sections:
        events.append(complete(rec, 1))
    for c in tl.counters:
        events.append({
            "ph": "C", "name": c["name"], "pid": pid,
            "ts": round(c["ts_ms"] * 1e3, 3),
            "args": {"value": c["value"]},
        })
    return events


def trace_document(timelines, *, labels=None) -> dict:
    """Merge one or more timelines into a single trace document (one
    pid each)."""
    if isinstance(timelines, PhaseTimeline):
        timelines = [timelines]
    events: list[dict] = []
    for pid, tl in enumerate(timelines):
        label = labels[pid] if labels else None
        events.extend(chrome_trace_events(tl, pid=pid,
                                          process_name=label))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"producer": "flashmoe_tpu.profiler"}}


def write_trace(timelines, path: str, *, labels=None) -> dict:
    """Write ``trace.json``; returns the document (already validated —
    a malformed export should fail at write time, not in Perfetto)."""
    doc = trace_document(timelines, labels=labels)
    errors = validate_trace(doc)
    if errors:
        raise ValueError(f"malformed trace export: {errors[:3]}")
    with open(path, "w") as f:
        json.dump(doc, f)
    return doc


def request_trace_events(tracer, *, base_pid: int = 1000) -> list[dict]:
    """One Perfetto track PER REQUEST from a
    :class:`flashmoe_tpu.telemetry_plane.tracing.RequestTracer`: each
    request gets its own pid (named ``request <rid> [<trace_id>]``),
    with its lifecycle spans — ``serve.queued`` (eviction gaps render
    as ``serve.queued [resumed]`` slices), ``serve.prefill``,
    ``serve.step`` windows and the nested ``serve.decode`` device
    slices — as ``ph:"X"`` complete events.  Composable with
    :func:`chrome_trace_events` output (phase timelines keep pids <
    ``base_pid``), so one trace.json can carry both views."""
    events: list[dict] = []
    for idx, rid in enumerate(sorted(tracer.requests)):
        st = tracer.requests[rid]
        pid = base_pid + idx
        name = f"request {rid}"
        if st.trace_id:
            name += f" [{st.trace_id}]"
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": name}})
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": 0, "args": {"name": "request lifecycle"}})
        for s in tracer.request_track(rid):
            label = s["name"]
            if s.get("resumed"):
                label += " [resumed]"
            events.append({
                "ph": "X", "name": label, "cat": "request",
                "ts": round(s["ts_ms"] * 1e3, 3),
                "dur": max(round(s["dur_ms"] * 1e3, 3), 0.001),
                "pid": pid, "tid": 0,
                "args": {"rid": rid, "trace_id": st.trace_id,
                         "step": s.get("step")},
            })
    return events


def request_trace_document(tracer, *, timelines=None,
                           labels=None) -> dict:
    """A full trace document of per-request tracks, optionally merged
    with phase timelines (one pid each, below the request pids)."""
    events: list[dict] = []
    if timelines is not None:
        events = trace_document(timelines, labels=labels)["traceEvents"]
    events.extend(request_trace_events(tracer))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"producer": "flashmoe_tpu.profiler"}}


def write_request_trace(tracer, path: str, *, timelines=None,
                        labels=None) -> dict:
    """Write the per-request trace (``validate_trace``-gated, like
    :func:`write_trace` — a malformed export fails at write time)."""
    doc = request_trace_document(tracer, timelines=timelines,
                                 labels=labels)
    errors = validate_trace(doc)
    if errors:
        raise ValueError(f"malformed request-trace export: {errors[:3]}")
    with open(path, "w") as f:
        json.dump(doc, f)
    return doc


def validate_trace(doc: dict) -> list[str]:
    """Schema check against the Trace Event Format invariants this
    exporter relies on.  Returns human-readable problems (empty =
    valid)."""
    errors: list[str] = []
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return ["document is not a dict with a traceEvents list"]
    events = doc["traceEvents"]
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in _KNOWN_PH:
            errors.append(f"{where}: unknown ph {ph!r}")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            errors.append(f"{where}: missing name")
        if not isinstance(ev.get("pid"), int):
            errors.append(f"{where}: missing integer pid")
        if ph == "M":
            if not isinstance(ev.get("args"), dict):
                errors.append(f"{where}: metadata event without args")
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f"{where}: ts must be a non-negative number")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur <= 0:
                errors.append(f"{where}: complete event needs dur > 0")
            if not isinstance(ev.get("tid"), int):
                errors.append(f"{where}: complete event needs tid")
        if ph == "C":
            args = ev.get("args")
            if not isinstance(args, dict) or not all(
                    isinstance(v, (int, float))
                    for v in args.values()):
                errors.append(
                    f"{where}: counter args must be numeric")
    try:
        json.dumps(doc)
    except (TypeError, ValueError) as e:
        errors.append(f"document not JSON-serializable: {e}")
    return errors
