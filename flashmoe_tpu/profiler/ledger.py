"""Predicted-vs-actual cost ledger: join the measured phase timeline
against the analytical planner, phase by phase.

PR 2's drift monitor compares END-TO-END latency against the planner —
it can say "this layer is 2x the prediction" but not which term of the
cost model is lying.  This module closes that gap: the profiler's
phase timeline (:mod:`flashmoe_tpu.profiler.spans`) measures gate /
dispatch-a2a / expert-FFN / combine-a2a individually, and the ledger
prices each phase with the same ingredients the planner's
:func:`~flashmoe_tpu.planner.model.predict_paths` uses (roofline
compute+HBM for the on-chip phases, per-leg wire serialization for the
exchanges), emitting one ``planner.phase_drift`` decision per phase.
An a2a leg drifting alone points at the transport model or a sick
link; the expert phase drifting alone points at the roofline's
mxu_fraction — per-phase drift supersedes end-to-end drift as the
tuning-override signal (docs/PLANNER.md).

The ledger also cross-checks the chunked-overlap story: the fenced
timeline's serialized phase sum over the same computation's *jitted*
(overlap-scheduled) step time is a measured overlap fraction, judged
against ``overlap.chunked_overlap_bound`` through the existing
``planner.overlap_drift`` monitor — the only way to *verify* the
Comet-style pipeline is hiding communication rather than just being
modeled to.

``run_ledger_matrix`` drives the acceptance matrix — flat /
hierarchical / ragged x {serial, chunked} x {wire off, e4m3} — on the
virtual CPU mesh (``bench.py --profile``), writing ``ledger.jsonl`` +
``trace.json`` artifacts that ``python -m flashmoe_tpu.observe
--ledger`` summarizes.
"""

from __future__ import annotations

import os
import time

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.profiler.spans import PhaseTimeline

#: the four phases of the reference kernel's thesis — the ledger's join
#: keys (scatter/gather phases ``moe.dispatch``/``moe.combine`` are
#: measured too but priced inside the on-chip roofline terms)
PHASES = ("moe.gate", "moe.a2a_dispatch", "moe.expert",
          "moe.a2a_combine")


def predicted_phase_ms(cfg: MoEConfig, d: int = 1, gen: str = "v5e", *,
                       path: str = "collective", slices: int = 1,
                       links: int = 4,
                       mxu_fraction: float = 1.0) -> dict[str, float]:
    """Per-phase predicted latency (ms) at (cfg, d ranks, gen) — the
    planner's cost decomposition re-cut along the profiler's phase
    boundaries, from the same primitives (``topology`` peaks,
    ``planner.model.slab_bytes``, ``analysis.wire_row_bytes``, and the
    per-leg formula ``planner.model.a2a_leg_ms``) so ledger and
    planner can never price the same bytes differently."""
    import jax.numpy as jnp

    from flashmoe_tpu.analysis import wire_row_bytes
    from flashmoe_tpu.planner.model import (
        _dtype_peak, a2a_leg_ms, slab_bytes,
    )

    peak_fs, hbm_bs = _dtype_peak(gen, cfg)
    peak_fs *= max(min(mxu_fraction, 1.0), 1e-6)
    d = max(d, 1)
    s_loc = max(cfg.tokens // d, 1)
    h, i_dim, e = cfg.hidden_size, cfg.intermediate_size, cfg.num_experts
    dt = jnp.dtype(cfg.dtype).itemsize
    n = cfg.a2a_chunks or 1

    # gate: router logits GEMM on local tokens (+ x and gate_w reads)
    gate_fl = 2.0 * s_loc * h * e
    gate_by = s_loc * h * dt + h * e * 4
    out = {"moe.gate": max(gate_fl / peak_fs, gate_by / hbm_bs) * 1e3}

    # expert FFN: routed rows this rank computes under uniform routing
    rows = s_loc * cfg.expert_top_k
    gemms = 3 if cfg.gated_ffn else 2
    ffn_fl = gemms * 2.0 * rows * h * i_dim
    nlx = max(e // d, 1)
    w_by = gemms * nlx * h * i_dim * dt        # local weights, once
    act_by = (2 * h + i_dim) * rows * dt       # rows in/out + hidden
    out["moe.expert"] = max(ffn_fl / peak_fs,
                            (w_by + act_by) / hbm_bs) * 1e3

    if d > 1:
        def leg(which: str) -> float:
            # the DCN-wire override only applies where the layer runs
            # the two-stage exchange (1 < inner < d, ep.py transport —
            # the same guard predict_paths uses: never price a discount
            # the transport cannot deliver).  The ragged transport is
            # flat-only (no per-hop codec), so it never re-encodes.
            hop = ("dcn" if path != "ragged"
                   and d // max(slices, 1) > 1 else "ici")
            if path == "ragged":
                slab = rows / d * wire_row_bytes(cfg, which)
                dcn_slab = rows / d * wire_row_bytes(cfg, which, hop)
            else:
                slab = slab_bytes(cfg, d, leg=which)
                dcn_slab = slab_bytes(cfg, d, leg=which, hop=hop)
            # THE per-leg formula (planner.model.a2a_leg_ms): ledger
            # and planner can never price the same bytes differently
            # (the dcn slab rides the wire_dtype_dcn row size when the
            # cross-slice hop re-encodes)
            ici, dcn = a2a_leg_ms(slab, "hierarchical", d=d, gen=gen,
                                  slices=slices, links=links, chunks=n,
                                  dcn_slab=dcn_slab)
            return ici + dcn

        out["moe.a2a_dispatch"] = leg("dispatch")
        out["moe.a2a_combine"] = leg("combine")
    return out


def profile_moe_phases(cfg: MoEConfig, mesh, *, path: str = "collective",
                       steps: int = 1, dcn_inner: int | None = None,
                       seed: int = 0, overlapped: bool = True,
                       recorder=None, label: str = "") -> PhaseTimeline:
    """Measure the phase timeline of one MoE layer point.

    Runs the layer EAGERLY (no jit) with ``profile_phases=True`` and a
    timeline armed: eager shard_map dispatches per primitive with
    concrete per-device values, so the in-body fences
    (:func:`flashmoe_tpu.profiler.spans.fence`) genuinely block and
    every trace_span's duration is device-complete wall time.  Stats
    collection is forced on so the imbalance counter track has data.

    ``overlapped=True`` additionally times the SAME computation jitted
    (XLA's latency-hiding schedule) and stores the median per-step ms
    on ``timeline.overlapped_ms`` — the denominator of the ledger's
    measured overlap fraction.  ``recorder``: a FlightRecorder to
    land per-step phase records in (the flight-ring integration)."""
    import jax

    from flashmoe_tpu.models.reference import init_moe_params
    from flashmoe_tpu.profiler import spans

    pcfg = cfg.replace(profile_phases=True, collect_stats=True)
    key = jax.random.PRNGKey(seed)
    params = init_moe_params(key, pcfg)
    params = jax.tree_util.tree_map(
        lambda p: p.astype(pcfg.dtype)
        if hasattr(p, "astype") else p, params)
    x = jax.random.normal(jax.random.PRNGKey(seed + 1),
                          (pcfg.tokens, pcfg.hidden_size), pcfg.dtype)

    if path == "ragged":
        from flashmoe_tpu.parallel.ragged_ep import ragged_ep_moe_layer

        def run(p, xx, c):
            return ragged_ep_moe_layer(p, xx, c, mesh)
    else:
        from flashmoe_tpu.parallel.ep import ep_moe_layer

        def run(p, xx, c):
            return ep_moe_layer(p, xx, c, mesh,
                                dcn_inner=(dcn_inner or 0))

    tl = PhaseTimeline(label=label or f"{path} d={mesh.shape['ep']}")
    tl.meta = {
        "path": path, "d": int(mesh.shape["ep"]),
        "chunks": cfg.a2a_chunks or 1, "dcn_inner": dcn_inner,
        "wire": cfg.wire_dtype or "off",
        "wire_combine": cfg.wire_dtype_combine or "off",
    }
    with spans.profiling(tl):
        for i in range(max(steps, 1)):
            tl.begin_step(i)
            out = run(params, x, pcfg)
            jax.block_until_ready(out.out)
            tl.end_step()
            if out.stats is not None:
                tl.counter("moe.load_imbalance",
                           float(out.stats.imbalance), step=i)
            if recorder is not None:
                recorder.record(**tl.step_records()[-1], **tl.meta)
                tl.counter("flight.queue_depth", len(recorder), step=i)
    if overlapped:
        # the jitted (overlap-scheduled) step: profile_phases stays on
        # — the knob is graph-neutral, so this times the IDENTICAL
        # graph the planner prices, with XLA free to overlap
        jf = jax.jit(lambda p, xx: run(p, xx, pcfg).out)
        jax.block_until_ready(jf(params, x))  # compile + warm
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(jf(params, x))
            times.append(time.perf_counter() - t0)
        tl.overlapped_ms = sorted(times)[len(times) // 2] * 1e3
    return tl


def phase_ledger(tl: PhaseTimeline, cfg: MoEConfig, *, d: int, gen: str,
                 path: str, slices: int = 1, links: int = 4,
                 mxu_fraction: float = 1.0, warn: bool = False
                 ) -> tuple[list[dict], dict | None]:
    """Join a measured timeline against the per-phase predictions.

    Returns ``(rows, overlap)``: one row per joined phase (each also
    recorded as a ``planner.phase_drift`` decision), and — when the
    timeline carries an overlapped (jitted) step time at d > 1 — the
    measured-vs-bound overlap fraction, recorded through the existing
    ``planner.overlap_drift`` monitor so the chunk picks' validation
    loop (PR 6) sees profiler data too."""
    from flashmoe_tpu.ops import wire as wr
    from flashmoe_tpu.planner.drift import (
        record_overlap_drift, record_phase_drift,
    )

    measured = tl.phase_means()
    pred = predicted_phase_ms(cfg, d, gen, path=path, slices=slices,
                              links=links, mxu_fraction=mxu_fraction)
    rows = []
    for ph in PHASES:
        if ph not in measured or ph not in pred:
            continue
        rec = record_phase_drift(cfg, path, ph, measured[ph],
                                 predicted_ms=pred[ph], d=d, gen=gen,
                                 warn=warn)
        rows.append({
            "phase": ph, "path": path, "gen": gen, "d": int(d),
            "chunks": rec.chunks, "wire": rec.wire,
            "measured_ms": round(measured[ph], 6),
            "predicted_ms": round(pred[ph], 6),
            "rel_error": round(rec.rel_error, 4),
            "exceeded": rec.exceeded,
        })

    overlap = None
    if tl.overlapped_ms and d > 1:
        from flashmoe_tpu.parallel.overlap import chunked_overlap_bound

        n = cfg.a2a_chunks or 1
        serial_ms = sum(measured.values())  # fenced = fully serialized
        frac = serial_ms / tl.overlapped_ms
        bound = chunked_overlap_bound(
            cfg, d, gen, n, links=links, mxu_fraction=mxu_fraction,
            path="ragged" if path == "ragged" else "collective",
        )["overlap_efficiency_bound"]
        odr = record_overlap_drift(path, frac,
                                   predicted_fraction=bound, gen=gen,
                                   d=d, chunks=n, warn=warn)
        overlap = {
            "path": path, "gen": gen, "d": int(d), "chunks": n,
            "wire": (f"{wr.canonical_name(cfg.wire_dtype)}/"
                     f"{wr.canonical_name(cfg.wire_dtype_combine)}"),
            "serial_phase_sum_ms": round(serial_ms, 6),
            "overlapped_ms": round(tl.overlapped_ms, 6),
            "measured_fraction": round(frac, 4),
            "predicted_fraction": round(bound, 4),
            "exceeded": odr.exceeded,
        }
    return rows, overlap


# ----------------------------------------------------------------------
# The acceptance matrix (bench.py --profile / tests)
# ----------------------------------------------------------------------

#: (name, ep width, dcn_inner, profiler path, planner slices)
MATRIX_PATHS = (
    ("flat", 2, None, "collective", 1),
    ("hierarchical", 4, 2, "collective", 2),
    ("ragged", 2, None, "ragged", 1),
)
MATRIX_CHUNKS = (None, 2)
MATRIX_WIRES = (None, "e4m3")


def ledger_config(ep: int) -> MoEConfig:
    """The matrix's measurement point: the invariant engine's
    small-config shape (drills every feature, costs kilobytes)."""
    import jax.numpy as jnp

    return MoEConfig(num_experts=8, expert_top_k=2, hidden_size=64,
                     intermediate_size=128, sequence_len=64 * ep,
                     drop_tokens=False, ep=ep, dtype=jnp.float32,
                     param_dtype=jnp.float32)


def run_ledger_matrix(obs_dir: str | None = None, *, quick: bool = False,
                      steps: int = 1, gen: str | None = None,
                      devices=None, overlapped: bool = True,
                      warn: bool = False) -> list[dict]:
    """Profile and ledger every matrix point; write artifacts.

    ``quick`` restricts to the first point (flat x serial x wire off) —
    the fast-lane CI smoke; the full matrix is slow-test / CLI
    material (eager per-primitive dispatch costs seconds per point on
    the virtual CPU mesh).  Artifacts into ``obs_dir``:
    ``ledger.jsonl`` (one line per joined phase + one ``overlap``
    line per point) and ``trace.json`` (all points merged, one
    Perfetto process per point).  Returns the per-point summary
    records (also the ``bench.py --profile`` output lines)."""
    import json

    import jax

    from flashmoe_tpu.ops import wire as wr
    from flashmoe_tpu.parallel.mesh import make_mesh
    from flashmoe_tpu.profiler.export import write_trace
    from flashmoe_tpu.utils.telemetry import FlightRecorder

    gen = gen or os.environ.get("FLASHMOE_TPU_GEN") or "v5e"
    devices = list(devices if devices is not None else jax.devices())
    records: list[dict] = []
    timelines: list[PhaseTimeline] = []
    labels: list[str] = []
    ledger_rows: list[dict] = []
    recorder = FlightRecorder()

    for pname, ep, dcn_inner, ppath, slices in MATRIX_PATHS:
        if len(devices) < ep:
            # no silent caps: a reduced matrix must be visible, or a
            # 2-chip run reads as "covered everything"
            import warnings

            warnings.warn(
                f"profile matrix: skipping the {pname!r} path — needs "
                f"{ep} devices, have {len(devices)}", RuntimeWarning,
                stacklevel=2)
            continue
        base = ledger_config(ep)
        mesh = make_mesh(base, dp=1, devices=devices[:ep])
        for chunks in MATRIX_CHUNKS:
            for wire in MATRIX_WIRES:
                cfg = base.replace(a2a_chunks=chunks, wire_dtype=wire)
                label = (f"{pname} chunks={chunks or 1} "
                         f"wire={wr.canonical_name(wire)}")
                tl = profile_moe_phases(
                    cfg, mesh, path=ppath, steps=steps,
                    dcn_inner=dcn_inner, overlapped=overlapped,
                    recorder=recorder, label=label)
                rows, overlap = phase_ledger(
                    tl, cfg, d=ep, gen=gen,
                    path=pname if pname == "hierarchical" else ppath,
                    slices=slices, warn=warn)
                # rows carry BOTH names: "path" is the planner's path
                # (the planner.phase_drift join key; "collective" IS
                # the flat transport) and "point" is the matrix point
                # the docs/bench records speak (flat/hierarchical/
                # ragged), so either vocabulary filters ledger.jsonl
                rows = [dict(r, point=pname) for r in rows]
                ledger_rows.extend(rows)
                if overlap is not None:
                    ledger_rows.append(dict(overlap, record="overlap",
                                            point=pname))
                timelines.append(tl)
                labels.append(label)
                records.append({
                    "metric": f"phase_ledger[{pname},"
                              f"chunks={chunks or 1},"
                              f"wire={wr.canonical_name(wire)}]",
                    "value": round(sum(r["measured_ms"]
                                       for r in rows), 3),
                    "unit": "ms", "path": pname, "gen": gen, "d": ep,
                    "a2a_chunks": chunks or 1,
                    "wire_dtype": wr.canonical_name(wire),
                    "step_ms": round(tl.step_wall_means() or 0.0, 3),
                    "overlapped_ms": (round(tl.overlapped_ms, 3)
                                      if tl.overlapped_ms else None),
                    "phases": {r["phase"]: r["measured_ms"]
                               for r in rows},
                    "phase_drift": {r["phase"]: r["rel_error"]
                                    for r in rows},
                    "overlap": overlap,
                })
                if quick:
                    break
            if quick:
                break
        if quick:
            break

    if obs_dir:
        os.makedirs(obs_dir, exist_ok=True)
        with open(os.path.join(obs_dir, "ledger.jsonl"), "w") as f:
            for row in ledger_rows:
                f.write(json.dumps(row) + "\n")
        write_trace(timelines, os.path.join(obs_dir, "trace.json"),
                    labels=labels)
        recorder.export_jsonl(os.path.join(obs_dir, "flight.jsonl"))
    return records
