"""Crash postmortem bundles: everything a triage needs, in one dir.

When in-job recovery gives up (``resilient_train`` exhausts its retry
budget, ``supervise`` exhausts its restart budget, a chaos drill
forces a process death), the state that explains the failure is spread
across process memory: the flight-recorder ring, the telemetry
decision stream, the resolved config, the planner's prediction, the
profiler timeline if one was armed, and the traceback itself.  A
bundle freezes all of it to disk as small, self-describing files::

    bundle-<stamp>/
      MANIFEST.json     bundle version, error summary, file inventory
      traceback.txt     the formatted exception chain
      decisions.jsonl   run + global decision streams (tagged)
      metrics.json      counters/gauges/timer summary
      flight.jsonl      flight-ring records (or the history list)
      config.json       the resolved MoEConfig (when known)
      planner.json      last path selection + fresh predictions
      env.json          python/jax versions, backend, devices, env
      trace.json        profiler timeline (when one was armed)

``python -m flashmoe_tpu.observe --postmortem <bundle>`` renders the
triage report.  Writing is strictly best-effort: a postmortem writer
must never mask the failure it documents, so every section is wrapped
and a partial bundle is still a valid bundle.
"""

from __future__ import annotations

import json
import os
import time
import traceback

BUNDLE_VERSION = 1
MANIFEST = "MANIFEST.json"

_SEQ = [0]  # same-process uniqueness for same-second bundles


def _bundle_name(step) -> str:
    _SEQ[0] += 1
    stamp = time.strftime("%Y%m%d-%H%M%S")
    tag = f"step{int(step)}" if step is not None else "nostep"
    return f"bundle-{stamp}-{tag}-p{os.getpid()}-{_SEQ[0]}"


def write_bundle(directory: str, *, error=None, cfg=None,
                 metrics_obj=None, history=None, recorder=None,
                 timeline=None, step=None, extra: dict | None = None
                 ) -> str | None:
    """Write one bundle under ``directory``; returns its path, or None
    when even the directory could not be created (best-effort all the
    way down — the caller is already on a failure path)."""
    try:
        os.makedirs(directory, exist_ok=True)
        bundle = os.path.join(directory, _bundle_name(step))
        os.makedirs(bundle)
    except OSError:
        return None

    files: list[str] = []

    def _write(name: str, writer) -> None:
        try:
            writer(os.path.join(bundle, name))
            files.append(name)
        except Exception:  # noqa: BLE001 — never mask the crash
            pass

    # the decision goes into the GLOBAL stream first so the bundle's
    # own decisions.jsonl carries the record of its creation
    from flashmoe_tpu.utils.telemetry import metrics as global_metrics

    try:
        sink = metrics_obj if metrics_obj is not None else global_metrics
        sink.decision("postmortem.saved", dir=bundle,
                      step=(int(step) if step is not None else None),
                      error=(f"{type(error).__name__}: {error}"[:300]
                             if error is not None else None))
    except Exception:  # noqa: BLE001
        pass

    if error is not None:
        def _tb(path):
            with open(path, "w") as f:
                if getattr(error, "__traceback__", None) is not None:
                    f.write("".join(traceback.format_exception(
                        type(error), error, error.__traceback__)))
                else:
                    f.write(f"{type(error).__name__}: {error}\n")
        _write("traceback.txt", _tb)

    def _decisions(path):
        with open(path, "w") as f:
            if metrics_obj is not None:
                for d in metrics_obj.decisions:
                    f.write(json.dumps(dict(d, stream="run")) + "\n")
            for d in global_metrics.decisions:
                f.write(json.dumps(dict(d, stream="global")) + "\n")
    _write("decisions.jsonl", _decisions)

    if metrics_obj is not None:
        _write("metrics.json", lambda p: json.dump(
            metrics_obj.summary(), open(p, "w"), default=str))

    flight = (recorder.records if recorder is not None
              else list(history or []))
    if flight:
        def _flight(path):
            with open(path, "w") as f:
                for rec in flight:
                    f.write(json.dumps(rec, default=str) + "\n")
        _write("flight.jsonl", _flight)

    if cfg is not None:
        _write("config.json", lambda p: open(p, "w").write(
            cfg.to_json()))

        def _planner(path):
            from flashmoe_tpu import tuning
            from flashmoe_tpu.planner.model import predict_paths

            sel = None
            for src in ([metrics_obj] if metrics_obj is not None
                        else []) + [global_metrics]:
                sel = sel or src.last_decision("planner.path_select")
            doc = {"last_path_select": sel}
            try:
                preds = predict_paths(cfg, max(cfg.ep, 1),
                                      tuning.generation())
                doc["predictions"] = [{
                    "path": p.path, "feasible": p.feasible,
                    "total_ms": round(p.total_ms, 4), "note": p.note,
                } for p in preds]
            except Exception as e:  # noqa: BLE001 — partial is fine
                doc["prediction_error"] = f"{type(e).__name__}: {e}"
            json.dump(doc, open(path, "w"))
        _write("planner.json", _planner)

    def _env(path):
        import platform
        import sys

        import jax

        doc = {
            "python": sys.version,
            "platform": platform.platform(),
            "jax": jax.__version__,
            "env": {k: v for k, v in sorted(os.environ.items())
                    if k.startswith(("FLASHMOE_", "JAX_", "XLA_"))},
        }
        try:
            doc["backend"] = jax.default_backend()
            doc["device_count"] = jax.device_count()
        except Exception as e:  # noqa: BLE001 — backend may be wedged
            doc["backend_error"] = f"{type(e).__name__}: {e}"
        json.dump(doc, open(path, "w"))
    _write("env.json", _env)

    if timeline is None:
        from flashmoe_tpu.profiler import spans

        timeline = spans.active()
    if timeline is not None and (timeline.spans or timeline.sections):
        def _trace(path):
            from flashmoe_tpu.profiler.export import write_trace

            write_trace(timeline, path)
        _write("trace.json", _trace)

    manifest = {
        "bundle_version": BUNDLE_VERSION,
        "created_unix": time.time(),
        "step": int(step) if step is not None else None,
        "error": (f"{type(error).__name__}: {error}"[:500]
                  if error is not None else None),
        "files": sorted(files),
    }
    if extra:
        manifest["extra"] = extra
    try:
        with open(os.path.join(bundle, MANIFEST), "w") as f:
            json.dump(manifest, f, indent=2, default=str)
    except OSError:
        return None
    return bundle


def is_bundle(path: str) -> bool:
    return os.path.isfile(os.path.join(path, MANIFEST))


def find_bundles(directory: str) -> list[str]:
    """Bundle dirs under ``directory`` (itself included if it IS one),
    oldest first."""
    if not os.path.isdir(directory):
        return []
    if is_bundle(directory):
        return [directory]
    out = [os.path.join(directory, n)
           for n in sorted(os.listdir(directory))
           if is_bundle(os.path.join(directory, n))]
    return out


def load_bundle(path: str) -> dict:
    """Parse a bundle back into memory (tolerant: missing files yield
    missing keys)."""
    if not is_bundle(path):
        raise FileNotFoundError(f"{path!r} is not a postmortem bundle "
                                f"(no {MANIFEST})")
    out: dict = {"path": path}
    with open(os.path.join(path, MANIFEST)) as f:
        out["manifest"] = json.load(f)

    def _maybe_json(name):
        p = os.path.join(path, name)
        if os.path.isfile(p):
            try:
                with open(p) as f:
                    return json.load(f)
            except ValueError:
                return None
        return None

    def _maybe_jsonl(name):
        p = os.path.join(path, name)
        recs = []
        if os.path.isfile(p):
            with open(p) as f:
                for line in f:
                    try:
                        recs.append(json.loads(line))
                    except ValueError:
                        continue
        return recs

    out["config"] = _maybe_json("config.json")
    out["env"] = _maybe_json("env.json")
    out["metrics"] = _maybe_json("metrics.json")
    out["planner"] = _maybe_json("planner.json")
    out["trace"] = _maybe_json("trace.json")
    out["decisions"] = _maybe_jsonl("decisions.jsonl")
    out["flight"] = _maybe_jsonl("flight.jsonl")
    tb = os.path.join(path, "traceback.txt")
    if os.path.isfile(tb):
        with open(tb) as f:
            out["traceback"] = f.read()
    return out
