"""SLO watchdog: step/phase-time budgets with breach escalation.

An SRE story for the training loop: declare a wall-time budget for the
step (and optionally per MoE phase), and the watchdog turns sustained
violations into the framework's existing recovery machinery —

* every budget violation is a ``slo.breach`` decision (target, measured
  vs budget, consecutive count) and a ``slo.breaches`` counter;
* the first in-budget observation after a breach run is a
  ``slo.recovered`` decision, so the JSONL stream reads as breach
  *episodes*, not noise;
* ``consecutive`` breaches of the STEP budget escalate: when
  ``demote_backend`` names an execution path, the watchdog calls
  :func:`flashmoe_tpu.planner.select.report_path_failure` — the PR 3
  demotion machinery — so a sustained a2a regression on a specialized
  transport (fused / ragged) demotes the job back onto the collective
  baseline at the next path resolution instead of missing its SLO
  forever.  Escalation fires once per breach episode.

Budgets come from an :class:`SLOConfig` built in code or loaded from a
YAML sidecar (``SLOConfig.from_yaml``; PyYAML when available, with a
dependency-free fallback parser for the flat schema below)::

    step_ms: 250          # budget for one train step
    consecutive: 3        # breaches before escalation
    demote_backend: ragged
    phase_ms:
      moe.expert: 120
      moe.a2a_dispatch: 40

Wiring: ``runtime.trainer.train(..., slo=...)`` and
``runtime.resilient.resilient_train(..., slo=...)`` feed the watchdog
every step's wall time; profiled runs can feed per-phase times too.
"""

from __future__ import annotations

import dataclasses

from flashmoe_tpu.utils.telemetry import Metrics, metrics as _global


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """Budgets and escalation policy (immutable; the watchdog carries
    the mutable episode state)."""

    step_ms: float | None = None
    phase_ms: tuple = ()            # ((phase, budget_ms), ...)
    consecutive: int = 3            # step breaches before escalation
    demote_backend: str | None = None
    # serving budgets (flashmoe_tpu/serving/engine.py): per-request
    # time-to-first-token and time-per-output-token — point
    # observations judged at retirement via :meth:`SLOWatchdog.
    # observe_request`, each violation its own ``slo.breach``
    ttft_ms: float | None = None
    tpot_ms: float | None = None

    def __post_init__(self):
        for name, v in (("step_ms", self.step_ms),
                        ("ttft_ms", self.ttft_ms),
                        ("tpot_ms", self.tpot_ms)):
            if v is not None and v <= 0:
                raise ValueError(f"{name} budget must be > 0, got {v}")
        if self.consecutive < 1:
            raise ValueError("consecutive must be >= 1")
        for ph, ms in self.phase_ms:
            if ms <= 0:
                raise ValueError(f"phase budget {ph!r} must be > 0")

    @property
    def phase_budgets(self) -> dict:
        return dict(self.phase_ms)

    @classmethod
    def from_dict(cls, raw: dict) -> "SLOConfig":
        known = {"step_ms", "consecutive", "demote_backend", "phase_ms",
                 "ttft_ms", "tpot_ms"}
        unknown = set(raw) - known
        if unknown:
            raise ValueError(f"unknown SLO keys {sorted(unknown)}; "
                             f"known: {sorted(known)}")
        phases = raw.get("phase_ms") or {}
        if not isinstance(phases, dict):
            raise ValueError("phase_ms must be a mapping of "
                             "phase -> budget ms")
        phase_ms = []
        for k, v in sorted(phases.items()):
            try:
                phase_ms.append((str(k), float(v)))
            except (TypeError, ValueError):
                raise ValueError(f"phase_ms[{k!r}] must be a number, "
                                 f"got {v!r}") from None
        cons = raw.get("consecutive")
        try:
            return cls(
                step_ms=(float(raw["step_ms"])
                         if raw.get("step_ms") is not None else None),
                consecutive=int(cons) if cons is not None else 3,
                demote_backend=raw.get("demote_backend") or None,
                phase_ms=tuple(phase_ms),
                ttft_ms=(float(raw["ttft_ms"])
                         if raw.get("ttft_ms") is not None else None),
                tpot_ms=(float(raw["tpot_ms"])
                         if raw.get("tpot_ms") is not None else None),
            )
        except TypeError as e:
            # a null/list where a scalar belongs: surface the documented
            # ValueError instead of a bare TypeError
            raise ValueError(f"bad SLO sidecar value: {e}") from None

    @classmethod
    def from_yaml(cls, path: str) -> "SLOConfig":
        """Load the YAML sidecar.  PyYAML when importable; otherwise a
        minimal parser for the documented flat two-level schema (maps
        of scalars, one nested ``phase_ms`` map)."""
        with open(path) as f:
            text = f.read()
        try:
            import yaml  # noqa: PLC0415 — optional dependency

            raw = yaml.safe_load(text) or {}
        except ImportError:
            raw = _parse_flat_yaml(text)
        if not isinstance(raw, dict):
            raise ValueError(f"SLO sidecar {path!r} must be a mapping")
        return cls.from_dict(raw)


def _parse_flat_yaml(text: str) -> dict:
    """Dependency-free subset parser: ``key: value`` lines, one level
    of nesting for mapping values, ``#`` comments.  A bare ``key:``
    with no indented children is YAML null (PyYAML parity), not an
    empty mapping."""
    out: dict = {}
    current: tuple[str, dict] | None = None  # open (key, mapping)

    def _close():
        # a "key:" that gathered no children parses as null, exactly
        # as PyYAML's safe_load would
        nonlocal current
        if current is not None and not current[1]:
            out[current[0]] = None
        current = None

    for line in text.splitlines():
        stripped = line.split("#", 1)[0].rstrip()
        if not stripped.strip():
            continue
        indented = stripped.startswith((" ", "\t"))
        key, sep, val = stripped.strip().partition(":")
        if not sep:
            raise ValueError(f"unparseable SLO line: {line!r}")
        val = val.strip()
        if indented:
            if current is None:
                raise ValueError(f"indented line outside a mapping: "
                                 f"{line!r}")
            current[1][key] = _scalar(val)
        elif val == "":
            _close()
            current = (key, out.setdefault(key, {}))
        else:
            _close()
            out[key] = _scalar(val)
    _close()
    return out


def _scalar(v: str):
    if v.lower() in ("null", "none", "~", ""):
        return None
    for cast in (int, float):
        try:
            return cast(v)
        except ValueError:
            pass
    return v.strip("'\"")


class SLOWatchdog:
    """Feed it every step; it narrates budget compliance and escalates
    sustained step-budget breaches into path demotion."""

    def __init__(self, slo: SLOConfig, metrics: Metrics | None = None):
        self.slo = slo
        self.metrics = metrics if metrics is not None else _global
        self._consecutive = 0           # step-budget breach run length
        self._breached: set = set()     # targets currently in breach
        self._escalated = False         # once per breach episode

    @property
    def consecutive_breaches(self) -> int:
        return self._consecutive

    def snapshot(self) -> dict:
        """Episode state for the live ``/healthz`` endpoint: which
        targets are currently in breach, the step-budget run length,
        whether this episode already escalated, and the budgets being
        judged against."""
        return {
            "consecutive_step_breaches": self._consecutive,
            "in_breach": sorted(self._breached),
            "escalated": self._escalated,
            "budgets": {
                k: v for k, v in (
                    ("step_ms", self.slo.step_ms),
                    ("ttft_ms", self.slo.ttft_ms),
                    ("tpot_ms", self.slo.tpot_ms),
                ) if v is not None
            },
        }

    def observe_request(self, step: int, request_id,
                        *, ttft_ms: float | None = None,
                        tpot_ms: float | None = None,
                        dominant: str | None = None) -> list[dict]:
        """Judge one completed serving request against the TTFT/TPOT
        budgets.  Point observations — requests are independent, so
        each violation is its own ``slo.breach`` (target ``ttft`` /
        ``tpot``, with the request id) and there is no recovery pair
        or escalation run: the step budget remains the escalation
        channel.  ``dominant``: the request's critical-path attribution
        verdict (telemetry_plane/attribution.py) — carried on the
        breach so the decision names WHERE the budget went, not just
        that it went.  Returns the breach records raised."""
        events: list[dict] = []
        for target, measured, budget in (
                ("ttft", ttft_ms, self.slo.ttft_ms),
                ("tpot", tpot_ms, self.slo.tpot_ms)):
            if budget is None or measured is None:
                continue
            if measured > budget:
                self.metrics.count("slo.breaches")
                events.append(self.metrics.decision(
                    "slo.breach", target=target, step=int(step),
                    request=request_id,
                    measured_ms=round(float(measured), 3),
                    budget_ms=float(budget), consecutive=None,
                    dominant=dominant))
        return events

    def observe_step(self, step: int, step_ms: float,
                     phases: dict | None = None) -> list[dict]:
        """Compare one step (and optionally its phase breakdown)
        against the budgets.  Returns the breach records raised this
        step (empty = within budget)."""
        events: list[dict] = []
        targets: list[tuple[str, float, float]] = []
        if self.slo.step_ms is not None:
            targets.append(("step", float(step_ms), self.slo.step_ms))
        if phases:
            for ph, budget in self.slo.phase_budgets.items():
                if ph in phases:
                    targets.append((ph, float(phases[ph]), budget))

        for target, measured, budget in targets:
            if measured > budget:
                if target == "step":
                    self._consecutive += 1
                self._breached.add(target)
                self.metrics.count("slo.breaches")
                rec = self.metrics.decision(
                    "slo.breach", target=target, step=int(step),
                    measured_ms=round(measured, 3),
                    budget_ms=float(budget),
                    consecutive=(self._consecutive
                                 if target == "step" else None))
                events.append(rec)
            elif target in self._breached:
                self._breached.discard(target)
                if target == "step":
                    self._consecutive = 0
                    self._escalated = False
                self.metrics.count("slo.recoveries")
                self.metrics.decision(
                    "slo.recovered", target=target, step=int(step),
                    measured_ms=round(measured, 3),
                    budget_ms=float(budget))
            elif target == "step":
                self._consecutive = 0
                self._escalated = False

        if (self._consecutive >= self.slo.consecutive
                and not self._escalated):
            self._escalated = True
            self.metrics.count("slo.escalations")
            if self.slo.demote_backend:
                # sustained breach -> the PR 3 path-demotion machinery:
                # the next 'auto' resolution re-plans off this backend
                from flashmoe_tpu.planner.select import (
                    report_path_failure,
                )

                report_path_failure(
                    self.slo.demote_backend,
                    f"slo: step budget {self.slo.step_ms} ms breached "
                    f"{self._consecutive} consecutive steps "
                    f"(last step {int(step)})")
        return events
