"""Host-side span clock: per-step, per-phase wall durations without
xprof.

How it measures real time.  The EP shard bodies already wrap their
phases in :func:`flashmoe_tpu.utils.telemetry.trace_span`.  When a
:class:`PhaseTimeline` is armed (:func:`install` /
:func:`profiling`) the spans report their host enter/exit instants
here; and when ``MoEConfig.profile_phases`` is on, the bodies
additionally call :func:`fence` on each phase's result.  Under *eager*
execution (no ``jit``) a shard_map body's values are
``ShardMapTracer``\\ s carrying concrete per-device arrays (``.val``),
so the fence genuinely blocks until the phase's work has executed —
the span exit instant is then device-complete time, and the per-step
phase durations sum to the step's wall time
(``tests/test_profiler.py`` asserts it).

Under ``jit`` the same code traces once and the fences see abstract
tracers: they no-op (nothing to block on), no op is added to the
graph, and the traced jaxpr is byte-identical with the knob on or off
— ``profile_phases`` is registered as a *graph-neutral* knob in the
staticcheck registry and the invariant engine proves it.  Phase spans
are only recorded while a step is open (:meth:`PhaseTimeline.
begin_step`), so a timeline armed around a jitted training loop never
collects trace-time garbage; the trainer's host-level *sections*
(``train.data_pull`` / ``train.step`` / ``train.checkpoint`` /
``train.drain``) are recorded regardless, because they are host work
by definition.

Everything here is host-side bookkeeping: with no timeline armed the
fast paths are a single ``None`` check.
"""

from __future__ import annotations

import contextlib
import time

#: the armed timeline (one slot; host-side — profiling is a process
#: activity, not a per-config one)
_ACTIVE: list = [None]


def active() -> "PhaseTimeline | None":
    return _ACTIVE[0]


def merged_phase(name: str) -> str:
    """Canonical phase of a span name: chunked pipeline spans
    (``moe.expert.3``) merge onto their base phase (``moe.expert``)."""
    head, _, tail = name.rpartition(".")
    return head if head and tail.isdigit() else name


class PhaseTimeline:
    """Collector for spans, host sections, per-step phase totals, and
    counter samples — the substrate the cost ledger joins and the
    Perfetto exporter renders.

    ``spans``: every closed span/section, host-clock ``ts_ms``/
    ``dur_ms`` relative to the timeline's birth.  ``steps``: one record
    per :meth:`begin_step`/:meth:`end_step` window with the merged
    per-phase totals.  ``counters``: (name, ts_ms, value) samples
    (Perfetto counter tracks).  ``overlapped_ms``: optionally, the same
    computation's *jitted* (overlap-scheduled) per-step time, set by
    the ledger driver for the measured-overlap cross-check."""

    def __init__(self, label: str = ""):
        self.label = label
        self.spans: list[dict] = []
        self.steps: list[dict] = []
        self.counters: list[dict] = []
        self.sections: list[dict] = []
        self.overlapped_ms: float | None = None
        self.meta: dict = {}
        self._birth = time.perf_counter()
        self._cur: dict | None = None

    # ---- clock --------------------------------------------------------

    def _now_ms(self) -> float:
        return (time.perf_counter() - self._birth) * 1e3  # staticcheck: ok host profiler clock, armed only around eager runs

    # ---- steps --------------------------------------------------------

    def begin_step(self, step: int) -> None:
        """Open a profiled step: phase spans are only recorded while a
        step is open (keeps jit TRACE-time spans out of the data)."""
        self._cur = {"step": int(step), "t0_ms": self._now_ms(),
                     "phases": {}, "wall_ms": None}

    def end_step(self) -> dict:
        rec = self._cur
        if rec is None:
            raise RuntimeError("end_step without begin_step")
        rec["wall_ms"] = self._now_ms() - rec["t0_ms"]
        rec["phases"] = {k: round(v, 6) for k, v in rec["phases"].items()}
        self.steps.append(rec)
        self._cur = None
        return rec

    # ---- span listener (telemetry.trace_span calls these) -------------

    def span_enter(self, name: str):
        if self._cur is None:
            return None
        from flashmoe_tpu.utils.compat import under_abstract_trace

        if under_abstract_trace():
            # a jaxpr-building trace (jit/make_jaxpr) is running: these
            # span instants would be TRACE time, not run time — drop
            # them, so a step opened around a jitted call stays clean.
            # (An eager shard_map body is also "under a trace" but its
            # values are concrete — those spans are kept.)
            return None
        return self._now_ms()

    def span_exit(self, name: str, tok) -> None:
        if tok is None or self._cur is None:
            return
        now = self._now_ms()
        dur = now - tok
        self.spans.append({
            "name": name, "phase": merged_phase(name),
            "ts_ms": round(tok, 6), "dur_ms": round(dur, 6),
            "step": self._cur["step"], "kind": "phase",
        })
        ph = merged_phase(name)
        self._cur["phases"][ph] = self._cur["phases"].get(ph, 0.0) + dur

    # ---- host sections (trainer-level, jit-agnostic) -------------------

    @contextlib.contextmanager
    def section(self, name: str, step: int | None = None):
        t0 = self._now_ms()
        try:
            yield
        finally:
            self.sections.append({
                "name": name, "ts_ms": round(t0, 6),
                "dur_ms": round(self._now_ms() - t0, 6),
                "step": step, "kind": "section",
            })

    # ---- counters -----------------------------------------------------

    def counter(self, name: str, value: float,
                step: int | None = None) -> None:
        self.counters.append({"name": name, "ts_ms": round(
            self._now_ms(), 6), "value": float(value), "step": step})

    # ---- summaries ----------------------------------------------------

    def phase_means(self) -> dict[str, float]:
        """Mean per-step duration of every merged phase (ms)."""
        sums: dict[str, float] = {}
        counts: dict[str, int] = {}
        for rec in self.steps:
            for ph, ms in rec["phases"].items():
                sums[ph] = sums.get(ph, 0.0) + ms
                counts[ph] = counts.get(ph, 0) + 1
        return {ph: sums[ph] / counts[ph] for ph in sorted(sums)}

    def step_wall_means(self) -> float | None:
        if not self.steps:
            return None
        return sum(s["wall_ms"] for s in self.steps) / len(self.steps)

    def step_records(self) -> list[dict]:
        """Flight-recorder-shaped records: one per profiled step, with
        the per-phase breakdown flattened to ``phase_ms.<name>``."""
        out = []
        for rec in self.steps:
            flat = {"step": rec["step"],
                    "step_ms": round(rec["wall_ms"], 6)}
            for ph, ms in rec["phases"].items():
                flat[f"phase_ms.{ph}"] = ms
            out.append(flat)
        return out


# ----------------------------------------------------------------------
# Arming
# ----------------------------------------------------------------------

def install(tl: PhaseTimeline) -> PhaseTimeline:
    """Arm ``tl``: trace_span sites report to it and :func:`fence`
    starts blocking.  One timeline at a time (profiling is a process
    activity); re-installing replaces."""
    from flashmoe_tpu.utils.telemetry import set_span_listener

    _ACTIVE[0] = tl
    set_span_listener(tl)
    return tl


def uninstall() -> None:
    from flashmoe_tpu.utils.telemetry import set_span_listener

    _ACTIVE[0] = None
    set_span_listener(None)


@contextlib.contextmanager
def profiling(tl: PhaseTimeline | None = None):
    """Arm a timeline for the duration of the block (and yield it)."""
    tl = tl if tl is not None else PhaseTimeline()
    install(tl)
    try:
        yield tl
    finally:
        uninstall()


# ----------------------------------------------------------------------
# Phase fencing
# ----------------------------------------------------------------------

def fence(x):
    """Block until ``x``'s concrete leaves have executed — the phase
    boundary of the profiled (eager) execution.  No timeline armed:
    one ``None`` check and out.  Abstract tracers (a jitted trace of
    the same code): nothing to block on, nothing recorded, the graph
    is untouched — which is what keeps ``profile_phases`` graph-
    neutral.  Returns ``x`` unchanged either way."""
    if _ACTIVE[0] is None:
        return x
    import jax

    from flashmoe_tpu.utils.compat import concrete_leaf

    for leaf in jax.tree_util.tree_leaves(x):
        # eager shard_map values are tracer onions (RewriteTracer over
        # ShardMapTracer) whose .val chain bottoms out at the concrete
        # per-device stack; plain arrays block directly
        v = concrete_leaf(leaf)
        if v is not None:
            v.block_until_ready()
    return x


def section(name: str, step: int | None = None):
    """A host section on the armed timeline, or a no-op context when
    nothing is armed (the trainer calls this every step)."""
    tl = _ACTIVE[0]
    if tl is None:
        return contextlib.nullcontext()
    return tl.section(name, step=step)
