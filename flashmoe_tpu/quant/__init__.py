"""Quantized expert storage & compute (ISSUE 15).

The paper's thesis is that distributed MoE is bytes-bound; PR 5/12
compressed the *wire* (fp8 all-to-all payloads, per-hop DCN dtypes) but
every expert weight still streams from HBM — and lives in memory — at
full compute precision.  This package is the storage-axis counterpart
of :mod:`flashmoe_tpu.ops.wire`: post-training quantization of the MoE
FFN expert weights to int8 or fp8 (e4m3) with per-output-channel (and
optional per-K-group) f32 scales, dequantized *in compute* so every
matmul still accumulates in f32.

Three layers:

* :mod:`flashmoe_tpu.quant.core` — the codec: symmetric absmax
  per-channel quantize/dequantize, byte accounting
  (:func:`weight_itemsize` is what the analysis/planner models price).
* :mod:`flashmoe_tpu.quant.state` — storage: :class:`QuantizedExpertState`
  (``quantize_state`` / ``dequantize_state`` round trip over flat MoE
  param dicts AND nested transformer trees), the CRC'd ``quant``
  manifest block (:mod:`flashmoe_tpu.runtime.checkpoint`), and
  :func:`ffn_compute_params` — the ONE layer-boundary hook every MoE
  layer calls (``None`` = off = the untouched dict, bit-identical by
  construction; proven by the staticcheck invariant engine).
* :mod:`flashmoe_tpu.quant.calibrate` — absmax / percentile-clipping
  calibration over a seeded activation sample, with a measured
  output-error report per percentile candidate.

Execution semantics (docs/PERF.md "Quantized expert storage"):

* ``MoEConfig.expert_quant`` set + params pre-quantized
  (:func:`quantize_state`): the layers stream int8/fp8 payloads from
  HBM and dequantize in compute — the storage and HBM savings the
  planner prices.
* ``expert_quant`` set + ordinary full-precision params: the layers
  fake-quant in-graph (quantize -> dequantize round trip) — identical
  numerics to offline absmax quantization, no storage savings; this is
  what the invariant engine traces and what a numerics A/B costs.
* ``expert_quant=None`` (default): no quant code runs at all.
"""

from flashmoe_tpu.quant.calibrate import (  # noqa: F401
    CalibrationResult, activation_sample, calibrate,
)
from flashmoe_tpu.quant.core import (  # noqa: F401
    QUANT_NAMES, canonical_name, dequantize_channelwise,
    quantize_channelwise, resolve, roundtrip, roundtrip_error,
    scale_overhead_bytes, weight_itemsize,
)
from flashmoe_tpu.quant.state import (  # noqa: F401
    QUANT_WEIGHT_KEYS, QuantizedExpertState, SCALE_SUFFIX,
    dequantize_state, ensure_unquantized, ffn_compute_params,
    is_quantized, quant_bytes_saved, quant_metadata,
    quantize_ffn_params, quantize_state, verify_quant_metadata,
    weight_quant_error,
)
