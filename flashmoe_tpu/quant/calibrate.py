"""Post-training calibration: absmax vs percentile clipping, judged on
a seeded activation sample.

Plain absmax per-channel quantization spends the whole int8 grid on the
channel's single largest weight; a heavy-tailed channel then wastes
most of its 254 levels on values that never occur.  Percentile clipping
caps each channel's scale at the ``p``-th percentile of its |weights|
(values beyond it saturate), trading rare saturation error for finer
resolution everywhere else — the standard PTQ knob.

Because the right percentile depends on what the layer actually
*computes*, :func:`calibrate` scores each candidate on a seeded
activation sample: run the expert FFN at full precision and at each
candidate's round-tripped weights, and keep the clip with the smallest
relative output error.  Deterministic (seeded sample, pure argmin), so
a committed calibration is reproducible bit-for-bit.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from flashmoe_tpu.quant import core
from flashmoe_tpu.quant.state import QUANT_WEIGHT_KEYS

#: candidate clip percentiles the calibrator scores (100 = plain
#: absmax, always a candidate so calibration can never be worse than
#: uncalibrated on the sample it measures)
DEFAULT_PERCENTILES = (100.0, 99.99, 99.9, 99.5)


@dataclasses.dataclass(frozen=True)
class CalibrationResult:
    """The winning clip for one expert FFN param group.

    ``clip``: per-key absmax caps (arrays broadcastable to the scale
    shapes — feed to :func:`~flashmoe_tpu.quant.state.quantize_state`);
    ``percentile``: the winning candidate; ``output_rel_err``: measured
    relative L2 output error of the winner on the calibration sample;
    ``report``: per-candidate errors, for the bench/docs tables."""

    qname: str
    percentile: float
    clip: dict
    output_rel_err: float
    report: dict


def activation_sample(cfg, n_tokens: int = 512, seed: int = 0):
    """Seeded activation sample shaped like the layer's input rows —
    deterministic across hosts, so a committed calibration is
    reproducible."""
    return jax.random.normal(
        jax.random.PRNGKey(seed), (n_tokens, cfg.hidden_size),
        jnp.float32)


def _channel_percentile(w, pct: float):
    """Per-(group, channel) |w| percentile over the K axis of an
    [..., K, N] weight — the clip candidate at ``pct`` (100 = absmax)."""
    aw = jnp.abs(w.astype(jnp.float32))
    return jnp.percentile(aw, pct, axis=-2, keepdims=True)


def _ffn_out(params, x, cfg):
    """Reference expert FFN on the sample, token rows fanned through
    EVERY expert (calibration wants weight coverage, not routing
    realism).  Pure f32."""
    from flashmoe_tpu.models.reference import activation_fn

    act = activation_fn(cfg.hidden_act)
    up = jnp.einsum("sh,ehi->esi", x, params["w_up"].astype(jnp.float32))
    up = up + params["b_up"][:, None, :].astype(jnp.float32)
    if cfg.gated_ffn and "w_gate" in params:
        g = jnp.einsum("sh,ehi->esi", x,
                       params["w_gate"].astype(jnp.float32))
        hid = act(g) * up
    else:
        hid = act(up)
    return jnp.einsum("esi,eih->esh", hid,
                      params["w_down"].astype(jnp.float32))


def calibrate(params: dict, cfg, qname: str, *,
              sample=None, percentiles=DEFAULT_PERCENTILES,
              group_size: int | None = None) -> CalibrationResult:
    """Pick the clip percentile minimizing measured output error of the
    quantized expert FFN on a seeded activation sample.

    ``params`` is one flat expert FFN param dict (``w_up`` [E, H, I],
    ...).  Returns the winning :class:`CalibrationResult`; feed its
    ``clip`` to :func:`~flashmoe_tpu.quant.state.quantize_state`
    (``calibration=result``)."""
    qname = core.canonical_name(qname)
    if qname == "off":
        raise ValueError("calibrate needs a quant dtype, not 'off'")
    x = sample if sample is not None else activation_sample(cfg)
    ref = _ffn_out(params, x, cfg)
    ref_norm = jnp.sqrt(jnp.sum(ref.astype(jnp.float32) ** 2)) + 1e-9

    best = None
    report: dict[str, float] = {}
    for pct in percentiles:
        clip = {}
        qp = dict(params)
        for k in QUANT_WEIGHT_KEYS:
            if k not in params:
                continue
            c = (None if pct >= 100.0
                 else _channel_percentile(params[k], pct))
            if c is not None:
                clip[k] = c
            qp[k] = core.roundtrip(params[k], qname,
                                   group_size=group_size, clip=c)
        out = _ffn_out(qp, x, cfg)
        err = float(jnp.sqrt(jnp.sum(
            (out.astype(jnp.float32) - ref.astype(jnp.float32)) ** 2))
            / ref_norm)
        report[f"p{pct:g}"] = round(err, 8)
        if best is None or err < best[0]:
            best = (err, pct, clip)
    err, pct, clip = best
    return CalibrationResult(qname=qname, percentile=pct, clip=clip,
                             output_rel_err=err, report=report)
