"""Weight-quantization codec: symmetric absmax per-output-channel (and
optional per-K-group) int8 / fp8-e4m3, f32 scales, dequant-in-compute.

Layout convention (matches ``models/reference.py:init_moe_params``):
every expert FFN weight is ``[..., K, N]`` with the contraction (K)
axis second-to-last and the OUTPUT channels (N) last — ``w_up`` /
``w_gate`` are ``[E, H, I]`` (channels = I), ``w_down`` is ``[E, I, H]``
(channels = H).  Scales therefore reduce over K: shape ``[..., 1, N]``
per-channel, or ``[..., K // g, N]`` with a K-group size ``g``.

Numerical contracts (property-tested in ``tests/test_quant.py``):

* zero channels survive the round trip exactly (scale pinned to 1.0);
* scaling a channel by ``c > 0`` scales the decoded channel by exactly
  ``c`` (the mantissa pattern is scale-invariant);
* int8 payloads are clipped to ``[-127, 127]`` (symmetric — no -128,
  so negation round-trips);
* accumulation dtype is untouched: dequant produces f32 (cast to the
  compute dtype by the caller), so the matmul's
  ``preferred_element_type=f32`` path is byte-identical to the
  full-precision kernel's.

Everything here is cast/round/`jnp.where` arithmetic: jit-, vmap- and
shard_map-safe, no collectives — the same hygiene bar as
:mod:`flashmoe_tpu.ops.wire`.
"""

from __future__ import annotations

import jax.numpy as jnp

# fp8 resolved lazily so the module imports on jax builds without
# float8 support; requesting the e4m3 store there is a config-time
# ValueError, never a mid-trace crash (the ops/wire.py convention).
_FP8_E4M3 = getattr(jnp, "float8_e4m3fn", None)

_ALIASES = {
    "int8": "int8",
    "i8": "int8",
    "e4m3": "e4m3",
    "float8_e4m3fn": "e4m3",
    "fp8": "e4m3",  # the weight-friendly fp8 (3 mantissa bits)
}

QUANT_NAMES = tuple(sorted(_ALIASES))

#: symmetric int8 range: +-127 (no -128, so q -> -q is exact)
_INT8_QMAX = 127.0


def canonical_name(name: str | None) -> str:
    """Canonical store name ('int8' / 'e4m3'), or 'off' for ``None`` —
    the spelling measurement keys, bench records and golden tables
    use."""
    if name is None:
        return "off"
    key = _ALIASES.get(str(name).lower())
    if key is None:
        raise ValueError(
            f"unknown expert_quant dtype {name!r}; supported: "
            f"{QUANT_NAMES}")
    return key


def resolve(name: str | None):
    """Store name -> payload jnp dtype, or ``None`` for off.  Raises
    ``ValueError`` for unknown names and for e4m3 on a jax build
    without float8 dtypes — config validation calls this so
    unsupported stores fail at ``MoEConfig`` construction."""
    if name is None:
        return None
    key = canonical_name(name)
    if key == "off":
        return None
    if key == "int8":
        return jnp.int8
    if _FP8_E4M3 is None:
        raise ValueError(
            f"expert_quant={name!r} needs float8 support this jax "
            f"build lacks; use expert_quant='int8' or None")
    return _FP8_E4M3


def weight_itemsize(name: str | None, compute_dtype) -> float:
    """Bytes ONE expert-weight element occupies on the HBM stream:
    1 for both quantized stores, the compute itemsize when quant is
    off.  The byte model (:mod:`flashmoe_tpu.analysis`) and the fused
    kernel's tile geometry (``parallel/fused.py:schedule_table``) both
    price weights through this one function, so the model can never
    disagree with the codec about what actually streams."""
    if name is None:
        return float(jnp.dtype(compute_dtype).itemsize)
    canonical_name(name)  # validate
    return 1.0


def scale_overhead_bytes(name: str | None, n_channels: int,
                         n_groups: int = 1) -> float:
    """Bytes of the f32 scale sidecar riding next to a quantized
    matrix: one f32 per (K-group, output channel), 0 when quant is
    off."""
    if name is None:
        return 0.0
    return 4.0 * n_channels * max(n_groups, 1)


def _qmax(qdtype) -> jnp.ndarray:
    if jnp.dtype(qdtype) == jnp.int8:  # staticcheck: ok static store dtype — host metadata, never a tracer
        return jnp.float32(_INT8_QMAX)
    return jnp.float32(jnp.finfo(qdtype).max)


def _check_group(k: int, group_size: int | None) -> int:
    g = int(group_size) if group_size else k
    if g < 1 or k % g:
        raise ValueError(
            f"quant group_size={group_size} must divide the "
            f"contraction dim K={k}")
    return g


def quantize_channelwise(w, qname: str, *, group_size: int | None = None,
                         clip=None):
    """Quantize ``w`` (``[..., K, N]``) to the ``qname`` store.

    Returns ``(payload, scales)``: ``payload`` has ``w``'s shape at the
    store dtype; ``scales`` is ``[..., K // g, N]`` f32 (``g = K``
    per-channel when ``group_size`` is None).  ``clip`` (optional,
    broadcastable to the scale shape) caps the absmax per channel —
    the percentile-calibration hook (:mod:`flashmoe_tpu.quant.
    calibrate`); values beyond the clip saturate at the clip point.
    """
    qd = resolve(qname)
    if qd is None:
        raise ValueError("cannot quantize with expert_quant off")
    *lead, k, n = w.shape
    g = _check_group(k, group_size)
    wf = w.astype(jnp.float32).reshape(*lead, k // g, g, n)
    amax = jnp.max(jnp.abs(wf), axis=-2)              # [..., K//g, N]
    if clip is not None:
        amax = jnp.minimum(amax, jnp.asarray(clip, jnp.float32))
    qmax = _qmax(qd)
    # all-zero channels keep scale 1.0 (0 / 1 -> 0 exactly)
    scale = jnp.where(amax > 0, amax / qmax, jnp.float32(1.0))
    scaled = wf / scale[..., None, :]
    if jnp.dtype(qd) == jnp.int8:  # staticcheck: ok static store dtype — host metadata, never a tracer
        payload = jnp.clip(jnp.round(scaled), -_INT8_QMAX,
                           _INT8_QMAX).astype(jnp.int8)
    else:
        payload = jnp.clip(scaled, -qmax, qmax).astype(qd)
    return payload.reshape(w.shape), scale


def dequantize_channelwise(payload, scales, out_dtype=jnp.float32):
    """Invert :func:`quantize_channelwise`: ``(payload [..., K, N],
    scales [..., G, N])`` -> f32 (or ``out_dtype``) weights.  The group
    size is inferred from the shapes, so a stored state carries its
    grouping in the scale array itself — no side-channel metadata
    needed to decode."""
    *lead, k, n = payload.shape
    gcount = scales.shape[-2]
    if gcount < 1 or k % gcount:
        raise ValueError(
            f"scale groups {gcount} do not divide K={k}")
    g = k // gcount
    wf = payload.astype(jnp.float32).reshape(*lead, gcount, g, n)
    wf = wf * scales[..., None, :].astype(jnp.float32)
    return wf.reshape(payload.shape).astype(out_dtype)


def roundtrip(w, qname: str, *, group_size: int | None = None,
              clip=None):
    """quantize + dequantize without storing — what the dequant-in-
    compute matmul would see.  This IS the in-graph fake-quant arm of
    ``ffn_compute_params`` (full-precision params under
    ``expert_quant``), so the A/B numerics of the knob match offline
    quantization exactly."""
    payload, scales = quantize_channelwise(w, qname,
                                           group_size=group_size,
                                           clip=clip)
    return dequantize_channelwise(payload, scales, w.dtype)


def roundtrip_error(w, qname: str, *,
                    group_size: int | None = None) -> jnp.ndarray:
    """Mean relative L1 quantization error of the store on ``w`` (f32
    scalar): ``sum|w - rt(w)| / (sum|w| + eps)`` — the
    ``MoEStats.quant_error`` proxy (the weight-space analogue of
    ``ops/wire.roundtrip_error``)."""
    wf = w.astype(jnp.float32)
    rt = roundtrip(wf, qname, group_size=group_size)
    num = jnp.sum(jnp.abs(wf - rt))
    den = jnp.sum(jnp.abs(wf)) + jnp.float32(1e-9)
    return (num / den).astype(jnp.float32)
