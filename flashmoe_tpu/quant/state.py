"""Quantized expert storage: the state pytree, the checkpoint metadata
block, and the ONE layer-boundary compute hook.

Storage layout: a quantized param dict is the ordinary MoE param dict
with each expert FFN weight key (``w_up`` / ``w_gate`` / ``w_down``)
holding the int8/e4m3 *payload* (same shape) and a sibling
``<key>_qscale`` f32 array holding the per-output-channel (or
per-K-group) scales.  Biases, ``gate_w`` and shared-expert weights stay
at full precision (they are a rounding error of the byte budget and
carry the layer's additive numerics).  Keeping the dict shape means the
whole existing plumbing — shard_map pspecs (scale arrays lead with the
expert axis, so ``P('ep')`` shards them like their payloads), orbax
checkpoints, the controller's ``permute_expert_state`` — moves payload
and scales coherently with zero special cases beyond key lists.
"""

from __future__ import annotations

import dataclasses
import json
import zlib

import jax.numpy as jnp

from flashmoe_tpu.quant import core

#: the expert FFN weight keys the quantizer owns ([E, K, N] layout)
QUANT_WEIGHT_KEYS = ("w_up", "w_gate", "w_down")
#: sibling key carrying a payload's f32 scales
SCALE_SUFFIX = "_qscale"


def _is_expert_dict(d) -> bool:
    """An expert FFN param group: a dict whose ``w_up`` is the stacked
    [E, H, I] expert tensor (``shared_w_up`` is 2-D and stays out)."""
    return (isinstance(d, dict) and "w_up" in d
            and getattr(d["w_up"], "ndim", 0) == 3)


def _walk_expert_dicts(tree, fn):
    """Rebuild ``tree`` with ``fn(expert_dict) -> new_dict`` applied to
    every expert FFN param group (nested transformer trees included)."""
    if _is_expert_dict(tree):
        return fn(tree)
    if isinstance(tree, dict):
        return {k: _walk_expert_dicts(v, fn) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        seq = [_walk_expert_dicts(v, fn) for v in tree]
        return type(tree)(seq)
    return tree


def _iter_expert_dicts(tree):
    if _is_expert_dict(tree):
        yield tree
        return
    if isinstance(tree, dict):
        for v in tree.values():
            yield from _iter_expert_dicts(v)
    elif isinstance(tree, (list, tuple)):
        for v in tree:
            yield from _iter_expert_dicts(v)


def is_quantized(params) -> bool:
    """Whether any expert FFN group in ``params`` carries quantized
    payload + scale pairs."""
    for d in _iter_expert_dicts(params):
        if any(k + SCALE_SUFFIX in d for k in QUANT_WEIGHT_KEYS):
            return True
    return False


@dataclasses.dataclass
class QuantizedExpertState:
    """A quantized parameter tree plus its storage metadata.

    ``params`` is layer-ready (pass it anywhere a param dict goes —
    the MoE layers, the serving engine, ``checkpoint.save`` via a
    TrainState); ``meta`` is the JSON-able ``quant`` block the
    checkpoint manifest carries (:func:`quant_metadata` regenerates it
    from the params alone, so the block can always be re-derived and
    verified)."""

    params: dict
    meta: dict

    def dequantize(self, out_dtype=None) -> dict:
        return dequantize_state(self.params, out_dtype)


def quantize_ffn_params(params: dict, qname: str, *,
                        group_size: int | None = None,
                        clip: dict | None = None) -> dict:
    """Quantize ONE flat expert FFN param dict: each
    :data:`QUANT_WEIGHT_KEYS` present is replaced by its payload with a
    ``<key>_qscale`` sibling.  ``clip``: optional per-key absmax caps
    (:class:`~flashmoe_tpu.quant.calibrate.CalibrationResult.clip`)."""
    out = dict(params)
    for k in QUANT_WEIGHT_KEYS:
        if k not in params:
            continue
        payload, scales = core.quantize_channelwise(
            params[k], qname, group_size=group_size,
            clip=None if clip is None else clip.get(k))
        out[k] = payload
        out[k + SCALE_SUFFIX] = scales
    return out


def _dequant_ffn_params(params: dict, out_dtype=None) -> dict:
    """Invert :func:`quantize_ffn_params` on one flat dict (pass-through
    for unquantized dicts)."""
    out = dict(params)
    for k in QUANT_WEIGHT_KEYS:
        sk = k + SCALE_SUFFIX
        if sk not in out:
            continue
        out[k] = core.dequantize_channelwise(
            out[k], out.pop(sk),
            out_dtype if out_dtype is not None else jnp.float32)
    return out


def quantize_state(params, qname: str, *, group_size: int | None = None,
                   calibration=None) -> QuantizedExpertState:
    """Post-training quantization of every expert FFN group in a param
    tree (a flat MoE dict or a nested transformer tree).  Returns a
    :class:`QuantizedExpertState` whose ``meta`` records the store
    dtype, grouping, per-key worst-case round-trip error, and the
    metadata CRC the checkpoint manifest verifies."""
    clip = getattr(calibration, "clip", calibration)
    qparams = _walk_expert_dicts(
        params, lambda d: quantize_ffn_params(
            d, qname, group_size=group_size, clip=clip))
    return QuantizedExpertState(params=qparams,
                                meta=quant_metadata(qparams))


def dequantize_state(params, out_dtype=None) -> dict:
    """Round-trip API: a quantized param tree (or
    :class:`QuantizedExpertState`) back to full-precision weights
    (f32 unless ``out_dtype``), scale keys dropped.  Unquantized trees
    pass through untouched."""
    if isinstance(params, QuantizedExpertState):
        params = params.params
    return _walk_expert_dicts(
        params, lambda d: _dequant_ffn_params(d, out_dtype))


def quant_metadata(params) -> dict | None:
    """The JSON-able ``quant`` manifest block derived from a param tree:
    store dtype, group size, quantized key census, and a CRC32 over the
    canonical block content so a manifest reader can detect a tampered/
    torn block (:func:`verify_quant_metadata`).  ``None`` for
    unquantized trees — legacy manifests stay byte-identical."""
    if isinstance(params, QuantizedExpertState):
        params = params.params
    dtypes = set()
    groups = set()
    keys: dict[str, int] = {}
    for d in _iter_expert_dicts(params):
        for k in QUANT_WEIGHT_KEYS:
            sk = k + SCALE_SUFFIX
            if sk not in d:
                continue
            keys[k] = keys.get(k, 0) + 1
            dtypes.add(jnp.dtype(d[k].dtype).name)
            kdim = d[k].shape[-2]
            # 0 = per-output-channel (one scale group spanning K);
            # otherwise the K-group size the scales were stored at
            ngroups = d[sk].shape[-2]
            groups.add(0 if ngroups == 1 else kdim // ngroups)
    if not keys:
        return None
    name = {"int8": "int8", "float8_e4m3fn": "e4m3"}.get(
        next(iter(dtypes)) if len(dtypes) == 1 else "", "mixed")
    block = {
        "version": 1,
        "dtype": name,
        "payload_dtypes": sorted(dtypes),
        "group_sizes": sorted(int(g) for g in groups),
        "keys": {k: keys[k] for k in sorted(keys)},
        "scale_suffix": SCALE_SUFFIX,
    }
    block["crc32"] = _meta_crc(block)
    return block


def _meta_crc(block: dict) -> int:
    body = {k: v for k, v in block.items() if k != "crc32"}
    return zlib.crc32(
        json.dumps(body, sort_keys=True).encode("utf-8"))


def verify_quant_metadata(block: dict | None) -> bool:
    """CRC-check a manifest ``quant`` block (True for None — no block
    is a legacy manifest, not a corrupt one)."""
    if block is None:
        return True
    if not isinstance(block, dict) or "crc32" not in block:
        return False
    return _meta_crc(block) == block["crc32"]


def quant_bytes_saved(params, full_dtype=jnp.float32) -> int:
    """HBM/storage bytes a quantized tree frees vs holding the same
    weights at ``full_dtype`` (scale sidecars charged against the
    saving).  0 for unquantized trees.  The serving engine reports
    this as additional KV-cache page headroom (``observe --serving``)."""
    full = jnp.dtype(full_dtype).itemsize
    saved = 0
    for d in _iter_expert_dicts(params):
        for k in QUANT_WEIGHT_KEYS:
            sk = k + SCALE_SUFFIX
            if sk not in d:
                continue
            payload, scales = d[k], d[sk]
            saved += payload.size * (full - jnp.dtype(payload.dtype)
                                     .itemsize)
            saved -= scales.size * 4
    return int(max(saved, 0))


def ffn_compute_params(params: dict, cfg) -> dict:
    """THE layer-boundary hook: resolve a flat MoE param dict to the
    weights the expert FFN should compute with, per
    ``cfg.expert_quant``.

    * ``None`` (default): the dict is returned UNTOUCHED — no quant
      code runs, the traced graph is byte-identical to a pre-quant
      build (invariant-engine-proven).
    * set + pre-quantized dict: payloads dequantize to f32
      (dequant-in-compute; the matmul casts to the compute dtype and
      accumulates f32 exactly like the full-precision kernel).
    * set + full-precision dict: in-graph fake-quant round trip —
      identical numerics to offline absmax quantization, so a numerics
      A/B needs no stored artifacts.
    """
    qname = getattr(cfg, "expert_quant", None)
    quantized = any(k + SCALE_SUFFIX in params
                    for k in QUANT_WEIGHT_KEYS)
    if qname is None:
        ensure_unquantized(params)
        return params
    if quantized:
        return _dequant_ffn_params(params)
    out = dict(params)
    for k in QUANT_WEIGHT_KEYS:
        if k in out:
            out[k] = core.roundtrip(out[k], qname)
    return out


def weight_quant_error(params: dict, cfg) -> jnp.ndarray | None:
    """In-graph round-trip error proxy of the store on this layer's
    weights (``MoEStats.quant_error``): the max over weight keys of
    :func:`~flashmoe_tpu.quant.core.roundtrip_error` — the real
    quantization loss on fake-quant runs.  Pre-quantized states
    short-circuit to ``None`` (the stat stays 0): re-measuring the
    already-lossy compute weights would spend three full weight passes
    per layer per step to report ~0 (code-review finding) — their
    baked loss lives in the state's ``meta`` / checkpoint quant block.
    ``None`` when quant is off."""
    qname = getattr(cfg, "expert_quant", None)
    if qname is None:
        return None
    if any(k + SCALE_SUFFIX in params for k in QUANT_WEIGHT_KEYS):
        return None
    err = None
    for k in QUANT_WEIGHT_KEYS:
        if k not in params:
            continue
        e = core.roundtrip_error(params[k], qname)
        err = e if err is None else jnp.maximum(err, e)
    return err


def ensure_unquantized(params: dict) -> None:
    """THE quant-off guard, shared by every layer path: refuse a
    quantized state whose scales a quant-off config would silently
    ignore — matmuling raw ±127 payloads is finite garbage, not an
    error (code-review finding)."""
    if any(k + SCALE_SUFFIX in params for k in QUANT_WEIGHT_KEYS):
        raise ValueError(
            "params carry quantized expert weights (+_qscale scales) "
            "but cfg.expert_quant is None; set expert_quant to the "
            "state's store dtype or dequantize_state() the params "
            "first")
