"""Host runtime: distributed bootstrap, launcher, worker entrypoints."""
