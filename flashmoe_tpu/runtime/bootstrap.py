"""Host bootstrap: distributed init, topology, placement, mesh.

The TPU-native equivalent of the reference's ``flashmoe::initialize()`` /
``distributedInit`` (``csrc/include/flashmoe/bootstrap.cuh:278-547``): where
the reference runs ``nvshmem_init``, probes throughput (``mT``), measures
topology, runs the Decider, and sizes a symmetric heap, we run
``jax.distributed.initialize`` (multi-host), derive the ICI adjacency
analytically, run the Python Decider, and build the device mesh — the
"symmetric heap" is XLA's job (buffers come from the collective layouts).

The result is a :class:`Runtime` handle, the analogue of the reference's
``Bookkeeping`` singleton (``types.cuh:696-1007``) minus everything XLA
already owns.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import jax

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.parallel.decider import Placement, decide, uniform_placement
from flashmoe_tpu.parallel.mesh import make_mesh
from flashmoe_tpu.parallel.topology import (
    device_slice_ids, ici_adjacency, measured_worker_attrs,
    merge_dcn_costs, probe_dcn_costs, slice_structure,
)

_runtime: Optional["Runtime"] = None


@dataclasses.dataclass
class GroupPlan:
    """Decider-driven DP x EP group formation (ISSUE 13 / ROADMAP 5):
    how a (measured or ``FLASHMOE_MOCK_SLICES``-mocked) slice topology
    maps onto the job's parallelism axes.

    ``mapping``:

    * ``'single'`` — one slice (or one decider group on it): the ep
      axis owns every device, no DCN structure to exploit;
    * ``'ep_across_dcn'`` — the ep axis (each EP group) spans the
      slices: the expert a2a runs the two-stage hierarchical exchange
      (``dcn_inner`` set; ``MoEConfig.wire_dtype_dcn`` applies) while
      any DP replication rides inside slices;
    * ``'dp_across_dcn'`` — the Decider kept one EP group per slice
      (DCN too expensive for per-step a2a relative to the gradient
      ring): the a2a never leaves ICI, DP crosses DCN;
    * ``'irregular'`` — the Decider's groups do not form equal
      contiguous blocks the (dp, ep) mesh grid can express: group
      structure is recorded but the single-group fold stands.
    """

    dp: int
    ep: int
    mapping: str
    slices: tuple[int, int] | None   # (n_slices, ranks_per_slice)
    dcn_inner: int | None            # two-stage a2a blocking of the ep axis
    groups: list
    placement: Placement


def form_groups(cfg: MoEConfig, devices, adj=None, workers=None, *,
                expert_costs=None) -> GroupPlan:
    """Run the Decider over the (DCN-aware) adjacency and classify its
    groups into a DP x EP mapping the mesh can express.

    The adjacency prices cross-slice pairs at DCN cost
    (``topology.ici_adjacency`` via ``device_slice_ids`` — mocked
    slices included), so the Decider's merge objective makes the
    EP-across-DCN vs DP-across-DCN trade the reference makes with its
    inter-group allreduce term (``decider.cuh:60-158``); the
    planner-side counterpart is
    :func:`flashmoe_tpu.planner.select.scaleout_plan`.  ``expert_costs``
    (observed load histogram) additionally routes the within-group
    assignment through the slice-aware cost-sorted multiset
    (:func:`flashmoe_tpu.parallel.decider.assign_experts_sliced`) so
    hot top-k companion pairs co-locate inside a slice."""
    devices = list(devices)
    n = len(devices)
    ss = slice_structure(devices)
    sids = device_slice_ids(devices)
    if adj is None:
        adj = ici_adjacency(devices)
    if workers is None:
        workers = measured_worker_attrs(devices, cfg, probe=False)
    placement = decide(adj, workers, cfg, slice_of=sids,
                       expert_costs=expert_costs)
    groups = placement.groups

    def blocked(size: int) -> bool:
        """Groups are exactly the contiguous rank blocks of ``size``
        (the only structure the (dp, ep) mesh grid can express)."""
        want = [list(range(i, i + size)) for i in range(0, n, size)]
        return sorted(map(tuple, groups)) == sorted(map(tuple, want))

    gsz = len(groups[0]) if groups else n
    regular = (len(groups) >= 1 and all(len(g) == gsz for g in groups)
               and gsz * len(groups) == n and blocked(gsz)
               and cfg.num_experts % gsz == 0)
    if not regular:
        ep = n
        while cfg.num_experts % ep:
            ep -= 1
        inner = ss[1] if ss else None
        hier = (inner is not None and 1 < inner < ep
                and ep % inner == 0)
        return GroupPlan(dp=1, ep=ep, mapping="irregular", slices=ss,
                         dcn_inner=inner if hier else None,
                         groups=groups, placement=placement)
    dp, ep = len(groups), gsz
    inner = ss[1] if ss else None
    if ss is None or dp == 1 and (inner is None or ep <= inner):
        mapping, dcn_inner = "single", None
    elif inner is not None and ep > inner and ep % inner == 0:
        # each EP group spans slices: two-stage a2a inside the group
        mapping, dcn_inner = "ep_across_dcn", inner
    elif inner is not None and ep <= inner and inner % ep == 0:
        mapping = "dp_across_dcn" if dp > 1 else "single"
        dcn_inner = None
    else:
        mapping, dcn_inner = "irregular", None
    return GroupPlan(dp=dp, ep=ep, mapping=mapping, slices=ss,
                     dcn_inner=dcn_inner, groups=groups,
                     placement=placement)


@dataclasses.dataclass
class Runtime:
    cfg: MoEConfig
    mesh: object
    placement: Placement
    num_processes: int
    process_id: int
    # non-None only on heterogeneous fabrics: the per-rank source
    # processing order for the fused RDMA kernel, from
    # topology.arrival_order (ring order needs no table)
    src_order: object = None
    # ranks per slice when the ep axis spans multiple slices
    # (topology.slice_structure): selects the two-stage ICI+DCN
    # all-to-all in the collective EP path (the reference's per-peer
    # P2P-vs-remote transport duality, bootstrap.cuh:442-446)
    dcn_inner: int | None = None
    # Decider-driven DP x EP group formation (form_groups): None on
    # single-device / decider-off bootstraps
    group_plan: "GroupPlan | None" = None

    @property
    def num_local_experts(self) -> int:
        """nLx for this process's first device (reference
        ``get_num_local_experts``, ``python_bindings.cu:187``).

        Placement keys are positions in the ``jax.devices()`` order, so the
        first local device is located by identity — no assumption of
        uniform per-process device counts or id ordering."""
        local = jax.local_devices()
        if local:
            pos = {id(d): i for i, d in enumerate(jax.devices())}
            first = pos.get(id(local[0]))
            if first is None:
                first = next(
                    (i for i, d in enumerate(jax.devices())
                     if d.id == local[0].id), 0,
                )
            got = self.placement.local_experts.get(first)
            if got:
                return len(got)
        return self.cfg.num_experts // max(1, self.cfg.ep)


def initialize(cfg: MoEConfig | dict | str | None = None, *,
               coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               use_decider: bool = True,
               measure: bool | None = None) -> Runtime:
    """Bring up the distributed runtime (idempotent).

    Single-process callers get the local devices; multi-process jobs (env
    ``FLASHMOE_COORDINATOR`` / ``JAX_COORDINATOR_ADDRESS`` or explicit
    args) run ``jax.distributed.initialize`` first, like the reference's
    rank discovery from OMPI/PMI/SLURM env vars (``worker.py:24-29``).

    ``measure`` runs the bootstrap probes the reference always runs
    (``mT`` throughput, ``discoverTopology`` — ``bootstrap.cuh:278-529``):
    per-worker expert throughput feeding rate-proportional assignment, and
    timed pairwise DCN transfers replacing the analytic cross-process
    costs.  Default (None): probe on real hardware and in multi-process
    jobs; skip on the single-process virtual backend (analytic costs).
    """
    global _runtime
    if _runtime is not None:
        return _runtime

    if isinstance(cfg, (dict, str)):
        cfg = MoEConfig.from_json(cfg)
    cfg = cfg or MoEConfig()

    coord = coordinator_address or os.environ.get(
        "FLASHMOE_COORDINATOR", os.environ.get("JAX_COORDINATOR_ADDRESS")
    )
    nproc = num_processes or int(os.environ.get("FLASHMOE_NPROCS", "0"))
    pid = process_id if process_id is not None else int(
        os.environ.get(
            "FLASHMOE_RANK",
            os.environ.get("OMPI_COMM_WORLD_RANK",
                           os.environ.get("PMI_RANK",
                                          os.environ.get("SLURM_PROCID", "0"))),
        )
    )
    if coord and nproc > 1:
        jax.distributed.initialize(
            coordinator_address=coord, num_processes=nproc, process_id=pid
        )

    devices = jax.devices()
    n = len(devices)
    ep_pinned = cfg.ep > 1
    # fold requested ep down to the available device count
    ep = min(cfg.ep if cfg.ep > 1 else n, n)
    while cfg.num_experts % ep:
        ep -= 1
    cfg = cfg.replace(ep=max(1, ep))

    if measure is None:
        measure = jax.process_count() > 1 or devices[0].platform != "cpu"
    src_order = None
    plan = None
    if use_decider and n > 1:
        adj = ici_adjacency(devices)
        if measure and jax.process_count() > 1:
            adj = merge_dcn_costs(adj, probe_dcn_costs(), devices)
        attrs = measured_worker_attrs(devices, cfg, probe=measure)
        plan = form_groups(cfg, devices, adj=adj, workers=attrs)
        placement = plan.placement
        if (not ep_pinned and plan.mapping in ("ep_across_dcn",
                                               "dp_across_dcn")
                and plan.ep >= 1 and cfg.num_experts % plan.ep == 0):
            # adopt the Decider's DP x EP factorization: each decider
            # group becomes one EP shard group, replicas ride the dp
            # axis (a user-pinned ep always stands)
            cfg = cfg.replace(ep=plan.ep)
        from flashmoe_tpu.utils.telemetry import metrics

        metrics.decision(
            "bootstrap.groups", mapping=plan.mapping,
            dp=plan.dp, ep=plan.ep, adopted_ep=cfg.ep,
            slices=list(plan.slices) if plan.slices else None,
            dcn_inner=plan.dcn_inner,
            groups=[list(g) for g in plan.groups],
            ep_pinned=ep_pinned)
        src_order = _heterogeneous_src_order(adj, cfg, n)
    else:
        placement = uniform_placement(n, cfg)

    mesh = make_mesh(cfg)
    if plan is not None and cfg.ep == plan.ep:
        dcn_inner = plan.dcn_inner
    else:
        # blocking of the ep PREFIX, derived from the WORLD's slice
        # membership (mock validated against the world size once —
        # re-running the mock on the subset would mis-partition it and
        # reject world-valid mocks that don't divide the folded ep)
        from flashmoe_tpu.parallel.topology import contiguous_blocking

        ss = (contiguous_blocking(device_slice_ids(devices)[:cfg.ep])
              if cfg.ep > 1 else None)
        # inner == 1 (one rank per slice) degenerates to the flat
        # exchange — publish None, matching the layer's gate
        dcn_inner = ss[1] if ss and 1 < ss[1] < cfg.ep else None
    _runtime = Runtime(
        cfg=cfg, mesh=mesh, placement=placement,
        num_processes=jax.process_count(), process_id=jax.process_index(),
        src_order=src_order,
        dcn_inner=dcn_inner,
        group_plan=plan,
    )
    return _runtime


def current_src_order(mesh, d_world: int):
    """The bootstrapped arrival-order table, iff it applies to ``mesh``:
    a live runtime must hold a table of matching ep width AND the mesh's
    devices must be ``jax.devices()`` in order (the table's rank indices
    are positions in that order; a permuted user mesh would misapply the
    schedule, processing slow sources early).  Returns None otherwise —
    the kernel's ring default stands."""
    rt = _runtime
    if rt is None or rt.src_order is None:
        return None
    if getattr(rt.src_order, "shape", None) != (d_world, d_world):
        return None
    try:
        flat = list(mesh.devices.flat)
    except AttributeError:
        return None
    devs = jax.devices()
    if len(flat) != d_world or any(
            a is not b for a, b in zip(flat, devs[:d_world])):
        return None
    return rt.src_order


def current_dcn_inner(mesh, d_world: int) -> int | None:
    """The bootstrapped ranks-per-slice for ``mesh``'s ep axis, iff the
    mesh's devices are ``jax.devices()`` in order (same gating as
    :func:`current_src_order`: the blocking indexes positions in that
    order).  None -> single slice or unknown; the flat all-to-all
    stands."""
    rt = _runtime
    if rt is None or rt.dcn_inner is None:
        return None
    if not (1 < rt.dcn_inner < d_world) or d_world % rt.dcn_inner:
        return None
    try:
        flat = list(mesh.devices.flat)
    except AttributeError:
        return None
    devs = jax.devices()
    if len(flat) != d_world or any(
            a is not b for a, b in zip(flat, devs[:d_world])):
        return None
    return rt.dcn_inner


def _heterogeneous_src_order(adj, cfg: MoEConfig, n: int):
    """Arrival-order schedule for the fused kernel, or None when it
    reduces to the kernel's default ring (homogeneous fabric, or the ep
    axis doesn't span the whole adjacency).  Payload = one source rank's
    slab toward one destination (nLx x cap x H)."""
    import numpy as np

    from flashmoe_tpu.parallel.ep import local_capacity
    from flashmoe_tpu.parallel.topology import arrival_order

    if cfg.ep <= 1 or cfg.ep != n:
        return None
    s_loc = max(cfg.tokens // cfg.ep, 1)
    nlx = cfg.num_experts // cfg.ep
    slab_mb = (nlx * local_capacity(cfg, s_loc) * cfg.hidden_size
               * np.dtype(cfg.dtype).itemsize) / 1e6
    order = arrival_order(adj, slab_mb)
    from flashmoe_tpu.parallel.topology import default_ring

    return None if np.array_equal(order, default_ring(n)) else order


def finalize():
    """Tear down (reference ``finalize()``, ``bootstrap.cuh:561-588``)."""
    global _runtime
    _runtime = None
    if jax.process_count() > 1:
        jax.distributed.shutdown()


def get_runtime() -> Runtime:
    if _runtime is None:
        raise RuntimeError("flashmoe_tpu.runtime not initialized")
    return _runtime
