"""Host bootstrap: distributed init, topology, placement, mesh.

The TPU-native equivalent of the reference's ``flashmoe::initialize()`` /
``distributedInit`` (``csrc/include/flashmoe/bootstrap.cuh:278-547``): where
the reference runs ``nvshmem_init``, probes throughput (``mT``), measures
topology, runs the Decider, and sizes a symmetric heap, we run
``jax.distributed.initialize`` (multi-host), derive the ICI adjacency
analytically, run the Python Decider, and build the device mesh — the
"symmetric heap" is XLA's job (buffers come from the collective layouts).

The result is a :class:`Runtime` handle, the analogue of the reference's
``Bookkeeping`` singleton (``types.cuh:696-1007``) minus everything XLA
already owns.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import jax

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.parallel.decider import Placement, decide, uniform_placement
from flashmoe_tpu.parallel.mesh import make_mesh
from flashmoe_tpu.parallel.topology import (
    ici_adjacency, measured_worker_attrs, merge_dcn_costs, probe_dcn_costs,
)

_runtime: Optional["Runtime"] = None


@dataclasses.dataclass
class Runtime:
    cfg: MoEConfig
    mesh: object
    placement: Placement
    num_processes: int
    process_id: int
    # non-None only on heterogeneous fabrics: the per-rank source
    # processing order for the fused RDMA kernel, from
    # topology.arrival_order (ring order needs no table)
    src_order: object = None
    # ranks per slice when the ep axis spans multiple slices
    # (topology.slice_structure): selects the two-stage ICI+DCN
    # all-to-all in the collective EP path (the reference's per-peer
    # P2P-vs-remote transport duality, bootstrap.cuh:442-446)
    dcn_inner: int | None = None

    @property
    def num_local_experts(self) -> int:
        """nLx for this process's first device (reference
        ``get_num_local_experts``, ``python_bindings.cu:187``).

        Placement keys are positions in the ``jax.devices()`` order, so the
        first local device is located by identity — no assumption of
        uniform per-process device counts or id ordering."""
        local = jax.local_devices()
        if local:
            pos = {id(d): i for i, d in enumerate(jax.devices())}
            first = pos.get(id(local[0]))
            if first is None:
                first = next(
                    (i for i, d in enumerate(jax.devices())
                     if d.id == local[0].id), 0,
                )
            got = self.placement.local_experts.get(first)
            if got:
                return len(got)
        return self.cfg.num_experts // max(1, self.cfg.ep)


def initialize(cfg: MoEConfig | dict | str | None = None, *,
               coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               use_decider: bool = True,
               measure: bool | None = None) -> Runtime:
    """Bring up the distributed runtime (idempotent).

    Single-process callers get the local devices; multi-process jobs (env
    ``FLASHMOE_COORDINATOR`` / ``JAX_COORDINATOR_ADDRESS`` or explicit
    args) run ``jax.distributed.initialize`` first, like the reference's
    rank discovery from OMPI/PMI/SLURM env vars (``worker.py:24-29``).

    ``measure`` runs the bootstrap probes the reference always runs
    (``mT`` throughput, ``discoverTopology`` — ``bootstrap.cuh:278-529``):
    per-worker expert throughput feeding rate-proportional assignment, and
    timed pairwise DCN transfers replacing the analytic cross-process
    costs.  Default (None): probe on real hardware and in multi-process
    jobs; skip on the single-process virtual backend (analytic costs).
    """
    global _runtime
    if _runtime is not None:
        return _runtime

    if isinstance(cfg, (dict, str)):
        cfg = MoEConfig.from_json(cfg)
    cfg = cfg or MoEConfig()

    coord = coordinator_address or os.environ.get(
        "FLASHMOE_COORDINATOR", os.environ.get("JAX_COORDINATOR_ADDRESS")
    )
    nproc = num_processes or int(os.environ.get("FLASHMOE_NPROCS", "0"))
    pid = process_id if process_id is not None else int(
        os.environ.get(
            "FLASHMOE_RANK",
            os.environ.get("OMPI_COMM_WORLD_RANK",
                           os.environ.get("PMI_RANK",
                                          os.environ.get("SLURM_PROCID", "0"))),
        )
    )
    if coord and nproc > 1:
        jax.distributed.initialize(
            coordinator_address=coord, num_processes=nproc, process_id=pid
        )

    devices = jax.devices()
    n = len(devices)
    # fold requested ep down to the available device count
    ep = min(cfg.ep if cfg.ep > 1 else n, n)
    while cfg.num_experts % ep:
        ep -= 1
    cfg = cfg.replace(ep=max(1, ep))
    mesh = make_mesh(cfg)

    if measure is None:
        measure = jax.process_count() > 1 or devices[0].platform != "cpu"
    src_order = None
    if use_decider and n > 1:
        adj = ici_adjacency(devices)
        if measure and jax.process_count() > 1:
            adj = merge_dcn_costs(adj, probe_dcn_costs(), devices)
        attrs = measured_worker_attrs(devices, cfg, probe=measure)
        placement = decide(adj, attrs, cfg)
        src_order = _heterogeneous_src_order(adj, cfg, n)
    else:
        placement = uniform_placement(n, cfg)

    from flashmoe_tpu.parallel.topology import slice_structure

    ss = slice_structure(devices[:cfg.ep]) if cfg.ep > 1 else None
    _runtime = Runtime(
        cfg=cfg, mesh=mesh, placement=placement,
        num_processes=jax.process_count(), process_id=jax.process_index(),
        src_order=src_order,
        dcn_inner=ss[1] if ss else None,
    )
    return _runtime


def current_src_order(mesh, d_world: int):
    """The bootstrapped arrival-order table, iff it applies to ``mesh``:
    a live runtime must hold a table of matching ep width AND the mesh's
    devices must be ``jax.devices()`` in order (the table's rank indices
    are positions in that order; a permuted user mesh would misapply the
    schedule, processing slow sources early).  Returns None otherwise —
    the kernel's ring default stands."""
    rt = _runtime
    if rt is None or rt.src_order is None:
        return None
    if getattr(rt.src_order, "shape", None) != (d_world, d_world):
        return None
    try:
        flat = list(mesh.devices.flat)
    except AttributeError:
        return None
    devs = jax.devices()
    if len(flat) != d_world or any(
            a is not b for a, b in zip(flat, devs[:d_world])):
        return None
    return rt.src_order


def current_dcn_inner(mesh, d_world: int) -> int | None:
    """The bootstrapped ranks-per-slice for ``mesh``'s ep axis, iff the
    mesh's devices are ``jax.devices()`` in order (same gating as
    :func:`current_src_order`: the blocking indexes positions in that
    order).  None -> single slice or unknown; the flat all-to-all
    stands."""
    rt = _runtime
    if rt is None or rt.dcn_inner is None:
        return None
    if not (1 < rt.dcn_inner < d_world) or d_world % rt.dcn_inner:
        return None
    try:
        flat = list(mesh.devices.flat)
    except AttributeError:
        return None
    devs = jax.devices()
    if len(flat) != d_world or any(
            a is not b for a, b in zip(flat, devs[:d_world])):
        return None
    return rt.dcn_inner


def _heterogeneous_src_order(adj, cfg: MoEConfig, n: int):
    """Arrival-order schedule for the fused kernel, or None when it
    reduces to the kernel's default ring (homogeneous fabric, or the ep
    axis doesn't span the whole adjacency).  Payload = one source rank's
    slab toward one destination (nLx x cap x H)."""
    import numpy as np

    from flashmoe_tpu.parallel.ep import local_capacity
    from flashmoe_tpu.parallel.topology import arrival_order

    if cfg.ep <= 1 or cfg.ep != n:
        return None
    s_loc = max(cfg.tokens // cfg.ep, 1)
    nlx = cfg.num_experts // cfg.ep
    slab_mb = (nlx * local_capacity(cfg, s_loc) * cfg.hidden_size
               * np.dtype(cfg.dtype).itemsize) / 1e6
    order = arrival_order(adj, slab_mb)
    from flashmoe_tpu.parallel.topology import default_ring

    return None if np.array_equal(order, default_ring(n)) else order


def finalize():
    """Tear down (reference ``finalize()``, ``bootstrap.cuh:561-588``)."""
    global _runtime
    _runtime = None
    if jax.process_count() > 1:
        jax.distributed.shutdown()


def get_runtime() -> Runtime:
    if _runtime is None:
        raise RuntimeError("flashmoe_tpu.runtime not initialized")
    return _runtime
