"""Checkpoint / resume with integrity verification.

The reference has none (SURVEY §5: weights are caller-provided tensors, no
optimizer, nothing to save).  A training framework needs it, so this module
provides orbax-backed save/restore of the :class:`TrainState` (params +
optimizer moments + step), preserving shardings on restore — multi-host
safe (orbax coordinates the write across processes).

Tier-2 fault tolerance (docs/RESILIENCE.md) hardens the job-level rung:

  * one :class:`ocp.CheckpointManager` is cached per directory and reused
    across save/latest_step/restore — constructing (and closing) a fresh
    manager per call put manager setup latency in the training hot loop;
  * every save writes a ``manifest-<step>.json`` next to the step dir:
    per-file sizes + CRC32 content checksums;
  * :func:`verify` recomputes the checksums; :func:`restore` verifies
    BEFORE handing bytes to orbax and, on corruption, falls back to the
    newest *intact* older step (recorded as a ``checkpoint.fallback``
    telemetry decision) instead of resuming from garbage;
  * :func:`emergency_save` best-effort persists the last good state when
    a run aborts, never raising into the abort path.
"""

from __future__ import annotations

import glob
import json
import os
import zlib
from typing import Any

import jax
import orbax.checkpoint as ocp

from flashmoe_tpu.runtime.trainer import TrainState
from flashmoe_tpu.utils.telemetry import metrics as _telemetry


class CheckpointCorruptionError(RuntimeError):
    """No intact checkpoint could be restored from the directory."""


# ----------------------------------------------------------------------
# Manager cache
# ----------------------------------------------------------------------

_MANAGERS: dict[str, ocp.CheckpointManager] = {}

# retained checkpoints per directory; a module constant rather than a
# _manager() parameter because the manager is cached per directory — a
# per-call value would silently bind only the FIRST caller's choice
MAX_TO_KEEP = 3


def _manager(directory: str) -> ocp.CheckpointManager:
    """The directory's cached manager (one per abspath, reused across
    every save/query/restore — satellite fix: the old per-call
    construct-then-close put manager setup in the hot loop)."""
    key = os.path.abspath(directory)
    mgr = _MANAGERS.get(key)
    if mgr is None:
        mgr = _MANAGERS[key] = ocp.CheckpointManager(
            key,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=MAX_TO_KEEP, create=True,
            ),
        )
    return mgr


def _payload(state: TrainState) -> dict:
    """The orbax save/restore dict for a state.  A ``None`` guard (the
    tier-1 feature is off) is OMITTED: guard-free states keep the
    pre-guard 3-key on-disk layout, so checkpoints written before the
    guard existed stay restorable and vice versa."""
    d = state._asdict()
    if d.get("guard") is None:
        d.pop("guard", None)
    return d


def close_manager(directory: str) -> None:
    """Close and drop the directory's cached manager (tests / shutdown)."""
    mgr = _MANAGERS.pop(os.path.abspath(directory), None)
    if mgr is not None:
        mgr.close()


def close_all_managers() -> None:
    for key in list(_MANAGERS):
        close_manager(key)


# ----------------------------------------------------------------------
# Integrity manifests
# ----------------------------------------------------------------------

def step_dir(directory: str, step: int) -> str:
    """The orbax step directory holding one checkpoint's payload."""
    return os.path.join(os.path.abspath(directory), str(step))


def _manifest_path(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory),
                        f"manifest-{step}.json")


def _file_crc32(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                return crc
            crc = zlib.crc32(b, crc)


def _walk_payload(root: str) -> dict[str, dict]:
    out: dict[str, dict] = {}
    for base, _dirs, files in os.walk(root):
        for name in files:
            p = os.path.join(base, name)
            rel = os.path.relpath(p, root)
            out[rel] = {"size": os.path.getsize(p),
                        "crc32": _file_crc32(p)}
    return out


def write_manifest(directory: str, step: int) -> str:
    """Checksum every file under the step dir into manifest-<step>.json.
    Called by :func:`save` after the write lands; returns the path."""
    root = step_dir(directory, step)
    manifest = {"step": step, "files": _walk_payload(root)}
    path = _manifest_path(directory, step)
    # per-process tmp name + atomic replace: even if two writers race
    # (they should not — save() gates on process 0), no reader ever sees
    # a torn manifest, and torn == corrupt would trigger a false fallback
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp, path)
    return path


def verify(directory: str, step: int) -> bool:
    """Recompute the step's content checksums against its manifest.

    False on any missing/resized/bit-flipped file or an unreadable
    manifest.  A checkpoint WITHOUT a manifest (written by an older
    build) verifies True — unverifiable is not the same as corrupt, and
    rejecting legacy checkpoints would turn an upgrade into data loss.
    """
    root = step_dir(directory, step)
    if not os.path.isdir(root):
        return False
    mpath = _manifest_path(directory, step)
    if not os.path.exists(mpath):
        return True  # legacy checkpoint: no integrity claim to check
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return False
    want = manifest.get("files", {})
    have = _walk_payload(root)
    if set(want) != set(have):
        return False
    return all(have[rel] == meta for rel, meta in want.items())


def _prune_stale_manifests(directory: str) -> None:
    """Drop manifests for steps the manager's max_to_keep GC removed."""
    keep = {str(s) for s in _manager(directory).all_steps()}
    for path in glob.glob(os.path.join(os.path.abspath(directory),
                                       "manifest-*.json")):
        step = os.path.basename(path)[len("manifest-"):-len(".json")]
        if step not in keep:
            try:
                os.remove(path)
            except OSError:
                pass


# ----------------------------------------------------------------------
# Save / restore
# ----------------------------------------------------------------------

def save(directory: str, state: TrainState, step: int | None = None,
         wait: bool = True) -> int:
    """Save a checkpoint; returns the step it was saved under."""
    step = int(state.step) if step is None else step
    mgr = _manager(directory)
    mgr.save(step, args=ocp.args.StandardSave(_payload(state)))
    if wait:
        mgr.wait_until_finished()
        # manifest bookkeeping is single-writer: orbax coordinates the
        # array write across hosts, but the manifest is plain JSON on a
        # shared directory — every process writing it would race
        if jax.process_index() == 0:
            write_manifest(directory, step)
            _prune_stale_manifests(directory)
    return step


def latest_step(directory: str) -> int | None:
    if not os.path.isdir(directory):
        return None
    return _manager(directory).latest_step()


def intact_steps(directory: str) -> list[int]:
    """All steps whose payload verifies, newest last."""
    if not os.path.isdir(directory):
        return []
    return [s for s in sorted(_manager(directory).all_steps())
            if verify(directory, s)]


def restore(directory: str, template: TrainState,
            step: int | None = None, *, check_integrity: bool = True,
            fallback: bool = True) -> TrainState:
    """Restore into the template's structure/shardings.

    ``template`` is a TrainState of the right pytree structure (e.g. from
    ``init_state`` + ``device_put`` with shardings); restored arrays land
    with the template's shardings.

    With ``check_integrity`` the requested step is checksum-verified
    first; on corruption, ``fallback`` retries the newest older INTACT
    step (a ``checkpoint.fallback`` telemetry decision records the
    demotion) and :class:`CheckpointCorruptionError` is raised only when
    nothing intact remains.
    """
    mgr = _manager(directory)
    want = step if step is not None else mgr.latest_step()
    if want is None:
        raise FileNotFoundError(f"no checkpoint in {directory}")

    chosen = want
    if check_integrity and not verify(directory, want):
        # only older steps are candidates — and only they get (re)hashed;
        # re-verifying ``want`` via intact_steps would checksum the known-
        # corrupt payload a second time on the recovery hot path
        older = [s for s in sorted(mgr.all_steps())
                 if s < want and verify(directory, s)]
        if not fallback or not older:
            raise CheckpointCorruptionError(
                f"checkpoint step {want} in {directory} failed integrity "
                f"verification and no intact older step exists")
        chosen = older[-1]
        _telemetry.decision(
            "checkpoint.fallback", directory=os.path.abspath(directory),
            corrupt_step=want, restored_step=chosen,
            lost_steps=want - chosen)

    tmpl = _payload(template)
    try:
        restored = mgr.restore(chosen, args=ocp.args.StandardRestore(tmpl))
    except Exception:
        if "guard" not in tmpl:
            raise
        # guard-carrying template, pre-guard checkpoint (no 'guard'
        # subtree on disk): restore the 3-key payload and seed a FRESH
        # GuardState — the EMA re-warms, nothing else is lost
        tmpl = {k: v for k, v in tmpl.items() if k != "guard"}
        restored = mgr.restore(chosen, args=ocp.args.StandardRestore(tmpl))
        restored = dict(restored, guard=_fresh_guard(template.guard))
    # a guard-free payload has no 'guard' key; the field defaults to None
    return TrainState(**restored)


def _fresh_guard(template_guard):
    """A newly initialized GuardState placed onto the template's
    shardings (when it carries any)."""
    from flashmoe_tpu.runtime.trainer import init_guard_state

    fresh = init_guard_state()
    try:
        return jax.tree_util.tree_map(
            lambda f, t: (jax.device_put(f, t.sharding)
                          if getattr(t, "sharding", None) is not None
                          else f),
            fresh, template_guard)
    except Exception:  # abstract/mismatched template: plain host arrays
        return fresh


def emergency_save(directory: str, state: TrainState) -> int | None:
    """Best-effort save for abort paths: persists ``state`` unless its
    step is already on disk; swallows every error (the caller is already
    crashing — the emergency copy must never mask the original fault).
    Returns the saved step, or None."""
    try:
        # refuse donated/deleted buffers UP FRONT: the jitted step donates
        # its input state, so an abort right after a dispatched failure
        # can hand us dead arrays — starting an orbax save with them
        # would leave a half-written step dir, worse than saving nothing
        for leaf in jax.tree_util.tree_leaves(state):
            if getattr(leaf, "is_deleted", None) and leaf.is_deleted():
                return None
        step = int(state.step)
        if latest_step(directory) == step:
            return None
        saved = save(directory, state, step=step)
        _telemetry.decision("checkpoint.emergency_save",
                            directory=os.path.abspath(directory),
                            step=saved)
        return saved
    except Exception:  # noqa: BLE001 — abort path, never re-raise
        return None
