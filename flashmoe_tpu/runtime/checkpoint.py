"""Checkpoint / resume.

The reference has none (SURVEY §5: weights are caller-provided tensors, no
optimizer, nothing to save).  A training framework needs it, so this module
provides orbax-backed save/restore of the :class:`TrainState` (params +
optimizer moments + step), preserving shardings on restore — multi-host
safe (orbax coordinates the write across processes).
"""

from __future__ import annotations

import os
from typing import Any

import jax
import orbax.checkpoint as ocp

from flashmoe_tpu.runtime.trainer import TrainState


def _manager(directory: str, max_to_keep: int = 3) -> ocp.CheckpointManager:
    return ocp.CheckpointManager(
        os.path.abspath(directory),
        options=ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, create=True,
        ),
    )


def save(directory: str, state: TrainState, step: int | None = None,
         wait: bool = True) -> int:
    """Save a checkpoint; returns the step it was saved under."""
    step = int(state.step) if step is None else step
    mgr = _manager(directory)
    mgr.save(step, args=ocp.args.StandardSave(state._asdict()))
    if wait:
        mgr.wait_until_finished()
    mgr.close()
    return step


def latest_step(directory: str) -> int | None:
    if not os.path.isdir(directory):
        return None
    mgr = _manager(directory)
    step = mgr.latest_step()
    mgr.close()
    return step


def restore(directory: str, template: TrainState,
            step: int | None = None) -> TrainState:
    """Restore into the template's structure/shardings.

    ``template`` is a TrainState of the right pytree structure (e.g. from
    ``init_state`` + ``device_put`` with shardings); restored arrays land
    with the template's shardings.
    """
    mgr = _manager(directory)
    step = step if step is not None else mgr.latest_step()
    if step is None:
        raise FileNotFoundError(f"no checkpoint in {directory}")

    restored = mgr.restore(
        step,
        args=ocp.args.StandardRestore(template._asdict()),
    )
    mgr.close()
    return TrainState(**restored)
