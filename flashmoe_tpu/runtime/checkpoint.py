"""Checkpoint / resume with integrity verification.

The reference has none (SURVEY §5: weights are caller-provided tensors, no
optimizer, nothing to save).  A training framework needs it, so this module
provides orbax-backed save/restore of the :class:`TrainState` (params +
optimizer moments + step), preserving shardings on restore — multi-host
safe (orbax coordinates the write across processes).

Tier-2 fault tolerance (docs/RESILIENCE.md) hardens the job-level rung:

  * one :class:`ocp.CheckpointManager` is cached per directory and reused
    across save/latest_step/restore — constructing (and closing) a fresh
    manager per call put manager setup latency in the training hot loop;
  * every save writes a ``manifest-<step>.json`` next to the step dir:
    per-file sizes + CRC32 content checksums;
  * :func:`verify` recomputes the checksums; :func:`restore` verifies
    BEFORE handing bytes to orbax and, on corruption, falls back to the
    newest *intact* older step (recorded as a ``checkpoint.fallback``
    telemetry decision) instead of resuming from garbage;
  * :func:`emergency_save` best-effort persists the last good state when
    a run aborts, never raising into the abort path.

Preemption-safe async saves (``save(..., blocking=False)``) snapshot the
state to host and hand serialize+fsync+atomic-rename to ONE background
writer thread with a depth-1 newest-wins queue per checkpoint
directory; :func:`wait_for_saves` is the drain/emergency barrier.  Durability ordering is preserved: the
manifest is written only after the payload commit (orbax renames the
step dir atomically), so a kill between the two leaves the previous
step — and :func:`verify` semantics — intact.  The manifest additionally
carries the data-loader cursor (``loader_state=``) so a resumed run can
continue the exact token stream (:mod:`flashmoe_tpu.runtime.data`).
"""

from __future__ import annotations

import glob
import json
import os
import threading
from typing import Any

import jax
import orbax.checkpoint as ocp

from flashmoe_tpu.runtime.trainer import TrainState
from flashmoe_tpu.utils.telemetry import metrics as _telemetry


class CheckpointCorruptionError(RuntimeError):
    """No intact checkpoint could be restored from the directory."""


# ----------------------------------------------------------------------
# Manager cache
# ----------------------------------------------------------------------

_MANAGERS: dict[str, ocp.CheckpointManager] = {}
_MANAGERS_LOCK = threading.Lock()

# retained checkpoints per directory; a module constant rather than a
# _manager() parameter because the manager is cached per directory — a
# per-call value would silently bind only the FIRST caller's choice
MAX_TO_KEEP = 3


def _manager(directory: str) -> ocp.CheckpointManager:
    """The directory's cached manager (one per abspath, reused across
    every save/query/restore — satellite fix: the old per-call
    construct-then-close put manager setup in the hot loop).  Lock-
    guarded: the async writer thread and the step loop both resolve
    managers."""
    key = os.path.abspath(directory)
    with _MANAGERS_LOCK:
        mgr = _MANAGERS.get(key)
        if mgr is None:
            mgr = _MANAGERS[key] = ocp.CheckpointManager(
                key,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=MAX_TO_KEEP, create=True,
                ),
            )
    return mgr


def _payload(state: TrainState) -> dict:
    """The orbax save/restore dict for a state.  A ``None`` guard (the
    tier-1 feature is off) is OMITTED: guard-free states keep the
    pre-guard 3-key on-disk layout, so checkpoints written before the
    guard existed stay restorable and vice versa."""
    d = state._asdict()
    if d.get("guard") is None:
        d.pop("guard", None)
    return d


def close_manager(directory: str) -> None:
    """Close and drop the directory's cached manager (tests / shutdown)."""
    with _MANAGERS_LOCK:
        mgr = _MANAGERS.pop(os.path.abspath(directory), None)
    if mgr is not None:
        mgr.close()


def close_all_managers() -> None:
    for key in list(_MANAGERS):
        close_manager(key)


# ----------------------------------------------------------------------
# Integrity manifests
# ----------------------------------------------------------------------

def step_dir(directory: str, step: int) -> str:
    """The orbax step directory holding one checkpoint's payload."""
    return os.path.join(os.path.abspath(directory), str(step))


def _manifest_path(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory),
                        f"manifest-{step}.json")


def _file_crc32(path: str, chunk: int = 1 << 20) -> int:
    # one CRC implementation repo-wide: the KV-handoff transport
    # (fabric/transport.py) checksums its wire frames with the same
    # helper this manifest uses for payload files
    from flashmoe_tpu.utils.integrity import crc32_file

    return crc32_file(path, chunk)


def _walk_payload(root: str) -> dict[str, dict]:
    out: dict[str, dict] = {}
    for base, _dirs, files in os.walk(root):
        for name in files:
            p = os.path.join(base, name)
            rel = os.path.relpath(p, root)
            out[rel] = {"size": os.path.getsize(p),
                        "crc32": _file_crc32(p)}
    return out


def write_manifest(directory: str, step: int,
                   loader_state: dict | None = None,
                   controller_state: dict | None = None,
                   quant_meta: dict | None = None) -> str:
    """Checksum every file under the step dir into manifest-<step>.json.
    Called by :func:`save` after the write lands; returns the path.

    ``loader_state``: the data-loader cursor captured with the state
    snapshot (``TokenLoader.state_dict()``) — stored in the manifest so
    a resumed run consumes the exact token stream the dead run would
    have (:func:`load_loader_state`).  ``controller_state``: the
    self-healing controller's persistent plan (morph overrides, replica
    map, spent budgets — :meth:`flashmoe_tpu.runtime.controller.
    RuntimeController.state_dict`), tied to the step so a restore
    always resumes the plan the PARAMS were written under (a replica
    map without its weight copies, or vice versa, would corrupt the
    model).  Written AFTER the payload is durable: a kill between the
    two leaves a legacy-style manifest-less checkpoint, never a
    manifest pointing at missing bytes."""
    root = step_dir(directory, step)
    manifest = {"step": step, "files": _walk_payload(root)}
    if loader_state is not None:
        manifest["loader"] = dict(loader_state)
    if controller_state is not None:
        manifest["controller"] = dict(controller_state)
    if quant_meta is not None:
        # quantized expert storage (flashmoe_tpu/quant/): the state's
        # quant block — store dtype, grouping, key census — with its
        # own content CRC (quant.verify_quant_metadata), so a restore
        # can prove the dequantization recipe matches the payload it
        # is about to decode.  Pre-quant manifests simply lack the key.
        manifest["quant"] = dict(quant_meta)
    path = _manifest_path(directory, step)
    # per-process tmp name + atomic replace: even if two writers race
    # (they should not — save() gates on process 0), no reader ever sees
    # a torn manifest, and torn == corrupt would trigger a false fallback
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp, path)
    return path


def verify(directory: str, step: int) -> bool:
    """Recompute the step's content checksums against its manifest.

    False on any missing/resized/bit-flipped file or an unreadable
    manifest.  A checkpoint WITHOUT a manifest (written by an older
    build) verifies True — unverifiable is not the same as corrupt, and
    rejecting legacy checkpoints would turn an upgrade into data loss.
    """
    root = step_dir(directory, step)
    if not os.path.isdir(root):
        return False
    mpath = _manifest_path(directory, step)
    if not os.path.exists(mpath):
        return True  # legacy checkpoint: no integrity claim to check
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return False
    want = manifest.get("files", {})
    have = _walk_payload(root)
    if set(want) != set(have):
        return False
    return all(have[rel] == meta for rel, meta in want.items())


def load_loader_state(directory: str, step: int) -> dict | None:
    """The data-loader cursor stored with the step's manifest, or None
    (legacy checkpoint, no loader attached, unreadable manifest)."""
    try:
        with open(_manifest_path(directory, step)) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    loader = manifest.get("loader")
    return dict(loader) if isinstance(loader, dict) else None


def load_quant_metadata(directory: str, step: int) -> dict | None:
    """The quantized-expert-storage block stored with the step's
    manifest (:func:`flashmoe_tpu.quant.quant_metadata`), CRC-verified,
    or None (full-precision state, legacy pre-quant manifest,
    unreadable manifest).  Raises :class:`CheckpointCorruptionError`
    when a block is present but fails its content CRC — a torn/tampered
    quant recipe must never silently decode payloads with the wrong
    scales."""
    try:
        with open(_manifest_path(directory, step)) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    block = manifest.get("quant")
    if block is None:
        return None
    from flashmoe_tpu.quant import verify_quant_metadata

    if not isinstance(block, dict) or not verify_quant_metadata(block):
        raise CheckpointCorruptionError(
            f"checkpoint step {step} in {directory} carries a quant "
            f"metadata block that fails its content CRC")
    return dict(block)


def _state_quant_meta(state) -> dict | None:
    """Derive the manifest quant block from a state's params (None for
    full-precision states — save() calls this automatically, so
    quantized TrainStates get their block without caller plumbing)."""
    params = getattr(state, "params", None)
    if params is None:
        return None
    try:
        from flashmoe_tpu.quant import quant_metadata

        return quant_metadata(params)
    except Exception:  # noqa: BLE001 — metadata must never fail a save
        return None


def load_controller_state(directory: str, step: int) -> dict | None:
    """The self-healing controller's plan stored with the step's
    manifest, or None (no controller, legacy checkpoint, unreadable
    manifest).  Restored by ``supervise``/``resilient_train`` so a
    restart resumes the morphed plan the params were saved under."""
    try:
        with open(_manifest_path(directory, step)) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    cs = manifest.get("controller")
    return dict(cs) if isinstance(cs, dict) else None


def restore_loader_state(directory: str, step: int, loader) -> bool:
    """Reposition ``loader`` from the step's manifest cursor (the ONE
    implementation behind resilient_train resume, elastic_resume and
    the supervisor).  False when the loader is stateless/None or the
    manifest carries no cursor; True after a successful restore."""
    if loader is None or not hasattr(loader, "load_state_dict"):
        return False
    ls = load_loader_state(directory, step)
    if ls is None:
        return False
    loader.load_state_dict(ls)
    return True


def has_guard(directory: str, step: int) -> bool | None:
    """Whether the step's on-disk payload carries the tier-1 ``guard``
    subtree — None when it cannot be determined (missing/opaque
    metadata).  Used for the clear guard-mismatch error in
    :func:`flashmoe_tpu.runtime.elastic.elastic_resume`."""
    try:
        meta = _manager(directory).item_metadata(step)
        keys = list(meta.keys()) if hasattr(meta, "keys") else None
        if keys is not None:
            return "guard" in keys
    except Exception:  # noqa: BLE001 — probe only, never fail the caller
        pass
    try:  # fallback: the orbax tree metadata JSON names every key path
        mpath = os.path.join(step_dir(directory, step), "default",
                             "_METADATA")
        with open(mpath) as f:
            return '"guard"' in f.read()
    except OSError:
        return None


def _prune_stale_manifests(directory: str) -> None:
    """Drop manifests for steps the manager's max_to_keep GC removed."""
    keep = {str(s) for s in _manager(directory).all_steps()}
    for path in glob.glob(os.path.join(os.path.abspath(directory),
                                       "manifest-*.json")):
        step = os.path.basename(path)[len("manifest-"):-len(".json")]
        if step not in keep:
            try:
                os.remove(path)
            except OSError:
                pass


# ----------------------------------------------------------------------
# Async writer: ONE background thread, depth-1 newest-wins queue
# ----------------------------------------------------------------------

class _AsyncWriter:
    """Serializes async checkpoint jobs off the step loop.

    Depth-1 **per directory**, newest-wins: a still-queued older
    snapshot is replaced by a newer one for the SAME checkpoint dir
    (the job of a checkpoint is to minimize loss-of-work — an old
    snapshot nobody would restore is not worth a disk write), while
    jobs for different directories queue side by side (two runs in one
    process must not cancel each other's checkpoints) and the IN-FLIGHT
    job always completes (its payload may already be half-committed).
    Errors are collected, surfaced as ``checkpoint.async_error``
    decisions, and returned by :func:`wait_for_saves` — an async save
    failure must not be silent, but it also must not crash the training
    step that outran it.
    """

    def __init__(self):
        self._cond = threading.Condition()
        # abspath -> job; dict order is FIFO across directories,
        # replacement (newest-wins) keeps the original slot
        self._pending: dict[str, tuple] = {}
        self._in_flight = False
        self._thread: threading.Thread | None = None
        self._errors: list[Exception] = []
        self.dropped = 0
        self.completed = 0

    def submit(self, job: tuple) -> None:
        with self._cond:
            key = os.path.abspath(job[0])
            if key in self._pending:
                self.dropped += 1  # newest wins, per directory
            self._pending[key] = job
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="flashmoe-ckpt-writer",
                    daemon=True)
                self._thread.start()
            self._cond.notify_all()

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending:
                    self._cond.wait()
                job = self._pending.pop(next(iter(self._pending)))
                self._in_flight = True
            directory, host_state, step, loader_state, ctrl_state = job
            try:
                _write_sync(directory, host_state, step, loader_state,
                            ctrl_state)
                with self._cond:
                    self.completed += 1
            except Exception as e:  # noqa: BLE001 — surfaced via barrier
                with self._cond:
                    self._errors.append(e)
                try:
                    _telemetry.decision(
                        "checkpoint.async_error",
                        directory=os.path.abspath(directory), step=step,
                        error=f"{type(e).__name__}: {str(e)[:200]}")
                except Exception:  # noqa: BLE001
                    pass
            finally:
                with self._cond:
                    self._in_flight = False
                    self._cond.notify_all()

    def wait(self, timeout: float | None = None) -> list[Exception]:
        """Block until the queue is empty and nothing is in flight
        (every directory — the barrier is process-wide); returns (and
        clears) the errors collected since the last call."""
        with self._cond:
            self._cond.wait_for(
                lambda: not self._pending and not self._in_flight,
                timeout=timeout)
            errors, self._errors = self._errors, []
            return errors


_WRITER = _AsyncWriter()


def wait_for_saves(timeout: float | None = None) -> list[Exception]:
    """Barrier for in-flight async saves (drain / emergency paths): block
    until the writer is idle, returning any errors it hit since the last
    barrier.  A no-op (empty list) when nothing was ever enqueued."""
    return _WRITER.wait(timeout)


def async_save_stats() -> dict:
    """Writer counters for telemetry/tests: completed, dropped
    (newest-wins replacements), pending errors."""
    return {"completed": _WRITER.completed, "dropped": _WRITER.dropped}


# ----------------------------------------------------------------------
# Save / restore
# ----------------------------------------------------------------------

def _write_sync(directory: str, state: TrainState, step: int,
                loader_state: dict | None,
                controller_state: dict | None = None) -> None:
    """The durable write: orbax payload (atomic step-dir commit), THEN
    the CRC manifest.  The ordering is the async-crash guarantee — a
    kill mid-payload leaves only an uncommitted tmp dir (invisible to
    the manager), a kill between payload and manifest leaves a complete
    legacy-style checkpoint; the previous step is intact either way."""
    mgr = _manager(directory)
    mgr.save(step, args=ocp.args.StandardSave(_payload(state)))
    mgr.wait_until_finished()
    # manifest bookkeeping is single-writer: orbax coordinates the
    # array write across hosts, but the manifest is plain JSON on a
    # shared directory — every process writing it would race
    if jax.process_index() == 0:
        write_manifest(directory, step, loader_state=loader_state,
                       controller_state=controller_state,
                       quant_meta=_state_quant_meta(state))
        _prune_stale_manifests(directory)


def save(directory: str, state: TrainState, step: int | None = None,
         wait: bool = True, *, blocking: bool = True,
         loader_state: dict | None = None,
         controller_state: dict | None = None) -> int:
    """Save a checkpoint; returns the step it was saved under.

    ``blocking=False`` snapshots the state to host (``jax.device_get`` —
    the only cost left on the step loop) and hands serialize + fsync +
    atomic-rename to the background writer; call :func:`wait_for_saves`
    before exiting (drain/emergency paths do).  ``loader_state`` is the
    data-loader cursor to persist in the step's manifest;
    ``controller_state`` the self-healing controller's plan
    (:func:`load_controller_state`).
    """
    step = int(state.step) if step is None else step
    if not blocking:
        host_state = jax.device_get(state)
        _WRITER.submit((directory, host_state, step, loader_state,
                        controller_state))
        return step
    mgr = _manager(directory)
    mgr.save(step, args=ocp.args.StandardSave(_payload(state)))
    if wait:
        mgr.wait_until_finished()
        if jax.process_index() == 0:
            write_manifest(directory, step, loader_state=loader_state,
                           controller_state=controller_state,
                           quant_meta=_state_quant_meta(state))
            _prune_stale_manifests(directory)
    return step


def latest_step(directory: str) -> int | None:
    if not os.path.isdir(directory):
        return None
    return _manager(directory).latest_step()


def intact_steps(directory: str) -> list[int]:
    """All steps whose payload verifies, newest last."""
    if not os.path.isdir(directory):
        return []
    return [s for s in sorted(_manager(directory).all_steps())
            if verify(directory, s)]


def restore(directory: str, template: TrainState,
            step: int | None = None, *, check_integrity: bool = True,
            fallback: bool = True) -> TrainState:
    """Restore into the template's structure/shardings.

    ``template`` is a TrainState of the right pytree structure (e.g. from
    ``init_state`` + ``device_put`` with shardings); restored arrays land
    with the template's shardings.

    With ``check_integrity`` the requested step is checksum-verified
    first; on corruption, ``fallback`` retries the newest older INTACT
    step (a ``checkpoint.fallback`` telemetry decision records the
    demotion) and :class:`CheckpointCorruptionError` is raised only when
    nothing intact remains.
    """
    mgr = _manager(directory)
    want = step if step is not None else mgr.latest_step()
    if want is None:
        raise FileNotFoundError(f"no checkpoint in {directory}")

    chosen = want
    if check_integrity and not verify(directory, want):
        # only older steps are candidates — and only they get (re)hashed;
        # re-verifying ``want`` via intact_steps would checksum the known-
        # corrupt payload a second time on the recovery hot path
        older = [s for s in sorted(mgr.all_steps())
                 if s < want and verify(directory, s)]
        if not fallback or not older:
            raise CheckpointCorruptionError(
                f"checkpoint step {want} in {directory} failed integrity "
                f"verification and no intact older step exists")
        chosen = older[-1]
        _telemetry.decision(
            "checkpoint.fallback", directory=os.path.abspath(directory),
            corrupt_step=want, restored_step=chosen,
            lost_steps=want - chosen)

    tmpl = _payload(template)
    try:
        restored = mgr.restore(chosen, args=ocp.args.StandardRestore(tmpl))
    except Exception:
        if "guard" not in tmpl:
            raise
        # guard-carrying template, pre-guard checkpoint (no 'guard'
        # subtree on disk): restore the 3-key payload and seed a FRESH
        # GuardState — the EMA re-warms, nothing else is lost
        tmpl = {k: v for k, v in tmpl.items() if k != "guard"}
        restored = mgr.restore(chosen, args=ocp.args.StandardRestore(tmpl))
        restored = dict(restored, guard=_fresh_guard(template.guard))
    # a guard-free payload has no 'guard' key; the field defaults to None
    return TrainState(**restored)


def _fresh_guard(template_guard):
    """A newly initialized GuardState placed onto the template's
    shardings (when it carries any)."""
    from flashmoe_tpu.runtime.trainer import init_guard_state

    fresh = init_guard_state()
    try:
        return jax.tree_util.tree_map(
            lambda f, t: (jax.device_put(f, t.sharding)
                          if getattr(t, "sharding", None) is not None
                          else f),
            fresh, template_guard)
    except Exception:  # abstract/mismatched template: plain host arrays
        return fresh


def emergency_save(directory: str, state: TrainState,
                   loader_state: dict | None = None,
                   controller_state: dict | None = None) -> int | None:
    """Best-effort save for abort paths: persists ``state`` unless its
    step is already on disk; swallows every error (the caller is already
    crashing — the emergency copy must never mask the original fault).
    Returns the saved step, or None."""
    try:
        # refuse donated/deleted buffers UP FRONT: the jitted step donates
        # its input state, so an abort right after a dispatched failure
        # can hand us dead arrays — starting an orbax save with them
        # would leave a half-written step dir, worse than saving nothing
        for leaf in jax.tree_util.tree_leaves(state):
            if getattr(leaf, "is_deleted", None) and leaf.is_deleted():
                return None
        # an in-flight async save must land before the emergency copy:
        # the writer and this path share the manager, and the freshest
        # durable step decides whether this save is even needed
        wait_for_saves()
        step = int(state.step)
        if latest_step(directory) == step:
            return None
        saved = save(directory, state, step=step,
                     loader_state=loader_state,
                     controller_state=controller_state)
        _telemetry.decision("checkpoint.emergency_save",
                            directory=os.path.abspath(directory),
                            step=saved)
        return saved
    except Exception:  # noqa: BLE001 — abort path, never re-raise
        return None
