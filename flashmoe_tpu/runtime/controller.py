"""Self-healing runtime controller: close the telemetry -> planner ->
placement loop (ROADMAP item 3; RaMP runtime-aware polymorphism,
arXiv 2604.26039).

Everything this module consumes already existed open-loop after PRs
2-8: MoEStats load histograms and drop fractions (PR 2), the SLO
watchdog and ``PathFailure`` demotion (PRs 3/8), the phase/overlap
drift monitors (PRs 6/8), the Kruskal/union-find Decider
(:mod:`flashmoe_tpu.parallel.decider`) and the elastic re-fold /
checkpoint machinery (PR 4).  The controller is the loop closure: it
watches those streams through debounced, hysteretic triggers and — at
step boundaries only — performs two graduated recovery actions:

* **path morphing** (:class:`MorphAction`) — re-run the planner's
  selection with the MEASURED cost of the running path overriding its
  analytic prior (:func:`flashmoe_tpu.planner.adapt.replan`) and switch
  backend / chunk depth / capacity mode mid-job, re-jitting behind the
  existing ``_resolved_plan`` seam (the runner rebuilds its train step
  with ``cfg.replace(**overrides)``; params and optimizer state are
  untouched).  Triggered by sustained token drops / load skew.
* **expert re-placement** (:class:`ReplaceAction`) — feed the observed
  per-expert load histogram (EMA of the MoEStats ``expert_load``
  vector) into the Decider's rate-proportional assignment
  (:func:`flashmoe_tpu.parallel.decider.rebalance_placement`), emit a
  new :class:`~flashmoe_tpu.parallel.decider.Placement`, and carry
  expert weights (and their optimizer moments) to their new owners by
  permuting the live TrainState (:func:`permute_expert_state`) — the
  same logical-array resharding story the elastic re-fold machinery
  uses, applied along the expert axis.  When a ~dead expert slot
  exists, the hottest expert is REPLICATED onto it
  (``MoEConfig.expert_replicas`` + the controller's weight copy): its
  traffic splits across two value-identical physical slots and the
  combine merges contributions unchanged.  Triggered by a sustained
  step-time regression (a slow/degraded device).

A third, narrower morph axis exists on multi-slice jobs (ISSUE 13):
when the phase ledger's a2a legs dominate the step
(``observe_step(metrics_dict={'phase_ms': ...})`` feeding the
``a2a_share_high`` trigger), the **wire morph** flips
``MoEConfig.wire_dtype_dcn`` so the two-stage exchange's DCN hop ships
fp8 — its own budget (``wire_morph_budget``), the same
cooldown/manifest discipline, recorded as ``controller.wire_morph``.

Oscillation is impossible by construction: every action starts a
cooldown window (triggers during it are recorded as
``controller.cooldown`` decisions, not acted on), each action class has
a hard per-job budget, and the skew trigger is hysteretic (the debounce
counter resets the moment the condition clears).  Every action is a
registered telemetry decision (``controller.morph`` /
``controller.replace`` / ``controller.cooldown``), the full trigger ->
action timeline rides :meth:`RuntimeController.state_dict` into the
checkpoint manifest (so restarts resume with the morphed plan and the
spent budgets, and a postmortem can replay the whole adaptation story —
``python -m flashmoe_tpu.observe`` renders it as the adaptation
report).

Default off = bit-identical: a run without a controller takes exactly
the pre-controller code path, and the one in-graph mechanism the
controller can enable (``MoEConfig.expert_replicas``) is registered in
the staticcheck knob matrix with its own invariant row.

Wiring: ``resilient_train(..., controller=, rebuild_step=)`` and
``supervise(..., controller=)`` / ``ResilienceConfig.adapt``;
``runtime.trainer.train(..., controller=)`` for the plain loop.
Drilled by ``python -m flashmoe_tpu.chaos`` (``skew_sustained`` must
recover via morph, ``slow_device`` via re-placement).
"""

from __future__ import annotations

import dataclasses
import re

import numpy as np

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.utils.telemetry import Metrics, metrics as _global


@dataclasses.dataclass(frozen=True)
class ControllerConfig:
    """Trigger thresholds, debounce/cooldown windows, and action
    budgets.  Defaults are deliberately conservative: a controller
    should be boringly inert on a healthy job."""

    enable_morph: bool = True
    enable_replace: bool = True
    # --- skew trigger (drives morphing) ---
    drop_high: float = 0.05        # dropped-fraction EMA above => skew
    imbalance_high: float = 2.5    # load-imbalance EMA above => skew
    # --- slow trigger (drives re-placement) ---
    slow_factor: float = 1.5       # step_ms EMA > factor * baseline
    baseline_steps: int = 3        # baseline = min of the first N steps
    # --- a2a-dominance trigger (drives the DCN wire morph, ISSUE 13;
    #     armed only on multi-slice jobs — RuntimeController(slices=)) ---
    enable_wire_morph: bool = True
    a2a_share_high: float = 0.5    # a2a legs' share of the phase-ledger
    #                                sum above which the exchange (and on
    #                                a multi-slice job its DCN leg, the
    #                                slowest hop) dominates the step
    wire_morph_dtype: str = "e4m3"  # the DCN-hop wire the morph enables
    wire_morph_budget: int = 1
    # --- replica-morph trigger (ISSUE 16: the fabric's rotation) ---
    # armed only when a ServingFabric feeds observe_fabric(); the
    # controller drains a replica when the fabric runs sustained-idle
    # (mean per-replica queue+active below replica_queue_low) and
    # returns a drained one when pressure is back
    # (above replica_queue_high) — same debounce / cooldown / budget
    # discipline as every other morph
    enable_replica_morph: bool = False
    replica_queue_high: float = 4.0   # mean per-replica depth above
    replica_queue_low: float = 0.5    # ... and below => drain one
    replica_morph_budget: int = 2
    # --- spec-morph trigger (ISSUE 20: speculative decoding) ---
    # armed only when a serving loop feeds observe_spec(); the
    # controller switches speculation OFF when the observed draft
    # acceptance runs below the planner's break-even acceptance for
    # the debounce window (below break-even the verify span prices
    # under 1x tokens/step — pure overhead).  Exact rejection sampling
    # makes the morph free: token streams are unchanged either way
    enable_spec_morph: bool = False
    # acceptance floor; None defers to the planner break-even passed
    # to observe_spec(break_even=) by the serving loop
    spec_accept_floor: float | None = None
    spec_morph_budget: int = 1
    # --- dynamics ---
    debounce_steps: int = 3        # consecutive triggering observations
    cooldown_steps: int = 8        # no action for N steps after one
    ema_decay: float = 0.5         # per-step EMA decay of every signal
    # --- budgets (oscillation bound: hard per-job caps) ---
    morph_budget: int = 2
    replace_budget: int = 2
    # --- replication policy ---
    replicate: bool = True         # allow hot-expert replication
    cold_eps: float = 1e-3         # "dead slot" load-share ceiling
    # a re-placement must improve the projected bottleneck finish time
    # by at least this fraction, else it is a noop (a balanced layout
    # must never be churned for marginal or zero gain)
    min_replace_gain: float = 0.1

    def __post_init__(self):
        if self.debounce_steps < 1:
            raise ValueError("debounce_steps must be >= 1")
        if self.cooldown_steps < 0:
            raise ValueError("cooldown_steps must be >= 0")
        if not 0 < self.ema_decay < 1:
            raise ValueError("ema_decay must be in (0, 1)")
        if self.slow_factor <= 1.0:
            raise ValueError("slow_factor must be > 1")
        if not 0 < self.a2a_share_high < 1:
            raise ValueError("a2a_share_high must be in (0, 1)")
        if self.replica_queue_low >= self.replica_queue_high:
            raise ValueError(
                "replica_queue_low must be < replica_queue_high (the "
                "hysteresis band keeps drain/undrain from oscillating)")
        if (self.spec_accept_floor is not None
                and not 0 < self.spec_accept_floor < 1):
            raise ValueError("spec_accept_floor must be in (0, 1)")


@dataclasses.dataclass(frozen=True)
class BrownoutConfig:
    """Front-door brownout load-shedding thresholds (ISSUE 18, the
    RaMP-style degrade-don't-die arm of the serving ladder).

    The :class:`~flashmoe_tpu.fabric.frontdoor.FrontDoor` observes
    fleet queue pressure and handoff-transport retry pressure every
    fabric step; when the hysteretic thresholds breach for
    ``debounce_steps`` consecutive observations it enters a brownout
    EPISODE — new admissions are shed (rejected at the door) or
    degraded (token budget capped) until pressure falls below the low
    watermark for the same debounce window.  Episodes are bounded by
    ``episode_budget`` and separated by ``cooldown_steps`` — the PR 9
    controller discipline, applied to admission control: a one-step
    blip must never shed a request, and a flapping signal must never
    oscillate the door."""

    #: mean per-live-replica (queue + active) depth above which the
    #: fleet counts as overloaded ...
    queue_high: float = 6.0
    #: ... and below which a brownout episode may end (hysteresis band)
    queue_low: float = 2.0
    #: handoff-transport retries observed since the previous step at or
    #: above this also count as a breach (the wire is failing — new
    #: admissions would pay retry latency on top of queue wait)
    retry_high: int = 3
    #: admission verdict while browned out: "shed" rejects the request
    #: at the door; "degrade" admits it with max_new_tokens capped at
    #: ``degrade_max_new``
    mode: str = "shed"
    degrade_max_new: int = 4
    debounce_steps: int = 2
    cooldown_steps: int = 4
    episode_budget: int = 2

    def __post_init__(self):
        if self.queue_low >= self.queue_high:
            raise ValueError(
                "queue_low must be < queue_high (the hysteresis band "
                "keeps the brownout from oscillating)")
        if self.mode not in ("shed", "degrade"):
            raise ValueError(f"mode must be 'shed' or 'degrade', "
                             f"got {self.mode!r}")
        if self.degrade_max_new < 1:
            raise ValueError("degrade_max_new must be >= 1")
        if self.debounce_steps < 1:
            raise ValueError("debounce_steps must be >= 1")
        if self.cooldown_steps < 0:
            raise ValueError("cooldown_steps must be >= 0")
        if self.episode_budget < 1:
            raise ValueError("episode_budget must be >= 1")
        if self.retry_high < 1:
            raise ValueError("retry_high must be >= 1")


@dataclasses.dataclass(frozen=True)
class MorphAction:
    """Path morph: rebuild the step with ``overrides`` applied."""

    overrides: dict
    trigger: str
    reason: str

    @property
    def needs_rebuild(self) -> bool:
        return bool(self.overrides)


@dataclasses.dataclass(frozen=True)
class ReplaceAction:
    """Expert re-placement: permute the live state by ``perm`` and, for
    each (hot, slot) replica pair, copy the hot expert's FFN weights
    onto the victim slot.  ``overrides`` carries the matching
    ``expert_replicas`` config change (empty when no replication, in
    which case the permutation needs no rebuild at all — the graph is
    placement-agnostic, only the params move)."""

    perm: tuple
    replica_pairs: tuple
    overrides: dict
    trigger: str
    reason: str

    @property
    def needs_rebuild(self) -> bool:
        return bool(self.overrides)


@dataclasses.dataclass(frozen=True)
class SpecMorphAction:
    """Speculation morph: switch draft-then-verify decoding ``off``
    across the fleet.  The fabric executes the verdict through each
    engine's :meth:`~flashmoe_tpu.serving.engine.ServingEngine.
    set_speculate`; exact rejection sampling makes the switch cost
    zero tokens — only the tokens-per-step multiplier changes."""

    kind: str                      # 'off'
    trigger: str
    reason: str


@dataclasses.dataclass(frozen=True)
class ReplicaMorphAction:
    """Fabric rotation morph: ``drain`` takes ``replica`` out of the
    router's rotation (in-flight work keeps decoding), ``undrain``
    returns it.  The fabric executes the verdict through
    :meth:`~flashmoe_tpu.fabric.router.ReplicaRouter.drain` /
    ``undrain``; the controller only decides."""

    kind: str                      # 'drain' | 'undrain'
    replica: int
    trigger: str
    reason: str


def detected_slices() -> int:
    """Slices the running job's ep axis spans — the default for
    :class:`RuntimeController`'s ``slices`` so production loops
    (``resilient_train`` / ``supervise`` / ``trainer.train``) arm the
    DCN wire morph without every call site learning the axis: the
    bootstrapped GroupPlan when a runtime exists, else live slice
    detection; 1 on any failure (detection must never block a step
    boundary)."""
    try:
        from flashmoe_tpu.runtime import bootstrap

        rt = bootstrap._runtime
        if rt is not None and rt.group_plan is not None \
                and rt.group_plan.slices:
            return int(rt.group_plan.slices[0])
        from flashmoe_tpu.parallel.topology import slice_structure

        ss = slice_structure()
        return int(ss[0]) if ss else 1
    except Exception:  # noqa: BLE001 — degrade to single-slice
        return 1


#: MoE param leaves stacked on a leading expert axis (permuted by
#: ``perm`` along axis 0); ``gate_w`` is the router table, permuted
#: along its expert COLUMNS instead.  The ``_qscale`` siblings are the
#: f32 scale sidecars of a quantized expert store
#: (flashmoe_tpu/quant/) — they MUST move with their payloads, or a
#: re-placement would decode every moved expert with another expert's
#: scales.
_EXPERT_AXIS0 = frozenset({
    "w_up", "b_up", "w_down", "b_down", "w_gate",
    "w_up_qscale", "w_down_qscale", "w_gate_qscale",
})


def _key_str(k) -> str:
    return re.sub(r"[^A-Za-z0-9_]", "", str(k))


def permute_expert_state(state, cfg: MoEConfig, perm,
                         replica_pairs=()):
    """Re-place experts in a live TrainState: every MoE leaf (params
    AND their mirrored optimizer moments — optax embeds the param tree,
    so trailing key paths match) with an expert axis is permuted by
    ``perm[new_slot] = old_slot``; ``gate_w`` columns move with their
    experts, so the model computes the identical function under the new
    physical layout.  ``replica_pairs``: (hot, victim) NEW-slot pairs —
    the victim slot's FFN weights (and moments) are overwritten with
    the hot slot's copy (its router column is left alone; the in-graph
    split happens after top-k, :func:`flashmoe_tpu.ops.gate.
    apply_replicas`).

    Host round-trip per touched leaf (device_get -> permute ->
    device_put onto the original sharding): re-placement is a rare
    step-boundary action, not a hot path."""
    import jax
    import jax.numpy as jnp

    e = cfg.num_experts
    perm = tuple(int(p) for p in perm)
    if sorted(perm) != list(range(e)):
        raise ValueError(f"perm must be a permutation of range({e}), "
                         f"got {perm}")
    idx = np.asarray(perm)

    def fix(path, leaf):
        keys = [_key_str(k) for k in path]
        if "moe" not in keys or not hasattr(leaf, "shape"):
            return leaf
        name = keys[-1]
        if name == "gate_w" and leaf.ndim >= 2 and leaf.shape[-1] == e:
            arr = np.asarray(jax.device_get(leaf))[..., idx]
        elif name in _EXPERT_AXIS0 and leaf.ndim >= 1 \
                and leaf.shape[0] == e:
            arr = np.asarray(jax.device_get(leaf))[idx]
            for hot, slot in replica_pairs:
                arr[slot] = arr[hot]
        else:
            return leaf
        sharding = getattr(leaf, "sharding", None)
        out = jnp.asarray(arr)
        return jax.device_put(out, sharding) if sharding is not None \
            else out

    return jax.tree_util.tree_map_with_path(fix, state)


class RuntimeController:
    """The closed loop.  Feed it every step
    (:meth:`observe_step`), ask it at every step boundary
    (:meth:`maybe_act`), apply what it returns
    (:meth:`apply_action` for re-placements; rebuild the step with
    :attr:`cfg_overrides` when ``action.needs_rebuild``).

    ``n_devices``: the device count the placement math targets (the EP
    width; defaults to ``cfg.ep`` or 1).  ``rates_fn``: callable
    returning per-device throughput; the DEFAULT (None) is a live
    re-probe through :func:`flashmoe_tpu.runtime.throughput.
    device_rates` — each slow-device trigger re-measures every device's
    expert throughput (fresh, cache-dropped) so the Decider's
    rate-proportional assignment sees today's silicon, not
    bootstrap's (ROADMAP item 3 follow-up; the chaos drill exercises
    this exact path through the ``probe_rates`` injection seam).  Pass
    an explicit callable to override, or one returning None to price
    devices uniformly; a probe that raises degrades to uniform rates
    with a ``controller.probe_error`` decision rather than blocking the
    step boundary.  ``d`` / ``gen``: the planner width/generation
    morphs re-select at (default ``n_devices`` / the trace-time pin).
    """

    def __init__(self, cfg: MoEConfig,
                 ccfg: ControllerConfig | None = None, *,
                 metrics: Metrics | None = None,
                 rates_fn=None, n_devices: int | None = None,
                 d: int | None = None, gen: str | None = None,
                 slices: int | None = None):
        self.cfg = cfg
        self.ccfg = ccfg or ControllerConfig()
        self.metrics = metrics if metrics is not None else _global
        self.rates_fn = (rates_fn if rates_fn is not None
                         else self._probe_rates)
        self.n_devices = int(n_devices or max(cfg.ep, 1))
        if cfg.num_experts % self.n_devices:
            raise ValueError(
                f"n_devices={self.n_devices} must divide "
                f"num_experts={cfg.num_experts}")
        self.d = int(d) if d is not None else self.n_devices
        self.gen = gen
        # slices the ep axis spans (bootstrap's GroupPlan / mocked):
        # the DCN wire morph only makes sense when a DCN hop exists.
        # Default (None) auto-detects, so the production loops arm the
        # axis on real multi-slice jobs without passing it through
        self.slices = (int(slices) if slices is not None
                       else detected_slices())
        # --- live signal state ---
        self.load_ema: np.ndarray | None = None   # [E] slot loads
        self.imbalance_ema: float | None = None
        self.drop_ema: float | None = None
        self.step_ms_ema: float | None = None
        # last INSTANTANEOUS observations: the debounce counters run on
        # these, not the EMAs — a single spike must not keep a trigger
        # "active" while its EMA tail decays across the window
        self._last_drop: float | None = None
        self._last_imb: float | None = None
        self._last_step_ms: float | None = None
        self.baseline_ms: float | None = None
        self._baseline_seen: list[float] = []
        # a2a-leg share of the phase ledger (ISSUE 13 wire morph)
        self.a2a_share_ema: float | None = None
        self._last_a2a_share: float | None = None
        self._skew_run = 0
        self._slow_run = 0
        self._a2a_run = 0
        # fabric replica-morph signal (ISSUE 16): fed by
        # observe_fabric(), never by the training loops
        self.fab_queue_ema: float | None = None
        self._last_fab_depth: float | None = None
        self._fab_n = 0
        self._fab_hi_run = 0
        self._fab_lo_run = 0
        # speculative-decode acceptance signal (ISSUE 20): fed by
        # observe_spec(), never by the training loops
        self.spec_accept_ema: float | None = None
        self._last_spec_accept: float | None = None
        self._spec_floor: float | None = None
        self._spec_lo_run = 0
        # --- persistent (manifest-riding) state ---
        self.overrides: dict = {}
        self.morphs_used = 0
        self.replaces_used = 0
        self.wire_morphs_used = 0
        self.replica_morphs_used = 0
        self.spec_morphs_used = 0
        self.cooldown_until = -1
        self.timeline: list[dict] = []
        self._cooldown_logged: set = set()

    # ------------------------------------------------------------------
    # Observation
    # ------------------------------------------------------------------

    def _ema(self, prev, value):
        a = self.ccfg.ema_decay
        return value if prev is None else a * prev + (1 - a) * value

    def observe_step(self, step: int, step_ms: float,
                     metrics_dict=None) -> None:
        """Fold one completed step into the trigger state.
        ``metrics_dict``: the step's device metrics (``moe_stats``
        consumed when present — requires ``cfg.collect_stats``)."""
        step = int(step)
        if len(self._baseline_seen) < self.ccfg.baseline_steps:
            self._baseline_seen.append(float(step_ms))
            # min, not mean: the first step carries compile time
            self.baseline_ms = min(self._baseline_seen)
        self.step_ms_ema = self._ema(self.step_ms_ema, float(step_ms))
        self._last_step_ms = float(step_ms)

        # phase-ledger a2a-leg share (the profiler's PhaseTimeline /
        # cost-ledger phase_ms dict — moe.a2a_dispatch[.k] +
        # moe.a2a_combine[.k] over every measured moe.* phase): the
        # signal the DCN wire morph debounces on
        self._last_a2a_share = None
        if isinstance(metrics_dict, dict):
            phases = metrics_dict.get("phase_ms")
            if isinstance(phases, dict) and phases:
                tot = sum(float(v) for v in phases.values())
                a2a = sum(float(v) for k, v in phases.items()
                          if str(k).startswith("moe.a2a_"))
                if tot > 0:
                    share = a2a / tot
                    self.a2a_share_ema = self._ema(self.a2a_share_ema,
                                                   share)
                    self._last_a2a_share = share

        stats = None
        if isinstance(metrics_dict, dict):
            stats = metrics_dict.get("moe_stats")
        if stats:
            from flashmoe_tpu.ops.stats import stats_to_host

            load = None
            imb, drop = 0.0, 0.0
            for st in stats:
                h = st if isinstance(st, dict) else stats_to_host(st)
                v = np.asarray(h["expert_load"], dtype=np.float64)
                load = v if load is None else load + v
                imb = max(imb, float(h["imbalance"]))
                drop = max(drop, float(h["dropped_fraction"]))
            if load is not None:
                if self.load_ema is None \
                        or self.load_ema.shape != load.shape:
                    self.load_ema = load
                else:
                    a = self.ccfg.ema_decay
                    self.load_ema = a * self.load_ema + (1 - a) * load
            self.imbalance_ema = self._ema(self.imbalance_ema, imb)
            self.drop_ema = self._ema(self.drop_ema, drop)
            self._last_imb, self._last_drop = imb, drop

        # --- debounce with hysteresis: any clear observation resets ---
        if self._skew_active():
            self._skew_run += 1
        else:
            self._skew_run = 0
        if self._slow_active():
            self._slow_run += 1
        else:
            self._slow_run = 0
        if self._a2a_active():
            self._a2a_run += 1
        else:
            self._a2a_run = 0

    def _skew_active(self) -> bool:
        # instantaneous values: the debounce counts CONSECUTIVE skewed
        # observations, so a one-step blip resets at the next clear
        # step instead of riding its EMA decay tail across the window
        c = self.ccfg
        return ((self._last_drop is not None
                 and self._last_drop > c.drop_high)
                or (self._last_imb is not None
                    and self._last_imb > c.imbalance_high))

    def _slow_active(self) -> bool:
        return (self.baseline_ms is not None
                and self._last_step_ms is not None
                and len(self._baseline_seen) >= self.ccfg.baseline_steps
                and self._last_step_ms
                > self.ccfg.slow_factor * self.baseline_ms)

    def _a2a_active(self) -> bool:
        # instantaneous like the other debounces; gated on the job
        # actually having a DCN hop to narrow and the knob being off
        return (self.slices > 1
                and self._last_a2a_share is not None
                and self._last_a2a_share > self.ccfg.a2a_share_high
                and self._current_cfg().wire_dtype_dcn is None)

    def observe_fabric(self, step: int, depths) -> None:
        """Fold one fabric step's per-replica load (``queue_depth +
        active_requests``, the router's own JSQ signal) into the
        replica-morph trigger state.  Called by
        :meth:`~flashmoe_tpu.fabric.engine.ServingFabric.step`; the
        debounce counts CONSECUTIVE pressured (or idle) observations,
        like every other trigger."""
        depths = [float(d) for d in depths]
        self._fab_n = len(depths)
        mean = sum(depths) / len(depths) if depths else 0.0
        self.fab_queue_ema = self._ema(self.fab_queue_ema, mean)
        self._last_fab_depth = mean
        c = self.ccfg
        if mean > c.replica_queue_high:
            self._fab_hi_run += 1
        else:
            self._fab_hi_run = 0
        if mean < c.replica_queue_low:
            self._fab_lo_run += 1
        else:
            self._fab_lo_run = 0

    def maybe_morph_replicas(self, step: int, draining=()):
        """The fabric's step-boundary decision: returns a
        :class:`ReplicaMorphAction` or None.  Sustained pressure
        returns the lowest-id DRAINED replica to the rotation
        (capacity back first); sustained idleness drains the highest-id
        replica still rotating (consolidate, never below one).  Same
        cooldown window / budget / decision-record discipline as
        :meth:`maybe_act` — and the same bit: a healthy fabric sees a
        boringly inert controller."""
        step = int(step)
        c = self.ccfg
        if not c.enable_replica_morph:
            return None
        hi = self._fab_hi_run >= c.debounce_steps
        lo = self._fab_lo_run >= c.debounce_steps
        if not (hi or lo):
            return None
        if step < self.cooldown_until:
            key = ("replica", self.cooldown_until)
            if key not in self._cooldown_logged:
                self._cooldown_logged.add(key)
                self._decide("controller.cooldown", step=step,
                             trigger="replica",
                             until=self.cooldown_until)
            return None
        if self.replica_morphs_used >= c.replica_morph_budget:
            return None
        draining = {int(d) for d in draining}
        if hi:
            if not draining:
                return None        # full rotation already
            target, kind, trig = min(draining), "undrain", "queue_high"
            reason = (f"sustained queue pressure (mean depth "
                      f"{self._last_fab_depth:.2f} > "
                      f"{c.replica_queue_high}): return replica "
                      f"{target} to the rotation")
        else:
            rotating = [i for i in range(self._fab_n)
                        if i not in draining]
            if len(rotating) <= 1:
                return None        # never drain the last replica
            target, kind, trig = max(rotating), "drain", "queue_low"
            reason = (f"sustained idle fabric (mean depth "
                      f"{self._last_fab_depth:.2f} < "
                      f"{c.replica_queue_low}): drain replica "
                      f"{target}")
        self.replica_morphs_used += 1
        self._cooldown(step)
        self._decide(
            "controller.replica_morph", step=step, trigger=trig,
            kind=kind, replica=int(target),
            queue_ema=(round(self.fab_queue_ema, 4)
                       if self.fab_queue_ema is not None else None),
            draining=sorted(draining), replicas=self._fab_n,
            budget_left=(c.replica_morph_budget
                         - self.replica_morphs_used),
            reason=reason)
        return ReplicaMorphAction(kind, int(target), trig, reason)

    def observe_spec(self, step: int, accept_rate, *,
                     break_even=None) -> None:
        """Fold one serving observation of the fleet draft-acceptance
        rate into the spec-morph trigger state.  ``accept_rate`` None
        (nothing drafted yet) leaves the state untouched —
        no-draft steps must not debounce toward a morph.
        ``break_even`` is the planner's break-even acceptance
        (:func:`~flashmoe_tpu.planner.model.speculate_break_even`); an
        explicit ``ControllerConfig.spec_accept_floor`` overrides it.
        Like every trigger, the debounce counter runs on the
        INSTANTANEOUS observation; the EMA rides the decision record."""
        if accept_rate is None:
            return
        ar = float(accept_rate)
        self.spec_accept_ema = self._ema(self.spec_accept_ema, ar)
        self._last_spec_accept = ar
        c = self.ccfg
        floor = c.spec_accept_floor
        if floor is None and break_even is not None:
            floor = float(break_even)
        self._spec_floor = floor
        if floor is not None and ar < floor:
            self._spec_lo_run += 1
        else:
            self._spec_lo_run = 0

    def maybe_morph_spec(self, step: int, *, spec_on: bool = True):
        """The serving loop's step-boundary decision: returns a
        :class:`SpecMorphAction` (switch speculation off) or None.
        Same debounce / cooldown window / budget / decision-record
        discipline as every other morph; ``spec_on`` False (already
        morphed, or never armed) is always a None."""
        step = int(step)
        c = self.ccfg
        if not c.enable_spec_morph or not spec_on:
            return None
        if self._spec_lo_run < c.debounce_steps:
            return None
        if step < self.cooldown_until:
            key = ("spec", self.cooldown_until)
            if key not in self._cooldown_logged:
                self._cooldown_logged.add(key)
                self._decide("controller.cooldown", step=step,
                             trigger="spec",
                             until=self.cooldown_until)
            return None
        if self.spec_morphs_used >= c.spec_morph_budget:
            return None
        reason = (f"sustained low draft acceptance "
                  f"({self._last_spec_accept:.3f} < break-even "
                  f"{self._spec_floor:.3f}): the verify span prices "
                  f"below 1x tokens/step — switch speculation off")
        self.spec_morphs_used += 1
        self._cooldown(step)
        self._decide(
            "controller.spec_morph", step=step, trigger="accept_low",
            kind="off",
            accept_ema=(round(self.spec_accept_ema, 4)
                        if self.spec_accept_ema is not None else None),
            break_even=(round(self._spec_floor, 4)
                        if self._spec_floor is not None else None),
            budget_left=c.spec_morph_budget - self.spec_morphs_used,
            reason=reason)
        return SpecMorphAction("off", "accept_low", reason)

    def device_load_share(self, device: int) -> float:
        """Observed load share of one device's slot block under the
        CURRENT physical layout (slot s lives on device s // nLx) —
        what a slow-device simulation (or dashboard) reads."""
        if self.load_ema is None:
            return 1.0 / self.n_devices
        total = float(self.load_ema.sum())
        if total <= 0:
            return 1.0 / self.n_devices
        nlx = self.cfg.num_experts // self.n_devices
        lo = device * nlx
        return float(self.load_ema[lo:lo + nlx].sum()) / total

    # ------------------------------------------------------------------
    # Decision
    # ------------------------------------------------------------------

    @property
    def cfg_overrides(self) -> dict:
        """Accumulated ``MoEConfig.replace`` kwargs a rebuilt step must
        apply (morph targets + the replica routing map)."""
        return dict(self.overrides)

    def apply_to(self, cfg: MoEConfig) -> MoEConfig:
        return cfg.replace(**self.overrides) if self.overrides else cfg

    def _current_cfg(self) -> MoEConfig:
        return self.apply_to(self.cfg)

    def maybe_act(self, step: int, can_rebuild: bool = True):
        """The step-boundary decision: returns a :class:`MorphAction`,
        a :class:`ReplaceAction`, or None.  At most one action per
        boundary; during a cooldown window suppressed triggers are
        recorded as ``controller.cooldown`` decisions (once per window
        per trigger)."""
        step = int(step)
        c = self.ccfg
        skew = self._skew_run >= c.debounce_steps and c.enable_morph
        slow = self._slow_run >= c.debounce_steps and c.enable_replace
        wire = (self._a2a_run >= c.debounce_steps
                and c.enable_wire_morph and self.slices > 1)
        if not (skew or slow or wire):
            return None
        if step < self.cooldown_until:
            for name, hit in (("skew", skew), ("slow", slow),
                              ("a2a", wire)):
                key = (name, self.cooldown_until)
                if hit and key not in self._cooldown_logged:
                    self._cooldown_logged.add(key)
                    self._decide("controller.cooldown", step=step,
                                 trigger=name,
                                 until=self.cooldown_until)
            return None
        # slow wins ties: a degraded device also skews load downstream,
        # and re-placement is the cheaper action (no retrace unless a
        # replica lands)
        if slow and self.replaces_used < c.replace_budget:
            act = self._plan_replace(step)
            if act is not None:
                return act
            if step < self.cooldown_until:
                return None  # planned a noop: its cooldown stands
        if skew and self.morphs_used < c.morph_budget and can_rebuild:
            act = self._plan_morph(step)
            if act is not None:
                return act
            if step < self.cooldown_until:
                return None
        if wire and self.wire_morphs_used < c.wire_morph_budget \
                and can_rebuild:
            return self._plan_wire_morph(step)
        return None

    def _cooldown(self, step: int) -> None:
        self.cooldown_until = step + self.ccfg.cooldown_steps
        self._skew_run = 0
        self._slow_run = 0
        self._a2a_run = 0
        self._fab_hi_run = 0
        self._fab_lo_run = 0
        self._spec_lo_run = 0
        # a fresh baseline: the action changed what "normal" looks like
        self._baseline_seen = []
        self.baseline_ms = None
        self.step_ms_ema = None
        self._last_step_ms = None

    def _decide(self, name: str, **fields) -> dict:
        rec = self.metrics.decision(  # staticcheck: ok forwarding helper; every call site passes a registered literal
            name, **fields)
        self.timeline.append(rec)
        return rec

    def _plan_morph(self, step: int):
        from flashmoe_tpu.planner import adapt

        cfg = self._current_cfg()
        drop_driven = (self.drop_ema is not None
                       and self.drop_ema > self.ccfg.drop_high)
        fam = adapt.current_family(cfg, self.d)
        measured = (adapt.measured_ledger(fam, self.step_ms_ema)
                    if self.step_ms_ema else None)
        plan = adapt.replan(cfg, self.d, gen=self.gen,
                            measured_ms=measured,
                            prefer_dropless=drop_driven)
        if plan.is_noop:
            self._decide("controller.cooldown", step=step,
                         trigger="skew", until=step,
                         reason=f"replan noop: {plan.reason}")
            self._cooldown(step)
            return None
        self.overrides.update(plan.overrides)
        self.morphs_used += 1
        self._cooldown(step)
        self._decide(
            "controller.morph", step=step, trigger="skew",
            mode=plan.mode, backend=plan.backend,
            a2a_chunks=plan.a2a_chunks, dropless=plan.dropless,
            overrides={k: v for k, v in plan.overrides.items()},
            drop_ema=(round(self.drop_ema, 4)
                      if self.drop_ema is not None else None),
            imbalance_ema=(round(self.imbalance_ema, 4)
                           if self.imbalance_ema is not None else None),
            predicted_ms=plan.predicted_ms,
            budget_left=self.ccfg.morph_budget - self.morphs_used,
            reason=plan.reason)
        return MorphAction(dict(plan.overrides), "skew", plan.reason)

    def _plan_wire_morph(self, step: int):
        """Wire-dtype morph (ROADMAP item 3 follow-up / ISSUE 13): the
        phase ledger shows the a2a legs dominating the step on a
        multi-slice job, so narrow the DCN hop — flip
        ``wire_dtype_dcn`` to the configured fp8 wire and let the
        runner re-jit, with the same cooldown / budget / manifest
        discipline as a path morph.  The two-stage exchange then ships
        ~4x fewer DCN bytes while the in-slice hop keeps the compute
        dtype (quality guarded by the ``wire_rtq_error_dcn`` proxy in
        MoEStats)."""
        overrides = {"wire_dtype_dcn": self.ccfg.wire_morph_dtype}
        self.overrides.update(overrides)
        self.wire_morphs_used += 1
        self._cooldown(step)
        self._decide(
            "controller.wire_morph", step=step, trigger="a2a",
            wire_dtype_dcn=self.ccfg.wire_morph_dtype,
            a2a_share_ema=(round(self.a2a_share_ema, 4)
                           if self.a2a_share_ema is not None else None),
            slices=self.slices,
            budget_left=(self.ccfg.wire_morph_budget
                         - self.wire_morphs_used),
            reason="a2a legs dominate the phase ledger on a "
                   "multi-slice job: narrow the DCN hop to "
                   f"{self.ccfg.wire_morph_dtype}")
        return MorphAction(overrides, "a2a",
                           "DCN-hop wire narrowed after sustained "
                           "a2a-leg dominance")

    def _probe_rates(self):
        """Default ``rates_fn``: live per-device throughput re-probe
        (:func:`flashmoe_tpu.runtime.throughput.device_rates`,
        ``fresh=True`` so a RE-trigger measures today's silicon).
        Consulted only when a slow-device re-placement is actually
        being planned — never in the step loop."""
        from flashmoe_tpu.runtime import throughput

        return throughput.device_rates(self._current_cfg(),
                                       self.n_devices, fresh=True)

    def _plan_replace(self, step: int):
        from flashmoe_tpu.parallel.decider import (
            placement_permutation, rebalance_placement,
        )

        if self.load_ema is None or float(self.load_ema.sum()) <= 0:
            return None  # no load signal yet: nothing to re-place on
        rates = None
        if self.rates_fn is not None:
            try:
                r = self.rates_fn()
            except Exception as e:  # noqa: BLE001 — degrade, don't block
                self._decide("controller.probe_error", step=step,
                             reason=f"{type(e).__name__}: {str(e)[:200]}")
                r = None
            if r is not None:
                rates = np.asarray(r, dtype=np.float64)
        placement = rebalance_placement(
            self.load_ema, self.n_devices, self.cfg, rates=rates,
            replicate=self.ccfg.replicate, cold_eps=self.ccfg.cold_eps)
        perm = placement_permutation(placement)
        pairs = tuple(sorted(
            (int(hot), int(v))
            for hot, vs in placement.replicas.items() for v in vs))

        # projected bottleneck finish time, current layout vs proposal
        # (a replica halves its hot slot's load): churn only for a real
        # improvement — a balanced layout re-shuffled for zero gain
        # would look like oscillation
        r = (rates if rates is not None
             else np.ones(self.n_devices, dtype=np.float64))
        nlx = self.cfg.num_experts // self.n_devices

        def makespan(slot_loads):
            per_dev = slot_loads.reshape(self.n_devices, nlx).sum(axis=1)
            return float(np.max(per_dev / np.maximum(r, 1e-9)))

        cur = makespan(self.load_ema)
        proposed_loads = self.load_ema[np.asarray(perm)].copy()
        for hot, victim in pairs:
            proposed_loads[victim] = proposed_loads[hot] / 2
            proposed_loads[hot] /= 2
        proposed = makespan(proposed_loads)
        if (perm == tuple(range(self.cfg.num_experts)) and not pairs) \
                or proposed > cur * (1 - self.ccfg.min_replace_gain):
            self._decide("controller.cooldown", step=step,
                         trigger="slow", until=step,
                         reason="re-placement noop: layout already "
                                "rate-balanced "
                                f"(projected {proposed:.3g} vs "
                                f"current {cur:.3g})")
            self._cooldown(step)
            return None
        before = [self.device_load_share(d)
                  for d in range(self.n_devices)]
        overrides = {"expert_replicas": pairs} if pairs else {}
        if pairs:
            self.overrides["expert_replicas"] = pairs
        self.replaces_used += 1
        self._cooldown(step)
        rec_rates = (rates.tolist() if rates is not None else None)
        self._decide(
            "controller.replace", step=step, trigger="slow",
            perm=list(perm), replicas=[list(p) for p in pairs],
            device_share_before=[round(s, 4) for s in before],
            rates=rec_rates,
            step_ms_ema=(round(self.step_ms_ema, 3)
                         if self.step_ms_ema is not None else None),
            baseline_ms=(round(self.baseline_ms, 3)
                         if self.baseline_ms is not None else None),
            budget_left=self.ccfg.replace_budget - self.replaces_used,
            reason="sustained step-time regression: rate-proportional "
                   "re-placement of the observed load histogram")
        # the load histogram indexes physical slots: re-index it under
        # the new layout so post-action observations stay coherent
        self.load_ema = self.load_ema[np.asarray(perm)]
        return ReplaceAction(perm, pairs, overrides, "slow",
                             "rate-proportional expert re-placement")

    # ------------------------------------------------------------------
    # Application / persistence
    # ------------------------------------------------------------------

    def apply_action(self, action, state):
        """Apply an action to the live TrainState.  Morphs leave the
        state untouched (the runner rebuilds the step); re-placements
        permute expert params/moments and copy replica weights."""
        if isinstance(action, ReplaceAction):
            return permute_expert_state(state, self.cfg, action.perm,
                                        action.replica_pairs)
        return state

    def snapshot(self) -> dict:
        """Live ``/healthz`` view: remaining action budgets, the
        cooldown window, current trigger run lengths, and the
        accumulated overrides — "what can the self-healer still do"."""
        c = self.ccfg
        return {
            "budgets": {
                "morph": c.morph_budget - self.morphs_used,
                "replace": c.replace_budget - self.replaces_used,
                "wire_morph": c.wire_morph_budget - self.wire_morphs_used,
                "replica_morph": (c.replica_morph_budget
                                  - self.replica_morphs_used),
                "spec_morph": (c.spec_morph_budget
                               - self.spec_morphs_used),
            },
            "cooldown_until": self.cooldown_until,
            "trigger_runs": {"skew": self._skew_run,
                             "slow": self._slow_run,
                             "a2a": self._a2a_run,
                             "replica_hi": self._fab_hi_run,
                             "replica_lo": self._fab_lo_run,
                             "spec_lo": self._spec_lo_run},
            "overrides": {k: (list(map(list, v))
                              if k == "expert_replicas" else v)
                          for k, v in self.overrides.items()},
            "actions_taken": len(self.timeline),
        }

    def state_dict(self) -> dict:
        """JSON-able persistent state, written into every checkpoint
        manifest after an action (``runtime.checkpoint.save(...,
        controller_state=)``), so a restarted incarnation resumes with
        the morphed plan, the replica map, and the SPENT budgets — a
        restart must not refill the oscillation bound."""
        ov = dict(self.overrides)
        if "expert_replicas" in ov:
            ov["expert_replicas"] = [list(p)
                                     for p in ov["expert_replicas"]]
        return {"overrides": ov,
                "morphs_used": self.morphs_used,
                "replaces_used": self.replaces_used,
                "wire_morphs_used": self.wire_morphs_used,
                "replica_morphs_used": self.replica_morphs_used,
                "spec_morphs_used": self.spec_morphs_used,
                "timeline": list(self.timeline)}

    def load_state_dict(self, sd: dict) -> None:
        ov = dict(sd.get("overrides") or {})
        if ov.get("expert_replicas"):
            ov["expert_replicas"] = tuple(
                tuple(int(v) for v in p) for p in ov["expert_replicas"])
        elif "expert_replicas" in ov:
            ov.pop("expert_replicas")
        self.overrides = ov
        # budgets are MONOTONIC: a rewind restores the plan the params
        # were saved under but never refills the oscillation bound
        self.morphs_used = max(self.morphs_used,
                               int(sd.get("morphs_used", 0)))
        self.replaces_used = max(self.replaces_used,
                                 int(sd.get("replaces_used", 0)))
        self.wire_morphs_used = max(self.wire_morphs_used,
                                    int(sd.get("wire_morphs_used", 0)))
        self.replica_morphs_used = max(
            self.replica_morphs_used,
            int(sd.get("replica_morphs_used", 0)))
        self.spec_morphs_used = max(
            self.spec_morphs_used,
            int(sd.get("spec_morphs_used", 0)))
        stored = list(sd.get("timeline") or [])
        if len(stored) > len(self.timeline):
            self.timeline = stored
