"""Training input pipeline: binary token shards with background prefetch.

Native path: the C++ loader (``csrc/dataloader.cpp``) decodes and shuffles
[batch, seq_len+1] windows on a background thread.  Fallback: a NumPy
implementation with identical window/shuffle semantics (same xorshift
order), so both paths produce the same batches for the same seed.
"""

from __future__ import annotations

import ctypes
import os

import jax.numpy as jnp
import numpy as np

from flashmoe_tpu.parallel import _native


def write_token_file(path: str, tokens: np.ndarray):
    """Write a flat int32 little-endian token stream."""
    np.asarray(tokens, dtype="<i4").tofile(path)


def _xorshift_order(n: int, seed: int, epoch: int) -> np.ndarray:
    """The C++ loader's epoch shuffle, replicated exactly."""
    s = (seed + 0x51ED270B * (epoch + 1)) & 0xFFFFFFFFFFFFFFFF
    if s == 0:
        s = 0x9E3779B97F4A7C15

    def nxt():
        nonlocal s
        s ^= (s << 13) & 0xFFFFFFFFFFFFFFFF
        s ^= s >> 7
        s ^= (s << 17) & 0xFFFFFFFFFFFFFFFF
        return s

    order = np.arange(n, dtype=np.int64)
    for i in range(n - 1, 0, -1):
        j = nxt() % (i + 1)
        order[i], order[j] = order[j], order[i]
    return order


class TokenLoader:
    """Iterator of {"tokens": [batch, seq_len+1] int32} batches."""

    def __init__(self, path: str, batch: int, seq_len: int, *,
                 seed: int = 0, shuffle: bool = True,
                 native: str | bool = "auto"):
        self.path, self.batch, self.seq_len = path, batch, seq_len
        self.seed, self.shuffle = seed, shuffle
        self._handle = None
        self._lib = None
        self._closed = False
        # rows handed out by the NATIVE loader (the C API exposes no
        # cursor, but both paths consume windows in the identical
        # xorshift order, so a host-side row count IS the cursor)
        self._native_rows = 0
        if native != False:  # noqa: E712
            lib = _native.load()
            if lib is not None:
                self._bind(lib)
                h = lib.flashmoe_loader_open(
                    path.encode(), seq_len, batch, seed, int(shuffle)
                )
                if h:
                    self._handle = h
                    self._lib = lib
                elif native is True:
                    raise RuntimeError(f"native loader failed to open {path}")
            elif native is True:
                raise RuntimeError("native library unavailable")
        if self._handle is None:
            toks = np.fromfile(path, dtype="<i4")
            w = seq_len + 1
            n = len(toks) // w
            if n < 1:
                raise ValueError(f"{path}: fewer tokens than one window")
            self._windows = toks[: n * w].reshape(n, w)
            self._epoch = 0
            self._cursor = 0
            self._order = (
                _xorshift_order(n, seed, 0) if shuffle
                else np.arange(n, dtype=np.int64)
            )

    @staticmethod
    def _bind(lib):
        if getattr(lib, "_loader_bound", False):
            return
        lib.flashmoe_loader_open.restype = ctypes.c_void_p
        lib.flashmoe_loader_open.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_uint64, ctypes.c_int,
        ]
        lib.flashmoe_loader_next.restype = ctypes.c_int
        lib.flashmoe_loader_next.argtypes = [
            ctypes.c_void_p,
            np.ctypeslib.ndpointer(np.int32, flags="C"),
        ]
        lib.flashmoe_loader_num_windows.restype = ctypes.c_int64
        lib.flashmoe_loader_num_windows.argtypes = [ctypes.c_void_p]
        lib.flashmoe_loader_close.restype = None
        lib.flashmoe_loader_close.argtypes = [ctypes.c_void_p]
        lib._loader_bound = True

    @property
    def is_native(self) -> bool:
        return self._handle is not None

    @property
    def num_windows(self) -> int:
        if self._handle is not None:
            return int(self._lib.flashmoe_loader_num_windows(self._handle))
        return len(self._windows)

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            # a closed NATIVE loader used to fall through to the NumPy
            # branch and crash with AttributeError: _windows — say what
            # actually happened instead
            raise RuntimeError("loader is closed")
        w = self.seq_len + 1
        if self._handle is not None:
            out = np.empty((self.batch, w), np.int32)
            rc = self._lib.flashmoe_loader_next(
                self._handle, out.reshape(-1)
            )
            if rc != 0:
                raise StopIteration
            self._native_rows += self.batch
            return {"tokens": jnp.asarray(out)}
        rows = []
        for _ in range(self.batch):
            if self._cursor >= len(self._order):
                self._epoch += 1
                self._cursor = 0
                if self.shuffle:
                    self._order = _xorshift_order(
                        len(self._windows), self.seed, self._epoch
                    )
            rows.append(self._windows[self._order[self._cursor]])
            self._cursor += 1
        return {"tokens": jnp.asarray(np.stack(rows))}

    # ------------------------------------------------------------------
    # Resumable state (preemption-safe training, docs/RESILIENCE.md)
    # ------------------------------------------------------------------

    def _consumed_rows(self) -> int:
        """Windows handed out since epoch 0 — the canonical cursor."""
        if self._handle is not None:
            return self._native_rows
        return self._epoch * len(self._windows) + self._cursor

    def state_dict(self) -> dict:
        """The loader's exact position, identical on both paths.

        (epoch, cursor) are normalized to ``cursor < num_windows`` (the
        NumPy path wraps its epoch lazily, the native path eagerly — the
        canonical form makes native/fallback state dicts compare equal
        and restore interchangeably)."""
        if self._closed:
            raise RuntimeError("loader is closed")
        n = self.num_windows
        consumed = self._consumed_rows()
        return {"epoch": consumed // n, "cursor": consumed % n,
                "seed": self.seed, "shuffle": bool(self.shuffle)}

    def load_state_dict(self, state: dict) -> None:
        """Reposition so the next batch is the exact batch a loader with
        this state would produce next.  ``seed``/``shuffle`` are restored
        from the state (the shuffle order is a function of both — the
        construction-time values are layout hints, the checkpoint is the
        truth)."""
        if self._closed:
            raise RuntimeError("loader is closed")
        n = self.num_windows
        epoch, cursor = int(state["epoch"]), int(state["cursor"])
        if not 0 <= cursor < max(n, 1):
            raise ValueError(
                f"loader state cursor {cursor} out of range for "
                f"{n} windows in {self.path}")
        self.seed = int(state.get("seed", self.seed))
        self.shuffle = bool(state.get("shuffle", self.shuffle))
        consumed = epoch * n + cursor
        if self._handle is not None:
            # the C API exposes no seek: reopen at epoch 0 and fast-
            # forward whole batches (both paths share the window order,
            # so discarding k batches lands on the identical position)
            if consumed % self.batch:
                raise ValueError(
                    f"native loader can only resume on a batch boundary: "
                    f"{consumed} rows consumed, batch={self.batch}; "
                    f"reopen with native=False to resume mid-batch")
            self._lib.flashmoe_loader_close(self._handle)
            self._handle = self._lib.flashmoe_loader_open(
                self.path.encode(), self.seq_len, self.batch,
                self.seed, int(self.shuffle))
            if not self._handle:
                raise RuntimeError(
                    f"native loader failed to reopen {self.path}")
            self._native_rows = 0
            scratch = np.empty(self.batch * (self.seq_len + 1), np.int32)
            for _ in range(consumed // self.batch):
                if self._lib.flashmoe_loader_next(self._handle, scratch):
                    raise RuntimeError(
                        f"native loader ended while fast-forwarding to "
                        f"row {consumed} of {self.path}")
                self._native_rows += self.batch
            return
        self._epoch, self._cursor = epoch, cursor
        self._order = (
            _xorshift_order(n, self.seed, epoch) if self.shuffle
            else np.arange(n, dtype=np.int64)
        )

    def close(self):
        """Release the native handle; idempotent on both paths.  A closed
        loader refuses iteration with a clear RuntimeError."""
        if self._handle is not None:
            self._lib.flashmoe_loader_close(self._handle)
            self._handle = None
        self._closed = True

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
