"""Elastic resume: continue training after the world size changes.

The reference has no elasticity at all — a dead worker stalls its NVSHMEM
collectives forever (SURVEY §5; the sequence-bit protocol only tolerates
*skipped* iterations, ``subscriber.cuh:104-137``).  The TPU-native story is
checkpoint resharding: every array in the TrainState is a logical global
array whose sharding is a layout annotation, so resuming on a different
device count is "rebuild the mesh, restore the checkpoint into the new
shardings" — orbax reshards on read.  Combined with
:mod:`flashmoe_tpu.runtime.resilient` (in-job detection + restore), this
covers the scheduler-restarts-the-job-smaller/larger case.
"""

from __future__ import annotations

import jax

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.parallel.mesh import make_mesh
from flashmoe_tpu.runtime import checkpoint as ckpt
from flashmoe_tpu.runtime.trainer import (
    TrainState, init_state, make_optimizer, state_shardings,
)


def fold_parallelism(cfg: MoEConfig, n_devices: int) -> MoEConfig:
    """Fit the config's parallelism to the CURRENT device count: ep folds
    down to the largest divisor of num_experts that fits, dp absorbs the
    rest (same folding bootstrap.initialize applies at first start).

    Only dp x ep survive the fold: a job that was pipelined or tensor/
    sequence-parallel resumes as a dp x ep job.  That silently changes
    the execution strategy (not the math — checkpoints reshard), so any
    dropped axis warns loudly (VERDICT r3 weak #8).
    """
    dropped = [ax for ax in ("pp", "tp", "sp") if getattr(cfg, ax) > 1]
    if dropped:
        import warnings
        warnings.warn(
            "elastic resume folds parallelism to dp x ep; dropping "
            + ", ".join(f"{ax}={getattr(cfg, ax)}" for ax in dropped)
            + " from the stored config (the restored model is identical; "
            "the execution strategy is not)", stacklevel=2)
    ep = min(cfg.ep if cfg.ep > 1 else n_devices, n_devices)
    while ep > 1 and (cfg.num_experts % ep or n_devices % ep):
        ep -= 1
    return cfg.replace(ep=max(1, ep), dp=max(1, n_devices // max(1, ep)),
                       pp=1, tp=1, sp=1)


def elastic_resume(cfg: MoEConfig, checkpoint_dir: str, *,
                   devices=None, optimizer=None, total_steps: int = 10000,
                   guard=None, loader=None):
    """Rebuild mesh + shardings for the current device set and restore the
    latest checkpoint into them.

    Returns (state, mesh, cfg', optimizer).  The restored arrays land
    resharded over the NEW mesh regardless of the world size that wrote
    the checkpoint.

    ``guard``: pass the job's :class:`flashmoe_tpu.runtime.trainer.
    GradGuardConfig` when the checkpoint was written by a tier-1 guarded
    step — the restore template must carry the matching GuardState
    subtree (docs/RESILIENCE.md).  A guarded checkpoint restored without
    it raises a clear ValueError (not the opaque orbax tree error).

    ``loader``: a stateful data loader (``load_state_dict``) to
    reposition from the checkpoint's manifest cursor, so the resumed run
    continues the exact token stream (docs/RESILIENCE.md, preemption).
    """
    devices = list(devices if devices is not None else jax.devices())
    cfg = fold_parallelism(cfg, len(devices))
    mesh = make_mesh(cfg, devices=devices)
    optimizer = optimizer or make_optimizer(cfg, total_steps=total_steps)

    step = ckpt.latest_step(checkpoint_dir)
    if step is None:
        raise FileNotFoundError(f"no checkpoint in {checkpoint_dir}")
    # abstract template only — never materialize a second copy of the model
    template = jax.eval_shape(
        lambda: init_state(jax.random.PRNGKey(0), cfg, optimizer,
                           guard=guard)
    )
    shardings = state_shardings(template, cfg, mesh)
    abstract = jax.tree_util.tree_map(
        lambda x, sh: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)
        if hasattr(x, "shape") else x,
        template, shardings,
    )
    try:
        state = ckpt.restore(checkpoint_dir, abstract, step=step)
    except Exception as e:
        # a guard-layout mismatch used to surface as an opaque orbax
        # tree-structure error; diagnose it from the on-disk metadata.
        # (The inverse — guard-carrying template over a pre-guard
        # checkpoint — is healed inside ckpt.restore with a fresh
        # GuardState, so only this direction can land here.)
        if guard is None and ckpt.has_guard(checkpoint_dir, step):
            raise ValueError(
                f"checkpoint step {step} in {checkpoint_dir} carries a "
                f"tier-1 GuardState subtree but elastic_resume was "
                f"called without guard=; pass the job's GradGuardConfig "
                f"(docs/RESILIENCE.md) so the restore template matches "
                f"the on-disk layout") from e
        raise
    ckpt.restore_loader_state(checkpoint_dir, int(state.step), loader)
    return state, mesh, cfg, optimizer
