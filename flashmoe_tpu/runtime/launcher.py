"""Multi-process launcher.

The reference shells out to ``nvshmrun -n N -ppn P python worker.py cfg``
(``flashmoe/launcher.py:38-56``).  On TPU, multi-host jobs are normally
started by the cluster scheduler (GKE/“one process per host”), so the
launcher's job is (a) single-host multi-process simulation for development
and (b) generating/executing the per-host command with the coordinator
environment that :mod:`flashmoe_tpu.runtime.bootstrap` consumes.
"""

from __future__ import annotations

import os
import subprocess
import sys


def run_workers(n_processes: int = 1, *, config_path: str | None = None,
                bench: bool = False, coordinator: str = "127.0.0.1:8476",
                extra_env: dict | None = None,
                per_rank_env: dict | None = None,
                worker_module: str = "flashmoe_tpu.runtime.worker") -> int:
    """Launch N local worker processes (CPU backend: each gets the virtual
    device set; TPU: single process owns the local chips).

    Returns the worst exit code.  Mirrors ``nvshmrun_launcher``'s contract:
    build the command, run it, surface stdout/stderr.  ``per_rank_env``
    maps rank -> env overrides for that rank only (heterogeneity/fault
    injection in tests).
    """
    procs = []
    for rank in range(n_processes):
        env = dict(os.environ)
        env.update(extra_env or {})
        env.update((per_rank_env or {}).get(rank, {}))
        if n_processes > 1:
            env.update({
                "FLASHMOE_COORDINATOR": coordinator,
                "FLASHMOE_NPROCS": str(n_processes),
                "FLASHMOE_RANK": str(rank),
            })
        cmd = [sys.executable, "-m", worker_module]
        if config_path:
            cmd.append(config_path)
        if bench:
            cmd.append("--bench")
        procs.append(subprocess.Popen(cmd, env=env))
    rc = 0
    for p in procs:
        p.wait()
        rc = max(rc, p.returncode)
    return rc


def slurm_command(n_nodes: int, config_path: str) -> str:
    """The srun command line for a multi-host job (reference README's SLURM
    path, ``README.md:118-126``)."""
    return (
        f"srun -N {n_nodes} --ntasks-per-node=1 "
        f"python -m flashmoe_tpu.runtime.worker {config_path} --bench"
    )
