"""Preemption notices: turn SIGTERM into a graceful drain, not a corpse.

The reference kernel's failure story assumes workers die silently — its
sequence-bit protocol only tolerates *skipped* iterations, and a worker
that goes away stalls the NVSHMEM collectives forever (SURVEY §5).  On
preemptible TPU pods the dominant "failure" is not a NaN: it is SIGTERM
with a short grace window.  Dying mid-checkpoint-write is how runs lose
hours of work to a 30-second eviction.

:class:`PreemptionListener` converts the asynchronous signal into a flag
that :func:`flashmoe_tpu.runtime.resilient.resilient_train` polls once
per step (one Python attribute read — nothing added to the compiled
graph).  On notice the loop finishes the in-flight step, writes a final
checkpoint + data-loader state, logs a ``preempt.drain`` decision, and
returns cleanly; :func:`flashmoe_tpu.runtime.resilient.supervise`
(or the cluster scheduler) resumes from exactly that step.

Signals are process-global and only installable from the main thread, so
the listener also accepts a *programmatic* :meth:`notify` — tests and
chaos drills (``FaultPlan("preempt")``) inject notices without touching
process signal state.
"""

from __future__ import annotations

import signal
import time

from flashmoe_tpu.utils.telemetry import metrics as _telemetry

#: default signals a preemption notice arrives on: SIGTERM is what
#: schedulers send at eviction; SIGUSR1 is the conventional early-warning
#: channel (e.g. a node-watcher forwarding the cloud preemption notice)
DEFAULT_SIGNALS = (signal.SIGTERM, signal.SIGUSR1)


class PreemptionListener:
    """A latched preemption flag with an optional signal hookup.

    ``grace_s`` is the scheduler's kill window: the time between the
    notice and the hard kill.  The drain path reports how much of it was
    left when the final checkpoint landed (``remaining_grace_s``), so an
    operator can see how close a run is to losing the race.
    """

    def __init__(self, grace_s: float = 30.0):
        self.grace_s = float(grace_s)
        # the latch is deliberately LOCK-FREE: notify() runs inside a
        # signal handler, which CPython executes on the main thread
        # between bytecodes — taking any lock there (threading.Lock,
        # or Event's internal condition) deadlocks if the interrupted
        # frame holds it (e.g. a clear() racing a re-sent SIGTERM).
        # Plain attribute writes are atomic under the GIL; the worst
        # race is two near-simultaneous notices both stamping the
        # clock, which is harmless (same instant)
        self._requested = False
        self._notice_t: float | None = None
        self._source: str | None = None
        self._installed: dict[int, object] = {}

    # ------------------------------------------------------------------
    # Notice
    # ------------------------------------------------------------------

    @property
    def requested(self) -> bool:
        """True once a notice has arrived (signal or programmatic)."""
        return self._requested

    def notify(self, source: str = "program") -> None:
        """Latch a preemption notice.  Async-signal-safe (no locks).
        Idempotent: only the FIRST notice starts the grace clock — a
        scheduler re-sending SIGTERM must not push the deadline out."""
        if self._requested:
            return
        self._notice_t = time.monotonic()
        self._source = source
        self._requested = True
        try:
            _telemetry.decision("preempt.notice", source=source,
                                grace_s=self.grace_s)
        except Exception:  # noqa: BLE001 — the latch must survive
            pass

    def clear(self) -> None:
        """Reset the latch (a new incarnation after a supervised
        restart).  Installed signal handlers stay installed.  Order
        matters against a signal interrupting this very call: the flag
        drops FIRST, so a notice landing mid-clear re-latches fully and
        survives (at worst its clock fields are wiped by the rest of
        this clear — a drain with unknown grace beats a lost notice and
        a hard kill)."""
        self._requested = False
        self._notice_t = None
        self._source = None

    @property
    def source(self) -> str | None:
        return self._source

    def notice_age_s(self) -> float | None:
        """Seconds since the notice, or None before one arrives."""
        t = self._notice_t
        return None if t is None else time.monotonic() - t

    def remaining_grace_s(self) -> float | None:
        """Grace budget left (may be negative: the drain lost the race)."""
        age = self.notice_age_s()
        return None if age is None else self.grace_s - age

    def wait(self, timeout: float | None = None,
             poll_s: float = 0.02) -> bool:
        """Block until a notice arrives (tests / supervisor idle
        loops).  Polls the lock-free latch rather than waiting on an
        Event — see ``__init__`` for why no Event exists."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self._requested:
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)
        return True

    # ------------------------------------------------------------------
    # Signal hookup
    # ------------------------------------------------------------------

    def install(self, signals=DEFAULT_SIGNALS) -> "PreemptionListener":
        """Register handlers for ``signals`` (main thread only — a
        CPython constraint on ``signal.signal``).  Previous handlers are
        remembered and restored by :meth:`uninstall`.  Returns self."""
        for sig in signals:
            if sig in self._installed:
                continue
            prev = signal.signal(
                sig, lambda signum, frame: self.notify(
                    source=signal.Signals(signum).name))
            self._installed[sig] = prev
        return self

    def uninstall(self) -> None:
        """Restore the pre-install handlers (idempotent)."""
        for sig, prev in list(self._installed.items()):
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):
                pass  # not main thread / handler gone: nothing to restore
            del self._installed[sig]

    def __enter__(self) -> "PreemptionListener":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
