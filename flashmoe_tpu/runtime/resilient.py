"""Resilient training: failure detection + checkpoint-based recovery.

The reference has no failure story (SURVEY §5): its closest mechanism is
the sequence-bit protocol that tolerates *skipped* iterations
(``subscriber.cuh:104-137``) — a dead worker stalls the collective forever.
This module provides the framework-level equivalent capability and more:

  * **detection** — every step is bounded by a wall-clock deadline and its
    loss is checked finite; a hung collective, a device error (XLA raises),
    or a NaN/inf step all count as failures;
  * **recovery** — state restores from the latest *intact* orbax
    checkpoint (integrity-verified, tier 2 of docs/RESILIENCE.md) and
    training resumes; transient failures are retried up to a budget,
    repeated failures at the same step abort with a diagnosis (after a
    best-effort emergency save of the last good state);
  * **exact replay** — batches consumed since the last checkpoint are
    buffered, so a retried step re-trains on the SAME data the failed
    attempt saw (rewinding only the model, not the data stream, silently
    diverged the replayed run before this);
  * **path fallback** — a :class:`flashmoe_tpu.planner.select.PathFailure`
    escaping a step demotes the failed execution path for the rest of the
    process (``planner.fallback`` decision) before the retry;
  * **periodic checkpointing** — bounded loss-of-work window.

Single-process recovery is fully testable (failures injected in tests and
by :mod:`flashmoe_tpu.chaos`); multi-host recovery composes with the
cluster scheduler restarting dead processes and every process restoring
from the shared checkpoint directory.
"""

from __future__ import annotations

import concurrent.futures as _fut
import dataclasses
import time
from typing import Callable, Iterator

import jax
import numpy as np

from flashmoe_tpu.runtime import checkpoint as ckpt
from flashmoe_tpu.runtime.trainer import TrainState
from flashmoe_tpu.utils.telemetry import Metrics


class StepFailure(RuntimeError):
    pass


@dataclasses.dataclass
class ResilienceConfig:
    checkpoint_dir: str = "/tmp/flashmoe_ckpt"
    checkpoint_every: int = 50
    step_timeout_s: float | None = None  # None = no deadline
    max_retries: int = 3
    # tier-2 hardening knobs (docs/RESILIENCE.md); defaults preserve the
    # strongest behavior — flip off only to reproduce legacy semantics
    verify_checkpoints: bool = True   # checksum-verify before restore
    emergency_save: bool = True       # persist last good state on abort


def _run_step(step_fn, state, batch, timeout_s):
    """Execute one step, optionally under a wall-clock deadline.

    The deadline wraps the *blocking* result fetch — a hung device shows up
    as a timeout rather than an eternal stall (the failure detector the
    reference's collectives lack).
    """
    if timeout_s is None:
        out = step_fn(state, batch)
        jax.block_until_ready(out)
        return out
    ex = _fut.ThreadPoolExecutor(max_workers=1)
    f = ex.submit(lambda: jax.block_until_ready(step_fn(state, batch)))
    try:
        return f.result(timeout=timeout_s)
    except _fut.TimeoutError as e:
        raise StepFailure(f"step exceeded {timeout_s}s deadline") from e
    finally:
        # wait=False: a worker genuinely stuck in a hung collective must be
        # abandoned, not joined — shutdown(wait=True) would re-stall the
        # caller on the very hang the deadline just detected.
        ex.shutdown(wait=False)


def scalar_metrics(m: dict) -> dict:
    """History-safe view of a step's metrics: scalars to floats,
    non-scalars (e.g. per-expert MoEStats arrays when collect_stats is
    on) skipped — ``float(v)`` on an [E]-shaped array raised mid-recovery
    before this guard existed."""
    out = {}
    for k, v in m.items():
        try:
            if np.asarray(v).size == 1:
                out[k] = float(np.asarray(v).reshape(()))
        except (TypeError, ValueError):
            continue
    return out


def _step_loss(m: dict) -> float | None:
    """The step's scalar loss, or None when absent/non-scalar — a custom
    step_fn without a 'loss' key must not KeyError the recovery loop."""
    v = m.get("loss")
    if v is None:
        return None
    try:
        a = np.asarray(v)
        return float(a.reshape(())) if a.size == 1 else None
    except (TypeError, ValueError):
        return None


class _ReplayBuffer:
    """Batches consumed since the last durable checkpoint, keyed by step.

    On rewind, steps re-execute against the EXACT batch the failed
    attempt consumed instead of silently pulling fresh data (the replay-
    divergence bug: retried steps trained on different batches than the
    history claimed).  Memory is bounded by ``2 * checkpoint_every``
    batches: pruning lags one checkpoint so a corruption-fallback
    restore to the PREVIOUS intact checkpoint still replays bit-exact.
    """

    def __init__(self, data_iter: Iterator):
        self._it = data_iter
        self._buf: dict[int, object] = {}

    def batch_for(self, step: int):
        b = self._buf.get(step)
        if b is None:
            b = next(self._it)
            self._buf[step] = b
        return b

    def prune_before(self, step: int):
        for s in [s for s in self._buf if s < step]:
            del self._buf[s]

    def __len__(self):
        return len(self._buf)


def resilient_train(state: TrainState, step_fn: Callable,
                    data_iter: Iterator, num_steps: int,
                    rcfg: ResilienceConfig | None = None,
                    metrics: Metrics | None = None,
                    fail_injector: Callable | None = None):
    """Run ``num_steps`` with detection + restore-and-retry recovery.

    ``step_fn(state, batch) -> (state, metrics_dict)`` — e.g. from
    :func:`flashmoe_tpu.runtime.trainer.make_train_step`.
    ``fail_injector(step_idx)`` may raise, for tests/chaos drills
    (:func:`flashmoe_tpu.chaos.make_injector`).

    Returns (state, history).  Raises :class:`StepFailure` after
    ``max_retries`` consecutive failures on one step (after a best-effort
    emergency checkpoint of the last good state).
    """
    rcfg = rcfg or ResilienceConfig()
    metrics = metrics or Metrics()
    history = []

    # resume if a checkpoint exists
    start = ckpt.latest_step(rcfg.checkpoint_dir)
    if start is not None and start > int(state.step):
        state = ckpt.restore(rcfg.checkpoint_dir, state,
                             check_integrity=rcfg.verify_checkpoints)
        metrics.count("resumes")

    i = int(state.step)
    retries = 0
    # retries are counted against the step that failed, not reset by any
    # success: recovery may rewind to an earlier step that succeeds again,
    # and that must not refill the budget for a deterministically failing
    # later step (it would livelock)
    last_fail_step = -1
    # In-memory recovery point for failures BEFORE the first checkpoint
    # exists: the jitted step donates its input state (trainer.py
    # donate_argnums), so a post-dispatch failure can leave ``state`` with
    # deleted buffers — retrying needs an undonated copy.  Dropped once a
    # checkpoint is on disk (holding a full host copy of params+moments
    # for the whole run would cost host RAM for nothing): restores then
    # use an abstract shape/dtype/sharding template instead.
    shardings = jax.tree_util.tree_map(
        lambda x: getattr(x, "sharding", None), state)
    abstract = jax.tree_util.tree_map(
        lambda x, sh: jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=sh
        ) if hasattr(x, "shape") else x,
        state, shardings,
    )
    safe_state = jax.device_get(state)
    replay = _ReplayBuffer(data_iter)
    prev_ckpt_step = None  # pruning lags one checkpoint (see below)
    while i < num_steps:
        # replay-exact data: a rewound step gets the batch its failed
        # attempt consumed, not the iterator's next fresh one
        batch = replay.batch_for(i)
        try:
            if fail_injector is not None:
                fail_injector(i)
            t0 = time.perf_counter()
            new_state, m = _run_step(step_fn, state, batch,
                                     rcfg.step_timeout_s)
            loss = _step_loss(m)
            if loss is not None and not np.isfinite(loss):
                raise StepFailure(f"non-finite loss at step {i}: {loss}")
        except Exception as e:  # timeout, NaN, device error, injected fault
            metrics.count("failures")
            from flashmoe_tpu.planner.select import (
                PathFailure, report_path_failure,
            )

            if isinstance(e, PathFailure):
                # tier-2 path fallback: demote the failed execution path
                # BEFORE retrying, so the retry re-resolves onto a
                # healthy one instead of re-tracing the same failure
                report_path_failure(e.backend, str(e))
                metrics.count("path_fallbacks")
            if i == last_fail_step:
                retries += 1
            else:
                retries, last_fail_step = 1, i
            if retries > rcfg.max_retries:
                if rcfg.emergency_save:
                    # persist the last good state.  ``state`` may hold
                    # DONATED buffers (a dispatched attempt consumed them
                    # before failing) — emergency_save refuses those, and
                    # we then fall back to the undonated host mirror.
                    # Once a periodic checkpoint exists the mirror is
                    # gone, but so is the need: the disk copy IS the
                    # recovery point.
                    saved = ckpt.emergency_save(rcfg.checkpoint_dir, state)
                    if saved is None and safe_state is not None:
                        saved = ckpt.emergency_save(
                            rcfg.checkpoint_dir,
                            jax.device_put(safe_state, shardings))
                    if saved is not None:
                        metrics.count("emergency_saves")
                raise StepFailure(
                    f"step {i} failed {retries} times; last error: {e}"
                ) from e
            last = ckpt.latest_step(rcfg.checkpoint_dir)
            if last is not None:
                template = (jax.device_put(safe_state, shardings)
                            if safe_state is not None else abstract)
                try:
                    state = ckpt.restore(
                        rcfg.checkpoint_dir, template,
                        check_integrity=rcfg.verify_checkpoints)
                except ckpt.CheckpointCorruptionError as ce:
                    # NOTHING intact on disk.  The in-memory mirror (if
                    # it still exists) is the recovery point of last
                    # resort; otherwise this run is unrecoverable — keep
                    # the documented StepFailure contract rather than
                    # leaking the corruption error past the retry logic
                    if safe_state is not None:
                        state = jax.device_put(safe_state, shardings)
                    else:
                        if rcfg.emergency_save:
                            ckpt.emergency_save(rcfg.checkpoint_dir, state)
                        raise StepFailure(
                            f"step {i} failed and no intact checkpoint "
                            f"remains: {ce}") from ce
            else:
                state = jax.device_put(safe_state, shardings)
            i = int(state.step)
            metrics.count("restores")
            continue

        if i > last_fail_step:
            retries = 0
        state = new_state
        metrics.count("steps")
        metrics.times["step"].append(time.perf_counter() - t0)
        rec = scalar_metrics(m)
        if rec.get("grad_ok", 1.0) == 0.0:
            # tier-1 guard fired inside the step: the update was skipped
            # in-graph; surface it as a decision, not a failure
            metrics.count("grad_skips")
            metrics.decision("trainer.grad_skip", step=i,
                             grad_norm=rec.get("grad_norm"),
                             grad_norm_ema=rec.get("grad_norm_ema"))
        history.append(rec)
        i += 1
        if i % rcfg.checkpoint_every == 0 or i == num_steps:
            ckpt.save(rcfg.checkpoint_dir, state, step=i)
            safe_state = None  # durable copy exists; free the host mirror
            # prune the replay buffer one checkpoint BEHIND: a corrupted
            # newest checkpoint falls back to the previous intact one,
            # whose replay window must still be replayable bit-exact.
            # Bound: <= 2 * checkpoint_every buffered batches.
            if prev_ckpt_step is not None:
                replay.prune_before(prev_ckpt_step)
            prev_ckpt_step = i
            metrics.count("checkpoints")
    return state, history
