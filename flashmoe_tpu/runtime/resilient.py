"""Resilient training: failure detection + checkpoint-based recovery.

The reference has no failure story (SURVEY §5): its closest mechanism is
the sequence-bit protocol that tolerates *skipped* iterations
(``subscriber.cuh:104-137``) — a dead worker stalls the collective forever.
This module provides the framework-level equivalent capability and more:

  * **detection** — every step is bounded by a wall-clock deadline and its
    loss is checked finite; a hung collective, a device error (XLA raises),
    or a NaN/inf step all count as failures;
  * **recovery** — state restores from the latest *intact* orbax
    checkpoint (integrity-verified, tier 2 of docs/RESILIENCE.md) and
    training resumes; transient failures are retried up to a budget,
    repeated failures at the same step abort with a diagnosis (after a
    best-effort emergency save of the last good state);
  * **exact replay** — batches consumed since the last checkpoint are
    buffered, so a retried step re-trains on the SAME data the failed
    attempt saw (rewinding only the model, not the data stream, silently
    diverged the replayed run before this);
  * **path fallback** — a :class:`flashmoe_tpu.planner.select.PathFailure`
    escaping a step demotes the failed execution path for the rest of the
    process (``planner.fallback`` decision) before the retry;
  * **periodic checkpointing** — bounded loss-of-work window, optionally
    async (``ResilienceConfig.async_save``): the step loop pays only the
    host snapshot, the background writer pays serialize+fsync+rename;
  * **graceful drain** — a :class:`flashmoe_tpu.runtime.preempt.
    PreemptionListener` notice (SIGTERM on a preemptible pod) finishes
    the in-flight step, writes a final checkpoint + data-loader cursor,
    logs a ``preempt.drain`` decision, and returns cleanly instead of
    dying mid-write;
  * **deterministic data resume** — when ``data_iter`` is a stateful
    loader (``state_dict``/``load_state_dict``, e.g.
    :class:`flashmoe_tpu.runtime.data.TokenLoader`), its cursor is
    persisted in every checkpoint manifest and restored on resume, so
    the continued run consumes the exact token stream the dead run
    would have — no replayed and no skipped batch.

:func:`supervise` is the job-level outer loop (the in-process analogue
of the cluster scheduler): it restarts after drains and crashes,
re-folding parallelism to the surviving device count via
:func:`flashmoe_tpu.runtime.elastic.elastic_resume`.

Single-process recovery is fully testable (failures injected in tests and
by :mod:`flashmoe_tpu.chaos`); multi-host recovery composes with the
cluster scheduler restarting dead processes and every process restoring
from the shared checkpoint directory.
"""

from __future__ import annotations

import concurrent.futures as _fut
import dataclasses
import time
from typing import Callable, Iterator

import jax
import numpy as np

from flashmoe_tpu.runtime import checkpoint as ckpt
from flashmoe_tpu.runtime.trainer import TrainState
from flashmoe_tpu.utils.telemetry import Metrics


class StepFailure(RuntimeError):
    """Unrecoverable (in-job) training failure.  Instances raised by
    :func:`resilient_train` carry ``partial_history`` — the per-step
    metric records executed before the abort — so callers (the
    supervisor, postmortems) keep the dead run's loss curve instead of
    losing it with the raise.  (Set per instance at raise time; read
    with ``getattr(e, "partial_history", [])``.)"""

    partial_history: list


def _make_deadline_executor() -> _fut.ThreadPoolExecutor:
    """The single-worker executor backing the step deadline; a named
    seam so tests can count constructions (exactly one per run, plus
    one per abandoned timeout)."""
    return _fut.ThreadPoolExecutor(
        max_workers=1, thread_name_prefix="flashmoe-deadline")


@dataclasses.dataclass
class ResilienceConfig:
    checkpoint_dir: str = "/tmp/flashmoe_ckpt"
    checkpoint_every: int = 50
    step_timeout_s: float | None = None  # None = no deadline
    max_retries: int = 3
    # tier-2 hardening knobs (docs/RESILIENCE.md); defaults preserve the
    # strongest behavior — flip off only to reproduce legacy semantics
    verify_checkpoints: bool = True   # checksum-verify before restore
    emergency_save: bool = True       # persist last good state on abort
    # periodic saves off the step loop: the loop pays only the host
    # snapshot; drain/failure paths barrier on ckpt.wait_for_saves()
    async_save: bool = False
    # self-healing runtime controller (flashmoe_tpu/runtime/controller):
    # a ControllerConfig arms mid-job path morphing + expert
    # re-placement in supervise()/resilient_train.  Default None = off =
    # the exact pre-controller loop (bit-identical training).
    adapt: "object | None" = None


def _run_step(step_fn, state, batch, timeout_s, ex_box=None):
    """Execute one step, optionally under a wall-clock deadline.

    The deadline wraps the *blocking* result fetch — a hung device shows up
    as a timeout rather than an eternal stall (the failure detector the
    reference's collectives lack).

    ``ex_box`` is a one-slot list holding the caller's reusable
    ThreadPoolExecutor: one executor serves the whole run (the old
    executor-per-step spawned thousands of threads over a long healthy
    run) and is abandoned/replaced only after a timeout — its worker may
    be stuck in the very hang the deadline detected, so it can never be
    joined or reused.
    """
    if timeout_s is None:
        out = step_fn(state, batch)
        jax.block_until_ready(out)
        return out
    if ex_box is None:
        ex_box = [None]
    if ex_box[0] is None:
        ex_box[0] = _make_deadline_executor()
    f = ex_box[0].submit(lambda: jax.block_until_ready(step_fn(state, batch)))
    try:
        return f.result(timeout=timeout_s)
    except _fut.TimeoutError as e:
        # wait=False: a worker genuinely stuck in a hung collective must be
        # abandoned, not joined — shutdown(wait=True) would re-stall the
        # caller on the very hang the deadline just detected.
        ex, ex_box[0] = ex_box[0], None
        ex.shutdown(wait=False)
        raise StepFailure(f"step exceeded {timeout_s}s deadline") from e


def scalar_metrics(m: dict) -> dict:
    """History-safe view of a step's metrics: scalars to floats,
    non-scalars (e.g. per-expert MoEStats arrays when collect_stats is
    on) skipped — ``float(v)`` on an [E]-shaped array raised mid-recovery
    before this guard existed."""
    out = {}
    for k, v in m.items():
        try:
            if np.asarray(v).size == 1:
                out[k] = float(np.asarray(v).reshape(()))
        except (TypeError, ValueError):
            continue
    return out


def _step_loss(m: dict) -> float | None:
    """The step's scalar loss, or None when absent/non-scalar — a custom
    step_fn without a 'loss' key must not KeyError the recovery loop."""
    v = m.get("loss")
    if v is None:
        return None
    try:
        a = np.asarray(v)
        return float(a.reshape(())) if a.size == 1 else None
    except (TypeError, ValueError):
        return None


class _ReplayBuffer:
    """Batches consumed since the last durable checkpoint, keyed by step.

    On rewind, steps re-execute against the EXACT batch the failed
    attempt consumed instead of silently pulling fresh data (the replay-
    divergence bug: retried steps trained on different batches than the
    history claimed).  Memory is bounded by ``2 * checkpoint_every``
    batches: pruning lags one checkpoint so a corruption-fallback
    restore to the PREVIOUS intact checkpoint still replays bit-exact.

    When the iterator is a stateful loader, the loader's cursor is
    snapshotted BEFORE each fresh pull: ``loader_state_for(k)`` is then
    the exact position a new process needs to resume at step ``k`` —
    the loop may have pulled batches past a rewound checkpoint step, so
    the loader's *current* cursor is not generally the right answer.
    """

    def __init__(self, data_iter: Iterator):
        self._it = data_iter
        self._stateful = (hasattr(data_iter, "state_dict")
                          and hasattr(data_iter, "load_state_dict"))
        self._buf: dict[int, object] = {}
        self._states: dict[int, dict] = {}

    @property
    def stateful(self) -> bool:
        return self._stateful

    def batch_for(self, step: int):
        b = self._buf.get(step)
        if b is None:
            if self._stateful and step not in self._states:
                self._states[step] = self._it.state_dict()
            b = next(self._it)
            self._buf[step] = b
        return b

    def loader_state_for(self, step: int) -> dict | None:
        """The loader cursor positioned so the next pull is batch
        ``step``: the pre-pull snapshot when that batch was consumed,
        else the live cursor (batch ``step`` not pulled yet — the
        checkpoint-boundary case, where pulls == step exactly)."""
        if not self._stateful:
            return None
        st = self._states.get(step)
        return dict(st) if st is not None else self._it.state_dict()

    def prune_before(self, step: int):
        for s in [s for s in self._buf if s < step]:
            del self._buf[s]
            self._states.pop(s, None)

    def __len__(self):
        return len(self._buf)


def resilient_train(state: TrainState, step_fn: Callable,
                    data_iter: Iterator, num_steps: int,
                    rcfg: ResilienceConfig | None = None,
                    metrics: Metrics | None = None,
                    fail_injector: Callable | None = None,
                    preempt=None, slo=None,
                    postmortem_dir: str | None = None, cfg=None,
                    controller=None, rebuild_step: Callable | None = None,
                    telemetry_port: int | None = None):
    """Run ``num_steps`` with detection + restore-and-retry recovery.

    ``step_fn(state, batch) -> (state, metrics_dict)`` — e.g. from
    :func:`flashmoe_tpu.runtime.trainer.make_train_step`.
    ``fail_injector(step_idx)`` may raise, for tests/chaos drills
    (:func:`flashmoe_tpu.chaos.make_injector`).
    ``preempt``: a :class:`flashmoe_tpu.runtime.preempt.
    PreemptionListener`; its flag is polled once per step, and a notice
    drains gracefully — final checkpoint + loader cursor, then a clean
    return with ``state.step < num_steps`` (the supervisor/scheduler
    resumes from exactly there).

    When ``data_iter`` carries ``state_dict``/``load_state_dict`` (a
    :class:`flashmoe_tpu.runtime.data.TokenLoader`), its cursor rides
    every checkpoint manifest and is restored on resume — the continued
    run consumes the exact token stream of an uninterrupted one.

    ``controller``: a :class:`flashmoe_tpu.runtime.controller.
    RuntimeController` closes the telemetry loop — it observes every
    successful step and may, at a step boundary, morph the execution
    path or re-place experts (docs/RESILIENCE.md "Self-healing
    controller").  ``rebuild_step(overrides) -> step_fn`` rebuilds the
    jitted step with the controller's accumulated
    ``MoEConfig.replace`` overrides applied (``supervise`` provides
    one automatically); without it, actions that need a re-jit are
    not offered.  Every controller action forces an immediate
    checkpoint whose manifest carries the controller plan, so restores
    and restarts resume the layout the params were written under.

    ``slo``: an :class:`flashmoe_tpu.profiler.slo.SLOConfig` / prebuilt
    watchdog — every successful step's wall time is judged against the
    step budget (``slo.breach`` decisions; sustained breaches escalate
    into planner path demotion).  ``postmortem_dir``: when in-job
    recovery gives up (the :class:`StepFailure` raise), a crash
    postmortem bundle (:mod:`flashmoe_tpu.profiler.postmortem`) is
    written there — flight history, decisions, config (``cfg`` when
    provided), env, traceback — for
    ``python -m flashmoe_tpu.observe --postmortem``.  In-job recoveries
    and graceful drains never write one: a bundle means a death.

    Returns (state, history).  Raises :class:`StepFailure` after
    ``max_retries`` consecutive failures on one step (after a best-effort
    emergency checkpoint of the last good state).
    """
    from flashmoe_tpu.profiler import spans as prof
    from flashmoe_tpu.runtime.trainer import _as_watchdog

    rcfg = rcfg or ResilienceConfig()
    metrics = metrics or Metrics()
    watchdog = _as_watchdog(slo)
    history = []
    # live scrape plane (telemetry_plane/server.py): /healthz carries
    # the step, SLO episode, controller budgets, and the last DURABLE
    # checkpoint step — default off = no thread, bit-identical loop
    progress = {"step": None}
    server = None
    if telemetry_port is not None:
        from flashmoe_tpu.runtime.telemetry_hooks import train_server

        server = train_server(
            telemetry_port, cfg, num_steps=num_steps, progress=progress,
            watchdog=watchdog, controller=controller,
            checkpoint_dir=rcfg.checkpoint_dir, metrics_obj=metrics)

    def _ctrl_state():
        return controller.state_dict() if controller is not None else None

    def _ctrl_resync(step: int):
        # a restore landed on some step's params: the controller plan
        # (morph overrides, replica map) must be the one THOSE params
        # were saved under, and the step must be rebuilt onto it — a
        # replica routing map without its weight copies corrupts the
        # model (budgets stay monotonic; a rewind never refills them)
        nonlocal step_fn
        if controller is None:
            return
        cs = ckpt.load_controller_state(rcfg.checkpoint_dir, step)
        before = controller.cfg_overrides
        controller.load_state_dict(cs or {})
        if rebuild_step is not None \
                and controller.cfg_overrides != before:
            step_fn = rebuild_step(controller.cfg_overrides)

    # resume if a checkpoint exists
    start = ckpt.latest_step(rcfg.checkpoint_dir)
    if start is not None and start > int(state.step):
        state = ckpt.restore(rcfg.checkpoint_dir, state,
                             check_integrity=rcfg.verify_checkpoints)
        metrics.count("resumes")
        _ctrl_resync(int(state.step))
        # the restore may have FALLEN BACK to an older intact step:
        # position the loader for the step actually restored
        if ckpt.restore_loader_state(rcfg.checkpoint_dir,
                                     int(state.step), data_iter):
            metrics.count("loader_restores")

    i = int(state.step)
    retries = 0
    # retries are counted against the step that failed, not reset by any
    # success: recovery may rewind to an earlier step that succeeds again,
    # and that must not refill the budget for a deterministically failing
    # later step (it would livelock)
    last_fail_step = -1
    # In-memory recovery point for failures BEFORE the first checkpoint
    # exists: the jitted step donates its input state (trainer.py
    # donate_argnums), so a post-dispatch failure can leave ``state`` with
    # deleted buffers — retrying needs an undonated copy.  Dropped once a
    # checkpoint is on disk (holding a full host copy of params+moments
    # for the whole run would cost host RAM for nothing): restores then
    # use an abstract shape/dtype/sharding template instead.
    shardings = jax.tree_util.tree_map(
        lambda x: getattr(x, "sharding", None), state)
    abstract = jax.tree_util.tree_map(
        lambda x, sh: jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=sh
        ) if hasattr(x, "shape") else x,
        state, shardings,
    )
    safe_state = jax.device_get(state)
    replay = _ReplayBuffer(data_iter)
    # checkpoint boundary steps saved so far, ascending; pruning is
    # gated on the DURABLE frontier, not on enqueue (see below)
    ckpt_boundaries: list[int] = []
    # one deadline executor per run, replaced only after a timeout
    # (satellite fix: the old executor-per-step leaked a thread per step)
    ex_box: list = [None]
    try:
        while i < num_steps:
            progress["step"] = i
            if preempt is not None and preempt.requested:
                # graceful drain: the in-flight step already finished
                # (the flag is polled between steps); make everything
                # durable and hand control back before the hard kill
                with prof.section("train.drain", step=i):
                    ckpt.wait_for_saves()
                    if ckpt.latest_step(rcfg.checkpoint_dir) != i:
                        ckpt.save(rcfg.checkpoint_dir, state, step=i,
                                  loader_state=replay.loader_state_for(i),
                                  controller_state=_ctrl_state())
                        metrics.count("checkpoints")
                metrics.count("preempt_drains")
                metrics.decision(
                    "preempt.drain", step=i, source=preempt.source,
                    remaining_grace_s=preempt.remaining_grace_s())
                return state, history
            # replay-exact data: a rewound step gets the batch its failed
            # attempt consumed, not the iterator's next fresh one
            with prof.section("train.data_pull", step=i):
                batch = replay.batch_for(i)
            try:
                if fail_injector is not None:
                    fail_injector(i)
                t0 = time.perf_counter()
                tl = prof.active()
                if tl is not None:
                    # armed timeline: per-step record; any eager fenced
                    # phases measured inside feed the SLO phase budgets
                    tl.begin_step(i)
                with prof.section("train.step", step=i):
                    new_state, m = _run_step(step_fn, state, batch,
                                             rcfg.step_timeout_s, ex_box)
                step_phases = (tl.end_step()["phases"]
                               if tl is not None else None)
                loss = _step_loss(m)
                if loss is not None and not np.isfinite(loss):
                    raise StepFailure(
                        f"non-finite loss at step {i}: {loss}")
            except Exception as e:  # timeout, NaN, device error, injected
                metrics.count("failures")
                from flashmoe_tpu.planner.select import (
                    PathFailure, report_path_failure,
                )

                if isinstance(e, PathFailure):
                    # tier-2 path fallback: demote the failed execution
                    # path BEFORE retrying, so the retry re-resolves onto
                    # a healthy one instead of re-tracing the failure
                    report_path_failure(e.backend, str(e))
                    metrics.count("path_fallbacks")
                # an async save may still be in flight: it must land
                # before latest_step decides where recovery restores from
                ckpt.wait_for_saves()
                if i == last_fail_step:
                    retries += 1
                else:
                    retries, last_fail_step = 1, i
                if retries > rcfg.max_retries:
                    if rcfg.emergency_save:
                        # persist the last good state.  ``state`` may
                        # hold DONATED buffers (a dispatched attempt
                        # consumed them before failing) — emergency_save
                        # refuses those, and we then fall back to the
                        # undonated host mirror.  Once a periodic
                        # checkpoint exists the mirror is gone, but so is
                        # the need: the disk copy IS the recovery point.
                        lstate = replay.loader_state_for(i)
                        saved = ckpt.emergency_save(
                            rcfg.checkpoint_dir, state,
                            loader_state=lstate,
                            controller_state=_ctrl_state())
                        if saved is None and safe_state is not None:
                            saved = ckpt.emergency_save(
                                rcfg.checkpoint_dir,
                                jax.device_put(safe_state, shardings),
                                loader_state=lstate,
                                controller_state=_ctrl_state())
                        if saved is not None:
                            metrics.count("emergency_saves")
                    raise StepFailure(
                        f"step {i} failed {retries} times; "
                        f"last error: {e}"
                    ) from e
                last = ckpt.latest_step(rcfg.checkpoint_dir)
                if last is not None:
                    template = (jax.device_put(safe_state, shardings)
                                if safe_state is not None else abstract)
                    try:
                        state = ckpt.restore(
                            rcfg.checkpoint_dir, template,
                            check_integrity=rcfg.verify_checkpoints)
                        _ctrl_resync(int(state.step))
                    except ckpt.CheckpointCorruptionError as ce:
                        # NOTHING intact on disk.  The in-memory mirror
                        # (if it still exists) is the recovery point of
                        # last resort; otherwise this run is
                        # unrecoverable — keep the documented StepFailure
                        # contract rather than leaking the corruption
                        # error past the retry logic
                        if safe_state is not None:
                            state = jax.device_put(safe_state, shardings)
                        else:
                            if rcfg.emergency_save:
                                ckpt.emergency_save(
                                    rcfg.checkpoint_dir, state,
                                    loader_state=replay.loader_state_for(i))
                            raise StepFailure(
                                f"step {i} failed and no intact "
                                f"checkpoint remains: {ce}") from ce
                else:
                    state = jax.device_put(safe_state, shardings)
                i = int(state.step)
                metrics.count("restores")
                continue

            if i > last_fail_step:
                retries = 0
            state = new_state
            metrics.count("steps")
            step_s = time.perf_counter() - t0
            metrics.times["step"].append(step_s)
            if watchdog is not None:
                # SLO watchdog: sustained step-budget breaches escalate
                # into planner path demotion (slo.breach decisions)
                watchdog.observe_step(i, step_s * 1e3,
                                      phases=step_phases)
            if controller is not None:
                controller.observe_step(i, step_s * 1e3, m)
            rec = scalar_metrics(m)
            if rec.get("grad_ok", 1.0) == 0.0:
                # tier-1 guard fired inside the step: the update was
                # skipped in-graph; surface it as a decision, not a
                # failure
                metrics.count("grad_skips")
                metrics.decision("trainer.grad_skip", step=i,
                                 grad_norm=rec.get("grad_norm"),
                                 grad_norm_ema=rec.get("grad_norm_ema"))
            history.append(rec)
            i += 1
            force_ckpt = False
            if controller is not None:
                # the self-healing decision point: a morph rebuilds the
                # step onto the controller's accumulated overrides; a
                # re-placement permutes the live state (and, with a
                # replica, also rebuilds).  Either way the action is
                # made durable IMMEDIATELY: the next restore must see
                # params and plan from the same side of the action.
                act = controller.maybe_act(
                    i, can_rebuild=rebuild_step is not None)
                if act is not None:
                    state = controller.apply_action(act, state)
                    if act.needs_rebuild and rebuild_step is not None:
                        step_fn = rebuild_step(controller.cfg_overrides)
                    force_ckpt = True
            if i % rcfg.checkpoint_every == 0 or i == num_steps \
                    or force_ckpt:
                with prof.section("train.checkpoint", step=i):
                    ckpt.save(rcfg.checkpoint_dir, state, step=i,
                              blocking=(not rcfg.async_save
                                        or force_ckpt),
                              loader_state=replay.loader_state_for(i),
                              controller_state=_ctrl_state())
                ckpt_boundaries.append(i)
                durable = ckpt.latest_step(rcfg.checkpoint_dir)
                # free the host mirror only once a checkpoint is DURABLE
                # — an enqueued async save is a promise, not a recovery
                # point (the writer may still fail on it)
                if safe_state is not None and durable is not None:
                    safe_state = None
                # prune the replay buffer one checkpoint BEHIND the
                # newest DURABLE boundary: a corrupted newest checkpoint
                # falls back to the previous intact one, whose replay
                # window must still be replayable bit-exact — and an
                # ASYNC save is not durable at enqueue (the writer may
                # drop it newest-wins or fail on it), so pruning keyed
                # on enqueue could strand a restore behind the buffer.
                # Bound: <= 2 * checkpoint_every batches once writes
                # land (sync saves land immediately, keeping the old
                # behavior exactly).
                confirmed = [b for b in ckpt_boundaries
                             if durable is not None and b <= durable]
                if len(confirmed) >= 2:
                    replay.prune_before(confirmed[-2])
                    ckpt_boundaries = [b for b in ckpt_boundaries
                                       if b >= confirmed[-2]]
                metrics.count("checkpoints")
        if rcfg.async_save:
            # the run is over: the final enqueued save must LAND before
            # the caller reads latest_step or tears the process down
            ckpt.wait_for_saves()
        return state, history
    except StepFailure as e:
        # the steps executed before the abort are real training history
        # (their losses/grad norms are the postmortem); hand them to the
        # caller on the exception instead of dropping them
        e.partial_history = list(history)
        if postmortem_dir:
            # in-job recovery gave up — the real process would be dead.
            # Freeze everything a triage needs into a bundle dir (best-
            # effort: the writer never masks the failure it documents).
            from flashmoe_tpu.profiler import postmortem as pm

            bundle = pm.write_bundle(
                postmortem_dir, error=e, cfg=cfg, metrics_obj=metrics,
                history=history, step=i,
                extra={"retries": retries, "num_steps": num_steps})
            if bundle is not None:
                e.postmortem_bundle = bundle
        raise
    finally:
        if server is not None:
            server.stop()
        if ex_box[0] is not None:
            ex_box[0].shutdown(wait=False)


def supervise(cfg, data_factory: Callable, num_steps: int,
              rcfg: ResilienceConfig | None = None, *,
              guard=None, metrics: Metrics | None = None,
              preempt=None, devices_fn: Callable | None = None,
              max_restarts: int = 3, fail_injector: Callable | None = None,
              step_wrapper: Callable | None = None, seed: int = 0,
              use_pallas: bool | None = None, slo=None,
              postmortem_dir: str | None = None, controller=None,
              telemetry_port: int | None = None):
    """Job-level restart loop: run to ``num_steps`` across preemptions,
    crashes, and world-size changes.

    The in-process analogue of the cluster scheduler: each *incarnation*
    sizes itself to the CURRENT device set (``devices_fn()`` or
    ``jax.devices()``), restores the newest checkpoint resharded onto the
    surviving devices (:func:`flashmoe_tpu.runtime.elastic.
    elastic_resume` — parallelism re-folds, a ``supervisor.resume``
    decision records the new world), repositions a fresh data loader
    from the manifest cursor, and continues under
    :func:`resilient_train`.

    A graceful preemption drain ends an incarnation cleanly (the notice
    is cleared — "the scheduler restarted us"); a :class:`StepFailure`
    (in-job recovery exhausted — "the process died") consumes one of
    ``max_restarts`` restarts.  ``data_factory(cfg) -> iterator`` builds
    each incarnation's loader; make it a stateful
    :class:`flashmoe_tpu.runtime.data.TokenLoader` for deterministic
    data resume.  ``step_wrapper`` wraps the jitted step (chaos stalls).
    ``slo`` / ``postmortem_dir`` ride through to
    :func:`resilient_train`; additionally every SUPERVISOR-level death
    (incarnation-budget exhaustion, refusing-to-spin) writes its own
    postmortem bundle — a clean drain or a successful restart does not.

    ``controller``: a prebuilt :class:`flashmoe_tpu.runtime.controller.
    RuntimeController` (or arm one via ``rcfg.adapt`` = a
    :class:`~flashmoe_tpu.runtime.controller.ControllerConfig`).  The
    supervisor owns its lifecycle across incarnations: each restart
    restores the controller plan from the resumed checkpoint's
    manifest, applies its accumulated config overrides before building
    the step, and hands :func:`resilient_train` a rebuild closure so
    mid-job morphs/re-placements can re-jit.  Each restart onto a
    (possibly re-folded) topology also clears the process-level path
    blacklist (``controller.demotion_reset``): a demotion earned on a
    dead topology must not outlive it.

    Returns (state, history) with history concatenated over
    incarnations (re-run steps appear once per execution, like
    :func:`resilient_train`).
    """
    import jax.random as _random

    from flashmoe_tpu.parallel.mesh import make_mesh
    from flashmoe_tpu.runtime.elastic import elastic_resume, fold_parallelism
    from flashmoe_tpu.runtime.trainer import (
        init_state, make_optimizer, make_train_step, state_shardings,
    )

    rcfg = rcfg or ResilienceConfig()
    metrics = metrics or Metrics()
    # a controller built from rcfg.adapt is OWNED by the supervisor:
    # it is re-targeted to every incarnation's folded topology below
    # (a prebuilt `controller=` is the caller's responsibility)
    own_controller = controller is None and rcfg.adapt is not None
    history: list = []
    restarts = 0
    incarnation = 0
    # one long-lived scrape server for the whole supervised job: the
    # box re-points it at each incarnation's folded cfg/controller, so
    # /healthz answers across restarts instead of churning ports.  The
    # watchdog is built HERE (one episode state across incarnations)
    # and handed down, so /healthz carries SLO state and the metrics
    # `steps` counter gives live step progress.
    from flashmoe_tpu.runtime.trainer import _as_watchdog

    watchdog = _as_watchdog(slo)
    tbox: dict = {"phase": "supervise",
                  "checkpoint_dir": rcfg.checkpoint_dir,
                  "watchdog": watchdog}
    tserver = None
    if telemetry_port is not None:
        from flashmoe_tpu.runtime.telemetry_hooks import train_server

        tserver = train_server(
            telemetry_port, cfg, num_steps=num_steps,
            metrics_obj=metrics, box=tbox,
            extra_health=lambda: {
                "steps_done": int(metrics.counters.get("steps", 0))})
    # drains don't consume the restart budget, but a notice source stuck
    # on "always preempted" must not loop forever either
    max_incarnations = max(8, 4 * (max_restarts + 1))
    def _bundle(err):
        if postmortem_dir:
            from flashmoe_tpu.profiler import postmortem as pm

            pm.write_bundle(postmortem_dir, error=err, cfg=cfg,
                            metrics_obj=metrics, history=history,
                            extra={"incarnation": incarnation,
                                   "restarts": restarts})

    try:
        while True:
            if incarnation >= max_incarnations:
                e = StepFailure(
                    f"supervisor exceeded {max_incarnations} incarnations "
                    f"without reaching step {num_steps}")
                _bundle(e)
                raise e
            devices = list(devices_fn() if devices_fn is not None
                           else jax.devices())
            resumed_step = None
            if ckpt.latest_step(rcfg.checkpoint_dir) is not None:
                state, mesh, fcfg, opt = elastic_resume(
                    cfg, rcfg.checkpoint_dir, devices=devices, guard=guard,
                    total_steps=num_steps)
                metrics.decision(
                    "supervisor.resume", incarnation=incarnation,
                    step=int(state.step), world=len(devices),
                    ep=fcfg.ep, dp=fcfg.dp)
                # an incarnation resumes on a fresh (possibly re-folded)
                # topology: path demotions earned by the DEAD incarnation
                # describe hardware/paths that may no longer exist — clear
                # the process blacklist so the planner re-evaluates every
                # path against the surviving world
                from flashmoe_tpu.planner.select import (
                    failed_backends, reset_path_failures,
                )

                stale = sorted(failed_backends())
                if stale:
                    reset_path_failures()
                    metrics.decision(
                        "controller.demotion_reset",
                        incarnation=incarnation, world=len(devices),
                        ep=fcfg.ep, dp=fcfg.dp, dropped=stale)
                resumed_step = int(state.step)
            else:
                fcfg = fold_parallelism(cfg, len(devices))
                mesh = make_mesh(fcfg, devices=devices)
                opt = make_optimizer(fcfg, total_steps=num_steps)
                state = init_state(_random.PRNGKey(seed), fcfg, opt,
                                   guard=guard)
                state = jax.device_put(state,
                                       state_shardings(state, fcfg, mesh))
            if own_controller:
                # re-target the controller to THIS incarnation's folded
                # topology: placement math (n_devices, slot -> device) and
                # morph re-selection (d, the folded cfg) must describe the
                # world that is actually running, not the one that died.
                # Spent budgets and the accumulated plan carry over (slot
                # ids are expert ids — independent of the device count);
                # the manifest restore below then pins the plan to the
                # params actually resumed.
                from flashmoe_tpu.runtime.controller import RuntimeController

                prev = controller
                controller = RuntimeController(fcfg, rcfg.adapt,
                                               metrics=metrics)
                if prev is not None:
                    controller.load_state_dict(prev.state_dict())
            if controller is not None and resumed_step is not None:
                cs = ckpt.load_controller_state(rcfg.checkpoint_dir,
                                                resumed_step)
                controller.load_state_dict(cs or {})
            data = data_factory(fcfg)
            if ckpt.restore_loader_state(rcfg.checkpoint_dir,
                                         int(state.step), data):
                metrics.count("loader_restores")

            def _build_step(overrides: dict, _fcfg=fcfg, _mesh=mesh,
                            _opt=opt):
                scfg = _fcfg.replace(**overrides) if overrides else _fcfg
                sf = make_train_step(scfg, _mesh, _opt,
                                     use_pallas=use_pallas, guard=guard)
                return step_wrapper(sf) if step_wrapper is not None else sf

            step_fn = _build_step(
                controller.cfg_overrides if controller is not None else {})
            # re-point the long-lived scrape server at THIS
            # incarnation's folded world (no port churn on restart)
            tbox.update(cfg=fcfg, mesh=mesh, controller=controller,
                        health={"incarnation": incarnation,
                                "restarts": restarts,
                                "world": len(devices)})
            incarnation += 1
            try:
                state, hist = resilient_train(
                    state, step_fn, data, num_steps, rcfg=rcfg,
                    metrics=metrics, fail_injector=fail_injector,
                    preempt=preempt, slo=watchdog,
                    postmortem_dir=postmortem_dir,
                    cfg=fcfg, controller=controller,
                    rebuild_step=_build_step)
                history.extend(hist)
            except StepFailure as e:
                # in-job recovery exhausted: the real process would be dead.
                # The scheduler restarts it — here, the next loop iteration —
                # against whatever checkpoint the drain/emergency paths left.
                # The dead incarnation's executed steps stay in the history.
                history.extend(getattr(e, "partial_history", []))
                restarts += 1
                metrics.count("supervisor_restarts")
                if restarts > max_restarts:
                    e.partial_history = list(history)
                    raise
                continue
            if int(state.step) >= num_steps:
                return state, history
            if preempt is not None and preempt.requested:
                # drained on a preemption notice: this incarnation is over;
                # clear the latch and "restart" with the current device set
                preempt.clear()
                metrics.count("preempt_restarts")
                continue
            e = StepFailure(
                f"incarnation ended at step {int(state.step)} of {num_steps} "
                f"with no drain and no failure — refusing to spin")
            _bundle(e)
            raise e
    finally:
        if tserver is not None:
            tserver.stop()
