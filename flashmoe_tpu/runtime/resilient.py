"""Resilient training: failure detection + checkpoint-based recovery.

The reference has no failure story (SURVEY §5): its closest mechanism is
the sequence-bit protocol that tolerates *skipped* iterations
(``subscriber.cuh:104-137``) — a dead worker stalls the collective forever.
This module provides the framework-level equivalent capability and more:

  * **detection** — every step is bounded by a wall-clock deadline and its
    loss is checked finite; a hung collective, a device error (XLA raises),
    or a NaN/inf step all count as failures;
  * **recovery** — state restores from the latest orbax checkpoint and
    training resumes; transient failures are retried up to a budget,
    repeated failures at the same step abort with a diagnosis;
  * **periodic checkpointing** — bounded loss-of-work window.

Single-process recovery is fully testable (failures injected in tests);
multi-host recovery composes with the cluster scheduler restarting dead
processes and every process restoring from the shared checkpoint directory.
"""

from __future__ import annotations

import concurrent.futures as _fut
import dataclasses
import time
from typing import Callable, Iterator

import jax
import numpy as np

from flashmoe_tpu.runtime import checkpoint as ckpt
from flashmoe_tpu.runtime.trainer import TrainState
from flashmoe_tpu.utils.telemetry import Metrics


class StepFailure(RuntimeError):
    pass


@dataclasses.dataclass
class ResilienceConfig:
    checkpoint_dir: str = "/tmp/flashmoe_ckpt"
    checkpoint_every: int = 50
    step_timeout_s: float | None = None  # None = no deadline
    max_retries: int = 3


def _run_step(step_fn, state, batch, timeout_s):
    """Execute one step, optionally under a wall-clock deadline.

    The deadline wraps the *blocking* result fetch — a hung device shows up
    as a timeout rather than an eternal stall (the failure detector the
    reference's collectives lack).
    """
    if timeout_s is None:
        out = step_fn(state, batch)
        jax.block_until_ready(out)
        return out
    ex = _fut.ThreadPoolExecutor(max_workers=1)
    f = ex.submit(lambda: jax.block_until_ready(step_fn(state, batch)))
    try:
        return f.result(timeout=timeout_s)
    except _fut.TimeoutError as e:
        raise StepFailure(f"step exceeded {timeout_s}s deadline") from e
    finally:
        # wait=False: a worker genuinely stuck in a hung collective must be
        # abandoned, not joined — shutdown(wait=True) would re-stall the
        # caller on the very hang the deadline just detected.
        ex.shutdown(wait=False)


def resilient_train(state: TrainState, step_fn: Callable,
                    data_iter: Iterator, num_steps: int,
                    rcfg: ResilienceConfig | None = None,
                    metrics: Metrics | None = None,
                    fail_injector: Callable | None = None):
    """Run ``num_steps`` with detection + restore-and-retry recovery.

    ``step_fn(state, batch) -> (state, metrics_dict)`` — e.g. from
    :func:`flashmoe_tpu.runtime.trainer.make_train_step`.
    ``fail_injector(step_idx)`` may raise, for tests/chaos drills.

    Returns (state, history).  Raises :class:`StepFailure` after
    ``max_retries`` consecutive failures on one step.
    """
    rcfg = rcfg or ResilienceConfig()
    metrics = metrics or Metrics()
    history = []

    # resume if a checkpoint exists
    start = ckpt.latest_step(rcfg.checkpoint_dir)
    if start is not None and start > int(state.step):
        state = ckpt.restore(rcfg.checkpoint_dir, state)
        metrics.count("resumes")

    i = int(state.step)
    retries = 0
    # retries are counted against the step that failed, not reset by any
    # success: recovery may rewind to an earlier step that succeeds again,
    # and that must not refill the budget for a deterministically failing
    # later step (it would livelock)
    last_fail_step = -1
    # In-memory recovery point for failures BEFORE the first checkpoint
    # exists: the jitted step donates its input state (trainer.py
    # donate_argnums), so a post-dispatch failure can leave ``state`` with
    # deleted buffers — retrying needs an undonated copy.  Dropped once a
    # checkpoint is on disk (holding a full host copy of params+moments
    # for the whole run would cost host RAM for nothing): restores then
    # use an abstract shape/dtype/sharding template instead.
    shardings = jax.tree_util.tree_map(
        lambda x: getattr(x, "sharding", None), state)
    abstract = jax.tree_util.tree_map(
        lambda x, sh: jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=sh
        ) if hasattr(x, "shape") else x,
        state, shardings,
    )
    safe_state = jax.device_get(state)
    while i < num_steps:
        batch = next(data_iter)
        try:
            if fail_injector is not None:
                fail_injector(i)
            t0 = time.perf_counter()
            new_state, m = _run_step(step_fn, state, batch,
                                     rcfg.step_timeout_s)
            loss = float(m["loss"])
            if not np.isfinite(loss):
                raise StepFailure(f"non-finite loss at step {i}: {loss}")
        except Exception as e:  # timeout, NaN, device error, injected fault
            metrics.count("failures")
            if i == last_fail_step:
                retries += 1
            else:
                retries, last_fail_step = 1, i
            if retries > rcfg.max_retries:
                raise StepFailure(
                    f"step {i} failed {retries} times; last error: {e}"
                ) from e
            last = ckpt.latest_step(rcfg.checkpoint_dir)
            if last is not None:
                template = (jax.device_put(safe_state, shardings)
                            if safe_state is not None else abstract)
                state = ckpt.restore(rcfg.checkpoint_dir, template)
            else:
                state = jax.device_put(safe_state, shardings)
            i = int(state.step)
            metrics.count("restores")
            continue

        if i > last_fail_step:
            retries = 0
        state = new_state
        metrics.count("steps")
        metrics.times["step"].append(time.perf_counter() - t0)
        history.append({k: float(v) for k, v in m.items()})
        i += 1
        if i % rcfg.checkpoint_every == 0 or i == num_steps:
            ckpt.save(rcfg.checkpoint_dir, state, step=i)
            safe_state = None  # durable copy exists; free the host mirror
            metrics.count("checkpoints")
    return state, history
