"""Resilient training: failure detection + checkpoint-based recovery.

The reference has no failure story (SURVEY §5): its closest mechanism is
the sequence-bit protocol that tolerates *skipped* iterations
(``subscriber.cuh:104-137``) — a dead worker stalls the collective forever.
This module provides the framework-level equivalent capability and more:

  * **detection** — every step is bounded by a wall-clock deadline and its
    loss is checked finite; a hung collective, a device error (XLA raises),
    or a NaN/inf step all count as failures;
  * **recovery** — state restores from the latest orbax checkpoint and
    training resumes; transient failures are retried up to a budget,
    repeated failures at the same step abort with a diagnosis;
  * **periodic checkpointing** — bounded loss-of-work window.

Single-process recovery is fully testable (failures injected in tests);
multi-host recovery composes with the cluster scheduler restarting dead
processes and every process restoring from the shared checkpoint directory.
"""

from __future__ import annotations

import concurrent.futures as _fut
import dataclasses
import time
from typing import Callable, Iterator

import jax
import numpy as np

from flashmoe_tpu.runtime import checkpoint as ckpt
from flashmoe_tpu.runtime.trainer import TrainState
from flashmoe_tpu.utils.telemetry import Metrics


class StepFailure(RuntimeError):
    pass


@dataclasses.dataclass
class ResilienceConfig:
    checkpoint_dir: str = "/tmp/flashmoe_ckpt"
    checkpoint_every: int = 50
    step_timeout_s: float | None = None  # None = no deadline
    max_retries: int = 3


def _run_step(step_fn, state, batch, timeout_s):
    """Execute one step, optionally under a wall-clock deadline.

    The deadline wraps the *blocking* result fetch — a hung device shows up
    as a timeout rather than an eternal stall (the failure detector the
    reference's collectives lack).
    """
    if timeout_s is None:
        out = step_fn(state, batch)
        jax.block_until_ready(out)
        return out
    with _fut.ThreadPoolExecutor(max_workers=1) as ex:
        f = ex.submit(lambda: jax.block_until_ready(step_fn(state, batch)))
        try:
            return f.result(timeout=timeout_s)
        except _fut.TimeoutError as e:
            raise StepFailure(f"step exceeded {timeout_s}s deadline") from e


def resilient_train(state: TrainState, step_fn: Callable,
                    data_iter: Iterator, num_steps: int,
                    rcfg: ResilienceConfig | None = None,
                    metrics: Metrics | None = None,
                    fail_injector: Callable | None = None):
    """Run ``num_steps`` with detection + restore-and-retry recovery.

    ``step_fn(state, batch) -> (state, metrics_dict)`` — e.g. from
    :func:`flashmoe_tpu.runtime.trainer.make_train_step`.
    ``fail_injector(step_idx)`` may raise, for tests/chaos drills.

    Returns (state, history).  Raises :class:`StepFailure` after
    ``max_retries`` consecutive failures on one step.
    """
    rcfg = rcfg or ResilienceConfig()
    metrics = metrics or Metrics()
    history = []

    # resume if a checkpoint exists
    start = ckpt.latest_step(rcfg.checkpoint_dir)
    if start is not None and start > int(state.step):
        state = ckpt.restore(rcfg.checkpoint_dir, state)
        metrics.count("resumes")

    i = int(state.step)
    retries = 0
    while i < num_steps:
        batch = next(data_iter)
        try:
            if fail_injector is not None:
                fail_injector(i)
            t0 = time.perf_counter()
            new_state, m = _run_step(step_fn, state, batch,
                                     rcfg.step_timeout_s)
            loss = float(m["loss"])
            if not np.isfinite(loss):
                raise StepFailure(f"non-finite loss at step {i}: {loss}")
        except StepFailure:
            raise
        except Exception as e:  # device error, injected fault, ...
            metrics.count("failures")
            retries += 1
            if retries > rcfg.max_retries:
                raise StepFailure(
                    f"step {i} failed {retries} times; last error: {e}"
                ) from e
            last = ckpt.latest_step(rcfg.checkpoint_dir)
            if last is not None:
                state = ckpt.restore(rcfg.checkpoint_dir, state)
                i = int(state.step)
                metrics.count("restores")
            continue

        retries = 0
        state = new_state
        metrics.count("steps")
        metrics.times["step"].append(time.perf_counter() - t0)
        history.append({k: float(v) for k, v in m.items()})
        i += 1
        if i % rcfg.checkpoint_every == 0 or i == num_steps:
            ckpt.save(rcfg.checkpoint_dir, state, step=i)
            metrics.count("checkpoints")
    return state, history
