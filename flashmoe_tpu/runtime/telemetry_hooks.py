"""Runtime-side wiring of the live telemetry plane.

One place builds the ``/healthz`` and ``/vars`` documents for every
training entry point (``train`` / ``resilient_train`` / ``supervise``),
so the three loops expose the same schema: step progress, SLO episode
state, controller budgets/cooldowns, the last durable checkpoint step,
and the job's resolved plan (the newest ``planner.path_select`` /
``bootstrap.groups`` decisions — the plan and GroupPlan the process is
actually running).

Everything is read-on-scrape: no thread does work unless an HTTP
request arrives, and with no ``telemetry_port`` nothing here is even
imported.
"""

from __future__ import annotations


def _config_vars(cfg) -> dict:
    """The "active knobs" slice of MoEConfig for ``/vars`` — the fields
    an on-call engineer asks about first."""
    if cfg is None:
        return {}
    return {k: getattr(cfg, k, None) for k in (
        "num_experts", "expert_top_k", "hidden_size",
        "intermediate_size", "sequence_len", "num_layers",
        "moe_backend", "serving_mode", "fused_schedule",
        "wire_dtype", "wire_dtype_combine", "wire_dtype_dcn",
        "a2a_chunks", "expert_replicas", "collect_stats",
        "degrade_unhealthy_experts", "ep", "dp",
    )}


def _plan_vars(metrics_obj=None) -> dict:
    """The resolved plan + GroupPlan from the decision stream (the
    planner and bootstrap already narrate them; ``/vars`` just shows
    the newest record of each)."""
    from flashmoe_tpu.utils.telemetry import metrics as _global

    mo = metrics_obj if metrics_obj is not None else _global
    out = {}
    sel = mo.last_decision("planner.path_select")
    if sel is not None:
        out["path_select"] = {k: v for k, v in sel.items()
                              if k != "decision"}
    groups = mo.last_decision("bootstrap.groups")
    if groups is not None:
        out["group_plan"] = {k: v for k, v in groups.items()
                             if k != "decision"}
    return out


def train_server(port, cfg=None, mesh=None, *, num_steps=None,
                 progress=None, watchdog=None, controller=None,
                 checkpoint_dir=None, metrics_obj=None,
                 extra_health=None, box=None):
    """Start (or return ``None`` for a ``None`` port) the scrape server
    for a training loop.

    ``progress``: a mutable ``{"step": int}`` the loop updates in
    place.  ``box``: an optional mutable dict whose ``watchdog`` /
    ``controller`` / ``cfg`` / ``checkpoint_dir`` entries OVERRIDE the
    arguments at scrape time — ``supervise`` re-points one long-lived
    server at each incarnation's objects through it."""
    from flashmoe_tpu.telemetry_plane.server import maybe_server

    box = box if box is not None else {}

    def health():
        wd = box.get("watchdog", watchdog)
        ctl = box.get("controller", controller)
        ckdir = box.get("checkpoint_dir", checkpoint_dir)
        doc: dict = {"phase": box.get("phase", "train")}
        if num_steps is not None:
            doc["num_steps"] = num_steps
        if progress is not None:
            doc["step"] = progress.get("step")
        doc.update(box.get("health", {}))
        if ckdir:
            from flashmoe_tpu.runtime import checkpoint as ckpt

            try:
                doc["last_checkpoint_step"] = ckpt.latest_step(ckdir)
            except Exception as e:  # noqa: BLE001 — health must answer
                doc["last_checkpoint_step_error"] = str(e)[:120]
        if wd is not None:
            doc["slo"] = wd.snapshot()
        if ctl is not None:
            doc["controller"] = ctl.snapshot()
        if extra_health is not None:
            doc.update(extra_health() or {})
        return doc

    def vars_fn():
        c = box.get("cfg", cfg)
        doc = {"config": _config_vars(c)}
        m = box.get("mesh", mesh)
        if m is not None:
            doc["mesh"] = {str(k): int(v) for k, v in m.shape.items()}
        doc.update(_plan_vars(metrics_obj))
        return doc

    return maybe_server(port, health_fn=health, vars_fn=vars_fn,
                        metrics_obj=metrics_obj)
