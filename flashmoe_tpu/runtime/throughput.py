"""Per-device expert-FFN throughput probe.

The reference measures each GPU's expert throughput at bootstrap with a
synthetic workload: 64 warmup + 16 timed runs of the standalone ``expert``
kernel, median latency -> ``WorkerAttribute.throughput`` in experts/ms
(``csrc/include/flashmoe/throughput.cuh:51-170``), feeding the Decider's
rate-proportional expert assignment.

The TPU version times the same synthetic grouped FFN through the real
kernel path.  Because remote-tunneled backends make single-dispatch timing
meaningless (host round-trip >> kernel), iterations are chained inside one
jit and differenced — see ``bench.py`` for the same technique.  Results are
cached per (device-kind, config shape) since homogeneous slices need one
probe, not one per chip — except :func:`device_rates`' per-DEVICE probes,
which exist precisely to spot the chip that stopped matching its kind
(the self-healing controller's slow-device trigger re-probes through it:
ISSUE 12 satellite / ROADMAP item 3 follow-up).
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.reference import init_moe_params
from flashmoe_tpu.ops import expert as exp

_cache: dict = {}


def _measure(cfg: MoEConfig, e: int, rows_per_expert: int, chain: int,
             trials: int) -> float:
    """One uncached probe on whatever device jax currently dispatches
    to (callers pin with ``jax.default_device``)."""
    pcfg = cfg.replace(num_experts=e, num_shared_experts=0)
    params = init_moe_params(jax.random.PRNGKey(0), pcfg)
    params = jax.tree_util.tree_map(lambda p: p.astype(cfg.dtype), params)
    xs = jax.random.normal(
        jax.random.PRNGKey(1), (e, rows_per_expert, cfg.hidden_size),
        cfg.dtype,
    )

    def chained(n):
        def run(p, xs):
            def body(xs, _):
                if jax.default_backend() == "tpu":
                    y = exp.capacity_buffer_ffn_pallas(xs, p, pcfg)
                else:
                    y = exp.expert_ffn_dense(xs, p, pcfg)
                return y.astype(xs.dtype), None
            xs, _ = jax.lax.scan(body, xs, None, length=n)
            return xs.astype(jnp.float32).sum()
        return jax.jit(run)

    def med(f):
        float(f(params, xs))  # compile+warm
        ts = []
        for _ in range(trials):
            t0 = time.perf_counter()
            float(f(params, xs))
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2]

    t1, tn = med(chained(1)), med(chained(chain))
    per_iter = max((tn - t1) / (chain - 1), 1e-9)
    return e / (per_iter * 1e3)  # experts per ms


def measure_expert_throughput(cfg: MoEConfig, *, experts: int | None = None,
                              rows_per_expert: int = 256,
                              chain: int = 8, trials: int = 3,
                              device=None) -> float:
    """Median throughput in experts/ms for this device kind.

    ``device``: pin the probe to ONE device (``jax.default_device``)
    and cache per device id instead of per kind — the form
    :func:`device_rates` uses to spot a degraded chip inside an
    otherwise homogeneous slice (a kind-keyed cache would return the
    first chip's number for every peer)."""
    e = experts or min(cfg.num_experts, 8)
    dev0 = device if device is not None else jax.devices()[0]
    key = (("dev", dev0.id) if device is not None else dev0.device_kind,
           e, rows_per_expert, cfg.hidden_size, cfg.intermediate_size,
           str(cfg.dtype))
    if key in _cache:
        return _cache[key]
    if device is not None:
        with jax.default_device(device):
            t = _measure(cfg, e, rows_per_expert, chain, trials)
    else:
        t = _measure(cfg, e, rows_per_expert, chain, trials)
    _cache[key] = t
    return t


def device_rates(cfg: MoEConfig, n_devices: int, *,
                 rows_per_expert: int = 64, chain: int = 4,
                 trials: int = 2, fresh: bool = False):
    """Live per-device throughput vector ``[n_devices]`` (experts/ms) —
    the self-healing controller's DEFAULT ``rates_fn`` on the
    slow-device trigger (ROADMAP item 3 follow-up: production
    re-placement re-probes instead of relying on drill-injected rates).
    Probes each local device individually (per-device cache keys);
    devices beyond the local count reuse the local readings in order
    (the homogeneous-host assumption every multi-host probe makes).

    Deliberately light defaults (64 rows, 4-chain, 2 trials): the probe
    runs at a rare step-boundary decision, not in the step loop, and
    relative rates are what the Decider consumes.  ``fresh=True`` drops
    the per-device cache entries first — a RE-probe must see today's
    silicon, not bootstrap's.

    Chaos seam: an armed ``probe_rates`` injection point
    (:mod:`flashmoe_tpu.chaos.inject`) supplies the reading a degraded
    chip WOULD produce, without touching the backend — how the
    ``slow_device`` drill exercises this exact production path (the
    host-sleep stall it injects is invisible to a real CPU probe, but a
    real TPU slow chip is exactly what the per-device probe exists to
    see)."""
    import numpy as np

    from flashmoe_tpu.chaos import inject

    if inject.is_armed("probe_rates"):
        armed = np.asarray(
            inject.spec("probe_rates").get("rates", ()), dtype=np.float64)
        if armed.size:
            out = np.ones(n_devices, dtype=np.float64) * armed[-1]
            out[:min(n_devices, armed.size)] = armed[:n_devices]
            return out
    devs = jax.local_devices()
    distinct = devs[:min(n_devices, len(devs))] or devs[:1]
    if fresh:
        # drop each DISTINCT device's cache entry once, before any
        # probing — popping inside the rank loop would re-measure the
        # same physical device once per logical rank mapped onto it
        # (and let timing noise hand the Decider different rates for
        # the same chip)
        for dev in distinct:
            _cache.pop((("dev", dev.id), min(cfg.num_experts, 8),
                        rows_per_expert, cfg.hidden_size,
                        cfg.intermediate_size, str(cfg.dtype)), None)
    readings = [
        measure_expert_throughput(
            cfg, rows_per_expert=rows_per_expert, chain=chain,
            trials=trials, device=dev)
        for dev in distinct
    ]
    return np.asarray(
        [readings[i % len(readings)] for i in range(n_devices)],
        dtype=np.float64)
