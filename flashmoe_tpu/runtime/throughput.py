"""Per-device expert-FFN throughput probe.

The reference measures each GPU's expert throughput at bootstrap with a
synthetic workload: 64 warmup + 16 timed runs of the standalone ``expert``
kernel, median latency -> ``WorkerAttribute.throughput`` in experts/ms
(``csrc/include/flashmoe/throughput.cuh:51-170``), feeding the Decider's
rate-proportional expert assignment.

The TPU version times the same synthetic grouped FFN through the real
kernel path.  Because remote-tunneled backends make single-dispatch timing
meaningless (host round-trip >> kernel), iterations are chained inside one
jit and differenced — see ``bench.py`` for the same technique.  Results are
cached per (device-kind, config shape) since homogeneous slices need one
probe, not one per chip.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.reference import init_moe_params
from flashmoe_tpu.ops import expert as exp

_cache: dict = {}


def measure_expert_throughput(cfg: MoEConfig, *, experts: int | None = None,
                              rows_per_expert: int = 256,
                              chain: int = 8, trials: int = 3) -> float:
    """Median throughput in experts/ms for this device kind."""
    e = experts or min(cfg.num_experts, 8)
    key = (jax.devices()[0].device_kind, e, rows_per_expert,
           cfg.hidden_size, cfg.intermediate_size, str(cfg.dtype))
    if key in _cache:
        return _cache[key]

    pcfg = cfg.replace(num_experts=e, num_shared_experts=0)
    params = init_moe_params(jax.random.PRNGKey(0), pcfg)
    params = jax.tree_util.tree_map(lambda p: p.astype(cfg.dtype), params)
    xs = jax.random.normal(
        jax.random.PRNGKey(1), (e, rows_per_expert, cfg.hidden_size),
        cfg.dtype,
    )

    def chained(n):
        def run(p, xs):
            def body(xs, _):
                if jax.default_backend() == "tpu":
                    y = exp.capacity_buffer_ffn_pallas(xs, p, pcfg)
                else:
                    y = exp.expert_ffn_dense(xs, p, pcfg)
                return y.astype(xs.dtype), None
            xs, _ = jax.lax.scan(body, xs, None, length=n)
            return xs.astype(jnp.float32).sum()
        return jax.jit(run)

    def med(f):
        float(f(params, xs))  # compile+warm
        ts = []
        for _ in range(trials):
            t0 = time.perf_counter()
            float(f(params, xs))
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2]

    t1, tn = med(chained(1)), med(chained(chain))
    per_iter = max((tn - t1) / (chain - 1), 1e-9)
    throughput = e / (per_iter * 1e3)  # experts per ms
    _cache[key] = throughput
    return throughput
