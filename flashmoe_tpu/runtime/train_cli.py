"""End-to-end training CLI.

Composes the whole framework: preset or JSON config -> runtime bootstrap
(mesh + placement) -> native data loader -> sharded optax train step ->
resilient loop with periodic orbax checkpoints and metrics JSONL.

Usage:
  python -m flashmoe_tpu.runtime.train_cli --preset mixtral-8x7b \
      --data tokens.bin --steps 1000 --batch 8 --checkpoint-dir ckpt/
  python -m flashmoe_tpu.runtime.train_cli --config cfg.json --synthetic

``--synthetic`` trains on random tokens (the reference worker's random-
tensor mode, ``flashmoe/worker.py:56-58``) for smoke runs without data.
"""

from __future__ import annotations

import argparse
import itertools
import json
import sys

import jax
import jax.numpy as jnp

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.presets import PRESETS
from flashmoe_tpu.runtime import bootstrap
from flashmoe_tpu.runtime.data import TokenLoader
from flashmoe_tpu.runtime.resilient import (
    ResilienceConfig, resilient_train, scalar_metrics,
)
from flashmoe_tpu.runtime.trainer import (
    GradGuardConfig, init_state, make_optimizer, make_train_step,
    state_shardings,
)
from flashmoe_tpu.utils.telemetry import Metrics


def _synthetic_batches(cfg: MoEConfig, batch: int):
    for i in itertools.count():
        yield {"tokens": jax.random.randint(
            jax.random.PRNGKey(i), (batch, cfg.sequence_len + 1), 0,
            cfg.vocab_size,
        )}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    src = ap.add_mutually_exclusive_group()
    src.add_argument("--preset", choices=sorted(PRESETS))
    src.add_argument("--config", help="flashmoe-style config JSON path")
    ap.add_argument("--data", help="binary int32 token file")
    ap.add_argument("--synthetic", action="store_true")
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--checkpoint-every", type=int, default=100)
    ap.add_argument("--async-save", action="store_true",
                    help="hand checkpoint serialization to the background "
                         "writer; the step loop pays only the host "
                         "snapshot (docs/RESILIENCE.md)")
    ap.add_argument("--grace-s", type=float, default=30.0,
                    help="preemption grace window: SIGTERM/SIGUSR1 drain "
                         "a final checkpoint + data-loader cursor inside "
                         "this budget instead of dying mid-write")
    ap.add_argument("--log-every", type=int, default=10)
    ap.add_argument("--metrics-jsonl", default=None)
    ap.add_argument("--telemetry-port", type=int, default=None,
                    metavar="PORT",
                    help="serve live /metrics, /healthz and /vars on "
                         "this port for the run's duration (0 = "
                         "ephemeral; default off = no thread, "
                         "byte-identical training)")
    ap.add_argument("--grad-guard", action="store_true",
                    help="tier-1 gradient anomaly guard: skip non-finite/"
                         "spiking updates in-graph (docs/RESILIENCE.md)")
    ap.add_argument("--grad-spike-factor", type=float, default=10.0)
    ap.add_argument("--num-layers", type=int, default=None,
                    help="override (e.g. shrink a preset for a smoke run)")
    ap.add_argument("--set", action="append", default=[],
                    metavar="FIELD=VALUE",
                    help="override any MoEConfig field (repeatable), e.g. "
                         "--set sequence_len=256 --set hidden_size=512")
    args = ap.parse_args(argv)

    if args.preset:
        cfg = PRESETS[args.preset]()
    elif args.config:
        cfg = MoEConfig.from_json(args.config)
    else:
        cfg = MoEConfig()
    overrides = {"is_training": True}
    if args.num_layers:
        overrides["num_layers"] = args.num_layers
    for kv in args.set:
        k, _, v = kv.partition("=")
        cur = getattr(cfg, k)  # raises on unknown field
        if isinstance(cur, bool):
            overrides[k] = v.lower() in ("1", "true", "yes")
        elif isinstance(cur, int):
            overrides[k] = int(v)
        elif isinstance(cur, float):
            overrides[k] = float(v)
        else:
            overrides[k] = v
    cfg = cfg.replace(**overrides)

    rt = bootstrap.initialize(cfg)
    cfg = rt.cfg
    mesh = rt.mesh
    print(f"mesh={dict(mesh.shape)} experts={cfg.num_experts} "
          f"layers={cfg.num_layers}", file=sys.stderr)

    if args.data and not args.synthetic:
        data = TokenLoader(args.data, args.batch, cfg.sequence_len)
    else:
        data = _synthetic_batches(cfg, args.batch)

    optimizer = make_optimizer(cfg, lr=args.lr, total_steps=args.steps)
    guard = (GradGuardConfig(spike_factor=args.grad_spike_factor)
             if args.grad_guard else None)
    state = init_state(jax.random.PRNGKey(0), cfg, optimizer, guard=guard)
    state = jax.device_put(state, state_shardings(state, cfg, mesh))
    step = make_train_step(cfg, mesh, optimizer, guard=guard)

    metrics = Metrics()
    if args.checkpoint_dir:
        from flashmoe_tpu.runtime.preempt import PreemptionListener

        rcfg = ResilienceConfig(
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_every=args.checkpoint_every,
            async_save=args.async_save,
        )
        # a TokenLoader's cursor rides every checkpoint manifest via
        # resilient_train (state_dict/load_state_dict), so a restarted
        # CLI run continues the exact token stream
        preempt = PreemptionListener(grace_s=args.grace_s).install()
        try:
            state, history = resilient_train(
                state, step, data, args.steps, rcfg=rcfg,
                metrics=metrics, preempt=preempt, cfg=cfg,
                telemetry_port=args.telemetry_port,
            )
        finally:
            preempt.uninstall()
        if preempt.requested:
            print(f"preempted: drained at step {int(state.step)} "
                  f"(checkpoint + loader state in "
                  f"{args.checkpoint_dir}); re-run to resume",
                  file=sys.stderr)
    else:
        server = None
        if args.telemetry_port is not None:
            from flashmoe_tpu.runtime.telemetry_hooks import train_server

            progress = {"step": 0}
            server = train_server(args.telemetry_port, cfg, mesh,
                                  num_steps=args.steps,
                                  progress=progress,
                                  metrics_obj=metrics)
        history = []
        try:
            for i in range(args.steps):
                if server is not None:
                    progress["step"] = i
                with metrics.timer("step"):
                    state, m = step(state, next(data))
                if i % args.log_every == 0 or i == args.steps - 1:
                    # scalar-safe: array-valued metrics (per-expert
                    # stats when collect_stats is on) must not crash
                    # the logger
                    rec = scalar_metrics(m)
                    history.append(rec)
                    print(json.dumps({"step": i, **rec}),
                          file=sys.stderr)
        finally:
            if server is not None:
                server.stop()

    summary = dict(metrics.summary(),
                   final_loss=history[-1].get("loss") if history else None,
                   steps=args.steps)
    if args.metrics_jsonl:
        metrics.dump_jsonl(args.metrics_jsonl, steps=args.steps)
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
