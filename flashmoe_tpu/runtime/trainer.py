"""Training loop: sharded optax train step over the device mesh.

The reference models DP training costs in its Decider (gradient-buffer
sizing ``types.cuh:491-493``, ring-allreduce pricing
``os/decider/functions.cuh:28-32``) but executes no training.  This module
is the executed version: a jit-compiled train step whose gradient averaging
over dp *is* the allreduce the Decider prices, inserted by XLA from the
sharding layout (params replicated over dp -> psum of grads over dp).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models import transformer
from flashmoe_tpu.parallel.mesh import transformer_param_specs


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array
    # tier-1 gradient-guard EMA state (GuardState) when the step was
    # built with a GradGuardConfig; None otherwise — a None leaf is an
    # empty pytree node, so guard-free states keep the pre-guard tree
    # structure (checkpoints, shardings, donation all unchanged)
    guard: Any = None


class GuardState(NamedTuple):
    """Running statistics for the tier-1 gradient anomaly guard."""

    norm_ema: jax.Array  # EMA of the (finite, accepted) grad norms
    seen: jax.Array      # accepted steps feeding the EMA (warmup gate)


@dataclasses.dataclass(frozen=True)
class GradGuardConfig:
    """Tier-1 fault tolerance: per-step gradient anomaly guard.

    A non-finite gradient or a grad-norm spike costs ONE skipped
    optimizer update (params/opt-state/EMA carried through a
    ``jnp.where`` select inside the compiled step) instead of a
    checkpoint rewind — the middle rung between tier-0 expert masking
    and tier-2 restore-and-retry (docs/RESILIENCE.md).

    ``spike_factor``: skip when grad_norm > spike_factor * EMA (only
    once ``warmup_steps`` accepted norms have seeded the EMA).
    ``ema_decay``: EMA decay per accepted step; skipped steps do not
    contaminate the EMA.
    """

    skip_nonfinite: bool = True
    spike_factor: float = 10.0
    ema_decay: float = 0.99
    warmup_steps: int = 10


def init_guard_state() -> GuardState:
    return GuardState(jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32))


def make_optimizer(cfg: MoEConfig, lr: float = 3e-4,
                   weight_decay: float = 0.1,
                   warmup_steps: int = 100,
                   total_steps: int = 10000) -> optax.GradientTransformation:
    sched = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup_steps, max(total_steps, warmup_steps + 1)
    )
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(sched, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )


def init_state(key, cfg: MoEConfig, optimizer,
               guard: GradGuardConfig | None = None) -> TrainState:
    params = transformer.init_params(key, cfg)
    return TrainState(params, optimizer.init(params),
                      jnp.zeros((), jnp.int32),
                      init_guard_state() if guard is not None else None)


def state_shardings(state: TrainState, cfg: MoEConfig, mesh: Mesh):
    """NamedShardings for the train state: params per the transformer
    specs, optimizer moments following their parameters, step replicated."""
    pspecs = transformer_param_specs(cfg)

    def to_sharding(spec):
        return NamedSharding(mesh, spec)

    param_sh = jax.tree_util.tree_map(
        to_sharding, pspecs,
        is_leaf=lambda x: isinstance(x, P),
    )

    # Optimizer moments mirror the param tree (optax states embed it as a
    # subtree), so match by KEY PATH, not by array shape: a moment leaf
    # whose trailing path equals a param's path (and shape agrees) gets
    # that param's sharding; everything else (counts, scalars) replicates.
    # Shape-only matching silently aliases two same-shaped params with
    # different shardings (e.g. an ep-sharded and a replicated tensor).
    flat_sh = jax.tree_util.tree_flatten_with_path(
        param_sh, is_leaf=lambda x: isinstance(x, NamedSharding)
    )[0]
    flat_p = jax.tree_util.tree_flatten_with_path(state.params)[0]
    by_path = {
        tuple(str(k) for k in path): (leaf.shape, sh)
        for (path, leaf), (_, sh) in zip(flat_p, flat_sh)
    }

    def match(path, leaf):
        key = tuple(str(k) for k in path)
        for start in range(len(key)):
            hit = by_path.get(key[start:])
            if hit is not None and getattr(leaf, "shape", None) == hit[0]:
                return hit[1]
        return NamedSharding(mesh, P())

    opt_sh = jax.tree_util.tree_map_with_path(match, state.opt_state)
    guard_sh = jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P()), state.guard)
    return TrainState(param_sh, opt_sh, NamedSharding(mesh, P()), guard_sh)


def make_train_step(cfg: MoEConfig, mesh: Mesh, optimizer,
                    use_pallas: bool | None = None,
                    guard: GradGuardConfig | None = None) -> Callable:
    """Build the jitted, mesh-sharded train step.

    Returns step(state, batch) -> (state, metrics).  Batch tokens shard
    over dp; XLA inserts the dp gradient allreduce from the sharding
    layout.

    ``guard`` arms the tier-1 gradient anomaly guard: the state must
    then carry a :class:`GuardState` (``init_state(..., guard=guard)``),
    and the metrics gain ``grad_ok`` (1.0 = update applied, 0.0 = update
    skipped in-graph) plus ``grad_norm_ema``.  ``guard=None`` builds the
    exact pre-guard step — bit-identical training.
    """
    # Training entry point implies is_training: without this, a hand-built
    # config silently differentiates through the inference-selected FFN path
    # (extra forward recompute in the VJP) instead of the residual-saving
    # training kernels (round-2 advisor finding).
    if not cfg.is_training:
        cfg = cfg.replace(is_training=True)

    def step_fn(state: TrainState, batch):
        (loss, metrics), grads = jax.value_and_grad(
            transformer.loss_fn, has_aux=True
        )(state.params, batch, cfg, mesh, use_pallas)
        from flashmoe_tpu.chaos import inject as chaos_inject

        if (chaos_inject.is_armed("nan_grad")
                or chaos_inject.is_armed("grad_spike")):
            grads = chaos_inject.poison_grads(grads, state.step)
        gnorm = optax.global_norm(grads)
        if guard is None:
            updates, opt_state = optimizer.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            metrics = dict(metrics, loss=loss, grad_norm=gnorm)
            return TrainState(params, opt_state, state.step + 1,
                              state.guard), metrics

        # ---- tier-1 guard: decide, then select — all in-graph ----
        gs: GuardState = state.guard
        finite = jnp.isfinite(gnorm)
        warm = gs.seen >= guard.warmup_steps
        spike = warm & (gnorm > guard.spike_factor
                        * jnp.maximum(gs.norm_ema, 1e-30))
        ok = (finite if guard.skip_nonfinite else jnp.bool_(True)) & ~spike
        # a non-finite gradient must never flow into the optimizer even
        # when its update is discarded: moment EMAs computed from NaN
        # grads would be selected away here, but XLA may still fuse the
        # NaN into reused subexpressions; feed zeros on skipped steps
        safe_grads = jax.tree_util.tree_map(
            lambda g: jnp.where(ok, g, jnp.zeros((), g.dtype))
            if jnp.issubdtype(jnp.asarray(g).dtype, jnp.inexact) else g,
            grads,
        )
        updates, new_opt = optimizer.update(
            safe_grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        sel = functools.partial(
            jax.tree_util.tree_map, lambda n, o: jnp.where(ok, n, o))
        params = sel(new_params, state.params)
        opt_state = sel(new_opt, state.opt_state)
        decay = jnp.float32(guard.ema_decay)
        seeded = gs.seen > 0
        ema_next = jnp.where(seeded,
                             decay * gs.norm_ema + (1 - decay) * gnorm,
                             gnorm.astype(jnp.float32))
        new_guard = GuardState(
            jnp.where(ok, ema_next, gs.norm_ema),
            gs.seen + ok.astype(gs.seen.dtype),
        )
        metrics = dict(metrics, loss=loss, grad_norm=gnorm,
                       grad_ok=ok.astype(jnp.float32),
                       grad_norm_ema=new_guard.norm_ema)
        return TrainState(params, opt_state, state.step + 1,
                          new_guard), metrics

    batch_sharding = {"tokens": NamedSharding(mesh, P("dp", None))}
    return jax.jit(
        step_fn,
        in_shardings=(None, batch_sharding),
        donate_argnums=(0,),
    )


def host_metrics(step_metrics: dict, moe_layers=None) -> dict:
    """Device step metrics -> one JSON-ready dict: scalars to floats,
    per-layer MoEStats (``moe_stats``, present when cfg.collect_stats)
    to the flight-recorder ``moe`` schema that
    ``python -m flashmoe_tpu.observe`` consumes.

    ``moe_layers``: the transformer layer index per stats entry
    (``cfg.moe_layer_indices`` — forward only collects stats for MoE
    layers, so position i of the tuple is that sequence's i-th layer);
    None falls back to the positional index."""
    from flashmoe_tpu.ops.stats import stats_to_host

    out: dict = {}
    for k, v in step_metrics.items():
        if k == "moe_stats":
            out["moe"] = [
                dict(layer=(moe_layers[i] if moe_layers is not None
                            and i < len(moe_layers) else i),
                     **stats_to_host(st))
                for i, st in enumerate(v)
            ]
        else:
            out[k] = float(v)
    return out


def train(cfg: MoEConfig, mesh: Mesh, data_iter, num_steps: int,
          key=None, log_every: int = 10, state: TrainState | None = None,
          use_pallas: bool | None = None,
          recorder: "FlightRecorder | None" = None,
          flight_path: str | None = None,
          flight_flush_every: int = 0,
          guard: GradGuardConfig | None = None,
          slo=None, controller=None, telemetry_port: int | None = None):
    """Simple host training loop (see runtime.worker for the CLI).

    ``recorder``: a :class:`flashmoe_tpu.utils.telemetry.FlightRecorder`
    capturing EVERY step (ring-bounded), independent of ``log_every``;
    with ``flight_path`` one is created if needed and its JSONL is
    exported there when the loop ends — the artifact
    ``python -m flashmoe_tpu.observe`` summarizes.  Set
    ``cfg.collect_stats`` to include the in-graph MoE stats per record.

    ``flight_flush_every``: > 0 flushes the recorder to ``flight_path``
    every that many steps via the OFFSET-AWARE append mode
    (:meth:`FlightRecorder.export_jsonl` with ``start``), so records
    that rotate out of the bounded ring between flushes are already on
    disk — the legacy end-of-run snapshot silently discarded them.

    ``slo``: a :class:`flashmoe_tpu.profiler.slo.SLOConfig` (or a
    prebuilt :class:`~flashmoe_tpu.profiler.slo.SLOWatchdog`): every
    step's wall time is judged against the step budget (``slo.breach`` /
    ``slo.recovered`` decisions, consecutive-breach escalation into
    planner path demotion).  Arming an SLO times every step.

    ``controller``: a :class:`flashmoe_tpu.runtime.controller.
    RuntimeController` closes the telemetry loop on this plain host
    loop too — the loop owns cfg/mesh/optimizer, so morphs rebuild the
    jitted step in place and re-placements permute the live state
    (checkpoint-free runs get no durable plan; production jobs should
    prefer ``resilient_train``/``supervise``, which persist controller
    actions in checkpoint manifests).  Arming a controller times every
    step.

    ``telemetry_port``: arm the live scrape server
    (telemetry_plane/server.py) for the loop's duration — ``/metrics``
    (the global registry), ``/healthz`` (step progress + SLO episode +
    controller budgets), ``/vars`` (the shape being trained).  Default
    ``None`` = no thread, byte-identical behavior.

    When a profiler timeline is armed (:func:`flashmoe_tpu.profiler.
    spans.profiling`), the loop's host work is recorded as
    ``train.data_pull`` / ``train.step`` sections.
    """
    import time

    from flashmoe_tpu.profiler import spans as prof
    from flashmoe_tpu.utils.telemetry import FlightRecorder, metrics as tm

    key = key if key is not None else jax.random.PRNGKey(0)
    optimizer = make_optimizer(cfg, total_steps=num_steps)
    if state is None:
        state = init_state(key, cfg, optimizer, guard=guard)
        sh = state_shardings(state, cfg, mesh)
        state = jax.device_put(state, sh)
    step = make_train_step(cfg, mesh, optimizer, use_pallas=use_pallas,
                           guard=guard)
    if flight_path is not None and recorder is None:
        recorder = FlightRecorder()
    watchdog = _as_watchdog(slo)
    history = []
    flushed = 0  # offset-aware export cursor (absolute record index)
    progress = {"step": 0}
    server = None
    if telemetry_port is not None:
        from flashmoe_tpu.runtime.telemetry_hooks import train_server

        server = train_server(telemetry_port, cfg, mesh,
                              num_steps=num_steps, progress=progress,
                              watchdog=watchdog, controller=controller)
    try:
        for i in range(num_steps):
            progress["step"] = i
            with prof.section("train.data_pull", step=i):
                batch = next(data_iter)
            log_step = i % log_every == 0 or i == num_steps - 1
            tl = prof.active()
            if recorder is not None or log_step or watchdog is not None \
                    or tl is not None or controller is not None:
                # block before reading the clock: jit dispatch is async, so
                # an unsynchronized timer would record ~0 host-dispatch ms.
                # With a recorder every step is timed exactly; log-only runs
                # time the logged step plus whatever backlog drained with it.
                t0 = time.perf_counter()
                if tl is not None:
                    # an armed timeline gets per-step records; any phases
                    # measured inside (eager fenced runs — under jit the
                    # phase dict stays empty) feed the SLO phase budgets
                    tl.begin_step(i)
                with prof.section("train.step", step=i):
                    state, metrics = step(state, batch)
                    jax.block_until_ready(metrics)
                phases = tl.end_step()["phases"] if tl is not None else None
                step_ms = (time.perf_counter() - t0) * 1e3
                # bounded: the histogram aggregates, no per-step list grows
                tm.histogram("trainer.step_ms", step_ms)
                if watchdog is not None:
                    watchdog.observe_step(i, step_ms, phases=phases)
                if controller is not None:
                    controller.observe_step(i, step_ms, metrics)
                    act = controller.maybe_act(i + 1)
                    if act is not None:
                        # self-healing action at the step boundary: permute
                        # the live state (re-placement) and/or re-jit onto
                        # the controller's accumulated config overrides
                        state = controller.apply_action(act, state)
                        if act.needs_rebuild:
                            step = make_train_step(
                                cfg.replace(**controller.cfg_overrides),
                                mesh, optimizer, use_pallas=use_pallas,
                                guard=guard)
                if recorder is not None or log_step:
                    # the full device->host metrics pull (per-layer MoEStats
                    # when collect_stats is on) only happens when someone
                    # consumes it; a watchdog alone needs just step_ms
                    rec = host_metrics(metrics,
                                       moe_layers=cfg.moe_layer_indices)
                    rec["step_ms"] = step_ms
                    if rec.get("grad_ok", 1.0) == 0.0:
                        # tier-1 guard fired: the skipped update is a
                        # structured decision so a postmortem can answer
                        # "which steps were dropped and why" without
                        # replaying the run
                        tm.decision("trainer.grad_skip", step=i,
                                    grad_norm=rec.get("grad_norm"),
                                    grad_norm_ema=rec.get("grad_norm_ema"))
                    if recorder is not None:
                        recorder.record(step=i, **rec)
                        if flight_path is not None and flight_flush_every > 0 \
                                and (i + 1) % flight_flush_every == 0:
                            flushed = recorder.export_jsonl(flight_path,
                                                            start=flushed)
                    if log_step:
                        history.append(rec)
            else:
                with prof.section("train.step", step=i):
                    state, metrics = step(state, batch)
        if flight_path is not None and recorder is not None:
            if flight_flush_every > 0:
                recorder.export_jsonl(flight_path, start=flushed)
            else:
                recorder.export_jsonl(flight_path)
        return state, history
    finally:
        if server is not None:
            server.stop()


def _as_watchdog(slo):
    """Accept an SLOConfig, a prebuilt SLOWatchdog, or None."""
    if slo is None:
        return None
    from flashmoe_tpu.profiler.slo import SLOConfig, SLOWatchdog

    if isinstance(slo, SLOConfig):
        return SLOWatchdog(slo)
    return slo
