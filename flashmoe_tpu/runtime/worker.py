"""Per-process worker entry point.

Mirrors the reference worker (``flashmoe/worker.py:11-75``): initialize the
runtime, build random inputs/weights sized from the config, run the MoE
forward (optionally a timed benchmark loop), print per-rank timing, and
finalize.

Usage:  python -m flashmoe_tpu.runtime.worker [config.json] [--bench]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.reference import init_moe_params
from flashmoe_tpu.ops.moe import moe_layer
from flashmoe_tpu.parallel.ep import ep_moe_layer
from flashmoe_tpu.runtime import bootstrap


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("config", nargs="?", default=None,
                    help="path to a flashmoe-style config JSON")
    ap.add_argument("--bench", action="store_true",
                    help="timed loop (skip + trials) like forwardHostBench")
    ap.add_argument("--trials", type=int, default=32)
    ap.add_argument("--skip", type=int, default=8)
    args = ap.parse_args(argv)

    cfg = MoEConfig.from_json(args.config) if args.config else MoEConfig()
    rt = bootstrap.initialize(cfg)
    cfg = rt.cfg

    key = jax.random.PRNGKey(rt.process_id)
    params = init_moe_params(key, cfg)
    params = jax.tree_util.tree_map(lambda p: p.astype(cfg.dtype), params)
    x = jax.random.normal(
        jax.random.PRNGKey(rt.process_id + 1),
        (cfg.tokens, cfg.hidden_size), cfg.dtype,
    )

    if cfg.ep > 1 and len(jax.devices()) >= cfg.ep:
        fwd = jax.jit(
            lambda p, x: ep_moe_layer(p, x, cfg, rt.mesh).out
        )
    else:
        fwd = jax.jit(lambda p, x: moe_layer(p, x, cfg).out)

    out = fwd(params, x)
    jax.block_until_ready(out)

    if args.bench:
        for _ in range(args.skip):
            out = fwd(params, x)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(args.trials):
            out = fwd(params, x)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / args.trials
        print(json.dumps({
            "rank": rt.process_id,
            "moe_fwd_ms": round(dt * 1e3, 3),
            "tokens": cfg.tokens,
            "num_experts": cfg.num_experts,
            "devices": len(jax.devices()),
        }))
    else:
        print(json.dumps({
            "rank": rt.process_id,
            "output_shape": list(out.shape),
            "finite": bool(jnp.isfinite(out).all()),
            "num_local_experts": rt.num_local_experts,
        }))
    bootstrap.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
