"""Production serving subsystem: continuous batching, paged ragged KV
cache, and decode-shaped planner integration.

The training half of this framework reproduces the reference kernel
library and grows it into a trainer; this package is the first
subsystem on the INFERENCE half of the north star (ROADMAP item 1):

* :mod:`flashmoe_tpu.serving.kvcache` — a paged KV cache built on the
  same row-major ragged machinery as :mod:`flashmoe_tpu.ops.ragged`:
  block-table indirection, per-request lengths, deterministic page
  reuse on eviction, bucketed-length jit policy.
* :mod:`flashmoe_tpu.serving.engine` — a continuous-batching engine:
  per-step request admission/eviction/retirement over a fixed slot
  grid, deterministic under a seeded arrival trace (CI-testable on
  CPU), TTFT/TPOT/queue-depth/cache-occupancy through the flight
  recorder and ``serve.*`` decisions, TTFT/TPOT SLO budgets through
  the PR 8 watchdog.
* :mod:`flashmoe_tpu.serving.pools` — prefill/decode pool formation as
  heterogeneous inference-mode Decider groups (the reference's
  ``decider.cuh:177-268`` specialization); :mod:`flashmoe_tpu.fabric`
  composes these pools, a DCN-priced KV handoff, and a replica router
  into the disaggregated serving fabric (ROADMAP item 5).

CLI: ``python -m flashmoe_tpu.serving`` drives a seeded multi-request
drill and prints a JSON summary; ``python -m flashmoe_tpu.observe
--serving`` renders the serving report from the artifacts; ``python
bench.py --serve`` sweeps offered load.  See docs/SERVING.md.
"""

from flashmoe_tpu.serving.engine import (  # noqa: F401
    Request, ServeConfig, ServingEngine,
)
from flashmoe_tpu.serving.kvcache import (  # noqa: F401
    PagedKVCache, PagePool, SCRATCH_PAGE, ShardedPagePool,
    init_paged_cache,
)
from flashmoe_tpu.serving.pools import (  # noqa: F401
    PoolPlan, plan_serving_pools,
)
