"""Serving drill CLI: a seeded multi-request continuous-batching run.

Usage::

    python -m flashmoe_tpu.serving                       # default drill
    python -m flashmoe_tpu.serving --requests 12 --max-batch 8 \\
        --max-new 8 --arrival-every 2 --seed 7
    python -m flashmoe_tpu.serving --obs-dir obs/ --ttft-slo-ms 50
    python -m flashmoe_tpu.serving --trace --telemetry-port 9464 \\
        --obs-dir obs/           # live /metrics + per-request traces
    python -m flashmoe_tpu.observe --serving obs/flight.jsonl \\
        obs/decisions.jsonl                              # the report
    python -m flashmoe_tpu.observe --trace 3 obs/trace.jsonl

Runs a small MoE transformer (CPU-sized by default) through the
continuous-batching engine under a seeded arrival trace, prints ONE
JSON summary line (requests completed, tokens/s, TTFT/TPOT, queue
depth, cache occupancy, evictions, the decode-vs-prefill planner
plans), and — with ``--obs-dir`` — writes ``flight.jsonl`` +
``decisions.jsonl`` for ``python -m flashmoe_tpu.observe --serving``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


from flashmoe_tpu.serving.loadgen import build_requests  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m flashmoe_tpu.serving",
        description="seeded continuous-batching serving drill")
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=8)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--arrival-every", type=int, default=1,
                    help="engine steps between arrival pairs (the "
                         "seeded arrival trace)")
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--num-pages", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--experts", type=int, default=4)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--ttft-slo-ms", type=float, default=None,
                    help="TTFT budget judged by the SLO watchdog "
                         "(slo.breach decisions on violation)")
    ap.add_argument("--tpot-slo-ms", type=float, default=None)
    ap.add_argument("--obs-dir", default=os.environ.get(
        "FLASHMOE_OBS_DIR"),
        help="write flight.jsonl + decisions.jsonl here "
             "(observe --serving input)")
    ap.add_argument("--telemetry-port", type=int, default=None,
                    metavar="PORT",
                    help="serve live /metrics, /healthz and /vars on "
                         "this port for the run's duration (0 = "
                         "ephemeral; default off = no thread, "
                         "bit-identical outputs)")
    ap.add_argument("--trace", action="store_true",
                    help="request-scoped tracing: per-request "
                         "Perfetto tracks (request_trace.json) + "
                         "trace.jsonl spans into --obs-dir, rendered "
                         "by `observe --trace <rid>`")
    ap.add_argument("--json", action="store_true",
                    help="(default) emit the JSON summary line")
    args = ap.parse_args(argv)

    import jax

    from flashmoe_tpu.models.transformer import init_params
    from flashmoe_tpu.serving.engine import ServeConfig, ServingEngine
    from flashmoe_tpu.serving.loadgen import tiny_config
    from flashmoe_tpu.utils.telemetry import FlightRecorder, metrics

    cfg = tiny_config(hidden=args.hidden, experts=args.experts,
                      layers=args.layers, vocab=args.vocab)
    params = init_params(jax.random.PRNGKey(args.seed), cfg)
    reqs, arrivals = build_requests(
        args.requests, vocab=args.vocab, prompt_len=args.prompt_len,
        max_new=args.max_new, seed=args.seed,
        arrival_every=args.arrival_every,
        temperature=args.temperature)

    slo = None
    if args.ttft_slo_ms or args.tpot_slo_ms:
        from flashmoe_tpu.profiler.slo import SLOConfig

        slo = SLOConfig(ttft_ms=args.ttft_slo_ms,
                        tpot_ms=args.tpot_slo_ms)

    recorder = FlightRecorder()
    serve = ServeConfig(
        max_batch=args.max_batch, page_size=args.page_size,
        num_pages=args.num_pages,
        max_pages_per_slot=max(
            2, -(-(args.prompt_len + args.max_new) // args.page_size)
            + 1),
        ctx_bucket_pages=1,
        prompt_bucket=args.page_size)
    import time

    t0 = time.monotonic()
    engine = ServingEngine(params, cfg, serve, recorder=recorder,
                           slo=slo, tracer=args.trace,
                           telemetry_port=args.telemetry_port)
    try:
        engine.run(reqs, arrivals)
        wall_s = time.monotonic() - t0

        summary = engine.summary()
        summary["wall_s"] = round(wall_s, 3)
        summary["tokens_per_sec"] = round(summary["tokens"] / wall_s, 1) \
            if wall_s > 0 else None
        summary["slo_breaches"] = int(
            metrics.counters.get("slo.breaches", 0))
        if args.telemetry_port is not None:
            summary["telemetry_port"] = engine.telemetry.port
        if args.obs_dir:
            os.makedirs(args.obs_dir, exist_ok=True)
            recorder.export_jsonl(os.path.join(args.obs_dir,
                                               "flight.jsonl"))
            metrics.dump_decisions_jsonl(
                os.path.join(args.obs_dir, "decisions.jsonl"))
            summary["obs_dir"] = args.obs_dir
            if engine.tracer is not None:
                from flashmoe_tpu.profiler.export import (
                    write_request_trace,
                )
                from flashmoe_tpu.telemetry_plane.server import (
                    host_shard_path,
                )

                problems = engine.tracer.validate()
                summary["trace_problems"] = problems
                engine.tracer.export_jsonl(
                    os.path.join(args.obs_dir, "trace.jsonl"))
                write_request_trace(
                    engine.tracer,
                    os.path.join(args.obs_dir, "request_trace.json"))
                # the per-host shard: this process's spans under its
                # host id, mergeable by `observe --merge`
                engine.tracer.export_jsonl(
                    host_shard_path(args.obs_dir))
    finally:
        engine.close()
    print(json.dumps(summary), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
