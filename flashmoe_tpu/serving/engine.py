"""Continuous-batching serving engine over the paged KV cache.

One fixed decode batch of ``max_batch`` slots; requests join and leave
per step (continuous batching) instead of padding a static batch to the
slowest member:

* **admission** — queued requests whose (seeded-trace) arrival step has
  passed take a free slot when the page pool can hold their prompt:
  single-pass batched prefill (:func:`flashmoe_tpu.models.generate.
  prefill_forward`) writes their pages in one shot, ``serve.admit``;
* **decode** — one jitted step advances every active slot: sample from
  each slot's pending logits (greedy / temperature / top-k / top-p,
  per-request), feed the sampled tokens, paged attention over each
  slot's block table, MoE FFN on the batch rows;
* **retirement** — a slot leaves when it emits a stop token or its
  ``max_new_tokens``-th token (``serve.retire`` with TTFT/TPOT); its
  pages return to the pool and the next admission reuses them;
* **eviction** — when decode needs a page and the pool is dry, the
  youngest active request is preempted back to the queue head
  (``serve.evict``): its pages free immediately, its already-delivered
  tokens stand, and it later re-prefills prompt+generated and
  continues.

Everything host-side is a pure function of the submitted requests and
their arrival steps, and the page allocator is LIFO — so a seeded drill
replays bit-identically on CPU, which is what makes the engine
CI-testable (tests/test_serving.py asserts engine outputs token-equal
to the same prompts decoded one at a time through ``generate()``).

Jit policy: the pool shape is fixed; prefill compiles once per padded
prompt bucket and decode once per bucketed context length
(:func:`flashmoe_tpu.serving.kvcache.ctx_pages_bucket`) — requests
joining mid-flight reuse existing compilations.

The planner runs in DECODE mode for the step path
(``resolve_moe_plan(mode='decode', decode_tokens=max_batch)``): decode
steps move ``max_batch`` tokens (x ``top_k`` exchange rows), not B x S,
so the training-shaped schedule sweep is the wrong question to ask —
the resolved (prefill, decode) plans land in one ``serve.plan``
decision (the reference's inference-mode Decider specialization,
``decider.cuh:177-268``, surfaces through the same call — see
:mod:`flashmoe_tpu.serving.pools` for the pool split).
"""

from __future__ import annotations

import dataclasses
import functools
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.generate import (
    init_cache, lm_logits, lm_logits_span, prefill_forward,
)
from flashmoe_tpu.models.transformer import rms_norm, _rope
from flashmoe_tpu.ops.moe import moe_layer
from flashmoe_tpu.serving.kvcache import (
    SCRATCH_PAGE, PagePool, ShardedPagePool, ctx_pages_bucket,
    gather_ctx, init_paged_cache, prompt_pad, store_prefill,
    store_token, store_tokens,
)
from flashmoe_tpu.serving.speculate import (
    DraftState, SpecConfig, spec_stats_fields,
)
from flashmoe_tpu.utils.telemetry import metrics as _global_metrics
from flashmoe_tpu.utils.telemetry import trace_span


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request.  ``seed`` keys the per-request sampler
    (folded with the token index, so sampling is independent of batch
    composition); ``stop_tokens`` retire the request the step one is
    emitted (the stop token itself is delivered)."""

    rid: int
    prompt: tuple
    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    stop_tokens: tuple = ()
    seed: int = 0

    def __post_init__(self):
        if not self.prompt:
            raise ValueError(f"request {self.rid}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"request {self.rid}: max_new_tokens must "
                             f"be >= 1")
        if not 0 < self.top_p <= 1.0:
            raise ValueError(f"request {self.rid}: top_p must be in "
                             f"(0, 1]")


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine shape knobs (all static: they size the jitted steps).

    ``num_pages`` includes the reserved scratch page; ``prompt_bucket``
    must be a multiple of ``page_size`` (prefilled pages are written
    whole); ``ctx_bucket_pages`` is the decode-gather granularity —
    the bucketed-length jit policy's bucket.

    ``prefill_chunk`` (tokens, a multiple of ``page_size``) bounds the
    per-step prefill budget: a prompt longer than one chunk is admitted
    in fixed-size slices, one slice per engine step, so a 32k-token
    prompt cannot hole a decode step.  ``ep_shards`` > 1 runs the
    decode step EP-sharded under ``shard_map`` on an ``("ep",)`` mesh
    with the paged KV slab partitioned alongside the experts (the
    fabric's decode-pool execution path).

    ``speculate`` (a :class:`~flashmoe_tpu.serving.speculate.
    SpecConfig`, None = off) arms speculative multi-token decoding
    (ISSUE 20): each step drafts up to ``draft_tokens`` continuation
    tokens per slot and verifies them in ONE ``k+1``-position paged
    forward — output tokens stay bit-equal to non-speculative decode
    (only canonical samples are ever emitted), and because the config
    rides ``ServeConfig`` it reaches every fabric replica, so
    speculation survives pool handoff and replica migration for
    free."""

    max_batch: int = 8
    page_size: int = 8
    num_pages: int = 64
    max_pages_per_slot: int = 8
    ctx_bucket_pages: int = 2
    prompt_bucket: int = 8
    pad_token: int = 0
    max_steps: int = 10_000
    prefill_chunk: int | None = None
    ep_shards: int = 1
    speculate: SpecConfig | None = None

    def __post_init__(self):
        if self.speculate is not None \
                and not isinstance(self.speculate, SpecConfig):
            raise ValueError(
                f"speculate must be a SpecConfig or None, got "
                f"{type(self.speculate).__name__}")
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.page_size < 1:
            raise ValueError("page_size must be >= 1")
        if self.num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is the "
                             "scratch page)")
        if not 1 <= self.ctx_bucket_pages <= self.max_pages_per_slot:
            raise ValueError("ctx_bucket_pages must be in "
                             "[1, max_pages_per_slot]")
        if self.prompt_bucket < self.page_size \
                or self.prompt_bucket % self.page_size:
            raise ValueError(
                f"prompt_bucket={self.prompt_bucket} must be a "
                f"positive multiple of page_size={self.page_size} "
                f"(prefill writes whole pages)")
        if self.prefill_chunk is not None and (
                self.prefill_chunk < self.page_size
                or self.prefill_chunk % self.page_size):
            raise ValueError(
                f"prefill_chunk={self.prefill_chunk} must be a "
                f"positive multiple of page_size={self.page_size} "
                f"(chunks write whole pages)")
        if self.ep_shards < 1:
            raise ValueError("ep_shards must be >= 1")
        if self.ep_shards > 1:
            if self.max_batch % self.ep_shards:
                raise ValueError(
                    f"ep_shards={self.ep_shards} must divide "
                    f"max_batch={self.max_batch} (the slot grid is "
                    f"row-partitioned across shards)")
            if self.num_pages % self.ep_shards:
                raise ValueError(
                    f"ep_shards={self.ep_shards} must divide "
                    f"num_pages={self.num_pages} (the page slab is "
                    f"partitioned across shards)")
            if self.num_pages // self.ep_shards < 2:
                raise ValueError(
                    f"num_pages={self.num_pages} leaves fewer than 2 "
                    f"pages per shard at ep_shards={self.ep_shards} "
                    f"(each shard reserves its own scratch page)")

    @property
    def max_context(self) -> int:
        return self.max_pages_per_slot * self.page_size


@dataclasses.dataclass
class _QueueEntry:
    """One queued (or evicted-and-requeued) request."""

    arrival_step: int
    req: Request                   # current incarnation (prompt grows
                                   # across evictions)
    orig: Request                  # pre-eviction identity (output key)
    arrival_s: float | None        # wall clock when the trace arrival
                                   # step was reached (TTFT base); None
                                   # until then — a future arrival must
                                   # not accrue synthetic queue wait
    first_token_s: float | None    # survives eviction: the client
                                   # already holds the first token


@dataclasses.dataclass
class _Slot:
    """Host-side state of one occupied batch slot."""

    req: Request
    orig: Request                  # pre-eviction identity (output key)
    pages: list
    length: int                    # cache positions written (prompt+fed)
    emitted: list                  # tokens delivered THIS incarnation
    admit_step: int
    arrival_s: float               # wall clock at trace arrival
    first_token_s: float | None
    prefill_pos: int | None = None  # next chunk start (chunked prefill
                                    # in flight); None = decoding
    prefill_toks: object = None     # padded np prompt for the chunks
    draft: object = None            # DraftState (speculative decode):
                                    # the slot's suffix-match table,
                                    # rebuilt from prompt+emitted so it
                                    # survives eviction and migration
    spec_drafted: int = 0           # drafts proposed this incarnation
    spec_accepted: int = 0          # ... and accepted (= canonical)


# ----------------------------------------------------------------------
# Jitted kernels (module-level so every engine instance shares caches)
# ----------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("cfg",))
def _prefill_padded(params, cfg: MoEConfig, prompt_padded, true_len):
    """Prefill one padded prompt: [1, T_pad] int32 -> (logits [V] at
    the true last position, k_seq/v_seq [L, N_kv, T_pad, D]).  Pad
    positions compute garbage no causal query before them ever sees;
    their K/V rows land in pages the length mask never exposes."""
    t_pad = prompt_padded.shape[1]
    cache = init_cache(cfg, 1, t_pad)
    x, cache = prefill_forward(params, cfg, prompt_padded, cache)
    h = jax.lax.dynamic_slice(
        x, (0, true_len - 1, 0), (1, 1, x.shape[-1]))
    logits = lm_logits(params, cfg, h)[0]                    # [V]
    return logits, cache.k[:, 0], cache.v[:, 0]


@functools.partial(jax.jit, static_argnames=("cfg",))
def _prefill_chunk(params, cfg: MoEConfig, k_pages, v_pages, chunk_toks,
                   block_table, chunk_page_ids, start_pos, rel_last):
    """Prefill ONE fixed-size chunk of a long prompt directly into the
    paged cache.

    chunk_toks: [1, C] int32 (C = ``ServeConfig.prefill_chunk``);
    block_table: [n] page ids covering positions [0, start_pos + C)
    (bucketed, scratch-padded); chunk_page_ids: [C / page] the pages
    THIS chunk writes; start_pos: absolute position of the chunk's
    first token; rel_last: in-chunk index of the prompt's true last
    token (clipped — only the chunk containing it keeps the logits).
    Returns (logits [V], k_pages, v_pages).

    Per-layer math mirrors :func:`_prefill_padded`'s single-shot path
    at chunk granularity: the chunk's K/V land in their pages BEFORE
    the gather, so in-chunk causal attention sees them through the
    same paged read decode uses.  Positions past the true prompt end
    write garbage rows that decode overwrites before any causal query
    exposes them — the whole-prefill invariant, per chunk."""
    c = chunk_toks.shape[1]
    nh, nkv, dh = (cfg.num_heads, cfg.resolved_num_kv_heads,
                   cfg.resolved_head_dim)
    page = k_pages.shape[3]
    n_ctx = block_table.shape[0] * page
    n_c = c // page
    positions = start_pos + jnp.arange(c, dtype=jnp.int32)   # [C]
    x = params["embed"].astype(cfg.dtype)[chunk_toks]        # [1, C, H]
    for li, layer in enumerate(params["layers"]):
        h_in = rms_norm(x, layer["attn_norm"])
        q = (h_in @ layer["wq"].astype(x.dtype)).reshape(1, c, nh, dh)
        k = (h_in @ layer["wk"].astype(x.dtype)).reshape(1, c, nkv, dh)
        v = (h_in @ layer["wv"].astype(x.dtype)).reshape(1, c, nkv, dh)
        q, k = _rope(q, k, positions[None, :], cfg.rope_theta)

        kc = k[0].reshape(n_c, page, nkv, dh).transpose(0, 2, 1, 3)
        vc = v[0].reshape(n_c, page, nkv, dh).transpose(0, 2, 1, 3)
        k_pages = k_pages.at[li, chunk_page_ids].set(
            kc.astype(k_pages.dtype))
        v_pages = v_pages.at[li, chunk_page_ids].set(
            vc.astype(v_pages.dtype))

        kk = gather_ctx(k_pages[li], block_table[None, :])
        vv = gather_ctx(v_pages[li], block_table[None, :])
        if nkv != nh:
            rep = nh // nkv
            kk = jnp.repeat(kk, rep, axis=1)
            vv = jnp.repeat(vv, rep, axis=1)
        qh = q.transpose(0, 2, 1, 3)                # [1, N, C, D]
        logits = jnp.einsum(
            "bntd,bnsd->bnts", qh, kk, preferred_element_type=jnp.float32
        ) * (dh ** -0.5)
        mask = (jnp.arange(n_ctx, dtype=jnp.int32)[None, :]
                <= positions[:, None])[None, None, :, :]
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
        ctx = jnp.einsum(
            "bnts,bnsd->bntd", probs, vv, preferred_element_type=jnp.float32
        ).transpose(0, 2, 1, 3).reshape(1, c, nh * dh).astype(x.dtype)
        x = x + ctx @ layer["wo"].astype(x.dtype)

        f_in = rms_norm(x, layer["ffn_norm"])
        layer_cfg = cfg if li in cfg.moe_layer_indices else cfg.replace(
            num_experts=1, expert_top_k=1, num_shared_experts=0)
        o = moe_layer(layer["moe"], f_in.reshape(c, -1), layer_cfg,
                      use_pallas=False)
        x = x + o.out.reshape(1, c, -1).astype(x.dtype)

    h = jax.lax.dynamic_slice(x, (0, rel_last, 0), (1, 1, x.shape[-1]))
    return lm_logits(params, cfg, h)[0], k_pages, v_pages


@functools.partial(jax.jit, static_argnames=("cfg",))
def _paged_decode_step(params, cfg: MoEConfig, k_pages, v_pages, toks,
                       block_tables, positions):
    """One decode step for the whole slot grid.

    toks: [B] int32 tokens to feed; block_tables: [B, n] page ids
    (bucketed); positions: [B] write positions (= each slot's current
    length; inactive slots pass 0 with an all-scratch table).  Returns
    (logits [B, V] f32, k_pages, v_pages).  Mirrors
    ``generate._decode_step``'s per-layer arithmetic with per-slot
    positions and paged K/V."""
    b = toks.shape[0]
    nh, nkv, dh = (cfg.num_heads, cfg.resolved_num_kv_heads,
                   cfg.resolved_head_dim)
    page = k_pages.shape[3]
    n_ctx = block_tables.shape[1] * page
    x = params["embed"].astype(cfg.dtype)[toks][:, None, :]  # [B, 1, H]
    page_ids = jnp.take_along_axis(
        block_tables, (positions // page)[:, None], axis=1)[:, 0]
    rows = positions % page
    for li, layer in enumerate(params["layers"]):
        h_in = rms_norm(x, layer["attn_norm"])
        q = (h_in @ layer["wq"].astype(x.dtype)).reshape(b, 1, nh, dh)
        k = (h_in @ layer["wk"].astype(x.dtype)).reshape(b, 1, nkv, dh)
        v = (h_in @ layer["wv"].astype(x.dtype)).reshape(b, 1, nkv, dh)
        q, k = _rope(q, k, positions[:, None], cfg.rope_theta)

        k_pages = k_pages.at[li].set(
            store_token(k_pages[li], k[:, 0], page_ids, rows))
        v_pages = v_pages.at[li].set(
            store_token(v_pages[li], v[:, 0], page_ids, rows))

        kk = gather_ctx(k_pages[li], block_tables)  # [B, nkv, ctx, D]
        vv = gather_ctx(v_pages[li], block_tables)
        if nkv != nh:
            rep = nh // nkv
            kk = jnp.repeat(kk, rep, axis=1)
            vv = jnp.repeat(vv, rep, axis=1)
        qh = q.transpose(0, 2, 1, 3)                # [B, N, 1, D]
        logits = jnp.einsum(
            "bntd,bnsd->bnts", qh, kk, preferred_element_type=jnp.float32
        ) * (dh ** -0.5)
        mask = (jnp.arange(n_ctx)[None, :]
                <= positions[:, None])[:, None, None, :]
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
        ctx = jnp.einsum(
            "bnts,bnsd->bntd", probs, vv, preferred_element_type=jnp.float32
        ).transpose(0, 2, 1, 3).reshape(b, 1, nh * dh).astype(x.dtype)
        x = x + ctx @ layer["wo"].astype(x.dtype)

        f_in = rms_norm(x, layer["ffn_norm"])
        layer_cfg = cfg if li in cfg.moe_layer_indices else cfg.replace(
            num_experts=1, expert_top_k=1, num_shared_experts=0)
        o = moe_layer(layer["moe"], f_in.reshape(b, -1), layer_cfg,
                      use_pallas=False)
        x = x + o.out.reshape(b, 1, -1).astype(x.dtype)

    return lm_logits(params, cfg, x), k_pages, v_pages


@functools.partial(jax.jit, static_argnames=("cfg",))
def _paged_verify_step(params, cfg: MoEConfig, k_pages, v_pages, toks,
                       block_tables, positions):
    """Speculative verify: score a ``T = draft_tokens + 1`` position
    SPAN per slot in one forward (ISSUE 20).

    toks: [B, T] int32 — column 0 is the canonical last-sampled token,
    columns 1..k the drafted continuation (pad past the real drafts);
    positions: [B] base write positions (column t lands at
    ``positions + t``).  Returns (logits [B, T, V] f32, k_pages,
    v_pages): logits[:, t] is the next-token distribution after feeding
    column t — column 0 is bit-equal to what :func:`_paged_decode_step`
    returns for the same token, columns 1..k are what it WOULD return
    after each draft, all for one weight pass (the planner's decode
    mode prices the step as wire/HBM-bound, so the extra columns ride
    nearly free).

    Span positions past the gathered context (a slot drafted into its
    context ceiling) route their KV writes to the scratch page and
    produce garbage columns the host never reads — the host truncates
    drafts to fit, this is the in-graph belt-and-suspenders.  Rejected
    columns DO write rows: the host rolls back the block-table/length
    state, and the next step's span overwrites those exact rows before
    any causal mask exposes them (the prefill pad-row invariant)."""
    b, t_span = toks.shape
    nh, nkv, dh = (cfg.num_heads, cfg.resolved_num_kv_heads,
                   cfg.resolved_head_dim)
    page = k_pages.shape[3]
    ntab = block_tables.shape[1]
    n_ctx = ntab * page
    x = params["embed"].astype(cfg.dtype)[toks]              # [B, T, H]
    pos = (positions[:, None]
           + jnp.arange(t_span, dtype=jnp.int32)[None, :])   # [B, T]
    valid = pos < n_ctx
    pidx = jnp.clip(pos // page, 0, ntab - 1)
    page_ids = jnp.where(
        valid, jnp.take_along_axis(block_tables, pidx, axis=1),
        jnp.int32(SCRATCH_PAGE))
    rows = jnp.where(valid, pos % page, 0)
    for li, layer in enumerate(params["layers"]):
        h_in = rms_norm(x, layer["attn_norm"])
        q = (h_in @ layer["wq"].astype(x.dtype)).reshape(b, t_span, nh,
                                                         dh)
        k = (h_in @ layer["wk"].astype(x.dtype)).reshape(b, t_span, nkv,
                                                         dh)
        v = (h_in @ layer["wv"].astype(x.dtype)).reshape(b, t_span, nkv,
                                                         dh)
        q, k = _rope(q, k, pos, cfg.rope_theta)

        k_pages = k_pages.at[li].set(
            store_tokens(k_pages[li], k, page_ids, rows))
        v_pages = v_pages.at[li].set(
            store_tokens(v_pages[li], v, page_ids, rows))

        kk = gather_ctx(k_pages[li], block_tables)  # [B, nkv, ctx, D]
        vv = gather_ctx(v_pages[li], block_tables)
        if nkv != nh:
            rep = nh // nkv
            kk = jnp.repeat(kk, rep, axis=1)
            vv = jnp.repeat(vv, rep, axis=1)
        qh = q.transpose(0, 2, 1, 3)                # [B, N, T, D]
        logits = jnp.einsum(
            "bntd,bnsd->bnts", qh, kk, preferred_element_type=jnp.float32
        ) * (dh ** -0.5)
        mask = (jnp.arange(n_ctx)[None, None, None, :]
                <= pos[:, None, :, None])
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
        ctx = jnp.einsum(
            "bnts,bnsd->bntd", probs, vv, preferred_element_type=jnp.float32
        ).transpose(0, 2, 1, 3).reshape(b, t_span, nh * dh).astype(
            x.dtype)
        x = x + ctx @ layer["wo"].astype(x.dtype)

        f_in = rms_norm(x, layer["ffn_norm"])
        layer_cfg = cfg if li in cfg.moe_layer_indices else cfg.replace(
            num_experts=1, expert_top_k=1, num_shared_experts=0)
        o = moe_layer(layer["moe"], f_in.reshape(b * t_span, -1),
                      layer_cfg, use_pallas=False)
        x = x + o.out.reshape(b, t_span, -1).astype(x.dtype)

    return lm_logits_span(params, cfg, x), k_pages, v_pages


# ----------------------------------------------------------------------
# EP-sharded decode (the fabric's decode-pool execution path)
# ----------------------------------------------------------------------

_EP_DECODE_CACHE: dict = {}


def _ep_param_specs(params, cfg: MoEConfig):
    """Partition specs for the EP decode step: expert-axis leaves of
    every MoE layer shard along ``"ep"`` (the ``_qscale`` sidecars
    included — their leading axis is the expert axis too); everything
    else (attention, norms, embed/head, the replicated router
    ``gate_w``, dense layers' single-expert FFNs) replicates."""
    from jax.sharding import PartitionSpec as P
    from jax.tree_util import DictKey, tree_map_with_path

    def spec(path, leaf):
        names = [p.key for p in path if isinstance(p, DictKey)]
        if ("moe" in names and (not names or names[-1] != "gate_w")
                and getattr(leaf, "ndim", 0) >= 1
                and leaf.shape[0] == cfg.num_experts):
            return P("ep")
        return P()

    return tree_map_with_path(spec, params)


def _ep_decode_fn(mesh, cfg: MoEConfig, params):
    """Build (and cache per (mesh, cfg, param-structure)) the
    EP-sharded twin of :func:`_paged_decode_step`: one jitted
    ``shard_map`` whose body runs the same per-layer arithmetic on the
    LOCAL slot rows and the LOCAL slab of the paged KV cache, with MoE
    layers dispatched through the decode-priced ragged EP path
    (:func:`flashmoe_tpu.parallel.ragged_ep.decode_moe_rows`) — the
    plan ``serve.plan`` resolves in decode mode is what actually
    executes here.  Block tables carry per-SHARD-local page ids."""
    import jax.tree_util as jtu
    from jax.sharding import PartitionSpec as P

    from flashmoe_tpu.utils.compat import shard_map

    key = (mesh, cfg, jtu.tree_structure(params))
    cached = _EP_DECODE_CACHE.get(key)
    if cached is not None:
        return cached

    from flashmoe_tpu.parallel import ragged_ep

    pspecs = _ep_param_specs(params, cfg)
    exchange = "ragged" if jax.default_backend() == "tpu" else "dense"

    def body(params, k_pages, v_pages, toks, block_tables, positions):
        # LOCAL view: max_batch/d slot rows, num_pages/d slab pages.
        # Attention mirrors _paged_decode_step (kept duplicated so the
        # unsharded path stays byte-identical to its pre-fabric form);
        # only the MoE FFN differs.
        b = toks.shape[0]
        nh, nkv, dh = (cfg.num_heads, cfg.resolved_num_kv_heads,
                       cfg.resolved_head_dim)
        page = k_pages.shape[3]
        n_ctx = block_tables.shape[1] * page
        x = params["embed"].astype(cfg.dtype)[toks][:, None, :]
        page_ids = jnp.take_along_axis(
            block_tables, (positions // page)[:, None], axis=1)[:, 0]
        rows = positions % page
        for li, layer in enumerate(params["layers"]):
            h_in = rms_norm(x, layer["attn_norm"])
            q = (h_in @ layer["wq"].astype(x.dtype)).reshape(b, 1, nh,
                                                             dh)
            k = (h_in @ layer["wk"].astype(x.dtype)).reshape(b, 1, nkv,
                                                             dh)
            v = (h_in @ layer["wv"].astype(x.dtype)).reshape(b, 1, nkv,
                                                             dh)
            q, k = _rope(q, k, positions[:, None], cfg.rope_theta)

            k_pages = k_pages.at[li].set(
                store_token(k_pages[li], k[:, 0], page_ids, rows))
            v_pages = v_pages.at[li].set(
                store_token(v_pages[li], v[:, 0], page_ids, rows))

            kk = gather_ctx(k_pages[li], block_tables)
            vv = gather_ctx(v_pages[li], block_tables)
            if nkv != nh:
                rep = nh // nkv
                kk = jnp.repeat(kk, rep, axis=1)
                vv = jnp.repeat(vv, rep, axis=1)
            qh = q.transpose(0, 2, 1, 3)
            logits = jnp.einsum(
                "bntd,bnsd->bnts", qh, kk,
                preferred_element_type=jnp.float32) * (dh ** -0.5)
            mask = (jnp.arange(n_ctx)[None, :]
                    <= positions[:, None])[:, None, None, :]
            logits = jnp.where(mask, logits, -1e30)
            probs = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
            ctx = jnp.einsum(
                "bnts,bnsd->bntd", probs, vv,
                preferred_element_type=jnp.float32
            ).transpose(0, 2, 1, 3).reshape(b, 1, nh * dh).astype(
                x.dtype)
            x = x + ctx @ layer["wo"].astype(x.dtype)

            f_in = rms_norm(x, layer["ffn_norm"])
            if li in cfg.moe_layer_indices:
                o_out = ragged_ep.decode_moe_rows(
                    layer["moe"], f_in.reshape(b, -1), cfg,
                    axis="ep", exchange=exchange).out
            else:
                dense_cfg = cfg.replace(num_experts=1, expert_top_k=1,
                                        num_shared_experts=0)
                o_out = moe_layer(layer["moe"], f_in.reshape(b, -1),
                                  dense_cfg, use_pallas=False).out
            x = x + o_out.reshape(b, 1, -1).astype(x.dtype)

        return lm_logits(params, cfg, x), k_pages, v_pages

    fn = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(pspecs, P(None, "ep"), P(None, "ep"), P("ep"),
                  P("ep", None), P("ep")),
        out_specs=(P("ep"), P(None, "ep"), P(None, "ep")),
        check_vma=False))
    _EP_DECODE_CACHE[key] = fn
    return fn


_EP_VERIFY_CACHE: dict = {}


def _ep_verify_fn(mesh, cfg: MoEConfig, params):
    """The EP-sharded twin of :func:`_paged_verify_step`: the same
    span-scoring body over the LOCAL slot rows and cache slab, MoE
    through the decode-priced ragged EP path on ``b_local * T`` rows.
    Cached like :func:`_ep_decode_fn`."""
    import jax.tree_util as jtu
    from jax.sharding import PartitionSpec as P

    from flashmoe_tpu.utils.compat import shard_map

    key = (mesh, cfg, jtu.tree_structure(params))
    cached = _EP_VERIFY_CACHE.get(key)
    if cached is not None:
        return cached

    from flashmoe_tpu.parallel import ragged_ep

    pspecs = _ep_param_specs(params, cfg)
    exchange = "ragged" if jax.default_backend() == "tpu" else "dense"

    def body(params, k_pages, v_pages, toks, block_tables, positions):
        b, t_span = toks.shape
        nh, nkv, dh = (cfg.num_heads, cfg.resolved_num_kv_heads,
                       cfg.resolved_head_dim)
        page = k_pages.shape[3]
        ntab = block_tables.shape[1]
        n_ctx = ntab * page
        x = params["embed"].astype(cfg.dtype)[toks]
        pos = (positions[:, None]
               + jnp.arange(t_span, dtype=jnp.int32)[None, :])
        valid = pos < n_ctx
        pidx = jnp.clip(pos // page, 0, ntab - 1)
        page_ids = jnp.where(
            valid, jnp.take_along_axis(block_tables, pidx, axis=1),
            jnp.int32(SCRATCH_PAGE))
        rows = jnp.where(valid, pos % page, 0)
        for li, layer in enumerate(params["layers"]):
            h_in = rms_norm(x, layer["attn_norm"])
            q = (h_in @ layer["wq"].astype(x.dtype)).reshape(
                b, t_span, nh, dh)
            k = (h_in @ layer["wk"].astype(x.dtype)).reshape(
                b, t_span, nkv, dh)
            v = (h_in @ layer["wv"].astype(x.dtype)).reshape(
                b, t_span, nkv, dh)
            q, k = _rope(q, k, pos, cfg.rope_theta)

            k_pages = k_pages.at[li].set(
                store_tokens(k_pages[li], k, page_ids, rows))
            v_pages = v_pages.at[li].set(
                store_tokens(v_pages[li], v, page_ids, rows))

            kk = gather_ctx(k_pages[li], block_tables)
            vv = gather_ctx(v_pages[li], block_tables)
            if nkv != nh:
                rep = nh // nkv
                kk = jnp.repeat(kk, rep, axis=1)
                vv = jnp.repeat(vv, rep, axis=1)
            qh = q.transpose(0, 2, 1, 3)
            logits = jnp.einsum(
                "bntd,bnsd->bnts", qh, kk,
                preferred_element_type=jnp.float32) * (dh ** -0.5)
            mask = (jnp.arange(n_ctx)[None, None, None, :]
                    <= pos[:, None, :, None])
            logits = jnp.where(mask, logits, -1e30)
            probs = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
            ctx = jnp.einsum(
                "bnts,bnsd->bntd", probs, vv,
                preferred_element_type=jnp.float32
            ).transpose(0, 2, 1, 3).reshape(b, t_span, nh * dh).astype(
                x.dtype)
            x = x + ctx @ layer["wo"].astype(x.dtype)

            f_in = rms_norm(x, layer["ffn_norm"])
            if li in cfg.moe_layer_indices:
                o_out = ragged_ep.decode_moe_rows(
                    layer["moe"], f_in.reshape(b * t_span, -1), cfg,
                    axis="ep", exchange=exchange).out
            else:
                dense_cfg = cfg.replace(num_experts=1, expert_top_k=1,
                                        num_shared_experts=0)
                o_out = moe_layer(layer["moe"],
                                  f_in.reshape(b * t_span, -1),
                                  dense_cfg, use_pallas=False).out
            x = x + o_out.reshape(b, t_span, -1).astype(x.dtype)

        return lm_logits_span(params, cfg, x), k_pages, v_pages

    fn = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(pspecs, P(None, "ep"), P(None, "ep"), P("ep", None),
                  P("ep", None), P("ep")),
        out_specs=(P("ep"), P(None, "ep"), P(None, "ep")),
        check_vma=False))
    _EP_VERIFY_CACHE[key] = fn
    return fn


@jax.jit
def _sample_dynamic(logits, keys, temps, top_ks, top_ps):
    """Per-slot sampling with DYNAMIC per-request knobs (the engine's
    batch mixes requests): temperature <= 0 rows take the exact argmax
    (bit-equal to ``sample_tokens``' greedy arm); sampled rows apply
    top-k then nucleus truncation, keyed per request."""
    v = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    neg = jnp.asarray(-1e30, jnp.float32)
    scaled = logits.astype(jnp.float32) / jnp.maximum(
        temps, 1e-6)[:, None]
    sort_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(
        sort_desc, jnp.clip(top_ks - 1, 0, v - 1)[:, None], axis=1)
    use_k = (top_ks > 0) & (top_ks < v)
    scaled = jnp.where(use_k[:, None] & (scaled < kth), neg, scaled)
    sort_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sort_desc, axis=-1)
    csum = jnp.cumsum(probs, axis=-1)
    keep = (csum - probs) < top_ps[:, None]
    thresh = jnp.min(
        jnp.where(keep, sort_desc, jnp.inf), axis=-1, keepdims=True)
    scaled = jnp.where(scaled < thresh, neg, scaled)
    sampled = jax.vmap(
        lambda kk, ll: jax.random.categorical(kk, ll))(keys, scaled)
    return jnp.where(temps <= 0.0, greedy, sampled.astype(jnp.int32))


def _as_watchdog(slo):
    if slo is None:
        return None
    from flashmoe_tpu.profiler.slo import SLOConfig, SLOWatchdog

    return SLOWatchdog(slo) if isinstance(slo, SLOConfig) else slo


class ServingEngine:
    """Multi-request continuous-batching driver (host loop + jitted
    steps).  See the module docstring for the lifecycle."""

    def __init__(self, params, cfg: MoEConfig,
                 serve: ServeConfig | None = None, *,
                 recorder=None, slo=None, mesh=None, metrics_obj=None,
                 tracer=None, telemetry_port=None, prefill_fn=None,
                 replica_tag=None, pools_info=None, clock=None,
                 heartbeat_fn=None):
        """``prefill_fn(prompt_padded, true_len, *, rid)`` replaces the
        local prefill when set — the fabric's KV-handoff seam: the
        callable must honor :func:`_prefill_padded`'s contract
        (logits [V], k_seq/v_seq [L, N_kv, T_pad, D]).  A handed-off
        prefill is always whole (``prefill_chunk`` applies to the LOCAL
        path only — in a disaggregated fabric long prompts cannot hole
        decode by construction).  ``replica_tag`` (e.g. ``"r0"``)
        additionally keys this engine's TTFT/TPOT sketches per replica;
        ``pools_info`` is surfaced verbatim in ``/vars``.  ``clock``: a
        zero-arg seconds source replacing ``time.monotonic`` for every
        latency measurement (arrival, TTFT, TPOT, step time) — a
        :class:`~flashmoe_tpu.fabric.vclock.VirtualClock` additionally
        gets its decode tick stepped at the end of every engine step;
        None (the default) is the wall clock, byte-identical to the
        pre-seam engine.  ``heartbeat_fn(phase)``: invoked at every
        sub-step phase boundary (``admit`` / ``prefill`` / ``sample`` /
        ``decode`` / ``end``) — the fabric's liveness seam (a
        :class:`~flashmoe_tpu.fabric.leasestore.HeartbeatPublisher`):
        a replica that hangs mid-step stops beating mid-step, so the
        watchdog catches it without waiting for the step boundary.
        None (the default) makes zero calls — byte-identical."""
        if cfg.drop_tokens:
            raise ValueError(
                "the serving engine requires a dropless config "
                "(drop_tokens=False): inactive/retired batch slots "
                "must never compete with live requests for capacity "
                "slots, and decode batches are token-count-tiny anyway")
        self.params = params
        self.cfg = cfg
        self.serve = serve if serve is not None else ServeConfig()
        self.mesh = mesh
        self._prefill_fn = prefill_fn
        self.replica_tag = replica_tag
        self.pools_info = pools_info
        self.recorder = recorder
        self.metrics = metrics_obj if metrics_obj is not None \
            else _global_metrics
        self.watchdog = _as_watchdog(slo)
        # ---- measured-latency clock seam -----------------------------
        # every wall read below goes through self._clock; a VirtualClock
        # (duck-typed on complete_step) additionally advances its decode
        # tick at the end of each engine step
        self._clock = clock if clock is not None else time.monotonic
        self._vclock = (clock if hasattr(clock, "complete_step")
                        else None)
        self._heartbeat = heartbeat_fn
        # ---- live telemetry plane (default off = zero threads, no
        # behavior change; outputs are bit-identical either way) ------
        self.tracer = None
        if tracer:
            from flashmoe_tpu.telemetry_plane.tracing import RequestTracer

            self.tracer = (tracer if isinstance(tracer, RequestTracer)
                           else RequestTracer(metrics_obj=self.metrics,
                                              clock=self._clock))
            self.tracer.install()
        self.telemetry = None
        if telemetry_port is not None:
            from flashmoe_tpu.telemetry_plane.server import maybe_server

            self.telemetry = maybe_server(
                telemetry_port, metrics_fn=lambda: self.metrics,
                health_fn=self._health_snapshot,
                vars_fn=self._vars_snapshot)
        from flashmoe_tpu.telemetry_plane.sketch import WindowedRate

        self._rates = {"tokens": WindowedRate(), "admits": WindowedRate(),
                       "evictions": WindowedRate()}

        # ---- quantized expert storage (flashmoe_tpu/quant/) ----------
        # the engine accepts a QuantizedExpertState (or a raw quantized
        # tree) whenever cfg.expert_quant is set; the HBM the narrow
        # store frees is reported as additional KV-cache page headroom
        # (`observe --serving`), since on a serving host weight bytes
        # and KV pages compete for the same memory.
        from flashmoe_tpu import quant as qt

        if isinstance(params, qt.QuantizedExpertState):
            self.params = params = params.params
        self.quant_info = None
        if cfg.expert_quant is not None:
            if not qt.is_quantized(params):
                # a full-precision checkpoint under the quant knob
                # would fake-quant ALL expert weights inside every
                # jitted step — strictly slower with zero memory
                # savings.  Quantize ONCE at load instead, so serving
                # always runs the dequant-in-compute store (code-review
                # finding).
                self.params = params = qt.quantize_state(
                    params, cfg.expert_quant).params
            self.quant_info = {
                "expert_quant": qt.canonical_name(cfg.expert_quant),
                "freed_bytes": qt.quant_bytes_saved(params,
                                                    cfg.param_dtype),
            }

        # ---- EP-sharded decode (fabric decode-pool path) -------------
        self._ep_fn = None
        d = self.serve.ep_shards
        if d > 1:
            if cfg.num_experts % d:
                raise ValueError(
                    f"ep_shards={d} must divide num_experts="
                    f"{cfg.num_experts} (every shard holds the same "
                    f"local expert count)")
            if cfg.num_shared_experts:
                raise ValueError(
                    "EP-sharded decode requires num_shared_experts=0 "
                    "(the ragged EP path has no shared-expert arm)")
            if self.mesh is None:
                devs = jax.devices()
                if len(devs) < d:
                    raise ValueError(
                        f"ep_shards={d} needs {d} devices, have "
                        f"{len(devs)}")
                self.mesh = jax.sharding.Mesh(
                    np.asarray(devs[:d]), ("ep",))
            elif ("ep" not in self.mesh.axis_names
                  or self.mesh.shape["ep"] != d):
                raise ValueError(
                    f"ep_shards={d} needs an 'ep' mesh axis of size "
                    f"{d}, got mesh axes {dict(self.mesh.shape)}")
            self._ep_fn = _ep_decode_fn(self.mesh, cfg, params)

        # ---- speculative decoding (serving/speculate.py) -------------
        # off (None) keeps the engine byte-identical: no draft tables,
        # no verify jit, the plain one-token decode step below
        self._spec = self.serve.speculate
        self._ep_verify = None   # lazily built EP verify twin
        self._spec_steps = 0     # steps that ran a verify forward
        self._spec_drafted = 0
        self._spec_accepted = 0
        if self._spec is not None:
            self.metrics.decision(
                "serve.spec", event="armed",
                draft_tokens=self._spec.draft_tokens,
                ngram=self._spec.ngram, source=self._spec.source)

        self.cache = init_paged_cache(cfg, self.serve.num_pages,
                                      self.serve.page_size)
        self.pool = (ShardedPagePool(self.serve.num_pages, d) if d > 1
                     else PagePool(self.serve.num_pages))
        if self.quant_info is not None:
            page_bytes = (self.cache.k_pages.nbytes
                          + self.cache.v_pages.nbytes
                          ) / self.serve.num_pages
            extra = int(self.quant_info["freed_bytes"] // page_bytes)
            self.quant_info.update(
                page_bytes=int(page_bytes), extra_kv_pages=extra)
            self.metrics.decision(
                "serve.quant",
                expert_quant=self.quant_info["expert_quant"],
                freed_mb=round(self.quant_info["freed_bytes"] / 2**20,
                               3),
                extra_kv_pages=extra,
                num_pages=self.serve.num_pages)
            self.metrics.gauge("serve.quant_freed_mb",
                               self.quant_info["freed_bytes"] / 2**20)
        self.queue: deque = deque()       # (arrival_step, _Slot-seed)
        self.slots: list[_Slot | None] = [None] * self.serve.max_batch
        self._logits = jnp.zeros(
            (self.serve.max_batch, cfg.vocab_size), jnp.float32)
        self.step_idx = 0
        self.outputs: dict[int, list] = {}
        self.stats = {
            "submitted": 0, "completed": 0, "evictions": 0, "adopted": 0,
            "tokens": 0, "steps": 0, "max_queue_depth": 0,
            "max_active": 0, "decode_buckets": set(),
            "prefill_buckets": set(), "peak_occupancy": 0.0,
        }
        self._record_plan()

    # ---- planner wiring ----------------------------------------------

    def _record_plan(self) -> None:
        """Resolve the prefill- and decode-priced execution plans once
        and record them as one ``serve.plan`` decision — decode is
        priced at per-step token counts (= the slot-grid width), the
        regime where the training-shaped schedules are wrong."""
        from flashmoe_tpu.planner.select import resolve_moe_plan

        cfg = self.cfg
        pre_b, pre_c = resolve_moe_plan(cfg, self.mesh, mode="prefill")
        dec_b, dec_c = resolve_moe_plan(
            cfg, self.mesh, mode="decode",
            decode_tokens=self.serve.max_batch)
        self.decode_plan = (dec_b, dec_c)
        self.prefill_plan = (pre_b, pre_c)
        self.metrics.decision(
            "serve.plan",
            prefill_backend=pre_b, prefill_chunks=pre_c or 1,
            decode_backend=dec_b, decode_chunks=dec_c or 1,
            decode_tokens=self.serve.max_batch,
            heterogeneous=(pre_b, pre_c) != (dec_b, dec_c),
            ep=cfg.ep, moe_backend=cfg.moe_backend)

    # ---- live-plane snapshots ----------------------------------------

    def _health_snapshot(self) -> dict:
        """The ``/healthz`` document: liveness plus the engine's load
        story and the SLO watchdog's episode state."""
        doc = {
            "steps": self.step_idx,
            "queue_depth": len(self.queue),
            "active_requests": len(self._active()),
            "cache_occupancy": round(self.pool.occupancy, 4),
            "completed": self.stats["completed"],
            "evictions": self.stats["evictions"],
        }
        if self.replica_tag is not None:
            doc["replica"] = self.replica_tag
        if self.serve.speculate is not None:
            doc["spec"] = self.spec_snapshot()
        if self.watchdog is not None:
            doc["slo"] = self.watchdog.snapshot()
        return doc

    def _vars_snapshot(self) -> dict:
        """The ``/vars`` document: what this engine actually resolved
        to run (plans + shape knobs)."""
        cfg = self.cfg
        return {
            "prefill_plan": list(self.prefill_plan),
            "decode_plan": list(self.decode_plan),
            "serve": dataclasses.asdict(self.serve),
            "config": {
                "num_experts": cfg.num_experts,
                "expert_top_k": cfg.expert_top_k,
                "hidden_size": cfg.hidden_size,
                "intermediate_size": cfg.intermediate_size,
                "num_layers": cfg.num_layers,
                "moe_backend": cfg.moe_backend,
                "serving_mode": cfg.serving_mode,
                "wire_dtype": cfg.wire_dtype,
                "a2a_chunks": cfg.a2a_chunks,
                "expert_quant": cfg.expert_quant,
                "kv_wire_dtype": cfg.kv_wire_dtype,
                "ep": cfg.ep,
            },
            "quant": self.quant_info,
            "tracing": self.tracer is not None,
            "replica": self.replica_tag,
            "pools": self.pools_info,
        }

    def close(self) -> None:
        """Tear down the live plane (scrape server thread, tracer
        listener).  Idempotent; engines without one are no-ops."""
        if self.telemetry is not None:
            self.telemetry.stop()
            self.telemetry = None
        if self.tracer is not None:
            self.tracer.uninstall()

    # ---- submission --------------------------------------------------

    def submit(self, req: Request, arrival_step: int = 0) -> None:
        # the BUCKETED full lifetime must fit the slot context, so an
        # evicted request's resumed (longer, re-bucketed) prompt plus
        # its remaining budget is covered by the same bound
        need = prompt_pad(len(req.prompt) + req.max_new_tokens,
                          self.serve.prompt_bucket)
        if need > self.serve.max_context:
            raise ValueError(
                f"request {req.rid}: bucketed prompt + max_new_tokens "
                f"({need}) exceeds the slot context "
                f"{self.serve.max_context} "
                f"(max_pages_per_slot x page_size)")
        # ... and the whole POOL: a request the allocator can never
        # serve would otherwise park at the queue head and spin the
        # engine through max_steps empty iterations
        need_pages = need // self.serve.page_size
        allocatable = (self.serve.num_pages // self.serve.ep_shards) - 1
        if need_pages > allocatable:
            raise ValueError(
                f"request {req.rid}: lifetime needs {need_pages} pages "
                f"but the pool only holds {allocatable} "
                f"allocatable pages"
                + (f" per shard (ep_shards={self.serve.ep_shards})"
                   if self.serve.ep_shards > 1 else ""))
        self.queue.append(_QueueEntry(int(arrival_step), req, req,
                                      None, None))
        self.stats["submitted"] += 1

    # ---- crash migration (the fabric's recovery path) ----------------

    def evacuate(self) -> tuple:
        """Crash evacuation: preempt EVERY active slot back through the
        PR 10 eviction path — each in-flight request's resumed prompt
        carries its delivered tokens, its pages free, its trace step
        span closes (``on_evict`` reopens the queued clock, so the
        fleet trace stays orphan-free) — then hand the whole queue to
        the caller.  Returns ``(inflight, queued)``: the evicted
        in-flight entries in ADMISSION order, and the entries that were
        still queued.  The engine is empty afterwards; the fabric
        re-routes both lists onto surviving replicas
        (:meth:`adopt`), and the deterministic resume makes the
        migrated token streams bit-equal to an uninterrupted run."""
        queued = list(self.queue)
        while self._evict_youngest():
            pass
        # _evict_youngest requeues at the FRONT, youngest first — so
        # the front of the deque now reads oldest-admitted .. youngest,
        # followed by the entries that were already queued
        inflight = list(self.queue)[:len(self.queue) - len(queued)]
        self.queue.clear()
        return inflight, queued

    def adopt(self, entry: _QueueEntry, *, front: bool = False) -> None:
        """Adopt a migrated queue entry from a crashed replica: a RAW
        queue insertion that preserves the entry's arrival and
        first-token clocks (the client already holds its delivered
        tokens — TTFT/TPOT must not restart) and its resumed prompt.
        ``front=True`` resumes ahead of local work: migrated in-flight
        requests outrank never-admitted ones, matching the eviction
        path's own head-of-queue discipline."""
        if front:
            # immediately admittable: the local step counter may trail
            # the dead replica's, and a resumed request must not wait
            # for it to catch up
            entry.arrival_step = min(entry.arrival_step, self.step_idx)
            self.queue.appendleft(entry)
        else:
            self.queue.append(entry)
        self.stats["adopted"] += 1

    # ---- internals ---------------------------------------------------

    def _active(self) -> list:
        return [i for i, s in enumerate(self.slots) if s is not None]

    def _decoding(self) -> list:
        """Occupied slots whose prefill has completed (the rows the
        sampler and the decode step actually advance)."""
        return [i for i, s in enumerate(self.slots)
                if s is not None and s.prefill_pos is None]

    # ---- shard-aware page accounting (ep_shards == 1: pass-through,
    # slots hold GLOBAL page ids; sharded: each slot belongs to the
    # shard owning its row block and holds shard-LOCAL ids, converted
    # to global only at the eager whole-page write sites) -------------

    def _shard_of(self, slot: int) -> int:
        return slot // (self.serve.max_batch // self.serve.ep_shards)

    def _alloc_pages(self, slot: int, n: int):
        if self.serve.ep_shards > 1:
            return self.pool.alloc(n, self._shard_of(slot))
        return self.pool.alloc(n)

    def _free_slot_pages(self, slot: int, pages) -> None:
        if self.serve.ep_shards > 1:
            self.pool.free(pages, self._shard_of(slot))
        else:
            self.pool.free(pages)

    def _global_pages(self, slot: int, pages):
        if self.serve.ep_shards > 1:
            return self.pool.to_global(pages, self._shard_of(slot))
        return pages

    def _arrived_head(self) -> bool:
        return bool(self.queue) \
            and self.queue[0].arrival_step <= self.step_idx

    def _mark_arrivals(self) -> None:
        """Stamp the wall clock on every queue entry whose trace
        arrival step has been reached — the TTFT base.  A future
        arrival accrues no synthetic queue wait."""
        now = self._clock()
        for entry in self.queue:
            if entry.arrival_s is None \
                    and entry.arrival_step <= self.step_idx:
                entry.arrival_s = now
                if self.tracer is not None:
                    self.tracer.on_arrival(entry.orig.rid)

    def _shard_free_pages(self, slot: int) -> int:
        if self.serve.ep_shards > 1:
            return self.pool.shard_free_pages(self._shard_of(slot))
        return self.pool.free_pages

    def _admit(self) -> None:
        sv = self.serve
        while self._arrived_head() and None in self.slots:
            entry = self.queue[0]
            req, orig = entry.req, entry.orig
            t0 = len(req.prompt)
            t_pad = prompt_pad(t0, sv.prompt_bucket)
            chunk = sv.prefill_chunk
            # a handed-off prefill is always whole: the fabric's
            # prefill pool absorbs the long prompt, so chunking (the
            # single-engine mitigation) only applies to the local path
            chunked = (chunk is not None and t_pad > chunk
                       and self._prefill_fn is None)
            n_pages = (chunk if chunked else t_pad) // sv.page_size
            # first free slot whose shard can hold the pages (LIFO
            # alloc never partially succeeds, so free_pages >= n is
            # exactly alloc-would-succeed — the unsharded order is the
            # pre-fabric alloc-then-first-free-slot order)
            slot = None
            for i, s in enumerate(self.slots):
                if s is None and self._shard_free_pages(i) >= n_pages:
                    slot = i
                    break
            if slot is None:
                break                      # head-of-line: deterministic
            pages = self._alloc_pages(slot, n_pages)
            self.queue.popleft()
            if self.tracer is not None:
                # closes the queued span and arms prefill attribution
                # for the trace_span below
                self.tracer.on_admit(orig.rid, self.step_idx,
                                     resumed=req is not orig)
            if chunked:
                # pad out to whole chunks; trailing all-pad chunks past
                # the true end are never run (_advance_prefill stops at
                # the chunk holding the prompt's last token)
                t_pad_c = ((t_pad + chunk - 1) // chunk) * chunk
                toks = np.full((t_pad_c,), sv.pad_token, np.int32)
                toks[:t0] = req.prompt
                self.slots[slot] = _Slot(
                    req=req, orig=orig, pages=list(pages), length=0,
                    emitted=[], admit_step=self.step_idx,
                    arrival_s=entry.arrival_s,
                    first_token_s=entry.first_token_s,
                    prefill_pos=0, prefill_toks=toks)
                self.stats["prefill_buckets"].add(chunk)
            else:
                prompt = jnp.asarray(req.prompt, jnp.int32)[None, :]
                if t_pad > t0:
                    prompt = jnp.pad(
                        prompt, ((0, 0), (0, t_pad - t0)),
                        constant_values=sv.pad_token)
                with trace_span("serve.prefill"):
                    if self._prefill_fn is not None:
                        logits, k_seq, v_seq = self._prefill_fn(
                            prompt, t0, rid=orig.rid)
                    else:
                        logits, k_seq, v_seq = _prefill_padded(
                            self.params, self.cfg, prompt,
                            jnp.int32(t0))
                    page_ids = jnp.asarray(
                        self._global_pages(slot, pages), jnp.int32)
                    self.cache = self.cache._replace(
                        k_pages=store_prefill(self.cache.k_pages,
                                              k_seq, page_ids),
                        v_pages=store_prefill(self.cache.v_pages,
                                              v_seq, page_ids))
                self._logits = self._logits.at[slot].set(logits)
                self.slots[slot] = _Slot(
                    req=req, orig=orig, pages=list(pages), length=t0,
                    emitted=[], admit_step=self.step_idx,
                    arrival_s=entry.arrival_s,
                    first_token_s=entry.first_token_s)
                self.stats["prefill_buckets"].add(t_pad)
            self._rates["admits"].add()
            self.metrics.decision(
                "serve.admit", rid=orig.rid, step=self.step_idx,
                slot=slot, prompt_tokens=t0, pages=n_pages,
                resumed=req is not orig, chunked=chunked,
                queue_depth=len(self.queue))

    def _advance_prefill(self) -> None:
        """Advance every mid-prefill slot by exactly ONE fixed-size
        chunk (slot order — deterministic): the per-step prefill budget
        is bounded by ``prefill_chunk`` tokens per prefilling slot, so
        a long prompt is amortized across steps instead of holing one
        decode step with a monolithic prefill.  The chunk containing
        the prompt's true last token finishes the prefill: its logits
        arm the sampler and the slot joins the decode grid next
        sampling pass (this same step)."""
        sv = self.serve
        chunk = sv.prefill_chunk
        for i, s in enumerate(self.slots):
            if s is None or s.prefill_pos is None:
                continue
            pos = s.prefill_pos
            t0 = len(s.req.prompt)
            # this chunk's pages (first chunk's were allocated at
            # admission); eviction fallback mirrors _grow_pages
            need_pages = (pos + chunk) // sv.page_size
            while len(s.pages) < need_pages:
                got = self._alloc_pages(i, need_pages - len(s.pages))
                if got is not None:
                    s.pages.extend(got)
                    continue
                shard = (self._shard_of(i) if sv.ep_shards > 1
                         else None)
                if not self._evict_youngest(shard):
                    raise RuntimeError("page pool exhausted with no "
                                       "evictable request")
                if self.slots[i] is None:   # we evicted ourselves
                    break
            if self.slots[i] is None:
                continue
            n_ctx_pages = ctx_pages_bucket(
                pos + chunk, sv.page_size, sv.ctx_bucket_pages,
                sv.max_pages_per_slot)
            # the chunk jit addresses the GLOBAL page slab (it runs
            # outside the EP shard_map); scratch fill rows are masked,
            # any valid page id serves
            gpages = self._global_pages(i, s.pages)
            table = np.full((n_ctx_pages,), SCRATCH_PAGE, np.int32)
            table[:len(gpages)] = gpages
            first_pg = pos // sv.page_size
            chunk_ids = gpages[first_pg:need_pages]
            rel_last = min(max(t0 - 1 - pos, 0), chunk - 1)
            toks = s.prefill_toks[pos:pos + chunk]
            if self.tracer is not None:
                # chunks interleave across slots: re-arm attribution so
                # the span lands on THIS slot's request track
                self.tracer.on_prefill_chunk(s.orig.rid)
            with trace_span("serve.prefill_chunk"):
                logits, kp, vp = _prefill_chunk(
                    self.params, self.cfg,
                    self.cache.k_pages, self.cache.v_pages,
                    jnp.asarray(toks)[None, :],
                    jnp.asarray(table),
                    jnp.asarray(chunk_ids, jnp.int32),
                    jnp.int32(pos), jnp.int32(rel_last))
            self.cache = self.cache._replace(k_pages=kp, v_pages=vp)
            s.prefill_pos = pos + chunk
            if pos <= t0 - 1 < pos + chunk:
                # prefill complete — arm the sampler, join decode
                self._logits = self._logits.at[i].set(logits)
                s.prefill_pos = None
                s.prefill_toks = None
                s.length = t0

    def _evict_youngest(self, shard: int | None = None) -> bool:
        """Preempt the most recently admitted request back to the
        queue head; its pages free immediately.  Returns False when no
        active slot remains to evict.  ``shard`` restricts the victim
        set to one page shard (EP-sharded decode: only a same-shard
        eviction can free the pages the caller needs).  A request
        evicted mid-chunked-prefill resumes from scratch — delivered
        tokens are carried in the resumed prompt either way, so the
        resume is bit-equal regardless of how far prefill got."""
        active = self._active()
        if shard is not None:
            active = [i for i in active if self._shard_of(i) == shard]
        if not active:
            return False
        victim = max(active, key=lambda i: (self.slots[i].admit_step,
                                            self.slots[i].req.rid))
        s = self.slots[victim]
        self._free_slot_pages(victim, s.pages)
        delivered = self._delivered(s)
        remaining = s.orig.max_new_tokens - delivered
        # the resumed prompt carries EVERY delivered token (across any
        # number of evictions): the previous resumed prompt plus this
        # incarnation's emissions
        resumed = dataclasses.replace(
            s.req,
            prompt=tuple(s.req.prompt) + tuple(s.emitted),
            max_new_tokens=max(remaining, 1))
        # re-queue at the FRONT: the evictee is the next admission;
        # arrival AND first-token clocks survive (the client already
        # holds the delivered tokens — TTFT/TPOT must not restart)
        self.queue.appendleft(_QueueEntry(
            self.step_idx, resumed, s.orig, s.arrival_s,
            s.first_token_s))
        self.slots[victim] = None
        self.stats["evictions"] += 1
        self._rates["evictions"].add()
        if self.tracer is not None:
            self.tracer.on_evict(s.orig.rid, self.step_idx)
        self.metrics.count("serve.evictions")
        self.metrics.decision(
            "serve.evict", rid=s.orig.rid, step=self.step_idx,
            slot=victim, freed_pages=len(s.pages),
            emitted=delivered)
        return True

    def _delivered(self, s: _Slot) -> int:
        """Tokens delivered across incarnations (an evicted request's
        resumed prompt carries its earlier output)."""
        return len(s.req.prompt) - len(s.orig.prompt) + len(s.emitted)

    def _grow_pages(self, span: int = 0) -> None:
        """Allocate the next page for every active slot whose write
        position crosses its allocated frontier, evicting the youngest
        request when the pool runs dry.  ``span`` extra positions (the
        verify step's drafted span) are pre-covered; the target index
        clamps to the slot's table width — the host truncates drafts to
        fit the context ceiling, and the verify graph routes any
        residual over-the-edge write to the scratch page."""
        shard = (self._shard_of if self.serve.ep_shards > 1
                 else lambda i: None)
        for i in list(self._decoding()):
            s = self.slots[i]
            if s is None:
                continue
            need_idx = min((s.length + span) // self.serve.page_size,
                           self.serve.max_pages_per_slot - 1)
            while need_idx >= len(s.pages):
                got = self._alloc_pages(i, 1)
                if got is not None:
                    s.pages.extend(got)
                    continue
                if not self._evict_youngest(shard(i)):
                    raise RuntimeError("page pool exhausted with no "
                                       "evictable request")
                if self.slots[i] is None:   # we evicted ourselves
                    break

    def _spec_decode(self, active) -> int | None:
        """Speculative decode step: draft, verify the span in one
        forward, emit the drafted prefix the engine's own sampler
        agrees with (ISSUE 20).

        Exactness: the sampler keys every token on
        ``fold_in(PRNGKey(seed), token_index)`` — a TOKEN POSITION, not
        a step — so the canonical sample for drafted position ``t`` is
        computable from the verify span's column ``t-1`` logits with
        that position's own key and the shared
        :func:`_sample_dynamic` numerics.  A draft is emitted iff it
        EQUALS its canonical sample; the emitted stream is therefore
        bit-equal to non-speculative decode for every temperature /
        top-k / top-p arm, and the next step's sample pass (from the
        pending logits column this method selects) produces exactly the
        token a rejected draft was compared against.

        Returns the number of EXTRA tokens emitted (accepted drafts;
        the canonical token was already emitted by the sample pass), or
        ``None`` when no slot drafted anything — the caller then runs
        the plain one-token decode step."""
        sv = self.serve
        spec = self._spec
        k = spec.draft_tokens
        # ---- draft (host-only: per-slot suffix-match tables) ---------
        drafts: dict[int, list] = {}
        with trace_span("serve.draft"):
            for i in active:
                s = self.slots[i]
                hist = list(s.req.prompt) + s.emitted
                if s.draft is None:
                    # deterministic rebuild from prompt + emitted: the
                    # same history the eviction / migration resume
                    # carries, so speculation survives both for free
                    s.draft = DraftState(spec, hist)
                else:
                    s.draft.sync(hist)
                dr = s.draft.draft(k)
                # truncate to the remaining token budget and the
                # context ceiling: every ACCEPTED draft's KV row must
                # land in a real page
                dr = dr[:max(0, s.orig.max_new_tokens
                             - self._delivered(s))]
                dr = dr[:max(0, sv.max_context - 1 - s.length)]
                if dr:
                    drafts[i] = [int(t) for t in dr]
        if not drafts:
            return None

        # pre-cover the span's write positions (may evict — re-fetch)
        self._grow_pages(span=k)
        active = self._decoding()
        if not active:
            return 0

        # ---- verify: score k+1 positions per slot in one forward ----
        t_span = k + 1
        feed = np.full((sv.max_batch, t_span), sv.pad_token, np.int32)
        positions = np.zeros((sv.max_batch,), np.int32)
        tables = np.full((sv.max_batch, sv.max_pages_per_slot),
                         SCRATCH_PAGE, np.int32)
        temps = np.zeros((sv.max_batch, k), np.float32)
        tks = np.zeros((sv.max_batch, k), np.int32)
        tps = np.ones((sv.max_batch, k), np.float32)
        keys = np.zeros((sv.max_batch, k, 2), np.uint32)
        longest = 1
        for i in active:
            s = self.slots[i]
            feed[i, 0] = s.emitted[-1]
            dr = drafts.get(i, ())
            feed[i, 1:1 + len(dr)] = dr
            positions[i] = s.length
            tables[i, :len(s.pages)] = s.pages
            longest = max(longest, s.length + t_span)
            r = s.req
            temps[i] = r.temperature
            tks[i] = r.top_k
            tps[i] = r.top_p
            base = self._delivered(s)   # emitted already holds tok_0
            root = jax.random.PRNGKey(r.seed)
            for t in range(k):
                keys[i, t] = np.asarray(
                    jax.random.fold_in(root, base + t))
        n_ctx = ctx_pages_bucket(longest, sv.page_size,
                                 sv.ctx_bucket_pages,
                                 sv.max_pages_per_slot)
        self.stats["decode_buckets"].add(n_ctx)
        with trace_span("serve.verify"):
            if self._ep_fn is not None:
                if self._ep_verify is None:
                    self._ep_verify = _ep_verify_fn(
                        self.mesh, self.cfg, self.params)
                span_logits, kp, vp = self._ep_verify(
                    self.params, self.cache.k_pages,
                    self.cache.v_pages, jnp.asarray(feed),
                    jnp.asarray(tables[:, :n_ctx]),
                    jnp.asarray(positions))
            else:
                span_logits, kp, vp = _paged_verify_step(
                    self.params, self.cfg, self.cache.k_pages,
                    self.cache.v_pages, jnp.asarray(feed),
                    jnp.asarray(tables[:, :n_ctx]),
                    jnp.asarray(positions))
        self.cache = self.cache._replace(k_pages=kp, v_pages=vp)
        self._spec_steps += 1

        # canonical samples for every drafted position: column t-1
        # logits, position-(base+t-1) key, the same sampler numerics
        cand = np.asarray(_sample_dynamic(
            span_logits[:, :k, :].reshape(sv.max_batch * k, -1),
            jnp.asarray(keys.reshape(sv.max_batch * k, 2)),
            jnp.asarray(temps.reshape(-1)),
            jnp.asarray(tks.reshape(-1)),
            jnp.asarray(tps.reshape(-1)))).reshape(sv.max_batch, k)

        # ---- accept the agreeing prefix; roll back the rest ----------
        n_extra = 0
        accepted_cols = np.zeros((sv.max_batch,), np.int32)
        for i in active:
            s = self.slots[i]
            dr = drafts.get(i, [])
            self._spec_drafted += len(dr)
            s.spec_drafted += len(dr)
            a = 0
            done = False
            for t in range(len(dr)):
                if int(cand[i, t]) != dr[t]:
                    break
                tok = dr[t]
                s.emitted.append(tok)
                a += 1
                n_extra += 1
                done = (tok in s.req.stop_tokens
                        or self._delivered(s) >= s.orig.max_new_tokens)
                if done:
                    break
            self._spec_accepted += a
            s.spec_accepted += a
            accepted_cols[i] = a
            s.length += 1 + a
            # roll back the block table past the accepted frontier:
            # rejected-draft rows free their surplus pages (LIFO, so
            # the next growth re-draws the same ids) and the rows
            # inside kept pages are overwritten by the next span
            # before any causal mask exposes them
            keep = (s.length - 1) // sv.page_size + 1
            if keep < len(s.pages):
                surplus = s.pages[keep:]
                del s.pages[keep:]
                self._free_slot_pages(i, surplus)
            if done:
                self._retire(i, s)
        # pending logits = the column after each slot's last emitted
        # token — exactly what the plain decode step would have
        # returned after feeding that token
        self._logits = span_logits[
            jnp.arange(sv.max_batch), jnp.asarray(accepted_cols)]
        return n_extra

    def set_speculate(self, enabled: bool, *, reason=None) -> None:
        """Morph speculation on/off at a step boundary (the runtime
        controller's actuator).  Off tears down nothing the sampler
        sees: draft tables idle on the slots, the next step simply runs
        the plain decode path — token streams are unchanged by
        construction, so morphing mid-request loses zero tokens."""
        if enabled and self.serve.speculate is None:
            raise ValueError(
                "cannot enable speculation: ServeConfig.speculate was "
                "never configured on this engine")
        was = self._spec is not None
        self._spec = self.serve.speculate if enabled else None
        if (self._spec is not None) != was:
            self.metrics.decision(
                "serve.spec",
                event="morph_on" if enabled else "morph_off",
                step=self.step_idx, reason=reason)

    def spec_snapshot(self) -> dict:
        """Live acceptance stats (the controller's observation feed)."""
        return dict(
            spec_stats_fields(self._spec_drafted, self._spec_accepted,
                              self._spec_steps),
            spec_steps=self._spec_steps,
            spec_on=self._spec is not None)

    def _retire(self, slot: int, s: _Slot) -> None:
        now = self._clock()
        self._free_slot_pages(slot, s.pages)
        self.slots[slot] = None
        out = (list(s.orig.prompt)
               + list(s.req.prompt[len(s.orig.prompt):])
               + list(s.emitted))
        self.outputs[s.orig.rid] = out
        self.stats["completed"] += 1
        n_tok = self._delivered(s)
        ttft_ms = ((s.first_token_s - s.arrival_s) * 1e3
                   if s.first_token_s is not None else None)
        tpot_ms = None
        if s.first_token_s is not None and n_tok > 1:
            tpot_ms = (now - s.first_token_s) * 1e3 / (n_tok - 1)
        # O(1)-memory rolling percentiles for the live /metrics scrape
        # (and summary()) — no per-request list grows under load
        if ttft_ms is not None:
            self.metrics.sketch("serve.ttft_ms", ttft_ms)
        if tpot_ms is not None:
            self.metrics.sketch("serve.tpot_ms", tpot_ms)
        # replica-keyed twins: the fabric's mid-drill scrape reads
        # per-replica latency sketches off the SHARED metrics object
        if self.replica_tag is not None:
            if ttft_ms is not None:
                self.metrics.sketch(
                    f"serve.{self.replica_tag}.ttft_ms", ttft_ms)
            if tpot_ms is not None:
                self.metrics.sketch(
                    f"serve.{self.replica_tag}.tpot_ms", tpot_ms)
        if self.tracer is not None:
            self.tracer.on_retire(s.orig.rid, self.step_idx,
                                  tokens=n_tok, ttft_ms=ttft_ms,
                                  tpot_ms=tpot_ms)
        spec_kw = {}
        if self.serve.speculate is not None:
            spec_kw = {
                "spec_drafted": s.spec_drafted,
                "spec_accepted": s.spec_accepted,
                "accept_rate": (round(s.spec_accepted / s.spec_drafted,
                                      6) if s.spec_drafted else None),
            }
        self.metrics.decision(
            "serve.retire", rid=s.orig.rid, step=self.step_idx,
            slot=slot, tokens=n_tok,
            ttft_ms=round(ttft_ms, 3) if ttft_ms is not None else None,
            tpot_ms=round(tpot_ms, 3) if tpot_ms is not None else None,
            **spec_kw)
        if self.recorder is not None:
            self.recorder.record(
                kind="serve_request", step=self.step_idx,
                rid=s.orig.rid, tokens=n_tok, ttft_ms=ttft_ms,
                tpot_ms=tpot_ms, **spec_kw)
        if self.watchdog is not None:
            dominant = None
            if self.tracer is not None:
                # name the critical-path culprit on any breach this
                # retirement raises (the track is one closing step-span
                # short mid-step — good enough to rank components)
                from flashmoe_tpu.telemetry_plane.attribution import (
                    attribute_track,
                )

                att = attribute_track(
                    self.tracer.request_track(s.orig.rid))
                dominant = att["dominant"]
            self.watchdog.observe_request(
                self.step_idx, s.orig.rid, ttft_ms=ttft_ms,
                tpot_ms=tpot_ms, dominant=dominant)

    # ---- the engine step ---------------------------------------------

    def step(self) -> dict:
        """One engine iteration: admit -> sample/retire -> decode.
        Returns the step's flight record (also appended to the
        recorder when one is attached)."""
        t0_s = self._clock()
        sv = self.serve
        if self.tracer is not None:
            # open the step window BEFORE admissions: everything in
            # this step (a neighbour's prefill compile included) rides
            # a serve.step span on each active request's track
            self.tracer.begin_step(
                self.step_idx,
                [self.slots[i].orig.rid for i in self._active()])
        self._mark_arrivals()
        self._admit()
        if self._heartbeat is not None:
            self._heartbeat("admit")
        self._advance_prefill()
        if self._heartbeat is not None:
            self._heartbeat("prefill")

        # sample each decoding slot's next token from its pending
        # logits (slots mid-chunked-prefill have none yet)
        emitted_now = 0
        active = self._decoding()
        if active:
            temps = np.zeros((sv.max_batch,), np.float32)
            tks = np.zeros((sv.max_batch,), np.int32)
            tps = np.ones((sv.max_batch,), np.float32)
            keys = np.zeros((sv.max_batch, 2), np.uint32)
            for i in active:
                r = self.slots[i].req
                temps[i] = r.temperature
                tks[i] = r.top_k
                tps[i] = r.top_p
                keys[i] = np.asarray(jax.random.fold_in(
                    jax.random.PRNGKey(r.seed),
                    self._delivered(self.slots[i])))
            toks = np.asarray(_sample_dynamic(
                self._logits, jnp.asarray(keys),
                jnp.asarray(temps), jnp.asarray(tks), jnp.asarray(tps)))
            now = self._clock()
            for i in active:
                s = self.slots[i]
                tok = int(toks[i])
                s.emitted.append(tok)
                emitted_now += 1
                if s.first_token_s is None:
                    s.first_token_s = now
                done = (tok in s.req.stop_tokens
                        or self._delivered(s) >= s.orig.max_new_tokens)
                if done:
                    self._retire(i, s)
        self.stats["tokens"] += emitted_now
        if self._heartbeat is not None:
            self._heartbeat("sample")

        # feed the survivors one decode step — speculative (draft +
        # span verify, possibly emitting extra tokens) when armed and
        # anything drafted, else the plain one-token step
        active = self._decoding()
        if active:
            self._grow_pages()
            active = self._decoding()
        n_extra = None
        if active and self._spec is not None:
            n_extra = self._spec_decode(active)
            if n_extra is not None:
                emitted_now += n_extra
                self.stats["tokens"] += n_extra
        if active and n_extra is None:
            feed = np.full((sv.max_batch,), sv.pad_token, np.int32)
            positions = np.zeros((sv.max_batch,), np.int32)
            tables = np.full((sv.max_batch, sv.max_pages_per_slot),
                             SCRATCH_PAGE, np.int32)
            longest = 1
            for i in active:
                s = self.slots[i]
                feed[i] = s.emitted[-1]
                positions[i] = s.length
                tables[i, :len(s.pages)] = s.pages
                longest = max(longest, s.length + 1)
            n_ctx = ctx_pages_bucket(longest, sv.page_size,
                                     sv.ctx_bucket_pages,
                                     sv.max_pages_per_slot)
            self.stats["decode_buckets"].add(n_ctx)
            with trace_span("serve.decode"):
                if self._ep_fn is not None:
                    logits, kp, vp = self._ep_fn(
                        self.params, self.cache.k_pages,
                        self.cache.v_pages, jnp.asarray(feed),
                        jnp.asarray(tables[:, :n_ctx]),
                        jnp.asarray(positions))
                else:
                    logits, kp, vp = _paged_decode_step(
                        self.params, self.cfg, self.cache.k_pages,
                        self.cache.v_pages, jnp.asarray(feed),
                        jnp.asarray(tables[:, :n_ctx]),
                        jnp.asarray(positions))
            self._logits = logits
            self.cache = self.cache._replace(k_pages=kp, v_pages=vp)
            for i in active:
                self.slots[i].length += 1
        if self._heartbeat is not None:
            self._heartbeat("decode")

        # telemetry
        if self._vclock is not None:
            # charge the decode tick INSIDE the step window (before
            # end_step closes it): virtual step duration becomes
            # max(tick, handoff time), so transfers overlap the tick
            # and request tracks stay contiguous in virtual time
            self._vclock.complete_step()
        if self.tracer is not None:
            self.tracer.end_step()
        step_ms = (self._clock() - t0_s) * 1e3
        n_active = len(self._active())
        qd = len(self.queue)
        occ = self.pool.occupancy
        self.stats["steps"] += 1
        self.stats["max_queue_depth"] = max(self.stats["max_queue_depth"],
                                            qd)
        self.stats["max_active"] = max(self.stats["max_active"], n_active)
        self.stats["peak_occupancy"] = max(self.stats["peak_occupancy"],
                                           occ)
        self.metrics.gauge("serve.queue_depth", qd)
        self.metrics.gauge("serve.active_requests", n_active)
        self.metrics.gauge("serve.cache_occupancy", occ)
        # rolling distributions + windowed rates for the live scrape
        self.metrics.sketch("serve.step_ms", step_ms)
        self.metrics.sketch("serve.queue_depth_dist", qd)
        self.metrics.gauge("serve.tokens_per_s",
                           self._rates["tokens"].add(emitted_now))
        self.metrics.gauge("serve.admits_per_s",
                           self._rates["admits"].rate())
        self.metrics.gauge("serve.evictions_per_s",
                           self._rates["evictions"].rate())
        rec = {
            "kind": "serve_step", "step": self.step_idx,
            "active": n_active, "queue_depth": qd,
            "pages_used": self.pool.used_pages,
            "cache_occupancy": round(occ, 4),
            "tokens": emitted_now,
            "completed": self.stats["completed"],
            "step_ms": round(step_ms, 3),
        }
        if self.serve.speculate is not None:
            rec["spec_tokens"] = int(n_extra or 0)
            rec["spec_on"] = self._spec is not None
        if self.recorder is not None:
            self.recorder.record(**rec)
        if self.watchdog is not None:
            self.watchdog.observe_step(self.step_idx, step_ms)
        self.step_idx += 1
        if self._heartbeat is not None:
            self._heartbeat("end")
        return rec

    # ---- drivers -----------------------------------------------------

    def pending(self) -> bool:
        return bool(self.queue) or bool(self._active())

    def run(self, requests=None, arrivals=None, *, until=None) -> dict:
        """Drive to completion.  ``requests``: iterable of
        :class:`Request`; ``arrivals``: matching arrival steps (default
        all 0 — the seeded arrival trace of a drill).  ``until``: an
        optional zero-arg predicate that PAUSES the drive early when it
        turns true (the live-plane mid-drill scrape; call ``run()``
        again to finish) — the max_steps wedge guard applies either
        way.  Returns {rid: full token list (prompt + generated)}."""
        for idx, req in enumerate(requests or ()):
            self.submit(req, int(arrivals[idx]) if arrivals else 0)
        while self.pending() and not (until is not None and until()):
            if self.step_idx >= self.serve.max_steps:
                raise RuntimeError(
                    f"engine exceeded max_steps={self.serve.max_steps} "
                    f"with work pending")
            self.step()
        return dict(self.outputs)

    def summary(self) -> dict:
        s = dict(self.stats)
        s["decode_buckets"] = sorted(s["decode_buckets"])
        s["prefill_buckets"] = sorted(s["prefill_buckets"])
        # O(1)-memory: the retire-time sketches, not a decision scan
        # (the decision list grows without bound under sustained load)
        tt = self.metrics.sketches.get("serve.ttft_ms")
        if tt is not None and tt.n:
            s["ttft_ms_mean"] = round(tt.mean, 3)
            s["ttft_ms_max"] = round(tt.max, 3)
            s["ttft_ms_p99"] = round(tt.quantile(0.99), 3)
        tp = self.metrics.sketches.get("serve.tpot_ms")
        if tp is not None and tp.n:
            s["tpot_ms_mean"] = round(tp.mean, 3)
        if self.serve.speculate is not None:
            s.update(self.spec_snapshot())
        s["decode_plan"] = list(self.decode_plan)
        s["prefill_plan"] = list(self.prefill_plan)
        if self.quant_info is not None:
            s["expert_quant"] = self.quant_info["expert_quant"]
            s["quant_freed_mb"] = round(
                self.quant_info["freed_bytes"] / 2**20, 3)
            s["quant_extra_kv_pages"] = self.quant_info["extra_kv_pages"]
        return s
