"""Paged ragged KV cache: block-table indirection over a fixed page
pool.

``generate.py``'s dense monolith allocates ``[L, B, N_kv, T_max, D]``
up front — every request pays the longest request's context, and a
retiring request's memory cannot be reused without reshaping the whole
cache (a recompile).  This module replaces it with the serving-standard
paged layout (vLLM-style, built on the same row-major "static shapes,
dynamic indices" machinery as :mod:`flashmoe_tpu.ops.ragged`):

* the device holds one fixed pool ``[L, P, N_kv, page, D]`` of KV
  pages (:class:`PagedKVCache`) — its shape never changes, so joining
  and retiring requests never force a recompile;
* each request owns a list of page ids (the *block table*); position
  ``t`` of a request lives in page ``table[t // page]``, row
  ``t % page`` — pure integer indirection, gathered/scattered with
  static shapes and dynamic indices;
* a host-side free-list allocator (:class:`PagePool`) hands pages out
  and takes them back on retirement/eviction — LIFO, so page reuse is
  deterministic and a drill replays bit-identically;
* attention reads a *bucketed* number of pages
  (:func:`ctx_pages_bucket`): the gather length is rounded up to a
  page-bucket granularity, so the decode step jit-compiles once per
  bucket instead of once per context length.

Page 0 is the **scratch page** (:data:`SCRATCH_PAGE`): never allocated,
it absorbs the KV writes of inactive batch slots (their block tables
point every entry at it) and backs the out-of-range block-table entries
of active requests — which the per-request length mask guarantees are
read back with exactly-zero attention weight.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from flashmoe_tpu.config import MoEConfig

#: page id reserved as the write target of inactive slots and the
#: backing of unallocated block-table entries — never handed out by
#: :class:`PagePool`, never read back with non-zero attention weight.
SCRATCH_PAGE = 0


class PagedKVCache(NamedTuple):
    """The device-side page pool.  ``k_pages`` / ``v_pages``:
    ``[L, P, N_kv, page, D]``.  Block tables and lengths live on the
    host (the engine's slot state) and ride into each jitted step as
    ordinary array arguments — values change, shapes never do."""

    k_pages: jax.Array
    v_pages: jax.Array

    @property
    def num_pages(self) -> int:
        return self.k_pages.shape[1]

    @property
    def page_size(self) -> int:
        return self.k_pages.shape[3]


def init_paged_cache(cfg: MoEConfig, num_pages: int,
                     page_size: int) -> PagedKVCache:
    """Allocate the pool.  ``num_pages`` includes the scratch page."""
    if num_pages < 2:
        raise ValueError(f"num_pages={num_pages} must be >= 2 (page 0 "
                         f"is the reserved scratch page)")
    if page_size < 1:
        raise ValueError(f"page_size={page_size} must be >= 1")
    nkv, dh = cfg.resolved_num_kv_heads, cfg.resolved_head_dim
    shape = (cfg.num_layers, num_pages, nkv, page_size, dh)
    return PagedKVCache(jnp.zeros(shape, cfg.dtype),
                        jnp.zeros(shape, cfg.dtype))


# ----------------------------------------------------------------------
# In-graph page ops (called inside the engine's jitted step)
# ----------------------------------------------------------------------

def store_token(pages, token_kv, page_ids, rows):
    """Scatter one decode step's per-slot K (or V) into its pages.

    pages: ``[P, N_kv, page, D]`` (one layer's pool); token_kv:
    ``[B, N_kv, D]``; page_ids/rows: ``[B]`` int32 (inactive slots pass
    ``SCRATCH_PAGE`` / 0 — duplicate scratch writes race, but scratch
    content is never read back with non-zero weight)."""
    return pages.at[page_ids, :, rows, :].set(token_kv)


def store_tokens(pages, span_kv, page_ids, rows):
    """Scatter a verify step's drafted SPAN into its pages — the
    multi-position twin of :func:`store_token` (ISSUE 20 speculative
    decode).

    pages: ``[P, N_kv, page, D]`` (one layer's pool); span_kv:
    ``[B, T, N_kv, D]`` for a ``T = draft_tokens + 1`` wide span;
    page_ids/rows: ``[B, T]`` int32.  The advanced indices at axes 0
    and 2 are split by the head-axis slice, so numpy semantics front
    the broadcast ``[B, T]`` dims — the result aligns with ``span_kv``
    exactly.  Out-of-span and inactive positions pass ``SCRATCH_PAGE``;
    rejected-draft rows land in pages the engine rolls back (or rows a
    later step overwrites before any causal mask exposes them — the
    same invariant prefill pad rows rely on)."""
    return pages.at[page_ids, :, rows, :].set(span_kv)


def gather_ctx(pages, block_tables):
    """Gather each slot's context window from its pages.

    pages: ``[P, N_kv, page, D]``; block_tables: ``[B, n]`` page ids
    (already sliced to the bucketed page count).  Returns
    ``[B, N_kv, n * page, D]`` — rows past a request's length are
    scratch/garbage and MUST be masked by the caller's length mask."""
    b, n = block_tables.shape
    g = pages[block_tables]                    # [B, n, N_kv, page, D]
    _, _, nkv, page, d = g.shape
    return g.transpose(0, 2, 1, 3, 4).reshape(b, nkv, n * page, d)


def store_prefill(pages, seq_kv, page_ids):
    """Scatter a prefilled dense K (or V) run into freshly-allocated
    pages, all layers at once.

    pages: ``[L, P, N_kv, page, D]``; seq_kv: ``[L, N_kv, T_pad, D]``
    with ``T_pad = len(page_ids) * page``; page_ids: ``[n]`` int32.
    Positions past the true prompt length write garbage rows the
    length mask never exposes."""
    l, nkv, t_pad, d = seq_kv.shape
    n = page_ids.shape[0]
    page = pages.shape[3]
    if t_pad != n * page:
        raise ValueError(f"prefill run of {t_pad} rows does not fill "
                         f"{n} pages of {page}")
    # [L, N_kv, n, page, D] -> [L, n, N_kv, page, D]
    chunks = seq_kv.reshape(l, nkv, n, page, d).transpose(0, 2, 1, 3, 4)
    return pages.at[:, page_ids].set(chunks)


# ----------------------------------------------------------------------
# Bucketed-length jit policy
# ----------------------------------------------------------------------

def ctx_pages_bucket(max_tokens: int, page_size: int, bucket_pages: int,
                     max_pages: int) -> int:
    """The (static) number of pages the decode step gathers for a batch
    whose longest request spans ``max_tokens`` written positions:
    rounded up to ``bucket_pages`` granularity so a request joining
    with a slightly longer context reuses the previous compilation —
    the bucketed-length jit policy.  Clamped to ``max_pages``."""
    if max_tokens < 1:
        max_tokens = 1
    pages = -(-max_tokens // page_size)
    pages = -(-pages // bucket_pages) * bucket_pages
    return min(max(pages, bucket_pages), max_pages)


def prompt_pad(t0: int, bucket: int) -> int:
    """Prompt length padded to the prefill bucket (one compilation per
    padded length, not per prompt length)."""
    return -(-max(t0, 1) // bucket) * bucket


# ----------------------------------------------------------------------
# Host-side page allocator
# ----------------------------------------------------------------------

class PagePool:
    """Deterministic LIFO free-list over pages ``1..num_pages-1``
    (page 0 is scratch).  All host-side Python: allocation order is a
    pure function of the alloc/free call sequence, which the engine
    derives from its seeded arrival trace — so a drill's page
    placement (and therefore its jitted gathers) replays exactly."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(f"num_pages={num_pages} must be >= 2")
        self.num_pages = num_pages
        # LIFO: lowest ids on top first, and freed pages come back on
        # top — eviction's pages are the next admission's pages
        self._free = list(range(num_pages - 1, 0, -1))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    @property
    def occupancy(self) -> float:
        """Allocated fraction of the allocatable pool (scratch page
        excluded) — the cache-occupancy gauge the engine reports."""
        total = self.num_pages - 1
        return self.used_pages / total if total else 0.0

    def alloc(self, n: int) -> list[int] | None:
        """Pop ``n`` pages, or ``None`` (no partial allocation) when
        fewer remain — the caller then defers admission or evicts."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        return out

    def free(self, pages) -> None:
        """Return pages to the pool (reverse order, so re-allocating
        the same count yields the same ids the evictee held)."""
        for p in reversed(list(pages)):
            if not 0 < p < self.num_pages:
                raise ValueError(f"page id {p} out of range")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
            self._free.append(p)


class ShardedPagePool:
    """The EP-sharded twin of :class:`PagePool`: the page slab is
    partitioned into ``shards`` equal contiguous blocks (matching the
    ``P(None, "ep")`` device partitioning of the cache arrays), each
    with its OWN deterministic LIFO free list over shard-LOCAL ids.

    Ids handed out are local — exactly what the EP decode step's
    per-shard block tables index; each shard's local page 0 is its own
    scratch (so every device's slab has a scratch at the same local
    offset).  :meth:`to_global` maps to slab-global ids for the eager
    whole-page writes (prefill store) that address the unpartitioned
    array view."""

    def __init__(self, num_pages: int, shards: int):
        if shards < 1:
            raise ValueError(f"shards={shards} must be >= 1")
        if num_pages % shards:
            raise ValueError(f"num_pages={num_pages} must divide "
                             f"evenly across {shards} shards")
        self.num_pages = num_pages
        self.shards = shards
        self.pages_per_shard = num_pages // shards
        if self.pages_per_shard < 2:
            raise ValueError(
                f"num_pages={num_pages} leaves fewer than 2 pages per "
                f"shard across {shards} shards (each shard reserves "
                f"its own scratch page)")
        self._pools = [PagePool(self.pages_per_shard)
                       for _ in range(shards)]

    @property
    def free_pages(self) -> int:
        return sum(p.free_pages for p in self._pools)

    @property
    def used_pages(self) -> int:
        return sum(p.used_pages for p in self._pools)

    @property
    def occupancy(self) -> float:
        total = self.num_pages - self.shards   # one scratch per shard
        return self.used_pages / total if total else 0.0

    def shard_free_pages(self, shard: int) -> int:
        return self._pools[shard].free_pages

    def alloc(self, n: int, shard: int) -> list[int] | None:
        """Pop ``n`` shard-LOCAL page ids from ``shard``'s free list
        (``None`` on shortfall — no partial allocation, no cross-shard
        spill: a slot's pages must live on its shard's device)."""
        return self._pools[shard].alloc(n)

    def free(self, pages, shard: int) -> None:
        self._pools[shard].free(pages)

    def to_global(self, pages, shard: int) -> list[int]:
        """Shard-local -> slab-global ids (the eager whole-page write
        sites address the global array view)."""
        base = shard * self.pages_per_shard
        return [base + int(p) for p in pages]
