"""Seeded load generation + the offered-load sweep behind
``bench.py --serve``.

Offered load is expressed as the arrival gap of the seeded trace
(requests arrive in pairs every ``arrival_every`` engine steps —
smaller gap = higher load).  Each sweep point drives a fresh engine on
a CPU-sized model and emits one bench record: throughput
(tokens/sec), TTFT/TPOT percentiles, queue depth, cache occupancy,
evictions — the latency/throughput curve a capacity plan reads off.
"""

from __future__ import annotations

from flashmoe_tpu.config import MoEConfig


def tiny_config(*, hidden: int = 64, experts: int = 4, layers: int = 2,
                vocab: int = 256) -> MoEConfig:
    """The CPU-sized serving drill model (dropless — the engine's
    requirement)."""
    import jax.numpy as jnp

    return MoEConfig(
        num_experts=experts, expert_top_k=min(2, experts),
        hidden_size=hidden, intermediate_size=2 * hidden,
        sequence_len=128, num_layers=layers, moe_frequency=2,
        vocab_size=vocab, num_heads=2, drop_tokens=False,
        dtype=jnp.float32, param_dtype=jnp.float32)


def build_requests(n: int, *, vocab: int, prompt_len: int,
                   max_new: int, seed: int, arrival_every: int,
                   temperature: float = 0.0,
                   repetitive: bool = False):
    """The seeded trace: ``n`` requests with deterministic prompts and
    staggered arrivals (one PAIR of arrivals every ``arrival_every``
    engine steps).  ``repetitive`` tiles each prompt from a per-request
    random bigram motif instead of i.i.d. tokens — the speculative
    sweep's trace, where the n-gram drafter has suffix matches to
    propose from (an i.i.d. prompt never drafts, which would bench the
    no-op path)."""
    import jax

    from flashmoe_tpu.serving.engine import Request

    if repetitive:
        motif = jax.random.randint(
            jax.random.PRNGKey(seed), (n, 2), 0, vocab)
        reps = -(-prompt_len // 2)
        toks = [([int(t) for t in motif[i]] * reps)[:prompt_len]
                for i in range(n)]
    else:
        toks = jax.random.randint(
            jax.random.PRNGKey(seed), (n, prompt_len), 0, vocab)
    reqs = [Request(rid=i, prompt=tuple(int(t) for t in toks[i]),
                    max_new_tokens=max_new, temperature=temperature,
                    seed=seed + i)
            for i in range(n)]
    arrivals = [(i // 2) * arrival_every for i in range(n)]
    return reqs, arrivals


def pctl(values, q: float):
    """Nearest-rank percentile (None on empty) — THE serving
    percentile: `bench.py --serve` records and the `observe --serving`
    report both use this one definition, so the two surfaces can never
    disagree about what p99 means."""
    if not values:
        return None
    v = sorted(values)
    return round(v[min(len(v) - 1, int(q * len(v)))], 3)


def serve_load_sweep(loads, *, n_requests: int = 8, max_batch: int = 4,
                     prompt_len: int = 8, max_new: int = 6,
                     seed: int = 0, page_size: int = 8,
                     num_pages: int = 64,
                     telemetry_port: int | None = None,
                     speculate: int | None = None) -> list[dict]:
    """One bench record per offered-load point (``loads``: arrival
    gaps in engine steps, descending = rising load).  ``vs_baseline``
    is each point's throughput relative to the LIGHTEST load measured
    — the saturation curve.  Deterministic token streams per seed;
    latency numbers are wall-clock.

    ``telemetry_port`` (``bench.py --serve --telemetry-port N``): one
    scrape server spans the whole sweep, resolving to the CURRENT
    point's metrics stream; each record then carries a mid-sweep
    ``/metrics`` self-scrape (``telemetry_scrape``: exposition size,
    whether the TTFT/TPOT summary quantiles were present and the text
    parsed) — the live plane drilled by the same contract tests as the
    rest of the bench surface.

    ``speculate`` (``bench.py --serve --speculate``, ISSUE 20): arm
    speculative decoding at ``draft_tokens=speculate`` over a
    repetitive trace and run an EQUAL-SLO baseline per point — the
    same requests at the same offered load with speculation off — so
    each record carries its own TPOT comparison
    (``baseline_tpot_ms_p50/p99``), the realized ``accept_rate`` /
    ``spec_tokens_per_step``, and ``bit_equal_to_baseline`` (the
    exactness guarantee, asserted per point, not trusted).  The metric
    identity gains a ``spec=kN`` tag: a speculative run's numbers must
    never baseline a plain run's in the sentry."""
    import time

    import jax

    from flashmoe_tpu.models.transformer import init_params
    from flashmoe_tpu.serving.engine import ServeConfig, ServingEngine
    from flashmoe_tpu.utils.telemetry import Metrics

    cfg = tiny_config()
    params = init_params(jax.random.PRNGKey(seed), cfg)
    serve = ServeConfig(
        max_batch=max_batch, page_size=page_size, num_pages=num_pages,
        max_pages_per_slot=max(
            2, -(-(prompt_len + max_new) // page_size) + 1),
        ctx_bucket_pages=1, prompt_bucket=page_size)
    if speculate:
        import dataclasses

        from flashmoe_tpu.serving.speculate import SpecConfig

        serve = dataclasses.replace(
            serve, speculate=SpecConfig(draft_tokens=int(speculate)))
    holder = [Metrics()]
    server = None
    if telemetry_port is not None:
        from flashmoe_tpu.telemetry_plane.server import maybe_server

        server = maybe_server(telemetry_port,
                              metrics_fn=lambda: holder[0])
    try:
        records = _sweep_points(loads, params, cfg, serve, holder,
                                server, n_requests=n_requests,
                                max_batch=max_batch,
                                prompt_len=prompt_len, max_new=max_new,
                                seed=seed)
    finally:
        if server is not None:
            server.stop()
    return records


def _scrape_metrics(server) -> dict:
    """The mid-sweep self-scrape: fetch ``/metrics`` off the live
    server and report whether it parsed and carried the serving
    summary quantiles."""
    from flashmoe_tpu.telemetry_plane.server import scrape

    try:
        body, ctype = scrape(f"{server.url}/metrics")
    except Exception as e:  # noqa: BLE001 — the record survives
        return {"ok": False, "error": f"{type(e).__name__}: "
                                      f"{str(e)[:120]}"}
    return {
        "ok": True,
        "bytes": len(body),
        "content_type": ctype,
        "has_ttft_quantiles":
            'flashmoe_serve_ttft_ms{quantile="' in body,
        "has_tpot_quantiles":
            'flashmoe_serve_tpot_ms{quantile="' in body,
    }


def _sweep_points(loads, params, cfg, serve, holder, server, *,
                  n_requests, max_batch, prompt_len, max_new, seed):
    import time

    from flashmoe_tpu.serving.engine import ServingEngine
    from flashmoe_tpu.utils.telemetry import Metrics

    import jax

    records = []
    base_tps = None
    spec = serve.speculate
    for every in loads:
        if every < 1:
            raise ValueError(f"offered-load gap {every} must be >= 1 "
                             f"engine step")
        reqs, arrivals = build_requests(
            n_requests, vocab=cfg.vocab_size, prompt_len=prompt_len,
            max_new=max_new, seed=seed, arrival_every=int(every),
            repetitive=spec is not None)
        spec_rec = None
        if spec is not None:
            # equal-SLO baseline: the SAME trace at the SAME offered
            # load with speculation off — the comparison each record
            # carries, and the oracle the exactness assert checks
            # against
            import dataclasses as _dc

            bmx = Metrics()
            b_eng = ServingEngine(
                params, cfg, _dc.replace(serve, speculate=None),
                metrics_obj=bmx)
            b_eng.run(list(reqs), list(arrivals))
            b_ret = [d for d in bmx.decisions
                     if d.get("decision") == "serve.retire"]
            spec_rec = {
                "baseline_outputs": dict(b_eng.outputs),
                "baseline_tpot_ms_p50": pctl(
                    [d["tpot_ms"] for d in b_ret
                     if d.get("tpot_ms") is not None], 0.5),
                "baseline_tpot_ms_p99": pctl(
                    [d["tpot_ms"] for d in b_ret
                     if d.get("tpot_ms") is not None], 0.99),
            }
        mx = Metrics()   # private stream per point: clean retire stats
        holder[0] = mx   # the live server scrapes THIS point now
        engine = ServingEngine(params, cfg, serve, metrics_obj=mx)
        t0 = time.monotonic()
        scrape_rec = None
        scrape_pause_s = 0.0
        if server is not None:
            # drive until the first retirement seeds the TTFT/TPOT
            # sketches, scrape MID-DRILL (work still in flight), then
            # run to completion — the live-plane acceptance: the
            # scrape must carry the serving summary quantiles.  Both
            # legs go through engine.run() (its max_steps wedge guard
            # applies: a starved queue fails fast, never spins).  The
            # scrape pause is EXCLUDED from the timed window so the
            # throughput number stays comparable with a plain sweep —
            # and the record's identity key is still tagged
            # ``telemetry`` below, so the sentry never baselines an
            # armed run against an unarmed one.
            engine.run(reqs, arrivals,
                       until=lambda: "serve.ttft_ms" in mx.sketches)
            t_pause = time.monotonic()
            scrape_rec = _scrape_metrics(server)
            scrape_pause_s = time.monotonic() - t_pause
            engine.run()
        else:
            engine.run(reqs, arrivals)
        wall_s = max(time.monotonic() - t0 - scrape_pause_s, 1e-9)
        s = engine.summary()
        tps = s["tokens"] / wall_s
        base_tps = base_tps if base_tps is not None else tps
        retires = [d for d in mx.decisions
                   if d.get("decision") == "serve.retire"]
        ttfts = [d["ttft_ms"] for d in retires
                 if d.get("ttft_ms") is not None]
        tpots = [d["tpot_ms"] for d in retires
                 if d.get("tpot_ms") is not None]
        # telemetry arming rides the measurement identity: an armed
        # run's numbers never baseline an unarmed run's in the sentry
        tag = ",telemetry" if server is not None else ""
        if spec is not None:
            tag += f",spec=k{spec.draft_tokens}"
        records.append({
            "metric": f"serve_load[every={every},B={max_batch},"
                      f"req={n_requests}{tag}]",
            "value": round(tps, 1),
            "unit": "tokens_per_sec",
            "vs_baseline": round(tps / base_tps, 3) if base_tps
            else None,
            "offered_every_steps": int(every),
            "completed": s["completed"],
            "tokens": s["tokens"],
            "steps": s["steps"],
            "ttft_ms_p50": pctl(ttfts, 0.5),
            "ttft_ms_p99": pctl(ttfts, 0.99),
            "tpot_ms_p50": pctl(tpots, 0.5),
            "tpot_ms_p99": pctl(tpots, 0.99),
            "queue_depth_max": s["max_queue_depth"],
            "cache_occupancy_peak": round(s["peak_occupancy"], 4),
            "evictions": s["evictions"],
            "decode_plan": s["decode_plan"],
            "backend": jax.default_backend(),
        })
        if scrape_rec is not None:
            records[-1]["telemetry_scrape"] = scrape_rec
            records[-1]["telemetry_port"] = server.port
        if spec_rec is not None:
            snap = engine.spec_snapshot()
            bit_equal = dict(engine.outputs) \
                == spec_rec.pop("baseline_outputs")
            records[-1].update(spec_rec)
            records[-1].update({
                "accept_rate": snap["accept_rate"],
                "spec_tokens_per_step": snap["spec_tokens_per_step"],
                "spec_drafted": snap["spec_drafted"],
                "spec_accepted": snap["spec_accepted"],
                "bit_equal_to_baseline": bit_equal,
            })
            if not bit_equal:
                # exactness is the whole contract — a diverged stream
                # is a broken run, not a data point
                raise AssertionError(
                    f"speculative decode diverged from baseline at "
                    f"load point every={every}")
    return records


def split_requests(n: int, *, replicas: int, vocab: int,
                   prompt_len: int, max_new: int, seed: int,
                   arrival_every: int, temperature: float = 0.0):
    """Deterministic per-replica trace split: replica ``r``'s trace is
    seeded with ``fold_in(PRNGKey(seed), r)``, so N independent drill
    processes (one per replica) generate disjoint, reproducible loads
    with no coordination — and their obs artifacts merge cleanly
    (``observe --merge``) because rids are globally unique
    (``rid * replicas + r``).  Returns ``[(requests, arrivals), ...]``,
    one pair per replica; requests total ``n`` (the remainder spreads
    over the lowest replica ids)."""
    import dataclasses

    import jax

    if replicas < 1:
        raise ValueError(f"replicas={replicas} must be >= 1")
    out = []
    for r in range(replicas):
        count = n // replicas + (1 if r < n % replicas else 0)
        sub = int(jax.random.fold_in(
            jax.random.PRNGKey(seed), r)[0]) % (2**31 - 1)
        reqs, arrivals = build_requests(
            count, vocab=vocab, prompt_len=prompt_len, max_new=max_new,
            seed=sub, arrival_every=arrival_every,
            temperature=temperature)
        reqs = [dataclasses.replace(q, rid=q.rid * replicas + r)
                for q in reqs]
        out.append((reqs, arrivals))
    return out


def merge_traces(splits):
    """Merge per-replica traces back into one arrival-ordered stream
    (ties break on rid — deterministic): what a single fabric front
    door submits when the split generated the load."""
    merged = []
    for reqs, arrivals in splits:
        merged.extend(zip(arrivals, reqs))
    merged.sort(key=lambda p: (p[0], p[1].rid))
    return [q for _, q in merged], [a for a, _ in merged]


def fabric_load_sweep(loads, *, replica_counts=(1, 2, 4),
                      n_requests: int = 8, max_batch: int = 4,
                      prompt_len: int = 8, max_new: int = 6,
                      seed: int = 0, page_size: int = 8,
                      num_pages: int = 64,
                      telemetry_port: int | None = None,
                      vclock: bool = False,
                      wire: str = "inproc") -> list[dict]:
    """The ``bench.py --fabric`` sweep: one record per (replica count,
    offered-load point), each driving a fresh
    :class:`~flashmoe_tpu.fabric.engine.ServingFabric` on the mocked
    ``FLASHMOE_MOCK_FABRIC`` blocking (set per point, restored on
    exit) with the :func:`split_requests` trace for that width.  Each
    record carries throughput, TTFT/TPOT percentiles, handoff count
    and modeled DCN cost, and the router's placement histogram;
    ``vs_baseline`` is relative to the same replica count's lightest
    load (the per-width saturation curve) and ``vs_single`` to the
    1-replica fabric at the same load (the scale-out curve).

    ``telemetry_port`` arms one scrape server for the whole sweep and
    self-scrapes ``/metrics`` mid-drill into each record — the fabric
    acceptance's live-plane leg.

    ``vclock`` (``bench.py --fabric --vclock``): each point steps on a
    :class:`~flashmoe_tpu.fabric.vclock.VirtualClock` behind a
    :class:`~flashmoe_tpu.fabric.frontdoor.FrontDoor` — requests come
    from :func:`build_requests` directly (the front door owns the
    trace namespace; no per-replica pre-split), the TTFT/TPOT
    percentiles are MEASURED UNDER the modeled DCN delay, and each
    record adds the measured-vs-priced handoff fields plus the
    per-request attribution rollup.  The record identity gains a
    ``vclock`` tag so the perf sentry never baselines virtual-time
    latencies against wall-clock ones.

    ``wire`` (``bench.py --fabric --wire tcp``): every KV handoff
    crosses a REAL localhost socket through a CRC-verifying
    :class:`~flashmoe_tpu.fabric.transport.HandoffTransport` instead
    of the in-process wire.  Tokens stay bit-identical (the wire is a
    byte codec); the record identity gains a ``wire=tcp`` tag so the
    sentry baselines socket and in-process throughput separately."""
    import os
    import time

    import jax

    from flashmoe_tpu.fabric.engine import ServingFabric
    from flashmoe_tpu.fabric.topo import ENV_MOCK_FABRIC
    from flashmoe_tpu.fabric.transport import WIRE_MODES
    from flashmoe_tpu.models.transformer import init_params
    from flashmoe_tpu.serving.engine import ServeConfig
    from flashmoe_tpu.utils.telemetry import Metrics

    if wire not in WIRE_MODES:
        raise ValueError(f"wire {wire!r} not in {WIRE_MODES}")
    cfg = tiny_config()
    params = init_params(jax.random.PRNGKey(seed), cfg)
    serve = ServeConfig(
        max_batch=max_batch, page_size=page_size, num_pages=num_pages,
        max_pages_per_slot=max(
            2, -(-(prompt_len + max_new) // page_size) + 1),
        ctx_bucket_pages=1, prompt_bucket=page_size)
    holder = [Metrics()]
    server = None
    if telemetry_port is not None:
        from flashmoe_tpu.telemetry_plane.server import maybe_server

        server = maybe_server(telemetry_port,
                              metrics_fn=lambda: holder[0])
    records = []
    single_tps: dict = {}       # every -> 1-replica tokens/sec
    saved = os.environ.get(ENV_MOCK_FABRIC)
    try:
        for k in replica_counts:
            if k < 1:
                raise ValueError(f"replica count {k} must be >= 1")
            os.environ[ENV_MOCK_FABRIC] = str(int(k))
            base_tps = None
            for every in loads:
                if every < 1:
                    raise ValueError(f"offered-load gap {every} must "
                                     f"be >= 1 engine step")
                if vclock:
                    # the front door owns the namespace: ONE global
                    # trace, no per-replica pre-split of rids/seeds
                    reqs, arrivals = build_requests(
                        n_requests, vocab=cfg.vocab_size,
                        prompt_len=prompt_len, max_new=max_new,
                        seed=seed, arrival_every=int(every))
                else:
                    reqs, arrivals = merge_traces(split_requests(
                        n_requests, replicas=int(k),
                        vocab=cfg.vocab_size, prompt_len=prompt_len,
                        max_new=max_new, seed=seed,
                        arrival_every=int(every)))
                mx = Metrics()
                holder[0] = mx
                vc = door = None
                if vclock:
                    from flashmoe_tpu.fabric.frontdoor import FrontDoor
                    from flashmoe_tpu.fabric.vclock import VirtualClock

                    vc = VirtualClock()
                transport = None
                if wire == "tcp":
                    from flashmoe_tpu.fabric.transport import (
                        HandoffTransport,
                    )

                    transport = HandoffTransport(metrics_obj=mx,
                                                 wire="tcp")
                fab = ServingFabric(params, cfg, serve, metrics_obj=mx,
                                    vclock=vc, transport=transport)
                driver = fab
                if vclock:
                    door = FrontDoor(fab)
                    driver = door
                t0 = time.monotonic()
                scrape_rec = None
                scrape_pause_s = 0.0
                if server is not None:
                    driver.run(reqs, arrivals,
                               until=lambda: "serve.ttft_ms"
                               in mx.sketches)
                    t_pause = time.monotonic()
                    scrape_rec = _scrape_metrics(server)
                    scrape_pause_s = time.monotonic() - t_pause
                    driver.run()
                else:
                    driver.run(reqs, arrivals)
                wall_s = max(time.monotonic() - t0 - scrape_pause_s,
                             1e-9)
                s = fab.summary()
                tokens = sum(e["tokens"] for e in s["engines"])
                tps = tokens / wall_s
                base_tps = base_tps if base_tps is not None else tps
                if int(k) == 1:
                    single_tps[int(every)] = tps
                retires = [d for d in mx.decisions
                           if d.get("decision") == "serve.retire"]
                ttfts = [d["ttft_ms"] for d in retires
                         if d.get("ttft_ms") is not None]
                tpots = [d["tpot_ms"] for d in retires
                         if d.get("tpot_ms") is not None]
                tag = ",telemetry" if server is not None else ""
                if vclock:
                    tag += ",vclock"
                if wire != "inproc":
                    tag += f",wire={wire}"
                rec = {
                    "metric": f"fabric_load[replicas={int(k)},"
                              f"every={int(every)},"
                              f"req={n_requests}{tag}]",
                    "value": round(tps, 1),
                    "unit": "tokens_per_sec",
                    "vs_baseline": (round(tps / base_tps, 3)
                                    if base_tps else None),
                    "vs_single": (round(
                        tps / single_tps[int(every)], 3)
                        if single_tps.get(int(every)) else None),
                    "replicas": int(k),
                    "offered_every_steps": int(every),
                    "completed": sum(e["completed"]
                                     for e in s["engines"]),
                    "tokens": tokens,
                    "steps": s["steps"],
                    "handoffs": s["handoffs"],
                    "handoff_kb": round(s["handoff_bytes"] / 1024, 3),
                    "handoff_ms_modeled": round(
                        fab.handoff.modeled_ms_total, 6),
                    "routed": s["routed"],
                    "evictions": sum(e["evictions"]
                                     for e in s["engines"]),
                    "ttft_ms_p50": pctl(ttfts, 0.5),
                    "ttft_ms_p99": pctl(ttfts, 0.99),
                    "tpot_ms_p50": pctl(tpots, 0.5),
                    "tpot_ms_p99": pctl(tpots, 0.99),
                    "pools_formed": fab.pool_plan is not None,
                    "backend": jax.default_backend(),
                }
                if scrape_rec is not None:
                    rec["telemetry_scrape"] = scrape_rec
                    rec["telemetry_port"] = server.port
                if door is not None:
                    # the measured-latency leg: TTFT/TPOT above are
                    # VIRTUAL-time numbers (under the priced DCN
                    # delay); these fields reconcile them against the
                    # planner's verdicts and the attribution gate
                    att = door.attribution()
                    errs = door.validate()
                    rec["vclock"] = True
                    rec["tick_ms"] = (round(vc.tick_ms, 6)
                                      if vc.tick_ms is not None
                                      else None)
                    rec["handoff_ms_measured"] = round(
                        fab.handoff.measured_ms_total, 6)
                    rec["handoff_hidden_frac"] = (
                        round(fab.handoff.hidden_ms_total
                              / fab.handoff.measured_ms_total, 6)
                        if fab.handoff.measured_ms_total > 0 else None)
                    rec["handoff_verdicts_agree"] = \
                        fab.handoff.drift_agree
                    rec["handoff_verdicts_total"] = \
                        fab.handoff.drift_total
                    rec["attribution_sum_ok"] = bool(
                        att and all(a["sum_ok"] for a in att.values()))
                    rec["attribution_max_rel_err"] = (
                        max(a["rel_err"] for a in att.values())
                        if att else None)
                    doms = [a["dominant"] for a in att.values()]
                    rec["attribution_dominant"] = {
                        d: doms.count(d) for d in sorted(set(doms))}
                    rec["trace_errors"] = len(errs)
                    door.close()
                if transport is not None:
                    # socket-wire provenance: real roundtrips + any
                    # real connection resets the ladder absorbed
                    rec["wire"] = wire
                    rec["wire_transfers"] = transport.transfers
                    rec["wire_resets"] = transport.reset_total
                records.append(rec)
                fab.close()
                if transport is not None:
                    transport.close()
    finally:
        if saved is None:
            os.environ.pop(ENV_MOCK_FABRIC, None)
        else:
            os.environ[ENV_MOCK_FABRIC] = saved
        if server is not None:
            server.stop()
    return records


#: the serving fault-tolerance ladder drilled by ``--fabric --faults``
#: (chaos.EXPECTED_TIER owns the fault -> recovery-tier mapping)
SERVING_FAULTS = ("replica_crash", "handoff_corrupt",
                  "handoff_timeout", "frontdoor_loss",
                  "net_partition", "lease_split_brain",
                  "replica_stall", "lease_torn_write")


def fabric_fault_sweep(faults=None, *, seed: int = 0,
                       include_brownout: bool = True) -> list[dict]:
    """The ``bench.py --fabric --faults`` sweep: one record per
    serving fault, each running that fault's chaos drill
    (:func:`flashmoe_tpu.chaos.drill.run_drill`) against a mocked
    2-replica fabric and reporting the recovery ledger — wall-clock
    recovery latency as the headline value plus migrated-request
    count, handoff retry/corrupt totals, front-door failovers, and
    the trace-contiguity verdict.  A drill that does not recover
    carries ``error`` so the perf sentry never baselines a broken
    run's latency.

    ``include_brownout`` appends one more record: a seeded flood
    through a brownout-armed :class:`~flashmoe_tpu.fabric.frontdoor.
    FrontDoor` on the virtual clock, whose headline value is the shed
    fraction (``unit: frac`` — admissions rejected / offered)."""
    import jax

    from flashmoe_tpu.chaos.drill import run_drill

    faults = tuple(faults) if faults is not None else SERVING_FAULTS
    bad = [f for f in faults if f not in SERVING_FAULTS]
    if bad:
        raise ValueError(f"not serving faults: {bad} "
                         f"(choose from {SERVING_FAULTS})")
    records = []
    for fault in faults:
        r = run_drill(fault, seed=seed)
        ev = r.evidence
        rec = {
            "metric": f"fabric_fault[{fault}]",
            "value": round(r.wall_s * 1e3, 1),
            "unit": "ms",
            "fault": fault,
            "tier": r.expected_tier,
            "recovered": r.recovered,
            "completed": ev.get("completed", 0),
            "bit_equal": ev.get("bit_equal_to_baseline", False),
            "migrated": ev.get("migrations", 0),
            "retries": ev.get("retries", 0),
            "corrupt": ev.get("corrupt", 0),
            "failovers": ev.get("failovers", 0),
            "partitions": ev.get("partitions", 0),
            "fences": ev.get("fences", 0),
            "lease_repairs": ev.get("lease_repairs", 0),
            "shed_frac": 0.0,   # fault drills never shed; the brownout
            "trace_errors": len(ev.get("trace_errors") or []),
            "backend": jax.default_backend(),
        }
        # sub-step detection latency (virtual ms from the hang to the
        # watchdog's verdict) — only the heartbeat drill prices one
        stalls = [d for d in r.decisions
                  if d.get("decision") == "fabric.heartbeat_stall"]
        if stalls:
            rec["heartbeat_detect_ms"] = round(
                max(d.get("detect_ms", 0.0) for d in stalls), 6)
        if not r.recovered:
            rec["error"] = r.reason[:200]
        records.append(rec)
    if include_brownout:
        records.append(_brownout_shed_record(seed=seed))
    return records


def _brownout_shed_record(*, seed: int = 0) -> dict:
    """One deterministic brownout drill: a seeded flood against the
    hysteretic admission controller on the virtual clock (shed
    decisions depend only on queue depth and step index — bit-stable
    across machines)."""
    import os
    import time

    import jax

    from flashmoe_tpu.fabric.engine import ServingFabric
    from flashmoe_tpu.fabric.frontdoor import FrontDoor
    from flashmoe_tpu.fabric.topo import ENV_MOCK_FABRIC
    from flashmoe_tpu.fabric.vclock import VirtualClock
    from flashmoe_tpu.models.transformer import init_params
    from flashmoe_tpu.runtime.controller import BrownoutConfig
    from flashmoe_tpu.serving.engine import ServeConfig
    from flashmoe_tpu.utils.telemetry import Metrics

    cfg = tiny_config()
    params = init_params(jax.random.PRNGKey(seed), cfg)
    serve = ServeConfig(
        max_batch=2, page_size=8, num_pages=64, max_pages_per_slot=4,
        ctx_bucket_pages=1, prompt_bucket=8)
    flood, _ = build_requests(10, vocab=cfg.vocab_size, prompt_len=8,
                              max_new=6, seed=seed + 1,
                              arrival_every=1)
    # front-loaded arrivals: the burst trips the threshold, the tail
    # arrives while the brownout holds
    arrivals = [0, 0, 0, 0, 2, 2, 3, 3, 4, 5]
    bo = BrownoutConfig(queue_high=2.0, queue_low=0.5,
                        debounce_steps=1, cooldown_steps=2,
                        episode_budget=2)
    mx = Metrics()
    saved = os.environ.get(ENV_MOCK_FABRIC)
    os.environ[ENV_MOCK_FABRIC] = "2"
    fab = door = None
    t0 = time.perf_counter()
    try:
        fab = ServingFabric(params, cfg, serve, metrics_obj=mx,
                            vclock=VirtualClock())
        door = FrontDoor(fab, brownout=bo)
        out = door.run(flood, arrivals)
        errs = door.validate()
        snap = door.brownout_snapshot()
    finally:
        if door is not None:
            door.close()
        if fab is not None:
            fab.close()
        if saved is None:
            os.environ.pop(ENV_MOCK_FABRIC, None)
        else:
            os.environ[ENV_MOCK_FABRIC] = saved
    wall_ms = (time.perf_counter() - t0) * 1e3
    return {
        "metric": "fabric_shed[brownout]",
        "value": round(snap["shed"] / len(flood), 4),
        "unit": "frac",
        "offered": len(flood),
        "completed": len(out),
        "shed": snap["shed"],
        "degraded": snap["degraded"],
        "episodes": snap["episodes"],
        "trace_errors": len(errs),
        "wall_ms": round(wall_ms, 1),
        "backend": jax.default_backend(),
    }
