"""Prefill/decode pool formation: heterogeneous inference-mode Decider
groups.

Prefill steps are compute-bound full-sequence forwards (B x S tokens);
decode steps move a tiny per-step batch and are latency-bound.  A
disaggregated serving deployment therefore runs them on SEPARATE device
pools — and sizing those pools is exactly the reference Decider's
inference-mode specialization (``decider.cuh:177-268``: the group
objective with NO gradient-allreduce term), applied twice with
different per-step workloads.

:func:`plan_serving_pools` is that split: devices are partitioned into
a decode pool (the fastest devices — decode is the latency-critical
phase) and a prefill pool, sized so the decode pool's throughput share
matches the offered decode compute share; each pool is then priced with
the inference objective (:func:`flashmoe_tpu.parallel.decider.
group_objective`, ``allreduce_ms=0``) at ITS OWN token count — prefill
at the full sequence, decode at the per-step decode batch (the same
decode shape the planner's ``mode='decode'`` prices).  This is the
stepping stone to ROADMAP item 5's multi-slice disaggregation, where
the pools become Decider groups over a measured DCN topology.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.parallel.decider import CostArgs, group_objective
from flashmoe_tpu.utils.telemetry import metrics as _metrics


@dataclasses.dataclass(frozen=True)
class PoolPlan:
    """The split: device id lists per pool plus each pool's priced
    per-step objective (ms, inference mode — no allreduce term)."""

    prefill_devices: tuple
    decode_devices: tuple
    prefill_ms: float
    decode_ms: float
    decode_share: float


def _pool_objective(members, rates, adj, cfg: MoEConfig,
                    tokens: int) -> float:
    """Inference-mode objective of one pool at its per-step token
    count: expert compute split over the pool's rate + the worst
    intra-pool activation transfer (``decider._intra_comm_ms``'s
    shrinking-slab rule), allreduce = 0 (``decider.cuh:177-268``)."""
    from flashmoe_tpu.parallel.decider import _intra_comm_ms

    import jax.numpy as jnp

    act_mb = tokens * cfg.hidden_size \
        * jnp.dtype(cfg.param_dtype).itemsize / 1e6
    gamma = max(1, cfg.num_layers // max(1, cfg.moe_frequency))
    args = CostArgs(
        total_expert_cost_ms=cfg.num_experts / max(
            min(rates[m] for m in members), 1e-9),
        comm_mbytes=act_mb, grad_buffer_mb=0.0, gamma=gamma)
    intra = _intra_comm_ms(members, adj, act_mb) if len(members) > 1 \
        else 0.0
    return group_objective(members, rates, intra, args,
                           allreduce_ms=0.0)


def plan_serving_pools(adj, workers, cfg: MoEConfig, *,
                       decode_share: float = 0.5,
                       decode_tokens: int | None = None,
                       record: bool = True) -> PoolPlan:
    """Partition the world into (prefill, decode) pools.

    ``decode_share``: fraction of total compute the decode phase is
    expected to consume (an offered-load property); the decode pool
    takes the FASTEST devices, throughput-greedy, until its rate share
    reaches it — decode is the latency-critical phase, so it gets the
    best silicon, and the assignment is deterministic (throughput
    descending, device id ascending).  Both pools must be non-empty
    (>= 2 devices total).  ``decode_tokens``: the decode pool's
    per-step token count (default
    ``planner.model.DECODE_TOKENS_DEFAULT``); prefill prices at the
    config's full ``cfg.tokens``.
    """
    from flashmoe_tpu.planner.model import DECODE_TOKENS_DEFAULT

    n = adj.n
    if n < 2:
        raise ValueError(
            f"pool split needs >= 2 devices, got {n} (run the engine "
            f"co-located instead)")
    if not 0.0 < decode_share < 1.0:
        raise ValueError(f"decode_share={decode_share} must be in "
                         f"(0, 1)")
    rates = [w.throughput for w in workers]
    total_rate = float(np.sum(rates))
    order = sorted(range(n), key=lambda d: (-rates[d], d))
    decode: list = []
    acc = 0.0
    for d in order:
        if len(decode) >= n - 1:
            break
        if acc / total_rate >= decode_share and decode:
            break
        decode.append(d)
        acc += rates[d]
    prefill = [d for d in range(n) if d not in decode]
    decode.sort()

    toks = int(decode_tokens or DECODE_TOKENS_DEFAULT)
    prefill_ms = _pool_objective(prefill, rates, adj, cfg, cfg.tokens)
    decode_ms = _pool_objective(decode, rates, adj, cfg, toks)
    plan = PoolPlan(tuple(prefill), tuple(decode), prefill_ms,
                    decode_ms, decode_share)
    if record:
        _metrics.decision(
            "serve.pools", prefill_devices=list(plan.prefill_devices),
            decode_devices=list(plan.decode_devices),
            prefill_ms=round(prefill_ms, 4),
            decode_ms=round(decode_ms, 4),
            decode_share=decode_share, decode_tokens=toks)
    return plan
