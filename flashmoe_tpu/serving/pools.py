"""Prefill/decode pool formation: heterogeneous inference-mode Decider
groups.

Prefill steps are compute-bound full-sequence forwards (B x S tokens);
decode steps move a tiny per-step batch and are latency-bound.  A
disaggregated serving deployment therefore runs them on SEPARATE device
pools — and sizing those pools is exactly the reference Decider's
inference-mode specialization (``decider.cuh:177-268``: the group
objective with NO gradient-allreduce term), applied twice with
different per-step workloads.

:func:`plan_serving_pools` is that split: devices are partitioned into
a decode pool (the fastest devices — decode is the latency-critical
phase) and a prefill pool, sized so the decode pool's throughput share
matches the offered decode compute share; each pool is then priced with
the inference objective (:func:`flashmoe_tpu.parallel.decider.
group_objective`, ``allreduce_ms=0``) at ITS OWN token count — prefill
at the full sequence, decode at the per-step decode batch (the same
decode shape the planner's ``mode='decode'`` prices).

The disaggregated fabric (ISSUE 16) grows each pool into a full
Decider group: pass ``devices`` and the split additionally runs
:func:`flashmoe_tpu.runtime.bootstrap.form_groups` per pool over the
pool's sub-adjacency (its own DP x EP mapping) plus
:func:`flashmoe_tpu.planner.select.select_path` in the pool's pricing
mode (its own execution plan), and ``prefill_overrides`` /
``decode_overrides`` give each pool its OWN config — the PR 14 int8
expert store on the decode pool, a KV handoff wire, per-pool a2a wire
dtypes — carried on ``PoolPlan.prefill_cfg`` / ``decode_cfg`` so the
fabric loads per-pool quantized states from them.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.parallel.decider import CostArgs, group_objective
from flashmoe_tpu.utils.telemetry import metrics as _metrics


@dataclasses.dataclass(frozen=True)
class PoolPlan:
    """The split: device id lists per pool plus each pool's priced
    per-step objective (ms, inference mode — no allreduce term).

    The fabric fields (``None`` unless ``plan_serving_pools`` ran with
    ``devices``): per-pool Decider group formations
    (:class:`~flashmoe_tpu.runtime.bootstrap.GroupPlan`), per-pool
    planner selections, and per-pool configs carrying each pool's own
    quant/wire settings."""

    prefill_devices: tuple
    decode_devices: tuple
    prefill_ms: float
    decode_ms: float
    decode_share: float
    prefill_group: object | None = None
    decode_group: object | None = None
    prefill_path: object | None = None     # planner Selection
    decode_path: object | None = None
    prefill_cfg: MoEConfig | None = None
    decode_cfg: MoEConfig | None = None

    def snapshot(self) -> dict:
        """JSON-safe ``/vars`` view of the split."""
        doc = {
            "prefill_devices": list(self.prefill_devices),
            "decode_devices": list(self.decode_devices),
            "prefill_ms": round(self.prefill_ms, 4),
            "decode_ms": round(self.decode_ms, 4),
            "decode_share": self.decode_share,
        }
        for name, grp in (("prefill_group", self.prefill_group),
                          ("decode_group", self.decode_group)):
            if grp is not None:
                doc[name] = {"dp": grp.dp, "ep": grp.ep,
                             "mapping": grp.mapping}
        for name, sel in (("prefill_path", self.prefill_path),
                          ("decode_path", self.decode_path)):
            if sel is not None:
                doc[name] = {"backend": getattr(sel, "backend", None),
                             "chunks": getattr(sel, "chunks", None)}
        for name, c in (("prefill_cfg", self.prefill_cfg),
                        ("decode_cfg", self.decode_cfg)):
            if c is not None:
                doc[name] = {"expert_quant": c.expert_quant,
                             "wire_dtype": c.wire_dtype,
                             "wire_dtype_dcn": c.wire_dtype_dcn,
                             "kv_wire_dtype": c.kv_wire_dtype}
        return doc


def _pool_objective(members, rates, adj, cfg: MoEConfig,
                    tokens: int) -> float:
    """Inference-mode objective of one pool at its per-step token
    count: expert compute split over the pool's rate + the worst
    intra-pool activation transfer (``decider._intra_comm_ms``'s
    shrinking-slab rule), allreduce = 0 (``decider.cuh:177-268``)."""
    from flashmoe_tpu.parallel.decider import _intra_comm_ms

    import jax.numpy as jnp

    act_mb = tokens * cfg.hidden_size \
        * jnp.dtype(cfg.param_dtype).itemsize / 1e6
    gamma = max(1, cfg.num_layers // max(1, cfg.moe_frequency))
    args = CostArgs(
        total_expert_cost_ms=cfg.num_experts / max(
            min(rates[m] for m in members), 1e-9),
        comm_mbytes=act_mb, grad_buffer_mb=0.0, gamma=gamma)
    intra = _intra_comm_ms(members, adj, act_mb) if len(members) > 1 \
        else 0.0
    return group_objective(members, rates, intra, args,
                           allreduce_ms=0.0)


def _sub_adjacency(adj, members):
    """Restrict the world adjacency to one pool's members (index order
    preserved — the sub-matrix keeps the DCN entries of any cross-slice
    pair inside the pool)."""
    from flashmoe_tpu.parallel.topology import Adjacency

    ix = np.ix_(members, members)
    return Adjacency(alpha=np.asarray(adj.alpha)[ix],
                     beta=np.asarray(adj.beta)[ix])


def _pool_ep_width(cfg: MoEConfig, n: int) -> int:
    """The EP width a pool of ``n`` devices can actually run: the
    largest divisor of ``num_experts`` that fits (deterministic; 1 for
    a single-device pool)."""
    for d in range(min(n, cfg.num_experts), 0, -1):
        if cfg.num_experts % d == 0:
            return d
    return 1


def _form_pool(cfg: MoEConfig, members, devices, adj, workers,
               *, mode: str, decode_tokens: int):
    """One pool's Decider group + planner selection at its own pricing
    mode.  ``devices``: the world's jax devices (parallel to the
    adjacency indices)."""
    from flashmoe_tpu.planner.select import select_path
    from flashmoe_tpu.runtime.bootstrap import form_groups

    sub_adj = _sub_adjacency(adj, members)
    sub_workers = [workers[m] for m in members]
    group = form_groups(cfg, [devices[m] for m in members],
                        adj=sub_adj, workers=sub_workers)
    d = group.ep if group.ep >= 1 else _pool_ep_width(cfg, len(members))
    sel = select_path(cfg, d=d, record=False, mode=mode,
                      decode_tokens=(decode_tokens
                                     if mode == "decode" else None))
    return group, sel


def plan_serving_pools(adj, workers, cfg: MoEConfig, *,
                       decode_share: float = 0.5,
                       decode_tokens: int | None = None,
                       devices=None,
                       prefill_overrides: dict | None = None,
                       decode_overrides: dict | None = None,
                       record: bool = True) -> PoolPlan:
    """Partition the world into (prefill, decode) pools.

    ``decode_share``: fraction of total compute the decode phase is
    expected to consume (an offered-load property); the decode pool
    takes the FASTEST devices, throughput-greedy, until its rate share
    reaches it — decode is the latency-critical phase, so it gets the
    best silicon, and the assignment is deterministic (throughput
    descending, device id ascending).  Both pools must be non-empty
    (>= 2 devices total).  ``decode_tokens``: the decode pool's
    per-step token count (default
    ``planner.model.DECODE_TOKENS_DEFAULT``); prefill prices at the
    config's full ``cfg.tokens``.

    ``devices`` (the world's jax devices, parallel to the adjacency
    indices) upgrades each pool to a full Decider group:
    ``bootstrap.form_groups`` runs per pool over the pool's
    sub-adjacency and ``select.select_path`` prices each pool's
    execution in ITS mode (prefill / decode).  ``prefill_overrides`` /
    ``decode_overrides`` are per-pool ``MoEConfig.replace`` fields
    (quant / wire knobs — e.g. ``{"expert_quant": "int8"}`` on decode
    only) applied before the pool is formed and carried on the plan's
    ``prefill_cfg`` / ``decode_cfg``.
    """
    from flashmoe_tpu.planner.model import DECODE_TOKENS_DEFAULT

    n = adj.n
    if n < 2:
        raise ValueError(
            f"pool split needs >= 2 devices, got {n} (run the engine "
            f"co-located instead)")
    if not 0.0 < decode_share < 1.0:
        raise ValueError(f"decode_share={decode_share} must be in "
                         f"(0, 1)")
    rates = [w.throughput for w in workers]
    total_rate = float(np.sum(rates))
    order = sorted(range(n), key=lambda d: (-rates[d], d))
    decode: list = []
    acc = 0.0
    for d in order:
        if len(decode) >= n - 1:
            break
        if acc / total_rate >= decode_share and decode:
            break
        decode.append(d)
        acc += rates[d]
    prefill = [d for d in range(n) if d not in decode]
    decode.sort()

    toks = int(decode_tokens or DECODE_TOKENS_DEFAULT)
    prefill_cfg = (cfg.replace(**prefill_overrides)
                   if prefill_overrides else cfg)
    decode_cfg = (cfg.replace(**decode_overrides)
                  if decode_overrides else cfg)
    prefill_ms = _pool_objective(prefill, rates, adj, prefill_cfg,
                                 cfg.tokens)
    decode_ms = _pool_objective(decode, rates, adj, decode_cfg, toks)

    pre_group = dec_group = pre_sel = dec_sel = None
    if devices is not None:
        pre_group, pre_sel = _form_pool(
            prefill_cfg, prefill, devices, adj, workers,
            mode="prefill", decode_tokens=toks)
        dec_group, dec_sel = _form_pool(
            decode_cfg, decode, devices, adj, workers,
            mode="decode", decode_tokens=toks)

    plan = PoolPlan(
        tuple(prefill), tuple(decode), prefill_ms, decode_ms,
        decode_share,
        prefill_group=pre_group, decode_group=dec_group,
        prefill_path=pre_sel, decode_path=dec_sel,
        prefill_cfg=(prefill_cfg if prefill_overrides or devices
                     is not None else None),
        decode_cfg=(decode_cfg if decode_overrides or devices
                    is not None else None))
    if record:
        fields = dict(
            prefill_devices=list(plan.prefill_devices),
            decode_devices=list(plan.decode_devices),
            prefill_ms=round(prefill_ms, 4),
            decode_ms=round(decode_ms, 4),
            decode_share=decode_share, decode_tokens=toks)
        if pre_group is not None:
            fields.update(
                prefill_mapping=pre_group.mapping,
                prefill_ep=pre_group.ep,
                decode_mapping=dec_group.mapping,
                decode_ep=dec_group.ep,
                prefill_quant=prefill_cfg.expert_quant,
                decode_quant=decode_cfg.expert_quant,
                kv_wire=decode_cfg.kv_wire_dtype)
        _metrics.decision("serve.pools", **fields)
    return plan
