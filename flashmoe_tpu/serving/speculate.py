"""Speculative multi-token decoding: drafting (ISSUE 20, ROADMAP item 3).

The planner's decode mode (PR 10) prices what the reference kernel is
built around: at decode shapes the step is wire/HBM-bound — the weights
stream past once per step regardless of how many tokens ride the batch
— so verifying ``k`` drafted tokens in one batched forward costs barely
more than verifying one.  Speculation converts that slack into tokens
per step: a cheap **drafter** proposes ``k`` continuation tokens per
active slot, the engine scores all ``k+1`` positions in one paged
forward (:func:`flashmoe_tpu.serving.engine._paged_verify_step`), and
an **exact acceptance rule** keeps only the drafted prefix that matches
what the engine's own sampler would have emitted anyway.

Exactness (the whole point): the serving engine keys every sampled
token on ``fold_in(PRNGKey(seed), token_index)`` — the key indexes a
TOKEN POSITION, not a step.  The verify pass computes the canonical
sample for each drafted position with that position's own key and the
shared :func:`~flashmoe_tpu.serving.engine._sample_dynamic` numerics,
and a draft is accepted **iff it equals the canonical sample**.  Only
accepted (= canonical) tokens are ever emitted, so the output stream is
bit-equal to non-speculative decode for every temperature / top-k /
top-p arm; drafting quality affects throughput only, never tokens.

The drafter here is **n-gram prompt-lookup** (no second model): each
slot keeps a suffix-match table over its own token history (prompt +
emitted) as plain host state alongside its block table.  The table is
rebuilt deterministically from ``prompt + emitted`` — which is exactly
the resumed prompt the eviction / replica-migration path carries — so
speculation survives an eviction/re-prefill cycle and a fabric handoff
with zero extra protocol.  :class:`SpecConfig` is the seam a small
draft MODEL slots into later (``source`` selects the backend); the
engine only ever sees "propose up to ``draft_tokens`` ints".
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Speculation knobs, carried on ``ServeConfig.speculate`` (None =
    off = the byte-identical non-speculative engine).  Frozen and
    hashable so it rides the jit cache key story and
    ``dataclasses.asdict`` (the engine's ``/vars`` snapshot) unchanged.

    ``draft_tokens``: drafts proposed per slot per step; the verify
    forward scores ``draft_tokens + 1`` positions.  ``ngram``: suffix
    length the prompt-lookup matches on.  ``source``: drafting backend
    — ``"ngram"`` today; the seam a draft model plugs into later.
    """

    draft_tokens: int = 3
    ngram: int = 2
    source: str = "ngram"

    def __post_init__(self):
        if self.draft_tokens < 1:
            raise ValueError(
                f"draft_tokens must be >= 1, got {self.draft_tokens}")
        if self.ngram < 1:
            raise ValueError(f"ngram must be >= 1, got {self.ngram}")
        if self.source != "ngram":
            raise ValueError(
                f"unknown draft source {self.source!r} (only 'ngram' "
                f"prompt-lookup drafting exists today)")


class DraftState:
    """One slot's suffix-match table: host state alongside the block
    table.  ``index[suffix] -> continuation position`` of the LATEST
    occurrence, with the previous occurrence kept so the current
    suffix's own registration never proposes past the end of history.

    Deterministic by construction (pure function of the token history),
    and rebuilt from ``prompt + emitted`` on adoption — the same
    resumed-prompt invariant the eviction path already guarantees.
    """

    def __init__(self, spec: SpecConfig, tokens=()):
        self.spec = spec
        self.tokens: list[int] = []
        self._index: dict[tuple, int] = {}
        self._prev: dict[tuple, int] = {}
        self.extend(tokens)

    def extend(self, toks) -> None:
        for t in toks:
            self.tokens.append(int(t))
            n = self.spec.ngram
            pos = len(self.tokens)
            if pos >= n:
                key = tuple(self.tokens[pos - n:pos])
                old = self._index.get(key)
                if old is not None:
                    self._prev[key] = old
                self._index[key] = pos

    def sync(self, tokens) -> None:
        """Catch the table up to ``tokens`` (= prompt + emitted).  The
        history only ever grows by appends, so this is O(new)."""
        if len(tokens) < len(self.tokens):
            raise ValueError(
                "draft history shrank: the table must be rebuilt, not "
                "synced, after a prompt rewrite")
        self.extend(tokens[len(self.tokens):])

    def draft(self, k: int) -> list:
        """Up to ``k`` proposed continuation tokens: the tokens that
        followed the most recent PRIOR occurrence of the current
        ``ngram``-token suffix.  Empty when history is too short or the
        suffix never occurred before."""
        n = self.spec.ngram
        if len(self.tokens) < n or k < 1:
            return []
        key = tuple(self.tokens[-n:])
        cont = self._index.get(key)
        if cont == len(self.tokens):
            # the latest occurrence is the current suffix itself; use
            # the one before it (if any)
            cont = self._prev.get(key)
        if cont is None:
            return []
        return list(self.tokens[cont:cont + k])


def spec_stats_fields(drafted: int, accepted: int, steps: int) -> dict:
    """Normalized acceptance stats for flight records / summaries:
    ``accept_rate`` = accepted drafts / drafted, ``spec_tokens_per_step``
    = mean emitted per speculative step (the canonical token plus the
    accepted drafts)."""
    return {
        "spec_drafted": int(drafted),
        "spec_accepted": int(accepted),
        "accept_rate": (round(accepted / drafted, 6) if drafted else None),
        "spec_tokens_per_step": (round(1.0 + accepted / steps, 6)
                                 if steps else None),
    }
