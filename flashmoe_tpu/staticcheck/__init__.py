"""Static-analysis subsystem: prove the knob matrix safe without silicon.

The paper's single-kernel design is safe because one kernel has one code
path.  This reproduction instead has a combinatorial knob matrix
(backend x wire_dtype x a2a_chunks x collect_stats x degrade x ...)
whose safety used to rest on per-PR one-off assertions scattered across
the test suite — and, with ``tuning_data/`` still empty (every hardware
bench window hung), comm-cost claims that nothing statically checked
against the code.  Like Comet's tile-level dependency analysis
(arXiv 2502.19811) and in the spirit of SonicMoE's IO accounting
(arXiv 2512.14080), this package verifies structure by *tracing*, never
executing:

* :mod:`flashmoe_tpu.staticcheck.invariants` — the jaxpr invariant
  engine: traces every registered (backend, knob) combination of the
  MoE layer under an abstract mesh and asserts structural invariants
  (default-off knobs yield the baseline jaxpr, wire off => no fp8
  dtypes, collect_stats off => no extra collectives, degrade off => no
  extra health ops, tracer hygiene);
* :mod:`flashmoe_tpu.staticcheck.census` — the collective census
  cross-check: counts the collectives (and the bytes they move) in the
  lowered graph of every golden config variant and reconciles them
  against ``analysis.comm_census`` / the planner's per-leg slabs — a
  CI-runnable drift detector between the analytical model and the code;
* :mod:`flashmoe_tpu.staticcheck.lint` — the AST lint pass: forbidden
  host-side patterns inside traced code, the central decision-name
  registry (:mod:`flashmoe_tpu.utils.telemetry`), doc sync, and the
  generalized slow-mark budget guard migrated from
  ``tests/test_collection.py``.

CLI: ``python -m flashmoe_tpu.staticcheck --all`` (exits nonzero on any
violation).  Registration of new knobs/backends/census rows is
declarative — :mod:`flashmoe_tpu.staticcheck.registry`.
"""

from flashmoe_tpu.staticcheck.registry import (  # noqa: F401
    BACKENDS,
    KNOBS,
    STRUCTURAL_FIELDS,
    Violation,
    check_knob_coverage,
)

__all__ = [
    "BACKENDS",
    "KNOBS",
    "STRUCTURAL_FIELDS",
    "Violation",
    "check_knob_coverage",
    "run_invariants",
    "run_census",
    "run_lint",
]


def run_invariants(**kw):
    """Lazy re-export (tracing imports jax; keep the lint path light)."""
    from flashmoe_tpu.staticcheck.invariants import run_invariants as f

    return f(**kw)


def run_census(**kw):
    from flashmoe_tpu.staticcheck.census import run_census as f

    return f(**kw)


def run_lint(**kw):
    from flashmoe_tpu.staticcheck.lint import run_lint as f

    return f(**kw)
