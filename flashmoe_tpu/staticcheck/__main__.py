"""Staticcheck CLI: ``python -m flashmoe_tpu.staticcheck``.

Examples::

    python -m flashmoe_tpu.staticcheck --all        # every engine (default)
    python -m flashmoe_tpu.staticcheck --invariants # jaxpr knob matrix
    python -m flashmoe_tpu.staticcheck --census     # collective census
    python -m flashmoe_tpu.staticcheck --lint       # AST rules only
    python -m flashmoe_tpu.staticcheck --lint --paths somefile.py
    python -m flashmoe_tpu.staticcheck --all --json # machine-readable

Exit status: 0 = clean, 1 = violations (printed / in the JSON doc).
Runtime budget: the full ``--all`` run traces the whole invariant and
census matrices on a virtual 8-device CPU mesh in well under a minute
(~15 s invariants + ~5 s census + ~5 s lint on a laptop-class CPU) —
fast-lane material, and wired into tier-1 via tests/test_staticcheck.py.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys


def _ensure_virtual_mesh():
    """The tracing engines need >= 8 devices.  Mirror tests/conftest.py:
    force the virtual CPU backend unless the caller explicitly asked for
    real hardware — static analysis never needs silicon."""
    if os.environ.get("FLASHMOE_TEST_TPU") == "1":
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m flashmoe_tpu.staticcheck",
        description="static verification of the MoE knob matrix: jaxpr "
                    "invariants, collective census, AST lint")
    ap.add_argument("--all", action="store_true",
                    help="run every engine (default when none selected)")
    ap.add_argument("--invariants", action="store_true",
                    help="jaxpr invariant engine (backend x knob matrix)")
    ap.add_argument("--census", action="store_true",
                    help="collective census vs analysis/planner models")
    ap.add_argument("--lint", action="store_true",
                    help="AST lint (in-graph hygiene, decision names, "
                         "doc sync, slow-mark budget guard)")
    ap.add_argument("--paths", nargs="+", default=None,
                    help="restrict the lint to explicit files")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON document instead of text")
    args = ap.parse_args(argv)

    run_all = args.all or not (args.invariants or args.census or args.lint)
    violations = []
    doc: dict = {"engines": {}}

    if run_all or args.lint:
        from flashmoe_tpu.staticcheck.lint import run_lint

        v = run_lint(paths=args.paths)
        violations += v
        doc["engines"]["lint"] = {"violations": len(v)}

    if run_all or args.invariants or args.census:
        _ensure_virtual_mesh()

    if run_all or args.invariants:
        from flashmoe_tpu.staticcheck.invariants import run_invariants

        v = run_invariants()
        violations += v
        doc["engines"]["invariants"] = {"violations": len(v)}

    if run_all or args.census:
        from flashmoe_tpu.staticcheck.census import (
            report_table, run_census,
        )

        v, rows = run_census()
        violations += v
        doc["engines"]["census"] = {
            "violations": len(v),
            "rows": [dataclasses.asdict(r) for r in rows],
        }
        if not args.json:
            print("\n## collective census (traced graph vs "
                  "analysis/planner models)\n")
            print(report_table(rows))

    doc["violations"] = [dataclasses.asdict(v) for v in violations]
    doc["ok"] = not violations
    if args.json:
        json.dump(doc, sys.stdout)
        print()
    else:
        print()
        if violations:
            print(f"FAIL: {len(violations)} violation(s)")
            for v in violations:
                print(f"  {v}")
        else:
            engines = ", ".join(doc["engines"]) or "none"
            print(f"OK: no violations ({engines})")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
