"""Collective census cross-check: reconcile the collectives a lowered
MoE graph actually contains — counts and operand bytes — against the
analytical comm model, for every golden planner config and knob variant.

With ``tuning_data/`` still empty (every hardware bench window hung),
the planner's comm claims are model-derived with nothing checking the
model against the code.  Real silicon would expose drift as wrong
timings; this engine exposes it *statically*: trace the layer under an
abstract mesh (``jax.eval_shape`` parameter shapes — no allocation, no
execution), walk the jaxpr, and require

* every ``all_to_all`` / ``all_gather`` byte to be explained by
  ``analysis.comm_census`` (which itself cross-checks the planner's
  ``slab_bytes`` against ``path_costs.comm_bytes``, so the graph, the
  planner, and the HBM model must all agree);
* the eqn *counts* to match the chunk/stage/sidecar structure the
  planner charges alphas for;
* no other collective (a ppermute, an extra psum, an unregistered
  gather) to appear at all — an unpriced collective is a violation, not
  noise.

Reconciliation rules with documented slack (docs/STATIC_ANALYSIS.md):

* read+write convention: graph bytes are one-sided (what a rank hands
  the transport); ``path_costs.comm_bytes`` counts read+write — exact
  factor 2;
* hierarchical staging: each two-stage exchange moves the full local
  buffer twice — exact factor 2 per leg vs flat when both hops share
  one wire; with a per-hop DCN wire (``MoEConfig.wire_dtype_dcn``) the
  two stages price at their OWN row sizes (ici hop at the leg wire,
  dcn hop at the dcn wire), each cross-checked against ``path_costs``
  of the matching single-wire config;
* ragged dense fallback: the CPU arm pads every transfer to the
  worst-case bound — exact factor ``d x chunks`` vs the uniform-routing
  expectation the model prices (the TPU ``ragged_all_to_all`` arm moves
  the data-dependent exact rows instead).

Every factor is exact, so the gate runs at ``rtol=1e-6`` — there is no
tolerance band for drift to hide in.
"""

from __future__ import annotations

import dataclasses

from flashmoe_tpu.staticcheck import graph as g
from flashmoe_tpu.staticcheck.registry import Violation

#: relative tolerance of the byte reconciliation: float roundoff only —
#: every structural factor is exact
RTOL = 1e-6

#: the census matrix: every golden.json config x wire variant x chunk
#: variant x XLA transport path (flat / hierarchical / ragged).  Skips
#: are explicit and reasoned, never silent (mixtral's nLx=1 has no
#: chunk axis; the ragged layer rejects shared experts at config time).
CENSUS_PATHS = ("collective", "hierarchical", "ragged")
CENSUS_D = 8              # golden.GOLDEN_D: the 8-rank virtual mesh
CENSUS_DCN_INNER = 4      # hierarchical blocking: 2 slices of 4 ranks

#: census-only wire variants beyond golden.GOLDEN_WIRES: the per-hop
#: DCN wire (MoEConfig.wire_dtype_dcn, ISSUE 13).  On the hierarchical
#: path the outer stage re-encodes at fp8 (its own payload+sidecar
#: eqns, priced at the dcn row size); on the FLAT paths the knob is
#: inert and the rows double-check it prices as off.  Kept out of
#: GOLDEN_WIRES because the planner's golden tables are computed at
#: slices=1, where the variant would just duplicate the base rows.
CENSUS_EXTRA_WIRES = {"dcn-e4m3": {"wire_dtype_dcn": "e4m3"}}

#: the quantized-expert-storage dimension (MoEConfig.expert_quant,
#: ISSUE 15): weights are rank-LOCAL, so the int8 store must leave
#: every collective — count and bytes — exactly where the
#: full-precision build put it.  One serial-chunk, wire-off variant
#: per (config, path) reconciles that claim against the traced graph;
#: a quant implementation that smuggled a gather/a2a (or re-sized an
#: exchange) fails these rows before any silicon runs it.
CENSUS_QUANT = {"int8": {"expert_quant": "int8"}}

#: the KV-handoff-wire dimension (MoEConfig.kv_wire_dtype, ISSUE 16):
#: the fabric's prefill->decode page stream is coded HOST-SIDE, so the
#: knob must move NO collective — count and bytes exactly where the
#: wire-off build put them, on every path.  One serial, leg-wire-off
#: variant per (config, path) reconciles that claim against the traced
#: graph: a handoff codec that leaked into the traced layer (an astype
#: on the exchange, a smuggled gather) fails these rows statically.
CENSUS_KV_WIRE = {"e4m3": {"kv_wire_dtype": "e4m3"}}


@dataclasses.dataclass(frozen=True)
class CensusRow:
    """One reconciled (config, wire, chunks, path) point."""

    config: str
    path: str
    wire: str
    chunks: str
    a2a_eqns: int
    a2a_bytes: float
    expected_a2a_bytes: float
    gather_eqns: int
    psum_eqns: int
    model_comm_bytes: float     # path_costs read+write HBM model
    bound_factor: float         # graph/model per-leg ratio (documented)
    ok: bool
    note: str = ""


def census_matrix():
    """Yield (config_name, cfg_with_knobs, wire_tag, chunk_tag, path,
    skip_reason) over the golden matrix.  ``skip_reason`` is non-empty
    for declared, documented skips."""
    from flashmoe_tpu.config import BENCH_CONFIGS
    from flashmoe_tpu.planner.golden import (
        GOLDEN_CONFIGS, GOLDEN_WIRES, golden_chunk_variants,
    )

    for name in GOLDEN_CONFIGS:
        base = BENCH_CONFIGS[name]
        wire_variants = dict(GOLDEN_WIRES, **CENSUS_EXTRA_WIRES)
        for wtag, wknobs in wire_variants.items():
            for ctag, cknobs in golden_chunk_variants(base).items():
                cfg = base.replace(ep=CENSUS_D, **wknobs, **cknobs)
                for path in CENSUS_PATHS:
                    skip = ""
                    if path == "ragged" and cfg.num_shared_experts:
                        skip = ("ragged layer rejects shared experts "
                                "(config.py); collective covers this "
                                "config")
                    yield name, cfg, wtag, ctag, path, skip
        # quantized-store rows (serial, wire off): the comm model must
        # be UNMOVED by expert_quant — weights never ride a collective
        for qtag, qknobs in CENSUS_QUANT.items():
            cfg = base.replace(ep=CENSUS_D, **qknobs)
            for path in CENSUS_PATHS:
                skip = ""
                if path == "ragged" and cfg.num_shared_experts:
                    skip = ("ragged layer rejects shared experts "
                            "(config.py); collective covers this "
                            "config")
                yield name, cfg, f"off+q:{qtag}", "serial", path, skip
        # kv-handoff-wire rows (serial, leg wire off): the comm model
        # must be UNMOVED by kv_wire_dtype — the page codec is a host
        # boundary, never an exchange
        for ktag, kknobs in CENSUS_KV_WIRE.items():
            cfg = base.replace(ep=CENSUS_D, **kknobs)
            for path in CENSUS_PATHS:
                skip = ""
                if path == "ragged" and cfg.num_shared_experts:
                    skip = ("ragged layer rejects shared experts "
                            "(config.py); collective covers this "
                            "config")
                yield name, cfg, f"off+kv:{ktag}", "serial", path, skip


def _trace(cfg, path, devices):
    from flashmoe_tpu.staticcheck.invariants import trace_backend

    backend = "hierarchical" if path == "hierarchical" else path
    return trace_backend(
        backend, cfg, devices,
        dcn_inner=CENSUS_DCN_INNER if path == "hierarchical" else None)


def run_census(configs=None, wires=None, chunks=None, paths=None,
               devices=None):
    """Run the census matrix.  Optional ``configs`` / ``wires`` /
    ``chunks`` / ``paths`` restrict to named subsets (tests plant
    violations on one cell).  Returns ``(violations, rows)`` — rows
    include the reconciled numbers for the CLI report."""
    from flashmoe_tpu.analysis import comm_census

    out: list[Violation] = []
    rows: list[CensusRow] = []
    for name, cfg, wtag, ctag, path, skip in census_matrix():
        if configs and name not in configs:
            continue
        if wires and wtag not in wires:
            continue
        if chunks and ctag not in chunks:
            continue
        if paths and path not in paths:
            continue
        subject = f"{name}/{path}/wire={wtag}/chunks={ctag}"
        if skip:
            rows.append(CensusRow(name, path, wtag, ctag, 0, 0.0, 0.0,
                                  0, 0, 0.0, 0.0, True,
                                  note=f"skipped: {skip}"))
            continue
        try:
            want = comm_census(cfg, CENSUS_D, path)
        except AssertionError as e:
            # pre-trace model-vs-model drift (planner slabs moved
            # without path_costs, or vice versa): report it through
            # the violations contract so `--all --json` stays a
            # well-formed document instead of a traceback
            out.append(Violation("census", "model-cross-check",
                                 subject, str(e)))
            rows.append(CensusRow(name, path, wtag, ctag, 0, 0.0, 0.0,
                                  0, 0, 0.0, 0.0, False,
                                  note="model cross-check failed"))
            continue
        jx = _trace(cfg, path, devices)
        got = g.collective_census(jx)

        a2a_n, a2a_b = got.pop("all_to_all", (0, 0))
        gat_n, gat_b = got.pop("all_gather", (0, 0))
        psum_n, _psum_b = got.pop("psum", (0, 0))

        exp_a2a_b = (sum(want["legs"].values())
                     + want["meta_bytes"]["all_to_all"])
        exp_gat_b = want["meta_bytes"]["all_gather"]
        ok = True

        def flag(rule, detail):
            nonlocal ok
            ok = False
            out.append(Violation("census", rule, subject, detail))

        if a2a_n != want["a2a_eqns"]:
            flag("a2a-count",
                 f"traced {a2a_n} all_to_all eqns, model structure "
                 f"expects {want['a2a_eqns']} (stages x legs x chunks "
                 f"+ fp8 sidecars + metadata)")
        if abs(a2a_b - exp_a2a_b) > RTOL * max(exp_a2a_b, 1.0):
            flag("a2a-bytes",
                 f"traced {a2a_b:.0f} B of all_to_all operands, the "
                 f"planner/analysis models price {exp_a2a_b:.0f} B "
                 f"(x{want['bound_factor']:.0f} documented bound "
                 f"factor) — an unpriced or mispriced exchange")
        if gat_n != want["gather_eqns"]:
            flag("gather-count",
                 f"traced {gat_n} all_gather eqns, expected "
                 f"{want['gather_eqns']}")
        if abs(gat_b - exp_gat_b) > RTOL * max(exp_gat_b, 1.0):
            flag("gather-bytes",
                 f"traced {gat_b:.0f} B of all_gather operands, "
                 f"expected {exp_gat_b:.0f} B")
        if psum_n != want["psum_eqns"]:
            flag("psum-count",
                 f"traced {psum_n} psum eqns, the EP layer contract "
                 f"(parallel/ep.py EXPECTED_PSUMS) is "
                 f"{want['psum_eqns']}")
        for prim, (n, b) in sorted(got.items()):
            flag("unpriced-collective",
                 f"{n} {prim} eqn(s) moving {b} B appear in the graph "
                 f"but no pricing rule covers {prim} on this path")

        rows.append(CensusRow(
            name, path, wtag, ctag, a2a_n, float(a2a_b),
            float(exp_a2a_b), gat_n, psum_n,
            float(want["model_comm_bytes"]), float(want["bound_factor"]),
            ok))
    return out, rows


def report_table(rows) -> str:
    """Markdown rendering of the census rows (the CLI report)."""
    lines = [
        "| config | path | wire | chunks | a2a eqns | a2a MB (traced) "
        "| a2a MB (model) | bound | ok |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        if r.note.startswith("skipped"):
            lines.append(
                f"| {r.config} | {r.path} | {r.wire} | {r.chunks} | "
                f"- | - | - | - | {r.note} |")
            continue
        lines.append(
            f"| {r.config} | {r.path} | {r.wire} | {r.chunks} | "
            f"{r.a2a_eqns} | {r.a2a_bytes / 2**20:.2f} | "
            f"{r.expected_a2a_bytes / 2**20:.2f} | "
            f"x{r.bound_factor:.0f} | {'yes' if r.ok else 'NO'} |")
    return "\n".join(lines)
