"""Jaxpr-walking utilities shared by the invariant and census engines.

Everything here operates on traced (never executed) jaxprs, descending
into sub-jaxprs carried by eqn params (shard_map bodies, scan/cond
branches, custom-vjp closures), so a collective hidden three levels deep
in a pipeline chunk counts the same as one at top level —
``tests/test_observe.py`` pioneered the recursion; this module is its
generalization.
"""

from __future__ import annotations

from collections import Counter


def iter_eqns(jaxpr):
    """Yield every eqn of ``jaxpr`` and of every nested sub-jaxpr."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            vs = v if isinstance(v, (list, tuple)) else (v,)
            for item in vs:
                inner = getattr(item, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    yield from iter_eqns(inner)
                elif hasattr(item, "eqns"):
                    yield from iter_eqns(item)


def prim_counts(jaxpr) -> Counter:
    """Multiset of primitive names over the whole (nested) jaxpr."""
    return Counter(eqn.primitive.name for eqn in iter_eqns(jaxpr))


#: collective primitives the census accounts for — anything from this
#: set appearing in a graph must be explained by a pricing rule
COLLECTIVE_PRIMS = (
    "all_to_all", "ragged_all_to_all", "all_gather", "psum", "pmean",
    "ppermute", "psum_scatter", "reduce_scatter",
)


def _eqn_operand_bytes(eqn) -> int:
    """Total bytes of an eqn's array operands (the payload a collective
    moves; index/axis params are not operands)."""
    total = 0
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        if aval is not None and hasattr(aval, "size") and \
                hasattr(aval, "dtype"):
            total += int(aval.size) * aval.dtype.itemsize
    return total


def collective_census(jaxpr) -> dict:
    """``{prim_name: (count, operand_bytes)}`` over every collective in
    the (nested) jaxpr.  Bytes are the operand sizes — what one rank
    hands the transport, the same per-rank convention
    ``analysis.comm_census`` prices."""
    out: dict[str, tuple[int, int]] = {}
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name not in COLLECTIVE_PRIMS:
            continue
        c, b = out.get(name, (0, 0))
        out[name] = (c + 1, b + _eqn_operand_bytes(eqn))
    return out


def dtype_names(jaxpr) -> set:
    """Every aval dtype name appearing anywhere in the (nested) jaxpr —
    eqn inputs and outputs, so a cast *to* a dtype counts even when
    nothing reads the result."""
    names = set()
    for eqn in iter_eqns(jaxpr):
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            if aval is not None and hasattr(aval, "dtype"):
                names.add(aval.dtype.name)
    return names


def has_fp8(jaxpr) -> bool:
    """True when any float8 dtype appears in the graph (the
    wire-off => fp8-free invariant's subject)."""
    return any(n.startswith("float8") for n in dtype_names(jaxpr))


def jaxpr_text(jaxpr) -> str:
    """Canonical text rendering used for identity comparison.  Two
    configs that are equal frozen dataclasses share a jit cache entry by
    construction; comparing the *text* of independent traces additionally
    catches trace-time nondeterminism and Python branching leaks."""
    return str(jaxpr)
