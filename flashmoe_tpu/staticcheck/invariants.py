"""Jaxpr invariant engine: trace every registered (backend, knob)
combination under an abstract mesh and assert structural invariants —
nothing executes, no silicon needed.

One generic engine replaces the per-PR one-off assertions that used to
live in tests/test_wire.py (wire off => fp8-free), tests/test_chunked.py
(chunks None == serial), and tests/test_observe.py (stats off => no
extra collectives):

* **config identity** — every off value of a knob is either the
  dataclass default (an EQUAL frozen config: one jit cache entry, same
  executable, bit-identical by construction — the convention every knob
  PR asserted by hand) or traces to the byte-identical jaxpr (e.g.
  ``a2a_chunks=1`` vs ``None``);
* **graph predicates** — wire off => no float8 dtype anywhere in the
  graph; collect_stats / degrade on => no extra exchange collectives;
  degrade on => health ops added; chunked => the payload all_to_all
  count scales exactly with the chunk count;
* **tracer hygiene** — every on-config is hashable (stable ``jit``
  cache keys) and round-trips through ``replace``; tracing the same
  (config, backend) twice yields the identical jaxpr (no trace-time
  Python branching or nondeterminism leaking into the graph).

Traces use ``jax.eval_shape``-derived parameter shapes, so even
Mixtral-width configs cost kilobytes, not gigabytes.
"""

from __future__ import annotations

import functools

from flashmoe_tpu.staticcheck import graph as g
from flashmoe_tpu.staticcheck.registry import (
    BACKENDS, BACKENDS_BY_NAME, KNOBS, KNOBS_BY_NAME, Violation,
    check_knob_coverage,
)


def small_config(ep: int = 1, **over):
    """The invariant matrix's trace point: small enough that a full
    knob-matrix sweep stays well under the tier-1 budget, shaped so
    every engine feature (multi-expert routing, chunkable local-expert
    axis, dropless ragged layout) is exercised.  f32 keeps the fp8-free
    predicate meaningful on CPU."""
    import jax.numpy as jnp

    from flashmoe_tpu.config import MoEConfig

    base = dict(num_experts=8, expert_top_k=2, hidden_size=64,
                intermediate_size=128, sequence_len=64 * max(ep, 1),
                drop_tokens=False, ep=ep,
                dtype=jnp.float32, param_dtype=jnp.float32)
    base.update(over)
    return MoEConfig(**base)


@functools.lru_cache(maxsize=None)
def _abstract_inputs(cfg):
    """(param ShapeDtypeStructs, token ShapeDtypeStruct) — abstract, no
    allocation (cached: the engine traces many knob points of the same
    shape)."""
    import jax

    from flashmoe_tpu.models.reference import init_moe_params

    params = jax.eval_shape(
        lambda k: init_moe_params(k, cfg), jax.random.PRNGKey(0))
    x = jax.ShapeDtypeStruct((cfg.tokens, cfg.hidden_size), cfg.dtype)
    return params, x


def trace_backend(backend: str, cfg, devices=None, *,
                  dcn_inner: int | None = None):
    """Trace one (backend, config) point to a closed jaxpr.

    ``backend`` is a :data:`~flashmoe_tpu.staticcheck.registry.BACKENDS`
    name; ``devices`` default to ``jax.devices()`` (the CLI forces an
    8-way virtual CPU mesh, the test suite inherits conftest's).
    ``dcn_inner`` overrides the hierarchical blocking (census use)."""
    import jax

    spec = BACKENDS_BY_NAME[backend]
    params, x = _abstract_inputs(cfg)
    if backend == "local":
        from flashmoe_tpu.ops.moe import moe_layer

        return jax.make_jaxpr(
            lambda p, xx: moe_layer(p, xx, cfg, use_pallas=False).out
        )(params, x)

    from flashmoe_tpu.parallel.mesh import make_mesh

    devices = list(devices if devices is not None else jax.devices())
    width = max(spec.ep, cfg.ep)  # census traces golden configs at d=8
    if len(devices) < width:
        raise RuntimeError(
            f"staticcheck needs >= {width} devices to trace "
            f"{backend!r}; run via `python -m flashmoe_tpu.staticcheck` "
            f"(which forces a virtual 8-device CPU mesh) or set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8")
    mesh = make_mesh(cfg, dp=1, devices=devices[:width])
    inner = dcn_inner if dcn_inner is not None else spec.dcn_inner
    if backend in ("collective", "hierarchical"):
        from flashmoe_tpu.parallel.ep import ep_moe_layer

        return jax.make_jaxpr(
            lambda p, xx: ep_moe_layer(
                p, xx, cfg, mesh, dcn_inner=(inner or 0)).out
        )(params, x)
    if backend == "ragged":
        from flashmoe_tpu.parallel.ragged_ep import ragged_ep_moe_layer

        return jax.make_jaxpr(
            lambda p, xx: ragged_ep_moe_layer(
                p, xx, cfg, mesh, exchange="dense").out
        )(params, x)
    raise ValueError(f"unknown backend {backend!r}")


def _exchange_count(jaxpr) -> int:
    """Data-exchange collectives (the ones a knob must never add)."""
    pc = g.prim_counts(jaxpr)
    return (pc.get("all_to_all", 0) + pc.get("ragged_all_to_all", 0)
            + pc.get("ppermute", 0) + pc.get("all_gather", 0))


# ----------------------------------------------------------------------
# Named predicates (KnobSpec.off_rules / on_rules reference these)
# ----------------------------------------------------------------------

def _pred_fp8_free(base, on, ctx):
    # off-rule: runs on the BASELINE trace of each backend — the
    # generalized "wire off => no f8 anywhere" assertion
    if g.has_fp8(base):
        bad = sorted(n for n in g.dtype_names(base)
                     if n.startswith("float8"))
        return (f"knob off but the graph carries fp8 dtypes {bad} — "
                f"compression is leaking outside the wire codec")
    return None


def _pred_fp8_present(base, on, ctx):
    # on-rule sanity: proves the off-rule has teeth on this backend
    if not g.has_fp8(on):
        return "fp8 wire enabled but no float8 dtype in the graph"
    return None


def _pred_no_extra_exchange(base, on, ctx):
    nb, no = _exchange_count(base), _exchange_count(on)
    if no != nb:
        return (f"exchange-collective count changed {nb} -> {no}; this "
                f"knob must never add (or drop) an exchange")
    return None


def _pred_health_ops_added(base, on, ctx):
    pb = g.prim_counts(base).get("is_finite", 0)
    po = g.prim_counts(on).get("is_finite", 0)
    if po <= pb:
        return (f"degrade on but is_finite count did not grow "
                f"({pb} -> {po}); the health mask is not in the graph")
    return None


def _pred_quant_ops_present(base, on, ctx):
    # on-rule teeth check for expert_quant: the dequant-in-compute (or
    # in-graph fake-quant) arithmetic must put int8 weight dtypes into
    # the traced graph — a quant knob that changes nothing is dead
    if "int8" not in g.dtype_names(on):
        return ("expert_quant='int8' enabled but no int8 dtype in the "
                "graph — the store is not reaching the expert FFN")
    if "int8" in g.dtype_names(base):
        return ("baseline (quant off) graph already carries int8 "
                "dtypes — quantization is leaking outside the "
                "expert_quant gate")
    return None


def _pred_chunked_a2a_count(base, on, ctx):
    from flashmoe_tpu.ops import wire as wr

    spec: object = ctx["backend_spec"]
    chunks = ctx["on_cfg"].a2a_chunks or 1
    fp8_legs = sum(1 for wd in (ctx["on_cfg"].wire_dtype,
                                ctx["on_cfg"].wire_dtype_combine)
                   if wr.is_fp8(wr.resolve(wd)))
    want = spec.stages * (2 + fp8_legs) * chunks + spec.meta_a2a_chunked
    got = g.prim_counts(on).get("all_to_all", 0)
    if got != want:
        return (f"chunked pipeline at n={chunks}: expected {want} "
                f"all_to_all eqns (stages={spec.stages} x legs x n + "
                f"meta={spec.meta_a2a_chunked}), traced {got}")
    return None


_PREDICATES = {
    "fp8_free": _pred_fp8_free,
    "fp8_present": _pred_fp8_present,
    "no_extra_exchange": _pred_no_extra_exchange,
    "health_ops_added": _pred_health_ops_added,
    "chunked_a2a_count": _pred_chunked_a2a_count,
    "quant_ops_present": _pred_quant_ops_present,
}


# ----------------------------------------------------------------------
# Engine
# ----------------------------------------------------------------------

def run_invariants(knobs=None, backends=None, devices=None,
                   include_coverage: bool = True) -> list[Violation]:
    """Run the (backend x knob) invariant matrix.  ``knobs`` /
    ``backends`` restrict to named subsets (tests plant violations on a
    single cell); default is the full registered matrix.  Returns the
    violations (empty = safe)."""
    import dataclasses as dc

    from flashmoe_tpu.config import MoEConfig

    out: list[Violation] = []
    if include_coverage:
        out.extend(check_knob_coverage())
    knob_specs = [KNOBS_BY_NAME[k] for k in knobs] if knobs else KNOBS
    if backends:
        backend_specs = [BACKENDS_BY_NAME[b] for b in backends]
    else:
        # only trace baselines a requested knob will actually compare
        # against (a wire-only run never needs the 'local' trace)
        needed = {b for k in knob_specs for b in k.backends}
        backend_specs = [b for b in BACKENDS if b.name in needed]
    wanted = {b.name for b in backend_specs}

    defaults = {f.name: f.default for f in dc.fields(MoEConfig)}

    # --- baselines: one trace per backend, re-traced for determinism --
    base_jaxprs: dict[str, object] = {}
    base_cfgs: dict[str, object] = {}
    for spec in backend_specs:
        cfg = small_config(ep=spec.ep)
        base_cfgs[spec.name] = cfg
        jx = trace_backend(spec.name, cfg, devices)
        base_jaxprs[spec.name] = jx
        jx2 = trace_backend(spec.name, cfg, devices)
        if g.jaxpr_text(jx) != g.jaxpr_text(jx2):
            out.append(Violation(
                "invariants", "trace-determinism", spec.name,
                "tracing the identical (config, backend) twice yielded "
                "different jaxprs — trace-time nondeterminism (host "
                "randomness / time / mutable global) is leaking into "
                "the graph"))

    for knob in knob_specs:
        # ---- config identity + hashability (backend-independent) -----
        if knob.off_values[0] != defaults[knob.name]:
            out.append(Violation(
                "invariants", "off-default", knob.name,
                f"registered off value {knob.off_values[0]!r} is not "
                f"the dataclass default {defaults[knob.name]!r}"))
        probe = small_config(ep=1)
        if probe.replace(**{knob.name: knob.off_values[0]}) != probe or \
                hash(probe.replace(**{knob.name: knob.off_values[0]})) \
                != hash(probe):
            out.append(Violation(
                "invariants", "config-identity", knob.name,
                "replace(knob=off) is not an equal/equal-hash frozen "
                "config — off no longer shares the baseline jit cache "
                "entry, so bit-identity-by-construction is broken"))
        try:
            on_probe = probe.replace(**knob.on)
            hash(on_probe)
            if on_probe.replace() != on_probe:
                raise ValueError("replace() round-trip changed the config")
        except (TypeError, ValueError) as e:
            out.append(Violation(
                "invariants", "static-hygiene", knob.name,
                f"on-config is not a stable jit static arg: {e}"))
            continue

        # ---- per-backend traces --------------------------------------
        for bname in knob.backends:
            if bname not in wanted:
                continue
            spec = BACKENDS_BY_NAME[bname]
            base_cfg = base_cfgs[bname]
            base = base_jaxprs[bname]

            # off values beyond the default must trace IDENTICALLY
            for off in knob.off_values[1:]:
                jx = trace_backend(
                    bname, base_cfg.replace(**{knob.name: off}), devices)
                if g.jaxpr_text(jx) != g.jaxpr_text(base):
                    out.append(Violation(
                        "invariants", "off-identity",
                        f"{bname}.{knob.name}={off!r}",
                        "off-equivalent value traces to a DIFFERENT "
                        "jaxpr than the default — Python branching on "
                        "the knob leaks into the off graph"))

            ctx = {"backend_spec": spec, "base_cfg": base_cfg}
            for rule in knob.off_rules:
                detail = _PREDICATES[rule](base, None, ctx)
                if detail:
                    out.append(Violation(
                        "invariants", rule,
                        f"{bname}.{knob.name}=off", detail))

            try:
                on_cfg = base_cfg.replace(**knob.on)
            except ValueError as e:
                out.append(Violation(
                    "invariants", "on-trace", f"{bname}.{knob.name}",
                    f"canonical on point rejected at config time: {e}"))
                continue
            on = trace_backend(bname, on_cfg, devices)
            ctx["on_cfg"] = on_cfg
            changed = g.jaxpr_text(on) != g.jaxpr_text(base)
            if knob.changes_graph and not changed:
                out.append(Violation(
                    "invariants", "on-changes-graph",
                    f"{bname}.{knob.name}",
                    "enabling the knob left the jaxpr identical — the "
                    "knob is dead on this backend (or the trace ignores "
                    "it)"))
            if not knob.changes_graph and changed:
                out.append(Violation(
                    "invariants", "on-changes-graph",
                    f"{bname}.{knob.name}",
                    "knob is declared graph-neutral here but the jaxpr "
                    "changed"))
            for rule in knob.on_rules:
                detail = _PREDICATES[rule](base, on, ctx)
                if detail:
                    out.append(Violation(
                        "invariants", rule,
                        f"{bname}.{knob.name}=on", detail))
    return out
