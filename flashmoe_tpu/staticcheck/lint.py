"""AST lint pass over ``flashmoe_tpu/`` and ``tests/``.

Five rule families, all pure AST — no imports of the heavy modules, no
pytest-in-pytest:

* **in-graph hygiene** — functions that end up inside a trace (bodies
  handed to ``shard_map`` / ``jit`` / ``lax.scan`` / ``pallas_call`` /
  ..., transitively through calls and ``functools.partial``) must not
  call host-time APIs (``time.time``, ``np.random``, ``random.*``, ...)
  whose results would be frozen into the compiled graph, and must not
  branch Python-``if``/``while`` on ``jnp.*`` expressions (tracer
  leakage — the branch would specialize on one traced value).  A line
  may opt out with a ``# staticcheck: ok`` comment plus a reason.
* **decision-name registry** — every literal passed to
  ``metrics.decision("x.y", ...)`` / ``last_decision("x.y")`` must be
  declared in ``utils/telemetry.py:DECISION_NAMES``; a typo'd name used
  to vanish silently into JSONL.  Non-literal names are flagged too:
  the registry cannot vouch for a name it cannot see.
* **span-name registry** — the same contract for phase spans: every
  literal handed to ``trace_span(...)`` / a profiler ``section(...)``
  must be declared in ``utils/telemetry.py:SPAN_NAMES`` (chunked
  pipeline f-strings must start with a registered base + ``.``) — a
  typo'd span silently forks the phase timeline the cost ledger joins.
* **doc sync** — every registered decision name must appear in
  docs/OBSERVABILITY.md, and every name in that doc's decision table
  must be registered (the table is generated from the registry:
  ``telemetry.decision_table_markdown``); span names likewise
  (``telemetry.span_table_markdown``).
* **slow-mark budget guard** — migrated from tests/test_collection.py
  (which now thinly wraps this engine): tests that run chaos drills
  (any test file) or execute shard_map MoE layers (files listed in
  ``SHARD_MAP_EXEC_FILES``; ``jax.make_jaxpr`` tracing is exempt — it
  is exactly what this package does) must carry ``@pytest.mark.slow``
  so the tier-1 gate stays inside its 870s budget (ROADMAP.md).
"""

from __future__ import annotations

import ast
import os
import re

from flashmoe_tpu.staticcheck.registry import Violation

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
PKG_DIR = os.path.join(REPO_ROOT, "flashmoe_tpu")
TESTS_DIR = os.path.join(REPO_ROOT, "tests")
OBS_DOC = os.path.join(REPO_ROOT, "docs", "OBSERVABILITY.md")

#: suppression marker: a line carrying this comment (with a reason) is
#: exempt from the in-graph rules
WAIVER = "# staticcheck: ok"

# ---------------------------------------------------------------------
# slow-mark rule (migrated from tests/test_collection.py)
# ---------------------------------------------------------------------

#: calls that make a test a chaos DRILL (a full resilient training job)
DRILL_CALLS = frozenset({"run_drill", "run_matrix"})

#: calls that EXECUTE a shard_map'd MoE layer on the virtual mesh
#: (jax.make_jaxpr over the same layer is trace-only and stays fast)
SHARD_MAP_CALLS = frozenset({"ep_moe_layer", "ragged_ep_moe_layer",
                             "fused_ep_moe_layer"})

#: files the shard_map-execution rule applies to (drills apply
#: everywhere).  Other test files budget their executions individually;
#: add a file here to opt it into the strict rule.
SHARD_MAP_EXEC_FILES = ("test_chaos.py",)

#: wrappers whose function arguments end up inside a trace
_TRACE_WRAPPERS = frozenset({
    "shard_map", "jit", "pallas_call", "scan", "cond", "switch",
    "while_loop", "fori_loop", "vmap", "pmap", "grad",
    "value_and_grad", "checkpoint", "remat", "custom_vjp",
    "custom_jvp", "make_jaxpr", "eval_shape",
})

#: dotted call names whose values must never be baked into a traced
#: graph (host wall-clock / host randomness)
_FORBIDDEN_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time",
    "np.random", "numpy.random",
    "random.random", "random.randint", "random.uniform",
    "random.choice", "random.sample", "random.shuffle",
    "random.gauss",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "os.urandom", "secrets.token_bytes", "secrets.randbits",
    "uuid.uuid4",
}

#: roots whose calls inside an ``if``/``while`` test mean Python is
#: branching on a tracer
_TRACER_ROOTS = ("jnp.", "jax.numpy.")


def _dotted(node) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _called_names(node: ast.AST) -> set:
    names = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Name):
                names.add(f.id)
            elif isinstance(f, ast.Attribute):
                names.add(f.attr)
    return names


def _is_slow_marked(fn) -> bool:
    return any("mark.slow" in ast.unparse(dec)
               for dec in fn.decorator_list)


def _test_functions(tree):
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and \
                node.name.startswith("test_"):
            yield node


def _parse(path: str):
    with open(path) as f:
        src = f.read()
    return ast.parse(src, filename=path), src.splitlines()


def _iter_py(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        # sort the traversal in place: os.walk yields subdirectories
        # in FILESYSTEM order, and the in-graph rule's bare-name index
        # resolves duplicate function names to the first file seen —
        # an unsorted walk made the lint verdict depend on checkout
        # inode order (found when a fresh container flagged a chain a
        # dev tree never built)
        dirnames.sort()
        if "__pycache__" in dirpath:
            continue
        for name in sorted(filenames):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def check_slow_marks(test_files=None) -> list[Violation]:
    """The tier-1 budget guard, generalized: drills anywhere, shard_map
    executions in the strict files."""
    out = []
    if test_files is None:
        test_files = [os.path.join(TESTS_DIR, n)
                      for n in sorted(os.listdir(TESTS_DIR))
                      if n.startswith("test_") and n.endswith(".py")]
    for path in test_files:
        name = os.path.basename(path)
        tree, _src = _parse(path)
        strict = name in SHARD_MAP_EXEC_FILES
        for fn in _test_functions(tree):
            called = _called_names(fn)
            if called & DRILL_CALLS and not _is_slow_marked(fn):
                out.append(Violation(
                    "lint", "slow-mark", f"{name}::{fn.name}",
                    "runs a chaos drill (a full resilient training "
                    "job) without @pytest.mark.slow — drills belong "
                    "outside the fast gate (ROADMAP.md tier-1 budget)"))
            if strict and called & SHARD_MAP_CALLS \
                    and "make_jaxpr" not in called \
                    and not _is_slow_marked(fn):
                out.append(Violation(
                    "lint", "slow-mark", f"{name}::{fn.name}",
                    "executes a shard_map MoE layer without "
                    "@pytest.mark.slow (jax.make_jaxpr tracing is the "
                    "fast-lane alternative)"))
    return out


def slow_mark_selfcheck() -> list[Violation]:
    """The scan must actually FIND the known drill/execution tests —
    an empty scan would make the guard vacuously green."""
    path = os.path.join(TESTS_DIR, "test_chaos.py")
    if not os.path.exists(path):
        return [Violation("lint", "slow-mark-selfcheck", "test_chaos.py",
                          "known drill file is missing")]
    tree, _src = _parse(path)
    drills, execs = [], []
    for fn in _test_functions(tree):
        called = _called_names(fn)
        if called & DRILL_CALLS:
            drills.append(fn.name)
        if called & SHARD_MAP_CALLS and "make_jaxpr" not in called:
            execs.append(fn.name)
    out = []
    if "test_drill_matrix" not in drills:
        out.append(Violation(
            "lint", "slow-mark-selfcheck", "test_chaos.py",
            f"drill scan no longer sees test_drill_matrix ({drills})"))
    if not execs:
        out.append(Violation(
            "lint", "slow-mark-selfcheck", "test_chaos.py",
            "shard_map-execution scan found nothing — rule is vacuous"))
    return out


# ---------------------------------------------------------------------
# decision-name registry rule
# ---------------------------------------------------------------------

def check_decision_names(files=None) -> list[Violation]:
    from flashmoe_tpu.utils.telemetry import DECISION_NAMES

    out = []
    if files is None:
        # tests included: a typo'd name in `last_decision("preempt.drian")`
        # makes the test silently assert against None — the same
        # vanish-into-JSONL failure this rule closes in the package
        files = list(_iter_py(PKG_DIR)) + list(_iter_py(TESTS_DIR))
    for path in files:
        rel = os.path.relpath(path, REPO_ROOT)
        tree, lines = _parse(path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            attr = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if attr not in ("decision", "last_decision"):
                continue
            if not node.args:
                continue
            # skip the registry's own definition site and methods on
            # unrelated objects taking non-name first args
            arg = node.args[0]
            line = lines[node.lineno - 1] if node.lineno <= len(
                lines) else ""
            if WAIVER in line:
                continue
            if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                            str):
                if arg.value not in DECISION_NAMES:
                    out.append(Violation(
                        "lint", "decision-name",
                        f"{rel}:{node.lineno}",
                        f"decision name {arg.value!r} is not declared "
                        f"in utils/telemetry.py:DECISION_NAMES — typo'd "
                        f"names vanish silently into JSONL; register "
                        f"it (with a one-line meaning), fix the "
                        f"spelling, or waive with "
                        f"'{WAIVER} <reason>'"))
            elif attr == "decision" and not (
                    isinstance(arg, ast.Name) and arg.id == "self"):
                out.append(Violation(
                    "lint", "decision-name", f"{rel}:{node.lineno}",
                    "non-literal decision name: the registry "
                    "cannot vouch for a computed name — pass a "
                    "registered literal (or waive with "
                    "'# staticcheck: ok <reason>')"))
    return out


# ---------------------------------------------------------------------
# span-name registry rule
# ---------------------------------------------------------------------

def _span_base(name: str) -> str:
    """Chunked pipeline spans carry a numeric suffix
    (``moe.expert.3``) — merge onto the registered base.  Delegates to
    :func:`flashmoe_tpu.profiler.spans.merged_phase` so the lint and
    the timeline can never disagree on the suffix convention."""
    from flashmoe_tpu.profiler.spans import merged_phase

    return merged_phase(name)


def check_span_names(files=None) -> list[Violation]:
    """Every literal handed to ``trace_span(...)`` or a profiler
    ``section(...)`` must be declared in
    ``utils/telemetry.py:SPAN_NAMES`` — a misspelled span silently
    forks the phase timeline the cost ledger joins on.  F-string spans
    (the chunked pipeline's ``f"moe.expert.{ck}"``) must start with a
    registered base followed by ``.``; a wholly computed name on
    ``trace_span`` is flagged (waivable) because the registry cannot
    vouch for it.  Non-literal ``section`` calls — plain variables and
    f-strings without a registered literal base — are skipped: the
    name is too generic to attribute (the profiler's own dispatcher
    forwards a variable)."""
    from flashmoe_tpu.utils.telemetry import SPAN_NAMES

    out = []
    if files is None:
        files = list(_iter_py(PKG_DIR)) + list(_iter_py(TESTS_DIR))

    def unregistered(rel, lineno, name):
        out.append(Violation(
            "lint", "span-name", f"{rel}:{lineno}",
            f"span name {name!r} is not declared in "
            f"utils/telemetry.py:SPAN_NAMES — a typo'd span forks the "
            f"phase timeline; register it (with a one-line meaning), "
            f"fix the spelling, or waive with '{WAIVER} <reason>'"))

    for path in files:
        rel = os.path.relpath(path, REPO_ROOT)
        tree, lines = _parse(path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            f = node.func
            attr = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if attr not in ("trace_span", "section"):
                continue
            line = lines[node.lineno - 1] if node.lineno <= len(
                lines) else ""
            if WAIVER in line:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                            str):
                if _span_base(arg.value) not in SPAN_NAMES:
                    unregistered(rel, node.lineno, arg.value)
            elif isinstance(arg, ast.JoinedStr):
                head = arg.values[0] if arg.values else None
                if isinstance(head, ast.Constant) and isinstance(
                        head.value, str) and head.value.endswith("."):
                    if head.value[:-1] not in SPAN_NAMES:
                        unregistered(rel, node.lineno, head.value + "*")
                elif attr == "trace_span":
                    # section() f-strings without a literal base are
                    # skipped like other non-literal section names —
                    # the documented contract only binds trace_span
                    out.append(Violation(
                        "lint", "span-name", f"{rel}:{node.lineno}",
                        "f-string span must start with a registered "
                        "base name followed by '.' (chunk-suffix "
                        "convention) — the registry cannot vouch for "
                        "a computed prefix"))
            elif attr == "trace_span":
                out.append(Violation(
                    "lint", "span-name", f"{rel}:{node.lineno}",
                    "non-literal span name: the registry cannot vouch "
                    "for a computed name — pass a registered literal "
                    f"(or waive with '{WAIVER} <reason>')"))
    return out


def check_span_doc_sync() -> list[Violation]:
    """Every registered span name must appear in docs/OBSERVABILITY.md
    (the span table is generated from the registry:
    ``telemetry.span_table_markdown``)."""
    from flashmoe_tpu.utils.telemetry import SPAN_NAMES

    if not os.path.exists(OBS_DOC):
        return [Violation("lint", "span-doc", "docs/OBSERVABILITY.md",
                          "document is missing")]
    with open(OBS_DOC) as f:
        doc = f.read()
    out = []
    for name in sorted(SPAN_NAMES):
        if f"`{name}`" not in doc:
            out.append(Violation(
                "lint", "span-doc", name,
                "registered span name is absent from "
                "docs/OBSERVABILITY.md — regenerate the table with "
                "telemetry.span_table_markdown()"))
    return out


def check_decision_doc_sync() -> list[Violation]:
    from flashmoe_tpu.utils.telemetry import DECISION_NAMES, SPAN_NAMES

    out = []
    if not os.path.exists(OBS_DOC):
        return [Violation("lint", "decision-doc", "docs/OBSERVABILITY.md",
                          "document is missing")]
    with open(OBS_DOC) as f:
        doc = f.read()
    for name in sorted(DECISION_NAMES):
        if f"`{name}`" not in doc:
            out.append(Violation(
                "lint", "decision-doc", name,
                "registered decision name is absent from "
                "docs/OBSERVABILITY.md — regenerate the table with "
                "telemetry.decision_table_markdown()"))
    for name in re.findall(r"^\| `([a-z_]+\.[a-z_.]+)` \|", doc,
                           re.MULTILINE):
        # dotted table rows are either decisions or spans (the span
        # table of the phase profiler shares the doc)
        if name not in DECISION_NAMES and name not in SPAN_NAMES:
            out.append(Violation(
                "lint", "decision-doc", name,
                "documented dotted name is registered neither in "
                "DECISION_NAMES nor SPAN_NAMES (stale doc row?)"))
    return out


# ---------------------------------------------------------------------
# in-graph hygiene rule
# ---------------------------------------------------------------------

def _module_functions(tree) -> dict:
    """name -> FunctionDef for module-level and one-level-nested defs."""
    fns = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns.setdefault(node.name, node)
    return fns


def _seed_traced(tree, fns) -> set:
    """Names of functions this module hands to trace wrappers —
    directly, or through a ``functools.partial`` binding."""
    partial_of: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            callee = _dotted(node.value.func) or ""
            if callee.endswith("partial") and node.value.args and \
                    isinstance(node.value.args[0], ast.Name):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        partial_of[tgt.id] = node.value.args[0].id
    seeds = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func) or ""
        if callee.split(".")[-1] not in _TRACE_WRAPPERS:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name):
                seeds.add(partial_of.get(arg.id, arg.id))
            elif isinstance(arg, ast.Call):
                inner = _dotted(arg.func) or ""
                if inner.endswith("partial") and arg.args and \
                        isinstance(arg.args[0], ast.Name):
                    seeds.add(arg.args[0].id)
    return {s for s in seeds if s in fns}


def check_in_graph(files=None) -> list[Violation]:
    """Forbidden host-side patterns inside (transitively) traced
    functions."""
    out = []
    if files is None:
        files = list(_iter_py(PKG_DIR))
    # global index: function name -> (rel, FunctionDef, lines), for
    # cross-module transitive closure (unique last-segment resolution —
    # a lint, not a type checker)
    index: dict[str, tuple[str, ast.AST, list]] = {}
    per_file = []
    for path in files:
        rel = os.path.relpath(path, REPO_ROOT)
        tree, lines = _parse(path)
        fns = _module_functions(tree)
        per_file.append((rel, tree, fns, lines))
        for name, fn in fns.items():
            index.setdefault(name, (rel, fn, lines))

    # BFS from every module's seeds through the call graph
    queue = []
    visited = set()
    for rel, tree, fns, lines in per_file:
        for s in _seed_traced(tree, fns):
            key = (rel, s)
            if key not in visited:
                visited.add(key)
                queue.append((rel, fns[s], lines))
    while queue:
        rel, fn, lines = queue.pop()
        out.extend(_scan_traced_fn(rel, fn, lines))
        for called in sorted(_called_names(fn)):
            if called in index:
                crel, cfn, clines = index[called]
                key = (crel, cfn.name)
                if key not in visited:
                    visited.add(key)
                    queue.append((crel, cfn, clines))
    return out


def _scan_traced_fn(rel, fn, lines) -> list[Violation]:
    out = []

    def waived(node) -> bool:
        i = node.lineno - 1
        return i < len(lines) and WAIVER in lines[i]

    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            callee = _dotted(node.func)
            if callee in _FORBIDDEN_CALLS or (
                    callee and (callee.startswith("np.random.")
                                or callee.startswith("numpy.random."))):
                if not waived(node):
                    out.append(Violation(
                        "lint", "in-graph-host-call",
                        f"{rel}:{node.lineno} ({fn.name})",
                        f"{callee}() inside traced code: the host "
                        f"value would be frozen into the compiled "
                        f"graph (and differ across ranks/restarts) — "
                        f"pass it in as an argument, or waive with "
                        f"'{WAIVER} <reason>'"))
        elif isinstance(node, (ast.If, ast.While)):
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Call):
                    callee = _dotted(sub.func) or ""
                    if any(callee.startswith(r) for r in _TRACER_ROOTS):
                        if not waived(node):
                            out.append(Violation(
                                "lint", "tracer-branch",
                                f"{rel}:{node.lineno} ({fn.name})",
                                f"Python {type(node).__name__.lower()} "
                                f"on {callee}(...): branching on a "
                                f"tracer value freezes one branch into "
                                f"the graph (or raises a "
                                f"ConcretizationError) — use jnp.where "
                                f"/ lax.cond"))
                        break
    return out


# ---------------------------------------------------------------------
# engine entry
# ---------------------------------------------------------------------

def run_lint(paths=None) -> list[Violation]:
    """Run every lint rule.  ``paths`` restricts the decision-name and
    in-graph rules to an explicit file list (tests plant violations in
    tmp files); the slow-mark and doc-sync rules always run on the
    repo unless ``paths`` is given."""
    out: list[Violation] = []
    if paths is not None:
        files = [os.path.abspath(p) for p in paths]
        out.extend(check_decision_names(files))
        out.extend(check_span_names(files))
        out.extend(check_in_graph(files))
        return out
    out.extend(check_slow_marks())
    out.extend(slow_mark_selfcheck())
    out.extend(check_decision_names())
    out.extend(check_decision_doc_sync())
    out.extend(check_span_names())
    out.extend(check_span_doc_sync())
    out.extend(check_in_graph())
    return out
