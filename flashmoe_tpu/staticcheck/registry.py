"""Declarative registry: which knobs exist, what each guarantees, and
which backends the invariant engine traces them on.

Adding a knob to :class:`~flashmoe_tpu.config.MoEConfig` REQUIRES adding
a row here (or classifying the field as structural) — the matrix-
coverage check (:func:`check_knob_coverage`, CI-gated by
``tests/test_staticcheck.py``) fails otherwise, so a PR 8+ knob (serving
paths, row-windowed fused, ...) gets invariant coverage by adding one
table row, not by writing another one-off jaxpr assertion.
"""

from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass(frozen=True)
class Violation:
    """One static-analysis finding.  ``engine`` is the subsystem that
    found it (invariants / census / lint), ``rule`` the check that
    fired, ``subject`` what it fired on (a knob, a config point, a
    file:line), ``detail`` the human-readable explanation."""

    engine: str
    rule: str
    subject: str
    detail: str

    def __str__(self) -> str:
        return f"[{self.engine}:{self.rule}] {self.subject}: {self.detail}"


# ----------------------------------------------------------------------
# Backends the invariant engine traces
# ----------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class BackendSpec:
    """One traceable MoE execution path.

    ``ep``: mesh width the trace needs (1 = single-chip layer);
    ``dcn_inner``: two-stage exchange blocking (hierarchical only);
    ``stages``: all_to_all hops per exchange leg (flat 1, hierarchical
    2 — each stage moves the full local buffer, the staging cost
    ``analysis.comm_census`` documents); ``meta_a2a_serial`` /
    ``meta_a2a_chunked``: metadata all_to_alls beyond the payload legs
    (the ragged layer's count-matrix exchange); ``meta_gather_*``: the
    same for all_gather."""

    name: str
    ep: int = 2
    dcn_inner: int | None = None
    stages: int = 1
    meta_a2a_serial: int = 0
    meta_a2a_chunked: int = 0
    meta_gather_serial: int = 0
    meta_gather_chunked: int = 0


BACKENDS: tuple[BackendSpec, ...] = (
    # single-chip dispatch (ops/moe.py) — no exchange, XLA oracle path
    BackendSpec("local", ep=1),
    # flat XLA all-to-all EP (parallel/ep.py)
    BackendSpec("collective", ep=2),
    # two-stage ICI+DCN exchange (parallel/ep.py _hierarchical_a2a)
    BackendSpec("hierarchical", ep=4, dcn_inner=2, stages=2),
    # dropless ragged EP, dense fallback arm (parallel/ragged_ep.py):
    # serial trades one [D,D] size gather + one count-matrix a2a;
    # chunked derives everything from one [D, D, nLx] gather
    BackendSpec("ragged", ep=2, meta_a2a_serial=1, meta_gather_serial=1,
                meta_gather_chunked=1),
)

BACKENDS_BY_NAME = {b.name: b for b in BACKENDS}


# ----------------------------------------------------------------------
# Knobs and their invariants
# ----------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class KnobSpec:
    """One behavior knob of :class:`MoEConfig` and its guarantees.

    ``off_values``: every value equivalent to "off" — the first must be
    the dataclass default (config-identity check: ``replace`` with it is
    an EQUAL frozen dataclass, one jit cache entry, bit-identical by
    construction); every further value must trace to the IDENTICAL
    jaxpr (e.g. ``a2a_chunks=1`` is the serial schedule, ``gather_fused
    =False`` the env-off default).  ``on``: the canonical enabled point
    the on-trace uses.  ``off_rules`` / ``on_rules``: named predicates
    (:mod:`flashmoe_tpu.staticcheck.invariants`) run on the baseline
    trace / the on-trace vs baseline.  ``changes_graph``: whether the
    on point must alter the traced graph at all (``gather_fused`` is a
    kernel-entry selector that leaves the XLA oracle path untouched)."""

    name: str
    off_values: tuple
    on: Any  # mapping of config overrides for the canonical on point
    backends: tuple = ("local", "collective", "hierarchical", "ragged")
    changes_graph: bool = True
    off_rules: tuple = ()
    on_rules: tuple = ()
    doc: str = ""


KNOBS: tuple[KnobSpec, ...] = (
    KnobSpec(
        "wire_dtype", off_values=(None,), on={"wire_dtype": "e4m3"},
        backends=("collective", "hierarchical", "ragged"),
        off_rules=("fp8_free",), on_rules=("fp8_present",),
        doc="EP dispatch-leg payload compression (ops/wire.py); off = "
            "bit-identical, fp8-free graph"),
    KnobSpec(
        "wire_dtype_combine", off_values=(None,),
        on={"wire_dtype_combine": "e5m2"},
        backends=("collective", "hierarchical", "ragged"),
        off_rules=("fp8_free",), on_rules=("fp8_present",),
        doc="EP combine-leg payload compression; off = bit-identical, "
            "fp8-free graph"),
    KnobSpec(
        "wire_dtype_dcn", off_values=(None,),
        on={"wire_dtype_dcn": "e4m3"},
        backends=("hierarchical",),
        off_rules=("fp8_free",), on_rules=("fp8_present",),
        doc="per-hop wire for the CROSS-SLICE (DCN) stage of the "
            "two-stage exchange (parallel/ep.py _wired_exchange): set, "
            "both legs re-encode their DCN hop at this dtype while the "
            "ICI hop keeps the leg wire; None inherits the leg wire — "
            "graph-identical to the single-dtype build.  Hierarchical "
            "backend only: the flat transports have no DCN hop, so the "
            "knob is inert (= off graph) there, which the census's "
            "flat rows double-check"),
    KnobSpec(
        "a2a_chunks", off_values=(None, 1), on={"a2a_chunks": 2},
        backends=("collective", "hierarchical", "ragged"),
        on_rules=("chunked_a2a_count",),
        doc="chunked double-buffered EP pipeline; None and 1 are both "
            "the serial schedule (identical jaxpr)"),
    KnobSpec(
        "collect_stats", off_values=(False,), on={"collect_stats": True},
        on_rules=("no_extra_exchange",),
        doc="in-graph MoEStats; off = bit-identical, on adds reductions "
            "but never an exchange"),
    KnobSpec(
        "degrade_unhealthy_experts", off_values=(False,),
        on={"degrade_unhealthy_experts": True},
        on_rules=("health_ops_added", "no_extra_exchange"),
        doc="tier-0 expert-health masking; off = bit-identical (no "
            "extra is_finite beyond the router's logsumexp), on is "
            "jnp.where-only — no collectives"),
    KnobSpec(
        "expert_replicas", off_values=((),),
        on={"expert_replicas": ((0, 1),)},
        on_rules=("no_extra_exchange",),
        doc="hot-expert replica routing map ((hot, slot), ...) written "
            "by the self-healing controller's re-placement action "
            "(runtime/controller.py): tokens routed to `hot` split "
            "across the two value-identical physical slots after top-k "
            "(ops/gate.py apply_replicas) — jnp.where-only, no "
            "collectives; off = bit-identical, replica-free graph"),
    KnobSpec(
        "expert_quant", off_values=(None,), on={"expert_quant": "int8"},
        on_rules=("quant_ops_present", "no_extra_exchange"),
        doc="quantized expert weight storage & compute "
            "(flashmoe_tpu/quant/): int8/e4m3 FFN weights with "
            "per-output-channel f32 scales, dequantized in compute "
            "(f32 accumulation untouched).  Off = no quant code runs "
            "= bit-identical graph on every backend; on adds the "
            "quantize/dequantize arithmetic (int8 dtypes appear in "
            "the graph — the teeth check) but NEVER an exchange: "
            "weights are rank-local, so compression of their storage "
            "cannot touch a collective"),
    KnobSpec(
        "kv_wire_dtype", off_values=(None,),
        on={"kv_wire_dtype": "e4m3"}, changes_graph=False,
        doc="KV-page handoff wire for the disaggregated fabric "
            "(fabric/handoff.py): the prefill->decode page stream is "
            "encoded/decoded HOST-SIDE between the prefill jit and the "
            "cache store, so BOTH values trace the byte-identical "
            "graph on every backend — off is bit-identical by "
            "construction (the 'off' codec arm returns the arrays "
            "untouched, no astype), and on never adds a collective "
            "(the handoff is a host boundary, not an exchange; the "
            "census's kv-wire rows double-check)"),
    KnobSpec(
        "gather_fused", off_values=(None, False), on={"gather_fused": True},
        backends=("local",), changes_graph=False,
        doc="inference kernel-entry selector; on the XLA oracle path "
            "(use_pallas=False) every value traces to the identical "
            "graph — the knob only swaps Pallas kernel entries"),
    KnobSpec(
        "profile_phases", off_values=(False,),
        on={"profile_phases": True}, changes_graph=False,
        doc="host-side phase-fence clock (flashmoe_tpu/profiler/): the "
            "fences block on concrete eager values only and no-op on "
            "tracers, so BOTH values trace the byte-identical graph on "
            "every backend — off is bit-identical by construction and "
            "on costs nothing under jit"),
)

KNOBS_BY_NAME = {k.name: k for k in KNOBS}

#: serving-plane knobs that live OUTSIDE MoEConfig (constructor seams
#: on the fabric/engine, not dataclass fields) — documented with the
#: same KnobSpec vocabulary so docs/OBSERVABILITY.md can cite one
#: registry, but excluded from :func:`check_knob_coverage`'s
#: MoEConfig-bidirectional matrix (registering them THERE would flag a
#: stale row).  Their off-identity story is drilled where they plug in
#: (tests/test_frontdoor.py's byte-identity gate), not by the jaxpr
#: invariant engine: a clock never appears in a traced graph.
SERVING_KNOBS: tuple[KnobSpec, ...] = (
    KnobSpec(
        "vclock", off_values=(None,), on={"vclock": "VirtualClock()"},
        backends=(), changes_graph=False,
        doc="the fabric's deterministic virtual clock (fabric/"
            "vclock.py): ServingFabric(vclock=...) steps every replica "
            "on per-lane virtual time, the KV handoff advances it by "
            "the measured DCN cost (modeled + chaos), and TTFT/TPOT "
            "become measured-under-delay numbers reconciled against "
            "the priced verdicts (fabric.handoff_drift).  Off (None, "
            "the default) is the wall clock: byte-identical graphs and "
            "token-bit-equal outputs to the unclocked fabric — the "
            "clock is a host-side seam that never enters a jit"),
    KnobSpec(
        "transport", off_values=(None,),
        on={"transport": "HandoffTransport()"},
        backends=(), changes_graph=False,
        doc="the failable KV-handoff wire (fabric/transport.py): "
            "ServingFabric(transport=...) routes every prefill->decode "
            "page stream through a serialize/verify/deserialize hop "
            "with per-page CRC32 checksums, capped-exponential-backoff "
            "retries on corruption or timeout (fabric.handoff_retry / "
            "fabric.handoff_corrupt), and the wasted wire time priced "
            "into the virtual clock (handoff_drift retry_ms).  Off "
            "(None, the default) hands the payload object across "
            "in-process untouched — byte-identical to the PR 15 path; "
            "on with a clean wire is token-bit-equal because the "
            "decode side caches the RECEIVED bytes"),
    KnobSpec(
        "brownout", off_values=(None,),
        on={"brownout": "BrownoutConfig()"},
        backends=(), changes_graph=False,
        doc="hysteretic brownout load-shedding at the front door "
            "(runtime/controller.py BrownoutConfig + frontdoor.py): "
            "FrontDoor(brownout=...) stages admissions and sheds "
            "(mode='shed') or truncates (mode='degrade') NEW arrivals "
            "while fleet queue depth or handoff-retry pressure holds "
            "above the enter threshold, with the controller's debounce"
            "/cooldown/episode-budget discipline (frontdoor.brownout / "
            "frontdoor.shed).  Off (None, the default) admits "
            "everything up front — the PR 15/17 path unchanged; "
            "already-admitted requests are never touched either way"),
    KnobSpec(
        "fault_plan", off_values=(None,),
        on={"fault_plan": "FaultPlan('replica_crash', ...)"},
        backends=(), changes_graph=False,
        doc="deterministic replica-crash injection (fabric/engine.py): "
            "ServingFabric(fault_plan=...) silently kills the planned "
            "replica at the planned step; the next step's health "
            "probes detect it, the router fences it (mark_failed), and "
            "its in-flight requests re-queue at the FRONT of surviving "
            "replicas via the eviction-resume path — token-bit-equal "
            "recovery (fabric.replica_crash / fabric.migrate).  Off "
            "(None, the default) injects nothing; detection and "
            "migration still guard real probe failures"),
    KnobSpec(
        "wire", off_values=("inproc",), on={"wire": "'tcp'"},
        backends=(), changes_graph=False,
        doc="the transport's socket wire (fabric/transport.py): "
            "HandoffTransport(wire='tcp') sends every KV transfer "
            "through a REAL localhost TCP socket — length-prefixed "
            "frames, per-page CRC32 verify on receive — so connection "
            "reset, partial read and recv timeout are genuine kernel "
            "failure modes feeding the same capped-backoff retry "
            "ladder (fabric.partition / fabric.handoff_retry "
            "reason='reset'), with wasted wire time priced into the "
            "virtual clock as retry_ms.  Off ('inproc', the default) "
            "hands the serialized frames across in-process: no "
            "sockets, no threads, byte-identical payloads — the wire "
            "is a byte codec either way, so tcp is token-bit-equal "
            "too (tests/test_transport.py)"),
    KnobSpec(
        "heartbeat", off_values=(None,),
        on={"heartbeat": "HeartbeatConfig()"},
        backends=(), changes_graph=False,
        doc="sub-step heartbeat crash detection (fabric/leasestore.py "
            "+ fabric/engine.py): ServingFabric(heartbeat=...) makes "
            "every decode replica publish monotonic per-phase "
            "heartbeats (admit/prefill/sample/decode/end, vclock-"
            "stamped) into the fcntl-locked external lease store, and "
            "a watchdog with misses_to_stall hysteresis declares a "
            "replica that stops beating WITH pending work stalled "
            "mid-step (fabric.heartbeat_miss / fabric.heartbeat_stall) "
            "— triggering the same fence+evacuate+adopt migration as "
            "a probed crash, detection latency priced in virtual ms.  "
            "Off (None, the default) installs no heartbeat_fn: zero "
            "engine callbacks, no store file, byte-identical to the "
            "probe-only PR 18 path"),
    KnobSpec(
        "speculate", off_values=(None,),
        on={"speculate": "SpecConfig(draft_tokens=3)"},
        backends=(), changes_graph=False,
        doc="speculative multi-token decoding (serving/speculate.py + "
            "engine.py): ServeConfig(speculate=SpecConfig(...)) drafts "
            "up to draft_tokens continuation tokens per slot from an "
            "n-gram/prompt-lookup index over each request's history "
            "and scores them in ONE k+1-position paged verify forward "
            "(serve.draft / serve.verify spans).  Only CANONICAL "
            "samples are emitted — each draft column is re-sampled "
            "with the per-request fold_in key stream the plain decode "
            "step would have used, so accepted prefixes are token-"
            "bit-equal to non-speculative decode at every temperature/"
            "top-k/top-p arm; KV pages for rejected suffixes roll "
            "back before the causal mask ever exposes them.  Off "
            "(None, the default) never builds the verify jit and "
            "traces the byte-identical decode graph; on is priced by "
            "the planner's verify_tokens axis and morphed off fleet-"
            "wide by the controller under sustained low acceptance "
            "(controller.spec_morph) with zero lost tokens"),
)

SERVING_KNOBS_BY_NAME = {k.name: k for k in SERVING_KNOBS}

#: fields that select among registered execution paths rather than
#: toggling graph content; their safety story is config-time validation
#: (config.py __post_init__) + planner selection tests
SELECTOR_FIELDS = {
    "moe_backend": "execution-path selector (collective / fused / "
                   "ragged / auto); invalid combinations rejected at "
                   "config time, auto resolution covered by "
                   "tests/test_planner.py",
    "serving_mode": "planner pricing-regime selector (None = training "
                    "shape / 'prefill' / 'decode'); only changes which "
                    "path moe_backend='auto' resolves to — the traced "
                    "graph is identical for every value; invalid names "
                    "rejected at config time, decode-mode selection "
                    "covered by tests/test_serving.py",
    "fused_schedule": "fused-kernel FFN-schedule selector (None = auto "
                      "/ 'batched' / 'resident' / 'stream' / 'rowwin'); "
                      "every value computes the same function on a "
                      "different execution geometry — invalid names "
                      "rejected at config time, VMEM-infeasible forced "
                      "schedules raise at launch, cross-schedule "
                      "bit-identity asserted by tests/test_fused.py and "
                      "the planner's per-schedule rows by "
                      "tests/test_planner.py",
}

#: model/job *shape* fields: changing one changes the problem, not a
#: default-off code path, so no identity invariant applies
STRUCTURAL_FIELDS = frozenset({
    "num_experts", "expert_top_k", "hidden_size", "intermediate_size",
    "sequence_len", "mini_batch", "global_batch", "capacity_factor",
    "drop_tokens", "is_training", "hidden_act",
    "num_layers", "moe_frequency", "vocab_size",
    "num_shared_experts", "num_heads", "num_kv_heads", "head_dim",
    "gated_ffn", "router_jitter", "aux_loss_coef", "router_z_loss_coef",
    "rope_theta",
    "dtype", "param_dtype", "accum_dtype",
    "dp", "ep", "tp", "sp", "pp",
})


def check_knob_coverage(field_names=None) -> list[Violation]:
    """Every MoEConfig field must be classified: structural, selector,
    or a registered knob.  ``field_names`` defaults to the live
    dataclass — tests pass a synthetic list to prove an unclassified
    knob fails the matrix."""
    if field_names is None:
        from flashmoe_tpu.config import MoEConfig

        field_names = [f.name for f in dataclasses.fields(MoEConfig)]
    known = STRUCTURAL_FIELDS | set(SELECTOR_FIELDS) | set(KNOBS_BY_NAME)
    out = []
    for name in field_names:
        if name not in known:
            out.append(Violation(
                "invariants", "knob-coverage", name,
                "MoEConfig field has no registered invariant: add a "
                "KnobSpec row (or classify it in STRUCTURAL_FIELDS / "
                "SELECTOR_FIELDS) in staticcheck/registry.py"))
    for name in sorted((set(KNOBS_BY_NAME) | set(SELECTOR_FIELDS))
                       - set(field_names)):
        out.append(Violation(
            "invariants", "knob-coverage", name,
            "registered knob is not a MoEConfig field (stale registry "
            "row?)"))
    return out
