"""Live telemetry plane: what the job looks like WHILE it runs.

Everything observability built so far is post-hoc: the flight recorder
ring, the phase ledger, Perfetto exports, and crash postmortems are all
artifacts you read after the fact.  This package is the live half of
that story (docs/OBSERVABILITY.md "Live telemetry plane"):

* :mod:`flashmoe_tpu.telemetry_plane.sketch` — bounded-memory streaming
  aggregation: a dependency-free P²-style quantile sketch (O(1) memory
  rolling p50/p90/p99 instead of full-history percentiles) and a
  bucketed windowed rate (tokens/s, admits/s, evictions/s).  Exposed
  through :meth:`flashmoe_tpu.utils.telemetry.Metrics.sketch`.
* :mod:`flashmoe_tpu.telemetry_plane.tracing` — request-scoped
  distributed tracing for the serving engine: a trace context minted at
  ``serve.admit`` and threaded through the whole request lifecycle
  (queued → admit → prefill → per-step decode → (evict → re-queue →
  re-prefill)* → retire), recorded via the existing telemetry
  span-listener hook (chainable with a PR 8 :class:`PhaseTimeline`, so
  the two join), exported as one Perfetto track per request through
  :func:`flashmoe_tpu.profiler.export.request_trace_document` and
  rendered by ``python -m flashmoe_tpu.observe --trace <rid>``.
* :mod:`flashmoe_tpu.telemetry_plane.server` — stdlib ``http.server``
  scrape endpoints on a background thread: ``/metrics`` (Prometheus
  text exposition, ``text/plain; version=0.0.4``), ``/healthz`` (SLO
  episode state, controller budgets/cooldowns, last checkpoint step,
  queue/occupancy), ``/vars`` (JSON snapshot of the resolved plan and
  active knobs).  Default off everywhere = zero threads = byte-identical
  behavior; armed via ``--telemetry-port`` on the train and serving
  CLIs.  Per-host JSONL shard helpers feed ``observe --merge``.
* :mod:`flashmoe_tpu.telemetry_plane.regression` — the perf-regression
  sentry: per-run metric summaries persisted to ``obs/history.jsonl``
  keyed by the bench/serving measurement-identity strings, compared
  against a rolling baseline by ``python -m flashmoe_tpu.observe
  --regression`` (``regress.detected`` decision, rc 2 under ``--ci``).

Import the submodules directly — this ``__init__`` stays import-light
(the sketch is pulled lazily by :class:`Metrics` on first use).
"""
