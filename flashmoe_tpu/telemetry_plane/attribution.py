"""Per-request critical-path attribution.

A breached SLO is only actionable if the millisecond budget names a
culprit: *where* did a request's TTFT/E2E go?  The
:class:`~flashmoe_tpu.telemetry_plane.tracing.RequestTracer` already
reconstructs every retired request as a contiguous track of lifecycle
spans; this module decomposes that track into named components that
**sum to the span total** by construction:

* ``queue_wait``   — arrival -> first admission (``serve.queued``,
  ``resumed=False``); reclassified as ``router_spill`` when the
  request's ``fabric.route`` decision spilled off its preferred
  replica (``policy="jsq_spill"``): the wait was load, not luck;
* ``eviction_gap`` — every preemption hole (``serve.queued``,
  ``resumed=True``);
* ``prefill``      — prefill compute (``serve.prefill`` +
  ``serve.prefill_chunk``) minus the handoff wait nested inside it;
* ``handoff_dcn``  — the prefill->decode KV-page transfer
  (``serve.handoff``, virtual-clock DCN delay included);
* ``decode_steps`` — the engine-step windows minus the prefill spans
  nested in them: decode compute plus the host glue between jits.

Because ``serve.prefill``/``serve.decode``/``serve.handoff`` nest
inside ``serve.step`` windows and ``serve.queued`` fills every
non-step gap, ``queued + step == track extent`` up to the tracer's
contiguity slack — under the virtual clock the identity is exact, and
the 1% ``sum_ok`` gate (acceptance criterion) has no wall-clock noise
to forgive.

Entry points: :func:`attribute_track` (one request, optionally clipped
at first-token time for a TTFT decomposition),
:func:`attribute_tracer` (every retired request of a live tracer, with
per-component ``serve.attr.*_ms`` sketches fed to ``/metrics`` and a
``serve.attribution`` decision per request), and
:func:`attribution_report` (fleet-wide over exported JSONL records —
what ``observe --attribution`` renders).
"""

from __future__ import annotations

#: attribution components, in render order
COMPONENTS = ("queue_wait", "router_spill", "eviction_gap", "prefill",
              "handoff_dcn", "decode_steps")

#: absolute slack (ms) forgiven by ``sum_ok`` on degenerate tiny tracks
_ABS_SLACK_MS = 0.05


def attribute_track(track, *, spilled: bool = False,
                    until_ms: float | None = None) -> dict:
    """Decompose one request's span track (timeline-ordered dicts with
    ``name``/``ts_ms``/``dur_ms``, e.g. ``RequestTracer.
    request_track``) into :data:`COMPONENTS`.

    ``until_ms`` clips every span at an absolute track time — pass
    ``track[0].ts_ms + ttft_ms`` to decompose TTFT instead of E2E.
    Returns components, their sum, the track's span extent, the
    relative error between the two, the 1%-gate verdict ``sum_ok``,
    and the ``dominant`` contributor."""
    queue_wait = evict_gap = steps = prefill_all = handoff = 0.0
    t_first: float | None = None
    t_last = 0.0
    for s in track:
        t0 = float(s["ts_ms"])
        t1 = t0 + float(s["dur_ms"])
        if until_ms is not None:
            t1 = min(t1, float(until_ms))
        d = max(0.0, t1 - t0)
        if d <= 0 and until_ms is not None and t0 >= until_ms:
            continue
        if t_first is None or t0 < t_first:
            t_first = t0
        t_last = max(t_last, t1)
        name = s["name"]
        if name == "serve.queued":
            if s.get("resumed"):
                evict_gap += d
            else:
                queue_wait += d
        elif name == "serve.step":
            steps += d
        elif name in ("serve.prefill", "serve.prefill_chunk"):
            prefill_all += d
        elif name == "serve.handoff":
            handoff += d
    components = {
        "queue_wait": 0.0 if spilled else queue_wait,
        "router_spill": queue_wait if spilled else 0.0,
        "eviction_gap": evict_gap,
        "prefill": max(prefill_all - handoff, 0.0),
        "handoff_dcn": handoff,
        "decode_steps": max(steps - prefill_all, 0.0),
    }
    total = sum(components.values())
    span_ms = (t_last - t_first) if t_first is not None else 0.0
    diff = abs(total - span_ms)
    rel_err = diff / span_ms if span_ms > 0 else 0.0
    dominant = (max(COMPONENTS, key=lambda k: components[k])
                if span_ms > 0 else None)
    return {
        "components": {k: round(v, 6) for k, v in components.items()},
        "total_ms": round(total, 6),
        "span_ms": round(span_ms, 6),
        "rel_err": round(rel_err, 6),
        "sum_ok": bool(diff <= max(0.01 * span_ms, _ABS_SLACK_MS)),
        "dominant": dominant,
    }


def spilled_rids(route_decisions) -> set:
    """Rids whose router placement spilled off the affinity-preferred
    replica — ``fabric.route`` decision dicts (live or JSONL form)."""
    out = set()
    for rec in route_decisions:
        if rec.get("policy") == "jsq_spill" and rec.get("rid") is not None:
            out.add(rec["rid"])
    return out


def attribute_tracer(tracer, *, spilled=(), metrics_obj=None,
                     ttft_ms=None) -> dict:
    """Attribute every RETIRED request of a live tracer.

    ``spilled``: rid set from :func:`spilled_rids`.  ``ttft_ms``:
    optional ``{rid: ttft_ms}`` — when given, each request also gets a
    TTFT decomposition (track clipped at first-token time).  With
    ``metrics_obj`` set, per-component totals feed ``serve.attr.
    <component>_ms`` sketches (the ``/metrics`` scrape view) and each
    request emits one ``serve.attribution`` decision naming its
    dominant contributor."""
    spilled = set(spilled)
    out: dict = {}
    for rid, st in sorted(tracer.requests.items()):
        if not st.retired:
            continue
        track = tracer.request_track(rid)
        att = attribute_track(track, spilled=rid in spilled)
        if ttft_ms and ttft_ms.get(rid) is not None and track:
            att["ttft"] = attribute_track(
                track, spilled=rid in spilled,
                until_ms=float(track[0]["ts_ms"]) + float(ttft_ms[rid]))
        out[rid] = att
        if metrics_obj is not None:
            for comp, v in att["components"].items():
                if v > 0:
                    metrics_obj.sketch(f"serve.attr.{comp}_ms", v)
            metrics_obj.decision(
                "serve.attribution", rid=rid, dominant=att["dominant"],
                span_ms=att["span_ms"], total_ms=att["total_ms"],
                rel_err=att["rel_err"], sum_ok=att["sum_ok"],
                **{k: v for k, v in att["components"].items() if v > 0})
    return out


def attribution_report(records) -> dict:
    """Fleet-wide attribution over exported JSONL records (``observe
    --attribution``): groups ``serve_trace_span`` records by rid
    (deduping shard overlap), pulls spill verdicts from ``fabric.
    route`` decisions, attributes each retired request, and rolls the
    components up fleet-wide."""
    tracks: dict = {}
    retired: dict = {}
    seen = set()
    routes = []
    for rec in records:
        kind = rec.get("kind")
        if kind == "serve_trace_span":
            key = (rec.get("rid"), rec.get("name"), rec.get("ts_ms"),
                   rec.get("dur_ms"), rec.get("step"))
            if key in seen:
                continue
            seen.add(key)
            tracks.setdefault(rec.get("rid"), []).append(rec)
            retired[rec.get("rid")] = (retired.get(rec.get("rid"), False)
                                       or bool(rec.get("retired")))
        elif rec.get("decision") == "fabric.route":
            routes.append(rec)
    spilled = spilled_rids(routes)
    per_request: dict = {}
    totals = {k: 0.0 for k in COMPONENTS}
    dominant_counts: dict = {}
    bad = []
    for rid in sorted(tracks, key=lambda r: (str(type(r)), str(r))):
        if not retired.get(rid):
            continue
        track = sorted(tracks[rid], key=lambda s: s["ts_ms"])
        att = attribute_track(track, spilled=rid in spilled)
        per_request[rid] = att
        for k, v in att["components"].items():
            totals[k] += v
        if att["dominant"] is not None:
            dominant_counts[att["dominant"]] = \
                dominant_counts.get(att["dominant"], 0) + 1
        if not att["sum_ok"]:
            bad.append(rid)
    grand = sum(totals.values())
    return {
        "requests": len(per_request),
        "spilled": sorted(spilled & set(per_request)),
        "totals_ms": {k: round(v, 3) for k, v in totals.items()},
        "shares": {k: round(v / grand, 4) if grand > 0 else 0.0
                   for k, v in totals.items()},
        "dominant_counts": dict(sorted(dominant_counts.items())),
        "sum_violations": bad,
        "per_request": per_request,
    }
