"""Perf-regression sentry: the repo's durable performance trajectory.

Every hardware bench window this repo ever asked for hung (BENCH_r01..
r05), so until now a modeled-cost regression in a PR was only caught if
a golden number happened to move.  This module gives the framework a
memory:

* :func:`append_run` persists one run's metric points to
  ``obs/history.jsonl`` — one JSON line per run: ``{"run", "meta",
  "metrics": {key: {"value", "unit"}}}`` — keyed by the measurement-
  identity strings the bench/serving records already carry (the PR 5/6/
  12 convention: the ``metric`` field encodes path/d/chunks/wire/slices,
  so a compressed timing can never baseline an uncompressed one);
* :func:`collect_points` extracts those points from any record pile
  (bench records, serving sweep records, ledger rows, drill summaries);
* :func:`reference_points` computes the deterministic modeled points of
  the golden planner configs (``predicted_ms`` at the golden 8-rank
  mesh) — the CI-stable rows the committed baseline seed is built from;
* :func:`check_regression` compares the NEWEST run against a rolling
  baseline (median of up to ``baseline_n`` prior runs per key) with
  per-unit tolerances, emitting one ``regress.detected`` decision per
  offending metric.

CLI: ``python -m flashmoe_tpu.observe --regression [--ci] [history]``
renders the report; ``--ci`` exits rc 2 when anything regressed.
``bench.py --regression`` appends the run it just measured.
"""

from __future__ import annotations

import json
import math
import os
import time

#: default history location (relative to the repo/session cwd)
DEFAULT_HISTORY = os.path.join("obs", "history.jsonl")

#: relative tolerance per unit before a move counts as a regression;
#: ``_DIR`` says which direction is "worse" (+1 = higher is worse)
UNIT_TOLERANCE = {
    "ms": 0.15,
    "tokens_per_sec": 0.15,
    "ratio_vs_serialized": 0.15,
    "hidden_frac": 0.15,
    "frac": 0.15,
    "accept_rate": 0.15,
    "tokens_per_step": 0.15,
}
DEFAULT_TOLERANCE = 0.25
_DIR = {
    "ms": +1.0,                   # latency: up is worse
    "tokens_per_sec": -1.0,       # throughput: down is worse
    "ratio_vs_serialized": -1.0,  # overlap efficiency: down is worse
    "hidden_frac": -1.0,          # handoff overlap: less hidden = worse
    "frac": +1.0,                 # shed fraction: more shedding = worse
    "accept_rate": +1.0,          # break-even acceptance: up = speculation
                                  # pays later = worse
    "tokens_per_step": -1.0,      # speculation uplift: down is worse
}


def collect_points(records) -> dict[str, dict]:
    """Metric points of one run, keyed by their identity string.

    A point is any record with a string ``metric`` and a finite numeric
    ``value`` (skipped/partial/error records are not a run's numbers —
    a wedged-tunnel ``skipped:true`` line must never enter the
    baseline).  Serving-drill summaries (``ttft_ms_p50`` et al. on a
    ``serve_load[...]`` record) ride along as derived points so the
    sentry watches tail latency, not just the headline value."""
    points: dict[str, dict] = {}
    for rec in records:
        if not isinstance(rec, dict):
            continue
        key = rec.get("metric")
        val = rec.get("value")
        if not isinstance(key, str) or rec.get("skipped") \
                or rec.get("partial") or rec.get("error"):
            continue
        if isinstance(val, bool) or not isinstance(val, (int, float)):
            continue
        points[key] = {"value": float(val),
                       "unit": str(rec.get("unit", ""))}
        for sub in ("ttft_ms_p50", "ttft_ms_p99", "tpot_ms_p50",
                    "predicted_ms"):
            sv = rec.get(sub)
            if isinstance(sv, (int, float)) and not isinstance(sv, bool):
                points[f"{key}.{sub}"] = {"value": float(sv),
                                          "unit": "ms"}
    return points


def reference_points(gen: str = "v5e") -> dict[str, dict]:
    """Deterministic modeled points for the golden planner configs:
    the resolved path's predicted latency on the golden 8-rank mesh.
    Pure cost-model output — stable across machines, which is what the
    committed baseline seed (and its clean-history CI gate) needs."""
    from flashmoe_tpu.config import BENCH_CONFIGS
    from flashmoe_tpu.planner.golden import GOLDEN_CONFIGS, GOLDEN_D
    from flashmoe_tpu.planner.model import predict_paths

    points: dict[str, dict] = {}
    for name in GOLDEN_CONFIGS:
        cfg = BENCH_CONFIGS[name].replace(ep=GOLDEN_D)
        preds = [p for p in predict_paths(cfg, GOLDEN_D, gen)
                 if p.feasible]
        if not preds:
            continue
        win = preds[0]
        points[f"planner_predicted_ms[{name},d={GOLDEN_D},{gen}]"] = {
            "value": round(win.total_ms, 4), "unit": "ms",
        }
        # quantized-store model points (ISSUE 15): the int8 winner's
        # total and the fused[rowwin] weight-stream time — the terms
        # the quant byte model owns, guarded by the sentry from day
        # one so a pricing regression trips `observe --regression
        # --ci` before any silicon measures it
        qcfg = cfg.replace(expert_quant="int8")
        qpreds = predict_paths(qcfg, GOLDEN_D, gen)
        qwin = next((p for p in qpreds if p.feasible), None)
        if qwin is not None:
            points[f"planner_predicted_ms[{name},d={GOLDEN_D},{gen},"
                   f"quant=int8]"] = {
                "value": round(qwin.total_ms, 4), "unit": "ms",
            }
        rw = next((p for p in qpreds if p.path == "fused[rowwin]"),
                  None)
        if rw is not None:
            from flashmoe_tpu.planner.model import _dtype_peak

            _, hbm_bs = _dtype_peak(gen, qcfg)
            points[f"quant_rowwin_weight_ms[{name},d={GOLDEN_D},{gen},"
                   f"quant=int8]"] = {
                "value": round(rw.cost.weight_bytes / hbm_bs * 1e3, 4),
                "unit": "ms",
            }
        # measured-latency plane (ISSUE 17): drive the golden handoff
        # through the virtual clock itself — first-token latency as a
        # request EXPERIENCES it (one decode tick with the modeled DCN
        # transfer overlapping it) and the fleet hidden fraction.
        # Pure vclock arithmetic over cost-model inputs: deterministic,
        # and a drift in EITHER the pricing or the clock's
        # hidden/exposed accounting moves these rows
        from flashmoe_tpu.fabric.vclock import VirtualClock
        from flashmoe_tpu.planner.golden import (
            GOLDEN_KV_PAGE, GOLDEN_KV_PAGES, _predicted_plan,
        )
        from flashmoe_tpu.planner.model import kv_handoff_ms

        base = BENCH_CONFIGS[name]
        tick = _predicted_plan(base, gen, "decode")["total_ms"]
        ms = kv_handoff_ms(base, GOLDEN_KV_PAGES, GOLDEN_KV_PAGE,
                           wire=None)
        vc = VirtualClock(tick_ms=tick)
        t0 = vc.now_ms()
        vc.on_handoff(ms)
        vc.complete_step()
        points[f"fabric_ttft_vclock_ms[{name},d={GOLDEN_D},{gen}]"] = {
            "value": round(vc.now_ms() - t0, 4), "unit": "ms",
        }
        hf = vc.hidden_fraction()
        points[f"fabric_handoff_hidden_frac[{name},d={GOLDEN_D},"
               f"{gen}]"] = {
            "value": round(hf if hf is not None else 1.0, 4),
            "unit": "hidden_frac",
        }
        # serving fault-tolerance plane (ISSUE 18): the modeled
        # replica-crash recovery latency — one decode tick of detection
        # delay (health probes run at step boundaries), the re-streamed
        # KV handoff to the adopting replica, and the first resumed
        # decode tick.  Pure cost-model + vclock arithmetic: a drift in
        # the DCN pricing or the tick model moves this row before any
        # chaos drill measures it
        points[f"fabric_recovery_ms[{name},d={GOLDEN_D},{gen}]"] = {
            "value": round(2 * tick + ms, 4), "unit": "ms",
        }
        # cross-process plane (ISSUE 19): the sub-step heartbeat
        # detection deadline (watchdog hysteresis x decode tick — the
        # virtual ms between a mid-step hang and the stall verdict)
        # and the modeled per-handoff socket-wire overhead for the
        # golden KV payload (tcp vs the free in-process wire).  Pure
        # arithmetic over committed constants: retuning the watchdog
        # default or the framing overhead model trips the sentry
        # before any drill measures it
        from flashmoe_tpu.fabric.leasestore import HeartbeatConfig
        from flashmoe_tpu.fabric.transport import wire_overhead_ms
        from flashmoe_tpu.planner.model import kv_page_mb

        hb = HeartbeatConfig()
        points[f"fabric_heartbeat_detect_ms[{name},d={GOLDEN_D},"
               f"{gen}]"] = {
            "value": round(hb.misses_to_stall * tick, 4), "unit": "ms",
        }
        payload_bytes = int(GOLDEN_KV_PAGES
                            * kv_page_mb(base, GOLDEN_KV_PAGE) * 2**20)
        points[f"fabric_wire_overhead_ms[{name},d={GOLDEN_D},{gen},"
               f"wire=tcp]"] = {
            "value": round(wire_overhead_ms(payload_bytes, "tcp"), 4),
            "unit": "ms",
        }
        # speculative-decoding plane (ISSUE 20): the break-even
        # acceptance of the golden verify depth (the floor the
        # controller's spec-morph trigger defends) and the modeled
        # tokens/step at the golden acceptance rate.  Pure cost-model
        # arithmetic: a verify-span pricing drift moves the break-even,
        # a draft-economics drift moves the uplift — either trips the
        # sentry before any acceptance-rate drill measures it
        from flashmoe_tpu.planner.golden import (
            GOLDEN_SPEC_ACCEPT, GOLDEN_SPEC_K,
        )
        from flashmoe_tpu.planner.model import (
            speculate_break_even, speculate_tokens_per_step,
        )

        points[f"decode_accept_rate[{name},d={GOLDEN_D},{gen},"
               f"spec=k{GOLDEN_SPEC_K}]"] = {
            "value": round(speculate_break_even(
                cfg, GOLDEN_D, gen, verify_tokens=GOLDEN_SPEC_K), 4),
            "unit": "accept_rate",
        }
        points[f"spec_tokens_per_step[{name},d={GOLDEN_D},{gen},"
               f"spec=k{GOLDEN_SPEC_K}]"] = {
            "value": round(speculate_tokens_per_step(
                GOLDEN_SPEC_ACCEPT, GOLDEN_SPEC_K), 4),
            "unit": "tokens_per_step",
        }
    # brownout shed fraction at the default BrownoutConfig against the
    # reference flood: deterministic hysteresis arithmetic — retuning
    # the admission controller's thresholds/debounce moves this row,
    # so an accidental "sheds half the traffic" default trips the
    # sentry before it ships
    from flashmoe_tpu.runtime.controller import BrownoutConfig

    points["fabric_shed_frac[brownout,reference]"] = {
        "value": round(_reference_shed_frac(BrownoutConfig()), 4),
        "unit": "frac",
    }
    return points


#: the reference flood behind ``fabric_shed_frac[brownout,reference]``:
#: per-step arrivals of a front-loaded burst with a long tail, served
#: at ``_REFERENCE_SERVICE_RATE`` requests/step
_REFERENCE_FLOOD = (8, 4, 4, 2, 2, 1, 1, 1, 0, 0, 0, 0)
_REFERENCE_SERVICE_RATE = 2.0


def _reference_shed_frac(bo) -> float:
    """Shed fraction of the reference flood under the hysteretic
    brownout controller — the same enter/exit discipline as
    ``FrontDoor.observe_brownout`` (breach debounce, calm debounce,
    cooldown, episode budget) run over a synthetic queue-depth
    trajectory in pure arithmetic."""
    depth = 0.0
    active = False
    breach = clear = episodes = 0
    cooldown_until = -1
    shed = offered = 0
    for step, a in enumerate(_REFERENCE_FLOOD):
        offered += a
        if active:
            shed += a
        else:
            depth += a
        depth = max(0.0, depth - _REFERENCE_SERVICE_RATE)
        if active:
            calm = depth < bo.queue_low
            clear = clear + 1 if calm else 0
            if clear >= bo.debounce_steps:
                active = False
                clear = 0
                cooldown_until = step + bo.cooldown_steps
        else:
            hot = depth > bo.queue_high
            if hot and step >= cooldown_until \
                    and episodes < bo.episode_budget:
                breach += 1
            else:
                breach = 0
            if breach >= bo.debounce_steps:
                active = True
                breach = 0
                episodes += 1
    return shed / offered if offered else 0.0


def append_run(path: str, points: dict[str, dict], *,
               run: str | None = None, meta: dict | None = None) -> dict:
    """Append one run line to the history (creating directories as
    needed).  Returns the entry written; a run with no points is not
    written (and returns {})."""
    if not points:
        return {}
    entry = {
        "run": run or time.strftime("%Y-%m-%dT%H:%M:%S"),
        "meta": dict(meta or {}),
        "metrics": points,
    }
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(entry) + "\n")
    return entry


def load_history(path: str) -> list[dict]:
    """All run entries, oldest first.  Unparseable lines skipped (the
    observe.load_jsonl convention)."""
    if not os.path.exists(path):
        return []
    runs = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and isinstance(rec.get("metrics"),
                                                    dict):
                runs.append(rec)
    return runs


def _tolerance(unit: str, overrides: dict | None) -> float:
    if overrides and unit in overrides:
        return float(overrides[unit])
    return UNIT_TOLERANCE.get(unit, DEFAULT_TOLERANCE)


def check_regression(runs: list[dict], *, baseline_n: int = 5,
                     tolerances: dict | None = None,
                     metrics_obj=None) -> dict:
    """Judge the newest run against the rolling baseline.

    For every metric key the newest run shares with at least one prior
    run, baseline = median of that key's values over the last
    ``baseline_n`` prior runs; the move is a regression when it exceeds
    the unit's tolerance in the unit's "worse" direction (higher ms,
    lower tokens/s).  Each regression emits one registered
    ``regress.detected`` decision.  Returns the report dict the CLI
    renders (``regressions`` non-empty = rc 2 under ``--ci``)."""
    report = {"runs": len(runs), "compared": 0, "regressions": [],
              "improvements": [], "new_metrics": [], "rows": []}
    if len(runs) < 2:
        report["note"] = ("need >= 2 runs to compare (newest vs rolling "
                          "baseline); history has "
                          f"{len(runs)}")
        return report
    newest = runs[-1]
    prior = runs[:-1]
    for key, pt in sorted(newest["metrics"].items()):
        vals = [r["metrics"][key]["value"] for r in prior[-baseline_n:]
                if key in r.get("metrics", {})
                and isinstance(r["metrics"][key].get("value"),
                               (int, float))]
        if not vals:
            report["new_metrics"].append(key)
            continue
        vals.sort()
        # true median: even-sized windows average the middle pair (the
        # upper-middle element alone made the sentry more lenient
        # exactly when history is short)
        mid = len(vals) // 2
        baseline = (vals[mid] if len(vals) % 2
                    else (vals[mid - 1] + vals[mid]) / 2.0)
        value = float(pt["value"])
        unit = str(pt.get("unit", ""))
        tol = _tolerance(unit, tolerances)
        direction = _DIR.get(unit, +1.0)  # unknown units: up is worse
        if baseline == 0:
            # rel carries the CHANGE's sign only (any move off a zero
            # baseline is an unbounded relative change); finite
            # sentinel keeps the --json report valid JSON, and the
            # direction multiply below decides bad vs good exactly
            # once — a throughput recovery from a 0-baseline run is an
            # improvement, not a regression
            rel = 0.0 if value == 0 else math.copysign(1e9, value)
        else:
            rel = (value - baseline) / abs(baseline)
        worse = rel * direction       # positive = moved the bad way
        row = {"metric": key, "value": value, "baseline": baseline,
               "unit": unit, "rel_change": round(rel, 4),
               "tolerance": tol, "n_baseline": len(vals),
               "regressed": bool(worse > tol)}
        report["rows"].append(row)
        report["compared"] += 1
        if worse > tol:
            report["regressions"].append(row)
            mo = metrics_obj
            if mo is None:
                from flashmoe_tpu.utils import telemetry as _t

                mo = _t.metrics
            mo.decision(
                "regress.detected", metric=key, value=value,
                baseline=baseline, unit=unit,
                rel_change=row["rel_change"], tolerance=tol,
                run=newest.get("run"))
        elif -worse > tol:
            report["improvements"].append(row)
    return report


def render_text(report: dict) -> str:
    lines = [f"perf sentry: {report['runs']} runs on record, "
             f"{report['compared']} metrics compared, "
             f"{len(report['regressions'])} regression(s)"]
    if report.get("note"):
        lines.append(f"  {report['note']}")
    for row in report["regressions"]:
        lines.append(
            f"  REGRESSED {row['metric']}: {row['value']:g} {row['unit']}"
            f" vs baseline {row['baseline']:g} "
            f"({row['rel_change']:+.1%}, tol ±{row['tolerance']:.0%}, "
            f"n={row['n_baseline']})")
    for row in report["improvements"]:
        lines.append(
            f"  improved  {row['metric']}: {row['value']:g} "
            f"{row['unit']} vs {row['baseline']:g} "
            f"({row['rel_change']:+.1%})")
    if report["new_metrics"]:
        lines.append("  new (no baseline yet): "
                     + ", ".join(report["new_metrics"][:8])
                     + (" ..." if len(report["new_metrics"]) > 8 else ""))
    if not report["regressions"] and report["compared"]:
        lines.append("  all within tolerance")
    return "\n".join(lines)
