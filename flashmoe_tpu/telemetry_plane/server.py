"""Scrape endpoints: a stdlib ``http.server`` on a background thread.

Three read-only endpoints over the live process (no third-party
dependency, no thread unless armed — default off everywhere keeps the
framework byte-identical):

* ``GET /metrics`` — Prometheus text exposition of the process
  :class:`~flashmoe_tpu.utils.telemetry.Metrics` registry (counters,
  gauges, timers, histograms, quantile sketches as summary metrics),
  served with the spec's ``text/plain; version=0.0.4`` content type
  (:data:`flashmoe_tpu.utils.telemetry.PROM_CONTENT_TYPE`);
* ``GET /healthz`` — liveness + the job's health narrative as JSON: SLO
  watchdog episode state, self-healing-controller budgets/cooldowns,
  last checkpoint step, serving queue depth / cache occupancy —
  whatever the arming caller's ``health_fn`` contributes;
* ``GET /vars`` — JSON snapshot of the resolved execution plan and
  active config knobs (``vars_fn``), the "what is this job actually
  running" page.

Arming: ``--telemetry-port N`` on ``python -m flashmoe_tpu.serving``,
``python -m flashmoe_tpu.runtime.train_cli``, and ``bench.py --serve``;
programmatically via :class:`TelemetryServer` (context manager) or the
``telemetry_port=`` argument on ``ServingEngine`` / ``train`` /
``resilient_train`` / ``supervise``.  Port 0 binds an ephemeral port
(tests); the bound port is on ``server.port`` and in the
``telemetry.server_start`` decision.

Per-host shards: :func:`host_shard_path` names one JSONL telemetry
shard per host (``telemetry.<host>.jsonl``) so every process of a
multi-slice job writes its own file; ``python -m flashmoe_tpu.observe
--merge shard...`` folds them into one fleet view.
"""

from __future__ import annotations

import http.server
import json
import os
import socket
import threading

from flashmoe_tpu.utils.telemetry import (
    PROM_CONTENT_TYPE, metrics as _global_metrics,
)


def host_shard_path(obs_dir: str, host: str | None = None) -> str:
    """The per-host telemetry shard file: ``telemetry.<host>.jsonl``
    under ``obs_dir``.  Host id: explicit arg, else ``FLASHMOE_HOST_ID``
    (the mocked-multislice drills set one per simulated host), else the
    machine hostname."""
    host = (host or os.environ.get("FLASHMOE_HOST_ID")
            or socket.gethostname() or "host0")
    safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in host)
    return os.path.join(obs_dir, f"telemetry.{safe}.jsonl")


class TelemetryServer:
    """Background scrape server.  ``metrics_fn`` resolves the
    :class:`Metrics` registry per request (a zero-arg callable, so bench
    sweeps can rotate per-point streams under one server); ``health_fn``
    / ``vars_fn`` return JSON-serializable dicts (both optional —
    ``/healthz`` always answers with at least ``{"ok": true}``)."""

    def __init__(self, port: int, *, metrics_fn=None, health_fn=None,
                 vars_fn=None, host: str = "127.0.0.1",
                 metrics_obj=None):
        if metrics_fn is None:
            obj = metrics_obj if metrics_obj is not None \
                else _global_metrics
            metrics_fn = lambda: obj  # noqa: E731 — default resolver
        self._metrics_fn = metrics_fn
        self._health_fn = health_fn
        self._vars_fn = vars_fn
        self._host = host
        self._want_port = int(port)
        self.port: int | None = None
        self._httpd = None
        self._thread = None

    # ---- lifecycle ---------------------------------------------------

    def start(self) -> "TelemetryServer":
        if self._httpd is not None:
            return self
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: D102 — quiet
                pass

            def _send(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        text = outer._metrics_fn().prometheus_text()
                        self._send(200, text.encode(),
                                   PROM_CONTENT_TYPE)
                    elif path == "/healthz":
                        doc = {"ok": True}
                        if outer._health_fn is not None:
                            doc.update(outer._health_fn() or {})
                        self._send(200, json.dumps(doc).encode(),
                                   "application/json")
                    elif path == "/vars":
                        doc = (outer._vars_fn() or {}
                               if outer._vars_fn is not None else {})
                        self._send(200, json.dumps(doc).encode(),
                                   "application/json")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except Exception as e:  # noqa: BLE001 — a scrape must
                    # never kill the job it observes
                    self._send(500, f"{type(e).__name__}: {e}\n"
                               .encode(), "text/plain")

        self._httpd = http.server.ThreadingHTTPServer(
            (self._host, self._want_port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="flashmoe-telemetry", daemon=True)
        self._thread.start()
        self._metrics_fn().decision("telemetry.server_start",
                                    port=self.port, host=self._host)
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._metrics_fn().decision("telemetry.server_stop",
                                    port=self.port)
        self._httpd = None
        self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def __enter__(self) -> "TelemetryServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def maybe_server(port: int | None, **kw) -> TelemetryServer | None:
    """``None``/falsy-but-not-0 port = live plane off = no thread, no
    behavior change; a port (0 = ephemeral) arms a started server."""
    if port is None:
        return None
    return TelemetryServer(int(port), **kw).start()


def scrape(url: str, timeout_s: float = 5.0) -> tuple[str, str]:
    """GET one endpoint; returns (body, content_type).  Stdlib only —
    the bench sweep and the tests share this one scraper."""
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return (r.read().decode(), r.headers.get("Content-Type", ""))
