"""Bounded-memory streaming aggregation: quantile sketch + windowed
rates.

``observe --serving`` used to compute TTFT/TPOT percentiles by
retaining every observation — unbounded under sustained load, which is
exactly the regime the serving engine exists for.  This module is the
O(1)-memory replacement:

* :class:`P2Quantile` — the P² algorithm (Jain & Chlamtac 1985): one
  target quantile tracked with five markers, each ``observe`` adjusting
  the marker heights by a piecewise-parabolic fit.  Exact for n <= 5,
  approximate beyond; no buffers, no sorting, no dependencies.
* :class:`QuantileSketch` — a bundle of P² cells (default p50/p90/p99)
  plus exact count/sum/min/max, with a small exact buffer for n <=
  ``EXACT_N`` so tiny samples (CI drills) report nearest-rank-exact
  percentiles.  Error bound documented on :meth:`quantile`.
* :class:`WindowedRate` — per-second rate over a sliding window,
  aggregated into coarse one-second buckets (memory = window seconds,
  not event count): tokens/s, admits/s, evictions/s for ``/metrics``.

Everything here is host-side stdlib Python: safe to import from
:mod:`flashmoe_tpu.utils.telemetry` without dragging jax along.
"""

from __future__ import annotations

import math
import time

#: below this count the sketch answers from an exact nearest-rank
#: buffer; at and beyond it the P² markers take over.  Keeps CI drills
#: (tens of requests) bit-comparable with the old exact percentiles.
EXACT_N = 64


class P2Quantile:
    """One target quantile via the P² algorithm: five markers whose
    heights converge on the q-quantile of the stream.  O(1) memory and
    O(1) per observation."""

    def __init__(self, q: float):
        if not 0.0 < q < 1.0:
            raise ValueError(f"quantile must be in (0, 1), got {q}")
        self.q = float(q)
        self._heights: list[float] = []        # marker heights (sorted)
        self._pos = [1.0, 2.0, 3.0, 4.0, 5.0]  # actual positions
        self._want = [1.0, 1.0 + 2 * q, 1.0 + 4 * q, 3.0 + 2 * q, 5.0]
        self._dwant = [0.0, q / 2.0, q, (1.0 + q) / 2.0, 1.0]
        self.n = 0

    def observe(self, value: float) -> None:
        v = float(value)
        self.n += 1
        h = self._heights
        if len(h) < 5:
            h.append(v)
            h.sort()
            return
        # locate the cell and bump marker positions
        if v < h[0]:
            h[0] = v
            k = 0
        elif v >= h[4]:
            h[4] = v
            k = 3
        else:
            k = 0
            while k < 3 and v >= h[k + 1]:
                k += 1
        for i in range(k + 1, 5):
            self._pos[i] += 1.0
        for i in range(5):
            self._want[i] += self._dwant[i]
        # adjust the three interior markers
        for i in (1, 2, 3):
            d = self._want[i] - self._pos[i]
            if (d >= 1.0 and self._pos[i + 1] - self._pos[i] > 1.0) or \
                    (d <= -1.0 and self._pos[i - 1] - self._pos[i] < -1.0):
                d = 1.0 if d >= 0 else -1.0
                hi = self._parabolic(i, d)
                if not h[i - 1] < hi < h[i + 1]:
                    hi = self._linear(i, d)
                h[i] = hi
                self._pos[i] += d

    def _parabolic(self, i: int, d: float) -> float:
        h, p = self._heights, self._pos
        return h[i] + d / (p[i + 1] - p[i - 1]) * (
            (p[i] - p[i - 1] + d) * (h[i + 1] - h[i])
            / (p[i + 1] - p[i])
            + (p[i + 1] - p[i] - d) * (h[i] - h[i - 1])
            / (p[i] - p[i - 1]))

    def _linear(self, i: int, d: float) -> float:
        h, p = self._heights, self._pos
        j = i + int(d)
        return h[i] + d * (h[j] - h[i]) / (p[j] - p[i])

    def value(self) -> float | None:
        if not self._heights:
            return None
        if len(self._heights) < 5:
            # tiny stream: nearest-rank over what we have
            s = sorted(self._heights)
            return s[min(len(s) - 1, int(self.q * len(s)))]
        return self._heights[2]


class QuantileSketch:
    """Streaming summary of one metric: exact count/sum/min/max plus a
    P² cell per target quantile, exact (nearest-rank) below
    :data:`EXACT_N` observations.

    Error bound: below ``EXACT_N`` observations the reported quantiles
    ARE the nearest-rank percentiles (the ``loadgen.pctl`` definition).
    Beyond, P² marker heights are always genuine observed-range values
    (clamped between the running min and max) and for well-behaved
    (unimodal, non-adversarial) streams the relative rank error is
    small — the classic P² result; tests/test_telemetry_plane.py gates
    a ~10% relative-value band on lognormal-ish latency data."""

    DEFAULT_QS = (0.5, 0.9, 0.99)

    def __init__(self, quantiles=DEFAULT_QS):
        self.quantiles = tuple(float(q) for q in quantiles)
        self._cells = {q: P2Quantile(q) for q in self.quantiles}
        self._exact: list[float] | None = []   # None once graduated
        self.n = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, value: float) -> None:
        v = float(value)
        self.total += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        for cell in self._cells.values():
            cell.observe(v)
        if self._exact is not None:
            self._exact.append(v)
            if len(self._exact) >= EXACT_N:
                self._exact = None            # bounded memory from here
        # count LAST: a scrape thread that sees n >= 1 must also see
        # the observation it counts (the first-scrape race class)
        self.n += 1

    def quantile(self, q: float) -> float | None:
        """The q-quantile estimate: nearest-rank exact below
        :data:`EXACT_N` observations, P² beyond (clamped to the
        observed [min, max])."""
        if not self.n:
            return None
        # bind once: the job thread may graduate the buffer to None
        # (64th observe) between a scrape thread's check and its read
        buf = self._exact
        if buf is not None:
            s = sorted(buf)
            if not s:                 # racing first observe: no data yet
                return None
            return s[min(len(s) - 1, int(q * len(s)))]
        cell = self._cells.get(float(q))
        if cell is None:
            # nearest tracked quantile stands in for an untracked ask
            qq = min(self.quantiles, key=lambda t: abs(t - q))
            cell = self._cells[qq]
        v = cell.value()
        return None if v is None else min(max(v, self.min), self.max)

    @property
    def mean(self) -> float | None:
        return self.total / self.n if self.n else None

    def summary(self) -> dict:
        if not self.n:
            return {"count": 0}
        out = {"count": self.n, "sum": self.total, "min": self.min,
               "max": self.max, "mean": self.total / self.n}
        for q in self.quantiles:
            out[f"p{int(q * 100)}"] = self.quantile(q)
        return out


class WindowedRate:
    """Events per second over a sliding window, bucketed at one-second
    granularity so memory is O(window seconds) regardless of event
    count.  ``add(n)`` records ``n`` events now; ``rate()`` is the
    window's per-second average."""

    def __init__(self, window_s: float = 30.0, clock=time.monotonic):
        if window_s <= 0:
            raise ValueError("window_s must be > 0")
        self.window_s = float(window_s)
        self._clock = clock
        self._buckets: dict[int, float] = {}
        self.total = 0.0

    def _prune(self, now: float) -> None:
        horizon = int(now - self.window_s)
        for k in [k for k in self._buckets if k < horizon]:
            del self._buckets[k]

    def add(self, n: float = 1.0) -> float:
        now = self._clock()
        b = int(now)
        self._buckets[b] = self._buckets.get(b, 0.0) + float(n)
        self.total += float(n)
        self._prune(now)
        return self.rate(now)

    def rate(self, now: float | None = None) -> float:
        now = self._clock() if now is None else now
        self._prune(now)
        if not self._buckets:
            return 0.0
        span = max(now - min(self._buckets), 1.0)
        return sum(self._buckets.values()) / min(span, self.window_s)
