"""Request-scoped distributed tracing for the serving engine.

The engine's telemetry used to be step-shaped (``serve_step`` flight
records) and event-shaped (``serve.admit`` / ``serve.evict`` /
``serve.retire`` decisions); nothing reconstructed ONE request's
end-to-end timeline — and an evicted request's life spans two (or more)
prefills with a queue gap in between, which no single span can show.

:class:`RequestTracer` closes that: a trace context (``trace_id``,
parent span ``serve.request``) is minted at ``serve.admit`` and
threaded through the whole lifecycle

    queued → admit → prefill → per-step decode
           → (evict → re-queue → re-prefill)* → retire

producing a contiguous per-request list of child spans:

* ``serve.queued`` — arrival (or eviction) to admission: the queue wait
  and every eviction gap (``resumed=True``), so preemption is VISIBLE
  as a hole in the decode train, not silently absorbed;
* ``serve.prefill`` — each prefill, captured via the existing telemetry
  span-listener hook (the tracer chains to whatever listener — e.g. a
  PR 8 :class:`PhaseTimeline` — was installed, so phase profiling and
  request tracing compose and their clocks share one origin);
* ``serve.decode`` — one span per decode step the request participated
  in, attributed through the same hook;
* ``serve.step`` — the full engine-step window every active request
  rode (begin_step → end_step): it covers the host work BETWEEN the
  jitted spans (sampling, page growth, first-call compiles), which is
  what makes a retired request's track contiguous rather than a comb
  of device slices with unexplained holes.

Export: :func:`flashmoe_tpu.profiler.export.request_trace_document`
renders one Perfetto track per request (``validate_trace``-gated);
:meth:`RequestTracer.export_jsonl` writes ``kind="serve_trace_span"``
records next to the flight/decision dumps, which ``python -m
flashmoe_tpu.observe --trace <rid>`` renders as a single request's
timeline.  :meth:`RequestTracer.validate` is the no-orphan /
contiguity gate the tests (and the drill CLI) run before trusting a
trace.

The tracer is pure host-side bookkeeping around the jitted calls: the
engine's token streams are bit-identical with it armed or not
(asserted by tests/test_serving.py).
"""

from __future__ import annotations

import json
import time

#: tolerated clock slack (ms) when checking track contiguity — spans
#: are stamped around host dispatch, so neighbours may be a hair apart
CONTIGUITY_SLACK_MS = 5.0


class _RequestState:
    """Mutable per-request trace under construction."""

    __slots__ = ("rid", "trace_id", "spans", "open_queued", "evictions",
                 "retired", "t_first", "t_last", "steps")

    def __init__(self, rid: int, trace_id: str, t0: float):
        self.rid = rid
        self.trace_id = trace_id
        self.spans: list[dict] = []
        self.open_queued: float | None = t0   # queue wait in progress
        self.evictions = 0
        self.retired = False
        self.t_first = t0
        self.t_last = t0
        self.steps = 0


class RequestTracer:
    """Span listener + lifecycle recorder.  Install with
    :meth:`install` (chains to the currently armed listener) or hand it
    to :class:`~flashmoe_tpu.serving.engine.ServingEngine` which does
    both ends of the lifecycle wiring."""

    def __init__(self, metrics_obj=None, clock=time.monotonic):
        self._clock = clock
        self._birth = clock()
        self._metrics = metrics_obj
        self._inner = None          # chained listener (PhaseTimeline)
        self._installed = False
        self.requests: dict[int, _RequestState] = {}
        # engine-set attribution context for listener spans
        self._prefill_rid: int | None = None
        self._active_rids: tuple[int, ...] = ()
        self._step: int | None = None
        self._step_t0: float | None = None
        self._joined_at: dict[int, float] = {}
        self._pending_retires: list = []

    # ---- clock --------------------------------------------------------

    def _now_ms(self) -> float:
        return (self._clock() - self._birth) * 1e3

    # ---- listener chaining -------------------------------------------

    def install(self) -> "RequestTracer":
        """Become the active telemetry span listener, forwarding to any
        previously armed one (a PhaseTimeline keeps working)."""
        from flashmoe_tpu.utils.telemetry import (
            get_span_listener, set_span_listener,
        )

        if not self._installed:
            self._inner = get_span_listener()
            set_span_listener(self)
            self._installed = True
        return self

    def uninstall(self) -> None:
        from flashmoe_tpu.utils.telemetry import (
            get_span_listener, set_span_listener,
        )

        if self._installed and get_span_listener() is self:
            set_span_listener(self._inner)
        self._installed = False
        self._inner = None

    # ---- the span-listener protocol ----------------------------------

    def span_enter(self, name: str):
        inner_tok = (self._inner.span_enter(name)
                     if self._inner is not None else None)
        return (self._now_ms(), inner_tok)

    def span_exit(self, name: str, tok) -> None:
        if tok is None:
            return
        t0, inner_tok = tok
        if self._inner is not None:
            self._inner.span_exit(name, inner_tok)
        now = self._now_ms()
        if name in ("serve.prefill", "serve.prefill_chunk",
                    "serve.handoff") and self._prefill_rid is not None:
            # serve.handoff nests inside serve.prefill (the fabric's
            # KV-page crossing), serve.prefill_chunk is armed per-slot
            # via on_prefill_chunk — all three attribute to the request
            # whose prompt is being prefilled
            self._span(self._prefill_rid, name, t0, now)
        elif name == "serve.decode":
            for rid in self._active_rids:
                self._span(rid, "serve.decode", t0, now)

    # ---- lifecycle events (called by the engine) ---------------------

    def on_arrival(self, rid: int) -> None:
        """The request's trace arrival step was reached: the queue-wait
        clock starts (TTFT base)."""
        if rid not in self.requests:
            self.requests[rid] = _RequestState(rid, "", self._now_ms())

    def on_admit(self, rid: int, step: int, resumed: bool) -> None:
        """Admission closes the open queued span; the first admission
        mints the trace id.  The engine runs its prefill immediately
        after, attributed to this rid via the listener hook."""
        now = self._now_ms()
        st = self.requests.get(rid)
        if st is None:
            st = self.requests[rid] = _RequestState(rid, "", now)
        if not st.trace_id:
            st.trace_id = f"req{rid:x}-{int(step):x}"
        if st.open_queued is not None:
            self._span(rid, "serve.queued", st.open_queued, now,
                       resumed=resumed)
            st.open_queued = None
        self._prefill_rid = rid
        self._step = int(step)
        # join the open step window from the admission instant on
        if self._step_t0 is not None and rid not in self._active_rids:
            self._active_rids = self._active_rids + (rid,)
            self._joined_at[rid] = now
            st.steps += 1

    def on_prefill_chunk(self, rid: int) -> None:
        """Arm prefill attribution for one mid-prefill slot before its
        ``serve.prefill_chunk`` span — chunked prefills interleave
        across slots, so the admission-time ``_prefill_rid`` context is
        stale by the time a later chunk runs."""
        self._prefill_rid = int(rid)

    def on_evict(self, rid: int, step: int) -> None:
        """Eviction re-opens the queued clock: the gap until the
        re-admission renders as a ``serve.queued`` span with
        ``resumed=True`` — the visible hole in the decode train.  The
        evictee LEAVES the open step window here: its ``serve.step``
        span closes at the eviction instant and the rest of the step
        (including the decode it no longer rides) is not attributed to
        it — decode slices must never overlap the eviction gap."""
        st = self.requests.get(rid)
        if st is None:
            return
        st.evictions += 1
        now = self._now_ms()
        if self._step_t0 is not None and rid in self._active_rids:
            t0 = self._joined_at.get(rid, self._step_t0)
            self._span(rid, "serve.step", t0, now)
            self._active_rids = tuple(r for r in self._active_rids
                                      if r != rid)
            self._joined_at.pop(rid, None)
        st.open_queued = now

    def begin_step(self, step: int, active_rids) -> None:
        """Engine step boundary (called at the TOP of the engine step,
        before arrivals/admissions): decode spans emitted by the
        listener hook until :meth:`end_step` belong to ``active_rids``
        plus any request admitted during the step, and each of them
        gets a ``serve.step`` window span when the step closes.  The
        window opening before ``_admit`` is what keeps a neighbour's
        prefill (or its first-call compile) from punching a hole in
        every other active request's track."""
        self._step = int(step)
        self._active_rids = tuple(int(r) for r in active_rids)
        self._prefill_rid = None
        self._step_t0 = self._now_ms()
        self._joined_at: dict[int, float] = {}
        for rid in self._active_rids:
            st = self.requests.get(rid)
            if st is not None:
                st.steps += 1

    def end_step(self) -> None:
        """Close the engine-step window: every request that rode this
        step gets a ``serve.step`` span covering it end to end — the
        contiguity filler over host sampling/compile time.  A request
        admitted mid-step starts its window at its admission instant,
        so the span never predates its queued span.  Retirements that
        happened during the step emit their ``serve.trace`` decision
        HERE, after the closing window span, so the decision's span
        count matches the finished track."""
        if self._step_t0 is None:
            return
        now = self._now_ms()
        for rid in self._active_rids:
            t0 = self._joined_at.get(rid, self._step_t0)
            self._span(rid, "serve.step", t0, now)
        self._step_t0 = None
        self._active_rids = ()
        self._joined_at = {}
        pending, self._pending_retires = self._pending_retires, []
        for rid, step, fields in pending:
            self._emit_trace_decision(rid, step, **fields)

    def on_retire(self, rid: int, step: int, *, tokens=None,
                  ttft_ms=None, tpot_ms=None) -> None:
        st = self.requests.get(rid)
        if st is None:
            return
        st.retired = True
        st.t_last = self._now_ms()
        fields = {"tokens": tokens, "ttft_ms": ttft_ms,
                  "tpot_ms": tpot_ms}
        if self._step_t0 is not None:
            # mid-step retire: the closing serve.step span is still
            # coming — decide at end_step so the count is final
            self._pending_retires.append((rid, int(step), fields))
        else:
            self._emit_trace_decision(rid, int(step), **fields)

    def _emit_trace_decision(self, rid: int, step: int, *, tokens=None,
                             ttft_ms=None, tpot_ms=None) -> None:
        st = self.requests.get(rid)
        if st is None or self._metrics is None:
            return
        self._metrics.decision(
            "serve.trace", rid=rid, trace_id=st.trace_id,
            step=step, spans=len(st.spans), steps=st.steps,
            evictions=st.evictions, tokens=tokens,
            ttft_ms=ttft_ms, tpot_ms=tpot_ms,
            dur_ms=round(st.t_last - st.t_first, 3))

    # ---- recording ---------------------------------------------------

    def _span(self, rid: int, name: str, t0: float, t1: float,
              **extra) -> None:
        st = self.requests.get(rid)
        if st is None:
            return
        st.t_last = max(st.t_last, t1)
        st.spans.append({
            "name": name, "rid": rid, "trace_id": st.trace_id,
            "ts_ms": round(t0, 6),
            "dur_ms": round(max(t1 - t0, 1e-6), 6),
            "step": self._step, **extra,
        })

    # ---- views -------------------------------------------------------

    def request_track(self, rid: int) -> list[dict]:
        """One request's spans in timeline order (the per-request
        Perfetto track, and what ``observe --trace`` renders)."""
        st = self.requests.get(rid)
        if st is None:
            return []
        return sorted(st.spans, key=lambda s: s["ts_ms"])

    def validate(self) -> list[str]:
        """The no-orphan / contiguity gate.  Empty list = every retired
        request reconstructs to a contiguous track: it starts with a
        queued span, every gap between consecutive spans is covered
        (within :data:`CONTIGUITY_SLACK_MS`), every eviction shows up
        as a ``resumed`` queued span, and no span belongs to an unknown
        request."""
        problems: list[str] = []
        for rid, st in sorted(self.requests.items()):
            track = self.request_track(rid)
            if not st.retired:
                continue
            if not track:
                problems.append(f"request {rid}: retired with no spans")
                continue
            if not st.trace_id:
                problems.append(f"request {rid}: no trace_id minted")
            if track[0]["name"] != "serve.queued":
                problems.append(
                    f"request {rid}: track starts with "
                    f"{track[0]['name']!r}, not serve.queued")
            gaps = [s for s in track if s["name"] == "serve.queued"
                    and s.get("resumed")]
            if len(gaps) != st.evictions:
                problems.append(
                    f"request {rid}: {st.evictions} evictions but "
                    f"{len(gaps)} resumed queued spans")
            end = None
            for s in track:
                if s.get("rid") != rid:
                    problems.append(f"request {rid}: orphan span "
                                    f"{s['name']} tagged rid={s.get('rid')}")
                if end is not None \
                        and s["ts_ms"] - end > CONTIGUITY_SLACK_MS:
                    problems.append(
                        f"request {rid}: {s['ts_ms'] - end:.3f} ms "
                        f"uncovered gap before {s['name']} at "
                        f"{s['ts_ms']:.3f}")
                end = max(end or 0.0, s["ts_ms"] + s["dur_ms"])
        return problems

    # ---- export ------------------------------------------------------

    def records(self) -> list[dict]:
        """Flight-recorder-shaped records (``kind="serve_trace_span"``),
        the JSONL form ``observe --trace`` consumes."""
        out = []
        for rid in sorted(self.requests):
            st = self.requests[rid]
            for s in self.request_track(rid):
                out.append({"kind": "serve_trace_span",
                            "evictions": st.evictions,
                            "retired": st.retired, **s})
        return out

    def export_jsonl(self, path: str) -> int:
        recs = self.records()
        with open(path, "w") as f:
            for rec in recs:
                f.write(json.dumps(rec) + "\n")
        return len(recs)
