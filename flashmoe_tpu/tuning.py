"""Per-generation kernel tuning table.

The reference encodes per-architecture execution geometry in a constexpr
trait table — blocks/SM, pipeline stages, tile sizes per (arch, register
budget) (``csrc/include/flashmoe/arch.cuh:95-222``).  The TPU analogue is
this table: measured winners for the Pallas kernels' block sizes keyed by
(generation, kernel, shape), consulted at trace time, with the existing
size-derived heuristics as the fallback when no measurement matches.

The table is populated by ``scripts/tune_sweep.py`` running on real
hardware (winners are committed to ``flashmoe_tpu/tuning_data/<gen>.json``
so they ship with the package); entries are ignored with a warning if
they stopped dividing the shapes they claim to match.

Knobs per kernel family:

  capacity_ffn   block_m (row tile), block_i (intermediate chunk) of the
                 grouped capacity-buffer / gather-fused FFN kernels
                 (``ops/expert.py:_capacity_tiling``).
  fused_ep       cm (slab row tile), bi_cap (streamed-weight chunk cap),
                 weights_resident (bool: per-source two-pass schedule),
                 batched (bool: arrival-batched expert-major schedule —
                 overrides the d>=3 default either way) of the fused
                 RDMA kernel (``parallel/fused.py:_fused_schedule``).
"""

from __future__ import annotations

import functools
import json
import os
import warnings

_DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "tuning_data")


def generation() -> str:
    """Current TPU generation, resolved without touching the backend (a
    wedged remote tunnel must not hang trace-time tuning lookups):
    FLASHMOE_TPU_GEN overrides, then the axon plugin's generation pin,
    else v5e."""
    return (os.environ.get("FLASHMOE_TPU_GEN")
            or os.environ.get("PALLAS_AXON_TPU_GEN", "v5e"))


@functools.lru_cache(maxsize=8)
def _load(gen: str) -> list:
    """Measured entries for a generation: a list of
    ``{"kernel": ..., "match": {...}, "set": {...}, "measured_ms": ...}``
    dicts, most-specific first.  FLASHMOE_TUNING_FILE overrides the
    committed per-generation file."""
    path = os.environ.get("FLASHMOE_TUNING_FILE") or os.path.join(
        _DATA_DIR, f"{gen}.json")
    if not os.path.exists(path):
        return []
    try:
        with open(path) as f:
            doc = json.load(f)
        return list(doc.get("entries", []))
    except (OSError, ValueError) as e:  # unreadable table = no tuning
        warnings.warn(f"ignoring unreadable tuning table {path}: {e}")
        return []


def lookup(kernel: str, gen: str | None = None, **shape) -> dict:
    """Measured knob overrides for ``kernel`` at ``shape`` (h=, i=, cap=,
    dtype=...), or {} when nothing matches.  An entry matches when every
    key in its ``match`` dict equals the corresponding shape value; among
    matches the one constraining the most keys wins regardless of file
    order, so a hand-added generic entry cannot shadow a more specific
    measured one (advisor r4 #3)."""
    gen = gen or generation()
    best = None
    for ent in _load(gen):
        if ent.get("kernel") != kernel:
            continue
        m = ent.get("match", {})
        if all(shape.get(k) == v for k, v in m.items()):
            if best is None or len(m) > len(best[0]):
                best = (m, dict(ent.get("set", {})))
    return best[1] if best else {}


def measured_path_latencies(gen: str | None = None, **shape) -> dict:
    """Measured end-to-end path latencies for ``shape`` (h=, i=, e=, k=,
    s=, d=, dtype=...): ``{path_name: measured_ms}``.

    Entries use ``kernel: "path_latency"`` with the path name inside the
    ``match`` dict (so the generic most-specific-match machinery applies
    per path) and the timing in ``measured_ms``::

        {"kernel": "path_latency",
         "match": {"path": "fused", "h": 2048, "i": 2048, "d": 8},
         "measured_ms": 2.71}

    The ``wire`` / ``wire_combine`` keys (EP payload compression,
    ``MoEConfig.wire_dtype``) and the ``chunks`` key (chunked a2a
    pipeline depth, ``MoEConfig.a2a_chunks``) are matched STRICTLY
    with implicit ``"off"`` / ``1`` defaults on both sides: a latency
    measured with compression or chunking on is never applied to a run
    without it — and a legacy entry without the keys never applies to
    a compressed/chunked one.

    The planner's measured-winner override
    (:mod:`flashmoe_tpu.planner.select`) consults this: a committed
    bench/tune_sweep measurement beats any prediction for the paths it
    covers.  Unknown shapes return {} and the roofline prediction stands.
    """
    gen = gen or generation()
    best: dict[str, tuple[int, float]] = {}
    for ent in _load(gen):
        if ent.get("kernel") != "path_latency":
            continue
        m = dict(ent.get("match", {}))
        path = m.pop("path", None)
        ms = ent.get("measured_ms", ent.get("set", {}).get("measured_ms"))
        if path is None or ms is None:
            continue
        if any(str(m.pop(wk, dv)) != str(shape.get(wk, dv))
               for wk, dv in (("wire", "off"), ("wire_combine", "off"),
                              ("chunks", 1))):
            continue
        if all(shape.get(kk) == v for kk, v in m.items()):
            if path not in best or len(m) > best[path][0]:
                best[path] = (len(m), float(ms))
    return {p: ms for p, (_, ms) in best.items()}


def save_entries(gen: str, entries: list, path: str | None = None) -> str:
    """Write a measured table (used by scripts/tune_sweep.py).  Replaces
    existing entries for the same (kernel, match) keys, keeps others."""
    path = path or os.path.join(_DATA_DIR, f"{gen}.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    old = []
    if os.path.exists(path):
        try:
            with open(path) as f:
                old = json.load(f).get("entries", [])
        except (OSError, ValueError):
            old = []
    keyof = lambda e: (e.get("kernel"),
                       tuple(sorted(e.get("match", {}).items())))
    new_keys = {keyof(e) for e in entries}
    merged = entries + [e for e in old if keyof(e) not in new_keys]
    with open(path, "w") as f:
        json.dump({"generation": gen, "entries": merged}, f, indent=1,
                  sort_keys=True)
    _load.cache_clear()
    return path
