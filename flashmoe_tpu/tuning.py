"""Per-generation kernel tuning table.

The reference encodes per-architecture execution geometry in a constexpr
trait table — blocks/SM, pipeline stages, tile sizes per (arch, register
budget) (``csrc/include/flashmoe/arch.cuh:95-222``).  The TPU analogue is
this table: measured winners for the Pallas kernels' block sizes keyed by
(generation, kernel, shape), consulted at trace time, with the existing
size-derived heuristics as the fallback when no measurement matches.

The table is populated by ``scripts/tune_sweep.py`` running on real
hardware (winners are committed to ``flashmoe_tpu/tuning_data/<gen>.json``
so they ship with the package); entries are ignored with a warning if
they stopped dividing the shapes they claim to match.

Knobs per kernel family:

  capacity_ffn   block_m (row tile), block_i (intermediate chunk) of the
                 grouped capacity-buffer / gather-fused FFN kernels
                 (``ops/expert.py:_capacity_tiling``).
  fused_ep       cm (slab row tile), bi_cap (streamed-weight chunk cap),
                 weights_resident (bool: per-source two-pass schedule),
                 batched (bool: arrival-batched expert-major schedule —
                 overrides the d>=3 default either way), rowwin (bool:
                 row-windowed K-streamed schedule — overrides the
                 stream-vs-rowwin byte heuristic either way) of the
                 fused RDMA kernel (``parallel/fused.py:
                 _fused_schedule``).
  fused_tiles    cm (row tile), kw (K-window width) of the rowwin
                 schedule's IO-aware tile chooser
                 (``parallel/fused.py:_rowwin_tiles``) — a measured
                 entry overrides the analytic minimum-HBM-traffic pick
                 when it still divides the shapes; the VMEM budget gate
                 is never overridable.  Swept by ``tune_sweep.py
                 --stage tiles`` / ``bench.py --tiles``.

Committed tables must pass :func:`validate_entries` — a malformed table
fails ``tests/test_tuning.py`` in CI instead of being silently ignored
at trace time (the runtime ``_load`` stays lenient so a corrupt file on
a production host degrades to heuristics, never to a crash).
"""

from __future__ import annotations

import functools
import json
import os
import warnings

_DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "tuning_data")


def generation() -> str:
    """Current TPU generation, resolved without touching the backend (a
    wedged remote tunnel must not hang trace-time tuning lookups):
    FLASHMOE_TPU_GEN overrides, then the axon plugin's generation pin,
    else v5e."""
    return (os.environ.get("FLASHMOE_TPU_GEN")
            or os.environ.get("PALLAS_AXON_TPU_GEN", "v5e"))


@functools.lru_cache(maxsize=8)
def _load(gen: str) -> list:
    """Measured entries for a generation: a list of
    ``{"kernel": ..., "match": {...}, "set": {...}, "measured_ms": ...}``
    dicts, most-specific first.  FLASHMOE_TUNING_FILE overrides the
    committed per-generation file."""
    path = os.environ.get("FLASHMOE_TUNING_FILE") or os.path.join(
        _DATA_DIR, f"{gen}.json")
    if not os.path.exists(path):
        return []
    try:
        with open(path) as f:
            doc = json.load(f)
        return list(doc.get("entries", []))
    except (OSError, ValueError) as e:  # unreadable table = no tuning
        warnings.warn(f"ignoring unreadable tuning table {path}: {e}")
        return []


def lookup(kernel: str, gen: str | None = None, **shape) -> dict:
    """Measured knob overrides for ``kernel`` at ``shape`` (h=, i=, cap=,
    dtype=...), or {} when nothing matches.  An entry matches when every
    key in its ``match`` dict equals the corresponding shape value; among
    matches the one constraining the most keys wins regardless of file
    order, so a hand-added generic entry cannot shadow a more specific
    measured one (advisor r4 #3)."""
    gen = gen or generation()
    best = None
    for ent in _load(gen):
        if ent.get("kernel") != kernel:
            continue
        m = ent.get("match", {})
        if all(shape.get(k) == v for k, v in m.items()):
            if best is None or len(m) > len(best[0]):
                best = (m, dict(ent.get("set", {})))
    return best[1] if best else {}


def measured_path_latencies(gen: str | None = None, **shape) -> dict:
    """Measured end-to-end path latencies for ``shape`` (h=, i=, e=, k=,
    s=, d=, dtype=...): ``{path_name: measured_ms}``.

    Entries use ``kernel: "path_latency"`` with the path name inside the
    ``match`` dict (so the generic most-specific-match machinery applies
    per path) and the timing in ``measured_ms``::

        {"kernel": "path_latency",
         "match": {"path": "fused", "h": 2048, "i": 2048, "d": 8},
         "measured_ms": 2.71}

    The ``wire`` / ``wire_combine`` / ``wire_dcn`` keys (EP payload
    compression, ``MoEConfig.wire_dtype`` family — ``wire_dcn`` is the
    cross-slice hop override), the ``chunks`` key (chunked a2a
    pipeline depth, ``MoEConfig.a2a_chunks``), the ``quant`` key
    (quantized expert weight store, ``MoEConfig.expert_quant``) and
    the ``spec`` key (speculative verify span, ``"v<k>"`` for a
    ``verify_tokens=k`` decode measurement) are matched STRICTLY with
    implicit ``"off"`` / ``1`` defaults on both sides: a latency
    measured with compression, chunking, int8 weights, or a
    speculative span on is never applied to a run without it — and a
    legacy entry without the keys never applies to one that has them.

    The planner's measured-winner override
    (:mod:`flashmoe_tpu.planner.select`) consults this: a committed
    bench/tune_sweep measurement beats any prediction for the paths it
    covers.  Unknown shapes return {} and the roofline prediction stands.
    """
    gen = gen or generation()
    best: dict[str, tuple[int, float]] = {}
    for ent in _load(gen):
        if ent.get("kernel") != "path_latency":
            continue
        m = dict(ent.get("match", {}))
        path = m.pop("path", None)
        ms = ent.get("measured_ms", ent.get("set", {}).get("measured_ms"))
        if path is None or ms is None:
            continue
        if any(str(m.pop(wk, dv)) != str(shape.get(wk, dv))
               for wk, dv in (("wire", "off"), ("wire_combine", "off"),
                              ("wire_dcn", "off"), ("chunks", 1),
                              ("quant", "off"), ("spec", "off"))):
            continue
        if all(shape.get(kk) == v for kk, v in m.items()):
            if path not in best or len(m) > best[path][0]:
                best[path] = (len(m), float(ms))
    return {p: ms for p, (_, ms) in best.items()}


#: known kernel families and the knob keys their ``set`` dict may carry
#: (``path_latency`` entries carry the timing in ``measured_ms`` and the
#: path identity inside ``match`` instead of a ``set``)
ENTRY_SCHEMA = {
    "capacity_ffn": {"block_m", "block_i"},
    "fused_ep": {"cm", "bi_cap", "weights_resident", "batched",
                 "rowwin"},
    "fused_tiles": {"cm", "kw"},
    "path_latency": set(),
}

#: keys an entry ``match`` dict may constrain (shape facts + the
#: measurement-identity knobs the lookups compare strictly)
MATCH_KEYS = {"h", "i", "e", "k", "s", "d", "cap", "dtype", "path",
              "wire", "wire_combine", "wire_dcn", "chunks", "quant",
              "spec"}


def validate_entries(doc) -> list[str]:
    """Schema-validate a tuning table document (the parsed JSON of a
    ``tuning_data/<gen>.json`` file).  Returns a list of problem
    strings, empty when the table is well-formed.  CI runs this over
    every committed table (``tests/test_tuning.py``) so a malformed
    entry — unknown kernel, misspelled knob, non-numeric measurement —
    fails review instead of being silently ignored by the lenient
    runtime loader."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return [f"table must be a JSON object, got {type(doc).__name__}"]
    entries = doc.get("entries")
    if not isinstance(entries, list):
        return ["table must carry an 'entries' list"]
    if "generation" in doc and not isinstance(doc["generation"], str):
        problems.append("'generation' must be a string")
    for n, ent in enumerate(entries):
        where = f"entries[{n}]"
        if not isinstance(ent, dict):
            problems.append(f"{where}: entry must be an object")
            continue
        kernel = ent.get("kernel")
        if kernel not in ENTRY_SCHEMA:
            problems.append(
                f"{where}: unknown kernel {kernel!r}; known: "
                f"{sorted(ENTRY_SCHEMA)}")
            continue
        match = ent.get("match", {})
        if not isinstance(match, dict):
            problems.append(f"{where}: 'match' must be an object")
            match = {}
        for mk, mv in match.items():
            if mk not in MATCH_KEYS:
                problems.append(
                    f"{where}: unknown match key {mk!r}; known: "
                    f"{sorted(MATCH_KEYS)}")
            elif mk in ("dtype", "path", "wire", "wire_combine",
                        "wire_dcn", "quant", "spec"):
                if not isinstance(mv, str):
                    problems.append(
                        f"{where}: match.{mk} must be a string, got "
                        f"{mv!r}")
            elif not isinstance(mv, int) or isinstance(mv, bool) \
                    or mv < 1:
                problems.append(
                    f"{where}: match.{mk} must be a positive int, got "
                    f"{mv!r}")
        ms = ent.get("measured_ms",
                     ent.get("set", {}).get("measured_ms")
                     if isinstance(ent.get("set"), dict) else None)
        if kernel == "path_latency":
            if "path" not in match:
                problems.append(
                    f"{where}: path_latency needs match.path")
            if not isinstance(ms, (int, float)) or ms <= 0:
                problems.append(
                    f"{where}: path_latency needs a positive "
                    f"measured_ms, got {ms!r}")
            continue
        st = ent.get("set")
        if not isinstance(st, dict) or not st:
            problems.append(
                f"{where}: {kernel} needs a non-empty 'set' object")
            continue
        allowed = ENTRY_SCHEMA[kernel]
        for sk, sv in st.items():
            if sk not in allowed:
                problems.append(
                    f"{where}: unknown {kernel} knob {sk!r}; known: "
                    f"{sorted(allowed)}")
            elif sk in ("weights_resident", "batched", "rowwin"):
                if not isinstance(sv, bool):
                    problems.append(
                        f"{where}: set.{sk} must be a bool, got {sv!r}")
            elif not isinstance(sv, int) or isinstance(sv, bool) \
                    or sv < 1:
                problems.append(
                    f"{where}: set.{sk} must be a positive int, got "
                    f"{sv!r}")
        if kernel == "fused_tiles" and not {"cm", "kw"} <= set(st):
            problems.append(
                f"{where}: fused_tiles must set both cm and kw (a "
                f"half-specified tile pair cannot override the "
                f"IO-aware chooser)")
        if "measured_ms" in ent and (
                not isinstance(ent["measured_ms"], (int, float))
                or ent["measured_ms"] <= 0):
            problems.append(
                f"{where}: measured_ms must be a positive number, got "
                f"{ent['measured_ms']!r}")
    return problems


def validate_table(path: str) -> list[str]:
    """:func:`validate_entries` over a table file; unreadable/unparsable
    files are themselves a problem (CI-facing — the runtime loader's
    lenient warning stance is unchanged)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable table {path}: {e}"]
    return validate_entries(doc)


def save_entries(gen: str, entries: list, path: str | None = None) -> str:
    """Write a measured table (used by scripts/tune_sweep.py).  Replaces
    existing entries for the same (kernel, match) keys, keeps others."""
    path = path or os.path.join(_DATA_DIR, f"{gen}.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    old = []
    if os.path.exists(path):
        try:
            with open(path) as f:
                old = json.load(f).get("entries", [])
        except (OSError, ValueError):
            old = []
    keyof = lambda e: (e.get("kernel"),
                       tuple(sorted(e.get("match", {}).items())))
    new_keys = {keyof(e) for e in entries}
    merged = entries + [e for e in old if keyof(e) not in new_keys]
    with open(path, "w") as f:
        json.dump({"generation": gen, "entries": merged}, f, indent=1,
                  sort_keys=True)
    _load.cache_clear()
    return path
