"""Utilities: telemetry, debug helpers, math."""
