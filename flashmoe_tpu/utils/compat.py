"""JAX version-compatibility shims.

The codebase targets current jax, where ``jax.shard_map`` is a public
top-level API and the replication check is spelled ``check_vma``.  Pinned
container images may carry an older release where shard_map still lives
in ``jax.experimental.shard_map`` and the same knob is ``check_rep`` —
without this shim every shard_map-based layer (ep / fused / ragged /
pipeline / ring attention / DCN probe) dies on AttributeError before it
can trace.  One resolution point keeps the seven call sites identical on
both versions.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` where available, else the experimental API with
    ``check_vma`` mapped onto its older ``check_rep`` spelling."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` where available; on older releases the
    constant-folded ``psum(1, axis)`` idiom yields the same static int
    inside shard_map bodies."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)
