"""JAX version-compatibility shims.

The codebase targets current jax, where ``jax.shard_map`` is a public
top-level API and the replication check is spelled ``check_vma``.  Pinned
container images may carry an older release where shard_map still lives
in ``jax.experimental.shard_map`` and the same knob is ``check_rep`` —
without this shim every shard_map-based layer (ep / fused / ragged /
pipeline / ring attention / DCN probe) dies on AttributeError before it
can trace.  One resolution point keeps the seven call sites identical on
both versions.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` where available, else the experimental API with
    ``check_vma`` mapped onto its older ``check_rep`` spelling."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` where available; on older releases the
    constant-folded ``psum(1, axis)`` idiom yields the same static int
    inside shard_map bodies."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


#: trace classes that build a jaxpr instead of executing — values under
#: them are abstract, so host-clock instants taken there are TRACE time
_ABSTRACT_TRACE_NAMES = frozenset(
    {"DynamicJaxprTrace", "JaxprTrace", "DynamicJaxprTrace2"})


def under_abstract_trace() -> bool:
    """True when an abstract (jaxpr-building) trace is active on this
    thread — i.e. the code is being TRACED by ``jit``/``make_jaxpr``,
    not executed.  ``jax.core.trace_state_clean()`` alone cannot answer
    this: an *eager* ``shard_map`` body also runs under a trace
    (ShardMapTrace, plus a RewriteTrace for the replication check), but
    its values are concrete per-device arrays and its wall clock is
    real execution time.  Walks the ``parent_trace`` chain looking for
    a jaxpr-building trace; unknown machinery (no chain to walk while
    a trace is active) is conservatively reported abstract."""
    import jax.core as jax_core

    try:
        if jax_core.trace_state_clean():
            return False
    except Exception:  # pragma: no cover - ancient jax
        return False
    try:
        from jax._src.core import trace_ctx

        trace = trace_ctx.trace
    except Exception:  # pragma: no cover - trace machinery moved again
        return True
    hops = 0
    while trace is not None and hops < 16:
        if type(trace).__name__ in _ABSTRACT_TRACE_NAMES:
            return True
        trace = getattr(trace, "parent_trace", None)
        hops += 1
    return False


def concrete_leaf(leaf):
    """The concrete array under ``leaf``, or ``None`` if it is abstract.

    Eager shard_map values arrive as tracer onions —
    ``RewriteTracer(ShardMapTracer(ArrayImpl))`` — whose ``.val`` chain
    bottoms out at a blockable concrete array; under an abstract trace
    the chain ends at a valueless tracer instead."""
    v = leaf
    hops = 0
    while v is not None and hops < 16:
        try:
            if hasattr(v, "block_until_ready"):
                return v
            v = getattr(v, "val", None)
        except Exception:  # noqa: BLE001 — tracer attr access can raise
            return None
        hops += 1
    return None
