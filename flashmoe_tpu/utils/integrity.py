"""Shared CRC32 content-checksum helpers.

One implementation behind every integrity seam in the repo:

* the checkpoint manifests (:mod:`flashmoe_tpu.runtime.checkpoint`)
  checksum each payload file with :func:`crc32_file` — per-file sizes +
  CRC32s in ``manifest-<step>.json``, verified before a restore hands
  bytes to orbax;
* the KV-handoff transport (:mod:`flashmoe_tpu.fabric.transport`)
  checksums each transfer's page-granular byte chunks with
  :func:`crc32_pages` — the per-page checksum sidecar that rides the
  wire frames the way the ``_qscale`` scales ride the page payloads,
  so a corrupted transfer is detected at the receiver and retried
  instead of silently decoding garbage into the paged cache.

Everything here is :func:`zlib.crc32` — cheap, deterministic, and good
enough to catch bit flips and truncation (the faults the chaos drills
inject); it is an integrity check, not an authenticity one.
"""

from __future__ import annotations

import zlib


def crc32_bytes(data: bytes, crc: int = 0) -> int:
    """CRC32 of a byte string, chainable via ``crc`` (the
    :func:`zlib.crc32` running-checksum convention)."""
    return zlib.crc32(data, crc)


def crc32_file(path: str, chunk: int = 1 << 20) -> int:
    """Chunked CRC32 of a file's content (constant memory — checkpoint
    payloads are GB-scale)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                return crc
            crc = zlib.crc32(b, crc)


def crc32_pages(data: bytes, pages: int) -> tuple[int, ...]:
    """Per-page CRC32 sidecar of a serialized payload: the buffer is
    split into ``pages`` contiguous chunks (the last absorbs the
    remainder) and each is checksummed independently, so a receiver can
    name WHICH page of a transfer was corrupted, not just that one
    was."""
    pages = max(1, int(pages))
    if not data:
        return tuple(zlib.crc32(b"") for _ in range(pages))
    step = max(1, len(data) // pages)
    out = []
    for i in range(pages):
        lo = i * step
        hi = (i + 1) * step if i < pages - 1 else len(data)
        out.append(zlib.crc32(data[lo:hi]))
    return tuple(out)
