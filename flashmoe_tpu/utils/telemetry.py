"""Tracing / profiling / metrics.

The reference instruments with NVTX scoped ranges in a "Flashmoe" domain
around every host phase (``csrc/include/flashmoe/telemetry.cuh:16-21``,
used throughout ``bootstrap.cuh``/``moe.cuh``), inline ``%globaltimer``
reads inside kernels, and cudaEvent kernel timing.  TPU equivalents:

  * :func:`trace_span` — ``jax.profiler.TraceAnnotation`` +
    ``jax.named_scope``: shows up both in host traces and as HLO op-name
    prefixes in xprof;
  * :func:`start_trace` / :func:`stop_trace` — whole-program profiler
    capture for tensorboard/xprof (the SM-utilization analogue: MXU
    utilization comes from the captured trace);
  * :class:`Metrics` — lightweight host-side counters/timers with JSONL
    export (the reference's per-rank ``fmt::println`` timings, structured).
"""

from __future__ import annotations

import contextlib
import json
import time
from collections import defaultdict

import jax


@contextlib.contextmanager
def trace_span(name: str):
    """Named scope visible in xprof traces and HLO metadata."""
    with jax.profiler.TraceAnnotation(name):
        with jax.named_scope(name):
            yield


def start_trace(log_dir: str):
    jax.profiler.start_trace(log_dir)


def stop_trace():
    jax.profiler.stop_trace()


@contextlib.contextmanager
def capture_trace(log_dir: str):
    start_trace(log_dir)
    try:
        yield
    finally:
        stop_trace()


class Metrics:
    """Host-side metrics registry: counters, gauges, wall timers, and
    structured decision records (planner path selections, schedule
    choices — anything a postmortem needs the full breakdown of, not
    just a scalar)."""

    def __init__(self):
        self.counters: dict[str, float] = defaultdict(float)
        self.gauges: dict[str, float] = {}
        self.times: dict[str, list[float]] = defaultdict(list)
        self.decisions: list[dict] = []

    def count(self, name: str, inc: float = 1.0):
        self.counters[name] += inc

    def gauge(self, name: str, value: float):
        self.gauges[name] = float(value)

    def decision(self, name: str, **fields) -> dict:
        """Record a structured decision (e.g. the planner's path choice
        with its full latency breakdown).  Kept as a list so repeated
        decisions (one per layer/config) are all visible; ``summary()``
        reports the count per decision name."""
        rec = {"decision": name, **fields}
        self.decisions.append(rec)
        self.counters[f"decision.{name}"] += 1
        return rec

    def last_decision(self, name: str) -> dict | None:
        for rec in reversed(self.decisions):
            if rec["decision"] == name:
                return rec
        return None

    @contextlib.contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.times[name].append(time.perf_counter() - t0)

    def summary(self) -> dict:
        out: dict[str, float] = dict(self.counters)
        out.update(self.gauges)
        for k, v in self.times.items():
            if v:
                s = sorted(v)
                out[f"{k}_ms_p50"] = s[len(s) // 2] * 1e3
                out[f"{k}_ms_sum"] = sum(v) * 1e3
                out[f"{k}_calls"] = len(v)
        return out

    def dump_jsonl(self, path: str, **extra):
        rec = dict(self.summary(), **extra)
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        return rec

    def dump_decisions_jsonl(self, path: str) -> int:
        """Append every recorded decision (full breakdowns) as JSONL."""
        with open(path, "a") as f:
            for rec in self.decisions:
                f.write(json.dumps(rec) + "\n")
        return len(self.decisions)


metrics = Metrics()
