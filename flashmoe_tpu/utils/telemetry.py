"""Tracing / profiling / metrics.

The reference instruments with NVTX scoped ranges in a "Flashmoe" domain
around every host phase (``csrc/include/flashmoe/telemetry.cuh:16-21``,
used throughout ``bootstrap.cuh``/``moe.cuh``), inline ``%globaltimer``
reads inside kernels, and cudaEvent kernel timing.  TPU equivalents:

  * :func:`trace_span` — ``jax.profiler.TraceAnnotation`` +
    ``jax.named_scope``: shows up both in host traces and as HLO op-name
    prefixes in xprof; the ep and fused MoE layers wrap their gate /
    dispatch / a2a / expert / combine phases so traces read like the
    reference's NVTX domain;
  * :func:`start_trace` / :func:`stop_trace` — whole-program profiler
    capture for tensorboard/xprof (the SM-utilization analogue: MXU
    utilization comes from the captured trace);
  * :class:`Metrics` — lightweight host-side counters/gauges/timers/
    histograms with JSONL export and Prometheus text exposition (the
    reference's per-rank ``fmt::println`` timings, structured);
  * :class:`FlightRecorder` — a bounded per-step ring buffer of
    structured records (the in-graph MoE stats of
    :mod:`flashmoe_tpu.ops.stats`, losses, step timings) with JSONL
    export, summarized offline by ``python -m flashmoe_tpu.observe``.
"""

from __future__ import annotations

import bisect
import contextlib
import json
import math
import os
import re
import time
import warnings
from collections import defaultdict, deque

import jax

#: Central decision-name registry.  Every ``Metrics.decision("x.y",
#: ...)`` literal in the codebase must be declared here with a one-line
#: meaning — a typo'd name used to vanish silently into the JSONL
#: stream.  Enforced two ways: :meth:`Metrics.decision` warns (and
#: counts ``decision.unregistered``) at runtime, and the static lint
#: pass (``python -m flashmoe_tpu.staticcheck --lint``) fails CI on any
#: unregistered literal.  The table in docs/OBSERVABILITY.md is
#: generated from this dict (:func:`decision_table_markdown`) and the
#: lint's doc-sync rule keeps the two aligned.
DECISION_NAMES: dict[str, str] = {
    "bootstrap.groups":
        "the Decider formed DP x EP groups from the measured/mocked "
        "slice topology at bootstrap",
    "checkpoint.async_error":
        "a background async save failed (surfaced, not raised)",
    "checkpoint.emergency_save":
        "last good state persisted on an abort path",
    "checkpoint.fallback":
        "restore demoted a corrupt step to an older intact one",
    "controller.cooldown":
        "a trigger fired during cooldown (or planned a noop) and was "
        "suppressed",
    "controller.demotion_reset":
        "a restart cleared path demotions earned on the dead topology",
    "controller.morph":
        "the self-healing controller re-selected the MoE path mid-job",
    "controller.probe_error":
        "the slow-trigger throughput re-probe failed; re-placement "
        "degraded to uniform rates",
    "controller.replace":
        "the self-healing controller re-placed/replicated experts "
        "mid-job",
    "controller.replica_morph":
        "the controller drained (sustained-idle fabric) or returned "
        "(sustained queue pressure) a decode replica in the fabric "
        "router's rotation",
    "controller.spec_morph":
        "the controller switched speculative decoding off after the "
        "fleet acceptance EMA ran below the planner's break-even "
        "acceptance for the debounce window (token streams unchanged "
        "by construction — the morph costs zero tokens)",
    "controller.wire_morph":
        "the controller flipped the DCN-hop wire dtype after sustained "
        "a2a-leg dominance on a multi-slice job",
    "fabric.handoff":
        "a prefill KV run crossed to a decode replica as wire-coded "
        "pages: payload size, modeled DCN cost, and whether it hides "
        "under the decode pool's per-step objective",
    "fabric.handoff_drift":
        "measured-vs-priced reconciliation for one KV handoff on the "
        "virtual clock: measured DCN (modeled + chaos), hidden/exposed "
        "split against the decode tick, and whether the measured "
        "overlap verdict agrees with the priced one",
    "fabric.handoff_corrupt":
        "a KV-handoff transfer failed its per-page CRC32 verify at the "
        "receiver: which pages were corrupted, on which attempt — the "
        "bytes never reach the paged cache",
    "fabric.handoff_retry":
        "the handoff transport retransmitted a failed transfer "
        "(corrupt or timed out): attempt number, wasted wire ms, "
        "capped-exponential backoff, remaining retry budget",
    "fabric.heartbeat_miss":
        "a decode replica with pending work advanced no heartbeat seq "
        "across one fabric-step observation: consecutive miss count "
        "and remaining deadline budget before a stall is declared",
    "fabric.heartbeat_stall":
        "the heartbeat watchdog declared a replica stalled MID-STEP "
        "(its probe still answers — only the sub-step heartbeat "
        "deadline catches a hang): last published phase/seq and the "
        "detection latency in virtual decode ms",
    "fabric.migrate":
        "a crashed replica's request moved to a survivor: the resumed "
        "prompt carries every delivered token, so the deterministic "
        "re-prefill replays the token stream bit-equal",
    "fabric.replica_crash":
        "the fabric's health probes detected a dead decode replica: "
        "in-flight and queued victim counts, surviving rotation",
    "fabric.partition":
        "the KV wire dropped a transfer mid-stream (injected "
        "net_partition, or a real kernel-socket reset on the tcp "
        "wire): bytes that never crossed, attempt number — the "
        "receiver discarded the partial transfer at the short read",
    "fabric.route":
        "the replica router placed a request (session affinity or "
        "join-shortest-queue over live /healthz depths)",
    "frontdoor.brownout":
        "the front door's hysteretic overload detector changed state "
        "(enter/exit): queue pressure vs thresholds, debounce/cooldown "
        "/budget bookkeeping (PR 9 controller discipline)",
    "frontdoor.failover":
        "a dead front-door peer's namespace lease moved to a survivor: "
        "shard, old/new owner, bumped epoch",
    "frontdoor.fence":
        "the external lease store REFUSED a stale-epoch lease write: "
        "the claimant's fencing token is not newer than the stored "
        "epoch — the split-brain guard (a zombie door cannot take a "
        "shard back)",
    "frontdoor.lease_repair":
        "the lease store found a torn tail (a writer died mid-append) "
        "and rolled the log back to the last intact CRC-framed "
        "record: torn bytes dropped, restored epoch",
    "frontdoor.shed":
        "a brownout admission verdict: the arriving request was shed "
        "(rejected) or degraded (token budget capped) instead of "
        "joining an overloaded fleet",
    "frontdoor.submit":
        "the fabric front door accepted a request into the fleet-wide "
        "trace namespace and recorded the router's placement",
    "planner.backend_constraint":
        "auto pick demoted to a backend the config can actually run",
    "planner.drift":
        "measured latency compared against the analytical prediction",
    "planner.fallback":
        "a failed execution path was demoted for the process",
    "planner.overlap_drift":
        "measured overlap fraction compared against the chunked bound",
    "planner.path_select":
        "moe_backend='auto' resolved a path (full latency breakdown)",
    "planner.scaleout":
        "the planner traded EP-across-DCN against DP-across-DCN for a "
        "multi-slice job",
    "preempt.drain":
        "graceful drain completed: final step, remaining grace",
    "preempt.notice":
        "a preemption notice arrived (signal source, grace budget)",
    "regress.detected":
        "the perf sentry found a metric past its tolerance vs the "
        "rolling baseline in obs/history.jsonl",
    "planner.phase_drift":
        "one MoE phase's measured time compared against its prediction",
    "postmortem.saved":
        "a crash postmortem bundle was written (dir, error, step)",
    "serve.admit":
        "the serving engine admitted a request into the decode batch",
    "serve.attribution":
        "one retired request's measured latency decomposed into "
        "critical-path components (queue wait, router spill, prefill, "
        "handoff DCN, decode, eviction gaps) with the dominant "
        "contributor named; components sum to the span within 1%",
    "serve.evict":
        "page pressure preempted the youngest request back to the "
        "queue (its pages freed, delivered tokens stand)",
    "serve.plan":
        "the engine resolved its prefill- and decode-priced execution "
        "plans (decode priced at per-step token counts)",
    "serve.pools":
        "prefill/decode pool split over the inference-mode Decider "
        "(heterogeneous groups, no allreduce term)",
    "serve.quant":
        "the serving engine loaded a quantized expert state: store "
        "dtype, freed HBM, and the extra KV-cache pages that headroom "
        "buys (flashmoe_tpu/quant/)",
    "serve.retire":
        "a request completed (stop token or max length) with its "
        "TTFT/TPOT (plus per-request draft-acceptance stats when "
        "speculation is configured)",
    "serve.spec":
        "speculative decoding lifecycle: armed at engine build, "
        "morph_on/morph_off at a controller (or operator) toggle — "
        "with the SpecConfig knobs or the morph reason",
    "serve.trace":
        "a request's trace closed at retirement: trace_id, span count, "
        "evictions, end-to-end duration (telemetry_plane/tracing.py)",
    "slo.breach":
        "a step/phase time exceeded its SLO budget",
    "slo.recovered":
        "a breached SLO target returned under budget",
    "supervisor.resume":
        "a restart resumed: incarnation, step, world size, ep x dp",
    "telemetry.server_start":
        "the live telemetry scrape server came up (bound port)",
    "telemetry.server_stop":
        "the live telemetry scrape server shut down",
    "trainer.grad_skip":
        "tier 1 skipped an anomalous update in-graph",
}

#: Central span-name registry — the trace_span / profiler-section
#: analogue of :data:`DECISION_NAMES`.  Every literal handed to
#: :func:`trace_span` or to a profiler ``section(...)`` must be declared
#: here (chunked pipeline spans append a numeric suffix to a registered
#: base: ``moe.expert.3``); the staticcheck lint
#: (``python -m flashmoe_tpu.staticcheck --lint``) flags typo'd or
#: computed literals, because a misspelled span silently forks the phase
#: timeline the cost ledger joins on.  The docs/OBSERVABILITY.md span
#: table is generated from this dict (:func:`span_table_markdown`).
SPAN_NAMES: dict[str, str] = {
    "moe.gate": "router: logits, top-k selection, aux losses",
    "moe.dispatch": "scatter tokens into the exchange layout",
    "moe.a2a_dispatch":
        "dispatch all-to-all (``.k`` suffix = pipeline chunk k)",
    "moe.expert": "expert FFN on received rows (``.k`` = chunk k)",
    "moe.a2a_combine":
        "return all-to-all (``.k`` suffix = pipeline chunk k)",
    "moe.combine": "weighted gather back to token order",
    "moe.fused_kernel": "fused RDMA kernel (dispatch+FFN in one launch)",
    "serve.prefill":
        "serving engine: single-pass prompt prefill into cache pages",
    "serve.prefill_chunk":
        "serving engine: one fixed-budget chunk of an admitted "
        "prompt's incremental prefill (chunked admission)",
    "serve.handoff":
        "fabric: a prefill KV run's page codec round-trip on its way "
        "to the decode replica",
    "serve.decode":
        "serving engine: one continuous-batching decode step",
    "serve.draft":
        "serving engine: host-side n-gram drafting over the per-slot "
        "suffix-match tables (speculative decode's propose phase)",
    "serve.verify":
        "serving engine: one speculative verify forward scoring "
        "draft_tokens+1 positions per slot (replaces serve.decode on "
        "steps where anything was drafted)",
    "serve.queued":
        "request trace: queue wait from arrival (or eviction — "
        "``resumed``) to admission; the visible eviction gap",
    "serve.request":
        "request trace: the parent span of one request's whole "
        "lifecycle (trace_id minted at serve.admit)",
    "serve.step":
        "request trace: the full engine-step window a request rode "
        "(covers host sampling/compile between the jitted spans)",
    "train.data_pull": "host wait on the data iterator",
    "train.step": "one train step: dispatch + device execution",
    "train.checkpoint": "checkpoint save on the step loop",
    "train.drain": "graceful preemption drain (final save + cursor)",
}


def register_span(name: str, meaning: str) -> None:
    """Declare a span name at runtime (plugins / experiments).  Repo
    code should add to :data:`SPAN_NAMES` directly so the static lint
    and the docs table see it."""
    SPAN_NAMES[name] = meaning


def span_table_markdown() -> str:
    """The docs/OBSERVABILITY.md span table, generated from the
    registry (the staticcheck doc-sync rule keeps the doc aligned)."""
    lines = ["| span | meaning |", "|------|---------|"]
    for name in sorted(SPAN_NAMES):
        lines.append(f"| `{name}` | {SPAN_NAMES[name]} |")
    return "\n".join(lines)


def register_decision(name: str, meaning: str) -> None:
    """Declare a decision name at runtime (plugins / experiments).
    Repo code should add to :data:`DECISION_NAMES` directly so the
    static lint and the docs table see it."""
    DECISION_NAMES[name] = meaning


def decision_table_markdown() -> str:
    """The docs/OBSERVABILITY.md decision table, generated from the
    registry (single source of truth; the staticcheck doc-sync rule
    verifies the doc carries every name)."""
    lines = ["| decision | meaning |", "|----------|---------|"]
    for name in sorted(DECISION_NAMES):
        lines.append(f"| `{name}` | {DECISION_NAMES[name]} |")
    return "\n".join(lines)


#: Active span listener (one slot): an object with ``span_enter(name)
#: -> token`` / ``span_exit(name, token)``, installed by the phase
#: profiler (:mod:`flashmoe_tpu.profiler.spans`) while a timeline is
#: armed.  ``None`` (default) keeps :func:`trace_span` exactly the
#: metadata-only context manager it always was.
_SPAN_LISTENER: list = [None]


def set_span_listener(listener) -> None:
    """Install (or, with ``None``, remove) the span listener the phase
    profiler uses to turn trace_span sites into a host-side timeline."""
    _SPAN_LISTENER[0] = listener


def get_span_listener():
    """The currently armed listener (None when nothing is armed) — the
    request tracer (telemetry_plane/tracing.py) chains to it so phase
    profiling and request tracing compose."""
    return _SPAN_LISTENER[0]


@contextlib.contextmanager
def trace_span(name: str):
    """Named scope visible in xprof traces and HLO metadata.  When a
    phase-profiler timeline is armed (:func:`set_span_listener`), the
    span's host enter/exit instants are additionally recorded — the
    xprof-free phase timeline of :mod:`flashmoe_tpu.profiler`."""
    lst = _SPAN_LISTENER[0]
    tok = lst.span_enter(name) if lst is not None else None
    try:
        with jax.profiler.TraceAnnotation(name):
            with jax.named_scope(name):
                yield
    finally:
        if lst is not None:
            lst.span_exit(name, tok)


def start_trace(log_dir: str):
    jax.profiler.start_trace(log_dir)


def stop_trace():
    jax.profiler.stop_trace()


@contextlib.contextmanager
def capture_trace(log_dir: str):
    start_trace(log_dir)
    try:
        yield
    finally:
        stop_trace()


class Histogram:
    """Fixed-bucket histogram with percentile estimates and
    Prometheus-compatible cumulative buckets.

    Default bounds span 1 µs – 1000 ms style magnitudes (1-2.5-5 decades)
    — wide enough for both per-step seconds and per-phase milliseconds
    without configuration; pass explicit ``buckets`` when the quantity
    has a known range."""

    DEFAULT_BUCKETS = tuple(
        m * 10.0 ** e for e in range(-3, 4) for m in (1.0, 2.5, 5.0)
    )

    def __init__(self, buckets=None):
        self.buckets = tuple(sorted(buckets)) if buckets \
            else self.DEFAULT_BUCKETS
        # counts[i] = observations <= buckets[i] (exclusive of earlier
        # buckets); counts[-1] = overflow (> buckets[-1])
        self.counts = [0] * (len(self.buckets) + 1)
        self.n = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, value: float):
        v = float(value)
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.n += 1
        self.total += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)

    def percentile(self, q: float) -> float:
        """Approximate q-quantile (0..1) from the bucket boundaries."""
        if not self.n:
            return 0.0
        target = q * self.n
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target and c:
                hi = self.buckets[i] if i < len(self.buckets) else self.max
                return min(hi, self.max)
        return self.max

    def summary(self) -> dict:
        if not self.n:
            return {"count": 0}
        return {
            "count": self.n, "sum": self.total,
            "min": self.min, "max": self.max,
            "mean": self.total / self.n,
            "p50": self.percentile(0.5), "p99": self.percentile(0.99),
        }


class FlightRecorder:
    """Bounded ring buffer of per-step structured records — the
    postmortem black box.  Old steps fall off the back, so a recorder
    left attached to a long run costs O(capacity) memory forever; export
    dumps whatever the window still holds.

    Capacity: explicit argument, else ``FLASHMOE_FLIGHT_CAPACITY``,
    else 1024 steps."""

    def __init__(self, capacity: int | None = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get(
                    "FLASHMOE_FLIGHT_CAPACITY", "1024"))
            except ValueError:
                capacity = 1024
        self._buf: deque = deque(maxlen=max(1, int(capacity)))
        self._total = 0  # records ever recorded (ring wraps don't reset)

    @property
    def capacity(self) -> int:
        return self._buf.maxlen

    @property
    def records(self) -> list[dict]:
        return list(self._buf)

    @property
    def total_recorded(self) -> int:
        """Records ever recorded, including ones the ring has dropped —
        the absolute-index space the offset-aware export speaks."""
        return self._total

    def __len__(self) -> int:
        return len(self._buf)

    def record(self, **fields) -> dict:
        rec = dict(fields)
        self._buf.append(rec)
        self._total += 1
        return rec

    def export_jsonl(self, path: str, start: int | None = None,
                     metrics_obj: "Metrics | None" = None) -> int:
        """Export records as JSONL.

        ``start=None`` (legacy): snapshot — truncate ``path`` and write
        every record the ring still holds; returns the count written.

        ``start=<int>``: offset-aware export (the
        :meth:`Metrics.dump_decisions_jsonl` convention): write every
        record with absolute index >= ``start`` that the ring still
        holds, and return the total record count — the next call's
        ``start``.  ``start == 0`` (the cursor's initial value) starts
        a FRESH file, so a stale artifact from an earlier run never
        contaminates this one; ``start > 0`` appends.  A periodic
        flusher passing the previous return value therefore writes each
        record exactly once, and records that rotate out of the bounded
        ring BETWEEN flushes are already on disk instead of silently
        discarded (the mode-"w" data-loss bug this closes).  Records
        that rotated out before ever being flushed are unrecoverable;
        the gap is counted as ``flight.export_lost`` in ``metrics_obj``
        (the global stream by default) so the loss is visible."""
        if start is None:
            with open(path, "w") as f:
                for rec in self._buf:
                    f.write(json.dumps(rec) + "\n")
            return len(self._buf)
        oldest = self._total - len(self._buf)  # abs index of buf[0]
        lost = max(0, oldest - max(start, 0))
        if lost:
            sink = metrics_obj if metrics_obj is not None else metrics
            sink.count("flight.export_lost", lost)
        first = max(start - oldest, 0)
        with open(path, "w" if start <= 0 else "a") as f:
            for i, rec in enumerate(self._buf):
                if i >= first:
                    f.write(json.dumps(rec) + "\n")
        return self._total


#: the content type every Prometheus text-exposition response must
#: carry (the 0.0.4 text format) — the scrape server
#: (telemetry_plane/server.py) sends exactly this on ``/metrics``
PROM_CONTENT_TYPE = "text/plain; version=0.0.4"


def _prom_name(name: str) -> str:
    """Sanitize to the Prometheus metric-name grammar
    ``[a-zA-Z_:][a-zA-Z0-9_:]*``."""
    n = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not n or n[0].isdigit():
        n = "_" + n
    return n


def escape_label_value(value) -> str:
    """Exposition-spec escaping for a label VALUE: backslash, newline,
    and double-quote must be escaped (in that order — escaping the
    backslash first keeps ``\\n`` from double-encoding), or a hostile
    value (a path with quotes, a reason string with newlines) breaks
    every parser downstream of ``/metrics``."""
    return (str(value).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _snapshot(obj, copy=dict):
    """Copy a registry container that another thread may be growing.

    Even a plain ``dict(d)`` / ``list(d.items())`` can raise
    "dictionary changed size during iteration" when the job thread
    inserts a new key mid-copy (observed under a scrape-hammer on
    CPython 3.10) — retry until a consistent copy lands; under the GIL
    a handful of attempts always suffices."""
    for _ in range(64):
        try:
            return copy(obj)
        except RuntimeError:
            continue
    return copy(obj)    # last try: surface the error if truly stuck


def _prom_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_prom_name(str(k))}="{escape_label_value(v)}"'
        for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Metrics:
    """Host-side metrics registry: counters, gauges, wall timers,
    histograms, and structured decision records (planner path
    selections, schedule choices — anything a postmortem needs the full
    breakdown of, not just a scalar)."""

    def __init__(self):
        self.counters: dict[str, float] = defaultdict(float)
        self.gauges: dict[str, float] = {}
        self.times: dict[str, list[float]] = defaultdict(list)
        self.histograms: dict[str, Histogram] = {}
        self.sketches: dict = {}          # name -> QuantileSketch
        # name -> {sorted (label, value) tuple -> gauge value}
        self.labeled_gauges: dict[str, dict[tuple, float]] = {}
        self.decisions: list[dict] = []

    def count(self, name: str, inc: float = 1.0):
        self.counters[name] += inc

    def gauge(self, name: str, value: float):
        self.gauges[name] = float(value)

    def labeled_gauge(self, name: str, value: float, **labels):
        """A gauge with label dimensions (one value per label set) —
        e.g. ``labeled_gauge("serve.rate", 120.0, kind="tokens")``.
        Label VALUES are exposition-escaped at render time, so hostile
        strings (quotes, newlines, backslashes) cannot corrupt
        ``/metrics``."""
        key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        self.labeled_gauges.setdefault(name, {})[key] = float(value)

    def histogram(self, name: str, value: float, buckets=None):
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram(buckets)
        h.observe(value)
        return h

    def sketch(self, name: str, value: float, quantiles=None):
        """Observe ``value`` on the named streaming quantile sketch
        (telemetry_plane/sketch.py): O(1)-memory rolling p50/p90/p99
        instead of a full-history percentile list — the live plane's
        replacement for unbounded TTFT/TPOT retention.  Rendered as a
        Prometheus summary by :meth:`prometheus_text`."""
        s = self.sketches.get(name)
        if s is None:
            from flashmoe_tpu.telemetry_plane.sketch import QuantileSketch

            s = self.sketches[name] = QuantileSketch(
                quantiles or QuantileSketch.DEFAULT_QS)
        s.observe(value)
        return s

    def decision(self, name: str, **fields) -> dict:
        """Record a structured decision (e.g. the planner's path choice
        with its full latency breakdown).  Kept as a list so repeated
        decisions (one per layer/config) are all visible; ``summary()``
        reports the count per decision name.

        Unregistered names (not in :data:`DECISION_NAMES`) are recorded
        anyway — losing the record would be worse — but warn and count
        ``decision.unregistered``, so a typo is visible instead of
        silently forking the JSONL stream."""
        if name not in DECISION_NAMES:
            self.counters["decision.unregistered"] += 1
            warnings.warn(
                f"unregistered decision name {name!r}: declare it in "
                f"flashmoe_tpu/utils/telemetry.py:DECISION_NAMES (the "
                f"staticcheck lint gates literals in-repo)",
                RuntimeWarning, stacklevel=2)
        rec = {"decision": name, **fields}
        self.decisions.append(rec)
        self.counters[f"decision.{name}"] += 1
        return rec

    def last_decision(self, name: str) -> dict | None:
        for rec in reversed(self.decisions):
            if rec["decision"] == name:
                return rec
        return None

    @contextlib.contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.times[name].append(time.perf_counter() - t0)

    def summary(self) -> dict:
        out: dict[str, float] = dict(self.counters)
        out.update(self.gauges)
        for k, v in self.times.items():
            if v:
                s = sorted(v)
                out[f"{k}_ms_p50"] = s[len(s) // 2] * 1e3
                out[f"{k}_ms_sum"] = sum(v) * 1e3
                out[f"{k}_calls"] = len(v)
        for k, h in self.histograms.items():
            for stat, val in h.summary().items():
                out[f"{k}_{stat}"] = val
        for k, s in self.sketches.items():
            for stat, val in s.summary().items():
                if val is not None:
                    out[f"{k}_{stat}"] = val
        return out

    def prometheus_text(self, prefix: str = "flashmoe") -> str:
        """Prometheus text-exposition (format 0.0.4) rendering of the
        registry: counters as ``*_total``, gauges (labeled included),
        timers and quantile sketches as summaries, histograms with
        cumulative ``le`` buckets.  Every family carries its ``# HELP``
        and ``# TYPE`` lines and every label value is spec-escaped
        (:func:`escape_label_value`); serve it with
        :data:`PROM_CONTENT_TYPE` (the scrape server does).

        Renders from SHALLOW SNAPSHOTS of the registry dicts: the
        scrape server calls this from its own thread while the job
        thread registers new metrics, and iterating the live dicts
        would intermittently raise "dictionary changed size during
        iteration" (an HTTP 500 on the first scrape that races a
        first-time counter/sketch)."""
        lines: list[str] = []
        counters = _snapshot(self.counters)
        gauges = _snapshot(self.gauges)
        labeled = {k: _snapshot(v)
                   for k, v in _snapshot(self.labeled_gauges).items()}
        times = {k: _snapshot(v, list)
                 for k, v in _snapshot(self.times).items()}
        sketches = _snapshot(self.sketches)
        histograms = _snapshot(self.histograms)

        def fmt(v: float) -> str:
            return repr(float(v))

        def family(n: str, kind: str, desc: str):
            lines.append(f"# HELP {n} {escape_label_value(desc)}")
            lines.append(f"# TYPE {n} {kind}")

        for name in sorted(counters):
            n = f"{prefix}_{_prom_name(name)}_total"
            family(n, "counter", f"flashmoe counter {name}")
            lines.append(f"{n} {fmt(counters[name])}")
        for name in sorted(gauges):
            n = f"{prefix}_{_prom_name(name)}"
            family(n, "gauge", f"flashmoe gauge {name}")
            lines.append(f"{n} {fmt(gauges[name])}")
        for name in sorted(labeled):
            series = labeled[name]
            n = f"{prefix}_{_prom_name(name)}"
            family(n, "gauge", f"flashmoe gauge {name}")
            for key in sorted(series):
                lines.append(f"{n}{_prom_labels(dict(key))} "
                             f"{fmt(series[key])}")
        for name in sorted(times):
            v = times[name]
            if not v:
                continue
            n = f"{prefix}_{_prom_name(name)}_seconds"
            s = sorted(v)
            family(n, "summary", f"flashmoe timer {name} (seconds)")
            lines += [
                f'{n}{{quantile="0.5"}} {fmt(s[len(s) // 2])}',
                f"{n}_sum {fmt(sum(v))}",
                f"{n}_count {len(v)}",
            ]
        for name in sorted(sketches):
            sk = sketches[name]
            if not sk.n:
                continue
            n = f"{prefix}_{_prom_name(name)}"
            family(n, "summary",
                   f"flashmoe streaming quantile sketch {name}")
            for q in sk.quantiles:
                val = sk.quantile(q)
                if val is not None:
                    lines.append(f'{n}{{quantile="{q:g}"}} {fmt(val)}')
            lines.append(f"{n}_sum {fmt(sk.total)}")
            lines.append(f"{n}_count {sk.n}")
        for name in sorted(histograms):
            h = histograms[name]
            n = f"{prefix}_{_prom_name(name)}"
            family(n, "histogram", f"flashmoe histogram {name}")
            cum = 0
            for bound, c in zip(h.buckets, h.counts):
                cum += c
                lines.append(f'{n}_bucket{{le="{bound:g}"}} {cum}')
            lines.append(f'{n}_bucket{{le="+Inf"}} {h.n}')
            lines.append(f"{n}_sum {fmt(h.total)}")
            lines.append(f"{n}_count {h.n}")
        return "\n".join(lines) + "\n"

    def dump_jsonl(self, path: str, **extra):
        rec = dict(self.summary(), **extra)
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        return rec

    def dump_decisions_jsonl(self, path: str, start: int = 0) -> int:
        """Append recorded decisions (full breakdowns) as JSONL from
        index ``start`` on — callers that flush repeatedly (bench sweeps)
        pass the previous return value so no decision is written twice.
        Returns the total decision count (the next call's ``start``)."""
        with open(path, "a") as f:
            for rec in self.decisions[start:]:
                f.write(json.dumps(rec) + "\n")
        return len(self.decisions)


metrics = Metrics()
