"""Grouped-GEMM sweep bench — the reference's ``gb`` benchmark
(``csrc/benchmarks/gemm_bench.cu``: sweeps sizes comparing the custom tile
GEMM against cuBLAS/MatX with isclose error % + times) re-done for the
grouped Pallas FFN kernel vs the XLA batched einsum.

Usage:
  python scripts/gemm_bench.py                  # real TPU, timed
  python scripts/gemm_bench.py --correctness    # any backend, error % only

Prints one JSON line per size point.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))))

from flashmoe_tpu.config import MoEConfig  # noqa: E402
from flashmoe_tpu.models.reference import init_moe_params  # noqa: E402
from flashmoe_tpu.ops.expert import (  # noqa: E402
    _capacity_tiling, capacity_buffer_ffn_pallas, expert_ffn_dense,
    grouped_ffn_tokens,
)

RTOL, ATOL = 2e-2, 2e-3  # the reference's isclose tolerances


def _bench_point(e, c, h, i, dtype, correctness, trials=3, chain=8):
    cfg = MoEConfig(num_experts=e, expert_top_k=1, hidden_size=h,
                    intermediate_size=i, dtype=dtype,
                    param_dtype=jnp.float32)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    params = jax.tree_util.tree_map(lambda p: p.astype(dtype), params)
    xs = jax.random.normal(jax.random.PRNGKey(1), (e, c, h), dtype)

    interpret = jax.default_backend() != "tpu"
    got = capacity_buffer_ffn_pallas(xs, params, cfg, interpret=interpret)
    want = expert_ffn_dense(xs, params, cfg)
    g32, w32 = got.astype(jnp.float32), want.astype(jnp.float32)
    mism = float(jnp.mean(
        (jnp.abs(g32 - w32) > ATOL + RTOL * jnp.abs(w32)).astype(jnp.float32)
    )) * 100.0

    # gather-fused variant: same slabs, rows pulled in-kernel from a flat
    # token array through an identity-ish index plane
    bm, cp, block_i = _capacity_tiling(c)
    x_flat = xs.reshape(e * c, h)
    src_tok = jnp.arange(e * c, dtype=jnp.int32).reshape(e, c)
    src_tok = jnp.pad(src_tok, ((0, 0), (0, cp - c))).reshape(-1)
    tile_gid = jnp.arange(e * (cp // bm), dtype=jnp.int32) // (cp // bm)

    def gather_ffn(xf, p, c_):
        y = grouped_ffn_tokens(
            xf, src_tok, tile_gid, p["w_up"].astype(xf.dtype), p["b_up"],
            p["w_down"].astype(xf.dtype), p["b_down"], None,
            act_name=c_.hidden_act, gated=False, block_m=bm,
            block_i=block_i, interpret=interpret)
        return y.reshape(e, cp, h)[:, :c, :]

    gog = gather_ffn(x_flat, params, cfg).astype(jnp.float32)
    mism_g = float(jnp.mean(
        (jnp.abs(gog - w32) > ATOL + RTOL * jnp.abs(w32)).astype(jnp.float32)
    )) * 100.0
    mism = max(mism, mism_g)
    rec = {
        "E": e, "rows": c, "H": h, "I": i,
        "dtype": jnp.dtype(dtype).name,
        "mismatch_pct": round(mism, 4),
        "backend": jax.default_backend(),
    }
    if not correctness and not interpret:
        def timed(fn):
            def run(p, xs):
                def body(xs, _):
                    return fn(xs, p, cfg).astype(xs.dtype), None
                xs, _ = jax.lax.scan(body, xs, None, length=chain)
                return xs.astype(jnp.float32).sum()
            f = jax.jit(run)
            float(f(params, xs))
            ts = []
            for _ in range(trials):
                t0 = time.perf_counter()
                float(f(params, xs))
                ts.append(time.perf_counter() - t0)
            return sorted(ts)[len(ts) // 2] / chain

        tp = timed(lambda xs, p, c_: capacity_buffer_ffn_pallas(xs, p, c_))
        tx = timed(expert_ffn_dense)

        def timed_flat(fn):
            def run(p, xf):
                def body(xf, _):
                    return fn(xf, p, cfg).reshape(e * c, h).astype(
                        xf.dtype), None
                xf, _ = jax.lax.scan(body, xf, None, length=chain)
                return xf.astype(jnp.float32).sum()
            f = jax.jit(run)
            float(f(params, x_flat))
            ts = []
            for _ in range(trials):
                t0 = time.perf_counter()
                float(f(params, x_flat))
                ts.append(time.perf_counter() - t0)
            return sorted(ts)[len(ts) // 2] / chain

        tg = timed_flat(gather_ffn)
        flops = 2 * e * c * 2 * h * i
        rec.update(
            pallas_ms=round(tp * 1e3, 3), xla_ms=round(tx * 1e3, 3),
            gather_fused_ms=round(tg * 1e3, 3),
            pallas_tflops=round(flops / tp / 1e12, 1),
            gather_tflops=round(flops / tg / 1e12, 1),
        )
    print(json.dumps(rec), flush=True)
    return mism


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--correctness", action="store_true",
                    help="error check only (works on CPU interpret)")
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32"])
    args = ap.parse_args()
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32

    sizes = [
        (4, 128, 256, 256),
        (8, 256, 512, 512),
        (8, 256, 1024, 4096),
        (16, 256, 2048, 2048),
        (64, 256, 2048, 2048),   # the reference's headline shape
        (8, 512, 4096, 14336),   # Mixtral expert shape
    ]
    if jax.default_backend() != "tpu":
        sizes = sizes[:2]  # interpreter-mode DMAs are slow; small shapes only
    worst = 0.0
    for e, c, h, i in sizes:
        worst = max(worst, _bench_point(e, c, h, i, dtype, args.correctness))
    print(json.dumps({"worst_mismatch_pct": round(worst, 4)}), flush=True)
    return 0 if worst < 1.0 else 1


if __name__ == "__main__":
    sys.exit(main())
