#!/bin/bash
# Watch the tunneled TPU backend; the moment it answers, run the full
# hardware pipeline and save every output.
#
# Four consecutive rounds of driver bench capture produced value:-1
# ("backend probe hung" — BENCH_r01..r04.json), so round 5 keeps a
# timestamped probe transcript (PROBE_r05.log) to make any further outage
# attributable to the environment, and arms an automatic capture so no
# up-window is missed (VERDICT.md round-4 ask #1, the standing order).
#
# Usage: bash scripts/probe_watch.sh [interval_s] [probe_timeout_s]
set -u
cd "$(dirname "$0")/.."
INTERVAL=${1:-240}
PTIMEOUT=${2:-90}
LOG=PROBE_r05.log
OUTDIR=HWLOG_r05
mkdir -p "$OUTDIR"

attempt=0
while true; do
  attempt=$((attempt + 1))
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  out=$(timeout "$PTIMEOUT" python -c \
    "import jax, jax.numpy as jnp; print(jax.default_backend(), float(jnp.ones(8).sum()))" \
    2>&1)
  rc=$?   # 124 = hung past the timeout; anything else is python's own exit
  out=$(printf '%s\n' "$out" | grep -v -E "WARNING|INFO|WARN" | tail -1)
  if [ $rc -eq 0 ] && echo "$out" | grep -q "8.0"; then
    echo "$ts attempt=$attempt OK: $out" >> "$LOG"
    echo "$ts backend is UP — running hardware pipeline" >> "$LOG"
    # Headline bench FIRST: the window may be short, the number is the
    # round's #1 deliverable, and every unvalidated new kernel is opt-in
    # so bench only exercises hardware-proven paths.  Then the full
    # validation sweep and the decision benches.  Each leg is
    # individually time-bounded so one hang cannot eat the whole window.
    run_leg() {  # run_leg <name> <timeout_s> <cmd...>
      local name=$1 tmo=$2; shift 2
      timeout "$tmo" "$@" > "$OUTDIR/$name.log" 2>&1
      local rc=$?
      echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) $name rc=$rc" >> "$LOG"
    }
    run_leg bench 1800 python bench.py
    run_leg tpu_validate 1800 python scripts/tpu_validate.py
    run_leg stage_bench 1800 python scripts/stage_bench.py
    run_leg stage_bench_explicit 1800 python scripts/stage_bench.py --path explicit
    run_leg combine_modes 1200 python scripts/stage_bench.py --path combine
    run_leg tune_sweep 2400 python scripts/tune_sweep.py
    run_leg bench_weak256 1800 python bench.py --config weak_scaling_256
    # commit whatever the window produced, so results survive even if
    # the session's turns ran out before contact
    git add "$OUTDIR" flashmoe_tpu/tuning_data "$LOG" 2>> "$LOG"
    git -c user.name=distsys-graft \
        -c user.email=distsys-graft@users.noreply.github.com \
        commit -q -m "Hardware window captured: $OUTDIR (bench, validate, stage benches, tune sweep)" \
        >> "$LOG" 2>&1 || true
    exit 0
  fi
  echo "$ts attempt=$attempt DOWN rc=$rc: ${out:-<no output>}" >> "$LOG"
  sleep "$INTERVAL"
done
