"""Skew-cost experiment: ring-order vs arrival-order slab processing.

The reference's subscriber consumes expert packets in whatever order they
physically arrive (``csrc/include/flashmoe/os/subscriber.cuh:333-451``);
the fused TPU kernel processes source slabs in a STATIC order (default
ring) because Mosaic semaphores cannot be polled without blocking.  This
script quantifies what that costs when links are skewed, using a
discrete-event model of the kernel's phase-1/phase-2 protocol:

  * every source's slab RDMA is issued asynchronously at t=0; slab s -> d
    arrives at alpha[s,d] + beta[s,d] * slab_mb;
  * each rank then processes sources sequentially (one grid step per
    source, compute t_c per slab); source q starts at
    max(prev_step_done, arrival_q); the own slab is local (arrival 0);
  * makespan of rank r = when its last slab finishes.

Orders compared:
  ring    — src_order[r, s] = (r+s) mod D (the kernel's default);
  pred    — :func:`flashmoe_tpu.parallel.topology.arrival_order` (sorted
            by the alpha-beta estimate — what a heterogeneous deployment
            should pass to ``fused_ep_moe_layer``);
  oracle  — sorted by true arrival times (the reference's dynamic
            subscriber, unattainable statically).

Empirical bound (asserted across every swept case, see
``tests/test_fused.py::test_arrival_order_and_skew_bounds``): for any
processing order

    makespan(order) - makespan(oracle) <= max_arrival - min_arrival

i.e. a mispredicted order can stall at most one full arrival spread —
one slow link cannot cascade beyond the slabs actually behind it.  On a
homogeneous torus ring == oracle (zero cost); under a skewed link the
predicted order recovers the oracle makespan whenever the alpha-beta
estimate ranks sources like the true arrivals do.

Usage: python scripts/skew_sim.py [--d 8] [--tc-ms 0.3] [--slab-mb 4]
Prints one JSON line per (case, skew-factor) point.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flashmoe_tpu.parallel.topology import Adjacency, arrival_order


def makespan(arrivals: np.ndarray, order: np.ndarray, t_c: float) -> float:
    """Sequential processing of sources in ``order`` with release times
    ``arrivals``: step j starts at max(prev done, arrival[order[j]])."""
    t = 0.0
    for q in order:
        t = max(t, float(arrivals[q])) + t_c
    return t


def rank_arrivals(adj: Adjacency, r: int, slab_mb: float) -> np.ndarray:
    a = np.array([adj.transfer_ms(s, r, slab_mb) for s in range(adj.n)])
    a[r] = 0.0  # own slab: local copy, effectively immediate
    return a


def simulate(adj_true: Adjacency, adj_est: Adjacency, slab_mb: float,
             t_c: float) -> dict:
    """Worst-rank makespan for ring / predicted / oracle orders, plus the
    empirical stall bound (max arrival spread)."""
    n = adj_true.n
    ring = np.array([[(r + s) % n for s in range(n)] for r in range(n)],
                    dtype=np.int32)
    pred = arrival_order(adj_est, slab_mb)
    out = {"ring": 0.0, "pred": 0.0, "oracle": 0.0, "spread": 0.0}
    for r in range(n):
        arr = rank_arrivals(adj_true, r, slab_mb)
        others = np.delete(arr, r)
        out["spread"] = max(out["spread"],
                            float(others.max() - others.min()) if n > 1
                            else 0.0)
        oracle = np.argsort(arr, kind="stable")
        out["ring"] = max(out["ring"], makespan(arr, ring[r], t_c))
        out["pred"] = max(out["pred"], makespan(arr, pred[r], t_c))
        out["oracle"] = max(out["oracle"], makespan(arr, oracle, t_c))
    return out


def torus_adj(n: int, alpha_ms: float = 0.001,
              beta_ms_mb: float = 0.0222) -> Adjacency:
    """Uniform single-hop ring costs (v5e-like: 45 GB/s/link)."""
    alpha = np.full((n, n), alpha_ms)
    beta = np.full((n, n), beta_ms_mb)
    np.fill_diagonal(alpha, 0.0)
    np.fill_diagonal(beta, 0.0)
    return Adjacency(alpha, beta)


def cases(n: int):
    """(name, mutate(alpha, beta, factor)) skew scenarios."""
    def one_link(al, be, f):
        be[0, 1] *= f          # a single contended link into rank 1
        al[0, 1] *= f

    def slow_source(al, be, f):
        be[0, :] *= f          # rank 0 behind a DCN hop: all its sends slow
        al[0, :] *= f
        be[0, 0] = al[0, 0] = 0.0

    return [("one_link", one_link), ("slow_source", slow_source)]


def run(n: int, slab_mb: float, t_c: float, factors=(1, 2, 4, 8, 16, 32)):
    rows = []
    for name, mutate in cases(n):
        for f in factors:
            adj = torus_adj(n)
            mutate(adj.alpha, adj.beta, float(f))
            r = simulate(adj, adj, slab_mb, t_c)
            rows.append({
                "case": name, "skew": f, "d": n,
                "t_ring_ms": round(r["ring"], 4),
                "t_pred_ms": round(r["pred"], 4),
                "t_oracle_ms": round(r["oracle"], 4),
                "arrival_spread_ms": round(r["spread"], 4),
                "ring_stall_ms": round(r["ring"] - r["oracle"], 4),
                "pred_stall_ms": round(r["pred"] - r["oracle"], 4),
            })
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--d", type=int, default=8)
    ap.add_argument("--slab-mb", type=float, default=4.0,
                    help="per-source slab size (reference config, ep=8: "
                         "nLx*C*H*2B ~ 4 MB)")
    ap.add_argument("--tc-ms", type=float, default=0.3,
                    help="per-slab expert-FFN compute time")
    args = ap.parse_args()
    for row in run(args.d, args.slab_mb, args.tc_ms):
        print(json.dumps(row))


if __name__ == "__main__":
    main()
