"""Per-stage latency breakdown of the fused MoE forward on live hardware.

Times cumulative prefixes of the pipeline (router | +plan | +dispatch |
+ffn | +combine) with the chained-scan method from bench.py; successive
differences isolate each stage.  Used to target the roofline gap
(BASELINE.md: measured 2.75 ms vs ~1.8 ms roofline on the reference
config).

Usage: python scripts/stage_bench.py [--trials 5] [--chain 8]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from flashmoe_tpu.config import BENCH_CONFIGS
from flashmoe_tpu.models.reference import init_moe_params
from flashmoe_tpu.ops import dispatch as dsp
from flashmoe_tpu.ops import expert as exp
from flashmoe_tpu.ops.gate import router


def make_prefix(params, cfg, depth: int, cap: int, path: str):
    """Prefix through `depth` stages, ending in a scalar that feeds the
    chain carry (dependency without materialization).

    ``path='gather'`` times the default inference pipeline (dispatch
    indices feed the gather-fused kernel, no [E, C, H] HBM buffer);
    ``path='explicit'`` times the training-shape pipeline (explicit
    dispatch buffer + grouped kernel).
    """

    def fn(x):
        r = router(x, params["gate_w"], cfg, use_pallas=True)
        if depth == 0:
            return r.combine_weights.astype(jnp.float32).sum()
        plan = dsp.make_plan(r.expert_idx, cfg, cap)
        if depth == 1:
            return (plan.position.sum() + r.combine_weights.sum()).astype(
                jnp.float32)
        if path == "gather":
            src_tok, _ = dsp.dispatch_indices(plan, cfg, cap)
            if depth == 2:
                return (src_tok.sum() + plan.position.sum()
                        + r.combine_weights.sum()).astype(jnp.float32)
            ybuf, cap_p = exp.capacity_ffn_gather(
                x.astype(cfg.dtype), plan, cfg, cap, params)
            if depth == 3:
                return ybuf.astype(jnp.float32).sum()
            out = dsp.combine(ybuf, plan, r.combine_weights, cfg, cap_p)
            return out.sum()
        xbuf = dsp.dispatch(x.astype(cfg.dtype), plan, cfg, cap)
        if depth == 2:
            return xbuf.astype(jnp.float32).sum()
        ybuf = exp.capacity_buffer_ffn_pallas(xbuf, params, cfg)
        if depth == 3:
            return ybuf.astype(jnp.float32).sum()
        out = dsp.combine(ybuf, plan, r.combine_weights, cfg, cap)
        return out.sum()

    return fn


def chained(fn, x0, iters: int):
    def run(x):
        def body(c, _):
            s = fn(c)
            return c * (1.0 + 0.0 * s.astype(c.dtype)), None
        c, _ = jax.lax.scan(body, x, None, length=iters)
        return c.astype(jnp.float32).sum()
    return jax.jit(run)


def time_chain(fn, x, trials: int):
    float(fn(x))
    ts = []
    for _ in range(trials):
        t0 = time.perf_counter()
        float(fn(x))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=5)
    ap.add_argument("--chain", type=int, default=8,
                    help="longer chain length for the differencing pair "
                         "(must be >= 2)")
    ap.add_argument("--config", default="reference")
    ap.add_argument("--path", choices=["gather", "explicit", "combine"],
                    default="gather",
                    help="'combine' times the fused layer with in-kernel "
                         "vs XLA combine instead of stage prefixes")
    args = ap.parse_args()
    if args.chain < 2:
        ap.error("--chain must be >= 2 (per-iteration time comes from "
                 "differencing two chain lengths)")
    if args.path == "combine":
        combine_modes(args)
        return

    cfg = BENCH_CONFIGS[args.config].replace(ep=1)
    cap = cfg.capacity_for(cfg.tokens)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    params = jax.tree_util.tree_map(lambda p: p.astype(cfg.dtype), params)
    x = jax.random.normal(
        jax.random.PRNGKey(1), (cfg.tokens, cfg.hidden_size), cfg.dtype)

    # router alone is known-negligible (~0 ms: one [S,H]x[H,E] GEMM);
    # three prefixes bound the interesting stages with 6 compiles instead
    # of 10 (tunnel compiles are ~60-90 s each, RPC'd server-side)
    stage2 = ("router+plan+indices" if args.path == "gather"
              else "router+plan+dispatch")
    names = {2: stage2, 3: "+ffn", 4: "+combine"}
    prev = 0.0
    for depth, name in names.items():
        fn = make_prefix(params, cfg, depth, cap, args.path)
        t1 = time_chain(chained(fn, x, 1), x, args.trials)
        tn = time_chain(chained(fn, x, args.chain), x, args.trials)
        t = max(tn - t1, 0.0) / (args.chain - 1)
        print(json.dumps({
            "prefix": name, "cum_ms": round(t * 1e3, 3),
            "stage_ms": round((t - prev) * 1e3, 3),
        }), flush=True)
        prev = t


def combine_modes(args):
    """Decision row: the fused RDMA layer with the in-kernel
    sorted-return combine (FLASHMOE_FUSED_COMBINE=1) vs the XLA combine.

    Since the round-5 restructure the in-kernel combine REQUIRES a
    multi-rank ep world (at one rank there is no return traffic to
    overlap and the gate falls back to the XLA combine by design), so
    this row can only be measured with >= 2 chips: both "modes" on one
    chip would time the identical kernel and report a noise winner.
    With one device the record says so explicitly instead."""
    from flashmoe_tpu.parallel.fused import fused_ep_moe_layer
    from flashmoe_tpu.parallel.mesh import make_mesh

    def bail(**why):
        print(json.dumps({
            "bench": "fused_combine_modes", "config": args.config, **why,
        }), flush=True)

    n_dev = len(jax.devices())
    if n_dev < 2:
        bail(requires_multichip=True,
             note="in-kernel combine is ep>1-only since the round-5 "
                  "sorted-return restructure; 1 device present — both "
                  "modes would time the identical kernel")
        return
    base = BENCH_CONFIGS[args.config]
    if base.num_experts % 2:
        bail(error=f"num_experts={base.num_experts} not divisible by "
                   f"ep=2")
        return
    cfg = base.replace(ep=2)
    # the gate can also fall back on SMEM/VMEM infeasibility — detect it
    # up front so the record never reports a noise winner between two
    # identical kernels (review r5 pass 6 #2)
    from flashmoe_tpu.parallel.ep import local_capacity
    from flashmoe_tpu.parallel.fused import _fuse_combine_budget_ok

    s_loc = cfg.tokens // cfg.ep
    cap_pad = -(-local_capacity(cfg, s_loc) // 32) * 32
    if not _fuse_combine_budget_ok(cfg, s_loc, cfg.hidden_size,
                                   cfg.intermediate_size, cap_pad):
        bail(combine_infeasible=True,
             note="combine maps/chunks exceed the SMEM/VMEM budget at "
                  "this config; the gate would fall back to the XLA "
                  "combine for both modes")
        return
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    params = jax.tree_util.tree_map(lambda p: p.astype(cfg.dtype), params)
    x = jax.random.normal(
        jax.random.PRNGKey(1), (cfg.tokens, cfg.hidden_size), cfg.dtype)
    mesh = make_mesh(cfg, dp=1, devices=jax.devices()[:cfg.ep])
    out = {}
    for mode in ("0", "1"):
        os.environ["FLASHMOE_FUSED_COMBINE"] = mode
        try:
            def fn(c):
                o = fused_ep_moe_layer(params, c, cfg, mesh)
                return o.out.astype(jnp.float32).sum()

            t1 = time_chain(chained(fn, x, 1), x, args.trials)
            tn = time_chain(chained(fn, x, args.chain), x, args.trials)
            out[mode] = max(tn - t1, 0.0) / (args.chain - 1)
        finally:
            os.environ.pop("FLASHMOE_FUSED_COMBINE", None)
    print(json.dumps({
        "bench": "fused_combine_modes", "config": args.config,
        "xla_combine_ms": round(out["0"] * 1e3, 3),
        "in_kernel_combine_ms": round(out["1"] * 1e3, 3),
        "winner": "in_kernel" if out["1"] < out["0"] else "xla",
    }), flush=True)


if __name__ == "__main__":
    main()
