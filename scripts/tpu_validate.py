"""Real-hardware validation sweep: drives every Pallas kernel and layer
path on the actual TPU chip and checks against the dense-math oracle.

Run: python scripts/tpu_validate.py        (needs the TPU backend live)

This is the hardware half of the verification story: the CPU interpreter
cannot catch Mosaic layout/lowering errors, so any kernel change must pass
here before it counts (see .claude/skills/verify/SKILL.md).
"""

from __future__ import annotations

import signal
import sys
import time

import jax
import jax.numpy as jnp


def deadline(seconds: int):
    def handler(signum, frame):
        print(f"FAIL: deadline {seconds}s exceeded (backend hung?)",
              flush=True)
        sys.exit(2)
    signal.signal(signal.SIGALRM, handler)
    signal.alarm(seconds)


def main() -> int:
    deadline(840)  # each remote compile is ~20-90s; checks 7/8 added four
    import flashmoe_tpu as fm
    from flashmoe_tpu.models.reference import init_moe_params, reference_moe
    from flashmoe_tpu.ops.attention import attention_xla, flash_attention

    assert jax.default_backend() == "tpu", jax.default_backend()
    failures = []

    def check(name, err, tol):
        ok = err < tol
        print(f"{'ok  ' if ok else 'FAIL'} {name}: err={err:.3e} tol={tol}",
              flush=True)
        if not ok:
            failures.append(name)

    # 1. capacity path, f32 (exact-ish)
    cfg = fm.MoEConfig(num_experts=8, expert_top_k=2, hidden_size=512,
                       intermediate_size=1024, sequence_len=256,
                       capacity_factor=4.0, drop_tokens=True,
                       dtype=jnp.float32, param_dtype=jnp.float32)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (256, 512), jnp.float32)
    t0 = time.time()
    got = fm.moe_layer(params, x, cfg, use_pallas=True)
    want, _ = reference_moe(params, x, cfg)
    check("capacity_f32", float(jnp.max(jnp.abs(got.out - want))), 1e-4)
    print(f"  (compile+run {time.time()-t0:.1f}s)")

    # 1b. gather-fused capacity path (opt-in kernel: dispatch built inside
    # the kernel via per-row DMA; must pass here before it can be default)
    cfg_g = cfg.replace(gather_fused=True)
    got_g = fm.moe_layer(params, x, cfg_g, use_pallas=True)
    check("capacity_gather_f32", float(jnp.max(jnp.abs(got_g.out - want))),
          1e-4)

    # 2. dropless ragged path
    cfg2 = cfg.replace(drop_tokens=False)
    got2 = fm.moe_layer(params, x, cfg2, use_pallas=True)
    want2, _ = reference_moe(params, x, cfg2)
    check("dropless_ragged_f32", float(jnp.max(jnp.abs(got2.out - want2))),
          1e-4)

    # 2b. dropless gather-fused kernel (grouped_ffn_tokens via the ragged
    # plan's inverse map) — same promotion gate as 1b
    got2g = fm.moe_layer(params, x, cfg2.replace(gather_fused=True),
                         use_pallas=True)
    check("dropless_gather_f32", float(jnp.max(jnp.abs(got2g.out - want2))),
          1e-4)

    # 3. gated bf16 (Mixtral-style)
    cfg3 = fm.MoEConfig(num_experts=8, expert_top_k=2, hidden_size=512,
                        intermediate_size=1024, sequence_len=256,
                        gated_ffn=True, hidden_act="silu",
                        drop_tokens=False)
    p3 = init_moe_params(jax.random.PRNGKey(2), cfg3)
    x3 = jax.random.normal(jax.random.PRNGKey(3), (256, 512), jnp.bfloat16)
    g3 = fm.moe_layer(p3, x3, cfg3, use_pallas=True)
    w3, _ = reference_moe(p3, x3, cfg3)
    rel = float(jnp.max(jnp.abs(g3.out.astype(jnp.float32)
                                - w3.astype(jnp.float32)))
                / jnp.max(jnp.abs(w3.astype(jnp.float32))))
    check("gated_bf16_rel", rel, 0.05)

    # 4. flash attention kernel
    q = jax.random.normal(jax.random.PRNGKey(4), (1, 4, 512, 64),
                          jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(5), (1, 4, 512, 64),
                          jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(6), (1, 4, 512, 64),
                          jnp.float32)
    fa = flash_attention(q, k, v, causal=True)
    wa = attention_xla(q, k, v, causal=True)
    check("flash_attention", float(jnp.max(jnp.abs(fa - wa))), 1e-4)

    # 5. TRAINING grad through the fused dropless path — the PALLAS
    # backward (ragged_dispatch buffer -> grouped_ffn_ad with
    # grouped_matmul/tgmm custom VJPs), checked against XLA-path grads.
    # is_training=True keeps the explicit dispatch buffer + residual-saving
    # backward; the (opt-in) gather-fused inference VJP is covered in 5b.
    def loss(p, use_pallas, c):
        o = fm.moe_layer(p, x, c, use_pallas=use_pallas)
        return jnp.sum(o.out.astype(jnp.float32) ** 2) + o.aux_loss

    def relerr(ga, gb):
        return max(
            float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                  - b.astype(jnp.float32))))
            / max(float(jnp.max(jnp.abs(b.astype(jnp.float32)))), 1e-9)
            for a, b in zip(jax.tree_util.tree_leaves(ga),
                            jax.tree_util.tree_leaves(gb))
        )

    cfg2t = cfg2.replace(is_training=True)
    gp = jax.grad(lambda p: loss(p, True, cfg2t))(params)
    gx = jax.grad(lambda p: loss(p, False, cfg2t))(params)
    finite = all(bool(jnp.isfinite(l).all())
                 for l in jax.tree_util.tree_leaves(gp))
    check("fused_grad_finite", 0.0 if finite else 1.0, 0.5)
    check("pallas_bwd_vs_xla_grads_rel", relerr(gp, gx), 0.02)

    # 5b. grad through the gather-fused inference capacity path (the
    # re-gather VJP) vs the XLA path
    gcap = jax.grad(lambda p: loss(p, True, cfg_g))(params)
    gcapx = jax.grad(lambda p: loss(p, False, cfg_g))(params)
    check("gather_fused_regather_vjp_rel", relerr(gcap, gcapx), 0.02)

    # 6. backward kernels standalone (grouped_matmul / tgmm vs einsum)
    from flashmoe_tpu.ops.expert import grouped_matmul, tgmm
    e, t_rows, kd, nd, bm = 4, 8 * 128, 512, 512, 128
    gid = (jnp.arange(t_rows // bm, dtype=jnp.int32)
           % e).sort()
    row_e = jnp.repeat(gid, bm)
    xg = jax.random.normal(jax.random.PRNGKey(7), (t_rows, kd), jnp.float32)
    wg = jax.random.normal(jax.random.PRNGKey(8), (e, nd, kd), jnp.float32)
    got_t = grouped_matmul(xg, gid, wg, transpose_w=True, block_m=bm)
    want_t = jnp.einsum("tk,tnk->tn", xg, wg[row_e])
    check("grouped_matmul_T", float(jnp.max(jnp.abs(got_t - want_t))), 5e-3)
    dyg = jax.random.normal(jax.random.PRNGKey(9), (t_rows, nd), jnp.float32)
    got_w = tgmm(xg, dyg, gid, e, block_m=bm)
    oh = jax.nn.one_hot(row_e, e, dtype=jnp.float32)
    want_w = jnp.einsum("tk,tn,te->ekn", xg, dyg, oh)
    check("tgmm", float(jnp.max(jnp.abs(got_w - want_w))), 5e-3)

    # 7. the DYNAMIC-size transport: jax.lax.ragged_all_to_all must lower
    # and run on the real chip (the reference ships exactly routedTokens
    # rows per packet, types.cuh:299-334; every CPU test forces the dense
    # arm because the op has no CPU lowering — this is the only place the
    # ragged arm executes for real).  ep=1 mesh: proves compilation +
    # numerics of the full ragged layout path vs the dense arm.

    from flashmoe_tpu.parallel.mesh import make_mesh
    from flashmoe_tpu.parallel.ragged_ep import ragged_ep_moe_layer

    cfg_r = cfg2.replace(ep=1)
    mesh1 = make_mesh(cfg_r, dp=1, devices=jax.devices()[:1])
    t0 = time.time()
    got_r = ragged_ep_moe_layer(params, x, cfg_r, mesh1, exchange="ragged")
    got_d = ragged_ep_moe_layer(params, x, cfg_r, mesh1, exchange="dense")
    check("ragged_all_to_all_vs_dense",
          float(jnp.max(jnp.abs(got_r.out - got_d.out))), 1e-5)
    check("ragged_arm_vs_oracle",
          float(jnp.max(jnp.abs(got_r.out - want2))), 1e-4)
    print(f"  (ragged compile+run {time.time()-t0:.1f}s)")

    # 8. fused RDMA kernel on silicon (ep=1: transfer legs degenerate to
    # local copies but the whole Mosaic kernel — semaphores, DMA chains,
    # streamed weights — must lower), XLA combine then in-kernel combine
    # (the round-3 kernel that had only ever run under the interpreter)
    from flashmoe_tpu.parallel.fused import fused_ep_moe_layer

    got_f = fused_ep_moe_layer(params, x, cfg_r, mesh1)
    check("fused_kernel_xla_combine",
          float(jnp.max(jnp.abs(got_f.out - want2))), 1e-4)
    # the in-kernel sorted-return combine is ep>1-only since round 5
    # (the gate falls back to the XLA combine at one rank), so its
    # Mosaic lowering cannot be validated on this single tunneled chip —
    # re-running here would just compile the identical kernel twice and
    # burn ~90 s of a hardware window
    print("  fused_kernel_in_kernel_combine: SKIPPED (ep>1-only; "
          "needs a multi-chip window)", flush=True)

    # 9. two-pass expert-tiled gate (large E): Mosaic-lowering check of
    # the multi-tile online-softmax/top-k kernel vs the XLA router
    from flashmoe_tpu.ops.gate import router_pallas_tiled, router_xla

    cfg_e = fm.MoEConfig(num_experts=1280, expert_top_k=2,
                         hidden_size=512, intermediate_size=1024,
                         dtype=jnp.float32, param_dtype=jnp.float32)
    w_big = jax.random.normal(jax.random.PRNGKey(10), (512, 1280),
                              jnp.float32) * 0.1
    rt = router_pallas_tiled(x, w_big, cfg_e)  # inference: pass 1 only
    rx = router_xla(x, w_big, cfg_e)
    idx_mism = float(jnp.sum(rt.expert_idx != rx.expert_idx))
    check("tiled_gate_idx_mismatch", idx_mism, 0.5)
    check("tiled_gate_weights",
          float(jnp.max(jnp.abs(rt.combine_weights
                                - rx.combine_weights))), 1e-4)
    # training mode lowers the logits spill + stats pass as well
    cfg_et = cfg_e.replace(is_training=True)
    rtt = router_pallas_tiled(x, w_big, cfg_et)
    rxt = router_xla(x, w_big, cfg_et)
    check("tiled_gate_train_aux",
          abs(float(rtt.aux_loss) - float(rxt.aux_loss)), 1e-3)

    print("ALL OK" if not failures else f"FAILURES: {failures}", flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
