"""Measure per-generation kernel block sizes and commit them to the
tuning table (``flashmoe_tpu/tuning.py`` — the TPU analogue of the
reference's per-arch trait table, ``csrc/include/flashmoe/arch.cuh:
95-222``, whose geometry was likewise chosen offline per architecture).

Sweeps, on the real chip:
  * capacity_ffn — (block_m, block_i) of the grouped capacity-buffer FFN
    kernel at each bench shape;
  * fused_ep     — (cm, bi_cap) of the fused RDMA kernel's compute loop
    (swept on a 1-rank mesh: transfer legs vanish, the streamed-weight /
    row-tile geometry being tuned is identical);
  * fused_tiles  — (cm row tile, kw K-window) of the row-windowed
    schedule's IO-aware chooser (``--stage tiles``; the rowwin schedule
    is pinned via ``MoEConfig.fused_schedule`` and each candidate pair
    forced through a throwaway ``fused_tiles`` table, same 1-rank-mesh
    rationale — the window/accumulator traffic being tuned is
    transfer-free).

Winners are written to ``flashmoe_tpu/tuning_data/<gen>.json`` (one
``{"kernel", "match", "set", "measured_ms"}`` entry per shape), which
ships with the package and is consulted at trace time.

Probe contract (the bench.py fail-fast contract, extended here per
ISSUE 12): before any non-``--interpret`` sweep the backend is probed
in an expendable subprocess with the same
``FLASHMOE_PROBE_ATTEMPTS`` / ``FLASHMOE_PROBE_TIMEOUT`` /
``FLASHMOE_PROBE_BUDGET`` bounds; a backend that never answers yields
ONE well-formed ``skipped: true`` JSON record and exit code 0
(machine-distinguishable from an error, rc 2), instead of wedging the
driver the way BENCH_r0* rounds did.

Usage: python scripts/tune_sweep.py [--trials 3] [--chain 8] [--dry]
                                    [--stage all|capacity|fused|tiles]
Prints one JSON line per (kernel, shape, candidate) measurement.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from flashmoe_tpu import tuning
from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.reference import init_moe_params

# shapes worth a table row: the reference bench config and the Mixtral
# FFN dims (BASELINE.json configs 2 and 3)
SHAPES = [
    dict(h=2048, i=2048, e=64, cap=256),
    dict(h=4096, i=14336, e=8, cap=2048),
]


def _chain_time(fn, args, trials, chain):
    def run(*a):
        def body(c, _):
            return c * (1.0 + 0.0 * fn(*a).astype(c.dtype)), None
        c, _ = jax.lax.scan(body, jnp.float32(1.0), None, length=chain)
        return c

    j = jax.jit(run)
    float(j(*args))
    ts = []
    for _ in range(trials):
        t0 = time.perf_counter()
        float(j(*args))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2] / chain


def sweep_capacity(shape, dtype, trials, chain):
    from flashmoe_tpu.ops.expert import grouped_ffn

    h, i, e, cap = shape["h"], shape["i"], shape["e"], shape["cap"]
    cfg = MoEConfig(num_experts=e, expert_top_k=1, hidden_size=h,
                    intermediate_size=i, dtype=dtype,
                    param_dtype=jnp.float32)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    params = jax.tree_util.tree_map(lambda p: p.astype(dtype), params)
    best = None
    for bm, bi in itertools.product((128, 256, 512), (256, 512)):
        if cap % bm and bm % cap:
            continue
        cp = ((cap + bm - 1) // bm) * bm
        x = jax.random.normal(jax.random.PRNGKey(1), (e * cp, h), dtype)
        gid = jnp.arange(e * (cp // bm), dtype=jnp.int32) // (cp // bm)

        def fn(xx):
            return grouped_ffn(
                xx, gid, params["w_up"], params["b_up"], params["w_down"],
                params["b_down"], None, act_name=cfg.hidden_act,
                gated=False, block_m=bm, block_i=bi,
            ).astype(jnp.float32).sum()

        t = _chain_time(fn, (x,), trials, chain)
        row = {"kernel": "capacity_ffn", "h": h, "i": i, "block_m": bm,
               "block_i": bi, "ms": round(t * 1e3, 4)}
        print(json.dumps(row), flush=True)
        if best is None or t < best[0]:
            best = (t, {"block_m": bm, "block_i": bi})
    return {"kernel": "capacity_ffn",
            "match": {"h": h, "i": i, "dtype": jnp.dtype(dtype).name},
            "set": best[1], "measured_ms": round(best[0] * 1e3, 4)}


def sweep_fused(shape, dtype, trials, chain, interpret=False):
    from flashmoe_tpu.parallel.fused import fused_ep_moe_layer
    from flashmoe_tpu.parallel.mesh import make_mesh

    h, i, e = shape["h"], shape["i"], shape["e"]
    cfg = MoEConfig(num_experts=e, expert_top_k=2, hidden_size=h,
                    intermediate_size=i, sequence_len=2048,
                    capacity_factor=1.0, drop_tokens=True, ep=1,
                    dtype=dtype, param_dtype=jnp.float32)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    params = jax.tree_util.tree_map(lambda p: p.astype(dtype), params)
    x = jax.random.normal(jax.random.PRNGKey(1), (cfg.tokens, h), dtype)
    mesh = make_mesh(cfg, dp=1, devices=jax.devices()[:1])
    tmp = "/tmp/flashmoe_tune_candidate.json"
    best = None
    cap = cfg.capacity_for(cfg.tokens)
    cap_pad = -(-cap // 32) * 32
    wr_was_swept = False
    try:
        for cm, bic in itertools.product((128, 256), (256, 512)):
            # the per-source weights-resident schedule only differs when
            # the capacity spans multiple row tiles — sweep it there so
            # its crossover becomes a measured row, not a heuristic
            # (the arrival-batched schedule needs >= 2 chips: at ep=1
            # the schedules coincide, so it has no single-chip row).
            # Gate on the EFFECTIVE cm (a tuned cm that does not divide
            # the padded capacity is discarded by _resolve_tiles) and on
            # VMEM feasibility — a wr=True row whose budget fails would
            # silently re-measure the stream schedule and let timing
            # noise write an unmeasured bit (review r5 pass 3 #2/#3).
            from flashmoe_tpu.parallel.fused import _resident_budget_ok

            eff_cm = cm if cap_pad % cm == 0 else next(
                t for t in (256, 128, 64, 32, 16, 8) if cap_pad % t == 0)
            eff_bi = min(bic, i)
            wr_feasible = (
                cap_pad // eff_cm > 1
                and _resident_budget_ok(
                    cap_pad, h, i, jnp.dtype(dtype).itemsize, False,
                    eff_cm, eff_bi, False, cfg.expert_top_k,
                    hid_rows=cap_pad)[0]
            )
            wr_opts = (False, True) if wr_feasible else (False,)
            wr_was_swept = wr_was_swept or len(wr_opts) > 1
            for wr in wr_opts:
                with open(tmp, "w") as f:
                    json.dump({"entries": [{
                        "kernel": "fused_ep",
                        "match": {"h": h, "i": i,
                                  "dtype": jnp.dtype(dtype).name},
                        "set": {"cm": cm, "bi_cap": bic,
                                "weights_resident": wr},
                    }]}, f)
                os.environ["FLASHMOE_TUNING_FILE"] = tmp
                tuning._load.cache_clear()

                def fn(xx):
                    return fused_ep_moe_layer(
                        params, xx, cfg, mesh,
                        interpret=interpret).out.astype(jnp.float32).sum()

                t = _chain_time(fn, (x,), trials, chain)
                row = {"kernel": "fused_ep", "h": h, "i": i, "cm": cm,
                       "bi_cap": bic, "weights_resident": wr,
                       "ms": round(t * 1e3, 4)}
                print(json.dumps(row), flush=True)
                if best is None or t < best[0]:
                    best = (t, {"cm": cm, "bi_cap": bic,
                                "weights_resident": wr})
    finally:
        os.environ.pop("FLASHMOE_TUNING_FILE", None)
        tuning._load.cache_clear()
    winner = dict(best[1])
    if not wr_was_swept:
        # a bit that was never measured must not override the deployment
        # heuristic at other capacities (review r5 pass 3 #1)
        winner.pop("weights_resident", None)
    return {"kernel": "fused_ep",
            "match": {"h": h, "i": i, "dtype": jnp.dtype(dtype).name},
            "set": winner, "measured_ms": round(best[0] * 1e3, 4)}


def sweep_tiles(shape, dtype, trials, chain, interpret=False):
    """Measure (cm, kw) candidates of the row-windowed schedule's
    IO-aware tile chooser at ``shape`` and return the winning
    ``fused_tiles`` entry, or None when the shape has no feasible
    rowwin geometry / fewer than two candidates worth ranking.  Each
    candidate pair is forced through a throwaway table +
    ``fused_schedule='rowwin'`` so the measurement times exactly the
    geometry the committed entry would select."""
    from flashmoe_tpu.parallel.fused import (
        fused_ep_moe_layer, rowwin_sweep_candidates,
    )
    from flashmoe_tpu.parallel.mesh import make_mesh

    h, i, e = shape["h"], shape["i"], shape["e"]
    cfg = MoEConfig(num_experts=e, expert_top_k=2, hidden_size=h,
                    intermediate_size=i, sequence_len=2048,
                    capacity_factor=1.0, drop_tokens=True, ep=1,
                    fused_schedule="rowwin",
                    dtype=dtype, param_dtype=jnp.float32)
    cap_pad = -(-cfg.capacity_for(cfg.tokens) // 32) * 32
    dt = jnp.dtype(dtype).itemsize
    # the kernel's own grid, per-kw best-cm (see fused.py) — shared
    # with bench.py --tiles so the enumerations cannot drift
    cands = rowwin_sweep_candidates(cap_pad, h, i, dt, cfg.gated_ffn,
                                    False, cfg.expert_top_k)
    if len(cands) < 2:
        print(json.dumps({"kernel": "fused_tiles", "h": h, "i": i,
                          "skipped": True,
                          "reason": f"{len(cands)} feasible (cm, kw) "
                                    f"candidates at this shape"}),
              flush=True)
        return None
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    params = jax.tree_util.tree_map(lambda p: p.astype(dtype), params)
    x = jax.random.normal(jax.random.PRNGKey(1), (cfg.tokens, h), dtype)
    mesh = make_mesh(cfg, dp=1, devices=jax.devices()[:1])
    tmp = "/tmp/flashmoe_tune_tiles_candidate.json"
    best = None
    try:
        for cm, kw in cands:
            with open(tmp, "w") as f:
                json.dump({"entries": [{
                    "kernel": "fused_tiles",
                    "match": {"h": h, "i": i,
                              "dtype": jnp.dtype(dtype).name},
                    "set": {"cm": cm, "kw": kw},
                }]}, f)
            os.environ["FLASHMOE_TUNING_FILE"] = tmp
            tuning._load.cache_clear()

            def fn(xx):
                return fused_ep_moe_layer(
                    params, xx, cfg, mesh,
                    interpret=interpret).out.astype(jnp.float32).sum()

            t = _chain_time(fn, (x,), trials, chain)
            row = {"kernel": "fused_tiles", "h": h, "i": i, "cm": cm,
                   "kw": kw, "schedule": "rowwin",
                   "ms": round(t * 1e3, 4)}
            print(json.dumps(row), flush=True)
            if best is None or t < best[0]:
                best = (t, {"cm": cm, "kw": kw})
    finally:
        os.environ.pop("FLASHMOE_TUNING_FILE", None)
        tuning._load.cache_clear()
    return {"kernel": "fused_tiles",
            "match": {"h": h, "i": i, "dtype": jnp.dtype(dtype).name},
            "set": best[1], "measured_ms": round(best[0] * 1e3, 4)}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--chain", type=int, default=8)
    ap.add_argument("--dry", action="store_true",
                    help="sweep without writing the table")
    ap.add_argument("--interpret", action="store_true",
                    help="interpret-mode structural dry run (timings "
                         "meaningless; implies --dry)")
    ap.add_argument("--stage", default="all",
                    choices=["all", "capacity", "fused", "tiles"],
                    help="which kernel family to sweep (tiles = the "
                         "rowwin schedule's fused_tiles (cm, kw) pairs)")
    ap.add_argument("--probe-budget", type=int,
                    default=int(os.environ.get("FLASHMOE_PROBE_BUDGET",
                                               300)),
                    help="how long to keep retrying the backend probe "
                         "(s) before giving up")
    ap.add_argument("--probe-attempts", type=int,
                    default=int(os.environ.get("FLASHMOE_PROBE_ATTEMPTS",
                                               0)),
                    help="max probe attempts (0 = budget-bounded only); "
                         "a probe that never answers yields a "
                         "well-formed skipped:true record with rc 0")
    ap.add_argument("--probe-timeout", type=int,
                    default=int(os.environ.get("FLASHMOE_PROBE_TIMEOUT",
                                               90)),
                    help="per-attempt probe timeout (s)")
    args = ap.parse_args(argv)
    if args.interpret:
        args.dry = True

    if not args.interpret:
        # the bench.py probe contract, shared verbatim: an expendable
        # subprocess answers "is the backend alive" with a hard bound,
        # and a tunnel that never answers becomes a machine-readable
        # skip instead of a wedged sweep
        import bench as _bench

        ok, info, hung = _bench._probe_backend_retry(
            args.probe_budget, each_s=max(args.probe_timeout, 10),
            max_attempts=args.probe_attempts)
        if not ok:
            if hung:
                print(json.dumps({
                    "metric": f"tune_sweep[{args.stage}]",
                    "value": None, "unit": "ms",
                    "skipped": True, "reason": info,
                }), flush=True)
                sys.exit(0)
            print(json.dumps({
                "metric": f"tune_sweep[{args.stage}]",
                "value": -1, "unit": "ms", "error": info,
            }), flush=True)
            sys.exit(2)
        print(f"# backend up: {info}", file=sys.stderr, flush=True)

    dtype = jnp.bfloat16
    entries = []
    for shape in SHAPES:
        if args.stage in ("all", "capacity"):
            entries.append(sweep_capacity(shape, dtype, args.trials,
                                          args.chain))
        if args.stage in ("all", "fused"):
            entries.append(sweep_fused(shape, dtype, args.trials,
                                       args.chain,
                                       interpret=args.interpret))
        if args.stage in ("all", "tiles"):
            ent = sweep_tiles(shape, dtype, args.trials, args.chain,
                              interpret=args.interpret)
            if ent is not None:
                entries.append(ent)
    gen = tuning.generation()
    if args.dry:
        print(json.dumps({"generation": gen, "entries": entries}))
    else:
        path = tuning.save_entries(gen, entries)
        print(json.dumps({"written": path, "n": len(entries)}))


if __name__ == "__main__":
    main()
