"""Worker for the measured-placement test: run the REAL bootstrap path
(throughput probe + DCN probe + Decider) and print the resulting expert
counts.  Launched per-rank by ``tests/test_runtime.py`` with a throughput
scale injected on one rank."""

import json
import os

import jax.numpy as jnp

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.runtime import bootstrap


def main():
    cfg = MoEConfig(
        num_experts=8, expert_top_k=2, hidden_size=256,
        intermediate_size=256, sequence_len=128, is_training=False,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    rt = bootstrap.initialize(cfg, measure=True)
    counts = {str(d): len(v) for d, v in rt.placement.local_experts.items()}
    rec = json.dumps({
        "rank": rt.process_id,
        "counts": counts,
        "groups": rt.placement.groups,
    })
    out = os.environ.get("FLASHMOE_PLACEMENT_OUT")
    if out:
        with open(f"{out}.rank{rt.process_id}.json", "w") as f:
            f.write(rec)
    print(rec, flush=True)
    bootstrap.finalize()


if __name__ == "__main__":
    main()
