"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (the "fake backend" the reference
never built — SURVEY.md §4): ``xla_force_host_platform_device_count=8``
gives real multi-device semantics (shard_map, collectives, all_to_all)
without TPU hardware.  Pallas kernels run in interpreter mode on CPU.
"""

import os

# Must be set before jax initializes.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    d = jax.devices()
    assert len(d) >= 8, f"expected >=8 virtual devices, got {len(d)}"
    return d
