"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (the "fake backend" the reference
never built — SURVEY.md §4): ``xla_force_host_platform_device_count=8``
gives real multi-device semantics (shard_map, collectives, all_to_all)
without TPU hardware.  Pallas kernels run in interpreter mode on CPU.
"""

import os

# Must be set before jax initializes its backends.  The environment may pin
# JAX_PLATFORMS to a TPU plugin (e.g. axon); tests explicitly force the
# 8-device virtual CPU backend unless FLASHMOE_TEST_TPU=1 requests running
# the suite against real hardware.
if os.environ.get("FLASHMOE_TEST_TPU") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

if os.environ.get("FLASHMOE_TEST_TPU") != "1":
    # A TPU plugin loaded from sitecustomize may have pinned the platform
    # via jax.config before this file ran; force it back.
    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    d = jax.devices()
    assert len(d) >= 8, f"expected >=8 virtual devices, got {len(d)}"
    return d
