"""Flash attention kernel + ring attention vs the XLA oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.ops.attention import attention_xla, flash_attention
from flashmoe_tpu.parallel.ringattn import ring_attention
from jax.sharding import Mesh


def _qkv(b=1, n=2, t=256, d=64, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, n, t, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, n, t, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, n, t, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_xla(causal):
    q, k, v = _qkv()
    want = attention_xla(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                          interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_flash_uneven_blocks():
    q, k, v = _qkv(t=384)
    want = attention_xla(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block_q=128, block_k=128,
                          interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


@pytest.mark.parametrize("sp,causal", [(4, True), (8, True), (4, False)])
def test_ring_attention_matches_full(sp, causal, devices):
    import numpy as onp
    q, k, v = _qkv(t=512)
    mesh = Mesh(onp.asarray(devices[:sp]), ("sp",))
    want = attention_xla(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_ring_attention_long_context(devices):
    """8-way sharded 2048-token causal attention, bf16 inputs."""
    import numpy as onp
    q, k, v = _qkv(b=1, n=1, t=2048, d=64)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    mesh = Mesh(onp.asarray(devices[:8]), ("sp",))
    got = ring_attention(q, k, v, mesh, causal=True)
    want = attention_xla(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        causal=True,
    )
    rel = float(
        jnp.max(jnp.abs(got.astype(jnp.float32) - want))
        / jnp.max(jnp.abs(want))
    )
    assert rel < 0.05, rel
