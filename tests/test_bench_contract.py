"""The driver-facing bench.py JSON contract (one line, machine-readable
partial semantics — advisor round-3 #4)."""

import json
import subprocess
import sys

import jax.numpy as jnp
import pytest


def test_emit_partial_vs_full(capsys):
    import bench
    from flashmoe_tpu.config import BENCH_CONFIGS

    cfg = BENCH_CONFIGS["reference"]
    bench._PARTIAL.update(cfg=cfg, name="reference")
    bench._emit(cfg, "reference", 2.5e-3, 2.6e-3)
    full = json.loads(capsys.readouterr().out.strip())
    assert full["vs_baseline"] == round(2.6 / 2.5, 3)
    assert "partial" not in full
    assert full["unit"] == "ms" and full["value"] == 2.5

    bench._PARTIAL.update(cfg=cfg, name="reference")
    bench._emit(cfg, "reference", 2.5e-3, None, note="deadline hit")
    part = json.loads(capsys.readouterr().out.strip())
    # a partial can never masquerade as a measured no-speedup result
    assert part["vs_baseline"] is None
    assert part["partial"] == "deadline hit"
    assert part["xla_path_ms"] is None


def test_mxu_util_label(monkeypatch):
    import bench
    from flashmoe_tpu.config import BENCH_CONFIGS
    from flashmoe_tpu.parallel import topology

    monkeypatch.setattr(topology, "tpu_generation", lambda d: "v5e")
    cfg = BENCH_CONFIGS["reference"]
    # reference config at the round-2 measured latency: utilization must
    # land in a sane (0, 1) band so the driver can gate on it
    u = bench._mxu_util(cfg, 2.749e-3)
    assert 0.1 < u < 1.0


def test_probe_retry_bounded_by_attempts(monkeypatch):
    """Satellite: FLASHMOE_PROBE_ATTEMPTS caps the retry loop — a wedged
    tunnel stops after N probes instead of burning the whole budget
    (BENCH_r05: 309 s of retries), and the hung flag survives so main()
    can emit the skip record instead of an error."""
    import bench

    calls = []

    def fake_probe(timeout_s):
        calls.append(timeout_s)
        return False, f"backend probe hung >{timeout_s}s", True

    monkeypatch.setattr(bench, "_probe_backend", fake_probe)
    ok, info, hung = bench._probe_backend_retry(
        budget_s=10_000, each_s=10, max_attempts=2)
    assert (ok, hung) == (False, True)
    assert len(calls) == 2
    assert "2 attempts" in info
    # a non-hung failure keeps hung=False (main() then errors, rc 2)
    monkeypatch.setattr(
        bench, "_probe_backend",
        lambda t: (False, "backend probe rc=1: boom", False))
    ok, info, hung = bench._probe_backend_retry(
        budget_s=10_000, each_s=10, max_attempts=1)
    assert (ok, hung) == (False, False)


def test_cli_emits_skipped_record_when_probe_hangs(monkeypatch, capsys):
    """A backend that never answers yields ONE well-formed
    skipped:true JSON record and exit code 0 — machine-distinguishable
    from both an error (rc 2) and a measurement."""
    import sys as _sys

    import bench

    monkeypatch.setattr(
        bench, "_probe_backend_retry",
        lambda budget_s, each_s=90, max_attempts=0:
        (False, "backend probe hung >10s after 2 attempts / 20s", True))
    monkeypatch.setattr(_sys, "argv",
                        ["bench.py", "--probe-attempts", "2"])
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["skipped"] is True
    assert rec["value"] is None and rec["vs_baseline"] is None
    assert "hung" in rec["reason"]


def test_wire_fields_in_records():
    """Records carry the wire identity (selection keys) and the modeled
    comm bytes the wire saves at the config's nominal ep width."""
    import bench
    from flashmoe_tpu.config import BENCH_CONFIGS

    cfg = BENCH_CONFIGS["reference"]
    off = bench._wire_fields(cfg)
    assert off == {"wire_dtype": "off", "wire_dtype_combine": "off"}
    on = bench._wire_fields(cfg.replace(ep=8, wire_dtype="e4m3"))
    assert on["wire_dtype"] == "e4m3"
    assert on["wire_modeled_comm_saved_mb"] > 0
    assert on["wire_modeled_comm_mb"] > 0
    # single chip: no exchange to save on, but the identity still rides
    one = bench._wire_fields(cfg.replace(ep=1, wire_dtype="e4m3"))
    assert one["wire_modeled_comm_saved_mb"] == 0.0


def test_cli_profile_plumbs_ledger_matrix(monkeypatch, capsys, tmp_path):
    """`bench.py --profile` is the CLI face of
    profiler.ledger.run_ledger_matrix (which test_profiler gates end to
    end): the arg plumbing must hand it the obs dir / quick / steps
    flags, print each returned record as a JSON line, and mirror it
    into the --obs-dir artifacts."""
    import sys as _sys

    import __graft_entry__
    import bench
    from flashmoe_tpu.profiler import ledger

    seen = {}

    def fake_matrix(obs_dir, *, quick=False, steps=1, devices=None,
                    **kw):
        seen.update(obs_dir=obs_dir, quick=quick, steps=steps,
                    n_devices=len(devices or []))
        return [{"metric": "phase_ledger[flat,chunks=1,wire=off]",
                 "value": 1.25, "unit": "ms", "path": "flat"}]

    monkeypatch.setattr(ledger, "run_ledger_matrix", fake_matrix)
    monkeypatch.setattr(__graft_entry__, "_force_cpu_devices",
                        lambda n: None)
    obs = tmp_path / "obs"
    monkeypatch.setattr(_sys, "argv",
                        ["bench.py", "--profile-quick", "--profile-steps",
                         "3", "--obs-dir", str(obs), "--deadline", "0"])
    bench.main()
    assert seen["obs_dir"] == str(obs)
    assert seen["quick"] is True and seen["steps"] == 3
    assert seen["n_devices"] >= 1
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"].startswith("phase_ledger[")
    mirrored = [json.loads(line) for line in
                (obs / "bench_records.jsonl").read_text().splitlines()]
    assert mirrored == [rec]


def test_cli_serve_plumbs_load_sweep(monkeypatch, capsys, tmp_path):
    """`bench.py --serve` is the CLI face of
    serving.loadgen.serve_load_sweep (gated end-to-end by
    tests/test_serving.py): the arg plumbing must parse the load list,
    hand through requests/batch, print each record as a JSON line with
    the TTFT/TPOT fields, and mirror into --obs-dir."""
    import sys as _sys

    import bench
    from flashmoe_tpu.serving import loadgen

    seen = {}

    def fake_sweep(loads, *, n_requests=8, max_batch=4, **kw):
        seen.update(loads=list(loads), n=n_requests, b=max_batch)
        return [{"metric": "serve_load[every=2,B=2,req=3]",
                 "value": 120.0, "unit": "tokens_per_sec",
                 "vs_baseline": 1.0, "ttft_ms_p50": 5.0,
                 "tpot_ms_p50": 1.0, "completed": 3}]

    monkeypatch.setattr(loadgen, "serve_load_sweep", fake_sweep)
    obs = tmp_path / "obs"
    monkeypatch.setattr(_sys, "argv",
                        ["bench.py", "--serve", "--serve-loads", "4,2",
                         "--serve-requests", "3", "--serve-batch", "2",
                         "--obs-dir", str(obs), "--deadline", "0"])
    bench.main()
    assert seen == {"loads": [4, 2], "n": 3, "b": 2}
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"].startswith("serve_load[")
    assert "ttft_ms_p50" in rec and "tpot_ms_p50" in rec
    mirrored = [json.loads(line) for line in
                (obs / "bench_records.jsonl").read_text().splitlines()]
    assert mirrored == [rec]


def test_cli_serve_flag_exclusivity(monkeypatch, capsys):
    """--serve fail-fasts on modes/knobs it would silently ignore
    (the --profile/--ckpt contract), and its own flags are rejected
    without --serve."""
    import sys as _sys

    import bench

    cases = [
        ["bench.py", "--serve", "--ckpt"],
        ["bench.py", "--serve", "--overlap", "4"],
        ["bench.py", "--serve", "--sweep", "ep"],
        ["bench.py", "--serve", "--wire-dtype", "e4m3"],
        ["bench.py", "--serve", "--a2a-chunks", "2"],
        ["bench.py", "--serve", "--serve-loads", "2,zero"],
        ["bench.py", "--serve", "--serve-loads", "0"],
        ["bench.py", "--serve-requests", "4"],      # needs --serve
        ["bench.py", "--profile-quick", "--serve"],
    ]
    for argv in cases:
        monkeypatch.setattr(_sys, "argv", argv)
        with pytest.raises(SystemExit) as e:
            bench.main()
        assert e.value.code == 2, argv
        capsys.readouterr()


def test_cli_speculate_plumbs_and_guards(monkeypatch, capsys):
    """--speculate K threads into serve_load_sweep(speculate=K)
    (gated end-to-end by tests/test_serving.py), and fail-fasts where
    it would be silently dropped: without --serve, under --fabric
    (whose dispatch returns before the serve lane), and at K < 1."""
    import sys as _sys

    import bench
    from flashmoe_tpu.serving import loadgen

    seen = {}

    def fake_sweep(loads, *, speculate=None, **kw):
        seen["speculate"] = speculate
        return [{"metric": "serve_load[every=4,B=2,req=3,spec=k3]",
                 "value": 120.0, "unit": "tokens_per_sec",
                 "vs_baseline": 1.0, "ttft_ms_p50": 5.0,
                 "tpot_ms_p50": 1.0, "completed": 3}]

    monkeypatch.setattr(loadgen, "serve_load_sweep", fake_sweep)
    monkeypatch.setattr(_sys, "argv",
                        ["bench.py", "--serve", "--speculate", "3",
                         "--serve-loads", "4", "--serve-requests", "3",
                         "--serve-batch", "2", "--deadline", "0"])
    bench.main()
    assert seen == {"speculate": 3}
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert ",spec=k3]" in rec["metric"]

    for argv in [
        ["bench.py", "--speculate", "3"],           # needs --serve
        ["bench.py", "--fabric", "--speculate", "3"],
        ["bench.py", "--serve", "--speculate", "0"],
    ]:
        monkeypatch.setattr(_sys, "argv", argv)
        with pytest.raises(SystemExit) as e:
            bench.main()
        assert e.value.code == 2, argv
        capsys.readouterr()


def test_cli_tiles_flag_exclusivity(monkeypatch, capsys):
    """--tiles fail-fasts on knobs/modes the rowwin tile sweep would
    silently ignore (the --profile/--ckpt/--serve contract)."""
    import sys as _sys

    import bench

    cases = [
        ["bench.py", "--tiles", "--wire-dtype", "e4m3"],
        ["bench.py", "--tiles", "--a2a-chunks", "2"],
        ["bench.py", "--tiles", "--sweep", "ep"],
        ["bench.py", "--tiles", "--overlap", "4"],
        ["bench.py", "--tiles", "--ckpt"],
        ["bench.py", "--tiles", "--serve"],
        ["bench.py", "--tiles", "--profile"],
    ]
    for argv in cases:
        monkeypatch.setattr(_sys, "argv", argv)
        with pytest.raises(SystemExit) as e:
            bench.main()
        assert e.value.code == 2, argv
        capsys.readouterr()


def test_cli_quant_flag_exclusivity(monkeypatch, capsys):
    """--quant fail-fasts on knobs/modes the store sweep would silently
    ignore (ISSUE 15 satellite: refused with --ckpt/--overlap like the
    other shape-changing flags)."""
    import sys as _sys

    import bench

    cases = [
        ["bench.py", "--quant", "--ckpt"],
        ["bench.py", "--quant", "--overlap", "4"],
        ["bench.py", "--quant", "--wire-dtype", "e4m3"],
        ["bench.py", "--quant", "--a2a-chunks", "2"],
        ["bench.py", "--quant", "--sweep", "ep"],
        ["bench.py", "--quant", "--serve"],
        ["bench.py", "--quant", "--profile"],
        ["bench.py", "--quant", "--tiles"],
        ["bench.py", "--quant", "--scaling"],
        ["bench.py", "--quant", "--regression"],
    ]
    for argv in cases:
        monkeypatch.setattr(_sys, "argv", argv)
        with pytest.raises(SystemExit) as e:
            bench.main()
        assert e.value.code == 2, argv
        capsys.readouterr()


def test_cli_quant_emits_skipped_record_when_probe_hangs(monkeypatch,
                                                         capsys):
    """The --quant stage inherits the bench probe fail-fast contract:
    a wedged tunnel yields ONE well-formed skipped:true record under
    the QUANT metric and rc 0."""
    import sys as _sys

    import bench

    monkeypatch.setattr(
        bench, "_probe_backend_retry",
        lambda budget_s, each_s=90, max_attempts=0:
        (False, "backend probe hung >10s after 2 attempts / 20s", True))
    monkeypatch.setattr(_sys, "argv",
                        ["bench.py", "--quant", "--config", "mixtral",
                         "--probe-attempts", "2"])
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"] == "quant_ms[mixtral]"
    assert rec["skipped"] is True and rec["value"] is None
    assert "hung" in rec["reason"]


def test_quant_fields_in_records():
    """Every emitted record carries the quantized-store identity (the
    wire-knob convention), and the modeled weight-bytes-saved fields
    appear when the store is on."""
    import bench
    from flashmoe_tpu.config import BENCH_CONFIGS

    off = bench._quant_fields(BENCH_CONFIGS["mixtral"])
    assert off == {"expert_quant": "off"}
    on = bench._quant_fields(
        BENCH_CONFIGS["mixtral"].replace(expert_quant="int8"))
    assert on["expert_quant"] == "int8"
    assert on["quant_modeled_weight_saved_mb"] > 0
    assert (on["quant_modeled_weight_mb"]
            < on["quant_modeled_weight_saved_mb"] * 1.05)  # ~half


def test_cli_tiles_emits_skipped_record_when_probe_hangs(monkeypatch,
                                                         capsys):
    """ISSUE 12 satellite: the --tiles stage inherits the bench probe
    fail-fast contract — a backend that never answers yields ONE
    well-formed skipped:true record under the TILES metric (so the
    driver files it against the right measurement) and rc 0."""
    import sys as _sys

    import bench

    monkeypatch.setattr(
        bench, "_probe_backend_retry",
        lambda budget_s, each_s=90, max_attempts=0:
        (False, "backend probe hung >10s after 2 attempts / 20s", True))
    monkeypatch.setattr(_sys, "argv",
                        ["bench.py", "--tiles", "--config", "mixtral",
                         "--probe-attempts", "2"])
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"] == "fused_tiles_ms[mixtral]"
    assert rec["skipped"] is True and rec["value"] is None
    assert "hung" in rec["reason"]


def _load_tune_sweep():
    import importlib.util as ilu
    import os

    spec = ilu.spec_from_file_location(
        "tune_sweep", os.path.join(os.path.dirname(__file__), "..",
                                   "scripts", "tune_sweep.py"))
    mod = ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_tune_sweep_tiles_probe_contract(monkeypatch, capsys):
    """tune_sweep.py shares bench's probe contract verbatim (ISSUE 12
    satellite): a hung probe yields a skipped:true record + rc 0, a
    dead-but-answering backend an error record + rc 2 — bounded by the
    same FLASHMOE_PROBE_ATTEMPTS/TIMEOUT knobs."""
    import bench

    ts = _load_tune_sweep()
    monkeypatch.setattr(
        bench, "_probe_backend_retry",
        lambda budget_s, each_s=90, max_attempts=0:
        (False, "backend probe hung >30s after 1 attempts / 30s", True))
    with pytest.raises(SystemExit) as e:
        ts.main(["--stage", "tiles", "--probe-attempts", "1"])
    assert e.value.code == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"] == "tune_sweep[tiles]"
    assert rec["skipped"] is True and "hung" in rec["reason"]

    monkeypatch.setattr(
        bench, "_probe_backend_retry",
        lambda budget_s, each_s=90, max_attempts=0:
        (False, "backend probe rc=1: boom", False))
    with pytest.raises(SystemExit) as e:
        ts.main(["--stage", "tiles", "--probe-attempts", "1"])
    assert e.value.code == 2
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == -1 and "boom" in rec["error"]


def test_tune_sweep_tiles_candidates_are_feasible():
    """The tiles stages measure THE kernel's own candidate grid
    (fused.rowwin_sweep_candidates — code-review finding: the sweeps
    once hand-copied a narrower cm list that silently diverged from
    the chooser): every measured pair divides the shapes, fits the
    VMEM window budget, covers every feasible K-window at its widest
    feasible row tile — including the pair the analytic chooser picks
    — and the wide (mixtral-FFN) shape offers at least two candidates,
    so the sweep cannot be vacuous at the shape the schedule exists
    for."""
    import jax.numpy as jnp

    from flashmoe_tpu.config import MoEConfig
    from flashmoe_tpu.parallel.fused import (
        _rowwin_budget_ok, _rowwin_tiles, rowwin_sweep_candidates,
        rowwin_tile_candidates,
    )

    h, i, e = 4096, 14336, 8
    cfg = MoEConfig(num_experts=e, expert_top_k=2, hidden_size=h,
                    intermediate_size=i, sequence_len=2048,
                    capacity_factor=1.0, drop_tokens=True, ep=1,
                    dtype=jnp.bfloat16)
    cap_pad = -(-cfg.capacity_for(cfg.tokens) // 32) * 32
    full = rowwin_tile_candidates(cap_pad, h, i, 2, False, False, 2)
    cands = rowwin_sweep_candidates(cap_pad, h, i, 2, False, False, 2)
    assert len(cands) >= 2
    assert set(cands) <= set(full)
    assert {kw for _, kw in cands} == {kw for _, kw in full}
    for cm, kw in cands:
        assert cap_pad % cm == 0 and i % kw == 0
        assert _rowwin_budget_ok(cap_pad, h, i, 2, False, cm, kw,
                                 False, 2)
        # widest feasible row tile for this kw
        assert cm == max(c for c, k2 in full if k2 == kw)
    # the analytic chooser's pick is itself a measured candidate
    assert _rowwin_tiles(cap_pad, h, i, 2, None, False, False,
                         2) in cands


def test_cli_emits_json_error_fast_when_backend_dead():
    """With the backend guaranteed dead (bogus platform — the probe
    subprocess fails deterministically, unlike relying on probe-timeout
    races) the CLI must exit quickly with a JSON error record rather
    than hang the way the wedged tunnel would."""
    import os

    env = {**os.environ, "JAX_PLATFORMS": "definitely_not_a_platform",
           "PALLAS_AXON_POOL_IPS": ""}
    r = subprocess.run(
        [sys.executable, "bench.py", "--probe-budget", "1",
         "--deadline", "30"],
        capture_output=True, text=True, timeout=120, cwd=".", env=env,
    )
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["value"] == -1 and "error" in rec
    assert r.returncode == 2


def test_cli_scaling_plumbs_sweep_and_knobs(monkeypatch):
    """`bench.py --scaling` hands the weak-scaling sweep its trials and
    wire/chunk knobs (wire-dcn included — the knob the sweep exists to
    measure)."""
    import sys as _sys

    import bench

    seen = {}

    def fake_scaling(trials, *, wire_dtype=None, wire_combine=None,
                     wire_dcn=None, a2a_chunks=None):
        seen.update(trials=trials, wire_dtype=wire_dtype,
                    wire_dcn=wire_dcn, a2a_chunks=a2a_chunks)

    monkeypatch.setattr(bench, "_bench_scaling", fake_scaling)
    monkeypatch.setattr(_sys, "argv",
                        ["bench.py", "--scaling", "--trials", "3",
                         "--wire-dcn", "e4m3", "--a2a-chunks", "2",
                         "--deadline", "0"])
    bench.main()
    assert seen == {"trials": 3, "wire_dtype": None,
                    "wire_dcn": "e4m3", "a2a_chunks": 2}


def test_cli_scaling_flag_exclusivity(monkeypatch, capsys):
    """--scaling fail-fasts on modes it would silently ignore, and
    --wire-dcn is rejected outside --scaling (no other mode runs a
    cross-slice hop)."""
    import sys as _sys

    import bench

    cases = [
        ["bench.py", "--scaling", "--overlap", "4"],
        ["bench.py", "--scaling", "--ckpt"],
        ["bench.py", "--scaling", "--tiles"],
        ["bench.py", "--scaling", "--serve"],
        ["bench.py", "--wire-dcn", "e4m3"],
        ["bench.py", "--wire-dcn", "e4m3", "--overlap", "4"],
    ]
    for argv in cases:
        monkeypatch.setattr(_sys, "argv", argv)
        with pytest.raises(SystemExit) as e:
            bench.main()
        assert e.value.code == 2, argv
        capsys.readouterr()


def test_cli_scaling_emits_skipped_record_when_probe_hangs(monkeypatch,
                                                           capsys):
    """The probe fail-fast contract on real hardware
    (FLASHMOE_OVERLAP_TPU=1): a wedged tunnel yields ONE well-formed
    skipped:true scaling record and rc 0 — never a hang, never an
    ambiguous rc 2."""
    import sys as _sys

    import bench

    monkeypatch.setenv("FLASHMOE_OVERLAP_TPU", "1")
    monkeypatch.setattr(
        bench, "_probe_backend_retry",
        lambda budget_s, each_s=90, max_attempts=0:
        (False, "backend probe hung >10s after 2 attempts / 20s", True))
    monkeypatch.setattr(
        bench, "_bench_scaling",
        lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("sweep must not run on a hung probe")))
    monkeypatch.setattr(_sys, "argv",
                        ["bench.py", "--scaling", "--probe-attempts",
                         "2"])
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["skipped"] is True
    assert rec["metric"] == "scaling_ms[slices]"
    assert rec["value"] is None and "hung" in rec["reason"]
    # a dead (non-hung) backend still errors rc 2 with the reason
    monkeypatch.setattr(
        bench, "_probe_backend_retry",
        lambda budget_s, each_s=90, max_attempts=0:
        (False, "backend probe rc=1: boom", False))
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 2
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["error"].startswith("backend probe rc=1")


def test_cli_serve_telemetry_port_plumbed(monkeypatch, capsys):
    """`bench.py --serve --telemetry-port N` hands the port to the
    load sweep (which self-scrapes /metrics mid-sweep)."""
    import sys as _sys

    import bench
    from flashmoe_tpu.serving import loadgen

    seen = {}

    def fake_sweep(loads, *, n_requests=8, max_batch=4,
                   telemetry_port=None, **kw):
        seen.update(port=telemetry_port)
        return [{"metric": "serve_load[every=4,B=4,req=8]",
                 "value": 10.0, "unit": "tokens_per_sec",
                 "telemetry_scrape": {"ok": True}}]

    monkeypatch.setattr(loadgen, "serve_load_sweep", fake_sweep)
    monkeypatch.setattr(_sys, "argv",
                        ["bench.py", "--serve", "--telemetry-port",
                         "0", "--deadline", "0"])
    bench.main()
    assert seen == {"port": 0}
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["telemetry_scrape"]["ok"] is True


def test_cli_live_plane_flag_exclusivity(monkeypatch, capsys):
    """The fail-fast contract on the new flags: --telemetry-port
    without --serve and --regression with modes it cannot record are
    rejected rc 2."""
    import sys as _sys

    import bench

    cases = [
        ["bench.py", "--telemetry-port", "9100"],
        ["bench.py", "--telemetry-port", "9100", "--ckpt"],
        ["bench.py", "--telemetry-port", "9100", "--profile-quick"],
        ["bench.py", "--regression", "--ckpt"],
        ["bench.py", "--regression", "--overlap", "4"],
        ["bench.py", "--regression", "--sweep", "ep"],
        ["bench.py", "--regression", "--tiles"],
    ]
    for argv in cases:
        monkeypatch.setattr(_sys, "argv", argv)
        with pytest.raises(SystemExit) as e:
            bench.main()
        assert e.value.code == 2, argv
        capsys.readouterr()


def test_cli_regression_appends_history(monkeypatch, capsys, tmp_path):
    """`bench.py --serve --regression` appends ONE run entry keyed by
    the records' measurement-identity strings to obs/history.jsonl
    under --obs-dir."""
    import sys as _sys

    import bench
    from flashmoe_tpu.serving import loadgen

    monkeypatch.setattr(
        loadgen, "serve_load_sweep",
        lambda loads, **kw: [
            {"metric": "serve_load[every=4,B=4,req=8]", "value": 50.0,
             "unit": "tokens_per_sec", "ttft_ms_p50": 4.0},
            {"metric": "serve_load[every=1,B=4,req=8]", "value": None,
             "unit": "tokens_per_sec", "skipped": True},
        ])
    obs = tmp_path / "obs"
    monkeypatch.setattr(_sys, "argv",
                        ["bench.py", "--serve", "--regression",
                         "--obs-dir", str(obs), "--deadline", "0"])
    bench.main()
    capsys.readouterr()
    runs = [json.loads(l) for l in
            (obs / "history.jsonl").read_text().splitlines()]
    assert len(runs) == 1
    keys = set(runs[0]["metrics"])
    assert "serve_load[every=4,B=4,req=8]" in keys
    assert "serve_load[every=4,B=4,req=8].ttft_ms_p50" in keys
    # the skipped point never entered the baseline
    assert not any(k.startswith("serve_load[every=1") for k in keys)


def test_cli_regression_wedged_probe_skip_stays_rc0(monkeypatch,
                                                    capsys, tmp_path):
    """The wedged-tunnel contract survives the new flag: a hung probe
    with --regression still yields ONE skipped:true record, rc 0, and
    writes NO history entry (a skip is not a run)."""
    import sys as _sys

    import bench

    monkeypatch.setattr(
        bench, "_probe_backend_retry",
        lambda budget_s, each_s=90, max_attempts=0:
        (False, "backend probe hung >10s after 2 attempts / 20s", True))
    obs = tmp_path / "obs"
    monkeypatch.setattr(_sys, "argv",
                        ["bench.py", "--regression", "--obs-dir",
                         str(obs), "--probe-attempts", "2"])
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["skipped"] is True and "hung" in rec["reason"]
    assert not (obs / "history.jsonl").exists()


def test_cli_fabric_plumbs_load_sweep(monkeypatch):
    """`bench.py --fabric` hands the fabric sweep its offered loads,
    request/batch sizes, the optional live-scrape port, and the
    virtual-clock arming."""
    import sys as _sys

    import bench

    seen = {}

    def fake_fabric(loads, *, requests, max_batch, telemetry_port=None,
                    vclock=False, wire="inproc"):
        seen.update(loads=loads, requests=requests,
                    max_batch=max_batch, telemetry_port=telemetry_port,
                    vclock=vclock, wire=wire)

    monkeypatch.setattr(bench, "_bench_fabric", fake_fabric)
    monkeypatch.setattr(_sys, "argv",
                        ["bench.py", "--fabric", "--telemetry-port",
                         "0", "--deadline", "0"])
    bench.main()
    assert seen == {"loads": [4, 2, 1], "requests": 8, "max_batch": 4,
                    "telemetry_port": 0, "vclock": False,
                    "wire": "inproc"}
    monkeypatch.setattr(_sys, "argv",
                        ["bench.py", "--fabric", "--vclock",
                         "--deadline", "0"])
    bench.main()
    assert seen["vclock"] is True and seen["telemetry_port"] is None
    # --wire tcp plumbs through to the sweep's socket-wire arm
    monkeypatch.setattr(_sys, "argv",
                        ["bench.py", "--fabric", "--wire", "tcp",
                         "--deadline", "0"])
    bench.main()
    assert seen["wire"] == "tcp"


def test_cli_fabric_flag_exclusivity(monkeypatch, capsys):
    """--fabric fail-fasts on modes/knobs it would silently ignore
    (its drill model pins its own config), and --telemetry-port is
    rejected outside --serve/--fabric."""
    import sys as _sys

    import bench

    cases = [
        ["bench.py", "--fabric", "--ckpt"],
        ["bench.py", "--fabric", "--quant"],
        ["bench.py", "--fabric", "--serve"],
        ["bench.py", "--fabric", "--scaling"],
        ["bench.py", "--fabric", "--profile"],
        ["bench.py", "--fabric", "--wire-dtype", "e4m3"],
        ["bench.py", "--fabric", "--a2a-chunks", "2"],
        ["bench.py", "--telemetry-port", "0"],
        ["bench.py", "--vclock"],
        ["bench.py", "--serve", "--vclock"],
        # the socket wire carries fabric KV handoffs only, and the
        # fault sweep picks each drill's wire itself
        ["bench.py", "--wire", "tcp"],
        ["bench.py", "--serve", "--wire", "tcp"],
        ["bench.py", "--fabric", "--faults", "--wire", "tcp"],
    ]
    for argv in cases:
        monkeypatch.setattr(_sys, "argv", argv)
        with pytest.raises(SystemExit) as e:
            bench.main()
        assert e.value.code == 2, argv
        capsys.readouterr()


def test_cli_fabric_faults_plumbs_fault_sweep(monkeypatch):
    """`bench.py --fabric --faults` dispatches the fault sweep (not
    the load sweep) — the recovery-ladder records ride the same
    emit/observability path as every other mode."""
    import sys as _sys

    import bench

    seen = {"faults": 0}
    monkeypatch.setattr(bench, "_bench_fabric_faults",
                        lambda: seen.update(faults=seen["faults"] + 1))
    monkeypatch.setattr(
        bench, "_bench_fabric",
        lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("--faults must not run the load sweep")))
    monkeypatch.setattr(_sys, "argv",
                        ["bench.py", "--fabric", "--faults",
                         "--deadline", "0"])
    bench.main()
    assert seen["faults"] == 1


def test_cli_faults_flag_exclusivity(monkeypatch, capsys):
    """--faults fail-fasts outside --fabric and refuses knobs the
    fault sweep would silently ignore (--vclock is implied — every
    drill already steps on the virtual clock; there is no live scrape
    window for --telemetry-port)."""
    import sys as _sys

    import bench

    cases = [
        ["bench.py", "--faults"],
        ["bench.py", "--serve", "--faults"],
        ["bench.py", "--fabric", "--faults", "--vclock"],
        ["bench.py", "--fabric", "--faults", "--telemetry-port", "0"],
    ]
    for argv in cases:
        monkeypatch.setattr(_sys, "argv", argv)
        with pytest.raises(SystemExit) as e:
            bench.main()
        assert e.value.code == 2, argv
        capsys.readouterr()


def test_cli_fabric_faults_probe_hang_skips(monkeypatch, capsys):
    """--fabric --faults inherits the probe fail-fast contract on real
    hardware: a hung probe yields ONE skipped:true record (with the
    fault-matrix headline identity) and rc 0 — the drills never run."""
    import sys as _sys

    import bench

    monkeypatch.setenv("FLASHMOE_OVERLAP_TPU", "1")
    monkeypatch.setattr(
        bench, "_probe_backend_retry",
        lambda budget_s, each_s=90, max_attempts=0:
        (False, "backend probe hung >10s after 2 attempts / 20s", True))
    monkeypatch.setattr(
        bench, "_bench_fabric_faults",
        lambda: (_ for _ in ()).throw(
            AssertionError("drills must not run on a hung probe")))
    monkeypatch.setattr(_sys, "argv",
                        ["bench.py", "--fabric", "--faults"])
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["skipped"] is True
    assert rec["metric"] == "fabric_fault[matrix]"
    assert rec["value"] is None and "hung" in rec["reason"]


def test_cli_fabric_emits_skipped_record_when_probe_hangs(monkeypatch,
                                                          capsys):
    """On real hardware (FLASHMOE_OVERLAP_TPU=1) --fabric inherits the
    probe fail-fast contract: a wedged tunnel yields ONE well-formed
    skipped:true record and rc 0; a dead backend errors rc 2."""
    import sys as _sys

    import bench

    monkeypatch.setenv("FLASHMOE_OVERLAP_TPU", "1")
    monkeypatch.setattr(
        bench, "_probe_backend_retry",
        lambda budget_s, each_s=90, max_attempts=0:
        (False, "backend probe hung >10s after 2 attempts / 20s", True))
    monkeypatch.setattr(
        bench, "_bench_fabric",
        lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("sweep must not run on a hung probe")))
    monkeypatch.setattr(_sys, "argv", ["bench.py", "--fabric"])
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["skipped"] is True
    assert rec["metric"] == "fabric_tokens_per_sec[replicas]"
    assert rec["value"] is None and "hung" in rec["reason"]
    monkeypatch.setattr(
        bench, "_probe_backend_retry",
        lambda budget_s, each_s=90, max_attempts=0:
        (False, "backend probe rc=1: boom", False))
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 2
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["error"].startswith("backend probe rc=1")
