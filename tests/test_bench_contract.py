"""The driver-facing bench.py JSON contract (one line, machine-readable
partial semantics — advisor round-3 #4)."""

import json
import subprocess
import sys

import jax.numpy as jnp


def test_emit_partial_vs_full(capsys):
    import bench
    from flashmoe_tpu.config import BENCH_CONFIGS

    cfg = BENCH_CONFIGS["reference"]
    bench._PARTIAL.update(cfg=cfg, name="reference")
    bench._emit(cfg, "reference", 2.5e-3, 2.6e-3)
    full = json.loads(capsys.readouterr().out.strip())
    assert full["vs_baseline"] == round(2.6 / 2.5, 3)
    assert "partial" not in full
    assert full["unit"] == "ms" and full["value"] == 2.5

    bench._PARTIAL.update(cfg=cfg, name="reference")
    bench._emit(cfg, "reference", 2.5e-3, None, note="deadline hit")
    part = json.loads(capsys.readouterr().out.strip())
    # a partial can never masquerade as a measured no-speedup result
    assert part["vs_baseline"] is None
    assert part["partial"] == "deadline hit"
    assert part["xla_path_ms"] is None


def test_mxu_util_label(monkeypatch):
    import bench
    from flashmoe_tpu.config import BENCH_CONFIGS
    from flashmoe_tpu.parallel import topology

    monkeypatch.setattr(topology, "tpu_generation", lambda d: "v5e")
    cfg = BENCH_CONFIGS["reference"]
    # reference config at the round-2 measured latency: utilization must
    # land in a sane (0, 1) band so the driver can gate on it
    u = bench._mxu_util(cfg, 2.749e-3)
    assert 0.1 < u < 1.0


def test_cli_emits_json_error_fast_when_backend_dead():
    """With the backend guaranteed dead (bogus platform — the probe
    subprocess fails deterministically, unlike relying on probe-timeout
    races) the CLI must exit quickly with a JSON error record rather
    than hang the way the wedged tunnel would."""
    import os

    env = {**os.environ, "JAX_PLATFORMS": "definitely_not_a_platform",
           "PALLAS_AXON_POOL_IPS": ""}
    r = subprocess.run(
        [sys.executable, "bench.py", "--probe-budget", "1",
         "--deadline", "30"],
        capture_output=True, text=True, timeout=120, cwd=".", env=env,
    )
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["value"] == -1 and "error" in rec
    assert r.returncode == 2
