"""Fault-tolerance ladder: tier-0 expert masking, tier-1 gradient
guards, tier-2 checkpoint integrity + path fallback, and the chaos
drill matrix that proves each rung (docs/RESILIENCE.md)."""

import itertools
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.chaos import (
    FaultPlan, clear, inject, make_injector, wrap_step,
)
from flashmoe_tpu.chaos.drill import drill_config
from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.reference import init_moe_params
from flashmoe_tpu.ops.moe import moe_layer
from flashmoe_tpu.parallel.mesh import make_mesh
from flashmoe_tpu.runtime import checkpoint as ckpt
from flashmoe_tpu.runtime.resilient import (
    ResilienceConfig, resilient_train,
)
from flashmoe_tpu.runtime.trainer import (
    GradGuardConfig, init_state, make_optimizer, make_train_step,
    state_shardings,
)
from flashmoe_tpu.utils.telemetry import Metrics, metrics as global_metrics

F32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)

TRAIN_CFG = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                      intermediate_size=128, sequence_len=32, num_layers=1,
                      moe_frequency=1, vocab_size=256, num_heads=2,
                      drop_tokens=False, is_training=True, ep=4, **F32)


@pytest.fixture(autouse=True)
def _clean_chaos():
    clear()
    yield
    clear()


# ----------------------------------------------------------------------
# Tier 0: expert-health masking
# ----------------------------------------------------------------------

def _moe_setup(**over):
    base = dict(num_experts=4, expert_top_k=2, hidden_size=64,
                intermediate_size=64, sequence_len=16,
                capacity_factor=2.0, collect_stats=True, **F32)
    base.update(over)
    cfg = MoEConfig(**base)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 64), jnp.float32)
    return cfg, params, x


def _prim_counts(jaxpr, acc=None):
    acc = {} if acc is None else acc
    for eqn in jaxpr.eqns:
        acc[eqn.primitive.name] = acc.get(eqn.primitive.name, 0) + 1
        for v in eqn.params.values():
            vs = v if isinstance(v, (list, tuple)) else (v,)
            for item in vs:
                if hasattr(item, "jaxpr"):
                    _prim_counts(item.jaxpr, acc)
                elif hasattr(item, "eqns"):
                    _prim_counts(item, acc)
    return acc


def test_degrade_off_is_bit_identical_and_check_free():
    """Flag off: outputs bit-identical to flag on (healthy experts), and
    the flag-off graph carries none of the health checks the flag-on
    graph adds (jax.nn.softmax contributes a baseline is_finite on both
    sides, so the assertion is on the DELTA, not absence)."""
    cfg, params, x = _moe_setup()
    o_off = moe_layer(params, x, cfg, use_pallas=False)
    o_on = moe_layer(params, x, cfg.replace(degrade_unhealthy_experts=True),
                     use_pallas=False)
    np.testing.assert_array_equal(np.asarray(o_off.out), np.asarray(o_on.out))
    assert float(o_on.stats.masked_experts) == 0.0
    assert float(o_on.stats.masked_fraction) == 0.0

    def prims(c):
        return _prim_counts(jax.make_jaxpr(
            lambda xx: moe_layer(params, xx, c, use_pallas=False).out)(x))

    off, on = prims(cfg), prims(cfg.replace(degrade_unhealthy_experts=True))
    assert on.get("is_finite", 0) > off.get("is_finite", 0)


def test_degrade_masks_injected_nan_expert():
    cfg, params, x = _moe_setup()
    inject.arm("nan_expert", expert=2)
    sick_off = moe_layer(params, x, cfg, use_pallas=False)
    assert not bool(np.isfinite(np.asarray(sick_off.out)).all())
    on = cfg.replace(degrade_unhealthy_experts=True)
    sick_on = moe_layer(params, x, on, use_pallas=False)
    assert bool(np.isfinite(np.asarray(sick_on.out)).all())
    assert float(sick_on.stats.masked_experts) == 1.0
    assert float(sick_on.stats.masked_fraction) > 0.0


def test_degrade_masks_nan_weights_under_jit_and_vmap():
    """The realistic fault: a corrupted expert WEIGHT tensor — every
    output row of that expert goes non-finite and is masked."""
    cfg, params, x = _moe_setup()
    cfg = cfg.replace(degrade_unhealthy_experts=True)
    params = dict(params)
    params["w_up"] = params["w_up"].at[1].set(jnp.nan)
    out = jax.jit(lambda xx: moe_layer(params, xx, cfg,
                                       use_pallas=False).out)(x)
    assert bool(np.isfinite(np.asarray(out)).all())
    v = jax.vmap(lambda xx: moe_layer(params, xx, cfg,
                                      use_pallas=False).stats.masked_experts
                 )(jnp.stack([x, x]))
    np.testing.assert_array_equal(np.asarray(v), [1.0, 1.0])


def test_degrade_all_experts_sick_yields_zero_not_nan():
    cfg, params, x = _moe_setup(expert_top_k=1)
    cfg = cfg.replace(degrade_unhealthy_experts=True)
    params = dict(params)
    params["w_up"] = jnp.full_like(params["w_up"], jnp.nan)
    o = moe_layer(params, x, cfg, use_pallas=False)
    np.testing.assert_array_equal(np.asarray(o.out),
                                  np.zeros_like(np.asarray(o.out)))
    assert float(o.stats.masked_experts) == cfg.num_experts


def _ep_setup(devices):
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=256, ep=8, **F32)
    mesh = make_mesh(cfg, dp=1, devices=devices[:8])
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (cfg.tokens, 64),
                          jnp.float32)
    return cfg, mesh, params, x


def test_degrade_ep_layer_graph_budget_unchanged(devices):
    """Trace-only (no compile): the degrade flag adds finiteness checks
    but NO collective to the EP layer's stats-off graph."""
    from flashmoe_tpu.parallel.ep import ep_moe_layer

    cfg, mesh, params, x = _ep_setup(devices)

    def prims(c):
        jx = jax.make_jaxpr(
            lambda p, xx: ep_moe_layer(p, xx, c, mesh))(params, x)
        return _prim_counts(jx.jaxpr)

    off = prims(cfg)
    on = prims(cfg.replace(degrade_unhealthy_experts=True))
    for coll in ("all_to_all", "psum", "pmean", "all_gather"):
        assert on.get(coll, 0) == off.get(coll, 0)
    assert on.get("all_to_all", 0) == 2 and on.get("psum", 0) == 3
    assert on.get("is_finite", 0) > off.get("is_finite", 0)


@pytest.mark.slow
def test_degrade_ep_layer_masks_and_counts(devices):
    from flashmoe_tpu.parallel.ep import ep_moe_layer

    cfg, mesh, params, x = _ep_setup(devices)
    on = cfg.replace(degrade_unhealthy_experts=True, collect_stats=True)
    o_healthy = ep_moe_layer(params, x, on, mesh)
    np.testing.assert_array_equal(
        np.asarray(o_healthy.out),
        np.asarray(ep_moe_layer(params, x, cfg, mesh).out))

    params = dict(params)
    params["w_down"] = params["w_down"].at[3].set(jnp.inf)
    o_sick = ep_moe_layer(params, x, on, mesh)
    assert bool(np.isfinite(np.asarray(o_sick.out)).all())
    # every one of the 8 ranks masks its own exposure to expert 3
    assert float(o_sick.stats.masked_experts) == 8.0
    assert float(o_sick.stats.masked_fraction) > 0.0


@pytest.mark.slow
def test_degrade_masks_nan_expert_through_fp8_wire(devices):
    """Chaos drill for the wire codec: a poisoned expert output must
    still trip the tier-0 health mask AFTER crossing an fp8 combine
    wire (nan_expert injects at the expert's owner, BEFORE the return
    exchange — ops/wire.py guarantees non-finite rows decode
    non-finite)."""
    from flashmoe_tpu.parallel.ep import ep_moe_layer

    cfg, mesh, params, x = _ep_setup(devices)
    wired = cfg.replace(wire_dtype="e4m3", wire_dtype_combine="e4m3",
                        collect_stats=True)
    inject.arm("nan_expert", expert=1)
    sick_off = ep_moe_layer(params, x, wired, mesh)
    assert not bool(np.isfinite(np.asarray(sick_off.out)).all())
    on = wired.replace(degrade_unhealthy_experts=True)
    sick_on = ep_moe_layer(params, x, on, mesh)
    assert bool(np.isfinite(np.asarray(sick_on.out)).all())
    # the armed spec names ONE global expert: all 8 ranks mask exactly
    # their own exposure to it, nothing else (the pre-exchange injector
    # keeps global-expert-id semantics — chaos/inject.py
    # poison_local_expert)
    assert float(sick_on.stats.masked_experts) == 8.0
    assert float(sick_on.stats.masked_fraction) > 0.0
    # and the uncompressed layer masks the same injection (the move of
    # the injection point to the pre-exchange side keeps the drill
    # meaningful with the wire off too)
    raw_on = cfg.replace(degrade_unhealthy_experts=True,
                         collect_stats=True)
    raw = ep_moe_layer(params, x, raw_on, mesh)
    assert bool(np.isfinite(np.asarray(raw.out)).all())
    assert float(raw.stats.masked_experts) == 8.0


@pytest.mark.slow
def test_degrade_masks_nan_expert_through_chunked_fp8_pipeline(devices):
    """Tier-0 masking through the chunked double-buffered pipeline
    (MoEConfig.a2a_chunks) with fp8 on both legs: the poisoned expert
    lives in a NON-ZERO chunk of its owner (global expert 5 -> owner
    rank 2, local row 1, chunk 1 of 2), so the injection's chunk-offset
    arithmetic (inject.poison_local_expert local_offset/local_total)
    is exercised, and the NaN crosses the per-chunk fp8 combine wire
    before the health mask sees it."""
    from flashmoe_tpu.parallel.ep import ep_moe_layer

    cfg = MoEConfig(num_experts=16, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=256, ep=8,
                    a2a_chunks=2, wire_dtype="e4m3",
                    wire_dtype_combine="e4m3", collect_stats=True,
                    **F32)
    mesh = make_mesh(cfg, dp=1, devices=devices[:8])
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (cfg.tokens, 64),
                          jnp.float32)
    inject.arm("nan_expert", expert=5)
    sick_off = ep_moe_layer(params, x, cfg, mesh)
    assert not bool(np.isfinite(np.asarray(sick_off.out)).all())
    on = cfg.replace(degrade_unhealthy_experts=True)
    sick_on = ep_moe_layer(params, x, on, mesh)
    assert bool(np.isfinite(np.asarray(sick_on.out)).all())
    # every rank masks exactly its own exposure to the one armed expert
    assert float(sick_on.stats.masked_experts) == 8.0
    assert float(sick_on.stats.masked_fraction) > 0.0


@pytest.mark.slow
def test_degrade_masks_nan_expert_through_fp8_dcn_hop(devices):
    """Tier-0 masking through the PER-HOP wire pipeline (ISSUE 13): a
    two-stage multi-slice exchange whose cross-slice hop re-encodes at
    e4m3 (wire_dtype_dcn, in-slice hop raw) with a chunked pipeline on
    top.  The poisoned expert's NaN must survive encode -> inner a2a ->
    decode -> fp8 re-encode -> DCN a2a -> decode (ops/wire.py:
    non-finite rows decode non-finite, per hop) before the health mask
    sees it — the through-the-wire guarantee extended to the fp8 DCN
    hop."""
    from flashmoe_tpu.parallel.ep import ep_moe_layer

    cfg = MoEConfig(num_experts=16, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=256, ep=8,
                    a2a_chunks=2, wire_dtype_dcn="e4m3",
                    collect_stats=True, **F32)
    mesh = make_mesh(cfg, dp=1, devices=devices[:8])
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (cfg.tokens, 64),
                          jnp.float32)
    inject.arm("nan_expert", expert=5)
    sick_off = ep_moe_layer(params, x, cfg, mesh, dcn_inner=4)
    assert not bool(np.isfinite(np.asarray(sick_off.out)).all())
    on = cfg.replace(degrade_unhealthy_experts=True)
    sick_on = ep_moe_layer(params, x, on, mesh, dcn_inner=4)
    assert bool(np.isfinite(np.asarray(sick_on.out)).all())
    assert float(sick_on.stats.masked_experts) == 8.0
    assert float(sick_on.stats.masked_fraction) > 0.0


@pytest.mark.slow
def test_degrade_masks_nan_expert_through_quantized_fp8_pipeline(
        devices):
    """Tier-0 masking through the quantized-expert + fp8-wire stack
    (ISSUE 15 satellite, extending the PR 5/6 through-the-wire drill):
    the serving build's full compression story — int8 expert weights
    (pre-quantized state, dequant-in-compute) under e4m3 wires on both
    legs.  The nan_expert injection poisons the quantized expert's
    output at its owner, crosses the fp8 combine wire, and must still
    trip the health mask; masking accounting stays exact (every rank
    masks exactly its own exposure to the one armed expert)."""
    from flashmoe_tpu import quant as qt
    from flashmoe_tpu.parallel.ep import ep_moe_layer

    cfg, mesh, params, x = _ep_setup(devices)
    qs = qt.quantize_state(params, "int8")
    wired = cfg.replace(expert_quant="int8", wire_dtype="e4m3",
                        wire_dtype_combine="e4m3", collect_stats=True)
    inject.arm("nan_expert", expert=1)
    sick_off = ep_moe_layer(qs.params, x, wired, mesh)
    assert not bool(np.isfinite(np.asarray(sick_off.out)).all())
    on = wired.replace(degrade_unhealthy_experts=True)
    sick_on = ep_moe_layer(qs.params, x, on, mesh)
    assert bool(np.isfinite(np.asarray(sick_on.out)).all())
    assert float(sick_on.stats.masked_experts) == 8.0
    assert float(sick_on.stats.masked_fraction) > 0.0


@pytest.mark.slow
def test_degrade_ragged_ep_layer(devices):
    from flashmoe_tpu.parallel.ragged_ep import ragged_ep_moe_layer

    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=256, ep=8,
                    drop_tokens=False, **F32)
    mesh = make_mesh(cfg, dp=1, devices=devices[:8])
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (cfg.tokens, 64),
                          jnp.float32)
    on = cfg.replace(degrade_unhealthy_experts=True, collect_stats=True)
    np.testing.assert_array_equal(
        np.asarray(ragged_ep_moe_layer(params, x, on, mesh,
                                       exchange="dense").out),
        np.asarray(ragged_ep_moe_layer(params, x, cfg, mesh,
                                       exchange="dense").out))
    params = dict(params)
    params["w_up"] = params["w_up"].at[5].set(jnp.nan)
    o = ragged_ep_moe_layer(params, x, on, mesh, exchange="dense")
    assert bool(np.isfinite(np.asarray(o.out)).all())
    assert float(o.stats.masked_experts) >= 1.0


# ----------------------------------------------------------------------
# Tier 1: gradient anomaly guard
# ----------------------------------------------------------------------
#
# The guard is mesh-agnostic, so these tests run on a SINGLE-device mesh
# (cheap XLA compiles keep the fast lane inside the tier-1 time budget;
# the ep=4 resilience path is covered by tests/test_resilient.py) and
# share one compiled step per (guard on/off) across the module.

GUARD = GradGuardConfig(warmup_steps=2, spike_factor=10.0)
_STEPS: dict = {}


def _small_cfg():
    return TRAIN_CFG.replace(ep=1)


def _shared_step(devices, guard):
    key = guard is not None
    if key not in _STEPS:
        cfg = _small_cfg()
        mesh = make_mesh(cfg, dp=1, devices=devices[:1])
        opt = make_optimizer(cfg, total_steps=8)
        _STEPS[key] = (make_train_step(cfg, mesh, opt, guard=guard), opt,
                       mesh)
    return _STEPS[key]


def _train_fixture(devices, guard=None):
    step, opt, mesh = _shared_step(devices, guard)
    cfg = _small_cfg()
    state = init_state(jax.random.PRNGKey(0), cfg, opt, guard=guard)
    state = jax.device_put(state, state_shardings(state, cfg, mesh))

    def batches():
        k = itertools.count()
        while True:
            yield {"tokens": jax.random.randint(
                jax.random.PRNGKey(next(k)), (2, 33), 0, 256)}

    return state, step, batches()


def test_guard_healthy_step_bit_identical(devices):
    s0, step0, data0 = _train_fixture(devices)
    sg, stepg, _ = _train_fixture(devices, guard=GUARD)
    batch = next(data0)
    n0, m0 = step0(s0, batch)
    ng, mg = stepg(sg, batch)
    assert float(mg["grad_ok"]) == 1.0
    for a, b in zip(jax.tree_util.tree_leaves(n0.params),
                    jax.tree_util.tree_leaves(ng.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_guard_skips_nan_grad_in_graph(devices):
    state, step, data = _train_fixture(devices, guard=GUARD)
    batch = next(data)
    state, m = step(state, batch)
    before = jax.device_get(state.params)
    inject.arm("nan_grad", step=1)
    _step, opt, mesh = _shared_step(devices, GUARD)
    step2 = make_train_step(_small_cfg(), mesh, opt, guard=GUARD)
    state, m = step2(state, batch)
    assert float(m["grad_ok"]) == 0.0
    assert np.isfinite(float(m["loss"]))  # loss itself was fine
    assert int(state.step) == 2           # training advanced
    after = jax.device_get(state.params)
    for a, b in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(after)):
        np.testing.assert_array_equal(a, b)  # update skipped exactly
    # the EMA never saw the NaN
    assert np.isfinite(float(m["grad_norm_ema"]))


def test_guard_skips_grad_spike_and_ema_recovers(devices):
    state, step, data = _train_fixture(devices, guard=GUARD)
    batch = next(data)
    for _ in range(3):
        state, m = step(state, batch)
    ema_before = float(m["grad_norm_ema"])
    inject.arm("grad_spike", step=3, scale=1e6)
    _step, opt, mesh = _shared_step(devices, GUARD)
    step2 = make_train_step(_small_cfg(), mesh, opt, guard=GUARD)
    state, m = step2(state, batch)
    assert float(m["grad_ok"]) == 0.0
    assert float(m["grad_norm"]) > 1e5
    assert float(m["grad_norm_ema"]) == pytest.approx(ema_before)
    inject.disarm()
    state, m = step2(state, batch)  # next step is accepted again
    assert float(m["grad_ok"]) == 1.0


def test_resilient_records_grad_skip_decision(devices, tmp_path):
    state, _step, data = _train_fixture(devices, guard=GUARD)
    inject.arm("nan_grad", step=2)
    _s, opt, mesh = _shared_step(devices, GUARD)
    step = make_train_step(_small_cfg(), mesh, opt, guard=GUARD)
    metrics = Metrics()
    final, hist = resilient_train(
        state, step, data, num_steps=4,
        rcfg=ResilienceConfig(checkpoint_dir=str(tmp_path / "ck"),
                              checkpoint_every=10),
        metrics=metrics)
    assert int(final.step) == 4
    assert metrics.counters["grad_skips"] == 1
    assert metrics.counters["failures"] == 0  # tier 1 absorbed it
    d = metrics.last_decision("trainer.grad_skip")
    assert d is not None and d["step"] == 2


@pytest.mark.slow
def test_elastic_resume_carries_guard_state(devices, tmp_path):
    """A tier-1 guarded job survives elastic resume: the template carries
    the GuardState subtree so the EMA/warmup counters restore."""
    from flashmoe_tpu.runtime.elastic import elastic_resume

    state, step, data = _train_fixture(devices, guard=GUARD)
    state, _m = step(state, next(data))
    d = str(tmp_path / "ck_guard")
    ckpt.save(d, state)
    new_state, _mesh, _cfg, _opt = elastic_resume(
        _small_cfg(), d, devices=devices[:4], guard=GUARD)
    assert new_state.guard is not None
    assert int(new_state.guard.seen) == 1
    assert float(new_state.guard.norm_ema) > 0


# ----------------------------------------------------------------------
# Tier 2: checkpoint integrity + fallback restore
# ----------------------------------------------------------------------

def _synthetic_state(step: int) -> "TrainState":
    """A tiny TrainState pytree — checkpoint integrity is about bytes on
    disk, not model structure, so these tests skip the XLA compile."""
    from flashmoe_tpu.runtime.trainer import TrainState

    k = jax.random.PRNGKey(step)
    return TrainState(
        params={"w": jax.random.normal(k, (32, 32), jnp.float32)},
        opt_state={"m": jnp.zeros((32, 32), jnp.float32)},
        step=jnp.asarray(step, jnp.int32))


def _ckpt_fixture(devices, tmp_path, steps=2):
    d = str(tmp_path / "ckpt")
    saved = []
    state = None
    for i in range(1, steps + 1):
        state = _synthetic_state(i)
        saved.append(ckpt.save(d, state))
    return d, state, saved


def test_manifest_verify_detects_corruption(devices, tmp_path):
    d, state, saved = _ckpt_fixture(devices, tmp_path)
    assert all(ckpt.verify(d, s) for s in saved)
    assert ckpt.intact_steps(d) == saved
    from flashmoe_tpu.chaos import _corrupt_latest_checkpoint

    victim = _corrupt_latest_checkpoint(d)
    assert victim is not None
    assert not ckpt.verify(d, saved[-1])
    assert ckpt.intact_steps(d) == saved[:-1]


def test_restore_falls_back_to_intact_step(devices, tmp_path):
    from flashmoe_tpu.chaos import _corrupt_latest_checkpoint

    d, state, saved = _ckpt_fixture(devices, tmp_path)
    _corrupt_latest_checkpoint(d)
    n0 = len(global_metrics.decisions)
    restored = ckpt.restore(d, state)
    assert int(restored.step) == saved[-2]
    fb = [r for r in global_metrics.decisions[n0:]
          if r["decision"] == "checkpoint.fallback"]
    assert fb and fb[0]["corrupt_step"] == saved[-1]
    assert fb[0]["restored_step"] == saved[-2]


def test_restore_raises_when_nothing_intact(devices, tmp_path):
    from flashmoe_tpu.chaos import _corrupt_latest_checkpoint

    d, state, saved = _ckpt_fixture(devices, tmp_path, steps=1)
    _corrupt_latest_checkpoint(d)
    with pytest.raises(ckpt.CheckpointCorruptionError):
        ckpt.restore(d, state)
    # opting out of verification restores the legacy behavior
    r = ckpt.restore(d, state, check_integrity=False)
    assert int(r.step) == saved[-1]


def test_emergency_save_persists_last_good_state(devices, tmp_path):
    d, state, saved = _ckpt_fixture(devices, tmp_path, steps=1)
    # state.step == 1 is already saved -> no duplicate
    assert ckpt.emergency_save(d, state) is None
    assert ckpt.emergency_save(d, _synthetic_state(2)) == 2
    assert ckpt.latest_step(d) == 2 and ckpt.verify(d, 2)


def test_abort_after_retries_emergency_saves(devices, tmp_path):
    from flashmoe_tpu.runtime.resilient import StepFailure

    state, step, data = _train_fixture(devices)
    rcfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck"),
                            checkpoint_every=100, max_retries=1)
    metrics = Metrics()

    def always_fail(i):
        if i == 1:
            raise RuntimeError("permanent fault")

    with pytest.raises(StepFailure):
        resilient_train(state, step, data, num_steps=4, rcfg=rcfg,
                        metrics=metrics, fail_injector=always_fail)
    # the last good state (step 1) was persisted on the way out
    assert metrics.counters["emergency_saves"] == 1
    assert ckpt.latest_step(rcfg.checkpoint_dir) == 1


def test_restore_pre_guard_checkpoint_layout(tmp_path):
    """Checkpoints written BEFORE TrainState grew the guard field (3-key
    payload) must restore into a guard-free template: the None guard is
    omitted from the orbax dict on both sides."""
    import orbax.checkpoint as ocp

    state = _synthetic_state(1)
    d = str(tmp_path / "old_layout")
    mgr = ocp.CheckpointManager(
        d, options=ocp.CheckpointManagerOptions(create=True))
    mgr.save(1, args=ocp.args.StandardSave(
        {"params": state.params, "opt_state": state.opt_state,
         "step": state.step}))
    mgr.wait_until_finished()
    mgr.close()
    restored = ckpt.restore(d, state)
    assert int(restored.step) == 1
    assert restored.guard is None
    np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                  np.asarray(state.params["w"]))
    # a guard-CARRYING template restores the same old payload with a
    # freshly seeded GuardState (re-launching with --grad-guard must not
    # abort on pre-guard checkpoints)
    from flashmoe_tpu.runtime.trainer import init_guard_state

    guarded = state._replace(guard=init_guard_state())
    r2 = ckpt.restore(d, guarded)
    assert int(r2.step) == 1
    assert r2.guard is not None and int(r2.guard.seen) == 0


def test_resilient_raises_step_failure_when_all_ckpts_corrupt(devices,
                                                              tmp_path):
    """All-corrupt checkpoint dir + a transient step failure: the loop
    must keep its StepFailure contract (not leak the corruption error)
    after attempting an emergency save."""
    from flashmoe_tpu.chaos import _corrupt_latest_checkpoint
    from flashmoe_tpu.runtime.resilient import StepFailure

    state, step, data = _train_fixture(devices)
    rcfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck"),
                            checkpoint_every=2, max_retries=2)

    def injector(i):
        if i == 3:
            if _corrupt_latest_checkpoint(rcfg.checkpoint_dir):
                pass
            raise RuntimeError("transient fault over corrupt disk")

    with pytest.raises(StepFailure, match="no intact checkpoint"):
        resilient_train(state, step, data, num_steps=5, rcfg=rcfg,
                        fail_injector=injector)


def test_abort_with_donated_state_saves_host_mirror(devices, tmp_path):
    """When the abort follows a DISPATCHED failure, ``state``'s buffers
    were donated into the dead attempt — the emergency save must refuse
    them and persist the undonated host mirror instead of silently
    writing nothing (or a torn step dir)."""
    from flashmoe_tpu.runtime.resilient import StepFailure

    state, step, data = _train_fixture(devices)
    rcfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck"),
                            checkpoint_every=100, max_retries=1)

    def nan_loss_step(s, b):
        ns, m = step(s, b)  # dispatches: donates s's buffers
        return ns, dict(m, loss=jnp.float32("nan"))

    metrics = Metrics()
    with pytest.raises(StepFailure):
        resilient_train(state, nan_loss_step, data, num_steps=2,
                        rcfg=rcfg, metrics=metrics)
    assert metrics.counters["emergency_saves"] == 1
    # the mirror holds the pre-failure step (0), verified intact
    assert ckpt.latest_step(rcfg.checkpoint_dir) == 0
    assert ckpt.verify(rcfg.checkpoint_dir, 0)


# ----------------------------------------------------------------------
# Exact batch replay after rewind (satellite: replay-divergence fix)
# ----------------------------------------------------------------------

def test_rewind_replays_exact_batches(devices, tmp_path):
    state, step, data = _train_fixture(devices)
    rcfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck"),
                            checkpoint_every=2, max_retries=3)
    seen: dict[int, list] = {}

    def recording_step(s, b):
        seen.setdefault(int(s.step), []).append(
            np.asarray(b["tokens"]).copy())
        return step(s, b)

    crashed = {"done": False}

    def injector(i):
        if i == 3 and not crashed["done"]:
            crashed["done"] = True
            raise RuntimeError("injected device loss")

    final, _ = resilient_train(state, recording_step, data, num_steps=5,
                               rcfg=rcfg, fail_injector=injector)
    assert int(final.step) == 5
    # steps 2 ran twice (rewind to ckpt@2 replays it); every execution of
    # a given step consumed the bit-exact same batch
    assert len(seen[2]) == 2
    for step_idx, batches in seen.items():
        for b in batches[1:]:
            np.testing.assert_array_equal(batches[0], b)


def test_history_tolerates_missing_loss_and_array_metrics(devices,
                                                          tmp_path):
    """Satellite: a step_fn without 'loss' or with array-valued metrics
    must not crash the recovery loop."""
    state, step, data = _train_fixture(devices)

    def odd_metrics_step(s, b):
        ns, m = step(s, b)
        m = dict(m)
        m.pop("loss")
        m["per_expert"] = jnp.arange(4, dtype=jnp.float32)
        return ns, m

    final, hist = resilient_train(
        state, odd_metrics_step, data, num_steps=2,
        rcfg=ResilienceConfig(checkpoint_dir=str(tmp_path / "ck"),
                              checkpoint_every=10))
    assert int(final.step) == 2
    assert len(hist) == 2
    assert all("per_expert" not in h and "loss" not in h for h in hist)
    assert all(np.isfinite(h["grad_norm"]) for h in hist)


# ----------------------------------------------------------------------
# Planner path fallback
# ----------------------------------------------------------------------

def test_report_path_failure_demotes_backend():
    from flashmoe_tpu.planner import select

    select.reset_path_failures()
    n0 = len(global_metrics.decisions)
    select.report_path_failure("fused", "Mosaic blew up")
    assert "fused" in select.failed_backends()
    recs = [r for r in global_metrics.decisions[n0:]
            if r["decision"] == "planner.fallback"]
    assert recs and recs[0]["failed"] == "fused"
    # collective is never blacklisted: it is the fallback of last resort
    select.report_path_failure("collective", "never happens")
    assert "collective" not in select.failed_backends()
    select.reset_path_failures()
    assert not select.failed_backends()


def test_auto_backend_avoids_failed_path(devices):
    from flashmoe_tpu.planner import select

    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256, ep=8,
                    moe_backend="auto", **F32)
    mesh = make_mesh(cfg, dp=1, devices=devices[:8])
    select.reset_path_failures()
    try:
        first = select.resolve_moe_backend(cfg, mesh)
        if first == "collective":
            pytest.skip("planner already picks the fallback baseline")
        select.report_path_failure(first, "injected")
        second = select.resolve_moe_backend(cfg, mesh)
        assert second != first
    finally:
        select.reset_path_failures()


def test_resilient_handles_path_failure(devices, tmp_path):
    from flashmoe_tpu.planner import select
    from flashmoe_tpu.planner.select import PathFailure

    state, step, data = _train_fixture(devices)
    rcfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck"),
                            checkpoint_every=2, max_retries=2)
    metrics = Metrics()
    fired = {"n": 0}

    def injector(i):
        if i == 1 and not fired["n"]:
            fired["n"] = 1
            raise PathFailure("fused", "injected trace failure")

    try:
        final, _ = resilient_train(state, step, data, num_steps=3,
                                   rcfg=rcfg, metrics=metrics,
                                   fail_injector=injector)
        assert int(final.step) == 3
        assert metrics.counters["path_fallbacks"] == 1
        assert "fused" in select.failed_backends()
    finally:
        select.reset_path_failures()


# ----------------------------------------------------------------------
# End-to-end drill matrix (slow) + CLI artifact export
# ----------------------------------------------------------------------

@pytest.mark.slow
def test_drill_matrix():
    from flashmoe_tpu.chaos.drill import run_matrix

    results = run_matrix()
    failed = [(r.fault, r.reason) for r in results if not r.recovered]
    assert not failed, f"drills failed: {failed}"
    # every recovery left telemetry evidence; in-graph tiers cost zero
    # re-executed steps, host tiers stay within the checkpoint window
    for r in results:
        if r.expected_tier.startswith(("monitor:", "fabric:")):
            # serving-plane drills run to DRAIN (every request must
            # complete bit-equal), not to a fixed step budget; their
            # per-fault recovery evidence is asserted in
            # test_fault_fabric.py / test_telemetry_plane.py
            assert r.final_step >= 6
            assert r.evidence.get("bit_equal_to_baseline", True)
            continue
        # controller drills need debounce + cooldown + recovery room,
        # so run_drill floors them at 12 steps
        want = 12 if r.expected_tier.startswith("controller") else 6
        assert r.final_step == want
        if r.expected_tier.startswith(("tier0", "tier1", "controller")):
            assert r.steps_rerun == 0


@pytest.mark.slow
def test_drill_preempt_drains_with_zero_lost_steps():
    from flashmoe_tpu.chaos.drill import run_drill

    r = run_drill("preempt")
    assert r.recovered, r.reason
    assert r.expected_tier == "tier3:drain_resume"
    assert r.steps_rerun == 0  # the drain checkpoints the exact step
    assert r.evidence["loader_state_present"]
    names = r.evidence["decision_names"]
    assert "preempt.drain" in names and "supervisor.resume" in names


@pytest.mark.slow
def test_drill_device_loss_refolds_world():
    from flashmoe_tpu.chaos.drill import run_drill

    r = run_drill("device_loss")
    assert r.recovered, r.reason
    assert r.expected_tier == "tier3:elastic_refold"
    assert r.evidence["supervisor_restarts"] >= 1
    # the restart landed on fewer devices (8 virtual devices available)
    worlds = [w for w in r.evidence["worlds"] if w]
    assert worlds and min(worlds) == 1


@pytest.mark.slow
def test_drill_cli_exports_obs_artifacts(tmp_path):
    from flashmoe_tpu.chaos.__main__ import main

    obs = tmp_path / "obs"
    rc = main(["--faults", "nan_grad,path_raise", "--obs-dir", str(obs)])
    assert rc == 0
    results = [json.loads(l) for l in
               (obs / "drill_results.jsonl").read_text().splitlines()]
    assert {r["fault"] for r in results} == {"nan_grad", "path_raise"}
    decisions = [json.loads(l) for l in
                 (obs / "decisions.jsonl").read_text().splitlines()]
    names = {d["decision"] for d in decisions}
    assert "trainer.grad_skip" in names and "planner.fallback" in names


def test_drill_cli_rejects_unknown_fault(capsys):
    from flashmoe_tpu.chaos.__main__ import main

    with pytest.raises(SystemExit):
        main(["--faults", "meteor_strike"])
    # an all-separator list must be a usage error, not a 0-drill PASS
    with pytest.raises(SystemExit):
        main(["--faults", ","])


# ----------------------------------------------------------------------
# Self-healing controller drills (slow) + sustained-fault plumbing
# ----------------------------------------------------------------------

def test_fault_plan_duration_validates():
    with pytest.raises(ValueError, match="duration"):
        FaultPlan("slow_step", duration=0)
    assert FaultPlan("slow_step").duration == 1  # legacy single-shot


def test_wrap_step_slow_step_holds_for_duration():
    """`duration` turns the one-step stall into a sustained window —
    the shape the controller's debounce requires."""
    import types

    calls = []

    def fake_step(state, batch):
        calls.append(int(state.step))
        return state, {}

    slept = []
    plan = FaultPlan("slow_step", step=2, duration=3, sleep_s=0.0)
    wrapped = wrap_step(fake_step, plan)
    import flashmoe_tpu.chaos as chaos_mod

    orig_sleep = chaos_mod.time.sleep
    chaos_mod.time.sleep = lambda s: slept.append(s)
    try:
        for i in range(7):
            st = types.SimpleNamespace(step=i)
            wrapped(st, None)
            wrapped(st, None)  # once=True: each window step fires once
    finally:
        chaos_mod.time.sleep = orig_sleep
    assert len(slept) == 3  # steps 2, 3, 4 — once each


def test_wrap_step_slow_device_prices_stall_from_load_share():
    import types

    plan = FaultPlan("slow_device", step=1, duration=2, sleep_s=10.0)
    shares = {1: 0.5, 2: 0.0}
    slept = []

    def fake_step(state, batch):
        return state, {}

    wrapped = wrap_step(fake_step, plan,
                        load_share=lambda i: shares.get(i, 1.0))
    import flashmoe_tpu.chaos as chaos_mod

    orig_sleep = chaos_mod.time.sleep
    chaos_mod.time.sleep = lambda s: slept.append(s)
    try:
        for i in range(4):
            wrapped(types.SimpleNamespace(step=i), None)
    finally:
        chaos_mod.time.sleep = orig_sleep
    # step 0: pre-window; step 1: 10 * 0.5; step 2: share 0 -> no
    # sleep at all; step 3: past the window
    assert slept == [5.0]


def test_rearmed_injection_survives_remat_cache(devices):
    """Regression: jax.checkpoint caches block traces by (function,
    static args), so two builds of an EQUAL config used to splice the
    FIRST build's arming state into the second's jaxpr — re-arming +
    rebuilding silently produced a fault-free step.  The chaos trace
    signature now rides the remat static args."""
    from flashmoe_tpu.models import transformer

    # as small as the config allows: the test pays two full jit
    # compiles, so every dimension is floored
    cfg = drill_config(num_layers=1, sequence_len=16, vocab_size=64,
                       intermediate_size=64)
    params = transformer.init_params(jax.random.PRNGKey(0), cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1),
                              (2, cfg.sequence_len + 1), 0,
                              cfg.vocab_size)
    batch = {"tokens": toks}
    clear()

    def build():
        # a FRESH jit wrapper per build, exactly like make_train_step
        return jax.jit(lambda p, b: transformer.loss_fn(
            p, b, cfg, None, False)[1]["moe_stats"][0].expert_load)

    calm = np.asarray(build()(params, batch))
    inject.arm("skewed_routing", expert=0, bias=100.0)
    try:
        skewed = np.asarray(build()(params, batch))
    finally:
        clear()
    n_tok = 2 * cfg.sequence_len  # batch of 2 next-token windows
    assert calm.max() < n_tok * 0.95  # sanity: calm routing is spread
    assert skewed[0] >= n_tok * 0.95  # collapse onto expert 0


@pytest.mark.slow
def test_drill_skew_sustained_triggers_morph():
    from flashmoe_tpu.chaos.drill import run_drill

    r = run_drill("skew_sustained")
    assert r.recovered, r.reason
    assert r.expected_tier == "controller:morph"
    assert r.steps_rerun == 0 and r.evidence["failures"] == 0
    assert "controller.morph" in r.evidence["decision_names"]
    assert r.evidence["action"]["dropless"]
    # the drop EMA recovered under the trigger after the morph
    assert r.evidence["drop_ema_end"] < 0.05
    # the plan is durable: the newest manifest carries it
    assert r.evidence["manifest_plan"]
    assert not r.evidence["postmortem_bundles"]


@pytest.mark.slow
def test_drill_slow_device_triggers_replacement():
    from flashmoe_tpu.chaos.drill import run_drill

    r = run_drill("slow_device")
    assert r.recovered, r.reason
    assert r.expected_tier == "controller:replace"
    assert r.steps_rerun == 0 and r.evidence["failures"] == 0
    names = r.evidence["decision_names"]
    assert "controller.replace" in names
    # the hot expert was replicated onto a dead slot
    assert r.evidence["action"]["replicas"]
    # ISSUE 12 satellite: the re-placement consumed the controller's
    # DEFAULT rates_fn — the live per-device throughput re-probe
    # (runtime/throughput.device_rates, degraded through the
    # probe_rates chaos seam) — so the decision record carries the
    # probed 0.25x slow-chip reading, not drill-injected rates
    assert r.evidence["action"]["rates"] == [0.25, 1.0, 1.0, 1.0]
    # the SLO watchdog narrated degradation AND recovery
    assert "slo.breach" in names and "slo.recovered" in names
    # measured step time collapsed after the re-placement
    assert r.evidence["post_ms"] < 0.5 * r.evidence["pre_ms"]


@pytest.mark.slow
def test_drill_cli_single_fault_filter(tmp_path):
    """`--fault NAME` drills exactly that fault — the CI fast path that
    smokes one fault without the full slow matrix."""
    from flashmoe_tpu.chaos.__main__ import main

    obs = tmp_path / "obs"
    rc = main(["--fault", "nan_grad", "--obs-dir", str(obs)])
    assert rc == 0
    results = [json.loads(l) for l in
               (obs / "drill_results.jsonl").read_text().splitlines()]
    assert [r["fault"] for r in results] == ["nan_grad"]


def test_drill_cli_fault_flag_validates():
    from flashmoe_tpu.chaos.__main__ import main

    with pytest.raises(SystemExit):
        main(["--fault", "meteor_strike"])


@pytest.mark.slow
def test_supervise_controller_morphs_and_plan_survives_restart(
        tmp_path, devices):
    """End-to-end supervisor wiring of the controller
    (``ResilienceConfig.adapt``): a sustained skew morphs the job
    mid-incarnation; a preemption drain then restarts it, and the new
    incarnation resumes the MORPHED plan and the SPENT budget from the
    checkpoint manifest (no re-morph, no oscillation)."""
    import os

    from flashmoe_tpu.runtime.controller import ControllerConfig
    from flashmoe_tpu.runtime.data import TokenLoader, write_token_file
    from flashmoe_tpu.runtime.preempt import PreemptionListener
    from flashmoe_tpu.runtime.resilient import supervise

    cfg = drill_config()
    tok = str(tmp_path / "tokens.bin")
    rng = np.random.default_rng(3)
    write_token_file(tok, rng.integers(
        0, cfg.vocab_size, size=40 * (cfg.sequence_len + 1),
        dtype=np.int32))
    inject.arm("skewed_routing", expert=0, bias=100.0)
    rcfg = ResilienceConfig(
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
        adapt=ControllerConfig(debounce_steps=2, cooldown_steps=3,
                               baseline_steps=2, ema_decay=0.5,
                               morph_budget=1, enable_replace=False))
    pl = PreemptionListener()
    fired = {"n": 0}

    def poke(i):
        if i == 6 and not fired["n"]:
            fired["n"] = 1
            pl.notify("test")

    metrics = Metrics()
    final, hist = supervise(
        cfg, lambda fcfg: TokenLoader(tok, 2, fcfg.sequence_len,
                                      seed=3, native=False),
        10, rcfg, metrics=metrics, preempt=pl,
        devices_fn=lambda: jax.devices()[:1], fail_injector=poke)
    assert int(final.step) == 10
    morphs = [d for d in metrics.decisions
              if d["decision"] == "controller.morph"]
    assert len(morphs) == 1 and morphs[0]["dropless"]
    assert metrics.counters["preempt_drains"] == 1
    assert metrics.last_decision("supervisor.resume") is not None
    plan = ckpt.load_controller_state(rcfg.checkpoint_dir, 10)
    assert plan is not None and plan["morphs_used"] == 1
    assert plan["overrides"] == {"drop_tokens": False}
