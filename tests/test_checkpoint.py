"""Checkpoint save/restore round trip with shardings."""

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.parallel.mesh import make_mesh
from flashmoe_tpu.runtime import checkpoint as ckpt
from flashmoe_tpu.runtime.trainer import (
    init_state, make_optimizer, make_train_step, state_shardings,
)

CFG = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                intermediate_size=256, sequence_len=64, num_layers=2,
                moe_frequency=2, vocab_size=512, num_heads=4,
                drop_tokens=False, is_training=True, ep=4,
                dtype=jnp.float32, param_dtype=jnp.float32)


@pytest.mark.slow
def test_save_restore_roundtrip(devices, tmp_path):
    mesh = make_mesh(CFG)
    opt = make_optimizer(CFG, total_steps=4)
    state = init_state(jax.random.PRNGKey(0), CFG, opt)
    state = jax.device_put(state, state_shardings(state, CFG, mesh))
    step = make_train_step(CFG, mesh, opt)
    batch = {"tokens": jax.random.randint(
        jax.random.PRNGKey(1), (2, 65), 0, 512)}
    state, _ = step(state, batch)

    d = str(tmp_path / "ckpt")
    saved_step = ckpt.save(d, state)
    assert saved_step == 1
    assert ckpt.latest_step(d) == 1

    # fresh template, different values
    fresh = init_state(jax.random.PRNGKey(42), CFG, opt)
    fresh = jax.device_put(fresh, state_shardings(fresh, CFG, mesh))
    restored = ckpt.restore(d, fresh)
    assert int(restored.step) == 1
    for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # restored arrays keep the template's shardings
    w = restored.params["layers"][1]["moe"]["w_up"]
    assert w.sharding.is_equivalent_to(
        state.params["layers"][1]["moe"]["w_up"].sharding, w.ndim
    )

    # training continues from the restored state
    state2, metrics = step(restored, batch)
    assert int(state2.step) == 2
    assert np.isfinite(float(metrics["loss"]))


def test_latest_step_empty(tmp_path):
    assert ckpt.latest_step(str(tmp_path / "none")) is None


# ----------------------------------------------------------------------
# Async atomic saves (preemption-safe checkpointing, docs/RESILIENCE.md)
# ----------------------------------------------------------------------

def _tiny_state(step: int):
    from flashmoe_tpu.runtime.trainer import TrainState

    k = jax.random.PRNGKey(step)
    return TrainState(
        params={"w": jax.random.normal(k, (16, 16), jnp.float32)},
        opt_state={"m": jnp.zeros((16, 16), jnp.float32)},
        step=jnp.asarray(step, jnp.int32))


def test_async_save_verifies_and_restores(tmp_path):
    d = str(tmp_path / "ck")
    state = _tiny_state(1)
    ckpt.save(d, state, blocking=False,
              loader_state={"epoch": 0, "cursor": 2, "seed": 7,
                            "shuffle": True})
    assert ckpt.wait_for_saves() == []
    assert ckpt.latest_step(d) == 1
    assert ckpt.verify(d, 1)  # CRC manifest semantics preserved
    assert ckpt.load_loader_state(d, 1) == {
        "epoch": 0, "cursor": 2, "seed": 7, "shuffle": True}
    restored = ckpt.restore(d, _tiny_state(9))
    np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                  np.asarray(state.params["w"]))


def test_async_queue_is_newest_wins(tmp_path, monkeypatch):
    """Depth-1 queue: while one save is in flight, the QUEUED (not yet
    started) snapshot is replaced by a newer one — the writer never
    falls behind by more than one checkpoint."""
    import threading

    import flashmoe_tpu.runtime.checkpoint as ckpt_mod

    d = str(tmp_path / "ck")
    gate = threading.Event()
    real = ckpt_mod._write_sync
    stalled = {"n": 0}

    def slow_write(directory, state, step, loader_state,
               controller_state=None):
        stalled["n"] += 1
        if stalled["n"] == 1:
            gate.wait(timeout=30)
        real(directory, state, step, loader_state)

    monkeypatch.setattr(ckpt_mod, "_write_sync", slow_write)
    before = ckpt.async_save_stats()
    ckpt.save(d, _tiny_state(1), blocking=False)  # in flight, stalled
    for _ in range(500):  # wait until the writer picked job 1 up
        if stalled["n"]:
            break
        import time
        time.sleep(0.01)
    assert stalled["n"] == 1
    for s in (2, 3, 4):  # queue depth 1: 2 and 3 are replaced by 4
        ckpt.save(d, _tiny_state(s), blocking=False)
    gate.set()
    assert ckpt.wait_for_saves() == []
    after = ckpt.async_save_stats()
    assert after["dropped"] - before["dropped"] == 2
    assert after["completed"] - before["completed"] == 2  # 1 and 4
    assert ckpt.latest_step(d) == 4
    assert ckpt.verify(d, 4)


def test_async_queue_never_drops_across_directories(tmp_path):
    """Newest-wins is PER DIRECTORY: two runs sharing the process must
    not cancel each other's pending checkpoints."""
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    before = ckpt.async_save_stats()
    ckpt.save(d1, _tiny_state(1), blocking=False)
    ckpt.save(d2, _tiny_state(1), blocking=False)
    assert ckpt.wait_for_saves() == []
    after = ckpt.async_save_stats()
    assert after["dropped"] == before["dropped"]  # nothing replaced
    assert ckpt.latest_step(d1) == 1 and ckpt.latest_step(d2) == 1
    assert ckpt.verify(d1, 1) and ckpt.verify(d2, 1)


def test_async_writer_error_is_surfaced_not_raised(tmp_path, monkeypatch):
    import flashmoe_tpu.runtime.checkpoint as ckpt_mod

    def boom(directory, state, step, loader_state,
         controller_state=None):
        raise OSError("disk on fire")

    monkeypatch.setattr(ckpt_mod, "_write_sync", boom)
    ckpt.save(str(tmp_path / "ck"), _tiny_state(1), blocking=False)
    errors = ckpt.wait_for_saves()
    assert len(errors) == 1 and "disk on fire" in str(errors[0])
    assert ckpt.wait_for_saves() == []  # errors drained once


def test_kill_between_payload_and_manifest_keeps_previous_step(tmp_path):
    """Durability ordering: the manifest lands only after the payload
    commit.  A kill mid-payload leaves an uncommitted tmp dir orbax
    ignores; a kill between payload and manifest leaves a legacy-style
    manifest-less (but complete) checkpoint — either way the previous
    step restores intact."""
    import os
    import shutil

    d = str(tmp_path / "ck")
    ckpt.save(d, _tiny_state(1))
    ckpt.save(d, _tiny_state(2))

    # kill mid-payload: the step dir never committed (tmp name), no
    # manifest was written — invisible to the manager, step 2 restores
    src = ckpt.step_dir(d, 2)
    shutil.copytree(src, os.path.join(
        str(tmp_path / "ck"), "3.orbax-checkpoint-tmp-999"))
    assert ckpt.latest_step(d) == 2
    restored = ckpt.restore(d, _tiny_state(9))
    assert int(restored.step) == 2

    # kill between payload commit and manifest write: a complete but
    # manifest-less checkpoint — restorable as legacy, previous steps
    # (and their manifests) untouched
    os.remove(os.path.join(d, "manifest-2.json"))
    assert ckpt.verify(d, 2)  # manifest-less: no integrity claim
    assert ckpt.verify(d, 1)  # previous step's manifest still verifies
    assert int(ckpt.restore(d, _tiny_state(9)).step) == 2
    assert ckpt.load_loader_state(d, 2) is None  # cursor died with it


def test_manifest_loader_state_roundtrip_and_legacy(tmp_path):
    d = str(tmp_path / "ck")
    ckpt.save(d, _tiny_state(1))  # no loader attached
    assert ckpt.load_loader_state(d, 1) is None  # legacy/absent: None
    ckpt.save(d, _tiny_state(2), loader_state={"epoch": 1, "cursor": 3,
                                               "seed": 0,
                                               "shuffle": False})
    assert ckpt.load_loader_state(d, 2)["cursor"] == 3
    assert ckpt.verify(d, 2)  # the extra manifest field breaks nothing


def _quant_state(step: int):
    from flashmoe_tpu import quant as qt
    from flashmoe_tpu.models.reference import init_moe_params
    from flashmoe_tpu.runtime.trainer import TrainState

    cfg = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, dtype=jnp.float32,
                    param_dtype=jnp.float32)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    qs = qt.quantize_state(params, "int8")
    return TrainState(params={"moe": dict(qs.params)},
                      opt_state={}, step=jnp.asarray(step, jnp.int32))


def test_quant_manifest_block_and_backcompat(tmp_path):
    """ISSUE 15 satellite: a pre-quant manifest (no `quant` block)
    restores unchanged; a quantized save -> restore -> dequantize round
    trip is bit-stable across the ASYNC save path; a tampered quant
    block trips the CRC instead of silently mis-decoding payloads."""
    import os

    from flashmoe_tpu import quant as qt

    d = str(tmp_path / "ck")
    # pre-quant checkpoint: no quant block, restore untouched
    ckpt.save(d, _tiny_state(1))
    assert ckpt.load_quant_metadata(d, 1) is None
    assert ckpt.verify(d, 1)

    # quantized save through the ASYNC path: the manifest gains the
    # CRC'd quant block automatically (derived from state.params)
    state = _quant_state(2)
    ckpt.save(d, state, step=2, blocking=False)
    assert ckpt.wait_for_saves() == []
    meta = ckpt.load_quant_metadata(d, 2)
    assert meta is not None and meta["dtype"] == "int8"
    assert qt.verify_quant_metadata(meta)
    assert ckpt.verify(d, 2)

    # restore -> dequantize bit-stable (int8 payloads + f32 scales ride
    # orbax unchanged, so decode(restore(x)) == decode(x) exactly)
    restored = ckpt.restore(d, _quant_state(9), step=2)
    want = qt.dequantize_state(state.params["moe"])
    got = qt.dequantize_state(restored.params["moe"])
    for k in ("w_up", "w_down"):
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(want[k]))
    assert np.asarray(restored.params["moe"]["w_up"]).dtype == np.int8

    # tamper the quant block: the content CRC must trip
    import json as _json

    mpath = os.path.join(d, "manifest-2.json")
    with open(mpath) as f:
        manifest = _json.load(f)
    manifest["quant"]["dtype"] = "e4m3"
    with open(mpath, "w") as f:
        _json.dump(manifest, f)
    with pytest.raises(ckpt.CheckpointCorruptionError,
                       match="quant metadata"):
        ckpt.load_quant_metadata(d, 2)


def test_has_guard_probe(tmp_path):
    from flashmoe_tpu.runtime.trainer import init_guard_state

    d = str(tmp_path / "ck")
    ckpt.save(d, _tiny_state(1))
    assert ckpt.has_guard(d, 1) is False
    guarded = _tiny_state(2)._replace(guard=init_guard_state())
    ckpt.save(d, guarded, step=2)
    assert ckpt.has_guard(d, 2) is True
