"""Checkpoint save/restore round trip with shardings."""

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.parallel.mesh import make_mesh
from flashmoe_tpu.runtime import checkpoint as ckpt
from flashmoe_tpu.runtime.trainer import (
    init_state, make_optimizer, make_train_step, state_shardings,
)

CFG = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                intermediate_size=256, sequence_len=64, num_layers=2,
                moe_frequency=2, vocab_size=512, num_heads=4,
                drop_tokens=False, is_training=True, ep=4,
                dtype=jnp.float32, param_dtype=jnp.float32)


@pytest.mark.slow
def test_save_restore_roundtrip(devices, tmp_path):
    mesh = make_mesh(CFG)
    opt = make_optimizer(CFG, total_steps=4)
    state = init_state(jax.random.PRNGKey(0), CFG, opt)
    state = jax.device_put(state, state_shardings(state, CFG, mesh))
    step = make_train_step(CFG, mesh, opt)
    batch = {"tokens": jax.random.randint(
        jax.random.PRNGKey(1), (2, 65), 0, 512)}
    state, _ = step(state, batch)

    d = str(tmp_path / "ckpt")
    saved_step = ckpt.save(d, state)
    assert saved_step == 1
    assert ckpt.latest_step(d) == 1

    # fresh template, different values
    fresh = init_state(jax.random.PRNGKey(42), CFG, opt)
    fresh = jax.device_put(fresh, state_shardings(fresh, CFG, mesh))
    restored = ckpt.restore(d, fresh)
    assert int(restored.step) == 1
    for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # restored arrays keep the template's shardings
    w = restored.params["layers"][1]["moe"]["w_up"]
    assert w.sharding.is_equivalent_to(
        state.params["layers"][1]["moe"]["w_up"].sharding, w.ndim
    )

    # training continues from the restored state
    state2, metrics = step(restored, batch)
    assert int(state2.step) == 2
    assert np.isfinite(float(metrics["loss"]))


def test_latest_step_empty(tmp_path):
    assert ckpt.latest_step(str(tmp_path / "none")) is None
